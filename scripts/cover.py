"""Line coverage for the package with zero external dependencies.

The image has no coverage.py / pytest-cov and no egress to vendor one
(reference parity target: `rebar3 cover`, reference Makefile:15-16,
rebar.config:5). Python 3.12's sys.monitoring (PEP 669) makes a real
line-coverage tool ~60 lines: register a LINE callback, record the first
hit per location, and return sys.monitoring.DISABLE so every subsequent
execution of that location costs nothing — the suite runs at near-native
speed. On older interpreters (no PEP 669) a `sys.settrace` fallback
produces the identical executed-line sets, just without the
disable-after-first-hit speedup.

Executable-line ground truth comes from compiling each source file and
walking the code-object tree's co_lines() — the same universe coverage.py
uses. Lines that only exist at class/module level (docstrings, imports)
execute at import time, which happens under monitoring because this
script starts monitoring BEFORE importing pytest or the package.

Usage:
  python scripts/cover.py [--threshold PCT] [pytest args...]
      run + report in one process (full suite by default)
  python scripts/cover.py --data-out F.json [pytest args...]
      run a shard, save the executed-line data, no report
  python scripts/cover.py --report F1.json F2.json [--threshold PCT]
      merge shard data files and report/enforce
Defaults: --threshold 85, pytest args `tests/ -q`. Exits 1 below
threshold (the committed gate for `make cover` / `make all`). Sharding
exists because one full-suite run is ~8-10 min and some CI wrappers cap
per-command wall time; union of line sets is exact, not approximate.

Subprocess coverage: the monitor is per-interpreter, so code that only
runs in test-spawned children (parallel/multihost.py's real multi-process
jax.distributed drills, the elastic crash/scale-up demos) would be a
blind spot. The cover run exports CCRDT_COVER_DIR; child entry points
(scripts/multihost_demo.py, scripts/elastic_demo.py) call
`install_child_cover()` — a no-op outside cover runs — and dump their own
executed-line shards there, merged into the parent's data.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "antidote_ccrdt_tpu")
sys.path.insert(0, REPO)


def executable_lines(path: str) -> set:
    with open(path, "rb") as f:
        src = f.read()
    try:
        code = compile(src, path, "exec")
    except SyntaxError:
        return set()
    lines = set()
    stack = [code]
    while stack:
        co = stack.pop()
        for _, _, ln in co.co_lines():
            if ln is not None:
                lines.add(ln)
        for const in co.co_consts:
            if hasattr(const, "co_lines"):
                stack.append(const)
    return lines


def _start_monitor():
    if hasattr(sys, "monitoring"):
        return _start_monitor_pep669()
    return _start_monitor_settrace()


def _start_monitor_settrace():
    """Pre-3.12 fallback: classic `sys.settrace` line tracing. Slower —
    every package-frame line re-fires the callback (no per-location
    DISABLE) — but the executed set and shard format are identical.
    Frames outside the package return None from their 'call' event, so
    no line events are generated for them at all."""
    import threading

    executed: dict = {}
    prefix = PKG + os.sep

    def tracer(frame, event, arg):
        code = frame.f_code
        if event == "call":
            return tracer if code.co_filename.startswith(prefix) else None
        if event == "line":
            executed.setdefault(code.co_filename, set()).add(frame.f_lineno)
        return tracer

    threading.settrace(tracer)
    sys.settrace(tracer)

    def stop():
        sys.settrace(None)
        threading.settrace(None)

    return executed, stop


def _start_monitor_pep669():
    executed: dict = {}
    mon = sys.monitoring
    TOOL = mon.COVERAGE_ID
    mon.use_tool_id(TOOL, "ccrdt-cover")
    prefix = PKG + os.sep

    def on_line(code, line):
        f = code.co_filename
        if f.startswith(prefix):
            executed.setdefault(f, set()).add(line)
        return mon.DISABLE

    mon.register_callback(TOOL, mon.events.LINE, on_line)
    mon.set_events(TOOL, mon.events.LINE)

    def stop():
        mon.set_events(TOOL, 0)
        mon.free_tool_id(TOOL)

    return executed, stop


def install_child_cover():
    """Opt-in coverage for SUBPROCESSES tests spawn (multihost / elastic
    real-process drills — otherwise a blind spot, see module docstring).
    No-op unless the parent cover run exported CCRDT_COVER_DIR; dumps a
    uniquely-named shard file there at interpreter exit."""
    out_dir = os.environ.get("CCRDT_COVER_DIR")
    if not out_dir:
        return
    if hasattr(sys, "monitoring"):
        already = sys.monitoring.get_tool(sys.monitoring.COVERAGE_ID) is not None
    else:
        already = sys.gettrace() is not None
    if already:
        # Already inside a monitored interpreter: the parent cover run
        # imported this entry point in-process (tests do that too) — its
        # monitor sees these lines directly.
        return
    executed, stop = _start_monitor()

    def dump():
        stop()
        _dump_shard(executed, os.path.join(out_dir, f"child-{os.getpid()}.json"))

    import atexit

    atexit.register(dump)


def _dump_shard(executed, path):
    with open(path, "w") as f:
        json.dump({fn: sorted(ls) for fn, ls in executed.items()}, f)


def _merge_shard(executed, path):
    with open(path) as f:
        for fn, lines in json.load(f).items():
            executed.setdefault(fn, set()).update(lines)


def run_instrumented(pytest_args):
    import glob
    import shutil
    import tempfile

    executed, stop = _start_monitor()
    child_dir = tempfile.mkdtemp(prefix="ccrdt-cover-children-")
    os.environ["CCRDT_COVER_DIR"] = child_dir

    import pytest  # noqa: E402 — imported under monitoring on purpose

    rc = pytest.main(pytest_args)
    stop()
    os.environ.pop("CCRDT_COVER_DIR", None)
    for path in glob.glob(os.path.join(child_dir, "child-*.json")):
        try:
            _merge_shard(executed, path)
        except (OSError, ValueError):
            pass  # a torn child dump must not fail the gate
    shutil.rmtree(child_dir, ignore_errors=True)
    return int(rc), executed


def report(executed, threshold) -> int:
    total_exec = total_hit = 0
    rows = []
    for root, _dirs, files in os.walk(PKG):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            exe = executable_lines(path)
            if not exe:
                continue
            hit = executed.get(path, set()) & exe
            total_exec += len(exe)
            total_hit += len(hit)
            rows.append((os.path.relpath(path, REPO), len(hit), len(exe)))

    rows.sort(key=lambda r: r[1] / r[2])
    print(f"\n{'file':58s} {'cover':>7s}")
    for rel, h, e in rows:
        print(f"{rel:58s} {100 * h / e:6.1f}% ({h}/{e})")
    pct = 100.0 * total_hit / max(1, total_exec)
    print(f"\nTOTAL line coverage: {pct:.1f}% ({total_hit}/{total_exec}) "
          f"— threshold {threshold:.0f}%")
    if pct < threshold:
        print("cover: FAIL (below threshold)")
        return 1
    print("cover: OK")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--threshold", type=float, default=85.0)
    ap.add_argument("--data-out", default=None)
    ap.add_argument("--report", nargs="+", default=None)
    args, rest = ap.parse_known_args()

    if args.report:
        executed: dict = {}
        for path in args.report:
            _merge_shard(executed, path)
        return report(executed, args.threshold)

    rc, executed = run_instrumented(rest or ["tests/", "-q"])
    if rc != 0:
        print(f"cover: pytest failed (rc={rc}); coverage not evaluated")
        return rc
    if args.data_out:
        _dump_shard(executed, args.data_out)
        print(f"cover: shard data -> {args.data_out}")
        return 0
    return report(executed, args.threshold)


if __name__ == "__main__":
    sys.exit(main())
