"""Certified-convergence CLI over the audit plane (obs/audit.py).

Three subcommands, mirroring the questions the certification layer
answers::

    # Machine-check merge commutativity/associativity/idempotence and
    # the delta-composition law for every registered op type, batched
    # on-device (--pairs instance pairs per dispatch). Exit 1 on any
    # law failure or any registered type with no fixture.
    python scripts/ccrdt_audit.py laws --pairs 512

    # Negative selftest: inject the committed non-commutative fixture
    # (ops/laws.py BrokenMergeDense) and REQUIRE the checker to flag
    # it — exit 0 iff the broken laws fail. A checker that waves the
    # broken merge through is itself broken.
    python scripts/ccrdt_audit.py laws --selftest

    # Replay-certify a finished run: flight-log spill + per-worker
    # final digests (JSON file and/or a dir of final-*.json drops) ->
    # signed convergence certificate, or a counterexample slice naming
    # the divergent partitions. Exit 1 when certification fails.
    python scripts/ccrdt_audit.py certify /path/to/obs-dir \
        --digests digests.json --reference <hex[-hex...]> --out cert.json

    # Recompute a certificate's sha256 signature over its canonical
    # body. Exit 1 on tamper/corruption.
    python scripts/ccrdt_audit.py verify cert.json

Digest inputs accept raw ints, int vectors, or the dashed-hex labels
the certificates themselves print, so a certificate's own
`worker_digests` block round-trips back in.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from antidote_ccrdt_tpu.obs import audit as obs_audit  # noqa: E402


def _parse_digest(v: Any) -> Any:
    """int / [ints] / 'a1b2c3d4' / 'a1b2c3d4-...' -> digest value."""
    if v is None or isinstance(v, int):
        return v
    if isinstance(v, (list, tuple)):
        return [int(x) for x in v]
    s = str(v).strip()
    if "-" in s:
        return [int(p, 16) for p in s.split("-")]
    try:
        return int(s, 16)
    except ValueError:
        return int(s)


def _load_digests(
    digests_file: Optional[str], final_dir: Optional[str]
) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    if final_dir:
        for path in sorted(glob.glob(os.path.join(final_dir, "final-*.json"))):
            try:
                with open(path) as fh:
                    doc = json.load(fh)
            except (OSError, ValueError):
                continue
            member = doc.get("member") or os.path.basename(path)[6:-5]
            if "digest" in doc:
                out[str(member)] = _parse_digest(doc["digest"])
    if digests_file:
        with open(digests_file) as fh:
            doc = json.load(fh)
        for m, d in doc.items():
            out[str(m)] = _parse_digest(d)
    return out


def cmd_laws(args: argparse.Namespace) -> int:
    extra = {}
    if args.selftest:
        from antidote_ccrdt_tpu.ops.laws import broken_merge_fixture

        extra["broken_merge_fixture"] = broken_merge_fixture
        types = ["broken_merge_fixture"]
    else:
        types = (
            [t.strip() for t in args.types.split(",") if t.strip()]
            if args.types else None
        )
    checker = obs_audit.LawChecker(
        types=types, seed=args.seed, pairs=args.pairs, extra_fixtures=extra
    )
    report = checker.run()
    if args.json:
        print(json.dumps(report, sort_keys=True))
    else:
        for name, rep in sorted(report["types"].items()):
            laws = " ".join(
                f"{law}={'ok' if e['ok'] else 'FAIL'}"
                for law, e in sorted(rep["laws"].items())
            )
            print(
                f"{name:>22} [{rep['merge_kind']:>6}] "
                f"x{rep['n_instances']:<5} {laws}"
            )
        for name in report["unaudited"]:
            print(f"{name:>22} UNAUDITED (no law fixture registered)")
        print(
            f"{report['n_law_checks']} law checks over "
            f"{report['n_types']} types, "
            f"{report['n_law_failures']} failures"
        )
    if args.selftest:
        rep = report["types"].get("broken_merge_fixture", {})
        bad = rep.get("laws", {})
        caught = (
            not bad.get("commutativity", {}).get("ok", True)
            and not bad.get("associativity", {}).get("ok", True)
            and bad.get("idempotence", {}).get("ok", False)
        )
        print(
            "selftest: broken merge "
            + ("CAUGHT (checker is alive)" if caught else "MISSED")
        )
        return 0 if caught else 1
    return 0 if report["ok"] else 1


def cmd_certify(args: argparse.Namespace) -> int:
    digests = _load_digests(args.digests, args.final_dir)
    reference = _parse_digest(args.reference) if args.reference else None
    cert = obs_audit.certify(
        obs_dir=args.obs_dir,
        digests=digests or None,
        reference=reference,
        meta={"obs_dir": os.path.abspath(args.obs_dir)},
    )
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(cert, fh, indent=2, sort_keys=True)
            fh.write("\n")
    if args.json:
        print(json.dumps(cert, sort_keys=True))
    else:
        print(f"certificate  : {'OK' if cert['ok'] else 'FAILED'}")
        for check, ok in sorted(cert["checks"].items()):
            print(f"  {check:<28}: {'ok' if ok else 'FAIL'}")
        print(f"  flight logs : {cert['n_flight_logs']}")
        print(f"  signature   : sha256:{cert['signature']}")
        if not cert["ok"]:
            print("counterexample:")
            print(json.dumps(cert.get("counterexample", {}), indent=2,
                             sort_keys=True))
        if args.out:
            print(f"written      : {args.out}")
    return 0 if cert["ok"] else 1


def cmd_verify(args: argparse.Namespace) -> int:
    with open(args.certificate) as fh:
        cert = json.load(fh)
    ok = obs_audit.verify_certificate(cert)
    kind_ok = cert.get("kind") == obs_audit.CERTIFICATE_KIND
    if args.json:
        print(json.dumps(
            {"signature_valid": ok, "kind_valid": kind_ok,
             "certificate_ok": bool(cert.get("ok"))},
            sort_keys=True,
        ))
    else:
        print(
            f"signature    : {'valid' if ok else 'INVALID (tampered?)'}\n"
            f"kind         : {cert.get('kind')}"
            f"{'' if kind_ok else ' (UNEXPECTED)'}\n"
            f"verdict      : {'OK' if cert.get('ok') else 'FAILED'}"
        )
    return 0 if ok and kind_ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ccrdt_audit", description=__doc__.splitlines()[0]
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("laws", help="lattice-law property check")
    p.add_argument("--types", help="comma-separated type subset")
    p.add_argument("--pairs", type=int, default=512,
                   help="instance pairs per law dispatch")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--json", action="store_true")
    p.add_argument("--selftest", action="store_true",
                   help="require the committed broken fixture to FAIL")
    p.set_defaults(fn=cmd_laws)

    p = sub.add_parser("certify", help="replay-certify a run's spill")
    p.add_argument("obs_dir")
    p.add_argument("--digests", help="JSON file {member: digest}")
    p.add_argument("--final-dir",
                   help="dir of final-<member>.json drops (elastic_demo)")
    p.add_argument("--reference",
                   help="sequential-reference digest (hex or hex-hex-...)")
    p.add_argument("--out", help="write the signed certificate here")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_certify)

    p = sub.add_parser("verify", help="check a certificate's signature")
    p.add_argument("certificate")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_verify)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
