"""Type gate: the reference's dialyzer analog (reference Makefile:31-32).

No static checker exists in this image (no mypy/pyright, no egress to
vendor one), but `typeguard` does: its import hook instruments every
annotated function in the package with runtime argument/return checks.
Running the python-heavy test subset under the hook is dynamic success
typing — closer in spirit to dialyzer (which types actual value flow)
than to mypy: an annotation that lies about what actually flows through
it fails the gate.

Scope: the scalar engines, registry, wire codecs, compaction, clock,
replay harness, and delta layer — the surfaces where python-level types
carry the contract. The dense/jit internals are exercised too (jax
tracers satisfy `jax.Array` annotations); the heavy CPU-mesh suites are
left to `make test`/`make cover` where they run uninstrumented.

Usage: python scripts/typecheck.py  (exit != 0 on any violation)
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import typeguard  # noqa: E402
from typeguard.importhook import install_import_hook  # noqa: E402

# typeguard 2.13 resolves string annotations with get_type_hints(func),
# which for SYNTHESIZED functions (NamedTuple __new__) evaluates against
# the wrong globals (the typing/collections namespace, not the defining
# module) and NameErrors on e.g. `Dict`. Retry against the defining
# module's namespace so those constructors are checked, not crashed.
_gth = typeguard.get_type_hints


def _drop_decorated_generator_return(func, hints):
    # @contextlib.contextmanager copies the generator's `-> Iterator[...]`
    # annotation (the mypy convention) onto a wrapper that actually
    # returns a context manager; typeguard 2.13 would flag every use.
    import inspect

    w = getattr(func, "__wrapped__", None)
    if w is not None and inspect.isgeneratorfunction(w):
        hints = dict(hints)
        hints.pop("return", None)
    return hints


def _tolerant_get_type_hints(func, globalns=None, localns=None, **kw):
    try:
        return _drop_decorated_generator_return(
            func, _gth(func, globalns, localns, **kw)
        )
    except NameError:
        mod = sys.modules.get(getattr(func, "__module__", "") or "")
        ns = dict(getattr(mod, "__dict__", {}))
        import typing

        ns.update({k: getattr(typing, k) for k in typing.__all__})
        try:
            return _drop_decorated_generator_return(
                func, _gth(func, ns, localns, **kw)
            )
        except NameError:
            return {}


typeguard.get_type_hints = _tolerant_get_type_hints


def _eval_forwardref_py312(ref, globalns, localns, frozen=frozenset()):
    # typeguard 2.13 calls ForwardRef._evaluate with 3.9-era positionals;
    # 3.12 grew a positional type_params and keyword-only recursive_guard.
    return ref._evaluate(
        globalns, localns, type_params=frozenset(), recursive_guard=frozen
    )


typeguard.evaluate_forwardref = _eval_forwardref_py312

install_import_hook("antidote_ccrdt_tpu")

import pytest  # noqa: E402

SUBSET = [
    "tests/test_average_scalar.py",
    "tests/test_topk_scalar.py",
    "tests/test_topk_rmv_scalar.py",
    "tests/test_leaderboard_scalar.py",
    "tests/test_wordcount_scalar.py",
    "tests/test_registry.py",
    "tests/test_etf_wire.py",
    "tests/test_compaction.py",
    "tests/test_harness.py",
    "tests/test_delta.py",
    "tests/test_batch_merge.py",
    "tests/test_bridge.py",
    "tests/test_bridge_erl.py",
]

if __name__ == "__main__":
    os.chdir(REPO)
    # Long property-based suites run uninstrumented in `make test`; the
    # type gate needs breadth across annotated surfaces, not soak depth.
    # argv (if given) overrides the subset for targeted debugging.
    targets = sys.argv[1:] or SUBSET + [
        "-k", "not interleavings and not chaos"
    ]
    sys.exit(pytest.main(targets + ["-q", "-p", "no:cacheprovider"]))
