"""Throughput regression gate over the committed BENCH_*.json rounds.

Each ``BENCH_r<NN>.json`` in the repo root is a benchmark round dump:
one JSON object whose ``tail`` field holds the benchmark harness's raw
stdout — including (for rounds that ran the batched-dispatch benchmark)
``"merges_per_sec": <float>`` lines, JSON-escaped INSIDE the tail
string. This gate:

1. parses every round, taking the best ``merges_per_sec`` per round
   (rounds without the metric — e.g. setup-only rounds — are skipped)
   plus the ``backend`` tag from the summary line;
2. compares, WITHIN each backend group, the latest round that has the
   metric against the best of its prior rounds — a CPU-fallback round
   must not be graded against TPU numbers (nor launder a TPU regression
   by resetting the baseline); rounds with no backend tag group
   together;
3. fails (exit 1) when any group's latest regressed more than
   ``--tolerance`` (default 20%) below its best prior — the same
   batched-dispatch throughput `obs.profile` now measures live, gated
   at CI time;
4. gates ``dispatch_gap_ms_p50`` the same way (PR 7 promoted it from
   report-only): the latest attribution-bearing round fails when its
   gap grew more than ``--gap-tolerance`` (default 20%) AND more than
   0.25 ms absolute over the best (lowest) prior carrier — the
   absolute floor keeps near-zero gaps from tripping on noise;
5. gates the partition plane's anti-entropy costs (r7+): the latest
   carrier's ``antientropy_bytes_per_resync`` and
   ``rejoin_stream_seconds`` must stay within the same double
   threshold (>20% relative AND an absolute floor — 512 B / 0.25 s)
   of the best prior carrier — a psnap fattening back toward whole
   snapshots or the incremental rejoin slowing down fails here.
6. gates the audit plane's per-round cost (r10+): the latest carrier's
   ``audit_overhead_pct`` (bench.bench_audit_overhead — digest
   sampling + watchdog observation on a gossip round loop) must stay
   within >20% relative AND >1pp absolute of the best prior carrier —
   certification drifting from "rides along" to "taxes the hot path"
   fails here.
7. gates the durability path (r9+, PR 11): ``p99_round_ms_e2e`` must
   stay within >20% relative AND >25ms absolute of the best prior
   carrier, and ``round.wal_append`` must not be the #1 phase on the
   latest round's critical path — the group-commit/parallel-stream
   work sliding back to fsync-per-append fails here. The round's
   ``wal_append_ms_total`` and ``wal_group_size_p50`` ride the same
   summary line for drift eyes.

With fewer than two comparable rounds a gate passes vacuously (exit 0)
and says so. The overall exit code is the worst of all gates.

Run: ``python scripts/bench_gate.py [--bench-dir DIR] [--tolerance 0.2]``
(also wired as ``make bench-gate`` and into ``make chaos``).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

_METRIC_RE = re.compile(r'"merges_per_sec":\s*([0-9][0-9_.eE+]*)')
_BACKEND_RE = re.compile(r'"backend":\s*"([A-Za-z0-9_]+)"')
# Fallback for tails whose fat details line pushed every
# "merges_per_sec" key past the driver's 2000-char window: the compact
# summary line (always last, checked < 1900 chars by bench.py) names
# the same number as `"metric": "topk_rmv merges/sec (...)" ...
# "value": N`.
_SUMMARY_RE = re.compile(
    r'"metric":\s*"topk_rmv merges/sec[^"]*",\s*"value":\s*'
    r"([0-9][0-9_.eE+]*)"
)


def round_number(path: str) -> int:
    """BENCH_r07.json -> 7 (unparseable names sort first)."""
    m = re.search(r"BENCH_r(\d+)\.json$", os.path.basename(path))
    return int(m.group(1)) if m else -1


def round_metrics(path: str) -> Tuple[Optional[float], Optional[str]]:
    """(best merges_per_sec, backend tag) of one round dump — (None,
    None) when the round didn't run the dispatch benchmark (or the file
    is torn). The backend rides the summary line so a CPU-fallback run
    is never graded against accelerator numbers."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None, None
    # The metrics live inside the "tail" stdout capture; json.load has
    # already unescaped it, so a plain regex over the text applies.
    tail = str(doc.get("tail", ""))
    vals = [float(v) for v in _METRIC_RE.findall(tail)]
    if not vals:
        vals = [float(v) for v in _SUMMARY_RE.findall(tail)]
    backends = _BACKEND_RE.findall(tail)
    return (max(vals) if vals else None), (backends[-1] if backends else None)


def best_merges_per_sec(path: str) -> Optional[float]:
    """Best merges_per_sec in one round dump, or None when the round
    didn't run the dispatch benchmark (or the file is torn)."""
    return round_metrics(path)[0]


def load_rounds(
    bench_dir: str,
) -> List[Tuple[int, str, Optional[float], Optional[str]]]:
    """[(round_no, path, best-or-None, backend-or-None)] sorted by
    round number."""
    paths = sorted(
        glob.glob(os.path.join(bench_dir, "BENCH_r*.json")), key=round_number
    )
    return [(round_number(p), p, *round_metrics(p)) for p in paths]


def best_prior_carrier(
    rounds: List[tuple], idx: int, mode: str = "min"
) -> Tuple[int, float]:
    """(round_no, value) of the best PRIOR carrier for tuple column
    `idx`: the min (cost metrics — lower is better) or the max
    (throughput metrics) over every round but the last. Every
    double-threshold gate below compares rounds[-1][idx] against exactly
    this baseline; one helper instead of a per-gate copy of the
    min/max-over-prefix loop. Requires len(rounds) >= 2 (the callers'
    vacuous-pass checks guarantee it)."""
    prior = rounds[:-1]
    pick = min if mode == "min" else max
    best = pick(prior, key=lambda r: r[idx])
    return int(best[0]), float(best[idx])


def evaluate(
    rounds: List[Tuple[int, str, Optional[float], Optional[str]]],
    tolerance: float,
) -> Tuple[int, str]:
    """(exit_code, human verdict) for a parsed round list. Rounds are
    compared within their backend group only (None groups with None):
    throughput on the CPU CI fallback and on a real accelerator are
    different experiments, and cross-grading would either fail every
    CPU round or let a later CPU round reset the accelerator baseline."""
    with_metric = [r for r in rounds if r[2] is not None]
    if len(with_metric) < 2:
        return 0, (
            f"bench-gate: only {len(with_metric)} round(s) carry "
            "merges_per_sec — nothing to compare, passing vacuously"
        )
    code = 0
    lines: List[str] = []
    seen: List[Optional[str]] = []
    for be in (r[3] for r in with_metric):
        if be not in seen:
            seen.append(be)
    for be in seen:
        grp = [r for r in with_metric if r[3] == be]
        tag = f"[{be}]" if be is not None else ""
        if len(grp) < 2:
            lines.append(
                f"bench-gate{tag}: only {len(grp)} round(s) on this "
                "backend — nothing to compare, passing vacuously"
            )
            continue
        latest_n, _latest_p, latest_v, _ = grp[-1]
        best_n, best_v = best_prior_carrier(grp, 2, "max")
        floor = best_v * (1.0 - tolerance)
        verdict = (
            f"bench-gate{tag}: r{latest_n:02d} best merges_per_sec = "
            f"{latest_v:,.0f} vs best prior r{best_n:02d} = {best_v:,.0f} "
            f"(floor at -{tolerance:.0%}: {floor:,.0f})"
        )
        if latest_v < floor:
            code = 1
            lines.append(
                f"{verdict}\nFAIL: batched-dispatch throughput regressed "
                f"{1 - latest_v / best_v:.1%} (> {tolerance:.0%} allowed)"
            )
        else:
            lines.append(f"{verdict}\nOK: within tolerance")
    return code, "\n".join(lines)


def load_topo_rounds(bench_dir: str) -> List[Tuple[int, str, Dict]]:
    """[(round_no, path, cross_zone-dict)] for every ``TOPO_r<NN>.json``
    round committed by scripts/topo_demo.py — the DCN byte bill of each
    topology round, reported (not yet gated) alongside the throughput
    rounds so cross-zone regressions are visible at the same place."""
    out: List[Tuple[int, str, Dict]] = []
    for p in sorted(glob.glob(os.path.join(bench_dir, "TOPO_r*.json"))):
        m = re.search(r"TOPO_r(\d+)\.json$", os.path.basename(p))
        if not m:
            continue
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        out.append((int(m.group(1)), p, dict(doc.get("cross_zone") or {})))
    return out


_GAP_RE = re.compile(r'"dispatch_gap_ms_p50":\s*([0-9][0-9_.eE+-]*)')
_COV_RE = re.compile(r'"span_coverage_p50":\s*([0-9][0-9_.eE+-]*)')


def load_attribution_rounds(
    bench_dir: str,
) -> List[Tuple[int, str, float, float]]:
    """[(round_no, path, dispatch_gap_ms_p50, span_coverage_p50)] for
    every BENCH round whose summary line carries the span-attribution
    headline (bench.bench_round_phases, r6+). The GAP is gated by
    `evaluate_gap` since PR 7 made it a load-bearing perf claim; since
    PR 15 the same gate also asserts the latest round's COVERAGE >=
    0.90 — the ingest fast path bills the decode stage and the host
    backpressure wait, so coverage sliding back under 0.90 means spans
    stopped explaining where round time goes."""
    out: List[Tuple[int, str, float, float]] = []
    for p in sorted(
        glob.glob(os.path.join(bench_dir, "BENCH_r*.json")), key=round_number
    ):
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        tail = str(doc.get("tail", ""))
        gaps = _GAP_RE.findall(tail)
        covs = _COV_RE.findall(tail)
        if gaps and covs:
            out.append(
                (round_number(p), p, float(gaps[-1]), float(covs[-1]))
            )
    return out


def evaluate_gap(
    rounds: List[Tuple[int, str, float, float]],
    tolerance: float = 0.20,
    abs_floor_ms: float = 40.0,
    min_coverage: float = 0.90,
) -> Tuple[int, str]:
    """(exit_code, verdict) for the dispatch-gap gate: the latest
    attribution-bearing round fails when its ``dispatch_gap_ms_p50``
    grew more than `tolerance` relative AND more than `abs_floor_ms`
    absolute over the best (lowest) prior carrier. Both thresholds must
    trip: the overlap pipeline drives the gap toward zero, where a pure
    percentage gate would fail on microseconds of scheduler noise
    (0.01ms -> 0.02ms is "+100%" and means nothing). Fewer than two
    carriers pass vacuously.

    `abs_floor_ms` is sized for shared-CPU carriers: under a cgroup CPU
    quota the whole process freezes for one CFS throttle window
    (~20-30ms) roughly once per ~100ms round, landing at an arbitrary
    bytecode boundary no span can cover. r06-r08 never saw it only
    because the then-enormous wal_append spans happened to blanket the
    stall; once PR 11 shrank those spans the noise surfaced. The gate
    still catches what it was built for — a host tail (fsync, encode,
    send) sliding back onto the round thread is a 100ms-class jump,
    well past floor + best.

    The coverage floor applies to the LATEST carrier only (historical
    rounds predate the billed decode + backpressure spans and sat at
    ~0.82): under `min_coverage` the attribution itself is lying, so
    the gap number above it is untrustworthy."""
    if rounds:
        cov_n, _cp, _cg, cov = rounds[-1]
        if cov < min_coverage:
            return 1, (
                f"gap-gate: r{cov_n:02d} span_coverage_p50 = {cov:.4f} "
                f"< {min_coverage:.2f}\nFAIL: spans no longer explain "
                "where round wall time goes — fix attribution before "
                "trusting the gap"
            )
    if len(rounds) < 2:
        return 0, (
            f"gap-gate: only {len(rounds)} round(s) carry "
            "dispatch_gap_ms_p50 — nothing to compare, passing vacuously"
        )
    latest_n, _p, latest_gap, _cov = rounds[-1]
    best_n, best_gap = best_prior_carrier(rounds, 2, "min")
    ceiling = max(best_gap * (1.0 + tolerance), best_gap + abs_floor_ms)
    verdict = (
        f"gap-gate: r{latest_n:02d} dispatch_gap_ms_p50 = {latest_gap:.2f} "
        f"vs best prior r{best_n:02d} = {best_gap:.2f} "
        f"(ceiling +{tolerance:.0%} and +{abs_floor_ms}ms: {ceiling:.2f})"
    )
    if latest_gap > ceiling:
        return 1, (
            f"{verdict}\nFAIL: the dispatch gap regressed "
            f"{latest_gap - best_gap:+.2f}ms — host phases are sliding "
            "back onto the round thread"
        )
    return 0, f"{verdict}\nOK: within tolerance"


_INGEST_RE = re.compile(r'"ingest_phase_ms_total":\s*([0-9][0-9_.eE+-]*)')
_RATIO_RE = re.compile(r'"coalesce_ratio":\s*([0-9][0-9_.eE+-]*)')


def load_ingest_rounds(
    bench_dir: str,
) -> List[Tuple[int, str, float, float]]:
    """[(round_no, path, ingest_phase_ms_total, coalesce_ratio)] for
    every BENCH round whose summary line carries the ingest fast-path
    headline (bench.bench_round_phases, r10+): the combined wall time
    of the five ingest phases (gossip_recv + delta_decode +
    device_dispatch + delta_apply + device_sync) and the windows-per-
    wire-frame ratio (1.0 = no compaction)."""
    out: List[Tuple[int, str, float, float]] = []
    for p in sorted(
        glob.glob(os.path.join(bench_dir, "BENCH_r*.json")), key=round_number
    ):
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        tail = str(doc.get("tail", ""))
        ing = _INGEST_RE.findall(tail)
        rat = _RATIO_RE.findall(tail)
        if ing and rat:
            out.append(
                (round_number(p), p, float(ing[-1]), float(rat[-1]))
            )
    return out


def evaluate_ingest(
    rounds: List[Tuple[int, str, float, float]],
    tolerance: float = 0.20,
    abs_floor_ms: float = 50.0,
) -> Tuple[int, str]:
    """(exit_code, verdict) for the ingest-phase gate: the latest
    carrier fails when its ``ingest_phase_ms_total`` grew more than
    `tolerance` relative AND more than `abs_floor_ms` absolute over the
    best (lowest) prior carrier. Double-threshold for the same reason
    as the gap gate: the drill runs on shared-CPU carriers where a
    single CFS throttle window is tens of ms of unattributable stall —
    a relative-only gate would flap, an absolute-only gate would let a
    slow creep through. Fewer than two carriers pass vacuously."""
    if len(rounds) < 2:
        return 0, (
            f"ingest-gate: only {len(rounds)} round(s) carry "
            "ingest_phase_ms_total — nothing to compare, passing "
            "vacuously"
        )
    latest_n, _p, latest_ms, latest_ratio = rounds[-1]
    best_n, best_ms = best_prior_carrier(rounds, 2, "min")
    ceiling = max(best_ms * (1.0 + tolerance), best_ms + abs_floor_ms)
    verdict = (
        f"ingest-gate: r{latest_n:02d} ingest_phase_ms_total = "
        f"{latest_ms:.1f}ms (coalesce ratio {latest_ratio:.2f}) vs best "
        f"prior r{best_n:02d} = {best_ms:.1f}ms "
        f"(ceiling +{tolerance:.0%} and +{abs_floor_ms}ms: {ceiling:.1f})"
    )
    if latest_ms > ceiling:
        return 1, (
            f"{verdict}\nFAIL: the ingest path regressed "
            f"{latest_ms - best_ms:+.1f}ms — frames are decoding or "
            "applying serially again"
        )
    return 0, f"{verdict}\nOK: within tolerance"


_AE_RE = re.compile(r'"antientropy_bytes_per_resync":\s*([0-9][0-9_.eE+-]*)')
_REJOIN_RE = re.compile(r'"rejoin_stream_seconds":\s*([0-9][0-9_.eE+-]*)')


def load_partition_rounds(
    bench_dir: str,
) -> List[Tuple[int, str, float, float]]:
    """[(round_no, path, antientropy_bytes_per_resync,
    rejoin_stream_seconds)] for every BENCH round whose summary line
    carries the partition-plane metrics (bench.bench_partition_antientropy,
    r7+). The microbench runs a FIXED protocol geometry on every backend,
    so rounds compare without backend grouping."""
    out: List[Tuple[int, str, float, float]] = []
    for p in sorted(
        glob.glob(os.path.join(bench_dir, "BENCH_r*.json")), key=round_number
    ):
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        tail = str(doc.get("tail", ""))
        ae = _AE_RE.findall(tail)
        rj = _REJOIN_RE.findall(tail)
        if ae and rj:
            out.append((round_number(p), p, float(ae[-1]), float(rj[-1])))
    return out


def evaluate_partition(
    rounds: List[Tuple[int, str, float, float]],
    tolerance: float = 0.20,
    ae_floor_bytes: float = 512.0,
    rejoin_floor_s: float = 0.25,
) -> Tuple[int, str]:
    """(exit_code, verdict) for the partition-plane gate: the latest
    carrier fails when `antientropy_bytes_per_resync` or
    `rejoin_stream_seconds` grew more than `tolerance` relative AND more
    than the metric's absolute floor over the best (lowest) prior
    carrier — both thresholds must trip, same double-threshold shape as
    the dispatch-gap gate (psnaps are a few KB and a cold rejoin tens of
    milliseconds; a pure percentage would fail on codec jitter or one
    slow fsync). Fewer than two carriers pass vacuously."""
    if len(rounds) < 2:
        return 0, (
            f"partition-gate: only {len(rounds)} round(s) carry the "
            "anti-entropy metrics — nothing to compare, passing vacuously"
        )
    latest_n, _p, latest_ae, latest_rj = rounds[-1]
    best_ae_n, best_ae = best_prior_carrier(rounds, 2, "min")
    best_rj_n, best_rj = best_prior_carrier(rounds, 3, "min")
    code = 0
    lines: List[str] = []
    ae_ceiling = max(best_ae * (1.0 + tolerance), best_ae + ae_floor_bytes)
    verdict = (
        f"partition-gate: r{latest_n:02d} antientropy_bytes_per_resync = "
        f"{latest_ae:,.0f} vs best prior r{best_ae_n:02d} = {best_ae:,.0f} "
        f"(ceiling +{tolerance:.0%} and +{ae_floor_bytes:.0f}B: "
        f"{ae_ceiling:,.0f})"
    )
    if latest_ae > ae_ceiling:
        code = 1
        lines.append(
            f"{verdict}\nFAIL: a partial resync moves "
            f"{latest_ae - best_ae:+,.0f} bytes more — psnaps are "
            "fattening back toward whole snapshots"
        )
    else:
        lines.append(f"{verdict}\nOK: within tolerance")
    rj_ceiling = max(best_rj * (1.0 + tolerance), best_rj + rejoin_floor_s)
    verdict = (
        f"partition-gate: r{latest_n:02d} rejoin_stream_seconds = "
        f"{latest_rj:.3f} vs best prior r{best_rj_n:02d} = {best_rj:.3f} "
        f"(ceiling +{tolerance:.0%} and +{rejoin_floor_s}s: {rj_ceiling:.3f})"
    )
    if latest_rj > rj_ceiling:
        code = 1
        lines.append(
            f"{verdict}\nFAIL: the incremental rejoin stream slowed "
            f"{latest_rj - best_rj:+.3f}s over the best prior carrier"
        )
    else:
        lines.append(f"{verdict}\nOK: within tolerance")
    return code, "\n".join(lines)


_SERVE_RPS_RE = re.compile(r'"serve_reads_per_sec":\s*([0-9][0-9_.eE+-]*)')
_SERVE_P99_RE = re.compile(r'"serve_read_p99_ms":\s*([0-9][0-9_.eE+-]*)')
_NPROC_RE = re.compile(r'"nproc":\s*([0-9]+)')


def load_serve_rounds(
    bench_dir: str,
) -> List[Tuple[int, str, float, float, Optional[int]]]:
    """[(round_no, path, serve_reads_per_sec, serve_read_p99_ms,
    nproc-or-None)] for every BENCH round whose summary line carries the
    serving-plane metrics (bench.bench_serve, r8+). The host class rides
    along: serve throughput is pure host-CPU wall clock (stdlib JSON
    encode per answer, no accelerator), so a 1-core CI box measures the
    machine, not the code, when graded against a many-core carrier.
    ``nproc`` comes from the summary line (r10+) or a top-level carrier
    field; legacy carriers without either load as None."""
    out: List[Tuple[int, str, float, float, Optional[int]]] = []
    for p in sorted(
        glob.glob(os.path.join(bench_dir, "BENCH_r*.json")), key=round_number
    ):
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        tail = str(doc.get("tail", ""))
        rps = _SERVE_RPS_RE.findall(tail)
        p99 = _SERVE_P99_RE.findall(tail)
        nprocs = _NPROC_RE.findall(tail)
        nproc: Optional[int] = int(nprocs[-1]) if nprocs else None
        if nproc is None and isinstance(doc.get("nproc"), int):
            nproc = doc["nproc"]
        if rps and p99:
            out.append(
                (round_number(p), p, float(rps[-1]), float(p99[-1]), nproc)
            )
    return out


def evaluate_serve(
    rounds: List[Tuple[int, str, float, float, Optional[int]]],
    tolerance: float = 0.20,
    rps_floor_abs: float = 5_000.0,
    p99_floor_ms: float = 1.0,
) -> Tuple[int, str]:
    """(exit_code, verdict) for the serving-plane gate: the latest
    carrier fails when `serve_reads_per_sec` fell more than `tolerance`
    relative AND more than `rps_floor_abs` under the best prior, or
    `serve_read_p99_ms` grew more than `tolerance` relative AND more
    than `p99_floor_ms` over the best (lowest) prior — the same
    double-threshold shape as the other microbench gates (a per-frame
    p99 of a few ms would trip a pure percentage on scheduler jitter).

    Carriers compare within one host class (``nproc``) only — the same
    within-group rule the wal e2e gate applies to backends, and the same
    honesty fix as PR 11's shared-CPU gap floor: the serve plane is
    stdlib-Python bound, so reads/sec tracks the host's core count and
    single-thread speed, and grading a 1-core carrier against a
    many-core baseline flags the machine swap, not a code regression.
    A latest carrier alone in its class passes vacuously, with the
    cross-class delta printed report-only so it stays visible; legacy
    carriers without the field form the None class. Fewer than two
    carriers pass vacuously."""
    if len(rounds) < 2:
        return 0, (
            f"serve-gate: only {len(rounds)} round(s) carry the serving "
            "metrics — nothing to compare, passing vacuously"
        )
    host = rounds[-1][4]
    group = [r for r in rounds if r[4] == host]
    if len(group) < 2:
        cls = "unknown" if host is None else str(host)
        others = [r for r in rounds if r[4] != host]
        note = ""
        if others:
            ref = max(others, key=lambda r: r[2])
            note = (
                f"\nserve-gate: report-only cross-host reference: "
                f"r{rounds[-1][0]:02d} {rounds[-1][2]:,.0f}/s "
                f"p99 {rounds[-1][3]:.3f}ms vs r{ref[0]:02d} "
                f"{ref[2]:,.0f}/s p99 {ref[3]:.3f}ms "
                f"(nproc {'unknown' if ref[4] is None else ref[4]})"
            )
        return 0, (
            f"serve-gate: r{rounds[-1][0]:02d} is the only carrier in "
            f"host class nproc={cls} — nothing comparable, passing "
            f"vacuously{note}"
        )
    rounds = group
    latest_n, _p, latest_rps, latest_p99, _host = rounds[-1]
    best_rps_n, best_rps = best_prior_carrier(rounds, 2, "max")
    best_p99_n, best_p99 = best_prior_carrier(rounds, 3, "min")
    code = 0
    lines: List[str] = []
    rps_floor = min(best_rps * (1.0 - tolerance), best_rps - rps_floor_abs)
    verdict = (
        f"serve-gate: r{latest_n:02d} serve_reads_per_sec = "
        f"{latest_rps:,.0f} vs best prior r{best_rps_n:02d} = "
        f"{best_rps:,.0f} (floor -{tolerance:.0%} and "
        f"-{rps_floor_abs:,.0f}/s: {rps_floor:,.0f})"
    )
    if latest_rps < rps_floor:
        code = 1
        lines.append(
            f"{verdict}\nFAIL: the serving engine lost "
            f"{best_rps - latest_rps:,.0f} reads/sec over the best "
            "prior carrier"
        )
    else:
        lines.append(f"{verdict}\nOK: within tolerance")
    p99_ceiling = max(best_p99 * (1.0 + tolerance), best_p99 + p99_floor_ms)
    verdict = (
        f"serve-gate: r{latest_n:02d} serve_read_p99_ms = {latest_p99:.3f} "
        f"vs best prior r{best_p99_n:02d} = {best_p99:.3f} "
        f"(ceiling +{tolerance:.0%} and +{p99_floor_ms}ms: "
        f"{p99_ceiling:.3f})"
    )
    if latest_p99 > p99_ceiling:
        code = 1
        lines.append(
            f"{verdict}\nFAIL: the per-frame read tail slowed "
            f"{latest_p99 - best_p99:+.3f}ms over the best prior carrier"
        )
    else:
        lines.append(f"{verdict}\nOK: within tolerance")
    return code, "\n".join(lines)


_AUDIT_RE = re.compile(r'"audit_overhead_pct":\s*([0-9][0-9_.eE+-]*)')


def load_audit_rounds(bench_dir: str) -> List[Tuple[int, str, float]]:
    """[(round_no, path, audit_overhead_pct)] for every BENCH round
    whose summary line carries the audit-plane overhead
    (bench.bench_audit_overhead, r10+). Fixed protocol geometry on
    every backend, so rounds compare without backend grouping."""
    out: List[Tuple[int, str, float]] = []
    for p in sorted(
        glob.glob(os.path.join(bench_dir, "BENCH_r*.json")), key=round_number
    ):
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        tail = str(doc.get("tail", ""))
        ov = _AUDIT_RE.findall(tail)
        if ov:
            out.append((round_number(p), p, float(ov[-1])))
    return out


def evaluate_audit(
    rounds: List[Tuple[int, str, float]],
    tolerance: float = 0.20,
    abs_floor_pp: float = 1.0,
) -> Tuple[int, str]:
    """(exit_code, verdict) for the audit-overhead gate: the latest
    carrier fails when ``audit_overhead_pct`` grew more than `tolerance`
    relative AND more than `abs_floor_pp` percentage points absolute
    over the best (lowest) prior carrier — the double-threshold shape
    shared with the other microbench gates (overhead of a few percent
    would trip a pure relative gate on timer jitter alone). Fewer than
    two carriers pass vacuously."""
    if len(rounds) < 2:
        return 0, (
            f"audit-gate: only {len(rounds)} round(s) carry "
            "audit_overhead_pct — nothing to compare, passing vacuously"
        )
    latest_n, _p, latest_ov = rounds[-1]
    best_n, best_ov = best_prior_carrier(rounds, 2, "min")
    ceiling = max(best_ov * (1.0 + tolerance), best_ov + abs_floor_pp)
    verdict = (
        f"audit-gate: r{latest_n:02d} audit_overhead_pct = {latest_ov:.2f} "
        f"vs best prior r{best_n:02d} = {best_ov:.2f} "
        f"(ceiling +{tolerance:.0%} and +{abs_floor_pp}pp: {ceiling:.2f})"
    )
    if latest_ov > ceiling:
        return 1, (
            f"{verdict}\nFAIL: running certified now costs "
            f"{latest_ov - best_ov:+.2f}pp more per gossip round — the "
            "audit plane is leaking onto the hot path"
        )
    return 0, f"{verdict}\nOK: within tolerance"


_P99E2E_RE = re.compile(r'"p99_round_ms_e2e":\s*([0-9][0-9_.eE+-]*)')
_WAL_MS_RE = re.compile(r'"wal_append_ms_total":\s*([0-9][0-9_.eE+-]*)')
_WAL_GRP_RE = re.compile(r'"wal_group_size_p50":\s*([0-9][0-9_.eE+-]*)')
_CRIT_RE = re.compile(r'"critical_path":\s*\[([^\]]*)\]')


def load_wal_rounds(
    bench_dir: str,
) -> List[Tuple[int, str, Optional[float], Optional[float],
                Optional[float], Optional[int]]]:
    """[(round_no, path, p99_round_ms_e2e, wal_append_ms_total,
    wal_group_size_p50, wal_critical_rank, backend)] for every BENCH
    round that carries the overlapped-e2e headline. The WAL columns are
    None before r9 (bench.py folded them into the summary with the
    PR 11 group-commit work); `wal_critical_rank` is round.wal_append's
    position in the phase critical path (0 = the most expensive phase),
    None when the round has no attribution. The backend tag rides along
    so `evaluate_wal` compares carriers within one backend group only —
    an e2e tail measured on the CPU fallback is a different experiment
    from an accelerator one (same rule as the merges gate)."""
    out: List[Tuple[int, str, Optional[float], Optional[float],
                    Optional[float], Optional[int], Optional[str]]] = []
    for p in sorted(
        glob.glob(os.path.join(bench_dir, "BENCH_r*.json")), key=round_number
    ):
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        tail = str(doc.get("tail", ""))
        p99s = _P99E2E_RE.findall(tail)
        if not p99s:
            continue
        wal_ms = _WAL_MS_RE.findall(tail)
        grp = _WAL_GRP_RE.findall(tail)
        crit = _CRIT_RE.findall(tail)
        rank: Optional[int] = None
        if crit:
            phases = [s.strip().strip('"') for s in crit[-1].split(",")]
            if "round.wal_append" in phases:
                rank = phases.index("round.wal_append")
        backends = _BACKEND_RE.findall(tail)
        out.append((
            round_number(p), p, float(p99s[-1]),
            float(wal_ms[-1]) if wal_ms else None,
            float(grp[-1]) if grp else None,
            rank,
            backends[-1] if backends else None,
        ))
    return out


def evaluate_wal(
    rounds: List[Tuple[int, str, Optional[float], Optional[float],
                       Optional[float], Optional[int], Optional[str]]],
    tolerance: float = 0.20,
    p99_floor_ms: float = 25.0,
) -> Tuple[int, str]:
    """(exit_code, verdict) for the durability-path gate (PR 11), two
    claims:

    * ``p99_round_ms_e2e`` — the overlapped end-to-end round tail must
      not regress more than `tolerance` relative AND `p99_floor_ms`
      absolute over the best (lowest) prior carrier OF THE SAME BACKEND
      (the shared double-threshold shape: a CPU carrier's p99 jitters
      tens of ms; and CPU vs accelerator tails are different
      experiments, same grouping rule as the merges gate).
    * critical-path rank — `round.wal_append` must not be the #1 phase
      on the latest attribution-bearing round: group commit's whole
      point is that durability rides the round instead of dominating
      it. Rank is an absolute claim about the latest round, so it needs
      no prior carrier (but only fires when the round carries the WAL
      columns at all — pre-r9 rounds pass through untouched).

    Fewer than two comparable p99 carriers pass that half vacuously."""
    code = 0
    lines: List[str] = []
    grp_rounds = (
        [r for r in rounds if r[6] == rounds[-1][6]] if rounds else []
    )
    tag = f"[{rounds[-1][6]}]" if rounds and rounds[-1][6] else ""
    if len(grp_rounds) < 2:
        lines.append(
            f"wal-gate{tag}: only {len(grp_rounds)} round(s) carry "
            "p99_round_ms_e2e on this backend — nothing to compare, "
            "passing vacuously"
        )
    else:
        latest_n, _p, latest_p99, _w, _g, _r, _be = grp_rounds[-1]
        best_n, best_p99 = best_prior_carrier(grp_rounds, 2, "min")
        ceiling = max(best_p99 * (1.0 + tolerance), best_p99 + p99_floor_ms)
        verdict = (
            f"wal-gate{tag}: r{latest_n:02d} p99_round_ms_e2e = "
            f"{latest_p99:.2f} vs best prior r{best_n:02d} = {best_p99:.2f} "
            f"(ceiling +{tolerance:.0%} and +{p99_floor_ms:.0f}ms: "
            f"{ceiling:.2f})"
        )
        if latest_p99 > ceiling:
            code = 1
            lines.append(
                f"{verdict}\nFAIL: the end-to-end round tail regressed "
                f"{latest_p99 - best_p99:+.2f}ms over the best prior "
                "carrier"
            )
        else:
            lines.append(f"{verdict}\nOK: within tolerance")
    latest_with_wal = next(
        (r for r in reversed(rounds) if r[3] is not None), None
    )
    if latest_with_wal is None:
        lines.append(
            "wal-gate: no round carries wal_append_ms_total yet — "
            "critical-path rank unchecked, passing vacuously"
        )
    else:
        n, _p, _e, wal_ms, grp, rank, _be = latest_with_wal
        verdict = (
            f"wal-gate: r{n:02d} wal_append {wal_ms:,.1f}ms total, "
            f"group size p50 {grp if grp is not None else float('nan'):.0f}, "
            f"critical-path rank "
            f"{'#%d' % (rank + 1) if rank is not None else 'n/a'}"
        )
        if rank == 0:
            code = 1
            lines.append(
                f"{verdict}\nFAIL: round.wal_append is the #1 phase on "
                "the critical path again — the durability hot path "
                "regressed to pre-group-commit behavior"
            )
        else:
            lines.append(f"{verdict}\nOK: wal_append off the top of the "
                         "critical path")
    return code, "\n".join(lines)


_PAGER_HIT_RE = re.compile(r'"pager_hit_rate":\s*([0-9][0-9_.eE+-]*)')
_PAGER_MISS_RE = re.compile(
    r'"resident_miss_ms_p50":\s*([0-9][0-9_.eE+-]*)'
)
_PAGER_CM_RE = re.compile(r'"cold_merges_per_sec":\s*([0-9][0-9_.eE+-]*)')


def load_pager_rounds(
    bench_dir: str,
) -> List[Tuple[int, str, float, float, Optional[float]]]:
    """[(round_no, path, pager_hit_rate, resident_miss_ms_p50,
    cold_merges_per_sec)] for every BENCH round whose summary line
    carries the out-of-core working-set metrics (bench.bench_working_set,
    r13+). Fixed zipfian geometry on every backend, so rounds compare
    without backend grouping; cold_merges_per_sec rides report-only."""
    out: List[Tuple[int, str, float, float, Optional[float]]] = []
    for p in sorted(
        glob.glob(os.path.join(bench_dir, "BENCH_r*.json")), key=round_number
    ):
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        tail = str(doc.get("tail", ""))
        hit = _PAGER_HIT_RE.findall(tail)
        miss = _PAGER_MISS_RE.findall(tail)
        cm = _PAGER_CM_RE.findall(tail)
        if hit and miss:
            out.append((
                round_number(p), p, float(hit[-1]), float(miss[-1]),
                float(cm[-1]) if cm else None,
            ))
    return out


def evaluate_pager(
    rounds: List[Tuple[int, str, float, float, Optional[float]]],
    tolerance: float = 0.20,
    hit_floor_pp: float = 0.05,
    miss_floor_ms: float = 2.0,
) -> Tuple[int, str]:
    """(exit_code, verdict) for the out-of-core pager gate, two claims
    with the shared double-threshold shape (relative AND absolute must
    both trip):

    * ``pager_hit_rate`` — the zipfian working-set hit rate must not
      FALL more than `tolerance` relative and `hit_floor_pp` (5pp)
      absolute under the best prior carrier: the clock policy drifting
      away from the hot set is the regression out-of-core serving
      cannot survive;
    * ``resident_miss_ms_p50`` — the median page-in stall must not GROW
      more than `tolerance` relative and `miss_floor_ms` absolute over
      the best (lowest) prior carrier: hydration sliding from one
      decode+join toward whole-state rebuilds fails here.

    ``cold_merges_per_sec`` rides the same summary line report-only.
    Fewer than two carriers pass vacuously."""
    if len(rounds) < 2:
        return 0, (
            f"pager-gate: only {len(rounds)} round(s) carry the "
            "working-set metrics — nothing to compare, passing vacuously"
        )
    latest_n, _p, latest_hit, latest_miss, _cm = rounds[-1]
    best_hit_n, best_hit = best_prior_carrier(rounds, 2, "max")
    best_miss_n, best_miss = best_prior_carrier(rounds, 3, "min")
    code = 0
    lines: List[str] = []

    hit_floor = min(best_hit * (1.0 - tolerance), best_hit - hit_floor_pp)
    verdict = (
        f"pager-gate: r{latest_n:02d} pager_hit_rate = {latest_hit:.3f} "
        f"vs best prior r{best_hit_n:02d} = {best_hit:.3f} "
        f"(floor -{tolerance:.0%} and -{hit_floor_pp * 100:.0f}pp: "
        f"{hit_floor:.3f})"
    )
    if latest_hit < hit_floor:
        code = 1
        lines.append(
            f"{verdict}\nFAIL: the residency policy lost "
            f"{(best_hit - latest_hit) * 100:.1f}pp of working-set hits "
            "— eviction is drifting away from the hot set"
        )
    else:
        lines.append(f"{verdict}\nOK: within tolerance")

    miss_ceiling = max(
        best_miss * (1.0 + tolerance), best_miss + miss_floor_ms
    )
    verdict = (
        f"pager-gate: r{latest_n:02d} resident_miss_ms_p50 = "
        f"{latest_miss:.3f} vs best prior r{best_miss_n:02d} = "
        f"{best_miss:.3f} (ceiling +{tolerance:.0%} and "
        f"+{miss_floor_ms}ms: {miss_ceiling:.3f})"
    )
    if latest_miss > miss_ceiling:
        code = 1
        lines.append(
            f"{verdict}\nFAIL: the median page-in stall slowed "
            f"{latest_miss - best_miss:+.3f}ms over the best prior "
            "carrier"
        )
    else:
        lines.append(f"{verdict}\nOK: within tolerance")
    return code, "\n".join(lines)


def load_mesh_rounds(
    bench_dir: str,
) -> List[Tuple[int, str, float, float, float]]:
    """[(round_no, path, mesh_merges_per_sec, ici_reduce_ms_p50,
    cross_slice_bytes)] for every ``MULTICHIP_r<NN>.json`` carrier
    committed by scripts/multichip_demo.py (r6+). The r01-r05 carriers
    are the legacy dryrun dumps (n_devices/rc/tail only) and carry none
    of the metric keys — skipped, not zeros. Fixed 8-virtual-device
    protocol geometry on every backend, so rounds compare without
    backend grouping."""
    out: List[Tuple[int, str, float, float, float]] = []
    for p in sorted(glob.glob(os.path.join(bench_dir, "MULTICHIP_r*.json"))):
        m = re.search(r"MULTICHIP_r(\d+)\.json$", os.path.basename(p))
        if not m:
            continue
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        keys = ("mesh_merges_per_sec", "ici_reduce_ms_p50",
                "cross_slice_bytes")
        if not all(isinstance(doc.get(k), (int, float)) for k in keys):
            continue
        out.append((
            int(m.group(1)), p,
            float(doc["mesh_merges_per_sec"]),
            float(doc["ici_reduce_ms_p50"]),
            float(doc["cross_slice_bytes"]),
        ))
    out.sort(key=lambda r: r[0])
    return out


def evaluate_mesh(
    rounds: List[Tuple[int, str, float, float, float]],
    tolerance: float = 0.20,
    merges_floor_abs: float = 200.0,
    ici_floor_ms: float = 2.0,
    bytes_floor: float = 2048.0,
) -> Tuple[int, str]:
    """(exit_code, verdict) for the mesh-plane gate over the MULTICHIP
    carriers, three claims with the shared double-threshold shape (both
    the relative AND the absolute bar must trip — CPU-rig reduce
    latencies are single-digit ms and jitter, and the byte bill moves
    with codec framing):

    * ``mesh_merges_per_sec`` must not FALL more than `tolerance`
      relative and `merges_floor_abs` absolute under the best prior;
    * ``ici_reduce_ms_p50`` must not GROW more than `tolerance` and
      `ici_floor_ms` over the best (lowest) prior — the batched
      collective sliding back toward per-row dispatch fails here;
    * ``cross_slice_bytes`` must not GROW more than `tolerance` and
      `bytes_floor` over the best (lowest) prior — anti-entropy
      fattening from shard-local slices back toward whole-instance
      snapshots fails here.

    Fewer than two carriers pass vacuously."""
    if len(rounds) < 2:
        return 0, (
            f"mesh-gate: only {len(rounds)} round(s) carry the mesh "
            "metrics — nothing to compare, passing vacuously"
        )
    latest_n, _p, latest_mps, latest_ici, latest_bytes = rounds[-1]
    best_mps_n, best_mps = best_prior_carrier(rounds, 2, "max")
    best_ici_n, best_ici = best_prior_carrier(rounds, 3, "min")
    best_byt_n, best_bytes = best_prior_carrier(rounds, 4, "min")
    code = 0
    lines: List[str] = []

    mps_floor = min(
        best_mps * (1.0 - tolerance), best_mps - merges_floor_abs
    )
    verdict = (
        f"mesh-gate: r{latest_n:02d} mesh_merges_per_sec = "
        f"{latest_mps:,.0f} vs best prior r{best_mps_n:02d} = "
        f"{best_mps:,.0f} (floor -{tolerance:.0%} and "
        f"-{merges_floor_abs:,.0f}/s: {mps_floor:,.0f})"
    )
    if latest_mps < mps_floor:
        code = 1
        lines.append(
            f"{verdict}\nFAIL: the ICI reduce lost "
            f"{best_mps - latest_mps:,.0f} merges/sec over the best "
            "prior carrier"
        )
    else:
        lines.append(f"{verdict}\nOK: within tolerance")

    ici_ceiling = max(best_ici * (1.0 + tolerance), best_ici + ici_floor_ms)
    verdict = (
        f"mesh-gate: r{latest_n:02d} ici_reduce_ms_p50 = {latest_ici:.3f} "
        f"vs best prior r{best_ici_n:02d} = {best_ici:.3f} "
        f"(ceiling +{tolerance:.0%} and +{ici_floor_ms}ms: "
        f"{ici_ceiling:.3f})"
    )
    if latest_ici > ici_ceiling:
        code = 1
        lines.append(
            f"{verdict}\nFAIL: the intra-slice reduce slowed "
            f"{latest_ici - best_ici:+.3f}ms — the batched collective "
            "is regressing toward per-row dispatch"
        )
    else:
        lines.append(f"{verdict}\nOK: within tolerance")

    byt_ceiling = max(
        best_bytes * (1.0 + tolerance), best_bytes + bytes_floor
    )
    verdict = (
        f"mesh-gate: r{latest_n:02d} cross_slice_bytes = "
        f"{latest_bytes:,.0f} vs best prior r{best_byt_n:02d} = "
        f"{best_bytes:,.0f} (ceiling +{tolerance:.0%} and "
        f"+{bytes_floor:.0f}B: {byt_ceiling:,.0f})"
    )
    if latest_bytes > byt_ceiling:
        code = 1
        lines.append(
            f"{verdict}\nFAIL: a cross-slice repair moves "
            f"{latest_bytes - best_bytes:+,.0f} bytes more — shard-local "
            "slices are fattening back toward whole-instance snapshots"
        )
    else:
        lines.append(f"{verdict}\nOK: within tolerance")
    return code, "\n".join(lines)


def load_router_rounds(
    bench_dir: str,
) -> List[Tuple[int, str, float, float, float]]:
    """[(round_no, path, fleet_reads_per_sec, read_p99_ms,
    failover_blip_ms)] for every ``READTIER_r<NN>.json`` carrier
    committed by scripts/read_tier_demo.py. Carriers missing any of the
    three metric keys are skipped, not zeros."""
    out: List[Tuple[int, str, float, float, float]] = []
    for p in sorted(glob.glob(os.path.join(bench_dir, "READTIER_r*.json"))):
        m = re.search(r"READTIER_r(\d+)\.json$", os.path.basename(p))
        if not m:
            continue
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        keys = ("fleet_reads_per_sec", "read_p99_ms", "failover_blip_ms")
        if not all(isinstance(doc.get(k), (int, float)) for k in keys):
            continue
        out.append((
            int(m.group(1)), p,
            float(doc["fleet_reads_per_sec"]),
            float(doc["read_p99_ms"]),
            float(doc["failover_blip_ms"]),
        ))
    out.sort(key=lambda r: r[0])
    return out


def evaluate_router(
    rounds: List[Tuple[int, str, float, float, float]],
    tolerance: float = 0.20,
    reads_floor_abs: float = 2000.0,
    p99_floor_ms: float = 2.0,
    blip_floor_ms: float = 250.0,
) -> Tuple[int, str]:
    """(exit_code, verdict) for the fleet read tier over the READTIER
    carriers — three claims with the shared double-threshold shape
    (both the relative AND the absolute bar must trip; the drill runs
    real sockets under seeded chaos, so single-run jitter is large):

    * ``fleet_reads_per_sec`` must not FALL more than `tolerance`
      relative and `reads_floor_abs` absolute under the best prior;
    * ``read_p99_ms`` must not GROW more than `tolerance` and
      `p99_floor_ms` over the best (lowest) prior — routing overhead
      creeping into every read fails here;
    * ``failover_blip_ms`` must not GROW more than `tolerance` and
      `blip_floor_ms` over the best (lowest) prior — mid-query failover
      sliding back toward timeout-waiting fails here.

    Fewer than two carriers pass vacuously."""
    if len(rounds) < 2:
        return 0, (
            f"router-gate: only {len(rounds)} round(s) carry the read-tier "
            "metrics — nothing to compare, passing vacuously"
        )
    latest_n, _p, latest_rps, latest_p99, latest_blip = rounds[-1]
    best_rps_n, best_rps = best_prior_carrier(rounds, 2, "max")
    best_p99_n, best_p99 = best_prior_carrier(rounds, 3, "min")
    best_blip_n, best_blip = best_prior_carrier(rounds, 4, "min")
    code = 0
    lines: List[str] = []

    rps_floor = min(
        best_rps * (1.0 - tolerance), best_rps - reads_floor_abs
    )
    verdict = (
        f"router-gate: r{latest_n:02d} fleet_reads_per_sec = "
        f"{latest_rps:,.0f} vs best prior r{best_rps_n:02d} = "
        f"{best_rps:,.0f} (floor -{tolerance:.0%} and "
        f"-{reads_floor_abs:,.0f}/s: {rps_floor:,.0f})"
    )
    if latest_rps < rps_floor:
        code = 1
        lines.append(
            f"{verdict}\nFAIL: the routed fleet lost "
            f"{best_rps - latest_rps:,.0f} reads/sec over the best "
            "prior carrier"
        )
    else:
        lines.append(f"{verdict}\nOK: within tolerance")

    p99_ceiling = max(
        best_p99 * (1.0 + tolerance), best_p99 + p99_floor_ms
    )
    verdict = (
        f"router-gate: r{latest_n:02d} read_p99_ms = {latest_p99:.3f} "
        f"vs best prior r{best_p99_n:02d} = {best_p99:.3f} "
        f"(ceiling +{tolerance:.0%} and +{p99_floor_ms}ms: "
        f"{p99_ceiling:.3f})"
    )
    if latest_p99 > p99_ceiling:
        code = 1
        lines.append(
            f"{verdict}\nFAIL: the routed read tail slowed "
            f"{latest_p99 - best_p99:+.3f}ms — routing overhead is "
            "leaking into every read"
        )
    else:
        lines.append(f"{verdict}\nOK: within tolerance")

    blip_ceiling = max(
        best_blip * (1.0 + tolerance), best_blip + blip_floor_ms
    )
    verdict = (
        f"router-gate: r{latest_n:02d} failover_blip_ms = "
        f"{latest_blip:,.0f} vs best prior r{best_blip_n:02d} = "
        f"{best_blip:,.0f} (ceiling +{tolerance:.0%} and "
        f"+{blip_floor_ms:.0f}ms: {blip_ceiling:,.0f})"
    )
    if latest_blip > blip_ceiling:
        code = 1
        lines.append(
            f"{verdict}\nFAIL: the SIGKILL blip grew "
            f"{latest_blip - best_blip:+,.0f}ms — mid-query failover is "
            "regressing toward waiting out dead-peer timeouts"
        )
    else:
        lines.append(f"{verdict}\nOK: within tolerance")
    return code, "\n".join(lines)


def load_write_rounds(
    bench_dir: str,
) -> List[Tuple[int, str, float, float, float, Optional[bool]]]:
    """[(round_no, path, fleet_writes_per_sec, write_p99_ms,
    failover_blip_ms, passed)] for every ``WRITETIER_r<NN>.json``
    carrier committed by scripts/write_tier_demo.py. Carriers missing
    any of the three metric keys are skipped, not zeros; ``passed`` is
    the carrier's own chaos-check verdict (None when absent)."""
    out: List[Tuple[int, str, float, float, float, Optional[bool]]] = []
    for p in sorted(glob.glob(os.path.join(bench_dir, "WRITETIER_r*.json"))):
        m = re.search(r"WRITETIER_r(\d+)\.json$", os.path.basename(p))
        if not m:
            continue
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        keys = ("fleet_writes_per_sec", "write_p99_ms", "failover_blip_ms")
        if not all(isinstance(doc.get(k), (int, float)) for k in keys):
            continue
        passed = doc.get("pass")
        out.append((
            int(m.group(1)), p,
            float(doc["fleet_writes_per_sec"]),
            float(doc["write_p99_ms"]),
            float(doc["failover_blip_ms"]),
            bool(passed) if isinstance(passed, bool) else None,
        ))
    out.sort(key=lambda r: r[0])
    return out


def evaluate_write(
    rounds: List[Tuple[int, str, float, float, float, Optional[bool]]],
    tolerance: float = 0.20,
    writes_floor_abs: float = 1.0,
    p99_floor_ms: float = 2000.0,
    blip_floor_ms: float = 1000.0,
) -> Tuple[int, str]:
    """(exit_code, verdict) for the fleet write tier over the WRITETIER
    carriers — the router gate's shape, plus one unconditional claim:

    * the latest carrier's own ``pass`` verdict must be True — the demo
      certifies zero acked-but-lost writes and convicts the deliberate
      ack-before-fsync arm, and a carrier that failed its own checks
      must never gate green (this claim fires even with one round);
    * ``fleet_writes_per_sec`` must not FALL more than `tolerance`
      relative and `writes_floor_abs` absolute under the best prior;
    * ``write_p99_ms`` must not GROW more than `tolerance` and
      `p99_floor_ms` over the best (lowest) prior — the ack path rides
      the worker step cadence, so the floor is generous;
    * ``failover_blip_ms`` must not GROW more than `tolerance` and
      `blip_floor_ms` over the best (lowest) prior — owner failover
      sliding back toward waiting out dead-peer timeouts fails here.

    The three drift claims pass vacuously with fewer than two rounds."""
    if not rounds:
        return 0, (
            "write-gate: no WRITETIER carriers — nothing to compare, "
            "passing vacuously"
        )
    latest = rounds[-1]
    latest_n, _p, latest_wps, latest_p99, latest_blip, latest_pass = latest
    code = 0
    lines: List[str] = []

    if latest_pass is False:
        code = 1
        lines.append(
            f"write-gate: r{latest_n:02d} carries pass=false\n"
            "FAIL: the latest write-tier drill failed its own chaos "
            "checks — regenerate the carrier with `make write-tier-demo` "
            "and fix what it names before gating on drift"
        )
    else:
        lines.append(
            f"write-gate: r{latest_n:02d} chaos checks "
            f"{'passed' if latest_pass else 'absent (legacy carrier)'}"
        )

    if len(rounds) < 2:
        lines.append(
            f"write-gate: only {len(rounds)} round(s) carry the "
            "write-tier metrics — no drift to compare, passing vacuously"
        )
        return code, "\n".join(lines)

    best_wps_n, best_wps = best_prior_carrier(rounds, 2, "max")
    best_p99_n, best_p99 = best_prior_carrier(rounds, 3, "min")
    best_blip_n, best_blip = best_prior_carrier(rounds, 4, "min")

    wps_floor = min(
        best_wps * (1.0 - tolerance), best_wps - writes_floor_abs
    )
    verdict = (
        f"write-gate: r{latest_n:02d} fleet_writes_per_sec = "
        f"{latest_wps:,.2f} vs best prior r{best_wps_n:02d} = "
        f"{best_wps:,.2f} (floor -{tolerance:.0%} and "
        f"-{writes_floor_abs:,.1f}/s: {wps_floor:,.2f})"
    )
    if latest_wps < wps_floor:
        code = 1
        lines.append(
            f"{verdict}\nFAIL: the write fleet lost "
            f"{best_wps - latest_wps:,.2f} acked bursts/sec over the "
            "best prior carrier"
        )
    else:
        lines.append(f"{verdict}\nOK: within tolerance")

    p99_ceiling = max(
        best_p99 * (1.0 + tolerance), best_p99 + p99_floor_ms
    )
    verdict = (
        f"write-gate: r{latest_n:02d} write_p99_ms = {latest_p99:,.0f} "
        f"vs best prior r{best_p99_n:02d} = {best_p99:,.0f} "
        f"(ceiling +{tolerance:.0%} and +{p99_floor_ms:,.0f}ms: "
        f"{p99_ceiling:,.0f})"
    )
    if latest_p99 > p99_ceiling:
        code = 1
        lines.append(
            f"{verdict}\nFAIL: the durable-ack tail slowed "
            f"{latest_p99 - best_p99:+,.0f}ms — the ack path is drifting "
            "past the step-cadence budget"
        )
    else:
        lines.append(f"{verdict}\nOK: within tolerance")

    blip_ceiling = max(
        best_blip * (1.0 + tolerance), best_blip + blip_floor_ms
    )
    verdict = (
        f"write-gate: r{latest_n:02d} failover_blip_ms = "
        f"{latest_blip:,.0f} vs best prior r{best_blip_n:02d} = "
        f"{best_blip:,.0f} (ceiling +{tolerance:.0%} and "
        f"+{blip_floor_ms:,.0f}ms: {blip_ceiling:,.0f})"
    )
    if latest_blip > blip_ceiling:
        code = 1
        lines.append(
            f"{verdict}\nFAIL: the owner-SIGKILL blip grew "
            f"{latest_blip - best_blip:+,.0f}ms — write failover is "
            "regressing toward waiting out dead-owner timeouts"
        )
    else:
        lines.append(f"{verdict}\nOK: within tolerance")
    return code, "\n".join(lines)


def load_rtrace_rounds(
    bench_dir: str,
) -> List[Tuple[int, str, float, float, float, Optional[bool]]]:
    """[(round_no, path, traced_reads_per_sec, overhead_pct,
    coverage_p50, passed)] for every ``RTRACE_r<NN>.json`` carrier
    committed by scripts/rtrace_demo.py. Carriers missing any of the
    three metric keys are skipped, not zeros; ``passed`` is the
    carrier's own chaos-check verdict (None when absent)."""
    out: List[Tuple[int, str, float, float, float, Optional[bool]]] = []
    for p in sorted(glob.glob(os.path.join(bench_dir, "RTRACE_r*.json"))):
        m = re.search(r"RTRACE_r(\d+)\.json$", os.path.basename(p))
        if not m:
            continue
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        keys = ("traced_reads_per_sec", "overhead_pct", "coverage_p50")
        if not all(isinstance(doc.get(k), (int, float)) for k in keys):
            continue
        passed = doc.get("pass")
        out.append((
            int(m.group(1)), p,
            float(doc["traced_reads_per_sec"]),
            float(doc["overhead_pct"]),
            float(doc["coverage_p50"]),
            bool(passed) if isinstance(passed, bool) else None,
        ))
    out.sort(key=lambda r: r[0])
    return out


def evaluate_rtrace(
    rounds: List[Tuple[int, str, float, float, float, Optional[bool]]],
    tolerance: float = 0.20,
    overhead_ceiling_pct: float = 5.0,
    coverage_floor_abs: float = 0.05,
) -> Tuple[int, str]:
    """(exit_code, verdict) for the request-tracing plane over the
    RTRACE carriers — the write gate's shape, with TWO unconditional
    claims that fire even on the very first round:

    * the latest carrier's own ``pass`` verdict must be True — the demo
      checks gap-free waterfalls, attribution coverage, the p99
      exemplar resolving to a stored trace, and the dead_reroute hop,
      and a carrier that failed its own checks must never gate green;
    * ``overhead_pct`` — sampled-on throughput loss vs the carrier's
      own ``CCRDT_RTRACE=0`` rerun — must stay under
      `overhead_ceiling_pct` ABSOLUTE: tracing that taxes the serve
      read path more than 5% is not an observability plane, it is a
      perf regression wearing one's clothes;
    * ``coverage_p50`` must not FALL more than `tolerance` relative and
      `coverage_floor_abs` absolute under the best prior — attribution
      silently un-explaining latency is the trace-plane analogue of a
      counter going dark (vacuous with fewer than two rounds)."""
    if not rounds:
        return 0, (
            "rtrace-gate: no RTRACE carriers — nothing to compare, "
            "passing vacuously"
        )
    latest = rounds[-1]
    latest_n, _p, _rps, latest_ov, latest_cov, latest_pass = latest
    code = 0
    lines: List[str] = []

    if latest_pass is False:
        code = 1
        lines.append(
            f"rtrace-gate: r{latest_n:02d} carries pass=false\n"
            "FAIL: the latest rtrace drill failed its own chaos checks — "
            "regenerate the carrier with `make rtrace-demo` and fix what "
            "it names before gating on drift"
        )
    else:
        lines.append(
            f"rtrace-gate: r{latest_n:02d} chaos checks "
            f"{'passed' if latest_pass else 'absent (legacy carrier)'}"
        )

    verdict = (
        f"rtrace-gate: r{latest_n:02d} overhead_pct = {latest_ov:.2f} "
        f"(ceiling {overhead_ceiling_pct:.1f}% absolute, vs the "
        "carrier's own CCRDT_RTRACE=0 rerun)"
    )
    if latest_ov > overhead_ceiling_pct:
        code = 1
        lines.append(
            f"{verdict}\nFAIL: tracing taxes the serve read path "
            f"{latest_ov:.2f}% — over the {overhead_ceiling_pct:.1f}% "
            "budget"
        )
    else:
        lines.append(f"{verdict}\nOK: within budget")

    if len(rounds) < 2:
        lines.append(
            f"rtrace-gate: only {len(rounds)} round(s) carry the rtrace "
            "metrics — no drift to compare, passing vacuously"
        )
        return code, "\n".join(lines)

    best_cov_n, best_cov = best_prior_carrier(rounds, 4, "max")
    cov_floor = min(
        best_cov * (1.0 - tolerance), best_cov - coverage_floor_abs
    )
    verdict = (
        f"rtrace-gate: r{latest_n:02d} coverage_p50 = {latest_cov:.4f} "
        f"vs best prior r{best_cov_n:02d} = {best_cov:.4f} (floor "
        f"-{tolerance:.0%} and -{coverage_floor_abs:.2f}: {cov_floor:.4f})"
    )
    if latest_cov < cov_floor:
        code = 1
        lines.append(
            f"{verdict}\nFAIL: attribution coverage lost "
            f"{best_cov - latest_cov:.4f} — hop instrumentation is "
            "going dark somewhere on the request path"
        )
    else:
        lines.append(f"{verdict}\nOK: within tolerance")
    return code, "\n".join(lines)


def load_devprof_rounds(
    bench_dir: str,
) -> List[Tuple[int, str, float, float, float, Optional[bool]]]:
    """[(round_no, path, recompiles_per_100_rounds, compile_ms_share_pct,
    overhead_pct, passed)] for every ``DEVPROF_r<NN>.json`` carrier
    committed by scripts/devprof_demo.py. Carriers missing any of the
    three metric keys are skipped, not zeros; ``passed`` is the
    carrier's own check verdict (None when absent)."""
    out: List[Tuple[int, str, float, float, float, Optional[bool]]] = []
    for p in sorted(glob.glob(os.path.join(bench_dir, "DEVPROF_r*.json"))):
        m = re.search(r"DEVPROF_r(\d+)\.json$", os.path.basename(p))
        if not m:
            continue
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        keys = (
            "recompiles_per_100_rounds", "compile_ms_share_pct",
            "overhead_pct",
        )
        if not all(isinstance(doc.get(k), (int, float)) for k in keys):
            continue
        passed = doc.get("pass")
        out.append((
            int(m.group(1)), p,
            float(doc["recompiles_per_100_rounds"]),
            float(doc["compile_ms_share_pct"]),
            float(doc["overhead_pct"]),
            bool(passed) if isinstance(passed, bool) else None,
        ))
    out.sort(key=lambda r: r[0])
    return out


def evaluate_devprof(
    rounds: List[Tuple[int, str, float, float, float, Optional[bool]]],
    tolerance: float = 0.20,
    overhead_ceiling_pct: float = 2.0,
    recompile_floor_abs: float = 2.0,
    share_floor_abs: float = 0.5,
) -> Tuple[int, str]:
    """(exit_code, verdict) for the device observatory over the DEVPROF
    carriers — the rtrace gate's shape, with TWO unconditional claims
    that fire even on the very first round:

    * the latest carrier's own ``pass`` verdict must be True — the demo
      checks 100% compile attribution, capacity growth named dominant,
      the >=5x warm-up cut, and the byte-identical kill-switch arm, and
      a carrier that failed its own checks must never gate green;
    * ``overhead_pct`` — armed-vs-CCRDT_DEVPROF=0 wall time on paired
      alternating rounds — must stay under `overhead_ceiling_pct`
      ABSOLUTE: an observatory that taxes every dispatch more than 2%
      is a perf regression wearing telemetry's clothes;
    * steady-state ``recompiles_per_100_rounds`` and
      ``compile_ms_share_pct`` must not RISE more than `tolerance`
      relative and their absolute floors under the best (lowest) prior
      carrier — compile churn creeping back into the warm steady state
      is exactly the regression this plane exists to catch (vacuous
      with fewer than two rounds)."""
    if not rounds:
        return 0, (
            "devprof-gate: no DEVPROF carriers — nothing to compare, "
            "passing vacuously"
        )
    latest = rounds[-1]
    latest_n, _p, latest_rc, latest_sh, latest_ov, latest_pass = latest
    code = 0
    lines: List[str] = []

    if latest_pass is False:
        code = 1
        lines.append(
            f"devprof-gate: r{latest_n:02d} carries pass=false\n"
            "FAIL: the latest devprof drill failed its own checks — "
            "regenerate the carrier with `make devprof-demo` and fix "
            "what it names before gating on drift"
        )
    else:
        lines.append(
            f"devprof-gate: r{latest_n:02d} checks "
            f"{'passed' if latest_pass else 'absent (legacy carrier)'}"
        )

    verdict = (
        f"devprof-gate: r{latest_n:02d} overhead_pct = {latest_ov:.2f} "
        f"(ceiling {overhead_ceiling_pct:.1f}% absolute, vs the "
        "carrier's own CCRDT_DEVPROF=0 paired rounds)"
    )
    if latest_ov > overhead_ceiling_pct:
        code = 1
        lines.append(
            f"{verdict}\nFAIL: the armed observatory taxes dispatches "
            f"{latest_ov:.2f}% — over the {overhead_ceiling_pct:.1f}% "
            "budget"
        )
    else:
        lines.append(f"{verdict}\nOK: within budget")

    if len(rounds) < 2:
        lines.append(
            f"devprof-gate: only {len(rounds)} round(s) carry the "
            "devprof metrics — no drift to compare, passing vacuously"
        )
        return code, "\n".join(lines)

    best_rc_n, best_rc = best_prior_carrier(rounds, 2, "min")
    rc_ceiling = max(
        best_rc * (1.0 + tolerance), best_rc + recompile_floor_abs
    )
    verdict = (
        f"devprof-gate: r{latest_n:02d} recompiles_per_100_rounds = "
        f"{latest_rc:.1f} vs best prior r{best_rc_n:02d} = {best_rc:.1f} "
        f"(ceiling +{tolerance:.0%} and +{recompile_floor_abs:.0f}: "
        f"{rc_ceiling:.1f})"
    )
    if latest_rc > rc_ceiling:
        code = 1
        lines.append(
            f"{verdict}\nFAIL: steady-state recompiles crept up "
            f"{latest_rc - best_rc:.1f}/100 rounds — a shape bucket or "
            "the prewarm ladder regressed"
        )
    else:
        lines.append(f"{verdict}\nOK: within tolerance")

    best_sh_n, best_sh = best_prior_carrier(rounds, 3, "min")
    sh_ceiling = max(
        best_sh * (1.0 + tolerance), best_sh + share_floor_abs
    )
    verdict = (
        f"devprof-gate: r{latest_n:02d} compile_ms_share_pct = "
        f"{latest_sh:.2f} vs best prior r{best_sh_n:02d} = {best_sh:.2f} "
        f"(ceiling +{tolerance:.0%} and +{share_floor_abs:.1f}: "
        f"{sh_ceiling:.2f})"
    )
    if latest_sh > sh_ceiling:
        code = 1
        lines.append(
            f"{verdict}\nFAIL: compile time is eating "
            f"{latest_sh:.2f}% of steady-state wall time — XLA is "
            "re-tracing where it used to hit cache"
        )
    else:
        lines.append(f"{verdict}\nOK: within tolerance")
    return code, "\n".join(lines)


def attribution_drift(
    rounds: List[Tuple[int, str, float, float]]
) -> List[str]:
    """Human drift report across attribution-bearing rounds (empty with
    fewer than one such round)."""
    lines: List[str] = []
    prev: Optional[Tuple[int, float, float]] = None
    for n, p, gap, cov in rounds:
        note = ""
        if prev is not None:
            pn, pgap, pcov = prev
            note = (
                f"  (vs r{pn:02d}: gap {gap - pgap:+.2f}ms, "
                f"coverage {cov - pcov:+.1%})"
            )
        lines.append(
            f"  spans r{n:02d} {os.path.basename(p)}: dispatch gap "
            f"{gap:.2f}ms p50, coverage {cov:.1%}{note}"
        )
        prev = (n, gap, cov)
    return lines


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="fail on >tolerance regression of merges_per_sec "
        "across BENCH_*.json rounds"
    )
    ap.add_argument(
        "--bench-dir",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        help="directory holding BENCH_r*.json (default: repo root)",
    )
    ap.add_argument("--tolerance", type=float, default=0.20)
    ap.add_argument(
        "--gap-tolerance", type=float, default=0.20,
        help="relative ceiling for the dispatch_gap_ms_p50 gate "
        "(a 0.25ms absolute floor always applies on top)",
    )
    args = ap.parse_args(argv)
    rounds = load_rounds(args.bench_dir)
    for n, p, v, be in rounds:
        tag = "-" if v is None else f"{v:,.0f}"
        print(f"  r{n:02d} {os.path.basename(p)} [{be or '?'}]: {tag}")
    for n, p, cz in load_topo_rounds(args.bench_dir):
        print(
            f"  topo r{n:02d} {os.path.basename(p)}: "
            f"cross-zone {cz.get('bytes', 0):,.0f} B in "
            f"{cz.get('frames', 0):,.0f} frames "
            f"(vs mesh ratio {cz.get('ratio', float('nan')):.2f})"
        )
    attr = load_attribution_rounds(args.bench_dir)
    for line in attribution_drift(attr):
        print(line)
    ing = load_ingest_rounds(args.bench_dir)
    for n, p, ms, ratio in ing:
        print(
            f"  ingest r{n:02d} {os.path.basename(p)}: "
            f"{ms:,.1f}ms combined, coalesce ratio {ratio:.2f}"
        )
    part = load_partition_rounds(args.bench_dir)
    for n, p, ae, rj in part:
        print(
            f"  partition r{n:02d} {os.path.basename(p)}: "
            f"{ae:,.0f} B/resync, rejoin {rj:.3f}s"
        )
    srv = load_serve_rounds(args.bench_dir)
    for n, p, rps, p99, nproc in srv:
        host = "" if nproc is None else f", nproc {nproc}"
        print(
            f"  serve r{n:02d} {os.path.basename(p)}: "
            f"{rps:,.0f} reads/s, frame p99 {p99:.3f}ms{host}"
        )
    aud = load_audit_rounds(args.bench_dir)
    for n, p, ov in aud:
        print(
            f"  audit r{n:02d} {os.path.basename(p)}: "
            f"overhead {ov:.2f}% per round"
        )
    mesh = load_mesh_rounds(args.bench_dir)
    for n, p, mps, ici, byt in mesh:
        print(
            f"  mesh r{n:02d} {os.path.basename(p)}: "
            f"{mps:,.0f} merges/s, ici p50 {ici:.3f}ms, "
            f"cross-slice {byt:,.0f} B"
        )
    rtr = load_router_rounds(args.bench_dir)
    for n, p, rps, p99, blip in rtr:
        print(
            f"  router r{n:02d} {os.path.basename(p)}: "
            f"{rps:,.0f} routed reads/s, p99 {p99:.1f}ms, "
            f"failover blip {blip:,.0f}ms"
        )
    wtr = load_write_rounds(args.bench_dir)
    for n, p, wps, p99, blip, passed in wtr:
        tag = "pass" if passed else ("FAIL" if passed is False else "?")
        print(
            f"  write r{n:02d} {os.path.basename(p)} [{tag}]: "
            f"{wps:,.2f} acked bursts/s, p99 {p99:,.0f}ms, "
            f"failover blip {blip:,.0f}ms"
        )
    rtrc = load_rtrace_rounds(args.bench_dir)
    for n, p, rps, ov, cov, passed in rtrc:
        tag = "pass" if passed else ("FAIL" if passed is False else "?")
        print(
            f"  rtrace r{n:02d} {os.path.basename(p)} [{tag}]: "
            f"{rps:,.0f} traced reads/s, overhead {ov:.2f}%, "
            f"coverage p50 {cov:.1%}"
        )
    dvp = load_devprof_rounds(args.bench_dir)
    for n, p, rc, sh, ov, passed in dvp:
        tag = "pass" if passed else ("FAIL" if passed is False else "?")
        print(
            f"  devprof r{n:02d} {os.path.basename(p)} [{tag}]: "
            f"{rc:.1f} recompiles/100 rounds, compile share {sh:.2f}%, "
            f"overhead {ov:.2f}%"
        )
    pgr = load_pager_rounds(args.bench_dir)
    for n, p, hit, miss, cm in pgr:
        cm_note = f", {cm:,.0f} cold merges/s" if cm is not None else ""
        print(
            f"  pager r{n:02d} {os.path.basename(p)}: "
            f"hit {hit:.3f}, miss p50 {miss:.3f}ms{cm_note}"
        )
    wal = load_wal_rounds(args.bench_dir)
    for n, p, p99, wal_ms, grp, rank, be in wal:
        wal_note = (
            f", wal_append {wal_ms:,.1f}ms"
            f" (group p50 {grp:.0f}, rank "
            f"{'#%d' % (rank + 1) if rank is not None else '?'})"
            if wal_ms is not None else ""
        )
        print(
            f"  wal r{n:02d} {os.path.basename(p)} [{be or '?'}]: "
            f"p99 e2e {p99:.2f}ms{wal_note}"
        )
    code, verdict = evaluate(rounds, args.tolerance)
    print(verdict)
    gap_code, gap_verdict = evaluate_gap(attr, args.gap_tolerance)
    print(gap_verdict)
    ing_code, ing_verdict = evaluate_ingest(ing, args.tolerance)
    print(ing_verdict)
    part_code, part_verdict = evaluate_partition(part, args.tolerance)
    print(part_verdict)
    serve_code, serve_verdict = evaluate_serve(srv, args.tolerance)
    print(serve_verdict)
    audit_code, audit_verdict = evaluate_audit(aud, args.tolerance)
    print(audit_verdict)
    wal_code, wal_verdict = evaluate_wal(wal, args.tolerance)
    print(wal_verdict)
    mesh_code, mesh_verdict = evaluate_mesh(mesh, args.tolerance)
    print(mesh_verdict)
    pager_code, pager_verdict = evaluate_pager(pgr, args.tolerance)
    print(pager_verdict)
    router_code, router_verdict = evaluate_router(rtr, args.tolerance)
    print(router_verdict)
    write_code, write_verdict = evaluate_write(wtr, args.tolerance)
    print(write_verdict)
    rtrace_code, rtrace_verdict = evaluate_rtrace(rtrc, args.tolerance)
    print(rtrace_verdict)
    devprof_code, devprof_verdict = evaluate_devprof(dvp, args.tolerance)
    print(devprof_verdict)
    return max(code, gap_code, ing_code, part_code, serve_code, audit_code,
               wal_code, mesh_code, pager_code, router_code, write_code,
               rtrace_code, devprof_code)


if __name__ == "__main__":
    sys.exit(main())
