"""Throughput regression gate over the committed BENCH_*.json rounds.

Each ``BENCH_r<NN>.json`` in the repo root is a benchmark round dump:
one JSON object whose ``tail`` field holds the benchmark harness's raw
stdout — including (for rounds that ran the batched-dispatch benchmark)
``"merges_per_sec": <float>`` lines, JSON-escaped INSIDE the tail
string. This gate:

1. parses every round, taking the best ``merges_per_sec`` per round
   (rounds without the metric — e.g. setup-only rounds — are skipped);
2. compares the LATEST round that has the metric against the best of
   all PRIOR rounds;
3. fails (exit 1) when the latest regressed more than ``--tolerance``
   (default 20%) below that best — the same batched-dispatch throughput
   `obs.profile` now measures live, gated at CI time.

With fewer than two metric-bearing rounds there is nothing to compare:
the gate passes vacuously (exit 0) and says so.

Run: ``python scripts/bench_gate.py [--bench-dir DIR] [--tolerance 0.2]``
(also wired as ``make bench-gate`` and into ``make chaos``).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

_METRIC_RE = re.compile(r'"merges_per_sec":\s*([0-9][0-9_.eE+]*)')


def round_number(path: str) -> int:
    """BENCH_r07.json -> 7 (unparseable names sort first)."""
    m = re.search(r"BENCH_r(\d+)\.json$", os.path.basename(path))
    return int(m.group(1)) if m else -1


def best_merges_per_sec(path: str) -> Optional[float]:
    """Best merges_per_sec in one round dump, or None when the round
    didn't run the dispatch benchmark (or the file is torn)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    # The metric lives inside the "tail" stdout capture; json.load has
    # already unescaped it, so a plain regex over the text applies.
    tail = str(doc.get("tail", ""))
    vals = [float(v) for v in _METRIC_RE.findall(tail)]
    return max(vals) if vals else None


def load_rounds(bench_dir: str) -> List[Tuple[int, str, Optional[float]]]:
    """[(round_no, path, best-or-None)] sorted by round number."""
    paths = sorted(
        glob.glob(os.path.join(bench_dir, "BENCH_r*.json")), key=round_number
    )
    return [(round_number(p), p, best_merges_per_sec(p)) for p in paths]


def evaluate(
    rounds: List[Tuple[int, str, Optional[float]]], tolerance: float
) -> Tuple[int, str]:
    """(exit_code, human verdict) for a parsed round list."""
    with_metric = [(n, p, v) for n, p, v in rounds if v is not None]
    if len(with_metric) < 2:
        return 0, (
            f"bench-gate: only {len(with_metric)} round(s) carry "
            "merges_per_sec — nothing to compare, passing vacuously"
        )
    latest_n, latest_p, latest_v = with_metric[-1]
    prior = with_metric[:-1]
    best_n, _best_p, best_v = max(prior, key=lambda r: r[2])
    floor = best_v * (1.0 - tolerance)
    verdict = (
        f"bench-gate: r{latest_n:02d} best merges_per_sec = {latest_v:,.0f} "
        f"vs best prior r{best_n:02d} = {best_v:,.0f} "
        f"(floor at -{tolerance:.0%}: {floor:,.0f})"
    )
    if latest_v < floor:
        return 1, (
            f"{verdict}\nFAIL: batched-dispatch throughput regressed "
            f"{1 - latest_v / best_v:.1%} (> {tolerance:.0%} allowed)"
        )
    return 0, f"{verdict}\nOK: within tolerance"


def load_topo_rounds(bench_dir: str) -> List[Tuple[int, str, Dict]]:
    """[(round_no, path, cross_zone-dict)] for every ``TOPO_r<NN>.json``
    round committed by scripts/topo_demo.py — the DCN byte bill of each
    topology round, reported (not yet gated) alongside the throughput
    rounds so cross-zone regressions are visible at the same place."""
    out: List[Tuple[int, str, Dict]] = []
    for p in sorted(glob.glob(os.path.join(bench_dir, "TOPO_r*.json"))):
        m = re.search(r"TOPO_r(\d+)\.json$", os.path.basename(p))
        if not m:
            continue
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        out.append((int(m.group(1)), p, dict(doc.get("cross_zone") or {})))
    return out


_GAP_RE = re.compile(r'"dispatch_gap_ms_p50":\s*([0-9][0-9_.eE+-]*)')
_COV_RE = re.compile(r'"span_coverage_p50":\s*([0-9][0-9_.eE+-]*)')


def load_attribution_rounds(
    bench_dir: str,
) -> List[Tuple[int, str, float, float]]:
    """[(round_no, path, dispatch_gap_ms_p50, span_coverage_p50)] for
    every BENCH round whose summary line carries the span-attribution
    headline (bench.bench_round_phases, r6+). Report-only, like the topo
    rows: the drift that matters here is ATTRIBUTION drift — coverage
    sliding down means spans stopped explaining where round time goes,
    gap sliding up means unowned host time is growing — and both deserve
    eyes before they deserve a hard gate."""
    out: List[Tuple[int, str, float, float]] = []
    for p in sorted(
        glob.glob(os.path.join(bench_dir, "BENCH_r*.json")), key=round_number
    ):
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        tail = str(doc.get("tail", ""))
        gaps = _GAP_RE.findall(tail)
        covs = _COV_RE.findall(tail)
        if gaps and covs:
            out.append(
                (round_number(p), p, float(gaps[-1]), float(covs[-1]))
            )
    return out


def attribution_drift(
    rounds: List[Tuple[int, str, float, float]]
) -> List[str]:
    """Human drift report across attribution-bearing rounds (empty with
    fewer than one such round)."""
    lines: List[str] = []
    prev: Optional[Tuple[int, float, float]] = None
    for n, p, gap, cov in rounds:
        note = ""
        if prev is not None:
            pn, pgap, pcov = prev
            note = (
                f"  (vs r{pn:02d}: gap {gap - pgap:+.2f}ms, "
                f"coverage {cov - pcov:+.1%})"
            )
        lines.append(
            f"  spans r{n:02d} {os.path.basename(p)}: dispatch gap "
            f"{gap:.2f}ms p50, coverage {cov:.1%}{note}"
        )
        prev = (n, gap, cov)
    return lines


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="fail on >tolerance regression of merges_per_sec "
        "across BENCH_*.json rounds"
    )
    ap.add_argument(
        "--bench-dir",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        help="directory holding BENCH_r*.json (default: repo root)",
    )
    ap.add_argument("--tolerance", type=float, default=0.20)
    args = ap.parse_args(argv)
    rounds = load_rounds(args.bench_dir)
    for n, p, v in rounds:
        tag = "-" if v is None else f"{v:,.0f}"
        print(f"  r{n:02d} {os.path.basename(p)}: {tag}")
    for n, p, cz in load_topo_rounds(args.bench_dir):
        print(
            f"  topo r{n:02d} {os.path.basename(p)}: "
            f"cross-zone {cz.get('bytes', 0):,.0f} B in "
            f"{cz.get('frames', 0):,.0f} frames "
            f"(vs mesh ratio {cz.get('ratio', float('nan')):.2f})"
        )
    for line in attribution_drift(load_attribution_rounds(args.bench_dir)):
        print(line)
    code, verdict = evaluate(rounds, args.tolerance)
    print(verdict)
    return code


if __name__ == "__main__":
    sys.exit(main())
