"""Trace query CLI over a fleet's flight-recorder logs.

`obs.events.delta_paths` groups every delta trace event by its
(origin, dseq) context; this tool turns that raw grouping into the
questions an operator actually asks of a ``CCRDT_OBS_DIR`` full of
``flight-*.jsonl`` spills::

    # Fleet-wide overview: deltas seen, complete paths, never-applied
    # deltas, p50/p99 propagation latency per origin->applier pair.
    python scripts/ccrdt_trace.py summary /path/to/obs-dir

    # One delta's full journey, hop by hop, with per-hop latency:
    # publish -> send/write -> recv/fetch -> apply on each peer.
    python scripts/ccrdt_trace.py path /path/to/obs-dir w0 3

    # Deltas whose propagation took >= factor x the fleet median.
    python scripts/ccrdt_trace.py stragglers /path/to/obs-dir --factor 3

    # Causal-order audit: per (process incarnation, origin), delta.apply
    # dseqs must advance contiguously from the first-seen baseline, with
    # snap.apply the only legitimate jump. A gap-skip or double-apply
    # here means the sweep cursor machinery broke.
    python scripts/ccrdt_trace.py audit /path/to/obs-dir

`summary` and `stragglers` take ``--json`` for machine-readable output
(the obs-demo and tests consume it).

Exit codes: 0 on success; `summary --require-complete` exits 1 when no
delta shows a complete publish->apply path (the obs-demo smoke gate);
`path` exits 1 when the requested delta left no events; `audit` exits 1
on any ordering violation.

All timestamps are the emitting process's wall clock (`time.time()`),
so cross-host latencies inherit clock skew — on one box (the drills)
they are exact; across hosts read them as approximate.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from antidote_ccrdt_tpu.obs import events as obs_events  # noqa: E402
from antidote_ccrdt_tpu.obs.audit import audit_apply_order  # noqa: E402,F401
# audit_apply_order moved to obs/audit.py (the certifier reuses it);
# re-exported here because `audit` below and the trace-CLI tests call it
# under this module's name. obs.audit stays stdlib-only at import time.

# Display order of a delta's lifecycle stages (fs medium uses write/
# fetch, tcp uses send/recv — a path holds whichever its medium emitted;
# relay = a topo/ zone anchor forwarding a routed frame across/inside a
# zone, so hierarchical paths read leaf -> anchor -> anchor -> leaf).
STAGE_ORDER = ("publish", "write", "send", "recv", "relay", "fetch", "apply")


def load_paths(obs_dir: str) -> Dict[tuple, Dict[str, List[Dict[str, Any]]]]:
    """{(origin, dseq): {stage: [events]}} for every flight log in a dir."""
    return obs_events.delta_paths(obs_events.scan_dir(obs_dir))


def fleet_members(obs_dir: str) -> List[str]:
    """Every member that wrote at least one flight event."""
    out = set()
    for evs in obs_events.scan_dir(obs_dir).values():
        for ev in evs:
            m = ev.get("member")
            if m:
                out.add(str(m))
    return sorted(out)


def path_timeline(
    stages: Dict[str, List[Dict[str, Any]]]
) -> List[Dict[str, Any]]:
    """One delta's events as a single time-ordered hop list. Each entry
    carries stage/member/t plus `hop_ms` (latency since the previous
    hop) and `total_ms` (since publish, when a publish exists)."""
    evs: List[Tuple[float, str, Dict[str, Any]]] = []
    for stage in STAGE_ORDER:
        for ev in stages.get(stage, []):
            evs.append((float(ev.get("t", 0.0)), stage, ev))
    evs.sort(key=lambda e: (e[0], STAGE_ORDER.index(e[1])))
    t_pub: Optional[float] = None
    if stages.get("publish"):
        t_pub = min(float(e.get("t", 0.0)) for e in stages["publish"])
    out: List[Dict[str, Any]] = []
    prev_t: Optional[float] = None
    for t, stage, ev in evs:
        out.append(
            {
                "stage": stage,
                "member": str(ev.get("member", "?")),
                "t": t,
                "hop_ms": None if prev_t is None else (t - prev_t) * 1e3,
                "total_ms": None if t_pub is None else (t - t_pub) * 1e3,
                "bytes": ev.get("bytes"),
            }
        )
        prev_t = t
    return out


def is_complete(stages: Dict[str, List[Dict[str, Any]]]) -> bool:
    """Complete = the delta was published AND applied somewhere else."""
    return bool(stages.get("publish")) and bool(stages.get("apply"))


def apply_latencies(
    paths: Dict[tuple, Dict[str, List[Dict[str, Any]]]]
) -> List[Dict[str, Any]]:
    """One row per (delta, applier): publish->apply propagation latency.
    Deltas without a publish event (foreign/pre-spill) are skipped."""
    rows: List[Dict[str, Any]] = []
    for (origin, dseq), stages in sorted(paths.items()):
        if not stages.get("publish"):
            continue
        t_pub = min(float(e.get("t", 0.0)) for e in stages["publish"])
        for ev in stages.get("apply", []):
            rows.append(
                {
                    "origin": str(origin),
                    "dseq": int(dseq),
                    "applier": str(ev.get("member", "?")),
                    "latency_ms": (float(ev.get("t", 0.0)) - t_pub) * 1e3,
                }
            )
    return rows


def _pctl(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[int(i)]


def pair_stats(
    rows: List[Dict[str, Any]]
) -> Dict[Tuple[str, str], Dict[str, float]]:
    """{(origin, applier): {n, p50_ms, p99_ms, max_ms}} propagation
    latency per peer-pair."""
    by_pair: Dict[Tuple[str, str], List[float]] = {}
    for r in rows:
        by_pair.setdefault((r["origin"], r["applier"]), []).append(
            r["latency_ms"]
        )
    out: Dict[Tuple[str, str], Dict[str, float]] = {}
    for pair, vals in sorted(by_pair.items()):
        vals.sort()
        out[pair] = {
            "n": float(len(vals)),
            "p50_ms": _pctl(vals, 0.50),
            "p99_ms": _pctl(vals, 0.99),
            "max_ms": vals[-1],
        }
    return out


def never_applied(
    paths: Dict[tuple, Dict[str, List[Dict[str, Any]]]]
) -> List[tuple]:
    """Published deltas with NO apply event anywhere — lost on the wire,
    stuck behind a gap, or pruned before any peer chained them."""
    return sorted(
        key
        for key, stages in paths.items()
        if stages.get("publish") and not stages.get("apply")
    )


def find_stragglers(
    rows: List[Dict[str, Any]], factor: float = 3.0
) -> Tuple[float, List[Dict[str, Any]]]:
    """(fleet median latency, rows at >= factor x that median). With
    fewer than 2 applies there is no meaningful baseline: no stragglers."""
    if len(rows) < 2:
        return 0.0, []
    vals = sorted(r["latency_ms"] for r in rows)
    med = _pctl(vals, 0.50)
    if med <= 0:
        return med, []
    return med, [r for r in rows if r["latency_ms"] >= factor * med]


# -- rendering ---------------------------------------------------------------


def _fmt_ms(v: Optional[float]) -> str:
    return "-" if v is None else f"{v:9.3f}ms"


def cmd_summary(args: argparse.Namespace) -> int:
    paths = load_paths(args.obs_dir)
    if not paths:
        if args.json:
            print(json.dumps({"deltas_traced": 0, "complete_paths": 0}))
        else:
            print(f"no delta trace events under {args.obs_dir}")
        return 1 if args.require_complete else 0
    complete = sorted(k for k, st in paths.items() if is_complete(st))
    rows = apply_latencies(paths)
    lost = never_applied(paths)
    if args.json:
        doc = {
            "deltas_traced": len(paths),
            "complete_paths": len(complete),
            "apply_samples": len(rows),
            "never_applied": [[o, d] for o, d in lost],
            "pairs": {
                f"{o}->{a}": s for (o, a), s in pair_stats(rows).items()
            },
        }
        print(json.dumps(doc))
        if args.require_complete and not complete:
            return 1
        return 0
    print(f"deltas traced   : {len(paths)}")
    print(f"complete paths  : {len(complete)} (publish -> apply)")
    print(f"apply samples   : {len(rows)}")
    print(f"never applied   : {len(lost)}"
          + (f"  {lost[:8]}" if lost else ""))
    # topo/ hierarchy: anchor relays and the hop depth of routed frames
    # (a flat mesh shows zero relays and no hop stamps).
    relays = [e for st in paths.values() for e in st.get("relay", [])]
    if relays:
        cross = sum(1 for e in relays if e.get("cross_zone"))
        hops = sorted(
            int(e["hops"])
            for st in paths.values()
            for e in st.get("recv", [])
            if e.get("hops") is not None
        )
        print(f"anchor relays   : {len(relays)} ({cross} cross-zone)")
        if hops:
            print(f"routed hop depth: max={hops[-1]} "
                  f"p50={hops[len(hops) // 2]} over {len(hops)} frames")
    stats = pair_stats(rows)
    if stats:
        print("propagation latency per origin->applier pair:")
        for (origin, applier), s in stats.items():
            print(
                f"  {origin:>8} -> {applier:<8} n={int(s['n']):<4} "
                f"p50={_fmt_ms(s['p50_ms'])} p99={_fmt_ms(s['p99_ms'])} "
                f"max={_fmt_ms(s['max_ms'])}"
            )
    if complete:
        origin, dseq = complete[0]
        print(f"example complete path: {origin}/{dseq} "
              f"(ccrdt_trace.py path {args.obs_dir} {origin} {dseq})")
    if args.require_complete and not complete:
        print("FAIL: no delta shows a complete publish->apply path")
        return 1
    return 0


def cmd_path(args: argparse.Namespace) -> int:
    paths = load_paths(args.obs_dir)
    key = (args.origin, args.dseq)
    stages = paths.get(key)
    if not stages:
        print(f"no events for delta {args.origin}/{args.dseq}")
        return 1
    print(f"delta {args.origin}/{args.dseq}:")
    for hop in path_timeline(stages):
        extra = f" bytes={hop['bytes']}" if hop.get("bytes") else ""
        print(
            f"  t={hop['t']:.6f} {hop['stage']:>7} @ {hop['member']:<8} "
            f"hop={_fmt_ms(hop['hop_ms'])} total={_fmt_ms(hop['total_ms'])}"
            f"{extra}"
        )
    if not is_complete(stages):
        print("  (path incomplete: no apply event recorded)")
    return 0


def cmd_stragglers(args: argparse.Namespace) -> int:
    rows = apply_latencies(load_paths(args.obs_dir))
    med, slow = find_stragglers(rows, factor=args.factor)
    if args.json:
        print(json.dumps(
            {
                "apply_samples": len(rows),
                "median_ms": med,
                "factor": args.factor,
                "stragglers": slow,
            }
        ))
        return 0
    print(f"apply samples: {len(rows)}, fleet median {med:.3f}ms, "
          f"threshold {args.factor:g}x")
    if not slow:
        print("no stragglers")
        return 0
    for r in slow:
        print(
            f"  {r['origin']}/{r['dseq']} -> {r['applier']}: "
            f"{r['latency_ms']:.3f}ms ({r['latency_ms'] / med:.1f}x median)"
        )
    return 0


def cmd_audit(args: argparse.Namespace) -> int:
    logs = obs_events.scan_dir(args.obs_dir)
    n_apply = sum(
        1 for evs in logs.values() for e in evs
        if e.get("kind") == "delta.apply"
    )
    violations = audit_apply_order(logs)
    if args.json:
        print(json.dumps(
            {
                "logs": len(logs),
                "apply_events": n_apply,
                "violations": violations,
            }
        ))
        return 1 if violations else 0
    print(f"audited {n_apply} delta.apply events across {len(logs)} "
          f"flight logs")
    if not violations:
        print("OK: every apply stream is contiguous per (incarnation, "
              "origin) — no gap-skips, no double-applies")
        return 0
    for v in violations:
        print(
            f"  {v['kind']:>12}: {v['applier']} applied {v['origin']}/"
            f"{v['dseq']} after cursor {v['prev_dseq']} "
            f"(seq={v['seq']}, {v['log']})"
        )
    print(f"FAIL: {len(violations)} apply-order violation(s) — the sweep "
          f"cursor machinery broke causal delivery")
    return 1


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="query a fleet's flight-recorder delta traces"
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("summary", help="fleet-wide propagation overview")
    s.add_argument("obs_dir")
    s.add_argument(
        "--require-complete",
        action="store_true",
        help="exit 1 unless at least one complete publish->apply path exists",
    )
    s.add_argument("--json", action="store_true", help="machine-readable")
    s.set_defaults(fn=cmd_summary)

    p = sub.add_parser("path", help="one delta's hop-by-hop journey")
    p.add_argument("obs_dir")
    p.add_argument("origin")
    p.add_argument("dseq", type=int)
    p.set_defaults(fn=cmd_path)

    g = sub.add_parser("stragglers", help="slow applies vs fleet median")
    g.add_argument("obs_dir")
    g.add_argument("--factor", type=float, default=3.0)
    g.add_argument("--json", action="store_true", help="machine-readable")
    g.set_defaults(fn=cmd_stragglers)

    a = sub.add_parser(
        "audit", help="per-origin dseq apply-order audit (exit 1 on violation)"
    )
    a.add_argument("obs_dir")
    a.add_argument("--json", action="store_true", help="machine-readable")
    a.set_defaults(fn=cmd_audit)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
