"""Real-socket gossip drill worker: the elastic drill over TCP.

Same drill as scripts/elastic_demo.py (deterministic op streams,
ownership-grows adoption, convergence to the sequential reference) but
the medium is `net.tcp.TcpTransport` — real localhost sockets, SWIM
membership from piggybacked ages, bounded send queues with backoff —
instead of a shared directory. The shared directory is still used for
two non-gossip jobs only: address rendezvous (each worker binds port 0
and publishes `addr-<member>`; a poller thread adds peers as their
files appear, so late joiners are discovered too) and the
`final-<member>.json` result drop the supervising test reads.

Run one worker:
    python scripts/net_gossip_demo.py --root /tmp/g --member w0 --n-members 3

The supervising test (tests/test_net_tcp.py, marked slow) launches
three, kills one mid-run, and checks the survivors detect the death via
SWIM timeouts, adopt its replicas, and converge — with the retry/backoff
counters visible in the result metrics.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scripts.cover import install_child_cover  # noqa: E402

install_child_cover()  # no-op outside `make cover` runs

from scripts.elastic_demo import DRILLS, run_worker  # noqa: E402


def _write_addr(root: str, member: str, addr, zone: str = "") -> None:
    path = os.path.join(root, f"addr-{member}")
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        # "host:port" or "host:port zone" — the optional zone token rides
        # the rendezvous file so peers learn topology before first contact
        # (the hello exchange re-teaches it; this just avoids a full-mesh
        # first round). Old readers split on ":" and never see the zone.
        f.write(f"{addr[0]}:{addr[1]} {zone}".rstrip())
    os.replace(tmp, path)


def _read_addrs(root: str) -> dict:
    out = {}
    for fn in os.listdir(root):
        if not fn.startswith("addr-") or ".tmp" in fn:
            continue
        try:
            with open(os.path.join(root, fn)) as f:
                text = f.read().strip()
            hostport, _, zone = text.partition(" ")
            host, port = hostport.rsplit(":", 1)
            out[fn[len("addr-"):]] = (host, int(port), zone.strip())
        except (OSError, ValueError):
            continue  # torn write: next poll sees it whole
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", required=True,
                    help="rendezvous + results directory (NOT the gossip "
                    "medium — that is TCP)")
    ap.add_argument("--member", required=True)
    ap.add_argument("--n-members", type=int, required=True)
    ap.add_argument("--type", default="topk_rmv", choices=sorted(DRILLS))
    ap.add_argument("--die-at", type=int, default=-1)
    ap.add_argument("--join-late", type=float, default=0.0)
    ap.add_argument("--hb-interval", type=float, default=0.05)
    ap.add_argument("--timeout", type=float, default=0.4)
    ap.add_argument("--step-sleep", type=float, default=0.15)
    ap.add_argument("--publish-every", type=int, default=2)
    ap.add_argument("--delta", action="store_true")
    ap.add_argument("--partitions", type=int, default=0,
                    help="arm the partition plane + divergence watchdog "
                    "(see elastic_demo.py --partitions); 0 disables")
    ap.add_argument("--overlap", dest="overlap", action="store_true",
                    default=None,
                    help="overlapped round pipeline (parallel/overlap.py); "
                    "default on unless CCRDT_OVERLAP=0 — see "
                    "elastic_demo.py")
    ap.add_argument("--no-overlap", dest="overlap", action="store_false",
                    help="force the serial round loop")
    ap.add_argument("--queue-max", type=int, default=64)
    ap.add_argument("--zone", default="",
                    help="DCN zone label for topo/ routing (default: flat "
                    "single-zone fleet)")
    ap.add_argument("--topo", action="store_true",
                    help="install the zone router: gossip intra-zone only, "
                    "the per-zone rendezvous anchor relays across zones")
    ap.add_argument("--lag-anchor-ops", type=float, default=0.0,
                    help="lag-driven backpressure threshold in ops (needs "
                    "--delta); 0 disables — see elastic_demo.py")
    ap.add_argument("--wal-dir", default="",
                    help="arm the per-worker crash WAL (harness/wal.py) "
                    "under this directory — see elastic_demo.py")
    ap.add_argument("--wal-segment-bytes", type=int, default=256 << 10)
    ap.add_argument("--steps", type=int, default=0,
                    help="per-worker step count override (0 = the "
                    "10-step default; every member must agree)")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from antidote_ccrdt_tpu.utils import faults

    faults.install_from_env()  # supervisor-injected deterministic faults
    # (parity with elastic_demo: the same CCRDT_FAULTS plans drive the
    # tcp.send/bridge.read points this drill exercises)

    from antidote_ccrdt_tpu.net.tcp import TcpTransport
    from antidote_ccrdt_tpu.net.transport import GossipNode
    from antidote_ccrdt_tpu.obs import spans as obs_spans

    # Arm the span plane BEFORE the transport exists: the hello exchange
    # on each fresh peer socket carries the NTP-style clock echo, and
    # those first offsets are what aligns this worker's timeline in the
    # merged trace (run_worker attaches the metrics mirror later).
    obs_spans.install_from_env(args.member)

    drill = DRILLS[args.type]
    dense = drill.make_engine()
    state = drill.init(dense)

    os.makedirs(args.root, exist_ok=True)
    transport = TcpTransport(
        args.member, queue_max=args.queue_max, zone=args.zone or None
    )
    if args.topo:
        transport.install_router(args.timeout)

    if args.join_late > 0:
        # Compile first, register (addr file + first pings) after the
        # delay — same late-join discipline as the fs drill.
        state = drill.apply(dense, state, 0, [])
        time.sleep(args.join_late)
    _write_addr(args.root, args.member, transport.address, args.zone)

    def discover():
        while True:
            for name, (host, port, zone) in _read_addrs(args.root).items():
                if zone:
                    transport.learn_zone(name, zone)
                transport.add_peer(name, (host, port))  # no-op for self/known
            time.sleep(0.05)

    threading.Thread(target=discover, daemon=True).start()

    store = GossipNode(transport)
    run_worker(store, drill, dense, state, args, result_dir=args.root)


if __name__ == "__main__":
    main()
