"""Elastic-recovery drill worker (see parallel/elastic.py).

N workers gossip a dense CRDT grid through a shared directory. Each
step, each worker applies a *deterministic* op batch for the replicas it
owns under the current alive set, heartbeats, and periodically publishes/
sweeps. A worker started with --die-at crashes (os._exit) at that step;
survivors detect the stale heartbeat, adopt its replicas, and — because
op generation is deterministic — regenerate the adopted replicas' entire
op history. Duplicated application of steps the victim already published
is harmless: for JOIN engines by idempotence of the join, for MONOID
engines (--type average/wordcount) because the versioned-row lift
(parallel/monoid.py) replaces rows by version instead of adding them —
the adopted row is regenerated into the adopter's own contribution state
(MonoidContributor: writes never land on swept-in peer copies) and its
version supersedes the victim's published prefix.

Run one worker:
    python scripts/elastic_demo.py --root /tmp/g --member w0 --n-members 3

The supervising test (tests/test_elastic.py) launches several and checks
every survivor converges to the sequential single-process reference.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scripts.cover import install_child_cover  # noqa: E402

install_child_cover()  # no-op outside `make cover` runs

# Demo geometry (shared with the test's reference computation).
R, NK, I, DCS, K, M, B, Br = 4, 1, 64, 4, 8, 2, 32, 8
NK_MONOID, V = 2, 32  # monoid drills: 2 keys, 32 wordcount buckets
STEPS = 10


# --- per-type drill adapters ----------------------------------------------


class _TopkRmvDrill:
    """The JOIN flagship: in-place history re-apply on adoption (the join
    dedups duplicated application — the round-1 drill semantics). JOIN
    states need no own/gossip split, so the view IS the state."""

    name = publish_name = "topk_rmv"

    def pub_state(self, dense, state):
        return state

    def set_view(self, dense, state, swept):
        return swept

    def make_engine(self):
        from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense

        return make_dense(n_ids=I, n_dcs=DCS, size=K, slots_per_id=M)

    def init(self, dense):
        return dense.init(R, NK)

    def gen_ops(self, step: int, owned):
        """Deterministic [R, ...] op batch for `step`; replicas not in
        `owned` are masked to padding (add_ts=0 / rmv_id=-1). Any member
        can generate any replica's stream — the durable op source."""
        import jax.numpy as jnp
        import numpy as np

        from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps

        owned = set(owned)
        a_key = np.zeros((R, B), np.int32)
        a_id = np.zeros((R, B), np.int32)
        a_score = np.zeros((R, B), np.int32)
        a_dc = np.zeros((R, B), np.int32)
        a_ts = np.zeros((R, B), np.int32)
        r_key = np.zeros((R, Br), np.int32)
        r_id = np.full((R, Br), -1, np.int32)
        r_vc = np.zeros((R, Br, DCS), np.int32)
        for r in range(R):
            rng = np.random.default_rng(10_000 * (step + 1) + r)
            ids = rng.integers(0, I, B)
            scores = rng.integers(1, 500, B)
            if r in owned:
                a_id[r], a_score[r] = ids, scores
                a_dc[r] = r % DCS
                a_ts[r] = step * B + np.arange(B) + 1  # unique, monotone
                r_id[r] = rng.integers(0, I, Br)
                r_vc[r, :, r % DCS] = rng.integers(1, max(2, step * B + 1), Br)
        return TopkRmvOps(
            add_key=jnp.asarray(a_key), add_id=jnp.asarray(a_id),
            add_score=jnp.asarray(a_score), add_dc=jnp.asarray(a_dc),
            add_ts=jnp.asarray(a_ts),
            rmv_key=jnp.asarray(r_key), rmv_id=jnp.asarray(r_id),
            rmv_vc=jnp.asarray(r_vc),
        )

    def apply(self, dense, state, step: int, owned):
        state, _ = dense.apply_ops(
            state, self.gen_ops(step, owned), collect_dominated=False
        )
        return state

    def adopt(self, dense, state, gained, upto_step: int):
        for g in sorted(gained):
            for s in range(upto_step):
                state = self.apply(dense, state, s, [g])
        return state

    def ingest(self, dense, state, effects, step: int, owned):
        """Fold CLIENT effect ops (write tier, PR 16) into the lowest
        owned replica row at `step` — one batched apply_ops dispatch, so
        the fold lands inside this step's WAL record and delta window.
        Effects are scalar topk_rmv tuples (`serve.effect_from_wire`):
        ("add"|"add_r", (id, score, (dc, ts))) / ("rmv"|"rmv_r",
        (id, {dc: ts})). Client ts stamps must be distinct from the
        deterministic drill streams' (the demo writers use a 1e6+ ts
        base) — identical (dc, ts) stamps would dedup under join."""
        import jax.numpy as jnp
        import numpy as np

        from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps

        adds = [p for k, p in effects if k in ("add", "add_r")]
        rmvs = [p for k, p in effects if k in ("rmv", "rmv_r")]
        r = min(owned) if owned else 0
        nb, nr = max(len(adds), 1), max(len(rmvs), 1)
        a_key = np.zeros((R, nb), np.int32)
        a_id = np.zeros((R, nb), np.int32)
        a_score = np.zeros((R, nb), np.int32)
        a_dc = np.zeros((R, nb), np.int32)
        a_ts = np.zeros((R, nb), np.int32)  # ts=0 padding elsewhere
        r_key = np.zeros((R, nr), np.int32)
        r_id = np.full((R, nr), -1, np.int32)  # id=-1 padding
        r_vc = np.zeros((R, nr, DCS), np.int32)
        for j, (id_, score, (dc, ts)) in enumerate(adds):
            a_id[r, j], a_score[r, j] = int(id_), int(score)
            a_dc[r, j] = int(dc) % DCS
            a_ts[r, j] = int(ts)
        for j, (id_, vc) in enumerate(rmvs):
            r_id[r, j] = int(id_)
            for d, t in vc.items():
                if 0 <= int(d) < DCS:
                    r_vc[r, j, int(d)] = int(t)
        ops = TopkRmvOps(
            add_key=jnp.asarray(a_key), add_id=jnp.asarray(a_id),
            add_score=jnp.asarray(a_score), add_dc=jnp.asarray(a_dc),
            add_ts=jnp.asarray(a_ts),
            rmv_key=jnp.asarray(r_key), rmv_id=jnp.asarray(r_id),
            rmv_vc=jnp.asarray(r_vc),
        )
        state, _ = dense.apply_ops(state, ops, collect_dominated=False)
        return state

    def digest(self, dense, state):
        from antidote_ccrdt_tpu.harness.dense_replay import fold_rows

        obs = dense.value(fold_rows(dense, state, range(R)))[0][0]
        return sorted((int(i), int(s)) for (i, s) in obs)


class _MonoidDrill:
    """Shared machinery for the MONOID types through the versioned-row
    lift: ops for non-owned rows are padding, versions bump only for
    owned rows. The drill state is a `MonoidContributor` — ops apply to
    the member's own contribution rows (never to swept-in peer copies;
    see parallel/monoid.py for why that would double-count), gossip
    lands on the peers side, publishes/reads use the merged view."""

    def init(self, lift):
        from antidote_ccrdt_tpu.parallel.monoid import MonoidContributor

        return MonoidContributor(lift, R, NK_MONOID)

    def apply(self, lift, contrib, step: int, owned):
        contrib.apply(self.gen_ops(step, owned), owned=sorted(owned))
        return contrib

    def adopt(self, lift, contrib, gained, upto_step: int):
        # Regenerate the gained rows' history into `own`, where they are
        # still identity/ver-0 — the regenerated version supersedes the
        # victim's published prefix by row-replace.
        for s in range(upto_step):
            contrib.apply(self.gen_ops(s, gained), owned=sorted(gained))
        return contrib

    def pub_state(self, lift, contrib):
        return contrib.view

    def set_view(self, lift, contrib, swept):
        contrib.absorb(swept)
        return contrib


class _AverageDrill(_MonoidDrill):
    name = "average"
    publish_name = "average_lifted"

    def make_engine(self):
        from antidote_ccrdt_tpu.models.average import AverageDense
        from antidote_ccrdt_tpu.parallel.monoid import MonoidLift

        return MonoidLift(AverageDense())

    def gen_ops(self, step: int, owned):
        import jax.numpy as jnp
        import numpy as np

        from antidote_ccrdt_tpu.models.average import AverageOps

        owned = set(owned)
        key = np.zeros((R, B), np.int32)
        val = np.zeros((R, B), np.int32)
        cnt = np.zeros((R, B), np.int32)
        for r in range(R):
            rng = np.random.default_rng(20_000 * (step + 1) + r)
            if r in owned:
                key[r] = rng.integers(0, NK_MONOID, B)
                val[r] = rng.integers(1, 100, B)
                cnt[r] = 1  # count==0 is the padding/no-op sentinel
        return AverageOps(
            key=jnp.asarray(key), value=jnp.asarray(val), count=jnp.asarray(cnt)
        )

    def digest(self, lift, contrib):
        import numpy as np

        tot = lift.total(contrib.view)  # [1, NK_MONOID] sum/num
        return [
            [int(x) for x in np.asarray(tot.sum)[0]],
            [int(x) for x in np.asarray(tot.num)[0]],
        ]


class _WordcountDrill(_MonoidDrill):
    name = "wordcount"
    publish_name = "wordcount_lifted"

    def make_engine(self):
        from antidote_ccrdt_tpu.models.wordcount import WordcountDense
        from antidote_ccrdt_tpu.parallel.monoid import MonoidLift

        return MonoidLift(WordcountDense(V))

    def gen_ops(self, step: int, owned):
        import jax.numpy as jnp
        import numpy as np

        from antidote_ccrdt_tpu.models.wordcount import WordcountOps

        owned = set(owned)
        key = np.zeros((R, B), np.int32)
        tok = np.full((R, B), -1, np.int32)  # token<0 is padding
        for r in range(R):
            rng = np.random.default_rng(30_000 * (step + 1) + r)
            if r in owned:
                key[r] = rng.integers(0, NK_MONOID, B)
                tok[r] = rng.integers(0, V, B)
        return WordcountOps(key=jnp.asarray(key), token=jnp.asarray(tok))

    def digest(self, lift, contrib):
        import numpy as np

        tot = lift.total(contrib.view)  # counts [1, NK, V], lost [1, NK]
        counts = np.asarray(tot.counts)[0]
        out = [
            [k, int(t), int(counts[k, t])]
            for k in range(NK_MONOID)
            for t in np.nonzero(counts[k])[0]
        ]
        return out + [["lost", int(np.asarray(tot.lost).sum())]]


DRILLS = {d.name: d for d in (_TopkRmvDrill(), _AverageDrill(), _WordcountDrill())}


# Back-compat shims (tests and docs import these for the flagship drill).
def make_engine():
    return DRILLS["topk_rmv"].make_engine()


def gen_step_ops(step: int, owned):
    return DRILLS["topk_rmv"].gen_ops(step, owned)


def observable_digest(dense, state):
    return DRILLS["topk_rmv"].digest(dense, state)


def reference_digest(type_name: str = "topk_rmv"):
    """Sequential single-process ground truth: every step, every replica."""
    drill = DRILLS[type_name]
    dense = drill.make_engine()
    state = drill.init(dense)
    for step in range(STEPS):
        state = drill.apply(dense, state, step, range(R))
    return drill.digest(dense, state)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", required=True)
    ap.add_argument("--member", required=True)
    ap.add_argument("--n-members", type=int, required=True)
    ap.add_argument("--type", default="topk_rmv", choices=sorted(DRILLS))
    ap.add_argument("--die-at", type=int, default=-1)
    ap.add_argument(
        "--join-late", type=float, default=0.0,
        help="delay registration this many seconds: the member joins an "
        "already-running gossip (scale-UP elasticity); it adopts whatever "
        "replicas the ownership map hands it and catches up by full "
        "history re-apply + sweep",
    )
    ap.add_argument("--hb-interval", type=float, default=0.05)
    ap.add_argument("--timeout", type=float, default=0.4)
    ap.add_argument("--step-sleep", type=float, default=0.15)
    ap.add_argument("--publish-every", type=int, default=2)
    ap.add_argument(
        "--delta", action="store_true",
        help="gossip chained deltas (DeltaPublisher) instead of full "
        "snapshots on every publish",
    )
    ap.add_argument(
        "--overlap", dest="overlap", action="store_true", default=None,
        help="overlapped round pipeline (parallel/overlap.py): WAL "
        "append, delta encode and gossip send run on a background host "
        "stage, inbound peer deltas are prefetched+pre-decoded into a "
        "bounded apply queue, and queued windows fold in one batched "
        "dispatch. Default: on unless CCRDT_OVERLAP=0",
    )
    ap.add_argument(
        "--no-overlap", dest="overlap", action="store_false",
        help="force the serial round loop (every phase on the round "
        "thread) regardless of CCRDT_OVERLAP",
    )
    ap.add_argument(
        "--lag-anchor-ops", type=float, default=0.0,
        help="lag-driven backpressure (needs --delta): when the lag "
        "tracker shows any peer >= this many ops behind, the publisher "
        "cuts full anchors every 2 publishes instead of every 4, so "
        "laggards resync from a recent snapshot instead of replaying a "
        "long delta chain; 0 disables",
    )
    ap.add_argument(
        "--partitions", type=int, default=0,
        help="arm the partition plane (needs --delta): every full anchor "
        "also publishes the P-partition digest vector + psnaps, delta-"
        "chain gaps repair partition-granularly (PartialAntiEntropy), "
        "and the divergence watchdog (obs/audit.py) rides the digest "
        "exchanges; 0 disables (legacy whole-snapshot resync)",
    )
    ap.add_argument(
        "--wal-dir", default="",
        help="enable the crash-consistent write-ahead delta log "
        "(harness/wal.py) under this directory: every applied op batch "
        "is appended (CRC-framed, fsynced) BEFORE the publish, and a "
        "restart recovers state = checkpoint ⊔ WAL suffix then resumes "
        "at the step after the last durable record — instead of "
        "regenerating its whole history via peer adoption",
    )
    ap.add_argument("--wal-segment-bytes", type=int, default=256 << 10)
    ap.add_argument(
        "--steps", type=int, default=0,
        help="per-worker step count override (0 = the 10-step default; "
        "every member of one fleet must agree)")
    ap.add_argument(
        "--wal-durability", default="",
        choices=("", "sync", "group", "async"),
        help="WAL durability mode (harness/wal.py): sync = fsync per "
        "append (legacy), group = group commit — appends stage and the "
        "publish boundary fsyncs the whole batch once (default), async "
        "= publish may ship before the fsync; the durable watermark is "
        "published (wal.durable_seq) and recovery truncates to it. "
        "Empty = CCRDT_WAL_DURABILITY env, else group",
    )
    ap.add_argument(
        "--mesh", action="store_true",
        help="force the device-mesh plane on (mesh/): state pins to a "
        "(dc, key) device mesh, intra-slice reconciliation runs as one "
        "batched ICI JOIN all-reduce per publish boundary, and anchors "
        "publish per-shard digest slices + psnaps. Default: CCRDT_MESH "
        "env; either way needs >1 visible device and a JOIN engine",
    )
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from antidote_ccrdt_tpu.utils import faults

    faults.install_from_env()  # supervisor-injected deterministic faults

    from antidote_ccrdt_tpu.parallel.elastic import GossipStore

    drill = DRILLS[args.type]
    dense = drill.make_engine()
    state = drill.init(dense)
    if args.join_late > 0:
        # Late join: compile the engine first (apply a no-op batch), THEN
        # register — from the fleet's view the member appears and is
        # immediately productive.
        state = drill.apply(dense, state, 0, [])
        time.sleep(args.join_late)
    store = GossipStore(args.root, args.member)
    run_worker(store, drill, dense, state, args, result_dir=args.root)


def run_worker(store, drill, dense, state, args, result_dir):
    """The drill body, transport-agnostic: `store` is any GossipNode
    (shared-directory here; scripts/net_gossip_demo.py reuses this loop
    over TCP). Heartbeats in a daemon thread, deterministic op streams
    for owned replicas, ownership-grows adoption, publish/sweep rounds,
    and a final convergence barrier; writes final-<member>.json (digest +
    alive view + metrics counters) into `result_dir`."""
    # An `--steps` override shadows the module default for this worker:
    # the acceptance drills that storm a fleet through warm-up, chaos
    # and a mid-load kill need more runway than the 10-step default
    # (0 / absent keeps the default, and every peer must agree — the
    # final barrier seq is STEPS + dead_n).
    STEPS = int(getattr(args, "steps", 0) or globals()["STEPS"])
    from antidote_ccrdt_tpu.obs import events as obs_events
    from antidote_ccrdt_tpu.obs import export as obs_export
    from antidote_ccrdt_tpu.obs.lag import LagTracker
    from antidote_ccrdt_tpu.obs.audit import DivergenceWatchdog
    from antidote_ccrdt_tpu.parallel.elastic import (
        DeltaPublisher,
        PartialAntiEntropy,
        my_replicas,
        owners,
        sweep,
        sweep_deltas,
    )

    from antidote_ccrdt_tpu.parallel.monoid import MonoidLift

    from antidote_ccrdt_tpu.obs import http as obs_http
    from antidote_ccrdt_tpu.obs import profile as obs_profile

    # Observability plane (all env-gated, like CCRDT_FAULTS): the flight
    # recorder spills every event to $CCRDT_OBS_DIR as it happens (so a
    # SIGKILL still leaves the full record), a metrics snapshot lands in
    # $CCRDT_METRICS_DIR at clean exit for the supervisor to merge, a
    # live OpenMetrics endpoint serves /metrics when $CCRDT_HTTP_PORT is
    # set (address dropped as http-<member> for the supervisor), and the
    # XLA hot-path profiler arms on $CCRDT_PROFILE.
    obs_events.install_from_env(args.member)
    obs_export.install_atexit_dump(store.metrics, args.member)
    obs_profile.install_from_env(store.metrics)
    # Device observatory (CCRDT_DEVPROF, default-armed; =0 kills): every
    # jit slot cache reports compile churn + signature diffs through it,
    # and the pager/live-buffer memory gauges ride the same registry.
    from antidote_ccrdt_tpu.obs import devprof as obs_devprof

    obs_devprof.install_from_env(store.metrics)
    # Span plane (CCRDT_SPANS): round-phase spans spill next to the
    # flight log and mirror into metrics as span.* latency series, so
    # both live scrape surfaces prove the plane is lit.
    from antidote_ccrdt_tpu.obs import spans as obs_spans

    if obs_spans.ACTIVE:
        # The tcp entrypoint arms the plane before building its
        # transport (hello-exchange clock offsets must be recorded);
        # just attach the metrics mirror it could not have yet.
        obs_spans.set_metrics(store.metrics)
    else:
        obs_spans.install_from_env(args.member, store.metrics)
    # Request-trace plane (CCRDT_RTRACE, PR 18): per-request hop records
    # + server echoes on the serve/ingest planes below. Armed here so a
    # worker that ALSO acts as a client (drills running in-process
    # routers) mints traces, and so health/scrape surfaces export the
    # rtrace counters.
    from antidote_ccrdt_tpu.obs import rtrace as obs_rtrace

    obs_rtrace.install_from_env(args.member, metrics=store.metrics)
    lag_tracker = LagTracker(args.member)
    confident_stale = max(1.5 * args.timeout, 0.6)
    # Divergence watchdog (obs/audit.py): always armed — with no
    # partition plane it just exports the OK gauges (so the dashboard
    # audit column renders on every fleet); with --partitions the
    # partial anti-entropy tier feeds it a per-peer digest-vector
    # observation on every fetch.
    watchdog = DivergenceWatchdog(args.member, metrics=store.metrics)

    pub = None  # set below when --delta
    pae = None  # set below when --delta --partitions
    cursors: dict = {}
    owned_prev: set = set()

    # --- read-serving plane (tentpole, PR 9): CCRDT_SERVE=1 attaches a
    # ServePlane to this worker — the replica swaps to the merged view at
    # every publish boundary, and all three wire surfaces (tcp {query}
    # frame, bridge {query} op, POST /query) answer off it with
    # bounded-staleness pedigrees fed by the lag tracker.
    from antidote_ccrdt_tpu import serve as serve_mod

    plane = serve_mod.install_from_env(
        dense, args.member, metrics=store.metrics, lag_tracker=lag_tracker
    )
    ctx = {"ovl": None, "wal": None, "ingest_step": -1}  # filled below;
    # health_extra closes over the cells (the scrape server may call
    # before they are assigned, so the dict — not late locals — carries
    # them)

    def _serve_swap(view, seq) -> None:
        if plane is not None:
            plane.swap(view, seq)

    # --- write-ingest plane (tentpole, PR 16): CCRDT_INGEST=1 attaches
    # an IngestPlane — client {write} frames park in its queue, the step
    # loop folds them BEFORE wal.log_step captures the post view (so a
    # write's seq IS the step whose WAL record and delta carry it), and
    # tiered acks pin `durable` to the WAL's fsync watermark. Admission
    # control sheds writers on WAL durability lag and overlap-queue
    # depth with an honest retry_after_ms.
    _ING_MAX_WAL_LAG = int(os.environ.get("CCRDT_INGEST_MAX_WAL_LAG", "64"))
    _ING_MAX_OVL_DEPTH = int(
        os.environ.get("CCRDT_INGEST_MAX_OVL_DEPTH", "8")
    )

    def _wal_pressure():
        w = ctx["wal"]
        if w is None:
            return None
        lag = max(0, int(w._last_appended) - int(w.durable_seq))
        if lag > _ING_MAX_WAL_LAG:
            return min(5000, 25 * lag)
        return None

    def _ovl_pressure():
        o = ctx["ovl"]
        if o is None:
            return None
        depth = o.pressure_depth()
        if depth > _ING_MAX_OVL_DEPTH:
            return min(5000, 100 * depth)
        return None

    def _ingest_watermarks() -> dict:
        out = {str(k): int(v) for k, v in cursors.items()}
        out[args.member] = int(ctx["ingest_step"])
        return out

    iplane = (
        serve_mod.install_ingest_from_env(
            args.member,
            metrics=store.metrics,
            durable_fn=lambda: (
                int(ctx["wal"].durable_seq) if ctx["wal"] is not None
                else -1
            ),
            watermarks_fn=_ingest_watermarks,
            pressure_fns=(_wal_pressure, _ovl_pressure),
        )
        if hasattr(drill, "ingest")
        else None
    )

    def health_extra() -> dict:
        """Serving-readiness: can a load balancer route reads here?"""
        lag = lag_tracker.report()
        doc = {
            "max_peer_staleness_s": round(
                max((r["staleness_s"] for r in lag.values()), default=0.0), 6
            ),
            "applied_watermark": max(cursors.values(), default=-1)
            if cursors
            else -1,
            "overlap_queue_depth": (
                len(ctx["ovl"].apq) if ctx["ovl"] is not None else 0
            ),
        }
        w = ctx["wal"]
        if w is not None:
            # Durability readiness: how exposed is this worker right now
            # (async mode: appended-but-unfsynced records a crash would
            # truncate; sync/group: always 0 outside a staged batch).
            doc["wal_durability"] = w.durability
            doc["wal_durable_seq"] = int(w.durable_seq)
            doc["wal_durability_lag"] = int(
                max(0, w._last_appended - w.durable_seq)
            )
        doc.update(watchdog.health_fields())
        if plane is not None:
            doc.update(plane.health_fields())
        if iplane is not None:
            doc.update(iplane.health_fields())
        doc.update(obs_rtrace.health_fields())
        return doc

    obs_http.install_from_env(
        store.metrics,
        args.member,
        addr_dir=result_dir,
        query_handler=plane.handler_for("http") if plane is not None else None,
        health_extra=health_extra,
        write_handler=(
            iplane.handler_for("http") if iplane is not None else None
        ),
    )
    tr = getattr(store, "transport", None)
    if plane is not None and tr is not None and hasattr(tr, "install_serve"):
        # TCP fleets additionally answer {query} frames in-band.
        tr.install_serve(plane)
    if iplane is not None and tr is not None and hasattr(tr, "install_ingest"):
        # ... and {write} frames via the ingest plane (PR 16).
        tr.install_ingest(iplane)

    # --- mesh plane (tentpole, PR 12): CCRDT_MESH=1 (or --mesh) pins this
    # worker's state onto a (dc, key) device mesh. Partitions map whole
    # onto key shards (MeshPlan.shard_of — digests/psnaps/WAL tags/sharded
    # checkpoints keep working per-shard as-is), intra-slice
    # reconciliation is one batched ICI JOIN all-reduce per publish
    # boundary (mesh/reduce), and the anchor/anti-entropy plumbing below
    # goes per-shard. JOIN engines only (mesh.supports). With the flag
    # off, on 1 device, or for MONOID engines this is None and every code
    # path below is bit-identical to the pre-mesh worker.
    from antidote_ccrdt_tpu import mesh as mesh_mod

    mplan = mesh_mod.install_from_env(
        dense,
        partitions=int(getattr(args, "partitions", 0) or 0) or None,
        override=(True if getattr(args, "mesh", False) else None),
        metrics=store.metrics,
    )

    def _mesh_tick(st, donate=False):
        """One intra-slice reduce at a publish boundary. Total: an
        injected `mesh.reduce` failure degrades to plain gossip. Donate
        only on the serial path — the overlap host stage may still be
        serializing buffers a submitted WAL task holds."""
        if mplan is None:
            return st
        view = drill.pub_state(dense, st)
        red = mesh_mod.try_ici_reduce(
            dense, mplan, view, donate=donate, metrics=store.metrics
        )
        return drill.set_view(dense, st, red) if red is not view else st

    # --- crash-consistent WAL (tentpole, PR 2): recover checkpoint ⊔
    # delta suffix, resume AFTER the last durable step. Peer adoption
    # stays the fallback: with no (or a deleted) WAL this block recovers
    # nothing and the worker rebuilds via the ownership/adopt path below.
    wal = None
    start_step = 0
    wal_dir = getattr(args, "wal_dir", "")
    if wal_dir:
        from antidote_ccrdt_tpu.harness.wal import ElasticWal

        wal = ElasticWal(
            wal_dir, args.member, dense, drill.publish_name,
            segment_bytes=getattr(args, "wal_segment_bytes", 256 << 10),
            metrics=store.metrics,
            partitions=int(getattr(args, "partitions", 0) or 0) or None,
            durability=getattr(args, "wal_durability", "") or None,
            mesh_plan=mplan,
        )
        ctx["wal"] = wal
        from antidote_ccrdt_tpu.parallel.overlap import CommitCoalescer

        coalescer = CommitCoalescer(metrics=store.metrics)
        coalescer.add(wal)
        rec_state, last_step, rec_owned = wal.recover(
            drill.pub_state(dense, state)
        )
        if last_step >= 0 and rec_state is not None:
            state = drill.set_view(dense, state, rec_state)
            start_step = last_step + 1
            store.metrics.set("wal.resume_step", start_step)
            if not isinstance(dense, MonoidLift):
                # JOIN engines: the recovered state already holds these
                # replicas' history, so they are NOT "gained" (no full
                # regeneration — that is the WAL's whole point).
                owned_prev = set(rec_owned)
            # MONOID engines keep owned_prev empty: the recovered view is
            # absorbed as peer rows (set_view), and the adopt path below
            # regenerates the own-side contribution with versions identical
            # to the lost incarnation's — row-replace dedups the overlap.

    if mplan is not None:
        # Pin the (possibly WAL-recovered) state onto the mesh once up
        # front; host-side folds later in the run may drift leaves off
        # their shardings, and `ici_reduce` re-pins those lazily
        # (ensure_placed) at each boundary.
        state = drill.set_view(
            dense, state, mplan.place(drill.pub_state(dense, state))
        )

    def do_publish(store, seq_hint):
        view = drill.pub_state(dense, state)
        if pub is not None:
            pub.publish(view)  # pub.on_publish swaps the read replica
        else:
            store.publish(drill.publish_name, view, seq_hint)
            _serve_swap(view, seq_hint)

    def do_sweep(store, st):
        view = drill.pub_state(dense, st)
        if pub is not None:
            swept, stats = sweep_deltas(store, dense, view, cursors,
                                        partial=pae)
        else:
            swept, stats = sweep(store, dense, view)
        return drill.set_view(dense, st, swept), stats

    def feed_lag() -> None:
        if obs_spans.ACTIVE:
            with obs_spans.span("round.lag_update"):
                _feed_lag()
        else:
            _feed_lag()

    def _feed_lag() -> None:
        """Watermarks from the transport vs what this worker merged.
        Delta mode: published = the peer's highest visible delta/anchor
        seq, applied = sweep_deltas' cursor. Snapshot mode: both sides
        are the snapshot header seq (sweep merges latest-wins whole
        states, so once swept we hold everything the header covers)."""
        for m in set(store.delta_members()) | set(store.snapshot_members()):
            if m == args.member:
                continue
            snap = store.snapshot_seq(m)
            seqs = store.delta_seqs(m)
            hi = max(seqs + ([snap] if snap is not None else [-1]))
            if hi >= 0:
                lag_tracker.observe_published(m, hi)
            # Every feed_lag call site directly follows a sweep, so the
            # visible snapshot has just been merged: the applied
            # watermark is the delta cursor OR that snapshot seq,
            # whichever is ahead (the final convergence loop sweeps full
            # snapshots without advancing delta cursors).
            applied = max(
                cursors.get(m, -1) if pub is not None else -1,
                snap if snap is not None else -1,
            )
            if applied >= 0:
                lag_tracker.observe_applied(m, applied)
        # A confidently-dead peer's frozen watermark must not read as
        # ever-growing lag in the exported gauges (re-observing a
        # revived peer re-creates its entry).
        alive_now = set(store.alive_members(confident_stale))
        for m in list(lag_tracker.report()):
            if m != args.member and m not in alive_now:
                lag_tracker.drop(m)
        lag_tracker.export_to(store.metrics)

    def drop_status(step, owned) -> None:
        """Periodic machine-readable status for the live dashboard:
        obs-<member>.json in the result dir (atomic replace)."""
        snap = store.metrics.snapshot()
        counters = snap["counters"]
        serve_doc = {
            k[len("serve."):]: v
            for k, v in counters.items()
            if k.startswith("serve.")
        }
        # Tail percentiles for the dashboard's serving columns, from the
        # same reservoirs the exporters read.
        reads = sorted(snap["latencies"].get("serve.read", []))
        if reads:
            serve_doc["read_p99_ms"] = round(
                reads[int(0.99 * (len(reads) - 1))] * 1e3, 3
            )
        bounds = sorted(snap["latencies"].get("serve.staleness_bound", []))
        if bounds:
            serve_doc["staleness_p99_s"] = round(
                bounds[int(0.99 * (len(bounds) - 1))], 6
            )
        doc = {
            "member": args.member,
            "zone": getattr(store, "zone", None),
            "t": time.time(),
            "step": step,
            "owned": sorted(int(r) for r in owned),
            "alive": store.alive_members(args.timeout),
            "lag": lag_tracker.report(),
            "sendq": {
                k[len("net.sendq."):]: v
                for k, v in counters.items()
                if k.startswith("net.sendq.")
            },
            "wal_last_seq": counters.get("wal.last_seq"),
            "wal_durable_seq": counters.get("wal.durable_seq"),
            "wal_durability_lag": counters.get("wal.durability_lag"),
            "wal_durability": (
                ctx["wal"].durability if ctx["wal"] is not None else None
            ),
            "serve": serve_doc,
            "mesh": {
                k[len("mesh."):]: v
                for k, v in counters.items()
                if k.startswith("mesh.")
            },
            "audit": watchdog.status_fields(),
            # rtrace plane counters (dashboard tail column): the live
            # plane mirrors them into metrics as rtrace.* on every bump.
            "rtrace": {
                k[len("rtrace."):]: v
                for k, v in counters.items()
                if k.startswith("rtrace.")
            },
            # Device observatory (dashboard churn column + the
            # watermarks CLI): trailing-minute recompiles, worst churn
            # site, and the device-memory gauges.
            "devprof": (
                dict(
                    obs_devprof.status_fields(),
                    **{
                        k[len("devprof_"):]: v
                        for k, v in obs_devprof.health_fields().items()
                    },
                )
                if obs_devprof.ACTIVE
                else {}
            ),
        }
        path = os.path.join(result_dir, f"obs-{args.member}.json")
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)

    if args.delta:
        # Lag-driven backpressure: the drill's pressure signal is this
        # worker's own worst peer lag — when convergence is straining
        # (we are behind, or the fleet is churning), anchors come sooner
        # so whoever is behind resyncs from a snapshot, not a chain.
        lag_anchor_ops = float(getattr(args, "lag_anchor_ops", 0) or 0)
        lag_source = None
        if lag_anchor_ops > 0:
            def lag_source():
                return max(
                    (r["lag_ops"] for r in lag_tracker.report().values()),
                    default=0,
                )
        P = int(getattr(args, "partitions", 0) or 0)
        pub = DeltaPublisher(
            store, dense, name=drill.publish_name, full_every=4,
            lag_source=lag_source, lag_threshold=lag_anchor_ops,
            partitions=P or None, mesh_plan=mplan,
        )
        pub.on_publish = _serve_swap
        if P:
            # Gap repairs go partition-granular, and every digest fetch
            # feeds the watchdog's per-peer divergence state machine.
            # With a mesh plan the fetches additionally group by owning
            # shard — cross-slice anti-entropy ships shard-local slices.
            pae = PartialAntiEntropy(
                store, partitions=P, watchdog=watchdog, mesh_plan=mplan
            )
        if start_step > 0:
            # Resume the delta-seq lineage PAST anything the lost
            # incarnation published (old seq <= old step < start_step):
            # peers' per-member cursors sit at the old high seq, so a
            # seq restart from 0 would read as already-seen and be
            # dropped forever. A fresh incarnation's first publish is a
            # full snapshot (no _prev), which resyncs every peer.
            pub.seq = start_step

    # --- overlapped round pipeline (tentpole, PR 7): take WAL append,
    # delta encode and gossip send off the round thread (one FIFO host
    # stage preserves durable-before-visible), prefetch + pre-decode
    # inbound peer deltas into a bounded apply queue, and fold queued
    # windows in one batched dispatch. Default ON (CCRDT_OVERLAP=0 or
    # --no-overlap forces the serial loop). Convergence is bit-identical
    # either way — everything gossiped is a join.
    from antidote_ccrdt_tpu.parallel import overlap as overlap_mod

    ovl = None
    if overlap_mod.enabled(getattr(args, "overlap", None)):
        ovl = overlap_mod.OverlapPipeline(
            store, dense, drill.pub_state(dense, state),
            post_fold=(
                (lambda s: mesh_mod.try_ici_reduce(
                    dense, mplan, s, donate=False, metrics=store.metrics
                ))
                if mplan is not None
                else None
            ),
        )
        # feed_lag's applied watermarks are now the pipeline's (what
        # drain_into actually folded), not sweep_deltas' cursor dict.
        cursors = ovl.cursors
        ctx["ovl"] = ovl  # healthz readiness reads the live queue depth

    def _overlap_boundary(view, step, owned_snapshot):
        """The publish boundary as ONE host-stage task, FIFO after this
        step's WAL append: block_until_ready at the boundary only (the
        round thread never waits for readback), then publish, lag/status
        bookkeeping, and the post-publish compaction checkpoint."""
        with store.metrics.timer("net.round"):
            tok = (
                obs_spans.begin("round.device_sync", step=step, via="overlap")
                if obs_spans.ACTIVE
                else None
            )
            try:
                import jax

                jax.block_until_ready(view)
            except Exception:  # noqa: BLE001 — non-array states are fine
                pass
            finally:
                obs_spans.end(tok)
            if wal is not None and wal.durability != "async":
                # Group commit: this boundary task runs FIFO-after every
                # append it covers, so ONE flush here makes the whole
                # batch durable BEFORE the publish below makes any of it
                # visible (the write-ahead contract, batched). Async
                # mode skips it on purpose — the publish may overtake
                # the fsync, and the published wal.durable_seq watermark
                # plus the certifier account for exactly that window.
                coalescer.flush()
            if pub is not None:
                # defer=True (ingest fast path): delta windows stage
                # host-side and ship as ONE coalesced range frame when
                # the coalesce cap fills or an anchor lands — the
                # pipeline flush + the explicit flush_wire below bound
                # how long a window can stay parked. on_publish still
                # swaps the read replica every boundary.
                pub.publish(view, defer=True)
            else:
                store.publish(drill.publish_name, view, step)
                _serve_swap(view, step)
        feed_lag()
        drop_status(step, owned_snapshot)
        if wal is not None:
            # Anchor AFTER the publish (same rule as the serial path):
            # the compaction watermark must never pass what gossip has
            # seen — FIFO on this thread gives exactly that order.
            wal.checkpoint(view, step)

    # Background heartbeat: dies with the process, so a crash goes stale.
    def beat():
        while True:
            store.heartbeat()
            time.sleep(args.hb_interval)

    threading.Thread(target=beat, daemon=True).start()

    # Start barrier: wait until the whole initial membership has joined
    # (late joiners skip it — the fleet is already running).
    while args.join_late == 0 and len(store.members()) < args.n_members:
        time.sleep(0.02)

    for step in range(start_step, STEPS):
        if step == args.die_at:
            os._exit(1)  # crash: no cleanup, heartbeat goes stale
        # The attribution denominator: everything the step does except
        # the pacing sleep. ccrdt_spans.py `attribute` reconciles the
        # phase spans inside this window against its duration.
        e2e_tok = (
            obs_spans.begin("round.e2e", step=step)
            if obs_spans.ACTIVE
            else None
        )
        pre_view = drill.pub_state(dense, state) if wal is not None else None
        # Ownership only ever GROWS during a run: dropping a replica on a
        # membership change is unsafe under asymmetric views (member A may
        # drop r for new owner B before B has even seen the new map — r's
        # trailing steps would be applied by no one). Keeping it means the
        # old and new owner briefly both apply r's deterministic stream,
        # which dedups: JOIN by idempotence, MONOID because identical
        # streams produce identical (version, content) rows under the
        # lift. (A real deployment would shed the old owner's copy at the
        # next reconciliation barrier.)
        owned = owned_prev | set(my_replicas(store, R, args.timeout))
        # Adoption: replicas gained since last step get their FULL history
        # regenerated — steps the previous owner already published merge
        # in harmlessly (join dedup / version row-replace), steps it lost
        # in the crash are recreated from the durable op source.
        gained = owned - owned_prev
        if gained:
            state = drill.adopt(dense, state, sorted(gained), step)
        owned_prev = owned
        if obs_spans.ACTIVE:
            # Honest split of the device side of the round: dispatch =
            # handing the batched op application to XLA, sync = waiting
            # for the result arrays. The sync point exists only when the
            # span plane is on — the untraced path is untouched.
            with obs_spans.span(
                "round.device_dispatch", step=step, site="drill.apply"
            ):
                state = drill.apply(dense, state, step, sorted(owned))
            with obs_spans.span("round.device_sync", step=step):
                try:
                    import jax

                    jax.block_until_ready(state)
                except Exception:  # noqa: BLE001 — non-array states are fine
                    pass
        else:
            state = drill.apply(dense, state, step, sorted(owned))
        if iplane is not None:
            # Fold parked client writes NOW — after the drill stream,
            # BEFORE wal.log_step captures post_view below — so every
            # write acked at this step is inside the step's WAL record
            # and its next published delta. Transport threads blocked in
            # handle() wake with (member, step) as their (origin, seq).
            def _fold_ingest(ops, _s=step, _o=tuple(sorted(owned))):
                nonlocal state
                effects = [serve_mod.effect_from_wire(o) for o in ops]
                state = drill.ingest(dense, state, effects, _s, _o)

            iplane.drain(step, _fold_ingest)
            ctx["ingest_step"] = step
        if ovl is not None:
            # Overlapped round: fold whatever peer windows the prefetcher
            # queued (device work — the round thread's only job), then
            # hand every host phase to the pipeline. WAL append is
            # submitted FIRST, so on the FIFO host stage this step's
            # delta is durable before the publish makes it visible —
            # the same write-ahead order as the serial path, minus the
            # round thread waiting for it.
            view = drill.pub_state(dense, state)
            swept = ovl.drain_into(view)
            if swept is not view:
                state = drill.set_view(dense, state, swept)
            if wal is not None:
                ovl.submit(
                    wal.log_step, step, sorted(owned), pre_view,
                    drill.pub_state(dense, state),
                )
            if step % args.publish_every == 0:
                # Pre-join the dc blocks BEFORE the boundary ships: the
                # published anchor carries reduced rows. No donation —
                # the WAL submit above may still hold these buffers on
                # the host stage.
                state = _mesh_tick(state, donate=False)
                ovl.submit(
                    _overlap_boundary, drill.pub_state(dense, state),
                    step, sorted(owned),
                )
        else:
            if wal is not None:
                # Write-ahead: this step's adopt+apply delta must be
                # durable BEFORE the publish makes it externally visible
                # — a crash after publish but before append could
                # otherwise leave peers holding state the restarted
                # worker cannot re-derive.
                wal.log_step(
                    step, sorted(owned), pre_view,
                    drill.pub_state(dense, state),
                )
            if step % args.publish_every == 0:
                # Pre-join the dc blocks before publishing. Donation is
                # safe here: log_step above serialized its record bytes
                # synchronously, so this round thread holds the only
                # live reference to the state buffers.
                state = _mesh_tick(state, donate=True)
                with store.metrics.timer("net.round"):
                    if wal is not None and wal.durability != "async":
                        coalescer.flush()  # durable before visible
                    do_publish(store, step)
                    state, _ = do_sweep(store, state)
                feed_lag()
                drop_status(step, owned)
                if wal is not None:
                    # Anchor AFTER the publish: the compaction watermark
                    # must never pass what gossip has seen (checkpoint
                    # durability substitutes for the compacted deltas
                    # only once peers could fetch the same state).
                    wal.checkpoint(drill.pub_state(dense, state), step)
        obs_spans.end(e2e_tok)
        time.sleep(args.step_sleep)

    if ovl is not None:
        # Flush the pipeline before settling: host tasks durable (WAL
        # tail + last publishes), prefetcher stopped, queued windows
        # folded in. The convergence loop below is the ordinary SERIAL
        # path on purpose — it must keep adopting late-detected deaths,
        # and it sweeps full snapshots without needing the pipeline.
        view = drill.pub_state(dense, state)
        swept = ovl.close(view)
        if swept is not view:
            state = drill.set_view(dense, state, swept)
        if pub is not None:
            # Ship any wire windows the deferred boundaries left staged.
            # The serial loop's own publishes would flush them too, but
            # a full anchor landing first would DISCARD them — flushing
            # here lets peers chain the tail instead of resyncing.
            pub.flush_wire()

    # Final convergence: publish/sweep until every member that ever
    # published has either published its FINAL state or is confidently
    # dead. Gating on snapshots rather than instantaneous liveness means
    # a live peer whose heartbeat thread stalls for one timeout window is
    # still waited for (its snapshot step says it isn't done) instead of
    # being dropped mid-convergence; the crashed victim is exempted by a
    # stale-beyond-doubt heartbeat.
    #
    # "Final" is STEPS + the number of members THIS worker believes
    # confidently dead, published only AFTER an adopt pass under that
    # belief. A bare seq==STEPS barrier has a race: a survivor that
    # detects the victim's death only after its step loop could publish
    # STEPS (pre-adoption), a peer sees "finished", sweeps that
    # pre-adoption snapshot and exits — the victim's trailing steps
    # reach no one. Tying the advertised seq to the death count means a
    # peer that has itself seen the death keeps sweeping until some
    # snapshot POSTDATES an adoption pass that accounted for it.
    # Death is STICKY here: a member once confirmed stale-beyond-doubt
    # has had its replicas adopted (ownership only grows), so a late
    # heartbeat from it — a starved-but-doomed victim flapping back
    # within the timeout window — must not resurrect it into the pending
    # set or the exit-time alive report. The deadline extends while the
    # barrier observes progress (pending membership or peer seqs
    # changing),
    # so a victim running slow under load gets waited out instead of
    # abandoned at a flat cutoff; a truly wedged fleet still exits.
    if wal is not None and wal.durability != "async":
        # The trailing steps since the last publish boundary are still
        # staged; the convergence loop below publishes state that
        # includes them, so commit the batch before anything ships.
        coalescer.flush()
    deadline = time.time() + 10
    hard_deadline = time.time() + 60
    confirmed_dead: set = set()
    last_progress = None
    while time.time() < min(deadline, hard_deadline):
        # Keep adopting here too: a victim whose death is only DETECTED
        # after the step loop ended (slow failure detection under load)
        # would otherwise leave its trailing steps applied by no one —
        # survivors must regenerate its full history before settling.
        owned = owned_prev | set(my_replicas(store, R, args.timeout))
        gained = owned - owned_prev
        if gained:
            state = drill.adopt(dense, state, sorted(gained), STEPS)
        owned_prev = owned
        swept, _ = sweep(store, dense, drill.pub_state(dense, state))
        state = drill.set_view(dense, state, swept)
        alive_now = set(store.alive_members(confident_stale))
        confirmed_dead |= {
            m for m in store.members()
            if m != args.member and m not in alive_now
        }
        dead_n = len(confirmed_dead)
        for m in confirmed_dead:
            # A dead peer's frozen digest vector must not age into a
            # wedged-divergence alarm; adoption already owns its ops.
            watchdog.drop(m)
        # Adopt under the SAME belief the publish below advertises. The
        # my_replicas pass above reads heartbeats at args.timeout, the
        # death confirmation reads them at confident_stale — two separate
        # samples. A heartbeat that ages past BOTH thresholds between
        # them lets this worker publish STEPS + dead_n in an iteration
        # whose adopt pass never saw the death; a peer satisfies its
        # barrier on that seq, final-sweeps the pre-adoption snapshot
        # (the post-adoption republish reuses the SAME seq, so a
        # seq-gated fetch skips it), and exits missing the victim's
        # trailing steps for the replicas that hashed here. Re-deriving
        # ownership over the advertised alive view before publishing
        # closes the gap; over-adoption is idempotent (ownership only
        # grows, regeneration dedups), so the extra pass is free.
        owned = owned_prev | {
            r for r, m in owners(
                sorted((alive_now | {args.member}) - confirmed_dead), R
            ).items()
            if m == args.member
        }
        gained = owned - owned_prev
        if gained:
            state = drill.adopt(dense, state, sorted(gained), STEPS)
        owned_prev = owned
        final_view = drill.pub_state(dense, state)
        store.publish(drill.publish_name, final_view, STEPS + dead_n)
        _serve_swap(final_view, STEPS + dead_n)
        feed_lag()
        drop_status(STEPS, owned)
        pending = []
        seqs = {}
        # Registered members count even before their first snapshot: a
        # fast worker can reach this barrier while peers are still
        # compiling — with snapshot_members() alone the pending set is
        # vacuously empty and it exits without sweeping anyone.
        for m in set(store.members()) | set(store.snapshot_members()):
            if m == args.member:
                continue
            # Poll the 8-byte seq header, not the whole (large) snapshot.
            seq = store.snapshot_seq(m)
            seqs[m] = seq
            finished = seq is not None and seq >= STEPS + dead_n
            if not finished and m in alive_now and m not in confirmed_dead:
                pending.append(m)
        if not pending:
            break
        progress = (frozenset(pending), tuple(sorted(seqs.items())))
        if progress != last_progress:
            last_progress = progress
            deadline = time.time() + 10
        time.sleep(0.1)
    swept, _ = sweep(store, dense, drill.pub_state(dense, state))
    state = drill.set_view(dense, state, swept)
    if wal is not None:
        wal.close()

    out = {
        "member": args.member,
        "zone": getattr(store, "zone", None),
        # Confirmed deaths stay dead in the exit report: replicas were
        # already adopted irreversibly, so a post-confirmation heartbeat
        # flap must not read as a revival.
        "alive": [
            m for m in store.alive_members(args.timeout)
            if m not in confirmed_dead
        ],
        "digest": drill.digest(dense, state),
        "metrics": store.metrics.snapshot()["counters"],
        "lag": lag_tracker.report(),
    }
    with open(os.path.join(result_dir, f"final-{args.member}.json"), "w") as f:
        json.dump(out, f)
    print(json.dumps(out), flush=True)

    # Env-gated post-drill serve linger: keep the process (and its
    # daemon serve plane) alive after the final barrier so a supervisor
    # can measure the serve path against a QUIESCED worker — no
    # stepping, no per-step JIT recompiles, no gossip churn. The
    # supervisor ends the linger early by dropping <root>/serve-stop;
    # the deadline bounds it if the supervisor dies first.
    try:
        linger_s = float(os.environ.get("CCRDT_SERVE_LINGER_S", "0") or 0.0)
    except ValueError:
        linger_s = 0.0
    if linger_s > 0:
        stop_f = os.path.join(result_dir, "serve-stop")
        deadline = time.time() + linger_s
        while time.time() < deadline and not os.path.exists(stop_f):
            time.sleep(0.1)


if __name__ == "__main__":
    main()
