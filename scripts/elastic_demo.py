"""Elastic-recovery drill worker (see parallel/elastic.py).

N workers gossip a dense topk_rmv grid through a shared directory. Each
step, each worker applies a *deterministic* op batch for the replicas it
owns under the current alive set, heartbeats, and periodically publishes/
sweeps. A worker started with --die-at crashes (os._exit) at that step;
survivors detect the stale heartbeat, adopt its replicas, and — because
op generation is deterministic and the join is idempotent — simply
re-apply the adopted replicas' entire op history. Duplicated application
of steps the victim already published is harmless by construction.

Run one worker:
    python scripts/elastic_demo.py --root /tmp/g --member w0 --n-members 3

The supervising test (tests/test_elastic.py) launches several and checks
every survivor converges to the sequential single-process reference.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scripts.cover import install_child_cover  # noqa: E402

install_child_cover()  # no-op outside `make cover` runs

# Demo geometry (shared with the test's reference computation).
R, NK, I, DCS, K, M, B, Br = 4, 1, 64, 4, 8, 2, 32, 8
STEPS = 10


def make_engine():
    from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense

    return make_dense(n_ids=I, n_dcs=DCS, size=K, slots_per_id=M)


def gen_step_ops(step: int, owned):
    """Deterministic [R, ...] op batch for `step`; replicas not in `owned`
    are masked to padding (add_ts=0 / rmv_id=-1). Any member can generate
    any replica's stream — the durable op source of the drill."""
    import jax.numpy as jnp
    import numpy as np

    from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps

    owned = set(owned)
    a_key = np.zeros((R, B), np.int32)
    a_id = np.zeros((R, B), np.int32)
    a_score = np.zeros((R, B), np.int32)
    a_dc = np.zeros((R, B), np.int32)
    a_ts = np.zeros((R, B), np.int32)
    r_key = np.zeros((R, Br), np.int32)
    r_id = np.full((R, Br), -1, np.int32)
    r_vc = np.zeros((R, Br, DCS), np.int32)
    for r in range(R):
        rng = np.random.default_rng(10_000 * (step + 1) + r)
        ids = rng.integers(0, I, B)
        scores = rng.integers(1, 500, B)
        if r in owned:
            a_id[r], a_score[r] = ids, scores
            a_dc[r] = r % DCS
            a_ts[r] = step * B + np.arange(B) + 1  # unique, monotone
            r_id[r] = rng.integers(0, I, Br)
            r_vc[r, :, r % DCS] = rng.integers(1, max(2, step * B + 1), Br)
    return TopkRmvOps(
        add_key=jnp.asarray(a_key), add_id=jnp.asarray(a_id),
        add_score=jnp.asarray(a_score), add_dc=jnp.asarray(a_dc),
        add_ts=jnp.asarray(a_ts),
        rmv_key=jnp.asarray(r_key), rmv_id=jnp.asarray(r_id),
        rmv_vc=jnp.asarray(r_vc),
    )


def observable_digest(dense, state):
    from antidote_ccrdt_tpu.harness.dense_replay import fold_rows

    obs = dense.value(fold_rows(dense, state, range(R)))[0][0]
    return sorted((int(i), int(s)) for (i, s) in obs)


def reference_digest():
    """Sequential single-process ground truth: every step, every replica."""
    dense = make_engine()
    state = dense.init(R, NK)
    for step in range(STEPS):
        state, _ = dense.apply_ops(state, gen_step_ops(step, range(R)))
    return observable_digest(dense, state)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", required=True)
    ap.add_argument("--member", required=True)
    ap.add_argument("--n-members", type=int, required=True)
    ap.add_argument("--die-at", type=int, default=-1)
    ap.add_argument(
        "--join-late", type=float, default=0.0,
        help="delay registration this many seconds: the member joins an "
        "already-running gossip (scale-UP elasticity); it adopts whatever "
        "replicas the ownership map hands it and catches up by full "
        "history re-apply + sweep",
    )
    ap.add_argument("--hb-interval", type=float, default=0.05)
    ap.add_argument("--timeout", type=float, default=0.4)
    ap.add_argument("--step-sleep", type=float, default=0.15)
    ap.add_argument("--publish-every", type=int, default=2)
    ap.add_argument(
        "--delta", action="store_true",
        help="gossip chained deltas (DeltaPublisher) instead of full "
        "snapshots on every publish",
    )
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from antidote_ccrdt_tpu.parallel.elastic import (
        DeltaPublisher,
        GossipStore,
        my_replicas,
        sweep,
        sweep_deltas,
    )

    dense = make_engine()
    state = dense.init(R, NK)
    pub = None  # set after the store exists when --delta
    cursors: dict = {}

    def do_publish(store, seq_hint):
        if pub is not None:
            pub.publish(state)
        else:
            store.publish("topk_rmv", state, seq_hint)

    def do_sweep(store, st):
        if pub is not None:
            return sweep_deltas(store, dense, st, cursors)
        return sweep(store, dense, st)

    if args.join_late > 0:
        # Late join: compile the engine first (apply a no-op batch), THEN
        # register — from the fleet's view the member appears and is
        # immediately productive.
        state, _ = dense.apply_ops(state, gen_step_ops(0, []))
        time.sleep(args.join_late)
    store = GossipStore(args.root, args.member)
    if args.delta:
        pub = DeltaPublisher(store, dense, full_every=4)

    # Background heartbeat: dies with the process, so a crash goes stale.
    def beat():
        while True:
            store.heartbeat()
            time.sleep(args.hb_interval)

    threading.Thread(target=beat, daemon=True).start()

    # Start barrier: wait until the whole initial membership has joined
    # (late joiners skip it — the fleet is already running).
    while args.join_late == 0 and len(store.members()) < args.n_members:
        time.sleep(0.02)

    owned_prev: set = set()
    for step in range(STEPS):
        if step == args.die_at:
            os._exit(1)  # crash: no cleanup, heartbeat goes stale
        # Ownership only ever GROWS during a run: dropping a replica on a
        # membership change is unsafe under asymmetric views (member A may
        # drop r for new owner B before B has even seen the new map — r's
        # trailing steps would be applied by no one). Keeping it means the
        # old and new owner briefly both apply r's deterministic stream,
        # which the join dedups — idempotence is what makes handoff need
        # no coordination. (A real deployment would shed the old owner's
        # copy at the next reconciliation barrier.)
        owned = owned_prev | set(my_replicas(store, R, args.timeout))
        # Adoption: replicas gained since last step get their FULL history
        # re-applied — steps the previous owner already published merge in
        # idempotently, steps it lost in the crash are regenerated.
        for gained in sorted(owned - owned_prev):
            for s in range(step):
                state, _ = dense.apply_ops(
                    state, gen_step_ops(s, [gained]), collect_dominated=False
                )
        owned_prev = owned
        state, _ = dense.apply_ops(
            state, gen_step_ops(step, sorted(owned)), collect_dominated=False
        )
        if step % args.publish_every == 0:
            do_publish(store, step)
            state, _ = do_sweep(store, state)
        time.sleep(args.step_sleep)

    # Final convergence: publish/sweep until every member that ever
    # published has either published its FINAL state (step >= STEPS) or is
    # confidently dead. Gating on snapshots rather than instantaneous
    # liveness means a live peer whose heartbeat thread stalls for one
    # timeout window is still waited for (its snapshot step says it isn't
    # done) instead of being dropped mid-convergence; the crashed victim
    # is exempted by a stale-beyond-doubt heartbeat.
    store.publish("topk_rmv", state, STEPS)
    confident_stale = max(1.5 * args.timeout, 0.6)
    deadline = time.time() + 10
    while time.time() < deadline:
        state, _ = sweep(store, dense, state)
        store.publish("topk_rmv", state, STEPS)
        pending = []
        alive_now = set(store.alive_members(confident_stale))
        for m in store.snapshot_members():
            if m == args.member:
                continue
            # Poll the 8-byte seq header, not the whole (large) snapshot.
            seq = store.snapshot_seq(m)
            finished = seq is not None and seq >= STEPS
            if not finished and m in alive_now:
                pending.append(m)
        if not pending:
            break
        time.sleep(0.1)
    state, _ = sweep(store, dense, state)

    out = {
        "member": args.member,
        "alive": store.alive_members(args.timeout),
        "digest": observable_digest(dense, state),
    }
    with open(os.path.join(args.root, f"final-{args.member}.json"), "w") as f:
        json.dump(out, f)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
