"""Read-serving plane acceptance drill (serve/ tentpole gate).

Three workers gossip the topk_rmv grid over real TCP sockets while
client threads hammer the in-band ``{query}`` frame with big batched
reads — under chaos-style faults (seeded tcp.send drops + serve.query
delays from utils/faults.py). The gate holds the serving plane to its
whole contract at once:

* throughput — the fleet must serve >= 50k batched reads/sec on CPU,
  with the client-side per-frame p99 measured and reported;
* honesty — zero responses whose advertised ``staleness_bound_s`` is
  smaller than the snapshot's true age at send time (client and servers
  share one monotonic clock in-process, so the check is exact: the
  bound must cover ``t_send - t_swap`` of the claimed ``as_of_seq``);
* bit-identity — every served "value" equals the engine's own `value()`
  of the snapshot that was swapped in at the claimed seq, recorded at
  swap time;
* the write plane is undisturbed — after the query storm the fleet
  converges to the sequential single-process reference digest.

Writes the measurements to SERVE_r01.json (committed as the carrier for
regression comparison) and exits nonzero if any gate fails.

Run:  make serve-demo
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scripts.cover import install_child_cover  # noqa: E402

install_child_cover()  # no-op outside `make cover` runs

# Drill geometry: NK=4 keys so the query mix actually spreads.
R, NK, I, DCS, K, M, B, Br = 4, 4, 64, 4, 8, 2, 32, 8
STEPS = 10
STEP_SLEEP = 0.25          # the query storm runs inside this window
MIN_READS_PER_SEC = 50_000
QUERY_BATCH = 1024
CLIENT_THREADS = 4


def _build():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense

    return make_dense(n_ids=I, n_dcs=DCS, size=K, slots_per_id=M)


def gen_ops(step: int, owned):
    import jax.numpy as jnp
    import numpy as np

    from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps

    owned = set(owned)
    a_key = np.zeros((R, B), np.int32)
    a_id = np.zeros((R, B), np.int32)
    a_score = np.zeros((R, B), np.int32)
    a_dc = np.zeros((R, B), np.int32)
    a_ts = np.zeros((R, B), np.int32)
    r_key = np.zeros((R, Br), np.int32)
    r_id = np.full((R, Br), -1, np.int32)
    r_vc = np.zeros((R, Br, DCS), np.int32)
    for r in range(R):
        rng = np.random.default_rng(55_000 * (step + 1) + r)
        if r in owned:
            a_key[r] = rng.integers(0, NK, B)
            a_id[r] = rng.integers(0, I, B)
            a_score[r] = rng.integers(1, 500, B)
            a_dc[r] = r % DCS
            a_ts[r] = step * B + np.arange(B) + 1
            r_key[r] = rng.integers(0, NK, Br)
            r_id[r] = rng.integers(0, I, Br)
            r_vc[r, :, r % DCS] = rng.integers(1, max(2, step * B + 1), Br)
    return TopkRmvOps(
        add_key=jnp.asarray(a_key), add_id=jnp.asarray(a_id),
        add_score=jnp.asarray(a_score), add_dc=jnp.asarray(a_dc),
        add_ts=jnp.asarray(a_ts),
        rmv_key=jnp.asarray(r_key), rmv_id=jnp.asarray(r_id),
        rmv_vc=jnp.asarray(r_vc),
    )


def apply_step(dense, state, step: int, owned):
    state, _ = dense.apply_ops(
        state, gen_ops(step, owned), collect_dominated=False
    )
    return state


def ref_values(dense, state):
    """Per-key reference: the engine's own value() of the folded
    snapshot, JSON-shaped — what every served "value" must equal."""
    from antidote_ccrdt_tpu.harness.dense_replay import fold_rows

    per_key = dense.value(fold_rows(dense, state, range(R)))[0]
    return [[[int(i), int(s)] for i, s in row] for row in per_key]


def digest(dense, state):
    return [sorted(map(tuple, row)) for row in ref_values(dense, state)]


def sequential_reference(dense):
    state = dense.init(R, NK)
    for step in range(STEPS):
        state = apply_step(dense, state, step, range(R))
    return digest(dense, state)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--out",
        default=os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "SERVE_r01.json",
        ),
    )
    ap.add_argument("--min-reads", type=float, default=MIN_READS_PER_SEC)
    args = ap.parse_args()

    import random

    from antidote_ccrdt_tpu import serve
    from antidote_ccrdt_tpu.net.tcp import TcpTransport, query_peer
    from antidote_ccrdt_tpu.net.transport import GossipNode
    from antidote_ccrdt_tpu.obs.lag import LagTracker
    from antidote_ccrdt_tpu.parallel.elastic import sweep
    from antidote_ccrdt_tpu.utils import faults

    dense = _build()
    members = ["w0", "w1", "w2"]
    owned = {"w0": [0, 1], "w1": [2], "w2": [3]}
    transports = {m: TcpTransport(m) for m in members}
    try:
        for m in members:
            for n in members:
                if n != m:
                    transports[m].add_peer(n, transports[n].address)
        stores = {m: GossipNode(transports[m]) for m in members}
        lags = {m: LagTracker(m) for m in members}
        planes = {
            m: serve.ServePlane(
                dense, member=m, metrics=stores[m].metrics,
                lag_tracker=lags[m],
            )
            for m in members
        }
        for m in members:
            transports[m].install_serve(planes[m])
        states = {m: dense.init(R, NK) for m in members}

        # Start barrier.
        deadline = time.time() + 10.0
        while any(len(stores[m].members()) < len(members) for m in members):
            for m in members:
                stores[m].heartbeat()
            if time.time() > deadline:
                print("FAIL: start barrier timed out", file=sys.stderr)
                return 1
            time.sleep(0.05)

        # Warm every jit path BEFORE the measured storm — the apply/fold
        # compiles would otherwise stall the GIL mid-storm and poison the
        # read p99: a throwaway write step on scratch state, plus swap
        # seq -1 and one throwaway query per worker.
        scratch = apply_step(dense, dense.init(R, NK), 0, range(R))
        ref_values(dense, scratch)
        for m in members:
            planes[m].swap(states[m], -1)
            query_peer(
                transports[m].address,
                serve.request_bytes([{"op": "value", "key": 0}]),
                timeout=10.0,
            )

        # truth[(member, seq)] = (mono recorded AFTER the swap returned,
        # per-key reference values of the swapped state). Recording after
        # keeps the bound audit conservative: t_rec >= the snapshot's
        # swap_mono, so `bound >= t_send - t_rec` is implied by honesty.
        truth = {}

        # Chaos-style faults for the storm: seeded send drops (gossip
        # AND query replies) plus occasional serve-side delays.
        faults.install({
            "tcp.send": [{"action": "drop", "rate": 0.02}],
            "serve.query": [{"action": "delay", "rate": 0.01,
                             "delay_s": 0.002}],
        }, seed=5)

        stop = threading.Event()
        frames = [[] for _ in range(CLIENT_THREADS)]
        frame_errors = [0] * CLIENT_THREADS

        def client(ci: int) -> None:
            rng = random.Random(1000 + ci)
            while not stop.is_set():
                m = members[rng.randrange(len(members))]
                qs = []
                for _ in range(QUERY_BATCH):
                    key = rng.randrange(NK)
                    pick = rng.random()
                    if pick < 0.7:
                        qs.append({"op": "value", "key": key})
                    elif pick < 0.9:
                        qs.append({"op": "topk", "key": key, "k": 5})
                    else:
                        qs.append({"op": "range", "key": key,
                                   "lo": 100, "hi": 400})
                # Mostly a loose knob; rarely an impossible one, to
                # prove rejection is a real code path under load.
                ms = 1e-6 if rng.random() < 0.02 else 5.0
                t_send = time.monotonic()
                try:
                    _, raw = query_peer(
                        transports[m].address,
                        serve.request_bytes(qs, max_staleness_s=ms),
                        timeout=2.0,
                    )
                    doc = json.loads(raw.decode("utf-8"))
                except Exception:  # noqa: BLE001 — chaos shot this frame
                    frame_errors[ci] += 1
                    continue
                frames[ci].append(
                    (m, t_send, time.monotonic() - t_send, qs, doc)
                )

        threads = [
            threading.Thread(target=client, args=(i,), daemon=True)
            for i in range(CLIENT_THREADS)
        ]
        t_storm0 = time.monotonic()
        for t in threads:
            t.start()

        # The write plane runs its ordinary rounds under the storm.
        for step in range(STEPS):
            for m in members:
                stores[m].heartbeat()
                states[m] = apply_step(dense, states[m], step, owned[m])
                stores[m].publish("topk_rmv", states[m], step)
            time.sleep(0.05)
            for m in members:
                swept, _ = sweep(stores[m], dense, states[m])
                states[m] = swept
                for peer in members:
                    if peer == m:
                        continue
                    hi = stores[m].snapshot_seq(peer)
                    if hi is not None:
                        lags[m].observe_published(peer, hi)
                        lags[m].observe_applied(peer, hi)
                vals = ref_values(dense, states[m])
                planes[m].swap(states[m], step)
                truth[(m, step)] = (time.monotonic(), vals)
            time.sleep(STEP_SLEEP)

        stop.set()
        for t in threads:
            t.join(5.0)
        t_storm = time.monotonic() - t_storm0
        faults.uninstall()

        # Convergence tail, chaos off: the storm must not have disturbed
        # the write plane.
        ref = sequential_reference(dense)
        converged = False
        for i in range(80):
            if all(digest(dense, states[m]) == ref for m in members):
                converged = True
                break
            for m in members:
                stores[m].heartbeat()
                stores[m].publish("topk_rmv", states[m], STEPS + i)
            time.sleep(0.05)
            for m in members:
                swept, _ = sweep(stores[m], dense, states[m])
                states[m] = swept

        # -- audit ----------------------------------------------------------
        served = rejected = violations = mismatches = overloaded = 0
        lat = []
        eps = 1e-9
        for ci in range(CLIENT_THREADS):
            for m, t_send, dt, qs, doc in frames[ci]:
                if "error" in doc:
                    overloaded += 1
                    continue
                lat.append(dt)
                for q, r in zip(qs, doc["results"]):
                    if "error" in r:
                        if r.get("error") == "stale":
                            rejected += 1
                        continue
                    served += 1
                    t_rec, vals = truth.get(
                        (m, r["as_of_seq"]), (None, None)
                    )
                    if t_rec is None:
                        continue  # warmup snapshot (seq -1)
                    if r["staleness_bound_s"] + eps < t_send - t_rec:
                        violations += 1
                    if q["op"] == "value" and r["value"] != vals[q["key"]]:
                        mismatches += 1
        lat.sort()
        p99_ms = (lat[int(0.99 * (len(lat) - 1))] * 1e3) if lat else None
        p50_ms = (lat[len(lat) // 2] * 1e3) if lat else None
        reads_per_sec = served / max(t_storm, 1e-9)
        errors = sum(frame_errors)

        counters = {}
        for m in members:
            for k, v in stores[m].metrics.snapshot()["counters"].items():
                if k.startswith(("serve.", "net.queries")):
                    counters[k] = counters.get(k, 0) + int(v)

        checks = {
            "reads_per_sec_ge_min": reads_per_sec >= args.min_reads,
            "zero_bound_violations": violations == 0,
            "zero_identity_mismatches": mismatches == 0,
            "stale_rejects_observed": rejected >= 1
            and counters.get("serve.stale_rejects", 0) >= 1,
            "write_fleet_converged": converged,
            "serve_counters_lit": all(
                counters.get(k, 0) > 0
                for k in ("serve.swaps", "serve.requests", "serve.batches",
                          "serve.queries", "serve.cache_hits")
            ),
            "chaos_actually_fired": errors > 0
            or counters.get("serve.requests", 0) > served // QUERY_BATCH,
        }
        report = {
            "drill": "serve_demo",
            "geometry": {"R": R, "NK": NK, "I": I, "DCS": DCS, "K": K,
                         "M": M, "B": B, "steps": STEPS},
            "clients": CLIENT_THREADS,
            "query_batch": QUERY_BATCH,
            "storm_s": round(t_storm, 3),
            "reads_per_sec": round(reads_per_sec, 1),
            "min_reads_per_sec": args.min_reads,
            "read_p50_ms": None if p50_ms is None else round(p50_ms, 3),
            "read_p99_ms": None if p99_ms is None else round(p99_ms, 3),
            "served": served,
            "stale_rejected": rejected,
            "frame_errors": errors,
            "overloaded_frames": overloaded,
            "bound_violations": violations,
            "identity_mismatches": mismatches,
            "counters": dict(sorted(counters.items())),
            "checks": checks,
            "pass": all(checks.values()),
        }
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(json.dumps(report, indent=2, sort_keys=True))
        if not report["pass"]:
            failed = [k for k, ok in checks.items() if not ok]
            print(f"FAIL: {', '.join(failed)}", file=sys.stderr)
            return 1
        print(
            f"PASS: served {served} reads at {reads_per_sec:,.0f}/s "
            f"(p99 {p99_ms:.2f}ms), 0 bound violations, 0 identity "
            f"mismatches, fleet converged under chaos"
        )
        return 0
    finally:
        faults.uninstall()
        for t in transports.values():
            t.close()


if __name__ == "__main__":
    raise SystemExit(main())
