"""Fleet read tier acceptance drill (serve/router.py tentpole gate).

Four real worker processes (scripts/net_gossip_demo.py, CCRDT_SERVE=1)
gossip the topk_rmv drill over TCP under seeded chaos (tcp.send drops +
serve.query delays inside the workers, router.route drops in the
supervisor) while client threads route batched reads through a
`serve.FleetRouter` — HRW candidate order, per-peer circuit breakers,
bounded retries, forced hedging on one client, and per-client
`ClientSession` tokens (read-your-writes + monotonic-reads). One
serving worker is SIGKILLed mid-load. The gate holds the read tier to
its whole contract at once:

* **degrade, never hang** — every routed query completes or errors
  honestly (ok / overloaded / session_unsatisfiable); zero
  ``unavailable`` results, and no query exceeds a hard latency ceiling
  even across the kill;
* **honesty** — zero served results whose ``staleness_bound_s`` exceeds
  the requested ``max_staleness_s`` (the plane enforces; the client
  re-checks);
* **SLOs under chaos** — fleet reads/sec and client-observed p99 stay
  inside bounds, and the post-kill failover blip (the longest gap
  between consecutive successful responses around the SIGKILL) is
  bounded;
* **observability** — the `router.*` counters the dashboard renders are
  actually lit (queries, successes, failovers, hedges), and the seeded
  ``router.route`` fault point demonstrably fired;
* **certified sessions** — `obs.audit.certify_sessions` replays the
  supervisor's flight log and signs a clean certificate (zero
  violations, nonzero reads AND writes), while a deliberately
  token-violating arm (`session_mode="ignore"` routed at a stale stub
  peer) must FAIL certification with a minimal counterexample.

A session whose guarantees die with the killed origin is surfaced as
``session_unsatisfiable`` (honest refusal); the client then opens a
fresh session — counted, never hidden.

Writes the measurements to READTIER_r01.json (committed as the carrier
scripts/bench_gate.py regresses fleet QPS / read p99 / failover blip
against) and exits nonzero if any gate fails.

Run:  make read-tier-demo
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from scripts.cover import install_child_cover  # noqa: E402

install_child_cover()  # no-op outside `make cover` runs

DEMO = os.path.join(REPO, "scripts", "net_gossip_demo.py")

MEMBERS = ["w0", "w1", "w2", "w3"]
CLIENTS = 3           # client 2 runs the forced-hedge router
QUERY_BATCH = 8
MAX_STALENESS_S = 5.0
HARD_LATENCY_CEILING_S = 10.0   # "zero hangs" — nothing may exceed this

# Worker-side chaos (rides CCRDT_FAULTS into every worker).
WORKER_FAULTS = {
    "tcp.send": [{"action": "drop", "rate": 0.02}],
    "serve.query": [{"action": "delay", "rate": 0.01, "delay_s": 0.002}],
}
# Supervisor-side chaos: the router's own fault point.
ROUTER_FAULTS = {"router.route": [{"action": "drop", "rate": 0.03}]}


def _spawn_fleet(root: str, obs_dir: str, args) -> dict:
    from antidote_ccrdt_tpu.utils import faults as faults_mod

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["CCRDT_OBS_DIR"] = obs_dir
    env["CCRDT_SERVE"] = "1"
    env["CCRDT_FAULTS"] = faults_mod.plan_to_env(WORKER_FAULTS, seed=11)
    procs = {}
    for member in MEMBERS:
        cmd = [
            sys.executable, DEMO, "--root", root, "--member", member,
            "--n-members", str(len(MEMBERS)), "--type", "topk_rmv",
            "--delta", "--publish-every", "1",
            "--timeout", str(args.timeout),
            "--step-sleep", str(args.step_sleep),
        ]
        procs[member] = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, text=True,
        )
    return procs


def _wait_addrs(root: str, timeout: float) -> dict:
    """Wait for every worker's addr-<member> rendezvous file."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        addrs = {}
        for m in MEMBERS:
            try:
                with open(os.path.join(root, f"addr-{m}")) as f:
                    hostport = f.read().split()[0]
                host, port = hostport.rsplit(":", 1)
                addrs[m] = (host, int(port))
            except (OSError, ValueError, IndexError):
                break
        if len(addrs) == len(MEMBERS):
            return addrs
        time.sleep(0.05)
    raise RuntimeError("workers never published their addresses")


def _step_of(root: str, member: str) -> int:
    try:
        with open(os.path.join(root, f"obs-{member}.json")) as f:
            return int(json.load(f).get("step", -1))
    except (OSError, ValueError):
        return -1


def _wait_step(root: str, member: str, step: int, timeout: float) -> bool:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if _step_of(root, member) >= step:
            return True
        time.sleep(0.05)
    return False


def _drop_router_status(root: str, router) -> None:
    """obs-router.json: the dashboard's router column-group feed, same
    atomic-replace convention as the workers' obs-<member>.json."""
    doc = {"member": "router", "t": time.time(), "router": router.status()}
    path = os.path.join(root, "obs-router.json")
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
    except OSError:
        pass


def _violating_arm():
    """The audit layer's negative control, in-process: a router in
    ``session_mode="ignore"`` routed at a stale stub peer must produce a
    flight log that FAILS `certify_sessions` with a counterexample."""
    from antidote_ccrdt_tpu.obs import events as obs_events
    from antidote_ccrdt_tpu.obs.audit import certify_sessions
    from antidote_ccrdt_tpu.serve import ClientSession, FleetRouter
    from antidote_ccrdt_tpu.topo import rendezvous_order
    from antidote_ccrdt_tpu.utils.metrics import Metrics

    wms = {"stale": {"w0": 1, "w1": 1}, "fresh": {"w0": 9, "w1": 9}}

    def qfn(peer, payload, timeout_s, cancel):
        return (json.dumps({
            "member": peer, "n": 1, "watermarks": wms[peer],
            "results": [{"value": [], "as_of_seq": 1,
                         "staleness_bound_s": 0.0}],
        }) + "\n").encode()

    # A key whose HRW head is the stale peer, so ignore-mode routing
    # deterministically serves the violating answer.
    vkey = next(k for k in (f"v{i}" for i in range(64))
                if rendezvous_order(k, ["stale", "fresh"])[0] == "stale")
    n0 = len(obs_events.events())
    r = FleetRouter(["stale", "fresh"], qfn, metrics=Metrics(),
                    hedge=False, retries=0, poll_s=0.001,
                    session_mode="ignore")
    sess = ClientSession("demo-violating")
    sess.note_write("w0", 7)  # the floor the stale answer cannot cover
    out = r.query([{"op": "value", "key": 0}], key=vkey, session=sess)
    evs = obs_events.events()[n0:]
    cert = certify_sessions(
        logs={"violating-arm": evs},
        meta={"arm": "session_mode=ignore", "drill": "read_tier_demo"},
    )
    return cert, out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--out", default=os.path.join(REPO, "READTIER_r01.json"))
    ap.add_argument("--timeout", type=float, default=0.5,
                    help="worker SWIM timeout")
    ap.add_argument("--step-sleep", type=float, default=1.0)
    ap.add_argument("--kill-at-step", type=int, default=5)
    ap.add_argument("--min-reads", type=float, default=300.0)
    ap.add_argument("--max-p99-ms", type=float, default=1500.0)
    ap.add_argument("--max-blip-ms", type=float, default=5000.0)
    ap.add_argument("--worker-timeout", type=float, default=240.0)
    args = ap.parse_args()

    import random

    from antidote_ccrdt_tpu.net.tcp import query_peer
    from antidote_ccrdt_tpu.obs import events as obs_events
    from antidote_ccrdt_tpu.obs.audit import certify_sessions, verify_certificate
    from antidote_ccrdt_tpu.serve import (
        ClientSession, FleetRouter, request_bytes, tcp_query_fn,
    )
    from antidote_ccrdt_tpu.topo import rendezvous_order
    from antidote_ccrdt_tpu.utils import faults
    from antidote_ccrdt_tpu.utils.metrics import Metrics

    # Session events are request-plane (per-kind rings in obs/events.py)
    # so the query storm can no longer evict the early session.write
    # evidence the certifier replays — a default recorder suffices.
    obs_events.reset("router")

    failures = []
    victim = rendezvous_order("k0", MEMBERS)[0]
    dead: set = set()
    metrics = Metrics()

    with tempfile.TemporaryDirectory(prefix="read-tier-") as tmp:
        root = os.path.join(tmp, "fleet")
        obs_dir = os.path.join(tmp, "obs")
        os.makedirs(root)
        print(f"== read tier: {len(MEMBERS)}-worker TCP fleet, "
              f"SIGKILL {victim} at step {args.kill_at_step} ==")
        procs = _spawn_fleet(root, obs_dir, args)
        try:
            addrs = _wait_addrs(root, 60.0)
            for m in MEMBERS:
                if not _wait_step(root, m, 1, 120.0):
                    raise RuntimeError(f"{m} never reached step 1")

            # Warm every worker's serve path (first query pays the
            # fold/value JIT) so the measured storm sees steady state.
            # Concurrently — a serial warm-up would eat the workers'
            # whole 10-step run before the load even starts.
            warm_errs: list = []

            def _warm(m: str) -> None:
                try:
                    query_peer(addrs[m],
                               request_bytes([{"op": "value", "key": 0}]),
                               timeout=30.0)
                except Exception as e:  # noqa: BLE001 — gate below
                    warm_errs.append(f"{m}: {e}")

            warmers = [
                threading.Thread(target=_warm, args=(m,), daemon=True)
                for m in MEMBERS
            ]
            for t in warmers:
                t.start()
            for t in warmers:
                t.join(60.0)
            if warm_errs:
                raise RuntimeError(
                    f"serve warm-up failed: {'; '.join(warm_errs)}")

            def verdict(p: str) -> str:
                return "dead" if p in dead else "alive"

            faults.install(ROUTER_FAULTS, seed=7)
            r_main = FleetRouter(
                MEMBERS, tcp_query_fn(addrs), metrics=metrics,
                verdict_fn=verdict, hedge=False, timeout_s=0.6,
                retries=2, backoff_base_s=0.02, session_wait_s=0.5,
                session_poll_s=0.05, poll_s=0.002, seed=1,
                # Injected route drops concentrate on a session's single
                # covering peer; the default threshold of 3 would open
                # its breaker on chaos alone and starve the session.
                breaker_failures=6,
            )
            r_hedge = FleetRouter(
                MEMBERS, tcp_query_fn(addrs), metrics=metrics,
                verdict_fn=verdict, hedge=True, hedge_after_s=0.001,
                timeout_s=0.6, retries=2, backoff_base_s=0.02,
                session_wait_s=0.5, session_poll_s=0.05, poll_s=0.002,
                seed=2, breaker_failures=6,
            )

            n_load0 = len(obs_events.events())
            stop = threading.Event()
            stats = [
                {"lat": [], "ok_t": [], "reads": 0, "stale": 0,
                 "bound_violations": 0, "unavailable": 0, "shed": 0,
                 "unsatisfiable": 0, "resets": 0}
                for _ in range(CLIENTS)
            ]

            def client(ci: int) -> None:
                rng = random.Random(100 + ci)
                router = r_hedge if ci == CLIENTS - 1 else r_main
                sess = ClientSession(f"demo-c{ci}-0")
                st = stats[ci]
                while not stop.is_set():
                    qs = []
                    for _ in range(QUERY_BATCH):
                        pick = rng.random()
                        if pick < 0.7:
                            qs.append({"op": "value", "key": 0})
                        elif pick < 0.9:
                            qs.append({"op": "topk", "key": 0, "k": 5})
                        else:
                            qs.append({"op": "range", "key": 0,
                                       "lo": 100, "hi": 400})
                    # ~20% of queries ride session-less: they route over
                    # the full candidate list (tokens shrink it), so
                    # injected route drops exercise same-pass failover.
                    use_sess = rng.random() < 0.8
                    t0 = time.monotonic()
                    out = router.query(
                        qs, key=f"k{rng.randrange(32)}",
                        max_staleness_s=MAX_STALENESS_S,
                        session=sess if use_sess else None,
                    )
                    st["lat"].append(time.monotonic() - t0)
                    if "peer" in out and "error" not in out:
                        st["ok_t"].append(time.monotonic())
                        for res in out.get("results", []):
                            if res.get("error") == "stale":
                                st["stale"] += 1
                            elif "error" not in res:
                                st["reads"] += 1
                                if (res.get("staleness_bound_s", 0.0)
                                        > MAX_STALENESS_S + 1e-9):
                                    st["bound_violations"] += 1
                        # Read-your-writes food: claim one served seq of
                        # a live origin as "our write"; later reads must
                        # keep covering it.
                        wm = out.get("watermarks") or {}
                        m = out.get("member")
                        if (rng.random() < 0.05 and m and m != victim
                                and m in wm):
                            sess.note_write(m, int(wm[m]))
                    elif out.get("error") == "session_unsatisfiable":
                        # Honest refusal (e.g. the killed origin's
                        # stream can no longer be proven covered):
                        # surface it, open a fresh session.
                        st["unsatisfiable"] += 1
                        st["resets"] += 1
                        sess = ClientSession(
                            f"demo-c{ci}-{st['resets']}")
                    elif out.get("error") == "overloaded":
                        st["shed"] += 1
                        time.sleep(
                            out.get("retry_after_ms", 50) / 1e3)
                    else:
                        st["unavailable"] += 1

            threads = [
                threading.Thread(target=client, args=(i,), daemon=True)
                for i in range(CLIENTS)
            ]
            t_load0 = time.monotonic()
            for t in threads:
                t.start()

            # Stage the kill mid-load.
            t_kill = None
            if _wait_step(root, victim, args.kill_at_step, 60.0):
                procs[victim].send_signal(signal.SIGKILL)
                dead.add(victim)
                t_kill = time.monotonic()
                print(f"   SIGKILL -> {victim} (mid-load)")
            else:
                failures.append(
                    f"{victim} never reached step {args.kill_at_step}")
                procs[victim].kill()
                dead.add(victim)

            # Keep the storm running through failover until a survivor
            # nears its final step; stop the clients BEFORE the workers
            # enter teardown so nothing races a closing socket.
            survivor = next(m for m in MEMBERS if m != victim)
            deadline = time.time() + 90.0
            while time.time() < deadline:
                _drop_router_status(root, r_main)
                if _step_of(root, survivor) >= 9:
                    break
                time.sleep(0.25)
            if t_kill is not None:  # ensure a post-kill observation window
                time.sleep(max(0.0, 2.0 - (time.monotonic() - t_kill)))
            stop.set()
            for t in threads:
                t.join(HARD_LATENCY_CEILING_S + 5.0)
            t_load = time.monotonic() - t_load0
            hung_threads = [t for t in threads if t.is_alive()]
            _drop_router_status(root, r_main)
            n_load1 = len(obs_events.events())
            route_faults = [
                e for e in faults.trace() if e[0] == "router.route"]
            faults.uninstall()

            # -- reap the fleet --------------------------------------------
            outs = {}
            for m, p in procs.items():
                try:
                    out, _ = p.communicate(timeout=args.worker_timeout)
                    outs[m] = (p.returncode, out)
                except subprocess.TimeoutExpired:
                    p.kill()
                    out, _ = p.communicate()
                    outs[m] = (None, out)
            for m, (rc, out) in outs.items():
                if m != victim and rc != 0:
                    failures.append(f"worker {m} rc={rc}:\n{out}")
            digests = {}
            for path in glob.glob(os.path.join(root, "final-*.json")):
                try:
                    with open(path) as f:
                        doc = json.load(f)
                    digests[doc["member"]] = doc["digest"]
                except (OSError, ValueError, KeyError):
                    continue
            survivors = [m for m in MEMBERS if m != victim]
            converged = sorted(digests) == survivors and len(
                {json.dumps(d, sort_keys=True) for d in digests.values()}
            ) == 1
            if not converged:
                failures.append(
                    "survivors did not all converge to one digest "
                    f"(finals from {sorted(digests)})")

            # -- audit the storm -------------------------------------------
            lat = sorted(x for st in stats for x in st["lat"])
            ok_t = sorted(x for st in stats for x in st["ok_t"])
            reads = sum(st["reads"] for st in stats)
            agg = {
                k: sum(st[k] for st in stats)
                for k in ("stale", "bound_violations", "unavailable",
                          "shed", "unsatisfiable", "resets")
            }
            p99_ms = (lat[int(0.99 * (len(lat) - 1))] * 1e3) if lat else None
            max_ms = (lat[-1] * 1e3) if lat else None
            reads_per_sec = reads / max(t_load, 1e-9)

            # Failover blip: the longest gap between consecutive
            # successful responses in the window around the kill.
            blip_ms = 0.0
            if t_kill is not None and ok_t:
                window = [t_kill - 0.5] + [
                    t for t in ok_t
                    if t_kill - 0.5 <= t <= t_kill + 4.0
                ]
                gaps = [b - a for a, b in zip(window, window[1:])]
                blip_ms = max(gaps) * 1e3 if gaps else (
                    4.5e3)  # no successes in the window at all
            counters = {
                k: int(v)
                for k, v in metrics.snapshot()["counters"].items()
                if k.startswith("router.")
            }

            # -- certify the clean arm, then the violating arm -------------
            clean_evs = obs_events.events()[n_load0:n_load1]
            cert = certify_sessions(
                logs={"router": clean_evs},
                meta={"arm": "enforce", "drill": "read_tier_demo"},
            )
            bad_cert, bad_out = _violating_arm()
            cx = bad_cert.get("counterexample") or {}

            checks = {
                "zero_hung_queries": not hung_threads
                and (max_ms is None
                     or max_ms <= HARD_LATENCY_CEILING_S * 1e3),
                "zero_unavailable": agg["unavailable"] == 0,
                "zero_bound_violations": agg["bound_violations"] == 0,
                "reads_per_sec_ge_min": reads_per_sec >= args.min_reads,
                "read_p99_under_slo": p99_ms is not None
                and p99_ms <= args.max_p99_ms,
                "failover_blip_bounded": blip_ms <= args.max_blip_ms,
                "router_counters_lit": all(
                    counters.get(k, 0) > 0
                    for k in ("router.queries", "router.successes",
                              "router.attempts", "router.failovers",
                              "router.hedges")
                ),
                "router_route_faults_fired": len(route_faults) > 0,
                "survivors_converged": converged,
                "clean_sessions_certified": bool(cert.get("ok"))
                and verify_certificate(cert)
                and cert.get("n_reads", 0) > 0
                and cert.get("n_writes", 0) > 0
                and cert.get("n_violations", 0) == 0,
                "violating_arm_caught": bad_cert.get("ok") is False
                and verify_certificate(bad_cert)
                and bool(cx)
                and any(
                    v.get("session") == "demo-violating"
                    and v.get("origin") == "w0"
                    and v.get("have", 9) < v.get("want", -1)
                    for v in cx.values()
                ),
            }
            report = {
                "drill": "read_tier_demo",
                "fleet": MEMBERS,
                "killed": victim,
                "clients": CLIENTS,
                "query_batch": QUERY_BATCH,
                "load_s": round(t_load, 3),
                "fleet_reads_per_sec": round(reads_per_sec, 1),
                "read_p99_ms": None if p99_ms is None else round(p99_ms, 3),
                "read_max_ms": None if max_ms is None else round(max_ms, 3),
                "failover_blip_ms": round(blip_ms, 3),
                "reads": reads,
                "outcomes": agg,
                "route_faults_fired": len(route_faults),
                "counters": dict(sorted(counters.items())),
                "session_certificate": {
                    "ok": cert.get("ok"),
                    "n_sessions": cert.get("n_sessions"),
                    "n_reads": cert.get("n_reads"),
                    "n_writes": cert.get("n_writes"),
                    "n_violations": cert.get("n_violations"),
                },
                "violating_arm": {
                    "ok": bad_cert.get("ok"),
                    "served_by": bad_out.get("peer"),
                    "counterexample": cx,
                },
                "checks": checks,
                "pass": all(checks.values()) and not failures,
            }
            with open(args.out, "w") as fh:
                json.dump(report, fh, indent=2, sort_keys=True)
                fh.write("\n")
            print(json.dumps(report, indent=2, sort_keys=True))
            if failures:
                print("FAIL:")
                for f in failures:
                    print(f"  - {f}")
                return 1
            if not report["pass"]:
                bad = [k for k, ok in checks.items() if not ok]
                print(f"FAIL: {', '.join(bad)}", file=sys.stderr)
                return 1
            print(
                f"PASS: {reads} reads at {reads_per_sec:,.0f}/s "
                f"(p99 {p99_ms:.1f}ms, blip {blip_ms:.0f}ms) across "
                f"{victim}'s SIGKILL; sessions certified clean, "
                f"violating arm convicted"
            )
            return 0
        finally:
            faults.uninstall()
            for p in procs.values():
                if p.poll() is None:
                    p.kill()


if __name__ == "__main__":
    raise SystemExit(main())
