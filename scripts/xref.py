"""xref: import every module in the package and fail on any error.

The rebuild's stand-in for the reference's rebar3 xref undefined-call check
(rebar.config:8) and its stale-manifest quirk (antidote_ccrdt.app.src:5-7,
SURVEY.md §2 quirk #5): the module list here is discovered from the tree,
never hand-maintained, so it cannot rot.

Runs on CPU (no TPU needed) so it works as a pre-commit / CI gate.
"""

import importlib
import os
import pkgutil
import sys
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    import antidote_ccrdt_tpu

    failed = []
    mods = ["antidote_ccrdt_tpu"]
    for m in pkgutil.walk_packages(
        antidote_ccrdt_tpu.__path__, prefix="antidote_ccrdt_tpu."
    ):
        mods.append(m.name)
    for name in mods:
        try:
            importlib.import_module(name)
        except Exception:
            failed.append(name)
            traceback.print_exc()
    print(f"xref: {len(mods)} modules, {len(failed)} failed")
    if failed:
        print("FAILED:", ", ".join(failed))
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
