"""Overlap demo/gate: the round pipeline's perf claim, on a real fleet.

``make overlap-demo`` runs this. It launches the same 3-worker TCP
gossip fleet (`scripts/net_gossip_demo.py`: real localhost sockets,
chained-delta gossip, WAL armed, publish every step) TWICE — once with
the serial round loop forced (``CCRDT_OVERLAP=0``) and once with the
overlapped pipeline (``CCRDT_OVERLAP=1``, `parallel/overlap.py`) — with
the span plane on in both runs, and after the workers exit:

1. prints both runs' dispatch-gap attribution
   (`obs.spans.attribute`) side by side — serial mode shows
   wal_append/delta_encode/gossip on the round thread, overlap mode
   shows the same phases re-threaded onto the pipeline;
2. FAILS (exit 1) unless
   - every worker in BOTH runs converged to the same digest — overlap
     on/off must be bit-identical (the pipeline changes scheduling,
     never values), and that digest is the sequential reference;
   - the overlap run's fleet-p50 ``round.e2e`` is at least
     ``MIN_REDUCTION`` below the serial run's — the PR's headline: host
     phases off the round thread must actually shorten the round;
   - the overlap run billed its own counters (``overlap.host_tasks``,
     ``overlap.windows`` in the workers' final metrics) — the speedup
     must come from the pipeline, not from a silent serial fallback.

This is the pipeline's end-to-end proof on real sockets, the analogue
of what `make spans-demo` is for the span plane; the sim-chaos and
bit-identity unit legs live in tests/test_overlap.py.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
from typing import Dict, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from antidote_ccrdt_tpu.obs import spans as obs_spans  # noqa: E402

MEMBERS = ("w0", "w1", "w2")

# Required fleet-p50 round.e2e reduction, overlap vs serial. The serial
# round carries WAL append + delta encode + socket sends inline at
# publish-every-1, all of which the pipeline moves off-thread, so the
# healthy margin is far above this bar (the tiny in-process drill
# measures ~45%); 0.30 is the acceptance floor, with slack for CI noise.
MIN_REDUCTION = 0.30


def _run_fleet(label: str, overlap: bool) -> Tuple[dict, Dict[str, dict]]:
    """One 3-worker TCP run; returns (span attribution, final-*.json
    per member)."""
    here = os.path.dirname(os.path.abspath(__file__))
    demo = os.path.join(here, "net_gossip_demo.py")
    root = tempfile.mkdtemp(prefix=f"overlap-demo-{label}-")
    obs_dir = os.path.join(root, "obs")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["CCRDT_OBS_DIR"] = obs_dir
    env["CCRDT_SPANS"] = "1"
    env["CCRDT_OVERLAP"] = "1" if overlap else "0"
    procs = [
        subprocess.Popen(
            [sys.executable, demo, "--root", root, "--member", m,
             "--n-members", str(len(MEMBERS)), "--delta",
             "--wal-dir", os.path.join(root, "wal"),
             "--publish-every", "1", "--step-sleep", "0.2"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, text=True,
        )
        for m in MEMBERS
    ]
    outs: Dict[str, str] = {}
    for m, p in zip(MEMBERS, procs):
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs[m] = out
    bad = [m for m, p in zip(MEMBERS, procs) if p.returncode != 0]
    if bad:
        for m in bad:
            print(f"-- {label} worker {m} failed --\n{outs[m][-2000:]}")
        raise SystemExit(1)
    finals = {}
    for m in MEMBERS:
        with open(os.path.join(root, f"final-{m}.json")) as f:
            finals[m] = json.load(f)
    att = obs_spans.attribute(obs_spans.scan_dir(obs_dir))
    return att, finals


def main() -> int:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from elastic_demo import reference_digest

    runs = {}
    for label, overlap in (("serial", False), ("overlap", True)):
        print(f"== {label} run (CCRDT_OVERLAP={int(overlap)}, 3 TCP "
              "workers, publish every step) ==")
        att, finals = _run_fleet(label, overlap)
        print(obs_spans.format_report(att))
        print()
        runs[label] = (att, finals)

    # -- bit-identical convergence, overlap on/off ------------------------
    ref = json.loads(json.dumps(reference_digest("topk_rmv")))
    digests = {
        (label, m): runs[label][1][m]["digest"]
        for label in runs for m in MEMBERS
    }
    wrong = sorted(k for k, d in digests.items() if d != ref)
    if wrong:
        print(f"FAIL: digests diverged from the sequential reference: "
              f"{wrong}")
        return 1
    print(f"OK: all {len(digests)} worker digests bit-identical across "
          "overlap on/off (== sequential reference)")

    # -- the pipeline actually ran ----------------------------------------
    ovl_finals = runs["overlap"][1]
    for name in ("overlap.host_tasks", "overlap.windows"):
        total = sum(
            ovl_finals[m]["metrics"].get(name, 0) for m in MEMBERS
        )
        if not total:
            print(f"FAIL: {name} is zero across the overlap fleet — the "
                  "run silently fell back to the serial path")
            return 1

    # -- the perf claim ----------------------------------------------------
    e2e = {
        label: runs[label][0]["fleet"]["e2e_ms_p50"] for label in runs
    }
    reduction = 1.0 - e2e["overlap"] / e2e["serial"]
    verdict = (
        f"round.e2e fleet p50: serial {e2e['serial']:.2f}ms -> overlap "
        f"{e2e['overlap']:.2f}ms ({reduction:+.1%} vs the "
        f"-{MIN_REDUCTION:.0%} bar)"
    )
    if reduction < MIN_REDUCTION:
        print(f"FAIL: {verdict} — the pipeline no longer takes the host "
              "phases off the round thread")
        return 1
    print(f"OK: {verdict}")
    gaps = {
        label: runs[label][0]["fleet"]["gap_ms_p50"] for label in runs
    }
    print(f"dispatch gap fleet p50: serial {gaps['serial']:.2f}ms -> "
          f"overlap {gaps['overlap']:.2f}ms")
    return 0


if __name__ == "__main__":
    sys.exit(main())
