"""Out-of-core working-set acceptance drill (core/pager.py tentpole gate).

Three workers gossip the topk_rmv grid over a shared-filesystem
transport while every worker's device residency is capped at ONE TENTH
of the instance: each worker owns a `PartitionPager` whose HBM budget
is forced to `state_bytes // 10`, so most partitions live as CCPT
blobs in the host tier and only the zipfian working set stays
device-resident. Every op batch goes through the pager front door
(`ensure_resident` on the per-access partition list) BEFORE the ops
touch the device state — the invariant that keeps cold digests honest.

Gossip runs the full partition plane — `DeltaPublisher` anchors carry
the logical (device ⊔ cold) state and serve cold psnaps straight from
stored blobs, `PartialAntiEntropy` compares pager digest vectors, and
`sweep_deltas` folds inbound cold deltas host-side — so no path ever
blocks on a page-in it didn't need.

Gates (all must hold):

* convergence: after the steps + a bounded tail, all three workers'
  P+1 digest vectors agree AND are BIT-IDENTICAL to an all-resident
  sequential single-process reference (paging is a residency
  optimization, never a semantic one);
* pressure:   state_bytes >= 10x the HBM budget, and the pager
  actually paged (evictions, hydrations, cold folds all nonzero);
* speed:      steady-state hit rate >= 0.9 on every worker (zipfian
  skew keeps the hot set resident);
* kill switch: a second fleet run under CCRDT_PAGER=0 (all-resident
  legacy path, pagers never constructed) produces the bit-identical
  digest vector and observable;
* hygiene:    net.psnap_wasted == 0 (same invariant chaos_gate
  enforces everywhere else), and the conditional
  `round.pager_hydrate` span is lit in the paged arm.

Writes the measurements to WORKSET_r01.json (committed as the carrier
for regression comparison) and exits nonzero if any gate fails.

Run:  make working-set-demo
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scripts.cover import install_child_cover  # noqa: E402

install_child_cover()  # no-op outside `make cover` runs

# Drill geometry. I is large enough that one partition (~I/P ids) is a
# meaningful page, and the zipf exponent keeps ~90% of accesses inside
# a handful of partitions so a 10x-overcommitted budget can still hit.
R, NK, I, DCS, K, M, B, Br = 3, 1, 2048, 4, 8, 2, 96, 8
STEPS = 10
WARM_STEPS = 2  # hit/miss counters reset after these (steady-state rate)
ZIPF_A = 2.2

MIN_HIT = 0.9     # acceptance gate from ISSUE
MIN_RATIO = 10.0  # state must be >= 10x the device budget


def _build():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense

    return make_dense(n_ids=I, n_dcs=DCS, size=K, slots_per_id=M)


def _zipf_ids(rng, n):
    import numpy as np

    return ((rng.zipf(ZIPF_A, size=n) - 1) % I).astype(np.int32)


def gen_ops(step: int, owned, seed: int):
    """Deterministic [R, ...] batch, zipf-skewed ids. Row r's stream
    depends only on (seed, step, r), so the fleet (each worker applying
    its own row) and the sequential reference (all rows at once) see
    byte-identical op streams."""
    import jax.numpy as jnp
    import numpy as np

    from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps

    owned = set(owned)
    a_key = np.zeros((R, B), np.int32)
    a_id = np.zeros((R, B), np.int32)
    a_score = np.zeros((R, B), np.int32)
    a_dc = np.zeros((R, B), np.int32)
    a_ts = np.zeros((R, B), np.int32)
    r_key = np.zeros((R, Br), np.int32)
    # Add-only on purpose: a rmv whose vc lands AFTER an add has already
    # gossiped prunes that add at apply time in the sequential reference
    # but merge (by design) only joins vc tables and re-prunes at READ
    # time, so the raw bytes legitimately differ. Add-only keeps the
    # drill a pure max-lattice where the bitwise gate is meaningful;
    # rmv races are partition_demo/test_elastic territory.
    r_id = np.full((R, Br), -1, np.int32)
    r_vc = np.zeros((R, Br, DCS), np.int32)
    for r in range(R):
        rng = np.random.default_rng(seed * 1_000_003 + 9_100 * (step + 1) + r)
        ids = _zipf_ids(rng, B)
        scores = rng.integers(1, 500, B)
        if r in owned:
            a_id[r], a_score[r] = ids, scores
            a_dc[r] = r % DCS
            a_ts[r] = step * B + np.arange(B) + 1
    return TopkRmvOps(
        add_key=jnp.asarray(a_key), add_id=jnp.asarray(a_id),
        add_score=jnp.asarray(a_score), add_dc=jnp.asarray(a_dc),
        add_ts=jnp.asarray(a_ts),
        rmv_key=jnp.asarray(r_key), rmv_id=jnp.asarray(r_id),
        rmv_vc=jnp.asarray(r_vc),
    )


def access_ids(ops, row: int):
    """The per-ACCESS id stream for one row's batch (adds then rmvs,
    every occurrence kept): this is what feeds `ensure_resident`, so
    hit/miss accounting bills each access, not each unique partition."""
    import numpy as np

    adds = np.asarray(ops.add_id)[row]
    rmvs = np.asarray(ops.rmv_id)[row]
    return np.concatenate([adds, rmvs[rmvs >= 0]])


def observable(dense, state):
    from antidote_ccrdt_tpu.harness.dense_replay import fold_rows

    obs = dense.value(fold_rows(dense, state, range(R)))[0][0]
    return sorted((int(i), int(s)) for (i, s) in obs)


def run_drill(seed: int = 7, *, P: int = 32, spans: bool = False,
              status_dir: str = None, keep_state: bool = False) -> dict:
    """One fleet run: 3 workers, zipfian ops, pager per worker when the
    CCRDT_PAGER kill switch allows it (all-resident legacy otherwise),
    converge, and compare against the all-resident sequential
    reference. Returns the full measurement dict; `main` and
    chaos_gate's working-set leg both gate on it."""
    import contextlib

    import numpy as np

    from antidote_ccrdt_tpu.core import pager as pg
    from antidote_ccrdt_tpu.core import partition as pt
    from antidote_ccrdt_tpu.net.transport import FsTransport, GossipNode
    from antidote_ccrdt_tpu.obs import spans as obs_spans
    from antidote_ccrdt_tpu.parallel.elastic import (
        DeltaPublisher, PartialAntiEntropy, sweep_deltas,
    )

    dense = _build()
    use_pager = pg.enabled()
    members = ["w0", "w1", "w2"]
    row_of = {"w0": 0, "w1": 1, "w2": 2}

    out: dict = {"seed": seed, "pager": use_pager, "partitions": P}
    with tempfile.TemporaryDirectory(prefix="workset-") as root:
        transports = {m: FsTransport(root, m) for m in members}
        stores = {m: GossipNode(transports[m]) for m in members}
        states = {m: dense.init(R, NK) for m in members}
        cursors: dict = {m: {} for m in members}

        pagers: dict = {m: None for m in members}
        if use_pager:
            for m in members:
                probe = pg.PartitionPager(
                    dense, states[m], P=P, name="workset",
                    metrics=stores[m].metrics,
                )
                total = probe.meta_bytes + sum(probe.part_bytes.values())
                budget = max(1, total // 10)  # forced 10x overcommit
                pagers[m] = pg.PartitionPager(
                    dense, states[m], P=P, name="workset",
                    hbm_budget_bytes=budget, metrics=stores[m].metrics,
                )
                out["state_bytes"] = total
                out["hbm_budget_bytes"] = budget
                out["state_over_budget_x"] = round(total / budget, 3)

        pubs = {
            m: DeltaPublisher(
                stores[m], dense, name="topk_rmv", full_every=2, keep=8,
                partitions=P, pager=pagers[m],
            )
            for m in members
        }
        partials = {
            m: PartialAntiEntropy(
                stores[m], partitions=P, max_tries=12, pager=pagers[m]
            )
            for m in members
        }

        def digest_vec(m):
            if pagers[m] is not None:
                return pagers[m].digest_vector(states[m])
            return pt.state_digests(states[m], P)

        def drop_status(m, step):
            if status_dir is None or pagers[m] is None:
                return
            path = os.path.join(status_dir, f"obs-{m}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(
                    {"member": m, "step": step,
                     "pager": pagers[m].status_fields()},
                    fh,
                )
            os.replace(tmp, path)

        def round_of(step):
            for m in members:
                stores[m].heartbeat()
                pubs[m].publish(states[m])
            time.sleep(0.05)
            for m in members:
                states[m], _ = sweep_deltas(
                    stores[m], dense, states[m], cursors[m],
                    partial=partials[m], pager=pagers[m],
                )
                drop_status(m, step)

        span_cm = (
            obs_spans.installed("workset", metrics=stores["w0"].metrics)
            if spans else contextlib.nullcontext()
        )
        span_names = set()
        try:
            with span_cm:
                # Start barrier: fs heartbeats are heard-from evidence.
                deadline = time.time() + 10.0
                while any(
                    len(stores[m].members()) < len(members) for m in members
                ):
                    for m in members:
                        stores[m].heartbeat()
                    if time.time() > deadline:
                        out["converged"] = False
                        out["error"] = "start barrier timed out"
                        return out
                    time.sleep(0.05)

                for step in range(STEPS):
                    for m in members:
                        ops = gen_ops(step, {row_of[m]}, seed)
                        if pagers[m] is not None:
                            # Front door BEFORE device writes: hydrate
                            # the batch's partitions (per-access billing)
                            # so ops never scatter into a cold hole.
                            acc = access_ids(ops, row_of[m])
                            states[m] = pagers[m].ensure_resident(
                                states[m], pt.part_of(acc, P)
                            )
                        states[m], _ = dense.apply_ops(
                            states[m], ops, collect_dominated=False
                        )
                    if step == WARM_STEPS and use_pager:
                        for m in members:
                            pagers[m].hits = pagers[m].misses = 0
                    round_of(step)

                # Convergence tail: republish/sweep until the digest
                # vectors agree fleet-wide (bounded).
                agree = False
                for _ in range(80):
                    vecs = [digest_vec(m) for m in members]
                    if all(np.array_equal(vecs[0], v) for v in vecs[1:]):
                        agree = True
                        break
                    round_of(STEPS)
                out["converged"] = agree

                if spans:
                    span_names = {
                        r.get("name")
                        for r in obs_spans.drain()
                        if r.get("k") == "span"
                    }

            # All-resident sequential reference: same op streams, one
            # process, no pager — the semantic ground truth.
            ref = dense.init(R, NK)
            for step in range(STEPS):
                ref, _ = dense.apply_ops(
                    ref, gen_ops(step, range(R), seed),
                    collect_dominated=False,
                )
            ref_vec = pt.state_digests(ref, P)
            ref_obs = observable(dense, ref)

            vec = digest_vec("w0")
            finals = {
                m: observable(
                    dense,
                    pagers[m].full_state(states[m])
                    if pagers[m] is not None else states[m],
                )
                for m in members
            }
            if keep_state:  # debug/forensics only: the logical w0 state
                out["_state"] = (
                    pagers["w0"].full_state(states["w0"])
                    if pagers["w0"] is not None else states["w0"]
                )
            out["digest_vector"] = [int(x) for x in vec]
            out["observable"] = finals["w0"]
            out["matches_reference"] = bool(
                np.array_equal(vec, ref_vec)
            ) and all(finals[m] == ref_obs for m in members)

            counters: dict = {}
            for m in members:
                for k, v in stores[m].metrics.counters.items():
                    if k.startswith(("pager.", "net.psnap", "net.partition")):
                        counters[k] = counters.get(k, 0) + int(v)
            out["counters"] = dict(sorted(counters.items()))
            if use_pager:
                out["hit_rates"] = {
                    m: round(pagers[m].hit_rate(), 4) for m in members
                }
                out["min_hit_rate"] = min(out["hit_rates"].values())
            if spans:
                out["span_names"] = sorted(
                    n for n in span_names if n is not None
                )
            return out
        finally:
            for t in transports.values():
                t.close()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--partitions", type=int, default=32)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument(
        "--out",
        default=os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "WORKSET_r01.json",
        ),
    )
    args = ap.parse_args()

    from antidote_ccrdt_tpu.core import pager as pg

    # Paged arm (spans armed: the conditional hydrate span must be lit).
    os.environ.pop(pg.ENV_FLAG, None)
    paged = run_drill(args.seed, P=args.partitions, spans=True)
    # Kill-switch arm: CCRDT_PAGER=0 means pagers are never constructed
    # and the drill runs the bit-identical all-resident legacy path.
    os.environ[pg.ENV_FLAG] = "0"
    try:
        legacy = run_drill(args.seed, P=args.partitions)
    finally:
        os.environ.pop(pg.ENV_FLAG, None)

    c = paged.get("counters", {})
    checks = {
        "fleet_converged": bool(paged.get("converged")),
        "matches_sequential_reference": bool(paged.get("matches_reference")),
        "kill_switch_bit_identical": bool(legacy.get("converged"))
        and legacy.get("digest_vector") == paged.get("digest_vector")
        and legacy.get("observable") == paged.get("observable"),
        "state_ge_10x_budget": paged.get("state_over_budget_x", 0) >= MIN_RATIO,
        "hit_rate_ge_min": paged.get("min_hit_rate", 0.0) >= MIN_HIT,
        "pager_paged": all(
            c.get(k, 0) > 0
            for k in ("pager.evictions", "pager.hydrations", "pager.cold_folds")
        ),
        "cold_psnaps_served_from_blobs": c.get("pager.blob_serves", 0) > 0,
        "no_wasted_psnaps": c.get("net.psnap_wasted", 0) == 0,
        "hydrate_span_lit": "round.pager_hydrate"
        in paged.get("span_names", []),
    }
    report = {
        "drill": "working_set_demo",
        "geometry": {
            "R": R, "NK": NK, "I": I, "DCS": DCS, "K": K, "M": M,
            "B": B, "Br": Br, "steps": STEPS, "zipf_a": ZIPF_A,
        },
        "partitions": args.partitions,
        "state_bytes": paged.get("state_bytes"),
        "hbm_budget_bytes": paged.get("hbm_budget_bytes"),
        "state_over_budget_x": paged.get("state_over_budget_x"),
        "hit_rates": paged.get("hit_rates"),
        "min_hit_rate": paged.get("min_hit_rate"),
        "counters": c,
        "span_names": paged.get("span_names"),
        "checks": checks,
        "pass": all(checks.values()),
    }
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print(json.dumps(report, indent=2, sort_keys=True))
    if not report["pass"]:
        failed = [k for k, ok in checks.items() if not ok]
        print(f"FAIL: {', '.join(failed)}", file=sys.stderr)
        return 1
    print(
        f"PASS: {paged['state_over_budget_x']}x over-budget instance "
        f"converged bit-identically at hit rate {paged['min_hit_rate']:.3f} "
        f"({c.get('pager.hydrations', 0)} hydrations, "
        f"{c.get('pager.evictions', 0)} evictions)"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
