"""Certified-convergence demo/gate (obs/audit.py — the PR-10 tentpole).

Three legs, each a different verdict surface of the audit plane:

* **laws** — the lattice-law property checker over every registered op
  type (merge commutativity/associativity/idempotence + the
  delta-composition law, batched on-device), plus the negative
  selftest: the committed non-commutative fixture
  (`ops.laws.BrokenMergeDense`) MUST be flagged — a checker that waves
  a broken merge through is itself broken.

* **healthy** — a seeded-chaos 3-worker REAL-PROCESS TCP fleet
  (scripts/net_gossip_demo.py: delta gossip, partition plane +
  divergence watchdog armed, deterministic `tcp.send` drops from
  utils/faults.py). After convergence the supervisor replay-certifies
  the run: flight-log spill (causal delivery + op-count
  reconciliation) + per-worker digests vs the sequential reference →
  a signed convergence certificate, written to AUDIT_r01.json. The
  healthy arm must certify OK with ZERO wedge alarms (no false
  alarms under injected-but-healing faults).

* **divergent** — the fault-injected arm, in-process and fully
  deterministic: a twin state gets one surgical extra op confined to
  one known partition (`core.partition.part_of`). The watchdog must
  flag the divergence on the FIRST digest exchange (within one
  round), escalate to a wedged alarm once the clock passes the bound
  with no repair, and close the episode with a time-to-agreement
  sample when the twin heals. Certification of the divergent digests
  must FAIL with a counterexample naming the diverging partition.

Run directly (`make audit-demo`) or via scripts/chaos_gate.py, which
re-runs all three legs and gates on their verdicts.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import zlib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from scripts.cover import install_child_cover  # noqa: E402

install_child_cover()  # no-op outside `make cover` runs

N_WORKERS = 3
PARTITIONS = 8
SEED = 7
WORKER_TIMEOUT_S = 240


def _crc(digest) -> int:
    """Canonical scalar digest over an arbitrary JSON-able observable
    digest (the topk_rmv drill digest is a nested list — the certificate
    layer compares exact ints, so hash the canonical JSON)."""
    return zlib.crc32(
        json.dumps(digest, sort_keys=True).encode("utf-8")
    ) & 0xFFFFFFFF


def run_laws(pairs: int = 32, seed: int = 0) -> dict:
    """Leg 1: every registered type passes its laws AND the committed
    broken fixture is caught."""
    from antidote_ccrdt_tpu.obs import audit as obs_audit
    from antidote_ccrdt_tpu.ops.laws import broken_merge_fixture

    report = obs_audit.LawChecker(seed=seed, pairs=pairs).run()
    broken = obs_audit.LawChecker(
        types=["broken_merge_fixture"], seed=seed, pairs=pairs,
        extra_fixtures={"broken_merge_fixture": broken_merge_fixture},
    ).run()
    laws = broken["types"]["broken_merge_fixture"]["laws"]
    selftest_caught = (
        not laws["commutativity"]["ok"]
        and not laws["associativity"]["ok"]
        # 2a-b is idempotent (2a-a == a): the checker must report the
        # laws INDEPENDENTLY, not fail everything wholesale.
        and laws["idempotence"]["ok"]
    )
    return {
        "ok": bool(report["ok"]) and selftest_caught,
        "registry_ok": bool(report["ok"]),
        "selftest_caught": selftest_caught,
        "n_types": report["n_types"],
        "n_law_checks": report["n_law_checks"],
        "n_law_failures": report["n_law_failures"],
        "unaudited": report["unaudited"],
    }


def run_healthy(root: str | None = None, keep: bool = False) -> dict:
    """Leg 2: real-process seeded-chaos fleet -> signed certificate."""
    from antidote_ccrdt_tpu.obs import audit as obs_audit
    from antidote_ccrdt_tpu.utils.faults import plan_to_env
    from scripts.elastic_demo import reference_digest

    own_root = root is None
    root = root or tempfile.mkdtemp(prefix="ccrdt-audit-")
    obs_dir = os.path.join(root, "obs")
    os.makedirs(obs_dir, exist_ok=True)

    procs = []
    for i in range(N_WORKERS):
        env = dict(os.environ)
        # Workers are CPU-only subprocesses; a TPU-targeting XLA_FLAGS
        # inherited from the supervisor would abort them at import.
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["CCRDT_OBS_DIR"] = obs_dir
        # Seeded chaos, healing by construction: a handful of dropped
        # TCP frames at fixed per-worker hit ordinals (past the hello
        # exchange). Lost deltas force real digest-vector resyncs —
        # the watchdog rides those — and the retry/final-convergence
        # machinery repairs everything, so certification must still
        # pass with zero wedge alarms.
        env["CCRDT_FAULTS"] = plan_to_env(
            {"tcp.send": [
                {"action": "drop", "at": [9 + 4 * i, 21 + 3 * i],
                 "max_fires": 2},
            ]},
            seed=SEED + i,
        )
        cmd = [
            sys.executable,
            os.path.join(REPO, "scripts", "net_gossip_demo.py"),
            "--root", root, "--member", f"w{i}",
            "--n-members", str(N_WORKERS),
            "--type", "topk_rmv", "--delta", "--no-overlap",
            "--partitions", str(PARTITIONS),
        ]
        procs.append(subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        ))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=WORKER_TIMEOUT_S)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise RuntimeError("audit_demo: fleet wedged (worker timeout)")
    for i, p in enumerate(procs):
        if p.returncode != 0:
            raise RuntimeError(
                f"audit_demo: worker w{i} rc={p.returncode}\n"
                + outs[i][-4000:]
            )

    finals = {}
    for i in range(N_WORKERS):
        with open(os.path.join(root, f"final-w{i}.json")) as f:
            finals[f"w{i}"] = json.load(f)
    digests = {m: _crc(doc["digest"]) for m, doc in finals.items()}
    reference = _crc(reference_digest("topk_rmv"))

    cert = obs_audit.certify(
        obs_dir=obs_dir, digests=digests, reference=reference,
        meta={
            "arm": "healthy", "workers": sorted(finals),
            "faults": "tcp.send deterministic drops (seeded chaos)",
            "partitions": PARTITIONS,
        },
    )
    verified = obs_audit.verify_certificate(cert)

    counters: dict = {}
    for doc in finals.values():
        for k, v in doc["metrics"].items():
            if k.startswith(("audit.", "net.partition", "net.psnap",
                             "net.dig_")):
                counters[k] = counters.get(k, 0) + v
    result = {
        "ok": bool(cert["ok"]) and verified
        and counters.get("audit.wedge_alarms", 0) == 0,
        "cert": cert,
        "verified": verified,
        "digests": digests,
        "reference": reference,
        "wedge_alarms": int(counters.get("audit.wedge_alarms", 0)),
        "counters": counters,
        "root": root,
    }
    if own_root and not keep:
        shutil.rmtree(root, ignore_errors=True)
    return result


def run_divergent() -> dict:
    """Leg 3: deterministic divergence — watchdog detection within one
    digest exchange, wedge alarm, heal, and a FAILED certificate whose
    counterexample names the partition."""
    import numpy as np

    from antidote_ccrdt_tpu.core import partition as pt
    from antidote_ccrdt_tpu.obs import audit as obs_audit
    from antidote_ccrdt_tpu.utils.metrics import Metrics
    from scripts.elastic_demo import B, Br, DCS, R, STEPS, DRILLS

    drill = DRILLS["topk_rmv"]
    dense = drill.make_engine()
    good = drill.init(dense)
    for step in range(3):
        good = drill.apply(dense, good, step, range(R))

    # The twin takes ONE extra add on a single known id — so exactly
    # that id's partition (plus the meta partition: the add bumps
    # whole-instance leaves) may diverge.
    id_star = 17
    p_star = int(pt.part_of([id_star], PARTITIONS)[0])
    twin, _ = dense.apply_ops(
        good, _single_add_ops(id_star, ts=STEPS * B + 1000, np=np,
                              B=B, Br=Br, DCS=DCS, R=R),
        collect_dominated=False,
    )

    va = [int(x) for x in pt.state_digests(good, PARTITIONS)]
    vb = [int(x) for x in pt.state_digests(twin, PARTITIONS)]
    div = pt.divergent_parts(va, vb)

    clock = {"t": 0.0}
    metrics = Metrics()
    wd = obs_audit.DivergenceWatchdog(
        "probe", wedge_after_s=2.0, mono=lambda: clock["t"],
        metrics=metrics,
    )
    s_agree = wd.observe_peer("twin", va, va, seq=1)
    s_first = wd.observe_peer("twin", va, vb, seq=2)   # one exchange
    clock["t"] += 3.0                                   # past the bound
    s_wedged = wd.observe_peer("twin", va, vb, seq=3)
    clock["t"] += 0.5
    s_healed = wd.observe_peer("twin", vb, vb, seq=4)   # twin adopted

    cert = obs_audit.certify(
        digests={"w_good": va, "w_twin": vb}, reference=va,
        meta={"arm": "divergent", "id_star": id_star, "p_star": p_star},
    )
    wd.note_certificate(cert)
    counters = metrics.snapshot()["counters"]
    counterexample_parts = cert.get("counterexample", {}).get(
        "divergent_parts", []
    )
    ok = (
        bool(div) and p_star in div
        and s_agree == wd.STATE_OK
        and s_first == wd.STATE_DIVERGED    # flagged within one round
        and s_wedged == wd.STATE_WEDGED
        and s_healed == wd.STATE_OK
        and not cert["ok"]
        and obs_audit.verify_certificate(cert)
        and p_star in counterexample_parts
        and counterexample_parts == div
    )
    return {
        "ok": ok,
        "p_star": p_star,
        "divergent_parts": div,
        "counterexample_parts": counterexample_parts,
        "states": {
            "agree": s_agree, "first": s_first,
            "wedged": s_wedged, "healed": s_healed,
        },
        "tta_p50_s": wd.tta_p50_s(),
        "counters": {k: v for k, v in counters.items()
                     if k.startswith("audit.")},
        "cert": cert,
    }


def run_durability(timeout: float = 240.0) -> dict:
    """PR 11 leg: the durability-watermark axis of the certifier, both
    directions.

    * **fleet arm** — the real-process SIGKILL crash drill re-run under
      ``CCRDT_WAL_DURABILITY=async`` (gossip may publish ahead of the
      fsync): the restarted victim re-derives whatever the crash dropped
      past the watermark, and `certify()`'s ``durability_watermark``
      check must ACTIVATE and pass — relaxed-durability speed with zero
      unaudited loss.

    * **fabricated arm** — a synthesized crashed-incarnation flight log
      that appended through seq 9 but acked durability only through 5,
      with no successor incarnation anywhere: certification must FAIL
      with a counterexample naming exactly the uncovered range [6, 9].
      A certifier that waves provable pre-fsync loss through is itself
      broken (the negative selftest, mirroring the laws leg's
      broken-merge fixture)."""
    from antidote_ccrdt_tpu.obs import audit as obs_audit
    from scripts.crash_recovery_demo import run_scenario

    fleet = run_scenario("wal", "topk_rmv", timeout, durability="async")

    evs = [{"kind": "proc.start", "member": "wX", "t": 1.0, "pid": 1, "seq": 0}]
    evs += [
        {"kind": "wal.append", "member": "wX", "t": 1.0 + 0.01 * i,
         "wseq": i, "bytes": 64, "seq": 1 + i}
        for i in range(10)
    ]
    evs.append({"kind": "wal.durable", "member": "wX", "t": 1.06,
                "through": 5, "group": 6, "seq": 11})
    # No proc.exit (crashed), no successor log (nothing re-derived
    # seqs 6..9): this loss is real and must be flagged.
    cert = obs_audit.certify(
        logs={"flight-wX-1.jsonl": evs},
        meta={"arm": "fabricated-pre-fsync-loss"},
    )
    exposures = cert.get("counterexample", {}).get("durability_exposures", [])
    fabricated_flagged = (
        not cert["ok"]
        and cert["checks"].get("durability_watermark") is False
        and any(
            x.get("member") == "wX" and x.get("uncovered") == [6, 9]
            for x in exposures
        )
    )
    fleet_certified = (
        bool(fleet["ok"])
        and fleet["certifier_checks"].get("durability_watermark") is True
    )
    return {
        "ok": fleet_certified and fabricated_flagged,
        "fleet": {
            k: fleet.get(k)
            for k in (
                "ok", "problems", "durability", "kill_seq",
                "victim_flight_durable", "victim_flight_last_step",
                "victim_recover_last_step", "certifier_checks",
            )
        },
        "fabricated_flagged": fabricated_flagged,
        "fabricated_exposures": exposures,
        "fabricated_cert_ok": bool(cert["ok"]),
    }


def _single_add_ops(id_star, ts, np, B, Br, DCS, R):
    """A TopkRmvOps batch that is all padding except one add of
    `id_star` on replica 0 (padding convention: add_ts=0 / rmv_id=-1,
    same as elastic_demo gen_ops)."""
    import jax.numpy as jnp

    from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps

    a_key = np.zeros((R, B), np.int32)
    a_id = np.zeros((R, B), np.int32)
    a_score = np.zeros((R, B), np.int32)
    a_dc = np.zeros((R, B), np.int32)
    a_ts = np.zeros((R, B), np.int32)
    a_id[0, 0], a_score[0, 0], a_ts[0, 0] = id_star, 499, ts
    r_key = np.zeros((R, Br), np.int32)
    r_id = np.full((R, Br), -1, np.int32)
    r_vc = np.zeros((R, Br, DCS), np.int32)
    return TopkRmvOps(
        add_key=jnp.asarray(a_key), add_id=jnp.asarray(a_id),
        add_score=jnp.asarray(a_score), add_dc=jnp.asarray(a_dc),
        add_ts=jnp.asarray(a_ts),
        rmv_key=jnp.asarray(r_key), rmv_id=jnp.asarray(r_id),
        rmv_vc=jnp.asarray(r_vc),
    )


def run_all(pairs: int = 32, root: str | None = None) -> dict:
    return {
        "laws": run_laws(pairs=pairs),
        "healthy": run_healthy(root=root),
        "divergent": run_divergent(),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", help="fleet scratch dir (default: tmp)")
    ap.add_argument("--pairs", type=int, default=32,
                    help="law-check instance pairs per dispatch")
    ap.add_argument("--out", default="AUDIT_r01.json",
                    help="where to write the healthy-arm certificate")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")

    res = run_all(pairs=args.pairs, root=args.root)
    laws, healthy, divergent = (
        res["laws"], res["healthy"], res["divergent"]
    )
    with open(args.out, "w") as f:
        json.dump(healthy["cert"], f, indent=2, sort_keys=True)
        f.write("\n")

    if args.json:
        print(json.dumps({
            "laws": laws,
            "healthy": {k: v for k, v in healthy.items() if k != "cert"},
            "divergent": {
                k: v for k, v in divergent.items() if k != "cert"
            },
        }, sort_keys=True, default=str))
    else:
        print("== certified convergence (obs/audit.py) ==")
        print(
            f"laws      : {'ok' if laws['ok'] else 'FAIL'} "
            f"({laws['n_law_checks']} checks / {laws['n_types']} types, "
            f"{laws['n_law_failures']} failures, broken fixture "
            f"{'caught' if laws['selftest_caught'] else 'MISSED'})"
        )
        cert = healthy["cert"]
        print(
            f"healthy   : cert {'OK' if cert['ok'] else 'FAILED'} "
            f"(signature {'valid' if healthy['verified'] else 'INVALID'}, "
            f"{cert['n_flight_logs']} flight logs, "
            f"wedge alarms {healthy['wedge_alarms']}) -> {args.out}"
        )
        print(
            f"divergent : watchdog "
            f"{divergent['states']} parts={divergent['divergent_parts']} "
            f"p*={divergent['p_star']} cert FAILED as required, "
            f"counterexample names {divergent['counterexample_parts']}"
        )
    ok = laws["ok"] and healthy["ok"] and divergent["ok"]
    print("audit-demo:", "OK" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
