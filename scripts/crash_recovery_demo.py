"""Real-process crash/recovery drill: SIGKILL a WAL-backed worker mid-run.

Supervises a 3-member elastic gossip fleet (scripts/elastic_demo.py
workers, shared-directory transport) with the crash-consistent WAL
enabled (--wal-dir). Once the victim has published a couple of steps it
is SIGKILLed — no cleanup, torn WAL tail possible — then restarted, and
every member's final digest must equal the sequential single-process
reference (the no-fault ground truth pinned by tests/test_elastic.py).

Two modes, both required by the robustness PR's acceptance bar:

* ``wal``   — the victim restarts with its WAL intact: it must recover
  state = checkpoint ⊔ WAL suffix (``wal.recovered_records > 0`` in its
  final metrics) and resume AFTER its last durable step instead of
  regenerating history.
* ``adopt`` — same crash, but the victim's WAL directory is deleted
  before the restart and the restart is delayed past failure detection:
  recovery must fall back to the deterministic-regeneration/adoption
  path (``wal.recovered_records`` absent) and still converge — the PR 1
  invariant stays load-bearing when the durable path is gone.

Both modes now run under every WAL durability discipline (PR 11:
``--durability sync|group|async|all``, exported to the workers as
``CCRDT_WAL_DURABILITY``). Per-mode assertions, all post-mortem from
the flight logs:

* sync/group — durable-before-visible: the restarted victim's
  ``wal.recover`` must reach at least the seq the victim had PUBLISHED
  at kill time (group commit flushes at the boundary, before publish).
* async — recovery == watermark truncation: recover.last_step must be
  bracketed by the killed incarnation's last ``wal.durable`` watermark
  (nothing acked is lost) and its last ``wal.append`` (nothing is
  invented), and the obs/audit certifier's ``durability_watermark``
  check must pass — any appended-but-unacked records the crash dropped
  are audited as covered by the successor, never silently gone.

Digest equality against the sequential reference stays bit-exact in
every combination.

Run:  python scripts/crash_recovery_demo.py [--mode both]
          [--durability all] [--type topk_rmv]
Make: make crash-demo
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import struct
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEMO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "elastic_demo.py")
MEMBERS = ("w0", "w1", "w2")
VICTIM = "w1"


def _env(root: str, durability: str) -> dict:
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # parent flags (device counts) break workers
    env["JAX_PLATFORMS"] = "cpu"
    # Observability plane: continuous flight-recorder spill (survives the
    # SIGKILL — that is the point) + exit-time metrics snapshots.
    env["CCRDT_OBS_DIR"] = os.path.join(root, "obs")
    env["CCRDT_METRICS_DIR"] = os.path.join(root, "metrics")
    env["CCRDT_WAL_DURABILITY"] = durability
    return env


def _launch(root: str, member: str, type_name: str, wal_dir: str,
            durability: str):
    return subprocess.Popen(
        [sys.executable, DEMO, "--root", root, "--member", member,
         "--n-members", str(len(MEMBERS)), "--type", type_name,
         "--wal-dir", wal_dir],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        env=_env(root, durability), text=True,
    )


def _snap_seq(root: str, member: str):
    """The 8-byte step header of `member`'s published snapshot, or None."""
    try:
        with open(os.path.join(root, f"snap-{member}"), "rb") as f:
            hdr = f.read(8)
    except OSError:
        return None
    if len(hdr) != 8:
        return None
    return struct.unpack("<Q", hdr)[0]


def run_scenario(
    mode: str, type_name: str, timeout: float, durability: str = "group"
) -> dict:
    """One kill/restart drill; returns a verdict dict (ok + evidence)."""
    from scripts.elastic_demo import reference_digest

    root = tempfile.mkdtemp(prefix=f"crash-{mode}-{durability}-")
    wal_dir = os.path.join(root, "wal")
    procs = {
        m: _launch(root, m, type_name, wal_dir, durability) for m in MEMBERS
    }

    # Wait for the victim to have durable, published progress (a couple
    # of steps in the WAL AND visible to peers), then SIGKILL it.
    deadline = time.time() + timeout
    while time.time() < deadline:
        seq = _snap_seq(root, VICTIM)
        if seq is not None and 2 <= seq < 8:
            break
        if procs[VICTIM].poll() is not None:
            raise RuntimeError("victim exited before the kill point")
        time.sleep(0.01)
    else:
        raise RuntimeError("victim never reached the kill window")
    kill_seq = seq
    victim_pid = procs[VICTIM].pid
    procs[VICTIM].kill()  # SIGKILL: no atexit, no flush, torn tail possible
    procs[VICTIM].wait()

    if mode == "adopt":
        # Destroy the durable path entirely and hold the restart past
        # failure detection: survivors must adopt, the restarted victim
        # must self-regenerate — convergence without WAL recovery.
        shutil.rmtree(os.path.join(wal_dir, f"wal-{VICTIM}"), ignore_errors=True)
        time.sleep(1.0)
    procs[VICTIM] = _launch(root, VICTIM, type_name, wal_dir, durability)
    restart_pid = procs[VICTIM].pid

    rcs, outs = {}, {}
    for m, p in procs.items():
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        rcs[m], outs[m] = p.returncode, out

    # JSON round-trip: digests come back from the workers' final-*.json
    # as lists, the in-process reference may hold tuples.
    ref = json.loads(json.dumps(reference_digest(type_name)))
    finals, bad = {}, []
    for m in MEMBERS:
        path = os.path.join(root, f"final-{m}.json")
        if not os.path.exists(path):
            bad.append(f"{m}: no final (rc={rcs[m]})\n{outs[m][-2000:]}")
            continue
        with open(path) as f:
            finals[m] = json.load(f)
        if finals[m]["digest"] != ref:
            bad.append(f"{m}: digest != reference")

    recovered = int(
        finals.get(VICTIM, {}).get("metrics", {}).get("wal.recovered_records", 0)
    )
    if mode == "wal" and recovered <= 0:
        bad.append("victim converged without WAL recovery (recovered_records=0)")
    if mode == "adopt" and recovered > 0:
        bad.append(f"adopt mode unexpectedly recovered {recovered} WAL records")

    # Flight-recorder post-mortem: the SIGKILLed incarnation must have
    # left a spill (the continuous JSONL write is what survives a kill
    # that no signal handler can see), identifiable by the ABSENCE of a
    # proc.exit trailer, and its last durable step must sit at/just past
    # the kill point — never beyond what the victim could have reached.
    from antidote_ccrdt_tpu.obs import events as obs_events

    killed_log = obs_events.read_log(
        os.path.join(root, "obs", f"flight-{VICTIM}-{victim_pid}.jsonl")
    )
    flight_last_step = max(
        (int(e["wseq"]) for e in killed_log if e.get("kind") == "wal.append"),
        default=None,
    )
    if not killed_log:
        bad.append("no flight-recorder dump for the SIGKILLed incarnation")
    elif any(e.get("kind") == "proc.exit" for e in killed_log):
        bad.append("killed incarnation's flight log has a clean proc.exit")
    elif flight_last_step is not None and flight_last_step > kill_seq + 2:
        bad.append(
            f"flight log claims step {flight_last_step}, but the victim "
            f"was killed at published seq {kill_seq}"
        )

    # Durability-mode post-mortem (PR 11): the killed incarnation's last
    # acked watermark (wal.durable) vs where the restarted incarnation's
    # wal.recover actually landed.
    flight_durable = max(
        (int(e["through"]) for e in killed_log
         if e.get("kind") == "wal.durable"),
        default=-1,
    )
    restart_log = obs_events.read_log(
        os.path.join(root, "obs", f"flight-{VICTIM}-{restart_pid}.jsonl")
    )
    recover_ev = next(
        (e for e in restart_log if e.get("kind") == "wal.recover"), None
    )
    recovered_last = (
        None if recover_ev is None else int(recover_ev["last_step"])
    )
    if mode == "wal" and recover_ev is None:
        bad.append("restarted victim emitted no wal.recover event")
    elif mode == "wal" and durability in ("sync", "group"):
        # Durable-before-visible: anything the victim had PUBLISHED was
        # fsync-acked first (sync: per append; group: boundary flush
        # precedes the publish), so recovery must reach the kill seq.
        if recovered_last < kill_seq:
            bad.append(
                f"{durability}: recovered last_step {recovered_last} < "
                f"published seq {kill_seq} at kill — acked record lost"
            )
    elif mode == "wal" and durability == "async":
        # Recovery == watermark truncation: the resume point is
        # bracketed by the killed incarnation's last ack (below it an
        # acked record was lost) and its last append (above it recovery
        # invented records the victim never wrote).
        if recovered_last < flight_durable:
            bad.append(
                f"async: recovered last_step {recovered_last} < durable "
                f"watermark {flight_durable} — acked record lost"
            )
        if flight_last_step is not None and recovered_last > flight_last_step:
            bad.append(
                f"async: recovered last_step {recovered_last} > last "
                f"appended {flight_last_step} — recovery past the log"
            )

    # Certifier reconciliation over the whole fleet's flight logs: any
    # records the crash dropped past the watermark must be audited as
    # covered by the successor incarnation — zero unaudited loss.
    from antidote_ccrdt_tpu.obs import audit as obs_audit

    cert = obs_audit.certify(obs_dir=os.path.join(root, "obs"))
    if durability in ("group", "async") and "durability_watermark" not in (
        cert["checks"]
    ):
        bad.append(f"{durability}: certifier durability check never activated")
    if cert["checks"].get("durability_watermark") is False:
        bad.append(
            "certifier durability_watermark FAILED: "
            + json.dumps(cert["durability"].get("exposed", []))
        )

    verdict = {
        "mode": mode,
        "durability": durability,
        "type": type_name,
        "ok": not bad,
        "problems": bad,
        "victim_recovered_records": recovered,
        "victim_resume_step": finals.get(VICTIM, {})
        .get("metrics", {})
        .get("wal.resume_step"),
        "kill_seq": kill_seq,
        "victim_flight_events": len(killed_log),
        "victim_flight_last_step": flight_last_step,
        "victim_flight_durable": flight_durable,
        "victim_recover_last_step": recovered_last,
        "certifier_checks": cert["checks"],
        "returncodes": rcs,
        "root": root,
    }
    if not bad:
        shutil.rmtree(root, ignore_errors=True)
        verdict.pop("root")
    return verdict


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", default="both", choices=("wal", "adopt", "both"))
    ap.add_argument(
        "--durability", default="all",
        choices=("sync", "group", "async", "all"),
        help="WAL durability discipline for the fleet (all = drill each)",
    )
    ap.add_argument("--type", default="topk_rmv")
    ap.add_argument("--timeout", type=float, default=240.0)
    args = ap.parse_args()

    modes = ("wal", "adopt") if args.mode == "both" else (args.mode,)
    durabilities = (
        ("sync", "group", "async")
        if args.durability == "all" else (args.durability,)
    )
    # The wal-mode drill runs under EVERY durability discipline (its
    # assertions differ per mode); adopt deletes the WAL outright, so
    # one representative durability is enough.
    plan = []
    if "wal" in modes:
        plan += [("wal", d) for d in durabilities]
    if "adopt" in modes:
        plan.append(("adopt", "group" if "group" in durabilities
                     else durabilities[0]))
    verdicts = [
        run_scenario(m, args.type, args.timeout, durability=d)
        for m, d in plan
    ]
    print(json.dumps(verdicts, indent=2), flush=True)
    if not all(v["ok"] for v in verdicts):
        sys.exit(1)


if __name__ == "__main__":
    main()
