"""Device-observatory acceptance drill (obs/devprof.py tentpole gate).

A seeded stepping 3-worker fleet grows topk_rmv state one live add per
round, so the dense fold's slots-per-id axis grows every round and —
cold — provokes a recompile storm at ``batch_merge.fold``. Four arms,
each its own subprocess so every arm starts from a stone-cold jit
cache:

* **cold storm** — ``CCRDT_DEVPROF=1``: every steady-state round
  recompiles; the observatory must attribute 100% of the compiles to
  (site, changed axis) and name topk_rmv capacity growth
  (``slot_score axis3``) as the dominant churn source;
* **warm** — ``CCRDT_DEVPROF_WARMUP=1`` on top: power-of-two shape
  padding plus the boot-time ``prewarm_topk_rmv`` capacity ladder
  collapse the storm — steady-state recompiles must drop >= 5x (to
  zero, in practice), with the deliberate boot compiles attributed to
  their own ``batch_merge.prewarm`` site;
* **overhead A/B** — paired ``CCRDT_DEVPROF=1`` vs ``CCRDT_DEVPROF=0``
  runs of stable-shape steady rounds (no recompiles in the timed
  window): the armed observatory must cost <= 2% wall time, and the
  kill-switch arm's merged result must be byte-identical (canonical
  digest) — observation never perturbs CRDT semantics.

Writes the measurements to DEVPROF_r01.json (committed as the carrier
scripts/bench_gate.py `evaluate_devprof` regresses steady-state
recompiles-per-100-rounds, compile-ms share, and overhead against) and
exits nonzero if any gate fails.

Run:  make devprof-demo
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import random
import subprocess
import sys
import time
from typing import Any, Dict, List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

WORKERS = 3
SIZE = 23          # topk capacity; part of the engine-memo key
STORM_ROUNDS = 24
AB_ROUNDS = 600        # alternating armed/unarmed single rounds
STABLE_ELEMS = 24      # per-worker live adds before the timed windows


def _step(sc, states, r: int, seed: int):
    rng = random.Random((seed << 16) ^ r)
    out = []
    for wi, st in enumerate(states):
        st, _ = sc.update(
            ("add", (1, 100 + rng.randrange(100),
                     (f"dc{wi}", r * len(states) + wi + 1))),
            st,
        )
        out.append(st)
    return out


def _canon(st) -> tuple:
    return (
        sorted((w, sorted(es)) for w, es in st.masked.items()),
        sorted((w, sorted(v.items())) for w, v in st.removals.items()),
        sorted(st.vc.items()),
        sorted(st.observed.items()),
        st.min,
        st.size,
    )


# -- child arms (fresh process each: stone-cold jit caches) -----------------


def _arm_storm(warm: bool, rounds: int, seed: int) -> Dict[str, Any]:
    from antidote_ccrdt_tpu.core import batch_merge
    from antidote_ccrdt_tpu.models.topk_rmv import TopkRmvScalar
    from antidote_ccrdt_tpu.obs import devprof, events
    from antidote_ccrdt_tpu.utils.metrics import Metrics

    events.reset("devprof-demo")
    m = Metrics()
    env = {devprof.ENV_FLAG: "1"}
    if warm:
        env[devprof.ENV_WARMUP] = "1"
    assert devprof.install_from_env(m, env=env)
    boot_rungs = 0
    if warm:
        # Each worker contributes a unique (dc, ts) per round, so the
        # union of live adds per id grows by WORKERS per round.
        boot_rungs = batch_merge.prewarm_topk_rmv(
            SIZE, n_ids=1, n_dcs=WORKERS, max_slots=(rounds + 1) * WORKERS
        )
    boot_compiles = m.snapshot()["counters"].get("devprof.compiles", 0)

    sc = TopkRmvScalar()
    states = [sc.new(SIZE) for _ in range(WORKERS)]
    round_walls: List[float] = []
    round_compiles: List[int] = []
    prev = boot_compiles
    for r in range(rounds):
        states = _step(sc, states, r, seed)
        t0 = time.perf_counter()
        batch_merge.batch_merge("topk_rmv", list(states))
        round_walls.append((time.perf_counter() - t0) * 1000.0)
        cur = m.snapshot()["counters"].get("devprof.compiles", 0)
        round_compiles.append(int(cur - prev))
        prev = cur

    evs = [e for e in events.events() if e["kind"] == "devprof.compile"]
    run_evs = [e for e in evs if e["site"] != "batch_merge.prewarm"]
    # Steady state = everything after round 0 (round 0 legitimately
    # first-traces the cold arm; the warm arm pre-traced it at boot).
    steady_compiles = sum(round_compiles[1:])
    steady_wall_ms = sum(round_walls[1:])
    # run_evs is in dispatch order, so the first round_compiles[0] of
    # them belong to round 0 and the rest to the steady window.
    steady_compile_ms = sum(
        float(e["ms"]) for e in run_evs[round_compiles[0]:]
    )
    axes = [e.get("axis", "") for e in run_evs]
    growth = [a for a in axes if "slot_score" in a and "axis3" in a]
    return {
        "warm": warm,
        "rounds": rounds,
        "boot_rungs": boot_rungs,
        "boot_compiles": int(boot_compiles),
        "n_compiles": len(run_evs),
        "steady_compiles": int(steady_compiles),
        "steady_per_100_rounds": round(
            steady_compiles / max(rounds - 1, 1) * 100.0, 2
        ),
        "steady_wall_ms": round(steady_wall_ms, 3),
        "steady_compile_ms": round(steady_compile_ms, 3),
        "compile_ms_share_pct": round(
            steady_compile_ms / max(steady_wall_ms, 1e-9) * 100.0, 2
        ),
        "unattributed": sum(
            1 for e in run_evs
            if not e.get("site") or not e.get("axis")
            or not e.get("signature")
        ),
        "n_capacity_growth": len(growth),
        "axes": axes[:64],
        "sites": sorted({e["site"] for e in evs}),
        "counters": {
            k: v for k, v in m.snapshot()["counters"].items()
            if not k.startswith("devprof.cache_depth")
        },
    }


def _arm_ab(seed: int) -> Dict[str, Any]:
    """Paired A/B: the observatory's per-dispatch cost (~10us) sits far
    below single-window scheduler noise, so a 2% budget is only
    decidable with strictly alternating single-round samples and a
    mean-of-best-quartile per arm — the quartile floor rejects the
    long-tail scheduler/GC outliers symmetrically, and alternation
    guarantees both arms see the same machine drift."""
    from antidote_ccrdt_tpu.core import batch_merge
    from antidote_ccrdt_tpu.models.topk_rmv import TopkRmvScalar
    from antidote_ccrdt_tpu.obs import devprof, events
    from antidote_ccrdt_tpu.utils.metrics import Metrics

    events.reset("devprof-demo-ab")
    m = Metrics()
    assert devprof.install_from_env(
        m, env={devprof.ENV_FLAG: "0"}
    ) is False  # kill switch: truly dark
    off_keys = sum(
        1 for k in m.snapshot()["counters"] if k.startswith("devprof.")
    )

    sc = TopkRmvScalar()
    states = [sc.new(SIZE) for _ in range(WORKERS)]
    for r in range(STABLE_ELEMS):
        states = _step(sc, states, r, seed)
    # Warm the (now stable) shapes out of every timed sample.
    for _ in range(3):
        merged = batch_merge.batch_merge("topk_rmv", list(states))
    digest_off = hashlib.sha256(repr(_canon(merged)).encode()).hexdigest()

    on_t: List[float] = []
    off_t: List[float] = []
    for i in range(AB_ROUNDS):
        armed = bool(i % 2)
        if armed:
            devprof.install(m)
        t0 = time.perf_counter()
        merged = batch_merge.batch_merge("topk_rmv", list(states))
        dt = time.perf_counter() - t0
        devprof.uninstall()
        (on_t if armed else off_t).append(dt)
    digest_on = hashlib.sha256(repr(_canon(merged)).encode()).hexdigest()
    on_t.sort()
    off_t.sort()
    k = max(len(on_t) // 4, 1)
    on_q = sum(on_t[:k]) / k
    off_q = sum(off_t[:k]) / k
    return {
        "overhead_pct": (on_q - off_q) / off_q * 100.0,
        "ab_rounds": AB_ROUNDS,
        "quartile_n": k,
        "on_best_quartile_ms": round(on_q * 1e3, 4),
        "off_best_quartile_ms": round(off_q * 1e3, 4),
        "digest_on": digest_on,
        "digest_off": digest_off,
        "off_devprof_counter_keys": off_keys,
        "on_dispatches": int(
            m.snapshot()["counters"].get("devprof.dispatches", 0)
        ),
    }


def _run_child(arm: str, seed: int) -> Dict[str, Any]:
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("CCRDT_DEVPROF", None)
    env.pop("CCRDT_DEVPROF_WARMUP", None)
    env.pop("CCRDT_PROFILE", None)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         "--arm", arm, "--seed", str(seed)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=600,
    )
    if proc.returncode != 0:
        print(proc.stdout)
        print(proc.stderr, file=sys.stderr)
        raise SystemExit(f"arm {arm} failed rc={proc.returncode}")
    return json.loads(proc.stdout.splitlines()[-1])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--arm", choices=["cold", "warm", "ab"])
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument(
        "--out", default=os.path.join(REPO, "DEVPROF_r01.json")
    )
    args = ap.parse_args(argv)

    if args.arm:  # child mode
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        if args.arm in ("cold", "warm"):
            doc = _arm_storm(args.arm == "warm", STORM_ROUNDS, args.seed)
        else:
            doc = _arm_ab(args.seed)
        print(json.dumps(doc))
        return 0

    t_start = time.time()
    print(f"devprof-demo: {WORKERS}-worker stepping fleet, "
          f"{STORM_ROUNDS} storm rounds, seed {args.seed}")
    cold = _run_child("cold", args.seed)
    print(f"  cold:  {cold['n_compiles']} compiles / {cold['rounds']} "
          f"rounds ({cold['steady_per_100_rounds']:.0f}/100 steady), "
          f"compile share {cold['compile_ms_share_pct']:.1f}%, "
          f"{cold['unattributed']} unattributed")
    warm = _run_child("warm", args.seed)
    print(f"  warm:  boot ladder {warm['boot_rungs']} rungs "
          f"({warm['boot_compiles']} prewarm compiles), then "
          f"{warm['steady_compiles']} steady compiles "
          f"({warm['steady_per_100_rounds']:.0f}/100), "
          f"compile share {warm['compile_ms_share_pct']:.1f}%")
    ab = _run_child("ab", args.seed)
    overhead_pct = ab["overhead_pct"]
    print(f"  a/b:   {ab['ab_rounds']} alternating rounds, best-quartile "
          f"on {ab['on_best_quartile_ms']:.3f}ms vs off "
          f"{ab['off_best_quartile_ms']:.3f}ms -> "
          f"overhead {overhead_pct:+.2f}%")

    cut_ok = (
        cold["steady_per_100_rounds"]
        >= 5.0 * warm["steady_per_100_rounds"]
        and cold["steady_compiles"] > 0
    )
    dominant = (
        cold["n_capacity_growth"] >= max(cold["n_compiles"] - 1, 1)
    )
    checks = {
        "storm_provoked": cold["steady_compiles"] >= STORM_ROUNDS // 2,
        "storm_attributed_100pct": (
            cold["unattributed"] == 0 and cold["n_compiles"] > 0
        ),
        "capacity_growth_dominant": dominant,
        "warmup_cut_5x": cut_ok,
        "warmup_boot_attributed": warm["boot_compiles"] > 0
        and "batch_merge.prewarm" in warm["sites"],
        "steady_recompiles_bounded": warm["steady_per_100_rounds"] <= 5.0,
        "compile_share_bounded": warm["compile_ms_share_pct"] <= 2.0,
        "overhead_under_budget": overhead_pct <= 2.0,
        "kill_switch_bit_identical": ab["digest_on"] == ab["digest_off"],
        "kill_switch_dark": ab["off_devprof_counter_keys"] == 0,
        "devprof_counters_lit": cold["counters"].get(
            "devprof.compiles", 0
        ) > 0 and cold["counters"].get("devprof.dispatches", 0) > 0,
    }
    doc = {
        "drill": "devprof_demo",
        "seed": args.seed,
        "workers": WORKERS,
        "storm_rounds": STORM_ROUNDS,
        # The three gated metrics (steady state = the warm/production
        # configuration; the cold arm exists to prove the storm is real
        # and fully attributed).
        "recompiles_per_100_rounds": warm["steady_per_100_rounds"],
        "compile_ms_share_pct": warm["compile_ms_share_pct"],
        "overhead_pct": round(overhead_pct, 2),
        # Capped: a zero-recompile warm arm is an infinite cut.
        "storm_cut_factor": round(min(
            cold["steady_per_100_rounds"]
            / max(warm["steady_per_100_rounds"], 1e-9), 999.0
        ), 1),
        "cold": cold,
        "warm": {k: v for k, v in warm.items() if k != "axes"},
        "overhead": {k: v for k, v in ab.items() if "digest" not in k},
        "checks": checks,
        "pass": all(checks.values()),
        "wall_s": round(time.time() - t_start, 1),
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    for name, ok in sorted(checks.items()):
        print(f"  {'PASS' if ok else 'FAIL'}  {name}")
    print(f"devprof-demo: {'PASS' if doc['pass'] else 'FAIL'} "
          f"-> {args.out} ({doc['wall_s']}s)")
    return 0 if doc["pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
