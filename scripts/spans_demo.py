"""Span-tracing demo/gate: a real 3-worker TCP fleet on one timeline.

`make spans-demo` runs this. It spawns three `net_gossip_demo` workers
(real localhost sockets, delta gossip, WAL armed) with the span plane on
(``CCRDT_SPANS=1`` + ``CCRDT_OBS_DIR``), NTP-probes each worker's clock
over the in-band ``{metrics_req, T1}`` frame while the fleet is alive,
and after the workers exit:

1. merges every worker's span spill into ONE Perfetto/Chrome trace-event
   JSON via `scripts/ccrdt_spans.py merge` — three processes, one
   clock-aligned timeline (the artifact path is printed; load it in
   ui.perfetto.dev);
2. prints the dispatch-gap attribution report (`ccrdt_spans.py
   attribute`);
3. FAILS (exit 1) unless: every worker recorded `round.e2e` rounds, all
   nine load-bearing phases (`obs.spans.PHASES`) are lit somewhere in
   the fleet, at least one cross-worker clock offset was captured (the
   alignment is real, not a fallback), and the phases' serial union
   explains at least ``MIN_COVERAGE`` of the measured round wall time —
   the "attribution sums reconcile against e2e" acceptance.

This is the span plane's end-to-end proof, the analogue of what
`make obs-demo` is for the flight recorder.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Dict, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from antidote_ccrdt_tpu.obs import spans as obs_spans  # noqa: E402

MEMBERS = ("w0", "w1", "w2")

# Fleet-p50 fraction of round.e2e wall the serial phase union must
# explain. The TCP drill's rounds carry real untraced slack (SWIM
# bookkeeping, status drops, scheduler noise between phases), so this is
# looser than chaos_gate's in-process drill — but low coverage still
# means the load-bearing spans went dark.
MIN_COVERAGE = 0.5


def _gossip_addrs(root: str) -> Dict[str, Tuple[str, int]]:
    out: Dict[str, Tuple[str, int]] = {}
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for fn in names:
        if not fn.startswith("addr-") or ".tmp" in fn:
            continue
        try:
            with open(os.path.join(root, fn)) as f:
                hostport = f.read().strip().split(" ")[0]
            host, port = hostport.rsplit(":", 1)
            out[fn[len("addr-"):]] = (host, int(port))
        except (OSError, ValueError):
            continue
    return out


def main() -> int:
    from antidote_ccrdt_tpu.net.tcp import probe_clock

    here = os.path.dirname(os.path.abspath(__file__))
    demo = os.path.join(here, "net_gossip_demo.py")
    spans_cli = os.path.join(here, "ccrdt_spans.py")
    root = tempfile.mkdtemp(prefix="spans-demo-")
    obs_dir = os.path.join(root, "obs")
    trace_out = os.path.join(root, "spans_trace.json")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["CCRDT_OBS_DIR"] = obs_dir
    env["CCRDT_SPANS"] = "1"
    procs = [
        subprocess.Popen(
            [sys.executable, demo, "--root", root, "--member", m,
             "--n-members", str(len(MEMBERS)), "--delta",
             "--wal-dir", os.path.join(root, "wal"),
             "--step-sleep", "0.2"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, text=True,
        )
        for m in MEMBERS
    ]
    # While the fleet runs, take one NTP-style probe per worker from THIS
    # process — the same exchange the workers ride on their hellos,
    # exercised over the operator surface.
    probes: Dict[str, Tuple[float, float]] = {}
    outs: Dict[str, str] = {}
    try:
        while any(p.poll() is None for p in procs):
            for m, addr in sorted(_gossip_addrs(root).items()):
                if m in probes:
                    continue
                try:
                    member, off, rtt = probe_clock(addr, timeout=1.0)
                    probes[member] = (off, rtt)
                except (OSError, ValueError, ConnectionError):
                    continue
            time.sleep(0.2)
    finally:
        for m, p in zip(MEMBERS, procs):
            try:
                out, _ = p.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
            outs[m] = out
    bad = [m for m, p in zip(MEMBERS, procs) if p.returncode != 0]
    if bad:
        for m in bad:
            print(f"-- worker {m} failed --\n{outs[m][-2000:]}")
        return 1

    print("== NTP probes (operator -> worker, monotonic-clock offset) ==")
    for m, (off, rtt) in sorted(probes.items()):
        print(f"  {m}: offset {off * 1e3:+.3f}ms rtt {rtt * 1e3:.3f}ms")

    print("\n== merged Perfetto trace (scripts/ccrdt_spans.py merge) ==")
    r = subprocess.run(
        [sys.executable, spans_cli, "merge", obs_dir, "-o", trace_out],
        capture_output=True, text=True, timeout=120,
    )
    print(r.stdout, end="")
    if r.returncode != 0:
        print(f"FAIL: merge exited {r.returncode}\n{r.stderr[-2000:]}")
        return 1

    print("\n== dispatch-gap attribution (scripts/ccrdt_spans.py attribute) ==")
    r = subprocess.run(
        [sys.executable, spans_cli, "attribute", obs_dir],
        capture_output=True, text=True, timeout=120,
    )
    print(r.stdout, end="")
    if r.returncode != 0:
        print(f"FAIL: attribute exited {r.returncode}\n{r.stderr[-2000:]}")
        return 1

    # -- acceptance: the plane measured a real fleet, end to end ----------
    by_member = obs_spans.scan_dir(obs_dir)
    att = obs_spans.attribute(by_member)
    with open(trace_out) as f:
        trace = json.load(f)
    n_events = len([
        e for e in trace.get("traceEvents", []) if e.get("ph") == "X"
    ])
    offsets = obs_spans.clock_offsets(by_member)

    missing_members = sorted(set(MEMBERS) - set(att["members"]))
    if missing_members:
        print(f"FAIL: no round.e2e spans from {missing_members}")
        return 1
    lit = set(att["fleet"]["phases_ms_total"])
    dark = sorted(set(obs_spans.PHASES) - lit)
    if dark:
        print(f"FAIL: load-bearing phases recorded no time: {dark}")
        return 1
    if not n_events:
        print("FAIL: merged trace holds no span events")
        return 1
    if not offsets:
        print("FAIL: no cross-worker clock offsets captured — the merged "
              "timeline is NOT aligned (hello/metrics clock echo dark)")
        return 1
    cov = att["fleet"]["coverage_p50"]
    if cov < MIN_COVERAGE:
        print(f"FAIL: phase spans explain only {cov:.1%} of round wall "
              f"(need >= {MIN_COVERAGE:.0%}) — attribution no longer "
              f"reconciles against round.e2e")
        return 1
    print(f"\nOK: {len(att['members'])} workers, "
          f"{att['fleet']['rounds']} rounds, all {len(obs_spans.PHASES)} "
          f"phases lit, {n_events} spans on one aligned timeline "
          f"({sum(len(v) for v in offsets.values())} offset edges), "
          f"coverage {cov:.1%}")
    print(f"perfetto trace: {trace_out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
