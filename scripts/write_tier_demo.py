"""Fleet write tier acceptance drill (serve/ingest.py tentpole gate).

Four real worker processes (scripts/net_gossip_demo.py, CCRDT_SERVE=1 +
CCRDT_INGEST=1, per-worker crash WAL) gossip the topk_rmv drill over
TCP under seeded chaos (tcp.send drops + serve.write delays inside the
workers, router.write drops in the supervisor) while writer threads
push client effect bursts through `serve.WriteSession` ->
`serve.WriteRouter` — pre-wire ops/compaction (one CCRF range frame per
burst), HRW owner-first routing, shared circuit breakers, bounded
retries, tiered acks (`durable` pinned to the owner's
`wal.durable_seq`, `replicated_to_k` certified client-side by peer
watermark probes). The partition owner of the hot key is SIGKILLed
mid-load. The gate holds the write tier to its whole contract at once:

* **degrade, never hang** — every routed write completes or errors
  honestly (ack / overloaded+retry_after_ms / unavailable); zero
  ``unavailable`` results, zero silent drops, and no write exceeds a
  hard latency ceiling even across the kill;
* **tiered acks for real** — nonzero ``durable`` AND
  ``replicated_to_k`` acks land during the storm, including hard acks
  from the victim before its SIGKILL (the contract under test);
* **read-your-writes across tiers** — each acked write teaches its
  `ClientSession` the ``(origin, seq)`` it landed at, and a follow-up
  read through the READ tier (`serve.FleetRouter`, same session) must
  cover that floor — across the owner's death via survivor delta
  cursors, or refuse honestly (``session_unsatisfiable``);
* **admission honesty** — a shed-arm probe against an overloaded
  in-process plane returns ``overloaded`` with the plane's own
  ``retry_after_ms`` hint, promptly, with the
  ``router.write_shed_returns`` counter lit;
* **observability** — the ``router.write*`` / ``write_session.*``
  counters the dashboard renders are actually lit, and the seeded
  ``router.write`` fault point demonstrably fired;
* **certified durability** — `obs.audit.certify_writes` replays the
  client's ``ingest.ack`` flight events against the fleet's spilled
  durability evidence (victim ``wal.durable`` watermarks, survivor
  ``delta.apply`` cursors) and signs a certificate of ZERO
  acked-but-lost writes across the SIGKILL, while a deliberately
  violating arm (`ack_before_fsync=True`) must FAIL certification
  with a counterexample naming the lost seq range and write_ids.

Writes the measurements to WRITETIER_r01.json (committed as the
carrier scripts/bench_gate.py regresses fleet writes/sec / write p99 /
failover blip against) and exits nonzero if any gate fails.

Run:  make write-tier-demo
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from scripts.cover import install_child_cover  # noqa: E402

install_child_cover()  # no-op outside `make cover` runs

DEMO = os.path.join(REPO, "scripts", "net_gossip_demo.py")

MEMBERS = ["w0", "w1", "w2", "w3"]
WRITERS = 3           # writer WRITERS-1 demands replicated_to_k acks
DCS = 4               # elastic_demo topk_rmv geometry (dc = writer % DCS)
IDS_PER_BURST = 3     # 4 adds per id, m_keep=2 -> steady 2.0 coalesce
ADDS_PER_ID = 4       # ...and ONE wire shape (no per-burst JIT churn)
M_KEEP = 2            # == the model's slots_per_id: extras are wire waste
MAX_STALENESS_S = 30.0
HARD_LATENCY_CEILING_S = 30.0   # "zero hangs" — nothing may exceed this
HARD_LEVELS = ("durable", "replicated_to_k")

# Counters that MUST be nonzero after the storm — the write tier going
# silently dark fails the leg even if every burst seems acked (the same
# contract scripts/chaos_gate.py REQUIRED_NONZERO enforces for gossip).
WRITE_REQUIRED_NONZERO = (
    "router.writes",
    "router.write_successes",
    "router.write_failovers",
    "write_session.flushes",
    "write_session.staged_ops",
)

# Worker-side chaos (rides CCRDT_FAULTS into every worker).
WORKER_FAULTS = {
    "tcp.send": [{"action": "drop", "rate": 0.02}],
    "serve.write": [{"action": "delay", "rate": 0.05, "delay_s": 0.002}],
}
# Supervisor-side chaos: the write router's own fault point — injected
# attempt drops force real owner failovers and retries during the storm.
ROUTER_FAULTS = {"router.write": [{"action": "drop", "rate": 0.05}]}


def _spawn_fleet(root: str, obs_dir: str, args) -> dict:
    from antidote_ccrdt_tpu.utils import faults as faults_mod

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["CCRDT_OBS_DIR"] = obs_dir
    env["CCRDT_SERVE"] = "1"
    env["CCRDT_INGEST"] = "1"
    # A write is folded at the NEXT step boundary; on a contended CPU
    # host (4 JAX workers sharing cores) a step can take several
    # seconds, so the default 2s ack deadline would time out honest
    # writes. The router's attempt timeout stays above this.
    env["CCRDT_INGEST_ACK_TIMEOUT_S"] = "8"
    env["CCRDT_FAULTS"] = faults_mod.plan_to_env(WORKER_FAULTS, seed=11)
    procs = {}
    for member in MEMBERS:
        cmd = [
            sys.executable, DEMO, "--root", root, "--member", member,
            "--n-members", str(len(MEMBERS)), "--type", "topk_rmv",
            "--delta", "--publish-every", "1",
            "--wal-dir", os.path.join(root, f"wal-{member}"),
            "--steps", str(args.steps),
            "--timeout", str(args.timeout),
            "--step-sleep", str(args.step_sleep),
        ]
        procs[member] = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, text=True,
        )
    return procs


def _wait_addrs(root: str, timeout: float) -> dict:
    """Wait for every worker's addr-<member> rendezvous file."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        addrs = {}
        for m in MEMBERS:
            try:
                with open(os.path.join(root, f"addr-{m}")) as f:
                    hostport = f.read().split()[0]
                host, port = hostport.rsplit(":", 1)
                addrs[m] = (host, int(port))
            except (OSError, ValueError, IndexError):
                break
        if len(addrs) == len(MEMBERS):
            return addrs
        time.sleep(0.05)
    raise RuntimeError("workers never published their addresses")


def _step_of(root: str, member: str) -> int:
    try:
        with open(os.path.join(root, f"obs-{member}.json")) as f:
            return int(json.load(f).get("step", -1))
    except (OSError, ValueError):
        return -1


def _wait_step(root: str, member: str, step: int, timeout: float) -> bool:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if _step_of(root, member) >= step:
            return True
        time.sleep(0.05)
    return False


def _shed_arm():
    """Admission-control honesty, in-process: a `WriteRouter` walked
    into a plane whose pressure probe sheds must come back PROMPTLY
    with ``overloaded`` and the plane's own retry_after_ms hint — no
    hang, no silent drop, and the shed-return counter lit."""
    from antidote_ccrdt_tpu.serve.ingest import IngestPlane, WriteRouter
    from antidote_ccrdt_tpu.utils.metrics import Metrics

    plane = IngestPlane(
        "shed0", metrics=Metrics(),
        pressure_fns=(lambda: 350,), poll_s=0.001,
    )

    def wfn(peer, payload, timeout_s, cancel):
        return plane.handle(payload, surface="local")

    m = Metrics()
    r = WriteRouter(
        ["shed0"], wfn, member="shed-probe", metrics=m, retries=1,
        backoff_base_s=0.0, backoff_max_s=0.0, poll_s=0.001,
    )
    t0 = time.monotonic()
    out = r.write([["add", [1, 5, [0, 2_000_001]]]], key="k0")
    dt_s = time.monotonic() - t0
    shed_returns = int(
        m.snapshot()["counters"].get("router.write_shed_returns", 0)
    )
    return out, dt_s, shed_returns


def _violating_arm():
    """The audit layer's negative control, in-process: a plane armed
    with ``ack_before_fsync=True`` acks ``durable`` the moment the fold
    lands, while its (truthful) origin log shows the fsync watermark
    never passed. `certify_writes` must FAIL with a counterexample
    naming the lost seq range and the acked write_ids inside it."""
    from antidote_ccrdt_tpu.obs import events as obs_events
    from antidote_ccrdt_tpu.obs.audit import certify_writes
    from antidote_ccrdt_tpu.serve.ingest import (
        ACK_DURABLE, IngestPlane, WriteRouter,
    )
    from antidote_ccrdt_tpu.utils.metrics import Metrics

    n0 = len(obs_events.events())
    pm = Metrics()
    plane = IngestPlane(
        "v0", metrics=pm, durable_fn=lambda: -1,
        ack_before_fsync=True, poll_s=0.001,
    )
    stop = threading.Event()

    def drain_loop():
        while not stop.is_set():
            plane.drain(20, lambda ops: None)
            time.sleep(0.002)

    th = threading.Thread(target=drain_loop, daemon=True)
    th.start()

    def wfn(peer, payload, timeout_s, cancel):
        return plane.handle(payload, surface="local")

    r = WriteRouter(
        ["v0"], wfn, member="v-probe", metrics=Metrics(),
        retries=0, poll_s=0.001,
    )
    outs = [
        r.write([["add", [i, 5, [0, 3_000_000 + i]]]],
                key="k0", ack=ACK_DURABLE)
        for i in range(3)
    ]
    stop.set()
    th.join(1.0)
    evs = obs_events.events()[n0:]
    # The arm's origin log records the truth the plane ignored: the
    # fsync watermark stalled at 7 while seq-20 folds were acked.
    logs = {
        "client-varm": evs,
        "flight-v0": [
            {"member": "v0", "kind": "wal.durable", "through": 7},
        ],
    }
    cert = certify_writes(
        logs=logs,
        meta={"arm": "ack_before_fsync", "drill": "write_tier_demo"},
    )
    unsafe = int(
        pm.snapshot()["counters"].get("ingest.unsafe_acks", 0)
    )
    return cert, outs, unsafe


def main() -> int:  # noqa: PLR0915 — one linear acceptance drill
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--out", default=os.path.join(REPO, "WRITETIER_r01.json"))
    ap.add_argument("--timeout", type=float, default=0.5,
                    help="worker SWIM timeout")
    ap.add_argument("--step-sleep", type=float, default=1.0)
    ap.add_argument("--steps", type=int, default=24,
                    help="per-worker step count: startup + warm-up eat "
                    "the first ~6 steps on a contended host, and the "
                    "storm needs a pre-kill AND a post-kill window")
    ap.add_argument("--kill-at-step", type=int, default=13)
    ap.add_argument("--min-writes", type=float, default=6.0,
                    help="minimum acked write bursts across the storm")
    ap.add_argument("--max-p99-ms", type=float, default=15000.0)
    ap.add_argument("--max-blip-ms", type=float, default=15000.0)
    ap.add_argument("--worker-timeout", type=float, default=240.0)
    args = ap.parse_args()

    import random

    from antidote_ccrdt_tpu.net.tcp import query_peer, write_peer
    from antidote_ccrdt_tpu.obs import events as obs_events
    from antidote_ccrdt_tpu.obs.audit import (
        certify_sessions, certify_writes, verify_certificate,
    )
    from antidote_ccrdt_tpu.serve import (
        ClientSession, FleetRouter, request_bytes, tcp_query_fn,
    )
    from antidote_ccrdt_tpu.serve.ingest import (
        ACK_DURABLE, ACK_REPLICATED, WriteRouter, tcp_write_fn,
    )
    from antidote_ccrdt_tpu.serve.plane import encode
    from antidote_ccrdt_tpu.serve.write_session import (
        WriteSession, effect_to_wire,
    )
    from antidote_ccrdt_tpu.topo import rendezvous_order
    from antidote_ccrdt_tpu.utils import faults
    from antidote_ccrdt_tpu.utils.metrics import Metrics

    # Ack/session/fold events are request-plane (per-kind rings in
    # obs/events.py) so the write storm can no longer evict the early
    # acks the durability certifier replays — a default recorder
    # suffices.
    obs_events.reset("writer")

    failures = []
    victim = rendezvous_order("k0", MEMBERS)[0]
    dead: set = set()
    metrics = Metrics()

    with tempfile.TemporaryDirectory(prefix="write-tier-") as tmp:
        root = os.path.join(tmp, "fleet")
        obs_dir = os.path.join(tmp, "obs")
        os.makedirs(root)
        print(f"== write tier: {len(MEMBERS)}-worker TCP fleet (WAL + "
              f"ingest), SIGKILL owner {victim} at step "
              f"{args.kill_at_step} ==")
        procs = _spawn_fleet(root, obs_dir, args)
        try:
            addrs = _wait_addrs(root, 60.0)
            for m in MEMBERS:
                if not _wait_step(root, m, 1, 120.0):
                    raise RuntimeError(f"{m} never reached step 1")

            # Warm every worker's write AND read paths (the first fold
            # of the storm's wire shape pays the apply_ops JIT; the
            # first query pays the serve fold). Concurrently — serial
            # warm-up would eat the workers' 10-step run.
            warm_errs: list = []

            def _warm(wi: int, m: str) -> None:
                ops = [
                    effect_to_wire(
                        ("add", (40 + j // M_KEEP,
                                 1 + j,
                                 (wi % DCS,
                                  900_000 + wi * 100 + j)))
                    )
                    for j in range(IDS_PER_BURST * M_KEEP)
                ]
                for attempt in range(3):
                    try:
                        write_peer(
                            addrs[m],
                            encode({"write_id": f"warm:{m}.{attempt}",
                                    "ops": ops, "ack": "applied",
                                    "type": "topk_rmv"}),
                            timeout=30.0,
                        )
                        query_peer(
                            addrs[m],
                            request_bytes([{"op": "value", "key": 0}]),
                            timeout=30.0)
                        return
                    except Exception as e:  # noqa: BLE001 — gate below
                        if attempt == 2:
                            warm_errs.append(f"{m}: {e}")
                        else:
                            time.sleep(0.5)

            warmers = [
                threading.Thread(target=_warm, args=(i, m), daemon=True)
                for i, m in enumerate(MEMBERS)
            ]
            for t in warmers:
                t.start()
            for t in warmers:
                t.join(90.0)
            if warm_errs:
                raise RuntimeError(
                    f"ingest warm-up failed: {'; '.join(warm_errs)}")

            def verdict(p: str) -> str:
                return "dead" if p in dead else "alive"

            faults.install(ROUTER_FAULTS, seed=7)
            r_read = FleetRouter(
                MEMBERS, tcp_query_fn(addrs), metrics=metrics,
                verdict_fn=verdict, hedge=False, timeout_s=1.0,
                retries=2, backoff_base_s=0.02, session_wait_s=3.5,
                session_poll_s=0.05, poll_s=0.002, seed=1,
                breaker_failures=6,
            )

            n_load0 = len(obs_events.events())
            stop = threading.Event()
            ts_lock = threading.Lock()
            ts_cell = [0]  # distinct client (dc, ts) stamps: join dedups
            stats = [
                {"lat": [], "ok_t": [], "acked": 0, "levels": {},
                 "downgrades": 0, "victim_hard": 0, "shed": 0,
                 "unavailable": 0, "ryw_ok": 0, "ryw_unsat": 0,
                 "ryw_shed": 0, "ryw_other": 0, "results": 0,
                 "raw": 0, "shipped": 0, "err_samples": []}
                for _ in range(WRITERS)
            ]

            def writer(ci: int) -> None:
                rng = random.Random(200 + ci)
                sess = ClientSession(f"demo-w{ci}")
                wrouter = WriteRouter(
                    MEMBERS, tcp_write_fn(addrs), member=f"c{ci}",
                    metrics=metrics, verdict_fn=verdict, timeout_s=10.0,
                    retries=2, backoff_base_s=0.02,
                    replication_wait_s=6.0, probe_timeout_s=1.0,
                    poll_s=0.002, seed=ci,
                    # Injected attempt drops would open the default
                    # 3-failure breaker on chaos alone mid-storm.
                    breaker_failures=6,
                )
                ack = ACK_REPLICATED if ci == WRITERS - 1 else ACK_DURABLE
                ws = WriteSession(
                    wrouter, "topk_rmv", session=sess,
                    session_id=f"demo-w{ci}", batch_max=999, ack=ack,
                    k=2, m_keep=M_KEEP, metrics=metrics,
                )
                st = stats[ci]
                n_burst = 0
                while not stop.is_set():
                    # One burst = one key = ONE range frame on the wire:
                    # 4 adds per id, top-2 survive compaction — a steady
                    # 2.0 coalesce ratio and a single wire shape. The
                    # FIRST burst always targets "k0" — the victim is
                    # chosen as k0's partition owner, so the
                    # victim_acked_hard_writes claim cannot starve on an
                    # unlucky key draw before the SIGKILL lands.
                    key = "k0" if n_burst == 0 else f"k{rng.randrange(6)}"
                    n_burst += 1
                    for id_ in rng.sample(range(40), IDS_PER_BURST):
                        for _ in range(ADDS_PER_ID):
                            with ts_lock:
                                ts_cell[0] += 1
                                ts = 1_000_000 + ts_cell[0]
                            ws.stage(key, (
                                "add",
                                (id_, rng.randrange(1, 1000),
                                 (ci % DCS, ts)),
                            ))
                    t0 = time.monotonic()
                    results = ws.flush()
                    dt = time.monotonic() - t0
                    for out in results:
                        st["results"] += 1
                        st["lat"].append(dt)
                        st["raw"] += int(out.get("raw_ops", 0))
                        st["shipped"] += int(out.get("shipped_ops", 0))
                        if out.get("error") is None:
                            st["ok_t"].append(time.monotonic())
                            st["acked"] += 1
                            lvl = str(out.get("level"))
                            st["levels"][lvl] = (
                                st["levels"].get(lvl, 0) + 1)
                            req = out.get("requested")
                            if req and req != lvl:
                                st["downgrades"] += 1
                            if (out.get("origin") == victim
                                    and lvl in HARD_LEVELS):
                                st["victim_hard"] += 1
                            # Cross-tier read-your-writes: the ack
                            # taught `sess` its (origin, seq); a READ
                            # through the read tier must cover it (or
                            # refuse honestly once the origin is dead
                            # and no survivor cursor reaches it yet).
                            rd = r_read.query(
                                [{"op": "value", "key": 0}],
                                key=out["key"],
                                max_staleness_s=MAX_STALENESS_S,
                                session=sess,
                            )
                            if "peer" in rd and "error" not in rd:
                                st["ryw_ok"] += 1
                            elif (rd.get("error")
                                    == "session_unsatisfiable"):
                                st["ryw_unsat"] += 1
                            elif rd.get("error") == "overloaded":
                                st["ryw_shed"] += 1
                                time.sleep(min(
                                    rd.get("retry_after_ms", 50),
                                    500) / 1e3)
                            else:
                                st["ryw_other"] += 1
                        elif out.get("error") == "overloaded":
                            # Honest shed: back off by the hint.
                            st["shed"] += 1
                            time.sleep(min(
                                out.get("retry_after_ms", 50),
                                500) / 1e3)
                        else:
                            st["unavailable"] += 1
                            if len(st["err_samples"]) < 3:
                                st["err_samples"].append(
                                    str(out.get("detail"))[:200])

            threads = [
                threading.Thread(target=writer, args=(i,), daemon=True)
                for i in range(WRITERS)
            ]
            t_load0 = time.monotonic()
            print("   storm start: steps "
                  + " ".join(f"{m}={_step_of(root, m)}" for m in MEMBERS)
                  + " alive "
                  + " ".join(m for m, p in procs.items()
                             if p.poll() is None))
            for t in threads:
                t.start()

            # Stage the kill mid-load: the hot key's HRW owner dies.
            t_kill = None
            if _wait_step(root, victim, args.kill_at_step, 60.0):
                procs[victim].send_signal(signal.SIGKILL)
                dead.add(victim)
                t_kill = time.monotonic()
                print(f"   SIGKILL -> {victim} (mid-load)")
            else:
                failures.append(
                    f"{victim} never reached step {args.kill_at_step}")
                procs[victim].kill()
                dead.add(victim)

            # Keep the storm running through failover, but stop the
            # writers a couple of steps BEFORE the survivors' final
            # step: a write parked after the last drain would time out
            # as an honest `unavailable`, which this gate forbids.
            survivor = next(m for m in MEMBERS if m != victim)
            deadline = time.time() + 150.0
            stop_at = max(2, args.steps - 3)
            while time.time() < deadline:
                if _step_of(root, survivor) >= stop_at:
                    break
                time.sleep(0.25)
            if t_kill is not None:  # ensure a post-kill observation window
                time.sleep(max(0.0, 2.0 - (time.monotonic() - t_kill)))
            print("   storm stop: steps "
                  + " ".join(f"{m}={_step_of(root, m)}" for m in MEMBERS)
                  + " alive "
                  + " ".join(m for m, p in procs.items()
                             if p.poll() is None))
            stop.set()
            for t in threads:
                t.join(HARD_LATENCY_CEILING_S + 10.0)
            t_load = time.monotonic() - t_load0
            hung_threads = [t for t in threads if t.is_alive()]
            n_load1 = len(obs_events.events())
            write_faults = [
                e for e in faults.trace() if e[0] == "router.write"]
            faults.uninstall()

            # -- reap the fleet --------------------------------------------
            outs = {}
            for m, p in procs.items():
                try:
                    out, _ = p.communicate(timeout=args.worker_timeout)
                    outs[m] = (p.returncode, out)
                except subprocess.TimeoutExpired:
                    p.kill()
                    out, _ = p.communicate()
                    outs[m] = (None, out)
            for m, (rc, out) in outs.items():
                if m != victim and rc != 0:
                    failures.append(f"worker {m} rc={rc}:\n{out}")
            digests = {}
            for path in glob.glob(os.path.join(root, "final-*.json")):
                try:
                    with open(path) as f:
                        doc = json.load(f)
                    digests[doc["member"]] = doc["digest"]
                except (OSError, ValueError, KeyError):
                    continue
            survivors = [m for m in MEMBERS if m != victim]
            converged = sorted(digests) == survivors and len(
                {json.dumps(d, sort_keys=True) for d in digests.values()}
            ) == 1
            if not converged:
                failures.append(
                    "survivors did not all converge to one digest "
                    f"(finals from {sorted(digests)})")

            # -- audit the storm -------------------------------------------
            lat = sorted(x for st in stats for x in st["lat"])
            ok_t = sorted(x for st in stats for x in st["ok_t"])
            acked = sum(st["acked"] for st in stats)
            results_n = sum(st["results"] for st in stats)
            levels: dict = {}
            for st in stats:
                for lvl, n in st["levels"].items():
                    levels[lvl] = levels.get(lvl, 0) + n
            agg = {
                k: sum(st[k] for st in stats)
                for k in ("downgrades", "victim_hard", "shed",
                          "unavailable", "ryw_ok", "ryw_unsat",
                          "ryw_shed", "ryw_other")
            }
            p99_ms = (lat[int(0.99 * (len(lat) - 1))] * 1e3) if lat else None
            max_ms = (lat[-1] * 1e3) if lat else None
            writes_per_sec = acked / max(t_load, 1e-9)
            raw_ops = sum(st["raw"] for st in stats)
            shipped_ops = sum(st["shipped"] for st in stats)
            coalesce = raw_ops / shipped_ops if shipped_ops else 1.0

            # Failover blip: the longest gap between consecutive acked
            # writes in the window around the kill.
            blip_ms = 0.0
            if t_kill is not None and ok_t:
                window = [t_kill - 0.5] + [
                    t for t in ok_t
                    if t_kill - 0.5 <= t <= t_kill + 6.0
                ]
                gaps = [b - a for a, b in zip(window, window[1:])]
                blip_ms = max(gaps) * 1e3 if gaps else (
                    6.5e3)  # no acks in the window at all
            counters = {
                k: int(v)
                for k, v in metrics.snapshot()["counters"].items()
                if k.startswith("router.write")
                or k.startswith("write_session.")
            }
            # -- certify the clean arm, then the negative controls ---------
            clean_evs = obs_events.events()[n_load0:n_load1]
            merged = obs_events.scan_dir(obs_dir)
            merged["client-writes"] = clean_evs
            wcert = certify_writes(
                logs=merged,
                meta={"arm": "honest", "drill": "write_tier_demo",
                      "killed": victim},
            )
            scert = certify_sessions(
                logs={"writer": clean_evs},
                meta={"arm": "cross-tier-ryw",
                      "drill": "write_tier_demo"},
            )
            shed_out, shed_dt_s, shed_returns = _shed_arm()
            bad_cert, bad_outs, unsafe_acks = _violating_arm()
            cx = (bad_cert.get("counterexample") or {}).get(
                "acked_but_lost") or []

            checks = {
                "zero_hung_writes": not hung_threads
                and (max_ms is None
                     or max_ms <= HARD_LATENCY_CEILING_S * 1e3),
                "zero_unavailable": agg["unavailable"] == 0,
                "zero_silent_drops": results_n == len(lat)
                and acked + agg["shed"] + agg["unavailable"] == results_n,
                "writes_ge_min": acked >= args.min_writes,
                "write_p99_under_slo": p99_ms is not None
                and p99_ms <= args.max_p99_ms,
                "failover_blip_bounded": blip_ms <= args.max_blip_ms,
                "hard_ack_levels_exercised":
                    levels.get("durable", 0) > 0
                    and levels.get("replicated_to_k", 0) > 0,
                "victim_acked_hard_writes": agg["victim_hard"] > 0,
                # 4 adds per id, top-2 kept: the steady-state ratio is
                # 2.0; 1.5 tolerates a partial first/last burst.
                "coalesce_ratio_ge": raw_ops > 0 and coalesce >= 1.5,
                "ryw_reads_verified": agg["ryw_ok"] > 0
                and agg["ryw_other"] == 0,
                "retry_hints_honest":
                    shed_out.get("error") == "overloaded"
                    and int(shed_out.get("retry_after_ms", -1)) == 350
                    and shed_dt_s < 5.0 and shed_returns >= 1,
                "write_counters_lit": all(
                    counters.get(k, 0) > 0
                    for k in WRITE_REQUIRED_NONZERO
                ),
                "router_write_faults_fired": len(write_faults) > 0,
                "survivors_converged": converged,
                "writes_certified": bool(wcert.get("ok"))
                and verify_certificate(wcert)
                and wcert.get("n_acks", 0) > 0
                and not (wcert.get("counterexample") or {}).get(
                    "acked_but_lost"),
                "sessions_certified": bool(scert.get("ok"))
                and verify_certificate(scert)
                and scert.get("n_writes", 0) > 0
                and scert.get("n_reads", 0) > 0
                and scert.get("n_violations", 0) == 0,
                "violating_arm_caught": bad_cert.get("ok") is False
                and verify_certificate(bad_cert)
                and all(o.get("level") == "durable" for o in bad_outs)
                and unsafe_acks >= len(bad_outs)
                and any(
                    e.get("origin") == "v0"
                    and e.get("uncovered") == [8, 20]
                    and e.get("lost_write_ids")
                    for e in cx
                ),
            }
            report = {
                "drill": "write_tier_demo",
                "fleet": MEMBERS,
                "killed": victim,
                "writers": WRITERS,
                "load_s": round(t_load, 3),
                "fleet_writes_per_sec": round(writes_per_sec, 3),
                "fleet_ops_per_sec": round(
                    raw_ops / max(t_load, 1e-9), 1),
                "write_p99_ms": None if p99_ms is None
                else round(p99_ms, 3),
                "write_max_ms": None if max_ms is None
                else round(max_ms, 3),
                "failover_blip_ms": round(blip_ms, 3),
                "writes_acked": acked,
                "acks_by_level": dict(sorted(levels.items())),
                "raw_ops": raw_ops,
                "shipped_ops": shipped_ops,
                "coalesce_ratio": round(coalesce, 3),
                "error_samples": [
                    s for st in stats for s in st["err_samples"]][:6],
                "outcomes": agg,
                "write_faults_fired": len(write_faults),
                "counters": dict(sorted(counters.items())),
                "write_certificate": {
                    "ok": wcert.get("ok"),
                    "n_acks": wcert.get("n_acks"),
                    "acks_by_level": wcert.get("acks_by_level"),
                    "origins": wcert.get("origins"),
                },
                "session_certificate": {
                    "ok": scert.get("ok"),
                    "n_sessions": scert.get("n_sessions"),
                    "n_reads": scert.get("n_reads"),
                    "n_writes": scert.get("n_writes"),
                    "n_violations": scert.get("n_violations"),
                },
                "shed_arm": {
                    "error": shed_out.get("error"),
                    "retry_after_ms": shed_out.get("retry_after_ms"),
                    "elapsed_s": round(shed_dt_s, 4),
                },
                "violating_arm": {
                    "ok": bad_cert.get("ok"),
                    "unsafe_acks": unsafe_acks,
                    "counterexample": cx,
                },
                "checks": checks,
                "pass": all(checks.values()) and not failures,
            }
            with open(args.out, "w") as fh:
                json.dump(report, fh, indent=2, sort_keys=True)
                fh.write("\n")
            print(json.dumps(report, indent=2, sort_keys=True))
            if failures:
                print("FAIL:")
                for f in failures:
                    print(f"  - {f}")
                return 1
            if not report["pass"]:
                bad = [k for k, ok in checks.items() if not ok]
                print(f"FAIL: {', '.join(bad)}", file=sys.stderr)
                return 1
            print(
                f"PASS: {acked} write bursts acked "
                f"({raw_ops} staged ops) across {victim}'s SIGKILL "
                f"(p99 {p99_ms:.0f}ms, blip {blip_ms:.0f}ms); "
                f"zero acked-but-lost certified, violating arm "
                f"convicted, sheds honest"
            )
            return 0
        finally:
            faults.uninstall()
            for p in procs.values():
                if p.poll() is None:
                    p.kill()


if __name__ == "__main__":
    raise SystemExit(main())
