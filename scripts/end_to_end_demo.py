"""End-to-end integration of the full stack on one host:

  client ops -> native C++ host (op log, lamport stamping, causal
  exactly-once delivery) -> dense batch drain -> TPU apply (one dispatch
  per round across all replicas) -> Orbax checkpoint / crash / elastic
  resume mid-stream -> lattice reconcile -> observable read
  == scalar reference replay of the identical delivered streams.

Run: python scripts/end_to_end_demo.py          (full sizes)
     pytest tests/test_end_to_end.py            (small sizes, CPU rig)

The scalar states are the semantic ground truth (PARITY.md): each
replica's dense state must observe exactly what the scalar engine computes
from the same causal stream, and after full delivery + reconcile every
replica must converge.
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def run(n_dcs=4, n_ids=512, k=16, m=16, rounds=6, adds_per_round=200,
        rmvs_per_round=20, seed=0, verbose=True):
    import jax
    import jax.numpy as jnp

    from antidote_ccrdt_tpu.harness import native_host as nh
    from antidote_ccrdt_tpu.harness.orbax_ckpt import (
        DenseCheckpointManager,
        available as orbax_available,
    )
    from antidote_ccrdt_tpu.models.topk_rmv import TopkRmvScalar
    from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps, make_dense
    from antidote_ccrdt_tpu.utils.benchtime import sync
    from antidote_ccrdt_tpu.utils.metrics import Metrics

    assert nh.available(), f"native host unavailable: {nh.build_error()}"
    rng = np.random.default_rng(seed)
    D = make_dense(n_ids=n_ids, n_dcs=n_dcs, size=k, slots_per_id=m)
    dense = D.init(n_replicas=n_dcs, n_keys=1)
    scalar_engine = TopkRmvScalar()
    scalar = [scalar_engine.new(k) for _ in range(n_dcs)]
    # Each origin's causal frontier (max ts seen per DC), fed by its drains;
    # removals carry it as their vc — "remove what I have seen".
    frontiers = np.zeros((n_dcs, n_dcs), np.int32)
    m_ = Metrics()

    # A replica drains ops from EVERY origin (its own included), plus any
    # backlog carried over; size one round's worth with slack.
    B = 2 * n_dcs * adds_per_round
    Br = 2 * n_dcs * rmvs_per_round

    apply_jit = jax.jit(
        lambda st, ops: D.apply_ops(st, ops, collect_dominated=False)[0]
    )

    with nh.NativeHost(n_dcs) as host, tempfile.TemporaryDirectory() as tmp:
        ckpt = DenseCheckpointManager(os.path.join(tmp, "ckpt")) \
            if orbax_available() else None
        for rnd in range(rounds):
            # -- clients submit effect ops at every origin ----------------
            for origin in range(n_dcs):
                na = rng.integers(adds_per_round // 2, adds_per_round + 1)
                host.submit_batch(
                    origin,
                    kinds=np.full(na, nh.KIND_ADD, np.int32),
                    keys=np.zeros(na, np.int32),
                    ids=rng.integers(0, n_ids, na),
                    scores=rng.integers(1, 10_000, na),
                )
                m_.count("submitted_adds", int(na))
                for _ in range(int(rng.integers(0, rmvs_per_round + 1))):
                    host.submit(
                        origin, nh.KIND_RMV, key=0,
                        id_=int(rng.integers(0, n_ids)),
                        vc=frontiers[origin],
                    )
                    m_.count("submitted_rmvs", 1)

            # -- drain causally-ready batches, apply on device ------------
            batches = []
            for r in range(n_dcs):
                ops, na, nr = host.drain_topk_rmv_ops(r, B, Br)
                batches.append(ops)
                m_.count("delivered", na + nr)
                # scalar ground truth consumes the SAME delivered stream
                # (one bulk device_get: per-element reads would each pay a
                # full device->host round trip on tunneled backends)
                o = jax.device_get(ops)
                for j in range(B):
                    if o.add_ts[0, j] > 0:
                        dc, ts = int(o.add_dc[0, j]), int(o.add_ts[0, j])
                        eff = ("add", (int(o.add_id[0, j]),
                                       int(o.add_score[0, j]), (dc, ts)))
                        scalar[r], _ = scalar_engine.update(eff, scalar[r])
                        frontiers[r, dc] = max(frontiers[r, dc], ts)
                for j in range(Br):
                    if int(o.rmv_id[0, j]) >= 0:
                        vc = {d: int(v) for d, v in
                              enumerate(o.rmv_vc[0, j]) if v}
                        eff = ("rmv", (int(o.rmv_id[0, j]), vc))
                        scalar[r], _ = scalar_engine.update(eff, scalar[r])
            stacked = TopkRmvOps(*[
                jnp.concatenate([getattr(b, f) for b in batches], axis=0)
                for f in TopkRmvOps.__dataclass_fields__
            ])
            with m_.timer("apply"):
                dense = apply_jit(dense, stacked)
                sync(dense)  # honest device time (benchtime rule #1)

            # -- mid-stream crash + elastic resume ------------------------
            if ckpt is not None and rnd == rounds // 2:
                ckpt.save(rnd, dense)
                dense = None  # "crash"
                like = jax.tree.map(
                    jnp.zeros_like, D.init(n_replicas=n_dcs, n_keys=1)
                )
                dense = ckpt.restore(like)
                m_.count("resumes", 1)

        # -- per-replica ground-truth check before reconcile --------------
        # The exact-parity claim only holds for unflagged states (the dense
        # engine's capacity contract): demand it loudly so a config change
        # that overflows slot capacity fails HERE, not as a puzzling
        # value mismatch below.
        assert not bool(jax.device_get(dense.lossy).any()), (
            "slot capacity overflow (lossy set): raise slots_per_id `m` "
            "for this workload before comparing against the scalar engine"
        )
        for r in range(n_dcs):
            got = D.value(dense)[r][0]
            want = scalar_engine.value(scalar[r])
            assert set(got) == set(want), (r, got[:4], sorted(want)[:4])

        # -- inter-DC reconcile: fold the lattice join over replicas ------
        with m_.timer("reconcile"):
            acc = jax.tree.map(lambda a: a[:1], dense)
            for r in range(1, n_dcs):
                acc = D.merge(acc, jax.tree.map(lambda a: a[r:r+1], dense))
            sync(acc)
        joined = set(D.value(acc)[0][0])
        m_.count("joined_observable", len(joined))

        if verbose:
            print("metrics:", m_.summary())
            print(f"joined top-{k}:", sorted(joined, key=lambda p: -p[1])[:5])
        backlogs = [host.backlog(r) for r in range(n_dcs)]
        if ckpt is not None:
            ckpt.close()
    return {
        "per_replica_match": True,
        "joined_size": len(joined),
        "resumed": ckpt is not None,
        "backlogs": backlogs,
        "metrics": m_.summary(),
    }


if __name__ == "__main__":
    out = run()
    print("END-TO-END-OK", {k: v for k, v in out.items() if k != "metrics"})
