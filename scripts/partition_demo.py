"""Partition-plane acceptance drill (core/partition.py tentpole gate).

Three workers gossip the topk_rmv grid over real TCP sockets
(net/tcp.py) with the partition plane on: every full anchor publishes
the P+1-entry digest vector plus per-partition psnaps, and every gap
repair goes through `PartialAntiEntropy` (parallel/elastic.py).

The drill manufactures exactly ONE divergent partition: during an
outage window, worker w2 stops gossiping (publish + sweep) while every
replica's ops are confined to ids that hash into a single partition
`p*`. When w2 comes back its delta chains have been pruned, so the
classic path would pull each peer's WHOLE snapshot; the partition path
compares digest vectors, sees divergence only on {p*, meta}, and
fetches just those psnaps.

Both repairs are run on the same pre-resync state and compared:

* bytes:  whole-instance snapshot blobs vs digest vector + fetched
  psnaps — the gate requires the partial path to move >= 5x fewer
  bytes;
* result: the post-repair per-partition digest vectors must be
  BIT-IDENTICAL between the two paths (partial resync is a pure
  bandwidth optimization, never a semantic one);
* fleet:  after the remaining steps + a convergence tail, all three
  workers' digest vectors agree and the observable top-k matches the
  sequential single-process reference bit-for-bit.

Writes the measurements to PART_r01.json (committed as the carrier for
regression comparison) and exits nonzero if any gate fails.

Run:  make partition-demo
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scripts.cover import install_child_cover  # noqa: E402

install_child_cover()  # no-op outside `make cover` runs

# Drill geometry. I is deliberately larger than elastic_demo's so one
# partition holds a meaningful slice (~I/P ids) and the byte comparison
# is not dominated by fixed per-blob overheads.
R, NK, I, DCS, K, M, B, Br = 4, 1, 256, 4, 8, 2, 32, 8
STEPS = 12
# Steps in [OUTAGE_LO, OUTAGE_HI): w2 neither publishes nor sweeps, and
# every replica's ops touch only ids from partition p* — the window
# that manufactures the single divergent partition.
OUTAGE_LO, OUTAGE_HI = 4, 9

MIN_RATIO = 5.0  # the acceptance gate from ISSUE/ROADMAP


def _build():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense

    return make_dense(n_ids=I, n_dcs=DCS, size=K, slots_per_id=M)


def gen_ops(step: int, owned, pool):
    """Deterministic [R, ...] batch like elastic_demo's drill, except
    add/rmv ids are drawn from `pool` (all ids normally, the single
    partition p*'s ids inside the outage window)."""
    import jax.numpy as jnp
    import numpy as np

    from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps

    owned = set(owned)
    pool = np.asarray(pool, np.int32)
    a_key = np.zeros((R, B), np.int32)
    a_id = np.zeros((R, B), np.int32)
    a_score = np.zeros((R, B), np.int32)
    a_dc = np.zeros((R, B), np.int32)
    a_ts = np.zeros((R, B), np.int32)
    r_key = np.zeros((R, Br), np.int32)
    r_id = np.full((R, Br), -1, np.int32)
    r_vc = np.zeros((R, Br, DCS), np.int32)
    for r in range(R):
        rng = np.random.default_rng(77_000 * (step + 1) + r)
        ids = pool[rng.integers(0, len(pool), B)]
        scores = rng.integers(1, 500, B)
        if r in owned:
            a_id[r], a_score[r] = ids, scores
            a_dc[r] = r % DCS
            a_ts[r] = step * B + np.arange(B) + 1
            r_id[r] = pool[rng.integers(0, len(pool), Br)]
            r_vc[r, :, r % DCS] = rng.integers(1, max(2, step * B + 1), Br)
    return TopkRmvOps(
        add_key=jnp.asarray(a_key), add_id=jnp.asarray(a_id),
        add_score=jnp.asarray(a_score), add_dc=jnp.asarray(a_dc),
        add_ts=jnp.asarray(a_ts),
        rmv_key=jnp.asarray(r_key), rmv_id=jnp.asarray(r_id),
        rmv_vc=jnp.asarray(r_vc),
    )


def step_pool(step: int, ids_p):
    import numpy as np

    if OUTAGE_LO <= step < OUTAGE_HI:
        return ids_p
    return np.arange(I, dtype=np.int32)


def apply_step(dense, state, step: int, owned, ids_p):
    state, _ = dense.apply_ops(
        state, gen_ops(step, owned, step_pool(step, ids_p)),
        collect_dominated=False,
    )
    return state


def observable(dense, state):
    from antidote_ccrdt_tpu.harness.dense_replay import fold_rows

    obs = dense.value(fold_rows(dense, state, range(R)))[0][0]
    return sorted((int(i), int(s)) for (i, s) in obs)


def sequential_reference(dense, ids_p):
    state = dense.init(R, NK)
    for step in range(STEPS):
        state = apply_step(dense, state, step, range(R), ids_p)
    return observable(dense, state)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--partitions", type=int, default=8)
    ap.add_argument(
        "--out",
        default=os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "PART_r01.json",
        ),
    )
    args = ap.parse_args()
    P = args.partitions

    import numpy as np

    from antidote_ccrdt_tpu.core import partition as pt
    from antidote_ccrdt_tpu.net.tcp import TcpTransport
    from antidote_ccrdt_tpu.net.transport import GossipNode
    from antidote_ccrdt_tpu.parallel.elastic import (
        DeltaPublisher, PartialAntiEntropy, sweep_deltas,
    )

    dense = _build()

    # Pick p* = the best-populated partition; its id roster is the op
    # pool inside the outage window.
    part_map = pt.part_of(np.arange(I), P)
    p_star = int(np.bincount(part_map, minlength=P).argmax())
    ids_p = np.arange(I, dtype=np.int32)[part_map == p_star]
    meta = pt.meta_part(P)

    members = ["w0", "w1", "w2"]
    owned = {"w0": [0, 3], "w1": [1], "w2": [2]}
    transports = {m: TcpTransport(m) for m in members}
    try:
        for m in members:
            for n in members:
                if n != m:
                    transports[m].add_peer(n, transports[n].address)
        stores = {m: GossipNode(transports[m]) for m in members}
        pubs = {
            m: DeltaPublisher(
                stores[m], dense, name="topk_rmv",
                full_every=2, keep=2, partitions=P,
            )
            for m in members
        }
        partials = {
            m: PartialAntiEntropy(stores[m], partitions=P, max_tries=12)
            for m in members
        }
        states = {m: dense.init(R, NK) for m in members}
        cursors = {m: {} for m in members}

        # Start barrier: TCP membership is heard-from evidence.
        deadline = time.time() + 10.0
        while any(len(stores[m].members()) < len(members) for m in members):
            for m in members:
                stores[m].heartbeat()
            if time.time() > deadline:
                print("FAIL: start barrier timed out", file=sys.stderr)
                return 1
            time.sleep(0.05)

        def round_of(step, fleet):
            for m in fleet:
                stores[m].heartbeat()
                pubs[m].publish(states[m])
            time.sleep(0.06)
            for m in fleet:
                states[m], _ = sweep_deltas(
                    stores[m], dense, states[m], cursors[m],
                    partial=partials[m],
                )

        # Phase 1: steps up to the end of the outage. w2 applies its own
        # ops every step (it is slow, not dead) but stops gossiping.
        for step in range(OUTAGE_HI):
            for m in members:
                states[m] = apply_step(dense, states[m], step, owned[m], ids_p)
            fleet = members if step < OUTAGE_LO else ["w0", "w1"]
            round_of(step, fleet)

        # Phase 2: the resync moment. w2's delta chains were pruned
        # (keep=2), so both repair paths start from the same gap. Run the
        # whole-instance repair on a clone for the byte/digest baseline,
        # then the partial repair on the live state.
        pre_state = states["w2"]
        peers = ["w0", "w1"]

        whole_bytes = 0
        whole_state = pre_state
        for m in peers:
            raw = transports["w2"].fetch(m)
            if raw is None:
                print(f"FAIL: no snapshot from {m} at resync", file=sys.stderr)
                return 1
            whole_bytes += len(raw)
            got = stores["w2"].fetch(m, pre_state, dense=dense)
            if got is None:
                print(f"FAIL: snapshot from {m} undecodable", file=sys.stderr)
                return 1
            whole_state = dense.merge(whole_state, got[1])

        c0 = dict(stores["w2"].metrics.counters)
        dig_bytes = 0
        div_seen = set()
        part_state = pre_state
        for m in peers:
            raw = transports["w2"].fetch_digest(m)
            if raw is not None:
                dig_bytes += len(raw)
            got = stores["w2"].fetch_digests(m)
            if got is not None:
                div_seen.update(
                    int(p) for p in pt.divergent_parts(
                        pt.state_digests(part_state, P), got[1]
                    )
                )
            cur = cursors["w2"].get(m, -1)
            for _ in range(40):
                part_state, cur2, handled = partials["w2"].try_resync(
                    m, dense, part_state, cur
                )
                if not handled:
                    print(
                        f"FAIL: partial resync fell back to full snap ({m})",
                        file=sys.stderr,
                    )
                    return 1
                if cur2 > cur:
                    cur = cur2
                    break
                time.sleep(0.05)  # psnap replies in flight
            else:
                print(f"FAIL: partial resync stalled ({m})", file=sys.stderr)
                return 1
            cursors["w2"][m] = cur
        c1 = dict(stores["w2"].metrics.counters)
        psnap_bytes = int(c1.get("net.psnap_bytes", 0) - c0.get("net.psnap_bytes", 0))
        partial_bytes = psnap_bytes + dig_bytes
        resyncs = int(
            c1.get("net.partition_resyncs", 0) - c0.get("net.partition_resyncs", 0)
        )
        wasted = int(c1.get("net.psnap_wasted", 0))

        vec_whole = pt.state_digests(whole_state, P)
        vec_part = pt.state_digests(part_state, P)
        repair_identical = bool(np.array_equal(vec_whole, vec_part))
        states["w2"] = part_state

        # Phase 3: remaining steps with everyone gossiping, then a
        # convergence tail until the digest vectors agree fleet-wide.
        for step in range(OUTAGE_HI, STEPS):
            for m in members:
                states[m] = apply_step(dense, states[m], step, owned[m], ids_p)
            round_of(step, members)
        agree = False
        for _ in range(80):
            vecs = [pt.state_digests(states[m], P) for m in members]
            if all(np.array_equal(vecs[0], v) for v in vecs[1:]):
                agree = True
                break
            round_of(STEPS, members)

        ref = sequential_reference(dense, ids_p)
        finals = {m: observable(dense, states[m]) for m in members}
        ref_match = all(finals[m] == ref for m in members)
        ratio = whole_bytes / max(1, partial_bytes)

        checks = {
            "partial_ge_5x_smaller": ratio >= MIN_RATIO,
            "repair_digests_bit_identical": repair_identical,
            "fleet_digest_vectors_agree": agree,
            "matches_sequential_reference": ref_match,
            "divergence_confined_to_pstar_meta": div_seen <= {p_star, meta}
            and p_star in div_seen,
            "partition_resyncs_counted": resyncs >= 1,
            "no_wasted_psnaps": wasted == 0,
        }
        report = {
            "drill": "partition_demo",
            "geometry": {
                "R": R, "NK": NK, "I": I, "DCS": DCS, "K": K, "M": M,
                "B": B, "Br": Br, "steps": STEPS,
            },
            "partitions": P,
            "p_star": p_star,
            "p_star_ids": int(len(ids_p)),
            "outage_steps": [OUTAGE_LO, OUTAGE_HI],
            "divergent_parts": sorted(div_seen),
            "whole_resync_bytes": whole_bytes,
            "partial_resync_bytes": {
                "psnaps": psnap_bytes, "digests": dig_bytes,
                "total": partial_bytes,
            },
            "bytes_ratio": round(ratio, 3),
            "min_ratio": MIN_RATIO,
            "counters_w2": {
                k: int(v)
                for k, v in sorted(stores["w2"].metrics.counters.items())
                if k.startswith(("net.psnap", "net.partition", "net.dig"))
            },
            "checks": checks,
            "pass": all(checks.values()),
        }
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(json.dumps(report, indent=2, sort_keys=True))
        if not report["pass"]:
            failed = [k for k, ok in checks.items() if not ok]
            print(f"FAIL: {', '.join(failed)}", file=sys.stderr)
            return 1
        print(
            f"PASS: partial anti-entropy moved {partial_bytes} bytes vs "
            f"{whole_bytes} whole-instance ({ratio:.1f}x reduction), "
            f"digests bit-identical"
        )
        return 0
    finally:
        for t in transports.values():
            t.close()


if __name__ == "__main__":
    raise SystemExit(main())
