"""Request-trace CLI over the rtrace plane (obs/rtrace.py).

Three subcommands over a finished run's obs spill dir (the
``flight-*.jsonl`` streams every worker and client drops on exit —
each committed trace rides a ``rtrace.trace`` event verbatim)::

    # Render one request's waterfall: every client hop (route decision,
    # attempt launch->settle, backoff, ack probe) and every server
    # stage (enqueue->drain->kernel fold / stage->fold->durable) as
    # ordered [t0_ms, t1_ms] segments on the request's own timeline.
    # Picks the slowest stored trace unless --trace names one.
    python scripts/ccrdt_rtrace.py waterfall /path/to/obs-dir \
        --trace w0-1a2b-3

    # Fleet-level tail attribution: decompose completed requests into
    # route / backoff / wire / queue_wait / kernel / ack_probe /
    # hedge_overlap milliseconds at p50 and p99, and name the p99
    # request's dominant bucket — "where did the tail go".
    python scripts/ccrdt_rtrace.py attribute /path/to/obs-dir --json

    # The N slowest stored traces (slow ring + sampled commits), one
    # line each: id, kind, outcome, total ms, hop count, completeness.
    python scripts/ccrdt_rtrace.py slowest /path/to/obs-dir -n 10

Offline scans have no live ClockSync, so server stages are anchored on
each attempt's midpoint (the same fallback the in-process waterfall
uses before the first offset sample); client-side hops are exact.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from antidote_ccrdt_tpu.obs import rtrace  # noqa: E402


def _load(obs_dir: str) -> List[Dict[str, Any]]:
    trs = rtrace.scan_traces(obs_dir)
    if not trs:
        print(f"no stored traces under {obs_dir}", file=sys.stderr)
        raise SystemExit(1)
    # One request can commit on the client AND spill through a slow
    # ring re-emit; keep the last doc per id (most hops absorbed).
    by_id: Dict[str, Dict[str, Any]] = {}
    for t in trs:
        by_id[str(t.get("id"))] = t
    return list(by_id.values())


def _fmt_waterfall(tr: Dict[str, Any]) -> str:
    rows = rtrace.waterfall(tr, offs={})
    ok, why = rtrace.complete(tr)
    end = max((r["t1_ms"] for r in rows), default=0.0)
    span = max(end, float(tr.get("ms", 0.0)), 1e-9)
    width = 40
    lines = [
        f"trace {tr.get('id')}  kind={tr.get('kind')} "
        f"key={tr.get('key')!r} outcome={tr.get('outcome')} "
        f"total={float(tr.get('ms', 0.0)):.3f}ms "
        f"{'complete' if ok else 'INCOMPLETE: ' + why}"
    ]
    for r in rows:
        a, b = r["t0_ms"], r["t1_ms"]
        lo = max(0, min(width - 1, int(a / span * width)))
        hi = max(lo + 1, min(width, int(b / span * width) + 1))
        bar = " " * lo + "#" * (hi - lo) + " " * (width - hi)
        extra = " ".join(
            f"{k}={v}" for k, v in r.items()
            if k not in ("name", "t0_ms", "t1_ms") and not isinstance(
                v, (list, dict))
        )
        lines.append(
            f"  {r['name']:<12} |{bar}| {a:>9.3f} -> {b:>9.3f}ms  {extra}"
        )
    return "\n".join(lines)


def cmd_waterfall(args: argparse.Namespace) -> int:
    trs = _load(args.obs_dir)
    if args.trace:
        match = [t for t in trs if t.get("id") == args.trace]
        if not match:
            print(f"trace {args.trace!r} not found "
                  f"({len(trs)} stored)", file=sys.stderr)
            return 1
        tr = match[0]
    else:
        tr = max(trs, key=lambda t: float(t.get("ms", 0.0)))
    if args.json:
        print(rtrace.to_json(
            {"trace": tr, "waterfall": rtrace.waterfall(tr, offs={})}
        ))
    else:
        print(_fmt_waterfall(tr))
    return 0


def cmd_attribute(args: argparse.Namespace) -> int:
    trs = _load(args.obs_dir)
    rep = rtrace.attribution_report(trs, offs={})
    if args.json:
        print(rtrace.to_json(rep))
    else:
        print(rtrace.format_report(rep))
    return 0


def cmd_slowest(args: argparse.Namespace) -> int:
    trs = _load(args.obs_dir)
    trs.sort(key=lambda t: float(t.get("ms", 0.0)), reverse=True)
    picked = trs[: args.n]
    if args.json:
        print(json.dumps(picked, indent=2))
        return 0
    for t in picked:
        ok, why = rtrace.complete(t)
        attr = rtrace.attribute(t, offs={})
        dom = max(
            (b for b in rtrace.BUCKETS if b != "hedge_overlap"),
            key=lambda b: attr.get(b, 0.0),
        )
        print(
            f"{float(t.get('ms', 0.0)):>10.3f}ms  {t.get('id'):<24} "
            f"{t.get('kind'):<5} {str(t.get('outcome')):<9} "
            f"hops={len(t.get('hops', ()))} "
            f"dominant={dom}:{attr.get(dom, 0.0):.3f}ms "
            f"{'' if ok else '[incomplete: ' + why + ']'}"
        )
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="ccrdt_rtrace", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("waterfall", help="render one request's waterfall")
    p.add_argument("obs_dir")
    p.add_argument("--trace", help="trace id (default: the slowest)")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_waterfall)

    p = sub.add_parser("attribute", help="fleet tail-attribution report")
    p.add_argument("obs_dir")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_attribute)

    p = sub.add_parser("slowest", help="the N slowest stored traces")
    p.add_argument("obs_dir")
    p.add_argument("-n", type=int, default=10)
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_slowest)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
