"""Request-tracing acceptance drill (obs/rtrace.py tentpole gate).

Four real worker processes (scripts/net_gossip_demo.py, CCRDT_SERVE=1)
serve the topk_rmv drill over TCP under seeded chaos (tcp.send drops +
serve.query stalls inside the workers, router.route drops in the
supervisor) while traced client threads — one of them hedging — route
batched reads through a `serve.FleetRouter` with the rtrace plane
armed at sample=1.0. One serving worker is SIGKILLed mid-load while a
probe request is held in flight at it, so the SWIM flip lands as a
``dead_reroute`` hop inside a stored waterfall. The gate holds the
tracing plane to its whole contract at once:

* **gap-free waterfalls** — every sampled completed request in the
  trace ring reassembles end-to-end (dense hop sequence, route
  decision, winning attempt, server echo) on the ClockSync-aligned
  timeline; zero orphan hops tolerated beyond 1%;
* **attribution** — the route / backoff / wire / queue_wait / kernel /
  ack_probe buckets sum to >= 90% of client-observed latency at the
  median AND at the p99 request — latency the plane cannot explain is
  latency nobody can fix;
* **exemplars** — the OpenMetrics exemplar on the read-latency
  histogram resolves to a real stored trace whose dominant bucket the
  report names (the scrape-to-trace pivot actually pivots);
* **failover evidence** — the mid-load SIGKILL renders as a
  ``dead_reroute`` hop in a stored trace and the post-kill success gap
  stays bounded;
* **overhead** — sampled-on tracing costs <= 5% of serve reads/sec
  against this same fleet's own ``CCRDT_RTRACE=0`` kill-switch windows
  (interleaved on/off measurement, same router, same workers).

Writes the measurements to RTRACE_r01.json (committed as the carrier
scripts/bench_gate.py `evaluate_rtrace` regresses overhead and
attribution coverage against) and exits nonzero if any gate fails.

Run:  make rtrace-demo
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from scripts.cover import install_child_cover  # noqa: E402

install_child_cover()  # no-op outside `make cover` runs

DEMO = os.path.join(REPO, "scripts", "net_gossip_demo.py")

MEMBERS = ["w0", "w1", "w2", "w3"]
CLIENTS = 3           # client 2 runs the forced-hedge router
QUERY_BATCH = 8
MAX_STALENESS_S = 5.0
HARD_LATENCY_CEILING_S = 10.0

# Worker-side chaos (rides CCRDT_FAULTS into every worker).
WORKER_FAULTS = {
    "tcp.send": [{"action": "drop", "rate": 0.02}],
    "serve.query": [{"action": "delay", "rate": 0.01, "delay_s": 0.002}],
}
# Supervisor-side chaos: the router's own fault point.
ROUTER_FAULTS = {"router.route": [{"action": "drop", "rate": 0.03}]}


def _spawn_fleet(root: str, obs_dir: str, args) -> dict:
    from antidote_ccrdt_tpu.utils import faults as faults_mod

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["CCRDT_OBS_DIR"] = obs_dir
    env["CCRDT_SERVE"] = "1"
    # Workers echo server-side hop timings for any traced request
    # (server_trace is stateless), but arming their planes exercises
    # the install_from_env propagation path and lights their obs-
    # <member>.json rtrace block for the dashboard column.
    env["CCRDT_RTRACE"] = "1"
    env["CCRDT_FAULTS"] = faults_mod.plan_to_env(WORKER_FAULTS, seed=11)
    # Survivors linger serving after their final barrier so the
    # overhead A/B runs against a QUIESCED fleet (no stepping, no
    # per-step recompiles); the supervisor drops <root>/serve-stop to
    # release them.
    env["CCRDT_SERVE_LINGER_S"] = "60"
    procs = {}
    for member in MEMBERS:
        cmd = [
            sys.executable, DEMO, "--root", root, "--member", member,
            "--n-members", str(len(MEMBERS)), "--type", "topk_rmv",
            "--delta", "--publish-every", "1",
            "--timeout", str(args.timeout),
            "--step-sleep", str(args.step_sleep),
            "--steps", str(args.steps),
        ]
        procs[member] = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, text=True,
        )
    return procs


def _wait_addrs(root: str, timeout: float) -> dict:
    deadline = time.time() + timeout
    while time.time() < deadline:
        addrs = {}
        for m in MEMBERS:
            try:
                with open(os.path.join(root, f"addr-{m}")) as f:
                    hostport = f.read().split()[0]
                host, port = hostport.rsplit(":", 1)
                addrs[m] = (host, int(port))
            except (OSError, ValueError, IndexError):
                break
        if len(addrs) == len(MEMBERS):
            return addrs
        time.sleep(0.05)
    raise RuntimeError("workers never published their addresses")


def _step_of(root: str, member: str) -> int:
    try:
        with open(os.path.join(root, f"obs-{member}.json")) as f:
            return int(json.load(f).get("step", -1))
    except (OSError, ValueError):
        return -1


def _wait_step(root: str, member: str, step: int, timeout: float) -> bool:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if _step_of(root, member) >= step:
            return True
        time.sleep(0.05)
    return False


def _drop_router_status(root: str, router, rtrace_mod) -> None:
    """obs-router.json: the dashboard's router + rtrace column feeds,
    same atomic-replace convention as the workers' obs-<member>.json."""
    doc = {
        "member": "router", "t": time.time(), "router": router.status(),
        "rtrace": rtrace_mod.counters(),
    }
    path = os.path.join(root, "obs-router.json")
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
    except OSError:
        pass


def _measure_overhead(router, rtrace_mod, seconds: float) -> tuple:
    """Paired-difference overhead measurement on the live fleet.

    A sequential off-window/on-window split bills the fleet's drift
    over the window (state growth, JIT recompiles, gossip load) to
    whichever arm ran second, and even per-request interleaving with
    per-ARM medians wobbles by whole percents between runs: the fleet's
    latency is regime-shaped (a recompile or gossip storm parks it
    hundreds of µs higher for stretches), and each arm's median moves
    with the regime mix it happened to draw. So instead:

    * requests run in kill-switch/traced PAIRS ~5 ms apart — both
      members of a pair land in the same regime, so their difference
      cancels the regime level;
    * the order within each pair alternates (off,on then on,off), so
      monotone drift inside a regime cancels across pairs instead of
      always charging the second slot;
    * pairs where EITHER slot landed in a stall (beyond 1.5x its own
      arm's median) are dropped before estimating — symmetrically, so
      the trim is unbiased: dropping only control-arm stalls would
      remove the negative outliers while keeping the positive ones and
      inflate the contrast;
    * the estimate is the MEDIAN of the surviving (calm, calm) paired
      deltas: the plane's fixed per-request cost measured in the calm
      regime, which is what the budget is about.

    Returns (on_reads_per_sec, off_reads_per_sec) built from the
    off-arm median latency and the paired-delta median on top of it."""
    import random

    rng = random.Random(1000)
    pairs = []  # (off_s, on_s)
    flip = False
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        flip = not flip
        pair = {}
        for armed in ((False, True) if flip else (True, False)):
            if armed:
                os.environ.pop(rtrace_mod.ENV, None)
                rtrace_mod.install("router", sample=1.0, metrics=None)
            else:
                os.environ[rtrace_mod.ENV] = "0"   # the kill switch
                rtrace_mod.uninstall()
            t0 = time.monotonic()
            out = router.query(
                [{"op": "value", "key": 0} for _ in range(QUERY_BATCH)],
                key=f"k{rng.randrange(32)}",
                max_staleness_s=MAX_STALENESS_S,
            )
            dt = time.monotonic() - t0
            if "error" not in out:
                pair[armed] = dt
        if len(pair) == 2:
            pairs.append((pair[False], pair[True]))
    rtrace_mod.uninstall()
    os.environ.pop(rtrace_mod.ENV, None)

    if not pairs:
        return 0.0, 0.0
    offs = sorted(p[0] for p in pairs)
    ons = sorted(p[1] for p in pairs)
    off_med = offs[len(offs) // 2]
    on_med = ons[len(ons) // 2]
    calm = [p for p in pairs
            if p[0] <= 1.5 * off_med and p[1] <= 1.5 * on_med] or pairs
    deltas = sorted(p[1] - p[0] for p in calm)
    calm_offs = sorted(p[0] for p in calm)
    off_med = calm_offs[len(calm_offs) // 2]
    delta_med = deltas[len(deltas) // 2]
    off_rps = QUERY_BATCH / max(off_med, 1e-9)
    on_rps = QUERY_BATCH / max(off_med + max(delta_med, 0.0), 1e-9)
    return on_rps, off_rps


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=os.path.join(REPO, "RTRACE_r01.json"))
    ap.add_argument("--timeout", type=float, default=0.5,
                    help="worker SWIM timeout")
    ap.add_argument("--step-sleep", type=float, default=1.4,
                    help="worker inter-step idle: big enough that the "
                         "serve path sees calm stretches between the "
                         "per-step JIT recompiles the growing topk "
                         "state forces")
    ap.add_argument("--steps", type=int, default=14,
                    help="worker step count (sets the serving window)")
    ap.add_argument("--overhead-window-s", type=float, default=6.0,
                    help="total per-request-interleaved overhead window")
    ap.add_argument("--storm-prekill-s", type=float, default=2.0)
    ap.add_argument("--storm-postkill-s", type=float, default=4.0)
    ap.add_argument("--max-overhead-pct", type=float, default=5.0)
    ap.add_argument("--min-coverage", type=float, default=0.9)
    ap.add_argument("--min-complete-frac", type=float, default=0.99)
    ap.add_argument("--max-blip-ms", type=float, default=5000.0)
    ap.add_argument("--worker-timeout", type=float, default=240.0)
    args = ap.parse_args()

    import random

    from antidote_ccrdt_tpu.net.tcp import query_peer
    from antidote_ccrdt_tpu.obs import events as obs_events
    from antidote_ccrdt_tpu.obs import export as obs_export
    from antidote_ccrdt_tpu.obs import rtrace
    from antidote_ccrdt_tpu.serve import (
        ClientSession, FleetRouter, request_bytes, tcp_query_fn,
    )
    from antidote_ccrdt_tpu.topo import rendezvous_order
    from antidote_ccrdt_tpu.utils import faults
    from antidote_ccrdt_tpu.utils.metrics import Metrics

    obs_events.reset("router")
    os.environ.pop(rtrace.ENV, None)  # a stale kill switch would void the drill

    failures = []
    victim = rendezvous_order("k0", MEMBERS)[0]
    dead: set = set()
    metrics = Metrics()

    with tempfile.TemporaryDirectory(prefix="rtrace-") as tmp:
        root = os.path.join(tmp, "fleet")
        obs_dir = os.path.join(tmp, "obs")
        os.makedirs(root)
        print(f"== rtrace drill: {len(MEMBERS)}-worker TCP fleet, "
              f"SIGKILL {victim} mid-load, sample=1.0 ==")
        procs = _spawn_fleet(root, obs_dir, args)
        try:
            addrs = _wait_addrs(root, 60.0)
            for m in MEMBERS:
                if not _wait_step(root, m, 1, 120.0):
                    raise RuntimeError(f"{m} never reached step 1")

            # Warm every worker's serve path concurrently (first query
            # pays the fold/value JIT).
            warm_errs: list = []

            def _warm(m: str) -> None:
                try:
                    query_peer(addrs[m],
                               request_bytes([{"op": "value", "key": 0}]),
                               timeout=30.0)
                except Exception as e:  # noqa: BLE001 — gate below
                    warm_errs.append(f"{m}: {e}")

            warmers = [threading.Thread(target=_warm, args=(m,), daemon=True)
                       for m in MEMBERS]
            for t in warmers:
                t.start()
            for t in warmers:
                t.join(60.0)
            if warm_errs:
                raise RuntimeError(
                    f"serve warm-up failed: {'; '.join(warm_errs)}")

            def verdict(p: str) -> str:
                return "dead" if p in dead else "alive"

            # -- the traced chaos storm --------------------------------------
            rtrace.install("router", sample=1.0, ring=1 << 14,
                           metrics=metrics)
            faults.install(ROUTER_FAULTS, seed=7)
            r_main = FleetRouter(
                MEMBERS, tcp_query_fn(addrs), metrics=metrics,
                verdict_fn=verdict, hedge=False, timeout_s=0.6,
                retries=3, backoff_base_s=0.02, session_wait_s=0.5,
                session_poll_s=0.05, poll_s=0.002, seed=1,
                breaker_failures=6,
            )
            r_hedge = FleetRouter(
                MEMBERS, tcp_query_fn(addrs), metrics=metrics,
                verdict_fn=verdict, hedge=True, hedge_after_s=0.001,
                timeout_s=0.6, retries=3, backoff_base_s=0.02,
                session_wait_s=0.5, session_poll_s=0.05, poll_s=0.002,
                seed=2, breaker_failures=6,
            )

            stop = threading.Event()
            stats = [
                {"lat": [], "ok_t": [], "reads": 0, "unavailable": 0,
                 "shed": 0, "unsatisfiable": 0, "resets": 0}
                for _ in range(CLIENTS)
            ]

            def client(ci: int) -> None:
                rng = random.Random(100 + ci)
                router = r_hedge if ci == CLIENTS - 1 else r_main
                sess = ClientSession(f"demo-c{ci}-0")
                st = stats[ci]
                while not stop.is_set():
                    qs = []
                    for _ in range(QUERY_BATCH):
                        pick = rng.random()
                        if pick < 0.7:
                            qs.append({"op": "value", "key": 0})
                        elif pick < 0.9:
                            qs.append({"op": "topk", "key": 0, "k": 5})
                        else:
                            qs.append({"op": "range", "key": 0,
                                       "lo": 100, "hi": 400})
                    use_sess = rng.random() < 0.8
                    t0 = time.monotonic()
                    out = router.query(
                        qs, key=f"k{rng.randrange(32)}",
                        max_staleness_s=MAX_STALENESS_S,
                        session=sess if use_sess else None,
                    )
                    st["lat"].append(time.monotonic() - t0)
                    if "peer" in out and "error" not in out:
                        st["ok_t"].append(time.monotonic())
                        st["reads"] += sum(
                            1 for r in out.get("results", [])
                            if "error" not in r
                        )
                        wm = out.get("watermarks") or {}
                        m = out.get("member")
                        if (rng.random() < 0.05 and m and m != victim
                                and m in wm):
                            sess.note_write(m, int(wm[m]))
                    elif out.get("error") == "session_unsatisfiable":
                        st["unsatisfiable"] += 1
                        st["resets"] += 1
                        sess = ClientSession(f"demo-c{ci}-{st['resets']}")
                    elif out.get("error") == "overloaded":
                        st["shed"] += 1
                        time.sleep(out.get("retry_after_ms", 50) / 1e3)
                    else:
                        st["unavailable"] += 1

            threads = [threading.Thread(target=client, args=(i,),
                                        daemon=True)
                       for i in range(CLIENTS)]
            t_load0 = time.monotonic()
            for t in threads:
                t.start()
            time.sleep(args.storm_prekill_s)

            # -- the staged dead_reroute + SIGKILL ---------------------------
            # A probe request is held in flight at the victim (the
            # wrapper stalls only pre-kill victim sends), then the SWIM
            # verdict flips and the process dies: the router must cancel
            # the in-flight attempt, record the `dead_reroute` hop, and
            # fail over — all inside ONE stored waterfall.
            base_qfn = tcp_query_fn(addrs)

            def probe_qfn(peer, payload, timeout_s, cancel):
                if peer == victim and victim not in dead:
                    # Stall until the ROUTER cancels the attempt: ending
                    # the stall on the verdict flip itself would race the
                    # router's poll loop (the attempt could settle as a
                    # plain failure before the loop sees the flip and
                    # records the dead_reroute hop).
                    for _ in range(600):
                        if cancel.is_set():
                            raise TimeoutError("probe attempt cancelled")
                        time.sleep(0.01)
                    raise TimeoutError("probe stall expired")
                return base_qfn(peer, payload, timeout_s, cancel)

            r_probe = FleetRouter(
                MEMBERS, probe_qfn, metrics=metrics, verdict_fn=verdict,
                hedge=False, timeout_s=5.0, retries=2,
                backoff_base_s=0.02, poll_s=0.002, seed=4,
                breaker_failures=6,
            )
            probe_out: dict = {}

            def probe() -> None:
                probe_out.update(r_probe.query(
                    [{"op": "value", "key": 0}], key="k0",
                    max_staleness_s=MAX_STALENESS_S,
                ))

            probe_thread = threading.Thread(target=probe, daemon=True)
            probe_thread.start()
            time.sleep(0.15)           # the probe attempt is in flight
            dead.add(victim)           # SWIM verdict flips first...
            time.sleep(0.05)           # ...and the poll loop observes it
            procs[victim].send_signal(signal.SIGKILL)
            t_kill = time.monotonic()
            print(f"   SIGKILL -> {victim} (probe in flight)")
            probe_thread.join(15.0)

            # Keep the storm running through failover; stop the clients
            # BEFORE the workers enter teardown.
            survivor = next(m for m in MEMBERS if m != victim)
            deadline = time.time() + args.storm_postkill_s
            while time.time() < deadline \
                    and _step_of(root, survivor) < args.steps - 3:
                _drop_router_status(root, r_main, rtrace)
                time.sleep(0.25)
            stop.set()
            for t in threads:
                t.join(HARD_LATENCY_CEILING_S + 5.0)
            t_load = time.monotonic() - t_load0
            hung_threads = [t for t in threads if t.is_alive()]
            _drop_router_status(root, r_main, rtrace)
            route_faults = [
                e for e in faults.trace() if e[0] == "router.route"]
            faults.uninstall()

            # -- reassemble the evidence BEFORE teardown ---------------------
            offs = rtrace.offsets()
            trs = rtrace.traces("read")
            sampled_ok = [t for t in trs
                          if t["outcome"] == "ok" and t.get("sampled")]
            incomplete = [(t, rtrace.complete(t)[1]) for t in sampled_ok]
            incomplete = [(t, why) for t, why in incomplete if why]
            complete_frac = (
                (len(sampled_ok) - len(incomplete)) / len(sampled_ok)
                if sampled_ok else 0.0
            )
            rep = rtrace.attribution_report(sampled_ok, offs)
            print(rtrace.format_report(rep))

            # The p99 exemplar on the scrape surface must resolve to a
            # real stored trace.
            scrape = obs_export.prometheus_text(metrics)
            ex_m = re.search(
                r'ccrdt_router_read_seconds[^\n]*trace_id="([^"]+)"',
                scrape)
            ex_trace = rtrace.find(ex_m.group(1)) if ex_m else None
            ex_dom = None
            if ex_trace is not None:
                attr = rtrace.attribute(ex_trace, offs)
                ex_dom = max(
                    (b for b in rtrace.BUCKETS if b != "hedge_overlap"),
                    key=lambda b: attr.get(b, 0.0),
                )

            # The dead_reroute hop must have landed in a stored trace.
            reroute_traces = [
                t for t in trs
                if any(h.get("k") == "dead_reroute"
                       for h in t.get("hops", ()))
            ]
            if reroute_traces:
                print("   dead_reroute waterfall "
                      f"({reroute_traces[-1]['id']}):")
                for row in rtrace.waterfall(reroute_traces[-1], offs):
                    print(f"     {row['name']:<13} {row['t0_ms']:>9.3f} -> "
                          f"{row['t1_ms']:>9.3f}ms "
                          f"{row.get('peer', '')}")

            counters = rtrace.counters()
            rc_router = {
                k: int(v)
                for k, v in metrics.snapshot()["counters"].items()
                if k.startswith("router.")
            }
            obs_events.dump(os.path.join(obs_dir, "flight-router.jsonl"))
            rtrace.uninstall()

            # -- overhead: kill-switch (off) vs traced (on), paired per
            # request against the QUIESCED survivors — they finished
            # stepping and are lingering in serve-only mode, so neither
            # arm can land inside a per-step JIT recompile or gossip
            # stall. No supervisor faults: the only variable is the
            # plane. ---------------------------------------------------------
            survivors_set = {m for m in MEMBERS if m != victim}
            fin_deadline = time.time() + 120.0
            while time.time() < fin_deadline:
                done = {
                    os.path.basename(p)[len("final-"):-len(".json")]
                    for p in glob.glob(os.path.join(root, "final-*.json"))
                }
                if survivors_set <= done:
                    break
                time.sleep(0.2)
            r_ovh = FleetRouter(
                MEMBERS, tcp_query_fn(addrs), metrics=Metrics(),
                verdict_fn=verdict, hedge=False, timeout_s=0.6,
                retries=2, backoff_base_s=0.02, poll_s=0.002, seed=3,
                breaker_failures=6,
            )
            on_rps, off_rps = _measure_overhead(
                r_ovh, rtrace, args.overhead_window_s)
            overhead_pct = max(0.0, (off_rps - on_rps) / max(off_rps, 1e-9)
                               * 100.0)
            print(f"   overhead: traced {on_rps:,.0f} reads/s vs "
                  f"CCRDT_RTRACE=0 {off_rps:,.0f} reads/s "
                  f"({overhead_pct:.2f}%) on the quiesced survivors")
            with open(os.path.join(root, "serve-stop"), "w") as f:
                f.write("done\n")

            # -- reap the fleet ----------------------------------------------
            outs = {}
            for m, p in procs.items():
                try:
                    out, _ = p.communicate(timeout=args.worker_timeout)
                    outs[m] = (p.returncode, out)
                except subprocess.TimeoutExpired:
                    p.kill()
                    out, _ = p.communicate()
                    outs[m] = (None, out)
            for m, (rc, out) in outs.items():
                if m != victim and rc != 0:
                    failures.append(f"worker {m} rc={rc}:\n{out}")
            digests = {}
            for path in glob.glob(os.path.join(root, "final-*.json")):
                try:
                    with open(path) as f:
                        doc = json.load(f)
                    digests[doc["member"]] = doc["digest"]
                except (OSError, ValueError, KeyError):
                    continue
            survivors = [m for m in MEMBERS if m != victim]
            converged = sorted(digests) == survivors and len(
                {json.dumps(d, sort_keys=True) for d in digests.values()}
            ) == 1
            if not converged:
                failures.append(
                    "survivors did not all converge to one digest "
                    f"(finals from {sorted(digests)})")

            # -- audit the storm ---------------------------------------------
            lat = sorted(x for st in stats for x in st["lat"])
            ok_t = sorted(x for st in stats for x in st["ok_t"])
            reads = sum(st["reads"] for st in stats)
            agg = {k: sum(st[k] for st in stats)
                   for k in ("unavailable", "shed", "unsatisfiable",
                             "resets")}
            max_ms = (lat[-1] * 1e3) if lat else None
            blip_ms = 0.0
            if ok_t:
                window = [t_kill - 0.5] + [
                    t for t in ok_t if t_kill - 0.5 <= t <= t_kill + 4.0
                ]
                gaps = [b - a for a, b in zip(window, window[1:])]
                blip_ms = max(gaps) * 1e3 if gaps else 4.5e3

            checks = {
                "zero_hung_queries": not hung_threads
                and (max_ms is None
                     or max_ms <= HARD_LATENCY_CEILING_S * 1e3),
                "zero_unavailable": agg["unavailable"] == 0,
                "waterfalls_complete": bool(sampled_ok)
                and complete_frac >= args.min_complete_frac,
                "attribution_p50_covered": rep.get("coverage_p50", 0.0)
                >= args.min_coverage,
                "attribution_p99_covered": rep.get("coverage_p99_req", 0.0)
                >= args.min_coverage,
                "exemplar_resolves": ex_trace is not None
                and ex_dom is not None,
                "dead_reroute_traced": bool(reroute_traces)
                and rc_router.get("router.dead_reroutes", 0) > 0,
                "probe_failed_over": probe_out.get("error") is None
                and probe_out.get("peer") in survivors,
                "failover_blip_bounded": blip_ms <= args.max_blip_ms,
                "overhead_under_budget": overhead_pct
                <= args.max_overhead_pct,
                "rtrace_counters_lit": all(
                    counters.get(k, 0) > 0
                    for k in ("minted", "sampled", "committed",
                              "slow_kept")
                ),
                "clock_offsets_learned": len(offs) > 0,
                "route_faults_fired": len(route_faults) > 0,
                "survivors_converged": converged,
            }
            report = {
                "drill": "rtrace_demo",
                "fleet": MEMBERS,
                "killed": victim,
                "clients": CLIENTS,
                "sample": 1.0,
                "load_s": round(t_load, 3),
                "traced_reads_per_sec": round(on_rps, 1),
                "untraced_reads_per_sec": round(off_rps, 1),
                "overhead_pct": round(overhead_pct, 3),
                "storm_reads": reads,
                "outcomes": agg,
                "n_sampled_ok": len(sampled_ok),
                "n_incomplete": len(incomplete),
                "complete_frac": round(complete_frac, 4),
                "incomplete_reasons": sorted(
                    {why for _t, why in incomplete})[:5],
                "coverage_p50": rep.get("coverage_p50", 0.0),
                "coverage_p99_req": rep.get("coverage_p99_req", 0.0),
                "p99_trace_id": rep.get("p99_trace_id"),
                "p99_dominant_bucket": rep.get("p99_dominant_bucket"),
                "exemplar_trace_id": ex_m.group(1) if ex_m else None,
                "exemplar_dominant_bucket": ex_dom,
                "dead_reroute_trace_id": (
                    reroute_traces[-1]["id"] if reroute_traces else None
                ),
                "failover_blip_ms": round(blip_ms, 3),
                "route_faults_fired": len(route_faults),
                "rtrace_counters": {
                    k: int(v) for k, v in sorted(counters.items())},
                "checks": checks,
                "pass": all(checks.values()) and not failures,
            }
            with open(args.out, "w") as fh:
                json.dump(report, fh, indent=2, sort_keys=True)
                fh.write("\n")
            print(json.dumps(report, indent=2, sort_keys=True))
            if failures:
                print("FAIL:")
                for f in failures:
                    print(f"  - {f}")
                return 1
            if not report["pass"]:
                bad = [k for k, ok in checks.items() if not ok]
                print(f"FAIL: {', '.join(bad)}", file=sys.stderr)
                return 1
            print(
                f"PASS: {len(sampled_ok)} waterfalls "
                f"({complete_frac:.1%} gap-free), coverage p50 "
                f"{rep.get('coverage_p50', 0):.1%} / p99 "
                f"{rep.get('coverage_p99_req', 0):.1%}, exemplar -> "
                f"{ex_dom}, dead_reroute traced across {victim}'s "
                f"SIGKILL (blip {blip_ms:.0f}ms), overhead "
                f"{overhead_pct:.2f}%"
            )
            return 0
        finally:
            faults.uninstall()
            os.environ.pop("CCRDT_RTRACE", None)
            for p in procs.values():
                if p.poll() is None:
                    p.kill()


if __name__ == "__main__":
    raise SystemExit(main())
