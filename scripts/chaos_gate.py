"""Chaos metrics gate: fail `make chaos` if the fault machinery goes dark.

Runs three seeded simulator chaos drills — the full-mesh drill pinned
by tests/test_net_chaos.py (loss + duplication + partition + crash over
chained-delta gossip), the partition-plane drill pinned by
tests/test_partition.py (same fault schedule with partitioned
publishers + PartialAntiEntropy; partial-resync counters must be lit
and `net.psnap_wasted` — a psnap fetched for an already-agreeing
partition — must be exactly zero), and the zone-topology drill pinned
by tests/test_topo_chaos.py (two zones, whole-zone partition, the za
anchor crashed; requires cross-zone traffic, anchor relays, AND an
observed failover off the crashed anchor) — then asserts that every
load-bearing counter is nonzero and prints the run's summary. The point is
regression detection at the *observability* layer: a refactor that
keeps convergence green but silently stops counting (metrics renamed,
instrumentation dropped, sim faults disabled) regresses these counters
to zero and must fail the gate, because every downstream consumer — the
dashboard, the lag tracker, the flight-log cross-checks — reads them.

The serving leg reruns the skewed-clock serve drill pinned by
tests/test_serve_staleness.py and holds it to the read plane's two
exactly-zero contracts — no served result older than its advertised
staleness bound, no served value differing from the engine's value()
at the claimed as_of_seq — on top of the usual counters-nonzero rule.

The span leg guards the span plane (obs/spans.py): it runs the tiny
round-phase drill (`bench.bench_round_phases`) with tracing armed and
fails if any load-bearing phase recorded zero time — the span analogue
of a counter going dark — or if the phases' union (serial AND
host-stage-overlapped: PR 7 moved wal_append/delta_encode/gossip onto
the overlap pipeline's threads, which re-threads their spans without
unrecording them) stops reconciling against the measured `round.e2e`
wall time. When the overlap pipeline is on (CCRDT_OVERLAP, default)
the leg also requires the pipeline's own counters nonzero —
`overlap.host_tasks` and `overlap.windows` at zero mean the drill
silently fell back to the serial path.

The audit leg guards the certified-convergence plane (obs/audit.py,
via scripts/audit_demo.py): the lattice-law checker must pass every
registered type AND catch the committed broken-merge fixture, the
seeded-chaos real-process fleet must replay-certify into a valid
signed certificate with ZERO false wedge alarms on the healthy arm,
and the deterministic divergent arm must light every watchdog counter
(divergence flagged within one digest exchange, wedge alarm past the
bound, time-to-agreement on heal) with the failed certificate's
counterexample naming the diverging partition.

The durability leg (PR 11) re-runs the real-process SIGKILL crash
drill under async WAL durability (publish may overtake fsync) and
holds the certifier's published-vs-durable reconciliation to both
verdicts: the real fleet's exposed-then-re-derived loss must certify
OK, and a deliberately fabricated pre-fsync-loss flight log (appended
through seq 9, acked through 5, no successor) must FAIL certification
with a counterexample naming the uncovered seq range.

The mesh leg (PR 12) re-runs tests/test_mesh.py's seeded chaos drill —
every worker mesh-sharded over a (2,4) device mesh, one ICI JOIN
all-reduce per publish boundary, per-shard anchors, mesh-grouped
partial repairs — in a subprocess with 8 forced host devices (this
gate's own process initialized its backend single-device). It must
converge to the sequential reference with `mesh.ici_reduces` and
`mesh.cross_slice_fetches` nonzero, `net.psnap_wasted` still exactly
zero, and the conditional `round.ici_reduce` span lit.

The working-set leg (PR 13) re-runs scripts/working_set_demo.py's
drill under a fresh seed — a 3-worker fleet whose per-worker HBM
budget is forced to a tenth of the instance, zipf-skewed ops through
the pager front door, full partition-plane gossip — and requires
bit-identical convergence against the all-resident sequential
reference, a steady-state hit rate >= 0.9, every pager heartbeat
counter (`pager.evictions` / `pager.hydrations` / `pager.cold_folds` /
`pager.blob_serves`) nonzero, `net.psnap_wasted` still exactly zero,
and the conditional `round.pager_hydrate` span lit.

The ingest leg (PR 15) re-runs the overlap chaos drill with the
publishers DEFERRING delta windows (tests/test_ingest_fastpath.py):
wire windows coalesce into range frames, the prefetcher batch-decodes
frame runs, and the tiny apply queue is still forced to shed. The
seeded drill must converge bit-identically BOTH to the sequential
reference AND to its own CCRDT_INGEST_COMPACT=0 kill-switch rerun, with
every fast-path counter lit — coalesced frames/ops on the wire, decoded
leaves staged to device, cross-member folds fused, and the delta shed
(hole-healing under compaction) actually exercised.

The devprof leg (PR 18) runs the seeded stepping drill from
tests/test_devprof.py: three workers grow topk_rmv state every round,
the fold's slots-per-id axis moves, and the device observatory
(obs/devprof.py) must attribute 100% of the resulting recompiles to
(site, changed axis) — with topk_rmv capacity growth named as the
dominant churn source, the devprof.* counters lit, and the
CCRDT_DEVPROF=0 kill-switch arm byte-identical and fully dark.

Run:  python scripts/chaos_gate.py
Make: part of `make chaos` (after the pytest leg).
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))
sys.path.insert(0, os.path.join(REPO, "scripts"))

from antidote_ccrdt_tpu.obs import export as obs_export  # noqa: E402
from antidote_ccrdt_tpu.utils.metrics import Metrics  # noqa: E402

# Counters that the seeded drill MUST move — each one is the heartbeat
# of a subsystem (sim fault engine, delta gossip, SWIM failure
# detection). Zero means the machinery silently stopped firing.
REQUIRED_NONZERO = (
    "net.sim_msgs",        # traffic flowed through the simulator at all
    "net.sim_lost",        # seeded loss actually dropped frames
    "net.sim_duplicated",  # seeded duplication actually fired
    "net.sim_unreachable", # partition/crash actually blocked routes
    "net.delta_publishes", # chained-delta gossip produced deltas
    "net.delta_fetches",   # ...and peers pulled them
    "net.snap_publishes",  # anchor/full-snapshot path exercised
    "net.dead_events",     # SWIM confirmed the crashed member
)

# Spans leg: minimum fleet-p50 fraction of round.e2e wall time the
# serial phase union must explain. The tiny drill measures ~99% when
# healthy; 0.6 is the "instrumentation collapsed" line, not a perf SLO.
SPAN_MIN_COVERAGE = 0.6

# Partition-plane leg (tests/test_partition.py's seeded sim drill with
# partitioned publishers + PartialAntiEntropy on every sweep): partial
# repairs must actually happen, and `net.psnap_wasted` — a psnap fetched
# for a partition whose digests already agreed — must stay EXACTLY zero:
# the wasted-resync detector. Partial anti-entropy's whole claim is
# "only divergent partitions cross the wire"; one wasted fetch means the
# divergence math broke even if convergence stays green.
PARTITION_REQUIRED_NONZERO = (
    "net.dig_publishes",        # digest vectors actually shipped
    "net.psnap_publishes",      # per-partition psnaps stored at anchors
    "net.psnap_fetches",        # peers pulled divergent partitions
    "net.psnap_bytes",          # ...with the byte bill counted
    "net.partition_resyncs",    # partial repairs completed
)

# Serving-plane leg (tests/test_serve_staleness.py's seeded sim drill:
# asymmetric link latency, seeded loss/dup, large asymmetric clock skew,
# queries served mid-gossip): the read path's own heartbeat counters,
# plus two EXACTLY-ZERO contracts checked on the audit — bound
# violations (a served result older than its advertised staleness
# bound) and identity mismatches (a served value differing from the
# engine's own value() at the claimed as_of_seq).
SERVE_REQUIRED_NONZERO = (
    "serve.swaps",         # replicas actually swapped at publish points
    "serve.requests",      # query frames reached the plane
    "serve.batches",       # the coalescing batcher actually drained
    "serve.queries",       # ...with the per-query bill counted
    "serve.stale_rejects", # the staleness knob actually rejected
    "net.queries",         # in-band wire queries crossed the (lossy) sim
)

# Audit leg (scripts/audit_demo.py's deterministic divergent arm): the
# divergence watchdog's full episode — detection, wedge alarm,
# agreement — must move its counters. Zero on any of these means the
# live divergence plane went dark even if certification stays green.
AUDIT_REQUIRED_NONZERO = (
    "audit.divergences",   # the watchdog flagged the divergence at all
    "audit.wedge_alarms",  # ...escalated once repair stalled past bound
    "audit.agreements",    # ...and closed the episode with a tta sample
)

# Mesh leg (tests/test_mesh.py's seeded drill, subprocessed onto 8
# forced host devices): the intra-slice collective and the cross-slice
# shard-local anti-entropy must both actually fire — a refactor that
# silently drops the reduce or regresses fetches to whole-instance
# resyncs keeps convergence green but zeroes these.
MESH_REQUIRED_NONZERO = (
    "mesh.ici_reduces",         # the ICI JOIN all-reduce actually dispatched
    "mesh.cross_slice_fetches", # shard-local psnap slices crossed slices
    "mesh.cross_slice_bytes",   # ...with the byte bill counted
    "mesh.shard_digest_slices", # anchors produced per-shard digest slices
    "net.psnap_publishes",      # ...and published the per-partition psnaps
)

# Working-set leg (scripts/working_set_demo.py's drill, fresh seed):
# the out-of-core pager must actually page under the forced 10x
# overcommit — a refactor that silently falls back to all-resident
# keeps convergence green (that IS the legacy path) but zeroes these.
PAGER_REQUIRED_NONZERO = (
    "pager.evictions",   # the clock actually demoted cold partitions
    "pager.hydrations",  # ...and misses pulled them back device-side
    "pager.cold_folds",  # inbound cold deltas folded host-side
    "pager.blob_serves", # cold psnaps answered straight from storage
)

# Ingest leg (tests/test_ingest_fastpath.py's seeded drill: deferred
# publishers, coalesce cap 2, depth-2 apply queue with drains withheld):
# the compacted wire path must actually run end to end — a refactor
# that silently stops staging (every publish ships per-window) or stops
# batch-decoding keeps convergence green (that IS the legacy path) but
# zeroes these.
INGEST_REQUIRED_NONZERO = (
    "ingest.coalesced_frames",    # multi-window range frames hit the wire
    "ingest.coalesced_ops",       # ...covering more than one window each
    "ingest.staged_bytes",        # decoded leaves pre-staged to device
    "ingest.fused_members",       # cross-member windows folded in one dispatch
    "overlap.prefetched_deltas",  # the prefetcher pulled the frames
    "overlap.dropped_deltas",     # the forced shed opened real holes
)

# Same contract for the zone-topology leg (tests/test_topo_chaos.py:
# two zones, whole-zone partition, the za anchor crashed mid-run).
TOPO_REQUIRED_NONZERO = (
    "topo.cross_zone.frames",  # traffic actually crossed the DCN
    "topo.cross_zone.bytes",   # ...with its byte bill counted
    "topo.relays",             # anchors actually relayed
    "topo.anchor_changes",     # elections (incl. the failover) observed
    "net.sim_unreachable",     # the zone partition actually blocked routes
    "net.dead_events",         # SWIM confirmed the crashed anchor
)


def main() -> int:
    from test_net_chaos import run_chaos  # heavy import (JAX) kept in main
    from test_topo_chaos import ZONES, run_topo_chaos
    from antidote_ccrdt_tpu.topo import rendezvous_anchor
    from elastic_demo import reference_digest

    digests, counters = run_chaos("topk_rmv", seed=7, delta=True)

    ref = reference_digest("topk_rmv")
    diverged = sorted(m for m, d in digests.items() if d != ref)
    zeroed = sorted(n for n in REQUIRED_NONZERO if not counters.get(n, 0))

    m = Metrics()
    m.merge({"counters": counters, "latencies": {}})
    print("== chaos drill metrics summary (seed=7, topk_rmv, delta) ==")
    print(obs_export.prometheus_text(m), end="")

    if diverged:
        print(f"FAIL: members diverged from the sequential reference: "
              f"{diverged}")
        return 1
    if zeroed:
        print("FAIL: chaos counters regressed to zero (instrumentation "
              f"or fault machinery went dark): {zeroed}")
        return 1
    print(f"OK: all {len(REQUIRED_NONZERO)} required chaos counters "
          f"nonzero; {len(digests)} survivors converged")

    # -- leg 2: the partition plane (partial anti-entropy under chaos) -----
    from test_partition import run_partition_chaos

    p_digests, p_counters = run_partition_chaos(seed=7)
    p_diverged = sorted(m for m, d in p_digests.items() if d != ref)
    p_zeroed = sorted(
        n for n in PARTITION_REQUIRED_NONZERO if not p_counters.get(n, 0)
    )
    wasted = int(p_counters.get("net.psnap_wasted", 0))
    print("== partition chaos drill (seed=7, partial anti-entropy) ==")
    print("  " + " ".join(
        f"{n}={int(p_counters.get(n, 0))}"
        for n in PARTITION_REQUIRED_NONZERO + ("net.psnap_wasted",)
    ))
    if p_diverged:
        print(f"FAIL: partition-plane members diverged from the sequential "
              f"reference: {p_diverged}")
        return 1
    if p_zeroed:
        print("FAIL: partition counters regressed to zero (partial "
              f"anti-entropy went dark): {p_zeroed}")
        return 1
    if wasted:
        print(f"FAIL: {wasted} psnap fetch(es) covered a partition whose "
              "digests already agreed — the wasted-resync detector fired")
        return 1
    print(f"OK: partition leg — {len(p_digests)} survivors converged via "
          f"{int(p_counters.get('net.partition_resyncs', 0))} partial "
          f"resyncs, 0 wasted psnaps")

    # -- leg 3: the zone topology (whole-zone partition + anchor crash) ----
    t_digests, t_counters, anchor_events = run_topo_chaos("topk_rmv", seed=7)
    t_diverged = sorted(m for m, d in t_digests.items() if d != ref)
    t_zeroed = sorted(
        n for n in TOPO_REQUIRED_NONZERO if not t_counters.get(n, 0)
    )
    victim = rendezvous_anchor(
        "za", sorted(m for m, z in ZONES.items() if z == "za")
    )
    failovers = [
        ev for ev in anchor_events
        if ev["zone"] == "za" and ev["old"] == victim and ev["new"] != victim
    ]
    print("== topo chaos drill (seed=7, 2 zones, za anchor crashed) ==")
    print("  " + " ".join(
        f"{n}={int(t_counters.get(n, 0))}" for n in TOPO_REQUIRED_NONZERO
    ))
    if t_diverged:
        print(f"FAIL: topo members diverged from the sequential reference: "
              f"{t_diverged}")
        return 1
    if t_zeroed:
        print("FAIL: topology counters regressed to zero (routing or "
              f"instrumentation went dark): {t_zeroed}")
        return 1
    if not failovers:
        print(f"FAIL: no anchor failover away from crashed {victim} "
              f"observed (events: {anchor_events})")
        return 1
    print(f"OK: topo leg — {len(t_digests)} survivors converged via "
          f"anchors, failover {victim} -> "
          f"{sorted({ev['new'] for ev in failovers})} observed")

    # -- leg 4: the span plane (round-phase tracing + attribution) ---------
    from bench import bench_round_phases
    from antidote_ccrdt_tpu.obs import spans as obs_spans
    from antidote_ccrdt_tpu.parallel import overlap as overlap_mod

    ovl_enabled = overlap_mod.enabled(None)
    rp = bench_round_phases(2, 256, 2, 100, 4, 32, 8, rounds=3,
                            overlap=ovl_enabled)
    dark = sorted(
        n for n in obs_spans.PHASES
        if rp["phases_ms_total"].get(n, 0.0) <= 0.0
    )
    mode = "overlap" if ovl_enabled else "serial"
    print(f"== span drill (2 members, 3 rounds, {mode} mode, all phases "
          "armed) ==")
    print(f"  e2e p50 {rp['e2e_ms_p50']:.2f}ms serial "
          f"{rp['serial_ms_p50']:.2f}ms gap {rp['dispatch_gap_ms_p50']:.2f}ms "
          f"coverage {rp['span_coverage_p50']:.1%}")
    if dark:
        print("FAIL: load-bearing round phases recorded no time (span "
              f"instrumentation went dark): {dark}")
        return 1
    if rp["span_coverage_p50"] < SPAN_MIN_COVERAGE:
        print(f"FAIL: span attribution no longer reconciles against the "
              f"round.e2e wall (coverage p50 {rp['span_coverage_p50']:.1%} < "
              f"{SPAN_MIN_COVERAGE:.0%})")
        return 1
    if ovl_enabled:
        ovl_zeroed = sorted(
            n for n in ("overlap.host_tasks", "overlap.windows")
            if not rp["overlap"].get(n, 0)
        )
        if ovl_zeroed:
            print("FAIL: overlap pipeline counters at zero — the drill "
                  f"silently fell back to the serial path: {ovl_zeroed}")
            return 1
    print(f"OK: span leg — all {len(obs_spans.PHASES)} phases lit, the "
          f"phase union explains {rp['span_coverage_p50']:.1%} of round "
          f"wall (critical path: {' > '.join(rp['critical_path'][:3])})")

    # -- leg 5: the serving plane (bounded-staleness reads under chaos) ----
    from test_serve_staleness import run_serve_chaos

    audit = run_serve_chaos(seed=7)
    s_counters = audit["counters"]
    s_zeroed = sorted(
        n for n in SERVE_REQUIRED_NONZERO if not s_counters.get(n, 0)
    )
    print("== serve chaos drill (seed=7, skewed clocks, asymmetric "
          "links) ==")
    print("  " + " ".join(
        f"{n}={int(s_counters.get(n, 0))}" for n in SERVE_REQUIRED_NONZERO
    ))
    print(f"  served={audit['served']} rejected={audit['rejected']} "
          f"wire_responses={audit['wire_responses']} "
          f"violations={audit['violations']} "
          f"identity_mismatches={audit['identity_mismatches']}")
    if s_zeroed:
        print("FAIL: serving counters regressed to zero (the read plane "
              f"went dark): {s_zeroed}")
        return 1
    if audit["violations"]:
        print(f"FAIL: {audit['violations']} served result(s) were older "
              "than their advertised staleness bound — the bound "
              "arithmetic leaked a foreign clock")
        return 1
    if audit["identity_mismatches"]:
        print(f"FAIL: {audit['identity_mismatches']} served value(s) "
              "differ from the engine's value() at the claimed as_of_seq "
              "— the replica served torn or stale-beyond-claim state")
        return 1
    if not audit["served"] or not audit["wire_responses"]:
        print("FAIL: the drill served nothing "
              f"(served={audit['served']}, "
              f"wire_responses={audit['wire_responses']})")
        return 1
    print(f"OK: serve leg — {audit['served']} reads served under chaos "
          f"({audit['rejected']} honestly rejected as stale), 0 bound "
          "violations, 0 identity mismatches")

    # -- leg 6: the certified-convergence plane (obs/audit.py) -------------
    import audit_demo

    laws = audit_demo.run_laws(pairs=32)
    healthy = audit_demo.run_healthy()
    divergent = audit_demo.run_divergent()
    a_counters = divergent["counters"]
    a_zeroed = sorted(
        n for n in AUDIT_REQUIRED_NONZERO if not a_counters.get(n, 0)
    )
    print("== audit drill (laws + certified fleet + divergent arm) ==")
    print("  " + " ".join(
        f"{n}={int(a_counters.get(n, 0))}" for n in AUDIT_REQUIRED_NONZERO
    ))
    print(f"  laws: {laws['n_law_checks']} checks / {laws['n_types']} "
          f"types, {laws['n_law_failures']} failures, broken fixture "
          f"{'caught' if laws['selftest_caught'] else 'MISSED'}")
    print(f"  healthy cert: ok={healthy['cert']['ok']} "
          f"verified={healthy['verified']} "
          f"wedge_alarms={healthy['wedge_alarms']}")
    print(f"  divergent: p*={divergent['p_star']} counterexample="
          f"{divergent['counterexample_parts']}")
    if not laws["ok"]:
        print("FAIL: lattice-law checker — "
              + ("registered type failed its laws "
                 f"({laws['n_law_failures']} failures, "
                 f"unaudited: {laws['unaudited']})"
                 if not laws["registry_ok"]
                 else "the committed broken-merge fixture was MISSED"))
        return 1
    if not healthy["cert"]["ok"] or not healthy["verified"]:
        print("FAIL: the healthy fleet did not certify "
              f"(checks: {healthy['cert']['checks']}, "
              f"signature valid: {healthy['verified']})")
        return 1
    if healthy["wedge_alarms"]:
        print(f"FAIL: {healthy['wedge_alarms']} wedge alarm(s) on the "
              "healthy arm — the watchdog false-alarmed on healing "
              "transient divergence")
        return 1
    if a_zeroed:
        print("FAIL: watchdog counters regressed to zero (the live "
              f"divergence plane went dark): {a_zeroed}")
        return 1
    if not divergent["ok"]:
        print("FAIL: divergent arm — expected diverged-within-one-"
              "exchange -> wedged -> healed and a failed certificate "
              f"naming partition {divergent['p_star']}; got states "
              f"{divergent['states']}, counterexample "
              f"{divergent['counterexample_parts']}")
        return 1
    print(f"OK: audit leg — {laws['n_law_checks']} laws green + broken "
          f"fixture caught, healthy fleet certified "
          f"(sha256:{healthy['cert']['signature'][:16]}…, 0 false "
          f"alarms), divergence flagged in one exchange naming "
          f"partition {divergent['p_star']}")

    # -- leg 7: async durability (published-vs-durable reconciliation) -----
    dur = audit_demo.run_durability()
    fleet = dur["fleet"]
    print("== async-durability drill (SIGKILL fleet + fabricated "
          "pre-fsync-loss arm) ==")
    print(f"  fleet: kill_seq={fleet['kill_seq']} "
          f"appended={fleet['victim_flight_last_step']} "
          f"durable={fleet['victim_flight_durable']} "
          f"recovered_to={fleet['victim_recover_last_step']} "
          f"checks={fleet['certifier_checks']}")
    print(f"  fabricated: cert_ok={dur['fabricated_cert_ok']} "
          f"exposures={dur['fabricated_exposures']}")
    if not fleet["ok"]:
        print("FAIL: async-durability fleet drill — "
              f"{fleet['problems']}")
        return 1
    if fleet["certifier_checks"].get("durability_watermark") is not True:
        print("FAIL: the certifier's durability_watermark check did not "
              f"activate+pass on the async fleet: "
              f"{fleet['certifier_checks']}")
        return 1
    if not dur["fabricated_flagged"]:
        print("FAIL: the fabricated pre-fsync-loss arm was NOT flagged — "
              "the certifier waved provable unaudited loss through "
              f"(exposures: {dur['fabricated_exposures']})")
        return 1
    print("OK: durability leg — async crash recovered to the watermark "
          "and certified (loss re-derived by the successor); fabricated "
          "loss flagged with uncovered range "
          f"{dur['fabricated_exposures'][0]['uncovered']}")

    # -- leg 8: the mesh plane (ICI reduces + cross-slice anti-entropy) ----
    # This process's backend initialized single-device (the gate must not
    # inherit the test rig's forced device count — legs 1-7 pin the
    # UNSHARDED paths); the mesh drill needs 8 virtual devices, so it
    # runs hermetically in a child with the conftest-built env.
    import json as _json
    import subprocess

    from conftest import cpu_mesh_subprocess_env

    child_src = (
        "import json, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        f"sys.path.insert(0, {os.path.join(REPO, 'tests')!r})\n"
        f"sys.path.insert(0, {os.path.join(REPO, 'scripts')!r})\n"
        "from test_mesh import run_mesh_chaos\n"
        "from elastic_demo import reference_digest\n"
        "digests, counters, span_names = run_mesh_chaos(seed=7, spans=True)\n"
        "ref = reference_digest('topk_rmv')\n"
        "print(json.dumps({\n"
        "    'diverged': sorted(m for m, d in digests.items() if d != ref),\n"
        "    'survivors': len(digests),\n"
        "    'counters': counters,\n"
        "    'span_names': sorted(span_names),\n"
        "}))\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", child_src],
        env=cpu_mesh_subprocess_env(8),
        capture_output=True, text=True, timeout=600,
    )
    print("== mesh chaos drill (seed=7, (2,4) mesh × 8 forced host "
          "devices, subprocess) ==")
    if proc.returncode != 0:
        print("FAIL: mesh drill subprocess crashed:\n"
              + (proc.stderr or proc.stdout)[-2000:])
        return 1
    mesh = _json.loads(proc.stdout.strip().splitlines()[-1])
    mc = mesh["counters"]
    m_zeroed = sorted(n for n in MESH_REQUIRED_NONZERO if not mc.get(n, 0))
    m_wasted = int(mc.get("net.psnap_wasted", 0))
    print("  " + " ".join(
        f"{n}={int(mc.get(n, 0))}"
        for n in MESH_REQUIRED_NONZERO + ("net.psnap_wasted",)
    ))
    if mesh["diverged"]:
        print("FAIL: mesh-sharded members diverged from the sequential "
              f"reference: {mesh['diverged']}")
        return 1
    if m_zeroed:
        print("FAIL: mesh counters regressed to zero (the ICI reduce or "
              f"the shard-local anti-entropy went dark): {m_zeroed}")
        return 1
    if m_wasted:
        print(f"FAIL: {m_wasted} psnap fetch(es) covered a partition whose "
              "digests already agreed — sharding broke the divergence math")
        return 1
    if "round.ici_reduce" not in mesh["span_names"]:
        print("FAIL: the conditional round.ici_reduce span never lit in a "
              f"mesh drill (spans seen: {mesh['span_names']})")
        return 1
    print(f"OK: mesh leg — {mesh['survivors']} mesh-sharded survivors "
          f"converged via {int(mc.get('mesh.ici_reduces', 0))} ICI reduces "
          f"and {int(mc.get('mesh.cross_slice_fetches', 0))} cross-slice "
          "shard fetches, 0 wasted psnaps, round.ici_reduce lit")

    # -- leg 9: out-of-core paging (10x-overcommitted working set) ---------
    from working_set_demo import run_drill

    ws = run_drill(seed=11, spans=True)
    wc = ws.get("counters", {})
    print("== working-set drill (seed=11, 3 workers, HBM budget = "
          "state/10, zipf ops) ==")
    print("  " + " ".join(
        f"{n}={int(wc.get(n, 0))}"
        for n in PAGER_REQUIRED_NONZERO + ("net.psnap_wasted",)
    ) + f" min_hit_rate={ws.get('min_hit_rate', 0.0)}")
    if not ws.get("converged"):
        print("FAIL: working-set fleet never agreed on a digest vector "
              f"({ws.get('error', 'tail exhausted')})")
        return 1
    if not ws.get("matches_reference"):
        print("FAIL: paged fleet converged but is NOT bit-identical to "
              "the all-resident sequential reference — paging leaked "
              "into semantics")
        return 1
    if ws.get("state_over_budget_x", 0.0) < 10.0:
        print("FAIL: drill lost its memory pressure — state is only "
              f"{ws.get('state_over_budget_x')}x the HBM budget (< 10x)")
        return 1
    if ws.get("min_hit_rate", 0.0) < 0.9:
        print("FAIL: steady-state pager hit rate degraded to "
              f"{ws.get('min_hit_rate')} (< 0.9) — the clock stopped "
              "keeping the zipf working set resident")
        return 1
    w_zeroed = sorted(n for n in PAGER_REQUIRED_NONZERO if not wc.get(n, 0))
    if w_zeroed:
        print("FAIL: pager counters regressed to zero (the drill "
              f"silently ran all-resident): {w_zeroed}")
        return 1
    w_wasted = int(wc.get("net.psnap_wasted", 0))
    if w_wasted:
        print(f"FAIL: {w_wasted} psnap fetch(es) covered a partition whose "
              "digests already agreed — cold digest caching broke the "
              "divergence math")
        return 1
    if "round.pager_hydrate" not in ws.get("span_names", []):
        print("FAIL: the conditional round.pager_hydrate span never lit "
              f"in a paging drill (spans seen: {ws.get('span_names')})")
        return 1
    print(f"OK: working-set leg — {ws['state_over_budget_x']}x "
          f"over-budget fleet converged bit-identically at hit rate "
          f"{ws['min_hit_rate']} via {int(wc.get('pager.hydrations', 0))} "
          f"hydrations / {int(wc.get('pager.evictions', 0))} evictions, "
          "0 wasted psnaps, round.pager_hydrate lit")

    # -- leg 10: the ingest fast path (compacted wire windows) -------------
    from test_ingest_fastpath import run_ingest_chaos

    i_digests, i_counters = run_ingest_chaos("topk_rmv", seed=7)
    i_off_digests, i_off_counters = run_ingest_chaos(
        "topk_rmv", seed=7, compact=False
    )
    i_diverged = sorted(m for m, d in i_digests.items() if d != ref)
    i_mismatch = sorted(
        m for m, d in i_digests.items() if i_off_digests.get(m) != d
    )
    i_zeroed = sorted(
        n for n in INGEST_REQUIRED_NONZERO if not i_counters.get(n, 0)
    )
    print("== ingest chaos drill (seed=7, deferred publishers, compact "
          "vs kill switch) ==")
    print("  " + " ".join(
        f"{n}={int(i_counters.get(n, 0))}" for n in INGEST_REQUIRED_NONZERO
    ))
    if i_diverged:
        print("FAIL: compacted-ingest members diverged from the "
              f"sequential reference: {i_diverged}")
        return 1
    if i_mismatch:
        print("FAIL: the CCRDT_INGEST_COMPACT=0 rerun disagrees with the "
              f"compacted run on: {i_mismatch} — the kill switch is no "
              "longer bit-identical")
        return 1
    if i_zeroed:
        print("FAIL: ingest fast-path counters regressed to zero (the "
              f"drill silently ran the legacy wire path): {i_zeroed}")
        return 1
    if i_off_counters.get("ingest.coalesced_frames", 0):
        print("FAIL: the kill-switch arm still shipped "
              f"{int(i_off_counters['ingest.coalesced_frames'])} coalesced "
              "frame(s) — CCRDT_INGEST_COMPACT=0 no longer disables "
              "staging")
        return 1
    print(f"OK: ingest leg — {len(i_digests)} survivors converged "
          "bit-identically to the reference AND the kill-switch rerun "
          f"via {int(i_counters.get('ingest.coalesced_frames', 0))} "
          f"coalesced frames ({int(i_counters.get('ingest.coalesced_ops', 0))} "
          f"windows), {int(i_counters.get('overlap.dropped_deltas', 0))} "
          f"shed deltas healed")

    # -- leg 11: the request-tracing plane (obs/rtrace.py) -----------------
    from test_rtrace import run_rtrace_chaos
    from antidote_ccrdt_tpu.obs import rtrace as obs_rtrace

    rt = run_rtrace_chaos(seed=7)
    obs_rtrace.uninstall()
    rc = rt["counters"]
    print("== rtrace chaos drill (seed=7, serve stalls + flaky peer + "
          "rtrace.record fault, 50% head sampling) ==")
    print("  " + " ".join(
        f"rtrace.{k}={int(rc.get(k, 0))}"
        for k in ("minted", "sampled", "committed", "forced", "degraded")
    ) + f" complete={rt['n_complete']}/{rt['n_sampled_ok']}"
        f" coverage_p50={rt['coverage_p50']}")
    rt_zeroed = sorted(
        k for k in ("minted", "sampled", "committed", "forced", "degraded")
        if not rc.get(k, 0)
    )
    if rt_zeroed:
        print("FAIL: rtrace counters regressed to zero (the tracing "
              f"plane went dark under chaos): {rt_zeroed}")
        return 1
    if rt["complete_frac"] < 0.99:
        print(f"FAIL: only {rt['n_complete']}/{rt['n_sampled_ok']} sampled "
              "completed requests reconstruct gap-free waterfalls "
              f"({rt['complete_frac']:.1%} < 99%) — hops are being "
              "orphaned or evicted")
        return 1
    if rt["n_forced_traces"] != rt["n_forced_reqs"]:
        print(f"FAIL: {rt['n_forced_reqs']} shed/failed requests but only "
              f"{rt['n_forced_traces']} forced traces stored — failures "
              "must be traced at 100% regardless of head sampling")
        return 1
    if rt["coverage_p50"] < 0.9:
        print("FAIL: median attribution coverage "
              f"{rt['coverage_p50']:.1%} < 90% — client-observed latency "
              "is leaking out of the route/wire/queue/kernel buckets")
        return 1
    print(f"OK: rtrace leg — {rt['n_complete']}/{rt['n_sampled_ok']} "
          "sampled completions reconstruct gap-free waterfalls, "
          f"{rt['n_forced_traces']}/{rt['n_forced_reqs']} failures force-"
          f"traced, attribution coverage p50 {rt['coverage_p50']:.1%}, "
          f"{int(rc.get('degraded', 0))} degraded trace(s) never failed "
          "a request")

    # -- leg 12: the device observatory (obs/devprof.py) -------------------
    from test_devprof import run_devprof_drill

    dv = run_devprof_drill(seed=7)
    dc = dv["counters"]
    print("== devprof stepping drill (seed=7, 3 workers, growing "
          "topk_rmv shapes) ==")
    print(f"  devprof.compiles={int(dc.get('devprof.compiles', 0))} "
          f"devprof.dispatches={int(dc.get('devprof.dispatches', 0))} "
          f"capacity_growth={dv['n_capacity_growth']}/{dv['n_compiles']}")
    dv_zeroed = sorted(
        k for k in ("devprof.compiles", "devprof.dispatches")
        if not dc.get(k, 0)
    )
    if dv_zeroed:
        print("FAIL: devprof counters regressed to zero (the compile "
              f"observatory went dark under the storm): {dv_zeroed}")
        return 1
    if dv["unattributed"]:
        print(f"FAIL: {dv['unattributed']}/{dv['n_compiles']} compile "
              "events lack a site, changed axis, or signature — every "
              "recompile must name what moved")
        return 1
    if dv["n_capacity_growth"] < dv["n_compiles"] - 1:
        print("FAIL: the dominant churn source is not the topk_rmv "
              f"capacity axis ({dv['n_capacity_growth']} of "
              f"{dv['n_compiles']} compiles name slot_score axis3) — "
              "attribution is pointing at the wrong axis")
        return 1
    if dv["digest_on"] != dv["digest_off"]:
        print("FAIL: the CCRDT_DEVPROF=0 kill-switch arm diverged from "
              "the observed arm — observation is perturbing merge "
              "results")
        return 1
    if dv["off_devprof_counters"] or dv["off_events"]:
        print("FAIL: the kill-switch arm still emitted devprof counters/"
              f"events ({dv['off_devprof_counters']} counter keys, "
              f"{dv['off_events']} events) — CCRDT_DEVPROF=0 must be "
              "fully dark")
        return 1
    print(f"OK: devprof leg — {dv['n_compiles']} storm compiles all "
          "attributed to (site, changed axis), "
          f"{dv['n_capacity_growth']} naming topk_rmv capacity growth, "
          "kill-switch arm byte-identical and dark")
    return 0


if __name__ == "__main__":
    sys.exit(main())
