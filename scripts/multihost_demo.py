"""Multi-process convergence demo/check: one OS process per simulated host.

Usage: python scripts/multihost_demo.py <process_id> <num_processes> <port>

Each process owns `local` CPU devices = that many replicas. Every replica
applies a DIFFERENT deterministic op batch (seeded by global replica id, so
any process can reconstruct the full workload for the reference check),
then `hierarchical_reconcile` joins all replicas — inside each host, then
across hosts over the real cross-process collective backend. Each process
asserts its local shards' observables equal a single-process reference
that applied and merged everything, then prints MULTIHOST-OK.

Run under tests/test_multihost.py; also runnable by hand.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scripts.cover import install_child_cover  # noqa: E402

install_child_cover()  # no-op outside `make cover` runs

LOCAL_DEVICES = 4
I, DCS, K, M, B = 256, 8, 8, 2, 64


def replica_ops(r: int, n_dcs: int):
    """Deterministic per-replica op batch (any process can rebuild all)."""
    import numpy as np

    rng = np.random.default_rng(1000 + r)
    return dict(
        add_key=np.zeros((1, B), np.int32),
        add_id=rng.integers(0, I, (1, B)).astype(np.int32),
        add_score=rng.integers(1, 10_000, (1, B)).astype(np.int32),
        add_dc=np.full((1, B), r % n_dcs, np.int32),
        add_ts=np.arange(1, B + 1, dtype=np.int32).reshape(1, B),
        rmv_key=np.zeros((1, 4), np.int32),
        rmv_id=rng.integers(0, I, (1, 4)).astype(np.int32),
        rmv_vc=rng.integers(0, B // 2, (1, 4, DCS)).astype(np.int32),
    )


def main() -> None:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    from antidote_ccrdt_tpu.parallel import multihost as mh

    mh.initialize(
        f"localhost:{port}", nproc, pid, cpu_devices_per_process=LOCAL_DEVICES
    )

    import jax
    import jax.numpy as jnp
    import numpy as np

    from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps, make_dense

    R = nproc * LOCAL_DEVICES
    D = make_dense(n_ids=I, n_dcs=DCS, size=K, slots_per_id=M)
    mesh = mh.global_replica_mesh()
    assert mesh.shape == {"dcn": nproc, "dc": LOCAL_DEVICES, "key": 1}, mesh.shape

    state = mh.init_global_state(lambda: D.init(n_replicas=R, n_keys=1), mesh)

    local_rs = range(pid * LOCAL_DEVICES, (pid + 1) * LOCAL_DEVICES)
    local = [replica_ops(r, DCS) for r in local_rs]
    stacked = {
        k: np.concatenate([o[k] for o in local], axis=0) for k in local[0]
    }
    ops = TopkRmvOps(**mh.ops_from_process_local(stacked, mesh))

    apply_sharded = jax.jit(
        lambda st, op: D.apply_ops(st, op, collect_dominated=False)[0],
        out_shardings=mh.state_sharding(mesh),
    )
    state = apply_sharded(state, ops)
    # D.merge is shape-polymorphic over leading axes, so it serves as the
    # single-replica combiner under hierarchical_reconcile's vmap.
    state = mh.hierarchical_reconcile(state, D.merge, mesh)

    mine = mh.process_local_shards(state)
    obs_mine = jax.device_get(
        D.observe(jax.tree.map(jnp.asarray, mine))
    )

    # Single-process reference: apply every replica's ops, fold all merges.
    ref_state = D.init(n_replicas=R, n_keys=1)
    all_ops = [replica_ops(r, DCS) for r in range(R)]
    ref_ops = TopkRmvOps(**{
        k: jnp.asarray(np.concatenate([o[k] for o in all_ops], axis=0))
        for k in all_ops[0]
    })
    ref_state, _ = D.apply_ops(ref_state, ref_ops, collect_dominated=False)
    folded = jax.tree.map(lambda a: a[:1], ref_state)
    for r in range(1, R):
        folded = D.merge(folded, jax.tree.map(lambda a: a[r : r + 1], ref_state))
    obs_ref = jax.device_get(D.observe(folded))

    for r in range(LOCAL_DEVICES):
        assert (obs_mine.valid[r] == obs_ref.valid[0]).all()
        v = obs_ref.valid[0]
        assert (obs_mine.ids[r][v] == obs_ref.ids[0][v]).all()
        assert (obs_mine.scores[r][v] == obs_ref.scores[0][v]).all()
    print(f"MULTIHOST-OK {pid}", flush=True)


if __name__ == "__main__":
    main()
