"""Two-zone TCP fleet demo: the topo/ hierarchy against real sockets.

Supervises the real-process gossip drill (scripts/net_gossip_demo.py)
twice over six localhost workers split into two zones (za: w0-w2,
zb: w3-w5):

1. **topo run** — routers installed (`--topo`), chained-delta gossip,
   and the za ANCHOR (computed with the same rendezvous hash the fleet
   uses) SIGKILLed mid-run. Survivors must fail over to the runner-up
   anchor, keep relaying across the zone boundary, and converge to the
   sequential single-process reference digest.
2. **baseline run** — the same fleet full-mesh (no router), as the
   traffic yardstick and the bit-identical-convergence witness.

Acceptance (exit 0 only if ALL hold):

* every surviving worker's digest == the sequential reference, in BOTH
  runs (topology is state-transparent);
* the survivors' merged `topo.cross_zone.frames` counter is nonzero and
  the flight logs contain a `topo.anchor_change` event moving off the
  killed anchor (failover actually happened, observably);
* cross-DCN economy: counting `frame.send` events whose sender and
  receiver zones differ — the same measurement applied to both runs'
  flight logs — the topo fleet crosses the zone boundary at most half
  as often as the full mesh (in practice ~O(zones)/O(peers), printed).

``--out TOPO_rNN.json`` additionally dumps the run's merged counters,
digests, failover events, and the cross-traffic ratio as a committed
round artifact (scripts/bench_gate.py reports the cross-zone bytes of
these rounds alongside the BENCH_r* throughput gate).

Run:  python scripts/topo_demo.py          (also: make topo-demo)
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

DEMO = os.path.join(REPO, "scripts", "net_gossip_demo.py")

ZONES = {
    "w0": "za", "w1": "za", "w2": "za",
    "w3": "zb", "w4": "zb", "w5": "zb",
}


def _spawn_fleet(root: str, obs_dir: str, topo: bool, args) -> dict:
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["CCRDT_OBS_DIR"] = obs_dir
    procs = {}
    for member, zone in ZONES.items():
        cmd = [
            sys.executable, DEMO, "--root", root, "--member", member,
            "--n-members", str(len(ZONES)), "--type", args.type,
            "--zone", zone, "--delta",
            "--timeout", str(args.timeout),
            "--step-sleep", str(args.step_sleep),
        ]
        if topo:
            cmd += ["--topo", "--lag-anchor-ops", str(args.lag_anchor_ops)]
        procs[member] = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, text=True,
        )
    return procs


def _wait_step(root: str, member: str, step: int, timeout: float) -> bool:
    """Poll the worker's obs-<member>.json status drop until it reports
    `step` (or the deadline passes)."""
    path = os.path.join(root, f"obs-{member}.json")
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with open(path) as f:
                if json.load(f).get("step", -1) >= step:
                    return True
        except (OSError, ValueError):
            pass
        time.sleep(0.05)
    return False


def _reap(procs: dict, timeout: float) -> dict:
    outs = {}
    for member, p in procs.items():
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            outs[member] = (None, out)  # hung — degrade-never-hang violated
            continue
        outs[member] = (p.returncode, out)
    return outs


def _finals(root: str) -> dict:
    out = {}
    for path in glob.glob(os.path.join(root, "final-*.json")):
        try:
            with open(path) as f:
                doc = json.load(f)
            out[doc["member"]] = doc
        except (OSError, ValueError, KeyError):
            continue
    return out


def _cross_zone_sends(obs_dir: str) -> int:
    """Count frame.send events whose sender and receiver live in
    different zones — the topology-independent cross-DCN yardstick."""
    from antidote_ccrdt_tpu.obs import events as obs_events

    n = 0
    for evs in obs_events.scan_dir(obs_dir).values():
        for ev in evs:
            if ev.get("kind") != "frame.send":
                continue
            src = ZONES.get(ev.get("member", ""))
            dst = ZONES.get(ev.get("peer", ""))
            if src and dst and src != dst:
                n += 1
    return n


def _failover_events(obs_dir: str, victim: str) -> list:
    from antidote_ccrdt_tpu.obs import events as obs_events

    logs = obs_events.scan_dir(obs_dir)
    return [
        ev for ev in obs_events.iter_kinds(logs, "topo.anchor_change")
        if ev.get("old") == victim and ev.get("new") != victim
        and ev.get("member") != victim
    ]


def _next_round_path() -> str:
    taken = [
        int(m.group(1))
        for p in glob.glob(os.path.join(REPO, "TOPO_r*.json"))
        if (m := re.search(r"TOPO_r(\d+)\.json$", os.path.basename(p)))
    ]
    return os.path.join(REPO, f"TOPO_r{max(taken, default=0) + 1:02d}.json")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--type", default="topk_rmv")
    ap.add_argument("--timeout", type=float, default=0.5)
    ap.add_argument("--step-sleep", type=float, default=0.15)
    ap.add_argument("--kill-at-step", type=int, default=3,
                    help="SIGKILL the za anchor once it reports this step")
    ap.add_argument("--lag-anchor-ops", type=float, default=8.0)
    ap.add_argument("--worker-timeout", type=float, default=240.0)
    ap.add_argument("--out", default="",
                    help="also write a TOPO_rNN.json round artifact "
                    "('auto' picks the next free round number)")
    args = ap.parse_args()

    from antidote_ccrdt_tpu.topo import rendezvous_anchor
    from elastic_demo import reference_digest

    # JSON-normalize (tuples -> lists) to match the workers' final-json
    # round-trip, exactly as the slow TCP test compares digests.
    ref = json.loads(json.dumps(reference_digest(args.type)))
    za_members = sorted(m for m, z in ZONES.items() if z == "za")
    victim = rendezvous_anchor("za", za_members)
    failures = []

    with tempfile.TemporaryDirectory(prefix="topo-demo-") as tmp:
        # -- leg 1: the zone topology, anchor killed mid-run ----------------
        t_root = os.path.join(tmp, "topo")
        t_obs = os.path.join(tmp, "topo-obs")
        os.makedirs(t_root)
        print(f"== topo run: 2 zones x 3 workers, killing za anchor "
              f"{victim} at step {args.kill_at_step} ==")
        procs = _spawn_fleet(t_root, t_obs, topo=True, args=args)
        if _wait_step(t_root, victim, args.kill_at_step, 120.0):
            procs[victim].send_signal(signal.SIGKILL)
            print(f"   SIGKILL -> {victim}")
        else:
            failures.append(f"{victim} never reached step "
                            f"{args.kill_at_step} — cannot stage the kill")
            procs[victim].kill()
        outs = _reap(procs, args.worker_timeout)
        for member, (rc, out) in outs.items():
            if member != victim and rc != 0:
                failures.append(f"topo worker {member} rc={rc}:\n{out}")

        finals = _finals(t_root)
        survivors = sorted(m for m in ZONES if m != victim)
        topo_digests = {}
        merged: dict = {}
        for m in survivors:
            doc = finals.get(m)
            if doc is None:
                failures.append(f"topo worker {m} left no final json")
                continue
            topo_digests[m] = doc["digest"]
            if doc["digest"] != ref:
                failures.append(
                    f"topo {m} diverged from the sequential reference")
            for k, v in doc.get("metrics", {}).items():
                merged[k] = merged.get(k, 0) + v
        cross_frames = merged.get("topo.cross_zone.frames", 0)
        cross_bytes = merged.get("topo.cross_zone.bytes", 0)
        if not cross_frames:
            failures.append("topo.cross_zone.frames == 0 — the hierarchy "
                            "never crossed the DCN")
        if not merged.get("topo.relays", 0):
            failures.append("topo.relays == 0 — anchors never relayed")
        failovers = _failover_events(t_obs, victim)
        if not failovers:
            failures.append(f"no topo.anchor_change away from {victim} in "
                            "the flight logs — failover unobserved")
        topo_cross_sends = _cross_zone_sends(t_obs)
        print(f"   survivors converged: "
              f"{sorted(m for m, d in topo_digests.items() if d == ref)}")
        print(f"   topo.cross_zone.frames={cross_frames} "
              f"bytes={cross_bytes} relays={merged.get('topo.relays', 0)} "
              f"anchor_changes={merged.get('topo.anchor_changes', 0)}")
        print(f"   failover events (old={victim}): {len(failovers)}")
        print(f"   codec: zlib_frames={merged.get('net.codec_zlib_frames', 0)} "
              f"saved_bytes={merged.get('net.codec_saved_bytes', 0)} "
              f"lag_anchor_cuts={merged.get('net.lag_anchor_cuts', 0)}")

        # -- leg 2: full-mesh baseline, same fleet shape --------------------
        b_root = os.path.join(tmp, "mesh")
        b_obs = os.path.join(tmp, "mesh-obs")
        os.makedirs(b_root)
        print("== baseline run: same fleet, full mesh ==")
        outs = _reap(_spawn_fleet(b_root, b_obs, topo=False, args=args),
                     args.worker_timeout)
        for member, (rc, out) in outs.items():
            if rc != 0:
                failures.append(f"baseline worker {member} rc={rc}:\n{out}")
        base_digests = {
            m: doc["digest"] for m, doc in _finals(b_root).items()
        }
        for m, d in sorted(base_digests.items()):
            if d != ref:
                failures.append(f"baseline {m} diverged from the reference")
        if topo_digests and base_digests and not failures:
            # Both fleets equal the reference => bit-identical to each
            # other; said explicitly because it is the headline claim.
            print("   topo and full-mesh digests are bit-identical "
                  "(both == sequential reference)")
        base_cross_sends = _cross_zone_sends(b_obs)

        ratio = (topo_cross_sends / base_cross_sends
                 if base_cross_sends else float("inf"))
        print(f"== cross-DCN economy: topo={topo_cross_sends} "
              f"mesh={base_cross_sends} frame sends "
              f"(ratio {ratio:.2f}) ==")
        if not topo_cross_sends:
            failures.append("topo run shows zero cross-zone frame.send "
                            "events — nothing crossed at all?")
        elif topo_cross_sends * 2 > base_cross_sends:
            failures.append(
                f"topo fleet crossed the DCN {topo_cross_sends} times vs "
                f"full mesh {base_cross_sends} — expected at most half "
                "(O(zones), not O(peers))")

        if args.out:
            path = (_next_round_path() if args.out == "auto"
                    else args.out)
            doc = {
                "demo": "topo_demo",
                "type": args.type,
                "fleet": ZONES,
                "killed_anchor": victim,
                "converged": sorted(
                    m for m, d in topo_digests.items() if d == ref),
                "baseline_converged": sorted(
                    m for m, d in base_digests.items() if d == ref),
                "counters": merged,
                "cross_zone": {
                    "topo_frame_sends": topo_cross_sends,
                    "mesh_frame_sends": base_cross_sends,
                    "ratio": ratio,
                    "frames": cross_frames,
                    "bytes": cross_bytes,
                },
                "failover_events": failovers[:8],
                "ok": not failures,
            }
            with open(path, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            print(f"   round artifact -> {path}")

    if failures:
        print("FAIL:")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(f"OK: 2-zone fleet survived anchor SIGKILL ({victim}), "
          f"converged bit-identically with full mesh, and crossed the "
          f"DCN {topo_cross_sends}x vs {base_cross_sends}x")
    return 0


if __name__ == "__main__":
    sys.exit(main())
