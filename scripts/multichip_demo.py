"""Mesh-sharded multichip acceptance drill (mesh/ tentpole gate).

Two phases over 8 forced host devices (the same compiled programs run
unchanged on a real TPU mesh; CI has no multi-chip hardware):

* Phase A (in-process, real FS-transport pair): a mesh-sharded anchor
  (`MeshPlan` (2,4), per-shard digest slices + psnap blobs) diverges on
  ONE partition; the peer repairs through the mesh-grouped
  `PartialAntiEntropy`. Gated: cross-slice anti-entropy ships only
  shard-local psnap slices — >= 5x fewer bytes than the whole-instance
  snapshot the legacy path would pull — and the repaired digest vector
  is BIT-IDENTICAL to the producer's. Also times the jitted ICI JOIN
  all-reduce (`mesh/reduce.py`) for the committed carrier metrics.

* Phase B (real processes): a 2-slice fleet of 3 mesh-sharded
  elastic_demo workers (CCRDT_MESH=1, CCRDT_ZONE=slice<i>, each with
  its own forced-8-device backend) gossips through a shared directory;
  one worker is SIGKILLed mid-load and NOT restarted. Gated: the
  survivors adopt its replicas and converge BIT-IDENTICALLY to the
  unsharded sequential reference, every survivor ran ICI reduces, and
  the PR 10 replay certificate verifies over the sharded flight logs.

Writes the measurements to MULTICHIP_r06.json (committed as the carrier
`scripts/bench_gate.py evaluate_mesh` gates future rounds against) and
exits nonzero if any gate fails.

Run:  make multichip-demo
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import struct
import subprocess
import sys
import tempfile
import time
import zlib

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "scripts")
)

from scripts.cover import install_child_cover  # noqa: E402

install_child_cover()  # no-op outside `make cover` runs

import partition_demo as pd  # noqa: E402  (geometry + op streams, I=256)

DEMO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "elastic_demo.py")
P = 8
MIN_RATIO = 5.0  # the acceptance gate from ISSUE/ROADMAP
MEMBERS = ("w0", "w1", "w2")
SLICE_OF = {"w0": 0, "w1": 0, "w2": 1}  # 2 slices; w1 shares slice0
VICTIM = "w1"


def _force_host_devices() -> None:
    """Give THIS process an 8-virtual-device CPU backend (same recipe as
    tests/conftest.py — env flag before the first `import jax`, then the
    config override the axon sitecustomize cannot undo)."""
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        pass  # older JAX: the XLA_FLAGS mutation already took effect


def phase_a(report: dict) -> list:
    """Shard-local anti-entropy byte gate + the ICI reduce microbench.
    Mutates `report`, returns the list of failed check names."""
    import math

    import numpy as np

    import jax

    from antidote_ccrdt_tpu.core import partition as pt
    from antidote_ccrdt_tpu.mesh import MeshPlan
    from antidote_ccrdt_tpu.mesh import reduce as mesh_reduce
    from antidote_ccrdt_tpu.net.transport import FsTransport, GossipNode
    from antidote_ccrdt_tpu.parallel.elastic import (
        DeltaPublisher, PartialAntiEntropy, sweep_deltas,
    )

    dense = pd._build()
    plan = MeshPlan.build(n_dc=2, n_key=4, partitions=P)
    part_map = pt.part_of(np.arange(pd.I), P)
    p_star = int(np.bincount(part_map, minlength=P).argmax())
    ids_p = np.arange(pd.I, dtype=np.int32)[part_map == p_star]
    all_ids = np.arange(pd.I, dtype=np.int32)

    def apply(st, step, pool):
        st, _ = dense.apply_ops(
            st, pd.gen_ops(step, range(pd.R), pool), collect_dominated=False
        )
        return st

    bad = []
    root = tempfile.mkdtemp(prefix="multichip-a-")
    try:
        a = GossipNode(FsTransport(root, "a"))
        b = GossipNode(FsTransport(root, "b"))
        a.heartbeat(), b.heartbeat()
        pub = DeltaPublisher(
            a, dense, name="topk_rmv", full_every=1, keep=1, partitions=P,
            mesh_plan=plan,
        )
        pae = PartialAntiEntropy(b, partitions=P, mesh_plan=plan)
        curs = {}

        # Shared prefix over the whole id space, one ICI reduce at the
        # publish boundary (the mesh loop's shape), then the peer
        # ingests the anchor.
        st_a = plan.place(dense.init(pd.R, pd.NK))
        for step in range(3):
            st_a = apply(st_a, step, all_ids)
        st_a = mesh_reduce.ici_reduce(dense, plan, st_a, metrics=a.metrics)
        pub.publish(st_a)
        st_b, _ = sweep_deltas(
            b, dense, plan.place(dense.init(pd.R, pd.NK)), curs, partial=pae
        )
        if not np.array_equal(
            pt.state_digests(st_b, P), pt.state_digests(st_a, P)
        ):
            bad.append("phase_a_prefix_converged")

        # The divergence: one step confined to p*'s ids (the reduce
        # joins rows, but the new content lives only in p*'s id slice,
        # so the digest gap stays {p*, meta}).
        st_a = apply(st_a, 3, ids_p)
        st_a = mesh_reduce.ici_reduce(dense, plan, st_a, metrics=a.metrics)
        pub.publish(st_a)

        raw_whole = b.transport.fetch("a")
        whole_bytes = len(raw_whole) if raw_whole else 0
        raw_dig = b.transport.fetch_digest("a")
        dig_bytes = len(raw_dig) if raw_dig else 0
        c0 = dict(b.metrics.counters)
        st_b, _ = sweep_deltas(b, dense, st_b, curs, partial=pae)
        c1 = dict(b.metrics.counters)
        psnap_bytes = int(
            c1.get("net.psnap_bytes", 0) - c0.get("net.psnap_bytes", 0)
        )
        partial_bytes = psnap_bytes + dig_bytes
        ratio = whole_bytes / max(1, partial_bytes)
        repair_identical = bool(np.array_equal(
            pt.state_digests(st_b, P), pt.state_digests(st_a, P)
        ))
        cross_fetches = int(c1.get("mesh.cross_slice_fetches", 0))
        cross_bytes = int(c1.get("mesh.cross_slice_bytes", 0))
        wasted = int(c1.get("net.psnap_wasted", 0))
        shard_slices = int(a.metrics.counters.get("mesh.shard_digest_slices", 0))

        # Microbench: the jitted reduce on the placed, row-divergent
        # state (one warm call already ran above via the boundary
        # reduces — time steady-state latency).
        iters = 20
        times = []
        t_all0 = time.perf_counter()
        for _ in range(iters):
            t0 = time.perf_counter()
            jax.block_until_ready(mesh_reduce.ici_reduce(dense, plan, st_a))
            times.append((time.perf_counter() - t0) * 1000.0)
        elapsed = time.perf_counter() - t_all0
        elems = sum(
            int(np.prod(leaf.shape))
            for leaf in jax.tree_util.tree_leaves(st_a)
        )
        stages = max(1, math.ceil(math.log2(plan.n_dc)))
    finally:
        shutil.rmtree(root, ignore_errors=True)

    if ratio < MIN_RATIO:
        bad.append("phase_a_partial_ge_5x_smaller")
    if not repair_identical:
        bad.append("phase_a_repair_digests_bit_identical")
    if cross_fetches <= 0 or cross_bytes <= 0:
        bad.append("phase_a_cross_slice_counters_lit")
    if wasted != 0:
        bad.append("phase_a_no_wasted_psnaps")
    if shard_slices < plan.n_key:
        bad.append("phase_a_anchor_published_per_shard")

    report.update({
        "mesh": {"n_dc": plan.n_dc, "n_key": plan.n_key},
        "p_star": p_star,
        "p_star_ids": int(len(ids_p)),
        "whole_resync_bytes": whole_bytes,
        "partial_resync_bytes": {
            "psnaps": psnap_bytes, "digests": dig_bytes,
            "total": partial_bytes,
        },
        "bytes_ratio": round(ratio, 3),
        "min_ratio": MIN_RATIO,
        "cross_slice_bytes": cross_bytes,
        "cross_slice_fetches": cross_fetches,
        "shard_digest_slices": shard_slices,
        "ici_reduce_ms_p50": round(sorted(times)[len(times) // 2], 3),
        "mesh_merges_per_sec": round(
            elems * stages * iters / max(elapsed, 1e-9), 1
        ),
    })
    return bad


def _worker_env(root: str, member: str) -> dict:
    """Hermetic forced-8-device CPU env for one mesh-sharded worker,
    zone-labeled by its mesh slice (tests/conftest.py's
    cpu_mesh_subprocess_env recipe, inlined so the demo runs without the
    test rig on sys.path)."""
    from antidote_ccrdt_tpu.topo import zones

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = ":".join(
        p for p in env.get("PYTHONPATH", "").split(":") if "axon" not in p
    )
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["CCRDT_MESH"] = "1"
    env[zones.ENV_ZONE] = zones.slice_zone(SLICE_OF[member])
    env["CCRDT_OBS_DIR"] = os.path.join(root, "obs")
    env["CCRDT_METRICS_DIR"] = os.path.join(root, "metrics")
    return env


def _launch(root: str, member: str):
    return subprocess.Popen(
        [sys.executable, DEMO, "--root", root, "--member", member,
         "--n-members", str(len(MEMBERS)), "--type", "topk_rmv",
         "--delta", "--partitions", str(P), "--publish-every", "1"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        env=_worker_env(root, member), text=True,
    )


def _snap_seq(root: str, member: str):
    """The 8-byte step header of `member`'s published anchor, or None."""
    try:
        with open(os.path.join(root, f"snap-{member}"), "rb") as f:
            hdr = f.read(8)
    except OSError:
        return None
    if len(hdr) != 8:
        return None
    return struct.unpack("<Q", hdr)[0]


def phase_b(report: dict, timeout: float) -> list:
    """The real-process 2-slice fleet with a mid-load SIGKILL. Mutates
    `report`, returns the list of failed check names."""
    from scripts.elastic_demo import reference_digest

    bad = []
    root = tempfile.mkdtemp(prefix="multichip-b-")
    procs = {m: _launch(root, m) for m in MEMBERS}

    # Kill window: the victim has published mid-load progress (anchors
    # land every 4th publish with --publish-every 1, so seq 4 of 10
    # steps) but the run is far from done.
    kill_seq = None
    deadline = time.time() + timeout
    while time.time() < deadline:
        seq = _snap_seq(root, VICTIM)
        if seq is not None and 3 <= seq < 8:
            kill_seq = seq
            break
        if procs[VICTIM].poll() is not None:
            bad.append("phase_b_victim_alive_at_kill_point")
            break
        time.sleep(0.01)
    if kill_seq is None and not bad:
        bad.append("phase_b_victim_reached_kill_window")
    if not bad:
        procs[VICTIM].kill()  # SIGKILL: no atexit, no flush
        procs[VICTIM].wait()

    rcs, outs = {}, {}
    for m, p in procs.items():
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        rcs[m], outs[m] = p.returncode, out

    survivors = [m for m in MEMBERS if m != VICTIM]
    ref = json.loads(json.dumps(reference_digest("topk_rmv")))
    finals = {}
    for m in survivors:
        path = os.path.join(root, f"final-{m}.json")
        if not os.path.exists(path):
            bad.append(f"phase_b_final_{m}")
            print(
                f"  {m}: no final (rc={rcs[m]})\n{outs[m][-2000:]}",
                file=sys.stderr,
            )
            continue
        with open(path) as f:
            finals[m] = json.load(f)
        if finals[m]["digest"] != ref:
            bad.append(f"phase_b_digest_{m}")
    if os.path.exists(os.path.join(root, f"final-{VICTIM}.json")):
        bad.append("phase_b_victim_stayed_dead")

    ici_per_worker = {
        m: int(finals.get(m, {}).get("metrics", {}).get("mesh.ici_reduces", 0))
        for m in survivors
    }
    if not all(v > 0 for v in ici_per_worker.values()):
        bad.append("phase_b_every_survivor_ran_ici_reduces")

    # PR 10 certificate over the SHARDED fleet's flight logs (the killed
    # incarnation's spill included) + the survivors' final digests vs
    # the unsharded sequential reference.
    from antidote_ccrdt_tpu.obs import audit as obs_audit

    # The topk drill digest is a nested list of [id, score] pairs; the
    # certifier's agreement probe compares exact ints, so hand it the
    # canonical-JSON CRC of each observable (same scalarization as
    # scripts/audit_demo.py).
    def _crc(digest) -> int:
        return zlib.crc32(
            json.dumps(digest, sort_keys=True).encode("utf-8")
        )

    cert = obs_audit.certify(
        obs_dir=os.path.join(root, "obs"),
        digests={m: _crc(finals[m]["digest"]) for m in finals},
        reference=_crc(ref),
    )
    if not cert.get("ok"):
        bad.append("phase_b_certificate_verifies")

    report.update({
        "victim": VICTIM,
        "kill_seq": kill_seq,
        "victim_rc": rcs.get(VICTIM),
        "slices": {m: SLICE_OF[m] for m in MEMBERS},
        "zones_reported": {
            m: finals.get(m, {}).get("zone") for m in survivors
        },
        "survivor_ici_reduces": ici_per_worker,
        "survivor_counters": {
            m: {
                k: int(v)
                for k, v in sorted(
                    finals.get(m, {}).get("metrics", {}).items()
                )
                if k.startswith("mesh.")
            }
            for m in survivors
        },
        "certifier_checks": cert.get("checks", {}),
    })
    if not bad:
        shutil.rmtree(root, ignore_errors=True)
    else:
        report["phase_b_root"] = root
    return bad


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--out",
        default=os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "MULTICHIP_r06.json",
        ),
    )
    ap.add_argument("--timeout", type=float, default=300.0)
    args = ap.parse_args()

    _force_host_devices()
    import jax

    n_dev = len(jax.devices())
    if n_dev < 8:
        print(f"FAIL: only {n_dev} devices after forcing 8", file=sys.stderr)
        return 1

    report = {
        "drill": "multichip_demo",
        "n_devices": n_dev,
        "geometry": {
            "R": pd.R, "NK": pd.NK, "I": pd.I, "DCS": pd.DCS, "K": pd.K,
            "M": pd.M, "B": pd.B, "Br": pd.Br,
        },
        "partitions": P,
    }
    t0 = time.time()
    failed = phase_a(report)
    print(
        f"phase A: {report['bytes_ratio']:.1f}x fewer anti-entropy bytes "
        f"({report['partial_resync_bytes']['total']} vs "
        f"{report['whole_resync_bytes']} whole), ici p50 "
        f"{report['ici_reduce_ms_p50']}ms"
    )
    failed += phase_b(report, args.timeout)
    report["storm_s"] = round(time.time() - t0, 3)

    checks = {
        "partial_ge_5x_smaller": "phase_a_partial_ge_5x_smaller" not in failed,
        "repair_digests_bit_identical": (
            "phase_a_repair_digests_bit_identical" not in failed
        ),
        "shard_local_slices_only": all(
            f not in failed
            for f in ("phase_a_cross_slice_counters_lit",
                      "phase_a_no_wasted_psnaps",
                      "phase_a_anchor_published_per_shard")
        ),
        "survivors_match_sequential_reference": not any(
            f.startswith("phase_b_digest") or f.startswith("phase_b_final")
            for f in failed
        ),
        "every_survivor_ran_ici_reduces": (
            "phase_b_every_survivor_ran_ici_reduces" not in failed
        ),
        "certificate_verifies": "phase_b_certificate_verifies" not in failed,
        "kill_landed_mid_load": not any(
            f in failed
            for f in ("phase_b_victim_alive_at_kill_point",
                      "phase_b_victim_reached_kill_window")
        ),
    }
    report["checks"] = checks
    report["pass"] = report["ok"] = not failed
    report["rc"] = 0 if not failed else 1
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print(json.dumps(report, indent=2, sort_keys=True))
    if failed:
        print(f"FAIL: {', '.join(sorted(set(failed)))}", file=sys.stderr)
        return 1
    print(
        f"PASS: mesh-sharded fleet survived a mid-load SIGKILL of "
        f"{VICTIM} (2 slices, seq {report['kill_seq']}), converged "
        f"bit-identically, certificate ok; shard-local anti-entropy "
        f"{report['bytes_ratio']:.1f}x smaller than whole-instance"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
