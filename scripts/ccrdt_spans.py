"""Span-trace CLI: merge a fleet's span spills and attribute round time.

`obs.spans` has every worker spill phase spans (`round.*`) plus NTP-style
clock-offset samples into ``CCRDT_OBS_DIR``; this tool turns a directory
of ``spans-*.jsonl`` files into the two artifacts an operator wants::

    # One Perfetto/Chrome trace-event JSON with every worker's spans on
    # a single clock-aligned timeline (load in ui.perfetto.dev).
    python scripts/ccrdt_spans.py merge /path/to/obs-dir -o trace.json

    # Dispatch-gap attribution: per round, how much host time each phase
    # accounts for, what was serial vs overlappable (other threads), and
    # the residue no span owns — reconciled against the measured
    # round.e2e wall time.
    python scripts/ccrdt_spans.py attribute /path/to/obs-dir

Exit codes: 0 on success; both subcommands exit 1 when the directory
holds no span records. `attribute --min-coverage F` exits 1 when the
fleet p50 serial coverage falls below F (the spans-demo smoke gate).

Alignment: offsets are RTT-halved estimates piggybacked on live frames
({hello}/{metrics_req}); members unreachable in the offset graph render
unshifted and are listed in the merge report.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from antidote_ccrdt_tpu.obs import spans as obs_spans  # noqa: E402


def cmd_merge(args: argparse.Namespace) -> int:
    by_member = obs_spans.scan_dir(args.obs_dir)
    n_spans = sum(
        1 for recs in by_member.values() for r in recs if r.get("k") == "span"
    )
    if not n_spans:
        print(f"no span records under {args.obs_dir}")
        return 1
    offsets = obs_spans.clock_offsets(by_member)
    shifts = obs_spans.align_offsets(offsets, by_member.keys())
    trace = obs_spans.to_chrome_trace(by_member, shifts=shifts)
    with open(args.out, "w") as f:
        json.dump(trace, f)
    ref = sorted(by_member)[0] if by_member else "?"
    # A member with no offset edge renders unshifted — call that out
    # rather than let a skewed lane masquerade as aligned.
    unaligned = sorted(
        m for m in by_member
        if m != ref and shifts.get(m, 0.0) == 0.0
        and m not in offsets
        and not any(m in peers for peers in offsets.values())
    )
    print(f"members : {len(by_member)} ({', '.join(sorted(by_member))})")
    print(f"spans   : {n_spans}")
    print(f"aligned : ref={ref} shifts=" + " ".join(
        f"{m}:{shifts.get(m, 0.0) * 1e3:+.3f}ms" for m in sorted(by_member)
    ))
    if unaligned:
        print(f"warning : no clock-offset path to {unaligned}; "
              f"their lanes are unshifted")
    print(f"wrote   : {args.out} ({len(trace['traceEvents'])} trace events; "
          f"load in ui.perfetto.dev)")
    return 0


def cmd_attribute(args: argparse.Namespace) -> int:
    by_member = obs_spans.scan_dir(args.obs_dir)
    att = obs_spans.attribute(by_member)
    if not att["fleet"]["rounds"]:
        print(f"no round.e2e spans under {args.obs_dir} "
              f"(did the workers run with CCRDT_SPANS=1?)")
        return 1
    if args.json:
        print(json.dumps(att))
    else:
        print(obs_spans.format_report(att))
    cov = att["fleet"]["coverage_p50"]
    if args.min_coverage is not None and cov < args.min_coverage:
        print(f"FAIL: fleet serial coverage p50 {cov:.1%} < "
              f"required {args.min_coverage:.1%} — load-bearing phases "
              f"are dark or the gap grew")
        return 1
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="merge and attribute a fleet's round-phase span traces"
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    m = sub.add_parser(
        "merge", help="merge spills into one aligned Perfetto trace JSON"
    )
    m.add_argument("obs_dir")
    m.add_argument("-o", "--out", default="spans_trace.json")
    m.set_defaults(fn=cmd_merge)

    a = sub.add_parser(
        "attribute", help="per-round critical path and dispatch-gap report"
    )
    a.add_argument("obs_dir")
    a.add_argument("--json", action="store_true", help="machine-readable")
    a.add_argument(
        "--min-coverage",
        type=float,
        default=None,
        help="exit 1 if fleet p50 serial coverage falls below this fraction",
    )
    a.set_defaults(fn=cmd_attribute)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
