"""Device-observatory CLI over the devprof plane (obs/devprof.py).

Four subcommands over a finished run's obs spill dir (the
``flight-*.jsonl`` streams every worker drops on exit — each compile
the observatory attributed rides a ``devprof.compile`` event carrying
its site, full abstract signature, and the structural diff vs the
site's previous signature)::

    # Top-N churn sites: compiles, total compile ms, deepest jit
    # cache, and the latest changed axis per site — "who is paying
    # the XLA tax, and which shape axis keeps moving".
    python scripts/ccrdt_devprof.py churn /path/to/obs-dir -n 10

    # One site's shape-growth timeline: every compile in order with
    # its changed axis, compile ms, and cache depth — the recompile
    # storm rendered as the axis walk that caused it.
    python scripts/ccrdt_devprof.py timeline /path/to/obs-dir \
        --site batch_merge.fold

    # Device-memory watermark report: live-buffer and pager HBM
    # gauges (vs CCRDT_PAGER_HBM_BUDGET) with high-watermarks, from
    # the workers' final scrape snapshots when present.
    python scripts/ccrdt_devprof.py watermarks /path/to/obs-dir

    # Run-vs-run diff of two committed DEVPROF_r*.json carriers:
    # steady-state recompiles, compile-ms share, overhead, and which
    # checks flipped.
    python scripts/ccrdt_devprof.py diff DEVPROF_r01.json DEVPROF_r02.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from antidote_ccrdt_tpu.obs import events  # noqa: E402


def _compiles(obs_dir: str) -> List[Dict[str, Any]]:
    logs = events.scan_dir(obs_dir)
    out: List[Dict[str, Any]] = []
    for member in sorted(logs):
        for e in logs[member]:
            if e.get("kind") == "devprof.compile":
                e = dict(e)
                e.setdefault("member", member)
                out.append(e)
    if not out:
        print(f"no devprof.compile events under {obs_dir}", file=sys.stderr)
        raise SystemExit(1)
    return out


def _by_site(evs: List[Dict[str, Any]]) -> Dict[str, List[Dict[str, Any]]]:
    sites: Dict[str, List[Dict[str, Any]]] = {}
    for e in evs:
        sites.setdefault(str(e.get("site", "?")), []).append(e)
    return sites


def cmd_churn(args) -> int:
    sites = _by_site(_compiles(args.obs_dir))
    rows = []
    for site, evs in sites.items():
        ms = sum(float(e.get("ms", 0.0)) for e in evs)
        depth = max(int(e.get("cache_depth", 0) or 0) for e in evs)
        rows.append({
            "site": site,
            "compiles": len(evs),
            "compile_ms": round(ms, 3),
            "max_cache_depth": depth,
            "last_axis": evs[-1].get("axis", "?"),
        })
    rows.sort(key=lambda r: (-r["compiles"], -r["compile_ms"]))
    rows = rows[: args.n]
    if args.json:
        print(json.dumps(rows, indent=1))
        return 0
    total = sum(r["compiles"] for r in rows)
    print(f"top {len(rows)} churn sites ({total} compiles):")
    for r in rows:
        print(
            f"  {r['site']:<28} {r['compiles']:>4} compiles "
            f"{r['compile_ms']:>9.1f}ms  depth {r['max_cache_depth']:>3}  "
            f"last: {r['last_axis']}"
        )
    return 0


def cmd_timeline(args) -> int:
    sites = _by_site(_compiles(args.obs_dir))
    evs = sites.get(args.site)
    if evs is None:
        print(
            f"site {args.site!r} has no compiles; sites: "
            f"{', '.join(sorted(sites))}",
            file=sys.stderr,
        )
        return 1
    evs.sort(key=lambda e: float(e.get("mono", 0.0)))
    if args.json:
        print(json.dumps(evs, indent=1))
        return 0
    print(f"{args.site}: {len(evs)} compiles")
    for i, e in enumerate(evs):
        print(
            f"  #{i:<3} {float(e.get('ms', 0.0)):>8.2f}ms  "
            f"depth {int(e.get('cache_depth', 0) or 0):>3}  "
            f"{e.get('axis', '?')}"
        )
    return 0


def _gauge(snap: Dict[str, Any], name: str) -> Optional[float]:
    v = snap.get(name)
    return float(v) if isinstance(v, (int, float)) else None


_WATERMARK_KEYS = (
    "live_buffer_bytes",
    "live_buffer_peak_bytes",
    "hbm_used_bytes",
    "hbm_budget_bytes",
    "hbm_peak_bytes",
    "hbm_occupancy",
)


def cmd_watermarks(args) -> int:
    # The workers' periodic status dumps (obs-<member>.json, atomic
    # replace) carry a "devprof" block; raw metrics dumps carry the
    # gauges flat under their devprof.* scrape names. Accept both.
    rows = []
    for name in sorted(os.listdir(args.obs_dir)):
        if not (name.startswith("obs-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(args.obs_dir, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        block = doc.get("devprof") or {}
        flat = doc.get("counters", doc)
        row: Dict[str, Any] = {"member": name[4:-5]}
        for k in _WATERMARK_KEYS:
            v = block.get(k)
            if not isinstance(v, (int, float)):
                v = _gauge(flat, f"devprof.{k}")
            row[k] = v
        if any(v is not None for k, v in row.items() if k != "member"):
            rows.append(row)
    if args.json:
        print(json.dumps(rows, indent=1))
        return 0
    if not rows:
        print(
            f"no devprof gauges in obs-*.json under {args.obs_dir}",
            file=sys.stderr,
        )
        return 1
    print("device-memory watermarks:")
    for r in rows:
        def b(v):
            return "-" if v is None else f"{v:,.0f}B"
        occ = (
            "-" if r["hbm_occupancy"] is None
            else f"{r['hbm_occupancy']:.1%}"
        )
        print(
            f"  {r['member']:<10} live {b(r['live_buffer_bytes'])} "
            f"(peak {b(r['live_buffer_peak_bytes'])})  "
            f"hbm {b(r['hbm_used_bytes'])}/{b(r['hbm_budget_bytes'])} "
            f"= {occ} (peak {b(r['hbm_peak_bytes'])})"
        )
    return 0


def cmd_diff(args) -> int:
    docs = []
    for p in (args.a, args.b):
        with open(p) as f:
            docs.append(json.load(f))
    a, b = docs
    keys = (
        "recompiles_per_100_rounds",
        "compile_ms_share_pct",
        "overhead_pct",
        "storm_cut_factor",
    )
    out: Dict[str, Any] = {"a": args.a, "b": args.b, "metrics": {}}
    for k in keys:
        va, vb = a.get(k), b.get(k)
        if isinstance(va, (int, float)) and isinstance(vb, (int, float)):
            out["metrics"][k] = {
                "a": va, "b": vb, "delta": round(vb - va, 3)
            }
    flips = {}
    for name in sorted(set(a.get("checks", {})) | set(b.get("checks", {}))):
        ca, cb = a.get("checks", {}).get(name), b.get("checks", {}).get(name)
        if ca != cb:
            flips[name] = {"a": ca, "b": cb}
    out["check_flips"] = flips
    out["pass"] = {"a": a.get("pass"), "b": b.get("pass")}
    if args.json:
        print(json.dumps(out, indent=1))
        return 0
    print(f"{os.path.basename(args.a)} -> {os.path.basename(args.b)}:")
    for k, d in out["metrics"].items():
        print(f"  {k:<28} {d['a']:>9} -> {d['b']:>9}  ({d['delta']:+})")
    if flips:
        for name, d in flips.items():
            print(f"  check {name}: {d['a']} -> {d['b']}")
    else:
        print("  no check flips")
    print(f"  pass: {out['pass']['a']} -> {out['pass']['b']}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="device-observatory CLI (compile churn, shape "
        "timelines, memory watermarks, run-vs-run diff)"
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("churn", help="top-N compile-churn sites")
    p.add_argument("obs_dir")
    p.add_argument("-n", type=int, default=10)
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_churn)

    p = sub.add_parser("timeline", help="one site's shape-growth timeline")
    p.add_argument("obs_dir")
    p.add_argument("--site", required=True)
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("watermarks", help="device-memory watermark report")
    p.add_argument("obs_dir")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_watermarks)

    p = sub.add_parser("diff", help="run-vs-run DEVPROF carrier diff")
    p.add_argument("a")
    p.add_argument("b")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_diff)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
