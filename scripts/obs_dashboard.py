"""Live terminal dashboard over a gossip fleet + propagation-path proof.

Two ways to run it:

* **Attach** to a running shared-directory fleet::

      python scripts/obs_dashboard.py --root /tmp/gossip-root \
          [--obs-dir $CCRDT_OBS_DIR] [--interval 0.5] [--frames N | --once]

  Each frame shows, per member: heartbeat age and the derived
  ALIVE/SUSPECT/DEAD state, published snapshot step, visible delta
  window, replication lag (ops and seconds, from the worker's own
  ``obs-<member>.json`` status drops), TCP send-queue depths, and the
  WAL durable watermark.

* **Demo** (`make obs-demo`): ``--demo`` spawns a 3-worker
  `net_gossip_demo` TCP fleet in delta mode with the full observability
  plane enabled (``CCRDT_OBS_DIR`` + ``CCRDT_METRICS_DIR`` +
  ``CCRDT_HTTP_PORT=0`` + ``CCRDT_PROFILE=1`` + ``CCRDT_SPANS=1``),
  renders live frames while it runs, and — while the workers are still
  alive — scrapes them over BOTH live surfaces (each worker's HTTP
  ``/metrics`` endpoint and the in-band TCP ``{metrics_req}`` frame),
  requiring lag gauges, profile.dispatch histogram buckets, AND
  round-phase span histograms (`obs.spans`' ``span.round.*`` latency
  mirror) in the response. After the fleet
  exits it prints the merged Prometheus snapshot, RECONSTRUCTS one
  delta's end-to-end propagation path (publish -> medium write/send ->
  apply on every peer) from the flight logs, and smoke-runs the trace
  CLI (``ccrdt_trace.py summary --require-complete`` + ``path``) over
  the same spill dir — exiting nonzero if any check fails.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import struct
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from antidote_ccrdt_tpu.obs import events as obs_events  # noqa: E402

# SWIM-ish thresholds for the fs medium (display only — workers make
# their own liveness calls; these just color the dashboard).
SUSPECT_S = 0.4
DEAD_S = 0.8


# -- fs-medium scraping ------------------------------------------------------


def hb_age(root: str, member: str) -> Optional[float]:
    """Seconds since `member`'s heartbeat (FsTransport timestamp payload,
    mtime fallback) — same read the transport itself performs."""
    p = os.path.join(root, f"hb-{member}")
    try:
        with open(p, "rb") as f:
            payload = f.read(8)
        if len(payload) == 8:
            return time.time() - struct.unpack("<d", payload)[0]
        return time.time() - os.path.getmtime(p)
    except OSError:
        return None


def scrape_root(root: str) -> Dict[str, Dict[str, Any]]:
    """One pass over the shared gossip dir -> {member: row}."""
    rows: Dict[str, Dict[str, Any]] = {}
    try:
        names = os.listdir(root)
    except OSError:
        return rows

    def row(m: str) -> Dict[str, Any]:
        return rows.setdefault(m, {"snap": None, "deltas": []})

    for fn in names:
        if ".tmp" in fn:
            continue
        if fn.startswith("hb-"):
            row(fn[3:])
        elif fn.startswith("snap-"):
            m = fn[5:]
            try:
                with open(os.path.join(root, fn), "rb") as f:
                    hdr = f.read(8)
                if len(hdr) == 8:
                    row(m)["snap"] = struct.unpack("<Q", hdr)[0]
            except OSError:
                pass
        elif fn.startswith("delta-"):
            m, _, seq = fn[len("delta-"):].rpartition("-")
            try:
                row(m)["deltas"].append(int(seq))
            except ValueError:
                pass
        elif fn.startswith("obs-") and fn.endswith(".json"):
            try:
                with open(os.path.join(root, fn)) as f:
                    row(fn[4:-5])["status"] = json.load(f)
            except (OSError, ValueError):
                pass
    for m, r in rows.items():
        age = hb_age(root, m)
        r["hb_age"] = age
        r["state"] = (
            "?" if age is None
            else "alive" if age <= SUSPECT_S
            else "suspect" if age <= DEAD_S
            else "dead"
        )
        r["deltas"].sort()
    return rows


# -- rendering ---------------------------------------------------------------


def _fmt_lag(status: Optional[Dict[str, Any]]) -> str:
    if not status or not status.get("lag"):
        return "-"
    return " ".join(
        f"{p}:{r['lag_ops']}/{r['lag_s']:.2f}s"
        for p, r in sorted(status["lag"].items())
    )


def _fmt_wal(status: Optional[Dict[str, Any]]) -> str:
    """WAL column group: durability mode, appended vs durable watermark,
    and the exposure lag between them (PR 11 group/async commit — a
    nonzero lag in async mode is the published-before-fsync window the
    certifier audits; in group mode it is at most one staged batch)."""
    st = status or {}
    last = st.get("wal_last_seq")
    if last is None:
        return "-"
    mode = str(st.get("wal_durability") or "?")[:1]  # s/g/a
    durable = st.get("wal_durable_seq")
    lag = st.get("wal_durability_lag")
    out = f"{mode}:{int(last)}"
    if durable is not None:
        out += f"/{int(durable)}"
    if lag:
        out += f" +{int(lag)}"
    return out


def _fmt_sendq(status: Optional[Dict[str, Any]]) -> str:
    q = (status or {}).get("sendq") or {}
    if not q:
        return "-"
    return " ".join(f"{p}:{int(v)}" for p, v in sorted(q.items()))


# QPS needs a rate, and status drops carry cumulative counters — so the
# renderer keeps the previous frame's (time, serve.queries) per member.
# Module state, same lifetime as the watch loop that calls render_frame.
_SERVE_PREV: Dict[str, Any] = {}


def _fmt_serve(status: Optional[Dict[str, Any]], member: str) -> str:
    """Serving column group: query rate since the previous frame, cache
    hit rate, client-visible read p99, and the p99 of the advertised
    staleness bounds — all from the worker's serve.* metrics."""
    sv = (status or {}).get("serve") or {}
    if not sv:
        return "-"
    now = time.time()
    q = float(sv.get("queries", 0))
    prev = _SERVE_PREV.get(member)
    _SERVE_PREV[member] = (now, q)
    qps = "-"
    if prev and now > prev[0]:
        qps = f"{max(0.0, (q - prev[1]) / (now - prev[0])):,.0f}"
    hits = float(sv.get("cache_hits", 0))
    misses = float(sv.get("cache_misses", 0))
    hit = f"{hits / (hits + misses):.0%}" if hits + misses else "-"
    p99 = sv.get("read_p99_ms")
    sp99 = sv.get("staleness_p99_s")
    return (
        f"q/s {qps} hit {hit} "
        f"p99 {'-' if p99 is None else format(p99, '.1f') + 'ms'} "
        f"stale99 {'-' if sp99 is None else format(sp99 * 1e3, '.1f') + 'ms'}"
    )


def _fmt_pager(status: Optional[Dict[str, Any]]) -> str:
    """Pager column group (out-of-core residency, core/pager.py):
    resident/total partitions, resident item bytes, and the page-in hit
    rate — from the worker's pager block (pager.status_fields()). "-"
    means paging is off (all-resident legacy)."""
    pg = (status or {}).get("pager") or {}
    if not pg:
        return "-"
    res = int(pg.get("resident_parts", 0))
    tot = int(pg.get("total_parts", 0))
    nbytes = float(pg.get("resident_bytes", 0))
    hit = pg.get("hit_rate")
    unit = "b"
    for u in ("k", "m", "g"):
        if nbytes < 1024:
            break
        nbytes /= 1024.0
        unit = u
    out = f"r:{res}/{tot} {nbytes:.0f}{unit}"
    if hit is not None:
        out += f" hit {float(hit):.0%}"
    return out


def _fmt_audit(status: Optional[Dict[str, Any]]) -> str:
    """Audit column group: divergence-watchdog verdict, how long the
    worst divergence has been open, and the time-to-agreement p50 — from
    the watchdog block elastic_demo's status drops carry (fed by the
    audit.* gauges every scrape surface also exports)."""
    au = (status or {}).get("audit") or {}
    if not au:
        return "-"
    state = str(au.get("state", "?"))
    age = au.get("age_s")
    tta = au.get("tta_p50_ms")
    cert = au.get("cert_ok")
    out = (
        f"{state} age {'-' if age is None else format(age, '.1f') + 's'} "
        f"tta50 {'-' if tta is None else format(tta, '.0f') + 'ms'}"
    )
    if cert is not None:
        out += f" cert {'ok' if cert else 'FAIL'}"
    return out


# Same rate trick as _SERVE_PREV: the router's status drop carries
# cumulative counters, so the renderer keeps the previous frame's
# (time, router.queries) per member to show a routed-QPS rate.
_ROUTER_PREV: Dict[str, Any] = {}


def _fmt_router(status: Optional[Dict[str, Any]], member: str) -> str:
    """Router column group (serve/router.py, from the obs-router.json
    drop read_tier_demo publishes): routed query rate, per-peer breaker
    state (only non-closed peers are listed — "ok" means every breaker
    is closed), failovers, hedge rate, and session waits. "-" means no
    router is publishing into this obs dir."""
    rt = (status or {}).get("router") or {}
    c = rt.get("counters") or {}
    if not c:
        return "-"
    now = time.time()
    q = float(c.get("router.queries", 0))
    prev = _ROUTER_PREV.get(member)
    _ROUTER_PREV[member] = (now, q)
    qps = "-"
    if prev and now > prev[0]:
        qps = f"{max(0.0, (q - prev[1]) / (now - prev[0])):,.0f}"
    brs = rt.get("breakers") or {}
    tripped = " ".join(
        f"{p}:{str(s)[:4]}" for p, s in sorted(brs.items()) if s != "closed"
    )
    hedges = float(c.get("router.hedges", 0))
    hrate = f"{hedges / q:.0%}" if q else "-"
    return (
        f"q/s {qps} br {tripped or 'ok'} "
        f"fo {int(c.get('router.failovers', 0))} hdg {hrate} "
        f"sw {int(c.get('router.session_waits', 0))}"
    )


def _fmt_rtrace(status: Optional[Dict[str, Any]]) -> str:
    """Request-tracing column group (obs/rtrace.py): traces minted /
    committed this process, forced commits (shed / failed / deadline —
    always stored regardless of sampling), and degraded traces (the
    ``rtrace.record`` fault point fired — tracing dropped out, the
    request itself survived). "-" means the plane is dark (CCRDT_RTRACE
    unset/0) or this process routes nothing."""
    rt = (status or {}).get("rtrace") or {}
    if not rt:
        return "-"
    out = (
        f"mint {int(rt.get('minted', 0))} "
        f"com {int(rt.get('committed', 0))} "
        f"fc {int(rt.get('forced', 0))}"
    )
    deg = int(rt.get("degraded", 0))
    if deg:
        out += f" DEG {deg}"
    return out


def _fmt_devprof(status: Optional[Dict[str, Any]]) -> str:
    """Device-observatory column group (obs/devprof.py): recompiles
    over the trailing minute, the worst churn site (basename'd to keep
    the column narrow), and pager HBM occupancy vs budget. "-" means
    the plane is dark (CCRDT_DEVPROF=0) or no status dump yet."""
    dv = (status or {}).get("devprof") or {}
    if not dv:
        return "-"
    worst = str(dv.get("worst_site") or "-")
    if "." in worst:
        worst = worst.rsplit(".", 1)[-1]
    out = (
        f"rc/m {dv.get('recompiles_per_min', 0):.0f} "
        f"{worst}:{int(dv.get('worst_site_compiles', 0))}"
    )
    occ = dv.get("hbm_occupancy")
    if isinstance(occ, (int, float)) and occ > 0:
        out += f" hbm {occ:.0%}"
    return out


def render_frame(root: str, clear: bool = True) -> str:
    rows = scrape_root(root)
    lines = []
    if clear:
        lines.append("\x1b[2J\x1b[H")
    lines.append(f"== ccrdt gossip dashboard  root={root}  t={time.time():.2f}")
    hdr = (
        f"{'member':<10}{'zone':<6}{'hb-age':>8} {'state':<9}{'snap':>5} "
        f"{'delta-window':<14}{'wal m:last/dur':>14}  {'sendq':<16}"
        f"{'lag (peer:ops/secs)':<26}  {'serving':<34}  "
        f"{'pager':<18}  {'audit':<32}  {'router':<42}  {'rtrace':<24}  "
        f"{'devprof'}"
    )
    lines.append(hdr)
    lines.append("-" * len(hdr))

    def zone_of(m: str) -> str:
        return str(((rows[m].get("status") or {}).get("zone")) or "?")

    # Rows grouped by zone (topo/ fleets), members sorted within; a
    # flat fleet is one "?" group with no visible change but the column.
    ordered = sorted(rows, key=lambda m: (zone_of(m), m))
    zones = sorted({zone_of(m) for m in rows})
    multi_zone = len(zones) > 1
    prev_zone = None
    for m in ordered:
        r = rows[m]
        z = zone_of(m)
        if multi_zone and z != prev_zone:
            states = [rows[n]["state"] for n in ordered if zone_of(n) == z]
            tally = " ".join(
                f"{states.count(s)} {s}"
                for s in ("alive", "suspect", "dead", "?")
                if states.count(s)
            )
            lines.append(f"-- zone {z}: {tally}")
            prev_zone = z
        st = r.get("status")
        age = "-" if r["hb_age"] is None else f"{r['hb_age']:.2f}s"
        d = r["deltas"]
        window = f"{d[0]}..{d[-1]}" if d else "-"
        lines.append(
            f"{m:<10}{z:<6}{age:>8} {r['state']:<9}"
            f"{'-' if r['snap'] is None else r['snap']:>5} "
            f"{window:<14}{_fmt_wal(st):>14}  "
            f"{_fmt_sendq(st):<16}{_fmt_lag(st):<26}  "
            f"{_fmt_serve(st, m):<34}  {_fmt_pager(st):<18}  "
            f"{_fmt_audit(st):<32}  {_fmt_router(st, m):<42}  "
            f"{_fmt_rtrace(st):<24}  {_fmt_devprof(st)}"
        )
    return "\n".join(lines)


# -- propagation-path reconstruction ----------------------------------------


def reconstruct_paths(obs_dir: str) -> Dict[str, Any]:
    """Group every traced delta by (origin, dseq) and classify coverage.
    A path is COMPLETE when the delta shows a publish, reached the medium
    (fs write or tcp frame send), and was applied by every OTHER member
    seen in the flight logs."""
    logs = obs_events.scan_dir(obs_dir)
    members = {evs[0]["member"] for evs in logs.values() if evs}
    paths = obs_events.delta_paths(logs)
    out: Dict[str, Any] = {"members": sorted(members), "deltas": {}}
    for (origin, dseq), stages in sorted(paths.items()):
        appliers = sorted({e["member"] for e in stages.get("apply", [])})
        expect = sorted(members - {origin})
        out["deltas"][f"{origin}#{dseq}"] = {
            "origin": origin,
            "dseq": dseq,
            "stages": sorted(stages),
            "appliers": appliers,
            "complete": (
                "publish" in stages
                and ("write" in stages or "send" in stages)
                and bool(expect)
                and appliers == expect
            ),
        }
    return out


def print_path_timeline(obs_dir: str, origin: str, dseq: int) -> None:
    """Human-readable end-to-end timeline for one delta, merged across
    every member's flight log, ordered by wall time."""
    logs = obs_events.scan_dir(obs_dir)
    hops = []
    for evs in logs.values():
        for e in evs:
            if e.get("origin") == origin and e.get("dseq") == dseq:
                hops.append(e)
    hops.sort(key=lambda e: e["t"])
    t0 = hops[0]["t"] if hops else 0.0
    print(f"-- propagation of delta {origin}#{dseq} "
          f"({len(hops)} events) --")
    for e in hops:
        extra = "".join(
            f" {k}={e[k]}" for k in ("peer", "fkind", "bytes") if k in e
        )
        print(
            f"  +{e['t'] - t0:8.4f}s  {e['member']:<8} {e['kind']:<22}{extra}"
        )


# -- demo mode ---------------------------------------------------------------

# What a live scrape must prove (acceptance for `make obs-demo`): lag
# gauges, profile.dispatch histogram buckets, and round-phase span
# histograms (obs/spans.py's metrics mirror), in valid exposition text,
# read from a RUNNING worker.
_LAG_RE = re.compile(r"^ccrdt_lag_\w+(?:\{[^}]*\})? ", re.M)
_BUCKET_RE = re.compile(
    r'^ccrdt_profile_dispatch_\w+_seconds_bucket\{[^}]*le="', re.M
)
_SPAN_RE = re.compile(
    r'^ccrdt_span_round_\w+_seconds_bucket\{[^}]*le="', re.M
)


def _scrape_proves_live(text: str) -> bool:
    return (
        "# TYPE " in text
        and bool(_LAG_RE.search(text))
        and bool(_BUCKET_RE.search(text))
        and bool(_SPAN_RE.search(text))
    )


def _http_metrics(addr, timeout: float = 2.0) -> str:
    import urllib.request

    with urllib.request.urlopen(
        f"http://{addr[0]}:{addr[1]}/metrics", timeout=timeout
    ) as resp:
        return resp.read().decode("utf-8")


def _gossip_addrs(root: str) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for fn in names:
        if not fn.startswith("addr-") or ".tmp" in fn:
            continue
        try:
            with open(os.path.join(root, fn)) as f:
                host, port = f.read().strip().rsplit(":", 1)
            out[fn[len("addr-"):]] = (host, int(port))
        except (OSError, ValueError):
            continue
    return out


def run_demo(frames_interval: float = 0.5) -> int:
    """Spawn a 3-worker TCP gossip fleet with the full obs plane on
    (flight recorder, metrics dumps, live HTTP endpoints, profiler),
    scrape it over BOTH surfaces while it runs, then verify the flight
    logs with the trace CLI. Returns the process exit code."""
    from antidote_ccrdt_tpu.obs import export as obs_export
    from antidote_ccrdt_tpu.obs import http as obs_http

    here = os.path.dirname(os.path.abspath(__file__))
    demo = os.path.join(here, "net_gossip_demo.py")
    trace_cli = os.path.join(here, "ccrdt_trace.py")
    root = tempfile.mkdtemp(prefix="obs-demo-")
    obs_dir = os.path.join(root, "obs")
    metrics_dir = os.path.join(root, "metrics")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["CCRDT_OBS_DIR"] = obs_dir
    env["CCRDT_METRICS_DIR"] = metrics_dir
    env["CCRDT_HTTP_PORT"] = "0"  # every worker serves /metrics (any port)
    env["CCRDT_PROFILE"] = "1"  # arm the XLA hot-path profiler
    env["CCRDT_SPANS"] = "1"  # arm round-phase span tracing (obs/spans.py)
    members = ["w0", "w1", "w2"]
    procs = [
        subprocess.Popen(
            [sys.executable, demo, "--root", root, "--member", m,
             "--n-members", str(len(members)), "--delta",
             "--step-sleep", "0.25"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, text=True,
        )
        for m in members
    ]
    http_live: Optional[tuple] = None  # (member, text) while fleet ran
    tcp_live: Optional[tuple] = None
    last_frame = 0.0
    try:
        while any(p.poll() is None for p in procs):
            if time.time() - last_frame >= frames_interval:
                print(render_frame(root))
                last_frame = time.time()
            if http_live is None:
                for m, addr in sorted(obs_http.read_addr_files(root).items()):
                    try:
                        text = _http_metrics(addr)
                    except OSError:
                        continue
                    if _scrape_proves_live(text):
                        http_live = (m, text)
                        break
            if tcp_live is None:
                from antidote_ccrdt_tpu.net.tcp import scrape_metrics

                for m, addr in sorted(_gossip_addrs(root).items()):
                    try:
                        member, text = scrape_metrics(addr, timeout=2.0)
                    except (OSError, ValueError):
                        continue
                    if _scrape_proves_live(text):
                        tcp_live = (member, text)
                        break
            time.sleep(0.2)
    finally:
        outs = {}
        for m, p in zip(members, procs):
            try:
                out, _ = p.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
            outs[m] = out
    print(render_frame(root, clear=False))
    bad = [m for m, p in zip(members, procs) if p.returncode != 0]
    if bad:
        for m in bad:
            print(f"-- worker {m} failed --\n{outs[m][-2000:]}")
        return 1

    print("\n== live scrapes (taken while the fleet was running) ==")
    for label, got in (("HTTP /metrics", http_live),
                       ("in-band TCP {metrics_req}", tcp_live)):
        if got is None:
            print(f"FAIL: no {label} scrape with lag gauges + "
                  "profile.dispatch buckets + round-phase span buckets "
                  "succeeded while the fleet ran")
            return 1
        m, text = got
        keep = [ln for ln in text.splitlines()
                if _LAG_RE.match(ln) or _BUCKET_RE.match(ln)
                or _SPAN_RE.match(ln)]
        print(f"[{label}] worker {m}: {len(text.splitlines())} lines, "
              f"proof series:")
        for ln in keep[:6]:
            print(f"    {ln}")

    print("\n== fleet-merged Prometheus snapshot (exit dumps) ==")
    merged, dumped = obs_export.merge_dir(metrics_dir)
    print(obs_export.prometheus_text(merged), end="")
    print(f"# merged from: {sorted(dumped)}")

    print("\n== delta propagation paths (from flight logs) ==")
    rec = reconstruct_paths(obs_dir)
    complete = [d for d in rec["deltas"].values() if d["complete"]]
    for key, d in rec["deltas"].items():
        mark = "OK " if d["complete"] else "..."
        print(f"  [{mark}] {key}: stages={d['stages']} "
              f"applied-by={d['appliers']}")
    if not complete:
        print("FAIL: no delta shows a complete publish->medium->apply-"
              "on-every-peer path")
        return 1
    pick = complete[0]
    print()
    print_path_timeline(obs_dir, pick["origin"], pick["dseq"])

    print("\n== trace CLI (scripts/ccrdt_trace.py) ==")
    for cmd in (
        [sys.executable, trace_cli, "summary", obs_dir, "--require-complete"],
        [sys.executable, trace_cli, "path", obs_dir,
         str(pick["origin"]), str(pick["dseq"])],
    ):
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        print(r.stdout, end="")
        if r.returncode != 0:
            print(f"FAIL: {' '.join(cmd[1:])} exited {r.returncode}\n"
                  f"{r.stderr[-2000:]}")
            return 1

    print(f"\nOK: {len(complete)}/{len(rec['deltas'])} traced deltas fully "
          f"propagated across {rec['members']}; live HTTP + in-band TCP "
          "scrapes carried lag gauges, profile.dispatch histograms, and "
          "round-phase span histograms")
    return 0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", help="shared gossip dir of a running fleet")
    ap.add_argument("--obs-dir", default=os.environ.get(obs_events.ENV_DIR),
                    help="flight-log spill dir (for path reconstruction)")
    ap.add_argument("--interval", type=float, default=0.5)
    ap.add_argument("--frames", type=int, default=0,
                    help="stop after N frames (0 = until interrupted)")
    ap.add_argument("--once", action="store_true",
                    help="render a single frame without clearing and exit")
    ap.add_argument("--demo", action="store_true",
                    help="spawn a 3-worker fleet and run the full check")
    args = ap.parse_args()

    if args.demo:
        sys.exit(run_demo(frames_interval=args.interval))
    if not args.root:
        ap.error("--root is required unless --demo")
    if args.once:
        print(render_frame(args.root, clear=False))
        return
    n = 0
    try:
        while args.frames <= 0 or n < args.frames:
            print(render_frame(args.root, clear=n > 0))
            n += 1
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    if args.obs_dir:
        rec = reconstruct_paths(args.obs_dir)
        print(json.dumps(rec, indent=2))


if __name__ == "__main__":
    main()
