# Build layer — the rebuild's counterpart of the reference's rebar3
# Makefile (reference: Makefile:1-32, rebar.config:1-9).
#
# Target parity map:
#   reference `make compile` (warnings_as_errors)  -> `make compile`
#   reference `make test`    (rebar3 eunit)        -> `make test`
#   reference `make cover`   (rebar3 cover)        -> `make cover`
#       (scripts/cover.py: sys.monitoring line coverage, committed
#        threshold; runs the full suite, so `all` uses it AS the test run)
#   reference `make dialyzer`/xref undefined-call  -> `make xref` +
#       `make typecheck` (scripts/typecheck.py: typeguard import hook over
#        the python-heavy test subset — dynamic success typing, the
#        closest dialyzer analog this image supports; no mypy/pyright and
#        no egress to vendor one)
# plus targets the reference has no equivalent of:
#   `make native`  — C++ host runtime + tokenizer (native/)
#   `make bench`   — north-star benchmark (one JSON line)
#   `make benchall`— every BASELINE.md config

PY ?= python
# Measured 94.2% at round-3 commit time (child-process shards included — see
# scripts/cover.py); 88 leaves drift headroom while keeping the gate
# meaningful.
COVER_THRESHOLD ?= 88

.PHONY: all compile test cover typecheck xref native bench benchall dryrun net-demo chaos crash-demo obs-demo topo-demo spans-demo overlap-demo partition-demo serve-demo audit-demo multichip-demo working-set-demo read-tier-demo write-tier-demo rtrace-demo devprof-demo bench-gate clean

all: compile xref typecheck cover

compile: native
	$(PY) -W error::SyntaxWarning -m compileall -q antidote_ccrdt_tpu tests scripts benchmarks bench.py __graft_entry__.py

test:
	$(PY) -m pytest tests/ -q

# Sharded (union of executed-line sets is exact); keeps each pytest run
# under CI per-command wall-time caps. Shard split: conftest + [a-e] /
# the rest.
cover:
	$(PY) scripts/cover.py --data-out $(CURDIR)/.cover-1.json tests/test_[a-e]*.py -q
	$(PY) scripts/cover.py --data-out $(CURDIR)/.cover-2.json tests/test_[f-z]*.py -q
	$(PY) scripts/cover.py --report $(CURDIR)/.cover-1.json $(CURDIR)/.cover-2.json --threshold $(COVER_THRESHOLD)

typecheck:
	$(PY) scripts/typecheck.py

# xref: every module in the package must import cleanly (catches undefined
# imports the way rebar.config:8's xref undefined_function_calls check does).
xref:
	$(PY) scripts/xref.py

native:
	$(MAKE) -C native

bench:
	$(PY) bench.py

benchall:
	$(PY) benchmarks/bench_all.py

dryrun:
	$(PY) __graft_entry__.py

# The real-socket gossip drill: three localhost TCP peers, one killed
# mid-run; survivors detect the death via SWIM ages, adopt its replicas,
# and converge (tests/test_net_tcp.py::test_real_process_tcp_crash_recovery).
net-demo:
	env JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_net_tcp.py -q -m slow -p no:cacheprovider

# Deterministic fault-matrix run: every utils/faults.py injection point
# (fsync failure, torn write, socket reset, read stalls) driven from a
# seeded, replayable schedule — no real processes, tier-1 compatible
# runtime, but kept out of tier-1 as its own gate.
# The second leg is the observability gate (scripts/chaos_gate.py): two
# seeded sim drills — full-mesh, plus the topo/ zone drill (whole-zone
# partition + za anchor crash) — whose load-bearing counters (sim
# faults, delta gossip, SWIM deaths, cross-zone frames, anchor
# relays/failover) must be nonzero — a refactor that silently stops
# counting fails here even if convergence stays green; chaos_gate's
# serve leg reruns the skewed-clock serving drill (zero served results
# older than their advertised staleness bound, zero identity
# mismatches); its span leg does the same for the span plane (all
# round phases lit, attribution reconciling against round.e2e). The third make leg adds
# the scrape-under-fault matrix (tcp.send / bridge.read must degrade a
# live scrape, never hang) and the trace-CLI unit surface; the fourth
# is the bench regression gate over the committed BENCH_r*.json rounds;
# then the real-process span demo (3 TCP workers, one merged Perfetto
# timeline, dispatch-gap attribution gated) and the overlap demo. The
# next leg is the out-of-core working-set demo: chaos_gate's
# working-set leg already ran the same drill on a fresh seed; this one
# adds the two-arm CCRDT_PAGER=0 kill-switch comparison and refreshes
# WORKSET_r01.json. The final leg is the fleet read tier
# (scripts/read_tier_demo.py): a 4-worker TCP fleet with one serving
# peer SIGKILLed mid-load — every routed query must complete or error
# honestly (zero hangs, zero bound violations), the router counters the
# dashboard renders must be lit, and certify_sessions must sign a
# clean certificate while the deliberately token-violating arm FAILS
# with a counterexample; refreshes READTIER_r01.json. The closing leg
# is the fleet WRITE tier (scripts/write_tier_demo.py): writer sessions
# batch client effects through serve/write_session.py ->
# serve/ingest.py into a WAL-armed fleet, the hot key's HRW owner is
# SIGKILLed mid-load, and the gate requires zero hung / silently
# dropped writes, nonzero durable AND replicated_to_k acks (including
# from the victim pre-kill), honest retry_after_ms sheds, the
# router.write* counters lit, and certify_writes signing ZERO
# acked-but-lost writes while the ack-before-fsync arm FAILS with the
# lost seq range named; refreshes WRITETIER_r01.json. Last comes the
# device observatory (scripts/devprof_demo.py): a stepping fleet's
# recompile storm must be 100% attributed to (site, changed axis), the
# warm-up arm must collapse it >=5x, and the CCRDT_DEVPROF=0 arm must
# be byte-identical at <=2% armed overhead; refreshes DEVPROF_r01.json.
chaos:
	env JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_faults.py tests/test_wal.py tests/test_fault_matrix.py -q -p no:cacheprovider
	env JAX_PLATFORMS=cpu $(PY) scripts/chaos_gate.py
	env JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_scrape_faults.py tests/test_trace_cli.py -q -p no:cacheprovider
	$(PY) scripts/bench_gate.py
	env JAX_PLATFORMS=cpu $(PY) scripts/spans_demo.py
	env JAX_PLATFORMS=cpu $(PY) scripts/overlap_demo.py
	env JAX_PLATFORMS=cpu $(PY) scripts/working_set_demo.py
	env JAX_PLATFORMS=cpu $(PY) scripts/read_tier_demo.py
	env JAX_PLATFORMS=cpu $(PY) scripts/write_tier_demo.py
	env JAX_PLATFORMS=cpu $(PY) scripts/devprof_demo.py

# Throughput regression gate: best merges_per_sec of the latest
# BENCH_r*.json round must stay within 20% of the best prior round —
# the same batched-dispatch throughput obs/profile.py measures live.
bench-gate:
	$(PY) scripts/bench_gate.py

# The crash-consistency drill (slow, real processes): SIGKILL a
# WAL-backed worker mid-run, restart it, and require bit-identical
# convergence — via WAL recovery under EVERY durability discipline
# (sync fsync-per-append, group commit, async watermark: recovery must
# equal watermark truncation and the certifier's durability check must
# pass), plus once with the WAL deleted via peer adoption.
crash-demo:
	env JAX_PLATFORMS=cpu $(PY) scripts/crash_recovery_demo.py --mode both --durability all

# Observability demo (slow, real processes): a 3-worker TCP gossip
# fleet with the full obs plane on — live dashboard frames, LIVE scrapes
# of the running workers over HTTP /metrics and the in-band TCP
# {metrics_req} frame (must carry lag gauges + profile.dispatch
# histogram buckets), then the fleet-merged Prometheus snapshot, a
# reconstructed end-to-end delta propagation path from the flight logs,
# and a trace-CLI smoke run (summary --require-complete + path).
obs-demo:
	env JAX_PLATFORMS=cpu $(PY) scripts/obs_dashboard.py --demo

# DCN-topology demo (slow, real processes): a 2-zone x 3-worker TCP
# fleet with the topo/ routers installed, the za anchor SIGKILLed
# mid-run (rendezvous failover), converging bit-identically with a
# full-mesh baseline while crossing the zone boundary O(zones) — the
# printed ratio — instead of O(peers).
topo-demo:
	env JAX_PLATFORMS=cpu $(PY) scripts/topo_demo.py

# Overlap demo/gate (slow, real processes): the same 3-worker TCP fleet
# run twice — serial round loop vs the overlapped pipeline
# (parallel/overlap.py) — gated on bit-identical digests across modes,
# the pipeline counters nonzero, and a >=30% fleet-p50 round.e2e
# reduction with publish-every-1 host load. Also part of `make chaos`.
overlap-demo:
	env JAX_PLATFORMS=cpu $(PY) scripts/overlap_demo.py

# Partition-plane gate (real sockets, in-process): a 3-worker TCP fleet
# with one deliberately divergent partition; the gap is repaired twice
# from the same state — whole-instance snapshot vs digest-vector +
# psnap partial anti-entropy (core/partition.py, PartialAntiEntropy) —
# gated on >=5x fewer anti-entropy bytes, bit-identical repair digests,
# zero wasted psnaps, and fleet convergence to the sequential
# reference. Writes PART_r01.json.
partition-demo:
	env JAX_PLATFORMS=cpu $(PY) scripts/partition_demo.py

# Serving-plane gate (real sockets, in-process): a 3-worker TCP fleet
# serves batched in-band {query} frames while writes flow and seeded
# faults drop sends / stall serves — gated on >=50k reads/sec (CPU),
# measured read p99, ZERO responses older than their advertised
# staleness bound, every served value bit-identical to the engine's
# value() at the claimed as_of_seq, and write-fleet convergence to the
# sequential reference. Writes SERVE_r01.json.
serve-demo:
	env JAX_PLATFORMS=cpu $(PY) scripts/serve_demo.py

# Certified-convergence gate (obs/audit.py): the lattice-law checker
# over every registered op type (+ the committed broken-merge fixture,
# which MUST be caught), a seeded-chaos 3-worker TCP fleet whose run is
# replay-certified from the flight-log spill into a signed convergence
# certificate (written to AUDIT_r01.json; per-worker digests must match
# the sequential reference, zero false wedge alarms), and the
# deterministic divergent arm — watchdog flags within one digest
# exchange, wedges past the bound, and the failed certificate's
# counterexample names the diverging partition. Also part of
# `make chaos` via scripts/chaos_gate.py.
audit-demo:
	env JAX_PLATFORMS=cpu $(PY) scripts/audit_demo.py

# Mesh-sharding gate (slow, real processes, 8 forced host devices): a
# 2-slice fleet of mesh-sharded workers (mesh/: state pinned to a
# (dc,key) device mesh, one batched ICI JOIN all-reduce per publish
# boundary, per-shard anchors) with one worker SIGKILLed mid-load —
# gated on bit-identical convergence vs the unsharded sequential
# reference, cross-slice anti-entropy shipping only shard-local psnap
# slices (>=5x fewer bytes than whole-instance), and the PR 10 replay
# certificate verifying over the sharded flight logs. Writes
# MULTICHIP_r06.json (the carrier bench_gate's evaluate_mesh compares).
multichip-demo:
	$(PY) scripts/multichip_demo.py

# Out-of-core paging gate (in-process fleet over a shared-fs
# transport): a 3-worker fleet whose per-worker device residency is
# forced to ONE TENTH of the instance (core/pager.py clock pager),
# zipf-skewed ops through the `ensure_resident` front door, full
# partition-plane gossip with anchors/psnaps served from cold CCPT
# blobs — gated on bit-identical convergence vs the all-resident
# sequential reference AND vs a CCRDT_PAGER=0 kill-switch rerun,
# steady-state hit rate >= 0.9, state >= 10x budget, pager counters
# nonzero, zero wasted psnaps, and the round.pager_hydrate span lit.
# Writes WORKSET_r01.json. Also part of `make chaos` via
# scripts/chaos_gate.py's working-set leg (fresh seed there).
working-set-demo:
	env JAX_PLATFORMS=cpu $(PY) scripts/working_set_demo.py

# Fleet read-tier gate (slow, real processes): a 4-worker TCP gossip
# fleet serving in-band {query} frames through serve/router.py — HRW
# rendezvous routing with staleness-aware peer picking, hedged retries,
# per-peer breakers, and session tokens (read-your-writes + monotonic
# reads) — with the rendezvous-head worker SIGKILLed mid-load. Gated on
# zero hung queries, zero staleness-bound violations, bounded failover
# blip, the router counters lit, survivors converging bit-identically,
# certify_sessions signing a clean certificate over the router flight
# log, and the deliberately token-violating arm FAILING certification
# with a minimal counterexample. Writes READTIER_r01.json (the carrier
# bench_gate's evaluate_router compares). Also the final leg of
# `make chaos`.
read-tier-demo:
	env JAX_PLATFORMS=cpu $(PY) scripts/read_tier_demo.py

# Fleet write-tier gate (slow, real processes): writer sessions compact
# client effect bursts into single CCRF range frames
# (serve/write_session.py) and route them owner-first through
# serve/ingest.py's WriteRouter into a 4-worker WAL-armed TCP fleet
# (CCRDT_INGEST=1) under seeded chaos, with the hot key's HRW owner
# SIGKILLed mid-load. Gated on zero hung or silently dropped writes,
# nonzero durable AND replicated_to_k acks (victim included), honest
# admission sheds (retry_after_ms), cross-tier read-your-writes via
# shared session tokens, the router.write* counters lit, survivors
# converging bit-identically, and obs/audit.py's certify_writes
# signing ZERO acked-but-lost writes — while the deliberately
# violating ack-before-fsync arm FAILS certification with the lost
# seq range named. Writes WRITETIER_r01.json (the carrier
# bench_gate's evaluate_write compares). Also the closing leg of
# `make chaos`.
write-tier-demo:
	env JAX_PLATFORMS=cpu $(PY) scripts/write_tier_demo.py

# Request-tracing gate (slow, real processes): a 4-worker TCP serving
# fleet under seeded chaos with the rtrace plane armed at sample=1.0 —
# every routed read mints a trace context that rides the {query} frame,
# workers echo their enqueue->drain->kernel stage marks back in the
# response, and the client reassembles ClockSync-aligned waterfalls
# without scraping. Gated on >=99% of sampled completions reassembling
# gap-free, attribution buckets covering >=90% of client-observed
# latency at p50 AND at the p99 request, the OpenMetrics read-latency
# exemplar resolving to a real stored trace with its dominant bucket
# named, the mid-load SIGKILL rendering as a dead_reroute hop inside a
# stored waterfall, and tracing overhead <=5% of reads/sec vs the same
# fleet's interleaved CCRDT_RTRACE=0 kill-switch windows. Writes
# RTRACE_r01.json (the carrier bench_gate's evaluate_rtrace compares).
rtrace-demo:
	env JAX_PLATFORMS=cpu $(PY) scripts/rtrace_demo.py

# Device-observatory demo (slow, subprocess arms): a seeded stepping
# 3-worker fleet whose growing topk_rmv shapes provoke a recompile
# storm — gated on 100% of compiles attributed to (site, changed
# axis), capacity growth named as the dominant churn source, the
# CCRDT_DEVPROF_WARMUP=1 arm collapsing steady-state recompiles >=5x
# via shape padding + the boot-time prewarm ladder, observatory
# overhead <=2% on alternating CCRDT_DEVPROF=0 A/B rounds, and the
# kill-switch arm byte-identical. Writes DEVPROF_r01.json (the carrier
# bench_gate's evaluate_devprof compares).
devprof-demo:
	env JAX_PLATFORMS=cpu $(PY) scripts/devprof_demo.py

# Span-tracing demo (slow, real processes): a 3-worker TCP fleet with
# the round-phase span plane armed (CCRDT_SPANS=1) — every worker's
# spans merged onto ONE clock-aligned Perfetto timeline (NTP-style
# offsets piggybacked on hello/metrics frames), plus the dispatch-gap
# attribution report, gated on all phases lit and the phase sums
# reconciling against the measured round.e2e wall time.
spans-demo:
	env JAX_PLATFORMS=cpu $(PY) scripts/spans_demo.py

clean:
	rm -rf native/build
	find . -name __pycache__ -type d -not -path './.git/*' -exec rm -rf {} +
