# Build layer — the rebuild's counterpart of the reference's rebar3
# Makefile (reference: Makefile:1-32, rebar.config:1-9).
#
# Target parity map:
#   reference `make compile` (warnings_as_errors)  -> `make compile`
#   reference `make test`    (rebar3 eunit)        -> `make test`
#   reference `make cover`   (rebar3 cover)        -> (no coverage tool in
#       this image; the test tiers in tests/ are the coverage story)
#   reference `make dialyzer`/xref undefined-call  -> `make xref`
#       (import-resolution check over every package module)
# plus targets the reference has no equivalent of:
#   `make native`  — C++ host runtime + tokenizer (native/)
#   `make bench`   — north-star benchmark (one JSON line)
#   `make benchall`— every BASELINE.md config

PY ?= python

.PHONY: all compile test xref native bench benchall dryrun clean

all: compile xref test

compile: native
	$(PY) -W error::SyntaxWarning -m compileall -q antidote_ccrdt_tpu tests scripts benchmarks bench.py __graft_entry__.py

test:
	$(PY) -m pytest tests/ -q

# xref: every module in the package must import cleanly (catches undefined
# imports the way rebar.config:8's xref undefined_function_calls check does).
xref:
	$(PY) scripts/xref.py

native:
	$(MAKE) -C native

bench:
	$(PY) bench.py

benchall:
	$(PY) benchmarks/bench_all.py

dryrun:
	$(PY) __graft_entry__.py

clean:
	rm -rf native/build
	find . -name __pycache__ -type d -not -path './.git/*' -exec rm -rf {} +
