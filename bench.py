"""North-star benchmark: topk_rmv effect-op merge throughput.

Config (BASELINE.md): topk_rmv K=100, 100k-element id space, 32 simulated
replicas/DCs, concurrent add/rmv workload. Compares:

* dense TPU path — `TopkRmvDense.apply_ops` over [32] replicas in one
  dispatch per round, plus one whole-grid replica-state merge dispatch;
* CPU baseline — the scalar (reference-semantics) implementation applying
  the identical effect ops one at a time (the "BEAM stand-in": the
  reference publishes no numbers, SURVEY.md §6, so the baseline is measured
  by reimplementing its semantics faithfully).

Metric: "merges/sec" = effect-op applications per second summed over
replicas (every applied op is one CRDT merge of an op into a state), the
BASELINE.json headline; plus p50 per-round merge latency and the
batched replica-state merge rate.

Prints exactly ONE JSON line.
"""

import json
import os
import sys
import time

import numpy as np


def bench_dense(R, I, D_DCS, K, M, B, Br, rounds):
    import jax

    from antidote_ccrdt_tpu.harness.opgen import TopkRmvEffectGen, Workload
    from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense

    D = make_dense(n_ids=I, n_dcs=D_DCS, size=K, slots_per_id=M)
    state = D.init(n_replicas=R, n_keys=1)
    gen = TopkRmvEffectGen(
        Workload(n_replicas=R, n_ids=I, zipf_a=1.2, score_max=100_000, seed=7)
    )
    batches = [gen.next_batch(B, Br) for _ in range(rounds + 2)]

    # Warmup (compile)
    state, _ = D.apply_ops(state, batches[0])
    state, _ = D.apply_ops(state, batches[1])
    jax.block_until_ready(state.slot_ts)

    from antidote_ccrdt_tpu.utils.metrics import Metrics, device_trace

    m = Metrics()
    for i in range(rounds):
        with m.timer("round"), device_trace("apply_ops_round"):
            state, _ = D.apply_ops(state, batches[2 + i])
            jax.block_until_ready(state.slot_ts)
        m.count("ops", R * (B + Br))
    apply_rate = m.rate("ops", "round")
    lat = m.latencies["round"].summary()
    p50_ms, p99_ms = lat["p50_ms"], lat["p99_ms"]

    # Batched replica-state merge: all R pairwise merges in ONE dispatch
    # (state row r joined with row (r+1) mod R) — the literal north-star
    # "merge thousands of replica states in one vectorized step".
    def rolled(s):
        return jax.tree.map(lambda x: jnp_roll(x), s)

    import jax.numpy as jnp

    def jnp_roll(x):
        return jnp.roll(x, 1, axis=0)

    merged = D.merge(state, rolled(state))  # compile
    jax.block_until_ready(merged.slot_ts)
    t0 = time.perf_counter()
    MERGE_REPS = 10
    for _ in range(MERGE_REPS):
        merged = D.merge(merged, rolled(merged))
    jax.block_until_ready(merged.slot_ts)
    state_merges_per_sec = MERGE_REPS * R / (time.perf_counter() - t0)

    return apply_rate, p50_ms, p99_ms, state_merges_per_sec


def bench_scalar_baseline(R, I, D_DCS, K, n_ops):
    """Apply the same kind of effect ops through the scalar reference
    semantics, one op per `update` call, on one CPU core."""
    from antidote_ccrdt_tpu.models.topk_rmv import TopkRmvScalar

    S = TopkRmvScalar()
    state = S.new(K)
    rng = np.random.default_rng(3)
    ids = rng.integers(0, I, n_ops)
    scores = rng.integers(1, 100_000, n_ops)
    dcs = rng.integers(0, D_DCS, n_ops)
    is_rmv = rng.random(n_ops) < 0.1
    frontier = {}
    effects = []
    for j in range(n_ops):
        dc = int(dcs[j])
        if is_rmv[j]:
            effects.append(("rmv", (int(ids[j]), dict(frontier))))
        else:
            ts = frontier.get(dc, 0) + 1
            frontier[dc] = ts
            effects.append(("add", (int(ids[j]), int(scores[j]), (dc, ts))))
    t0 = time.perf_counter()
    for eff in effects:
        state, _extras = S.update(eff, state)
    dt = time.perf_counter() - t0
    return n_ops / dt


def main():
    import jax

    backend = jax.default_backend()
    if backend == "cpu":
        # CI / no-accelerator fallback: shrink so the bench still completes.
        R, I, B, Br, rounds, base_ops = 8, 10_000, 1024, 64, 5, 5_000
    else:
        R, I, B, Br, rounds, base_ops = 32, 100_000, 4096, 256, 10, 20_000
    D_DCS, K, M = R, 100, 4  # every simulated replica is a DC: vc width = R

    apply_rate, p50_ms, p99_ms, state_merge_rate = bench_dense(
        R, I, D_DCS, K, M, B, Br, rounds
    )
    baseline_rate = bench_scalar_baseline(R, I, D_DCS, K, base_ops)

    print(
        json.dumps(
            {
                "metric": f"topk_rmv merges/sec ({I//1000}k ids x {R} replicas, K={K})",
                "value": round(apply_rate),
                "unit": "merges/sec",
                "vs_baseline": round(apply_rate / baseline_rate, 2),
                "p50_round_latency_ms": round(p50_ms, 2),
                "p99_round_latency_ms": round(p99_ms, 2),
                "replica_state_merges_per_sec": round(state_merge_rate, 1),
                "baseline_cpu_merges_per_sec": round(baseline_rate),
                "backend": backend,
            }
        )
    )


if __name__ == "__main__":
    main()
