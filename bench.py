"""North-star benchmark: topk_rmv effect-op merge throughput.

Config (BASELINE.md): topk_rmv K=100, 100k-element id space, 32 simulated
replicas/DCs, concurrent add/rmv workload. Compares:

* dense TPU path — `TopkRmvDense.apply_ops` over [32] replicas in one
  dispatch per round, plus one whole-grid replica-state merge dispatch;
* CPU baseline — the scalar (reference-semantics) implementation applying
  the identical effect ops one at a time (the "BEAM stand-in": the
  reference publishes no numbers, SURVEY.md §6, so the baseline is measured
  by reimplementing its semantics faithfully).

Metric: "merges/sec" = effect-op applications per second summed over
replicas (every applied op is one CRDT merge of an op into a state), the
BASELINE.json headline; plus p50 per-round merge latency and the
batched replica-state merge rate.

Measurement discipline: rounds are scan-fused into multi-round windows
(one XLA dispatch per window) and every timed region ends with a real
device->host readback — on tunneled TPU backends `jax.block_until_ready`
returns without waiting, so naive per-round timing measures dispatch, not
compute.

Prints TWO JSON lines: a full-detail line (hbm roofline, compute
attribution, throughput/latency curve — mirrored to
benchmarks/bench_details.json) and then a compact final summary line
(<1,900 chars). The driver records only the tail of stdout and parses the
LAST line, so the summary must stay small — round 4's single fat line
overflowed the driver's window and the official record came back
unparseable (VERDICT-r4 weak #1).
"""

import dataclasses
import json
import os
import sys
import time

import numpy as np


sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# TPU v5e HBM peak bandwidth (public spec: 819 GB/s/chip). frac_of_peak in
# the roofline block is computed against this; on the CPU fallback backend
# the fraction is not meaningful (the JSON carries the backend name).
HBM_PEAK_GB_S = 819.0
# TPU v5e MXU int8 peak (public spec: 394 TOPS/chip; the tombstone one-hot
# matmul is the s8 x s8 -> s32 native path, ops = 2 * MACs).
MXU_INT8_PEAK_TOPS = 394.0

# Shared measurement discipline (host-readback sync, round stacking); see
# utils/benchtime.py for why block_until_ready is not enough here.
from antidote_ccrdt_tpu.utils.benchtime import (  # noqa: E402
    stack_rounds as _stack_rounds,
    sync as _sync,
)


def bench_dense(R, I, D_DCS, K, M, B, Br, windows, rounds_per_window):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from antidote_ccrdt_tpu.harness.opgen import TopkRmvEffectGen, Workload
    from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense
    from antidote_ccrdt_tpu.utils.metrics import Metrics

    D = make_dense(n_ids=I, n_dcs=D_DCS, size=K, slots_per_id=M)
    state = D.init(n_replicas=R, n_keys=1)
    gen = TopkRmvEffectGen(
        Workload(n_replicas=R, n_ids=I, zipf_a=1.2, score_max=100_000, seed=7)
    )
    W = rounds_per_window
    # One stacked [W, R, ...] op pytree per window; each window is a single
    # scan-fused dispatch, so per-dispatch tunnel overhead (10-30ms) is
    # amortized and the measurement is true device throughput.
    window_batches = []
    for _ in range(windows + 1):
        window_batches.append(_stack_rounds([gen.next_batch(B, Br) for _ in range(W)]))

    @jax.jit
    def run_window(state, stacked):
        def body(st, ops):
            st2, _ = D.apply_ops(st, ops, collect_dominated=False)
            return st2, ()
        out, _ = lax.scan(body, state, stacked)
        return out

    state = run_window(state, window_batches[0])  # compile + warm
    _sync(state)

    m = Metrics()
    for w in range(windows):
        with m.timer("window"):
            state = run_window(state, window_batches[1 + w])
            _sync(state)
        m.count("ops", R * (B + Br) * W)
    apply_rate = m.rate("ops", "window")

    # Extras collection ON (dominated-add re-broadcast vcs, reference
    # :234-237) — the configuration the replay harness runs. "table" mode
    # is the replication path: the id-keyed dominated mask (payload =
    # state.rmv_vc rows, live as part of the carried state) derived
    # elementwise from the delta table — no per-op gather. True is the
    # legacy op-aligned mode whose per-add tombstone gather dominated the
    # round in round 1 (kept for small-batch surfaces). The summed extras
    # leaf keeps each mode's collection live against DCE.
    def extras_runner(mode, pick):
        @jax.jit
        def run(state, stacked):
            def body(st, ops):
                st2, extras = D.apply_ops(st, ops, collect_dominated=mode)
                return st2, jnp.sum(pick(extras))
            out, doms = lax.scan(body, state, stacked)
            return out, jnp.sum(doms)
        return run

    def time_extras(run, n_windows):
        (warm, _d) = run(state, window_batches[0])
        _sync(warm)
        me = Metrics()
        for w in range(n_windows):
            with me.timer("window"):
                out, _d = run(warm, window_batches[1 + w])
                _sync(out)
            me.count("ops", R * (B + Br) * W)
        return me.rate("ops", "window")

    extras_rate = time_extras(
        extras_runner("table", lambda e: e.dominated_tbl), min(2, windows)
    )
    extras_ops_rate = time_extras(
        extras_runner(True, lambda e: e.dominated), 1
    )
    # Per-round latency, two estimators (VERDICT r1 weak #4):
    # * windowed — window_time / W over scan-fused windows; a smoothed
    #   MEAN-based estimator (true per-round variation inside a window is
    #   invisible), kept for continuity with round-1 numbers.
    # * single-dispatch E2E — each round its own dispatch with a real host
    #   readback: the honest per-round tail as a client would see it. On
    #   this tunneled backend every sample includes the dispatch+readback
    #   RTT, so the fixed overhead is calibrated with a 1-element dispatch
    #   and reported separately rather than subtracted (percentile
    #   subtraction would fabricate a tail).
    per_round = [s / W for s in m.latencies["window"].samples]
    p50_ms = float(np.percentile(per_round, 50) * 1e3)
    p99_ms = float(np.percentile(per_round, 99) * 1e3)

    @jax.jit
    def run_one(state, ops):
        st2, _ = D.apply_ops(state, ops, collect_dominated=False)
        return st2

    @jax.jit
    def tiny(x):
        return x + 1

    single_ops = [
        jax.tree.map(lambda a: a[i], window_batches[1 + j])
        for j in range(windows)
        for i in range(W)
    ]
    st1 = run_one(state, single_ops[0])  # compile
    _sync(st1)
    _sync(tiny(jnp.zeros((), jnp.int32)))
    singles, overheads = [], []
    for ops in single_ops:
        t0 = time.perf_counter()
        st1 = run_one(st1, ops)
        _sync(st1)
        singles.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        _sync(tiny(jnp.zeros((), jnp.int32)))
        overheads.append(time.perf_counter() - t0)
    p50_e2e_ms = float(np.percentile(singles, 50) * 1e3)
    p99_e2e_ms = float(np.percentile(singles, 99) * 1e3)
    dispatch_overhead_ms = float(np.percentile(overheads, 50) * 1e3)

    # Overlapped single-dispatch e2e (PR 7, boundary discipline fixed in
    # PR 11): the parallel/overlap.py round shape. Every round dispatches
    # immediately; the host readback — the publish boundary's
    # block_until_ready — rides a HostStage worker every PUB_EVERY
    # rounds, and the loop DRAINS the stage at each boundary before
    # timing the next round. The drain bounds run-ahead to one publish
    # window and bills each boundary sample with exactly its own
    # window's device work: the previous shape queued readbacks without
    # ever waiting, so ALL windows' device time collapsed into the
    # single final-drain sample — a ~570ms p99 that was an artifact of
    # where the flush was billed, not a latency any round experienced.
    # Non-boundary samples still measure pure dispatch (the p50).
    from antidote_ccrdt_tpu.parallel.overlap import HostStage

    PUB_EVERY = 4
    stage = HostStage(Metrics(), name="bench-readback")
    st2 = run_one(st1, single_ops[0])
    _sync(st2)
    marks = [time.perf_counter()]
    for i, ops in enumerate(single_ops):
        st2 = run_one(st2, ops)
        if (i + 1) % PUB_EVERY == 0:
            stage.submit(_sync, st2)
            stage.drain()  # boundary waits for ITS window, nothing more
        marks.append(time.perf_counter())
    stage.drain()
    _sync(st2)
    stage.close()
    olap = [b - a for a, b in zip(marks, marks[1:])]
    p50_e2e_overlap_ms = float(np.percentile(olap, 50) * 1e3)
    p99_e2e_overlap_ms = float(np.percentile(olap, 99) * 1e3)

    # Batched replica-state merge: all R pairwise merges in ONE dispatch
    # (state row r joined with peer row (r+1) mod R) — the literal north-
    # star "merge thousands of replica states in one vectorized step". The
    # peer side is materialized ONCE outside the timed loop: a real merge
    # (gossip fetch, delta apply) joins two states that already exist, and
    # the roofline model below accordingly charges 3x state (read both
    # sides + write). Round 1 re-rolled inside the loop, which billed an
    # extra full-state copy to every rep (~5.4ms of the then-11.4ms,
    # measured by ablation) — that was measuring roll+merge, not merge.
    # The carried dependency keeps every scan iteration live. Round 4
    # measured the RTT at ~100-125ms via a null-scan probe
    # (benchmarks/merge_probe2.py): at 64 reps that is still ~20% of a
    # ~9.6ms/rep total, so the RAW state_merges_per_sec figure under-read
    # the device by a fifth (the round-3 "~2%" comment was wrong about
    # its own arithmetic). 192 reps cut the bias to ~6%; the
    # overhead-adjusted mean (compute.merge.measured_ms) stays the
    # authoritative device number either way.
    MERGE_REPS = 192
    peer = jax.tree.map(lambda x: jnp.roll(x, 1, axis=0), state)

    @jax.jit
    def run_merges(state, peer):
        def body(st, _):
            return D.merge(st, peer), ()
        out, _ = lax.scan(body, state, None, length=MERGE_REPS)
        return out

    _sync(run_merges(state, peer))
    t0 = time.perf_counter()
    merged = run_merges(state, peer)
    _sync(merged)
    merge_time = time.perf_counter() - t0
    state_merges_per_sec = MERGE_REPS * R / merge_time

    # Observe (read path): the derived observable top-K over the grid.
    # The observe input is perturbed by the scan carry — a loop-INVARIANT
    # body would be hoisted by XLA and the measurement would be pure
    # dispatch RTT (caught empirically: length=1 and length=256 scans took
    # identical wall time). The scalar broadcast add fuses into
    # masked_topk's plane-0 read, so it adds no meaningful traffic.
    OBS_REPS = 64

    @jax.jit
    def run_observes(state):
        def body(c, _):
            st = dataclasses.replace(state, slot_score=state.slot_score + (c % 2))
            obs = D.observe(st)
            return c + jnp.sum(obs.scores) + jnp.sum(obs.ids), ()
        out, _ = lax.scan(body, jnp.zeros((), jnp.int32), None, length=OBS_REPS)
        return out

    _sync(run_observes(state))
    t0 = time.perf_counter()
    _sync(run_observes(state))
    observe_total = time.perf_counter() - t0

    # --- roofline: analytic bytes touched per phase vs HBM peak ----------
    # Minimum-traffic accounting (each array touched once; intermediates
    # assumed fused). This workload is bandwidth-bound only on the
    # full-state merge; apply sits above every peak floor — the compute
    # block below (compute_model) quantifies what actually binds it
    # (scheduling/serialized small ops, with the measured evidence).
    # These rows are MEAN-based throughputs, so the single measured
    # dispatch RTT per timed call (dispatch_overhead_ms_p50) is subtracted
    # once — valid for means, unlike the tail estimators above.
    overhead_s = dispatch_overhead_ms / 1e3

    def adj(total, reps):
        return max(total - overhead_s, total * 0.05) / reps

    state_nbytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(state))
    ops_nbytes = sum(
        x.nbytes for x in jax.tree_util.tree_leaves(window_batches[0])
    ) // W
    window_med = float(np.median(m.latencies["window"].samples))
    hbm = {}
    for phase, nbytes, secs in (
        # apply: read state + ops, write state.
        ("apply", 2 * state_nbytes + ops_nbytes, adj(window_med, W)),
        # merge: read both sides (rolled copy counts once), write out.
        ("replica_state_merge", 3 * state_nbytes, adj(merge_time, MERGE_REPS)),
        # observe: one pass over slot plane 0 of the three slot leaves
        # (1/M of each) + K-sized outputs (negligible).
        ("observe", sum(
            x.nbytes
            for x in (state.slot_score, state.slot_dc, state.slot_ts)
        ) // M, adj(observe_total, OBS_REPS)),
    ):
        gbps = nbytes / secs / 1e9
        hbm[phase] = {
            "bytes_per_dispatch": int(nbytes),
            "achieved_gb_s": round(gbps, 3),
            "frac_of_peak": round(gbps / HBM_PEAK_GB_S, 4),
        }

    compute = compute_model(
        R, 1, I, D_DCS, M, B, Br,
        apply_ms=adj(window_med, W) * 1e3,
        apply_hbm_bytes=hbm["apply"]["bytes_per_dispatch"],
    )
    compute.update(compute_merge_model(
        R, 1, I, D_DCS, M,
        merge_ms=adj(merge_time, MERGE_REPS) * 1e3,
        merge_hbm_bytes=hbm["replica_state_merge"]["bytes_per_dispatch"],
    ))

    return (
        apply_rate, extras_rate, extras_ops_rate, p50_ms, p99_ms,
        p50_e2e_ms, p99_e2e_ms, p50_e2e_overlap_ms, p99_e2e_overlap_ms,
        dispatch_overhead_ms, state_merges_per_sec, hbm, compute,
    )


# TPU v5e VPU peak (derived from public specs: 8x128 vector lanes x 4
# ALUs x the ~1.5GHz clock the 197 bf16 TFLOPS MXU figure implies).
VPU_PEAK_OPS = 8 * 128 * 4 * 1.5e9


def compute_merge_model(R, NK, I, D_DCS, M, merge_ms, merge_hbm_bytes):
    """Analytic compute roofline for the batched replica-state merge
    (VERDICT-r3 item 3 — the apply treatment for the metric the north
    star literally names). Kernel: `TopkRmvDense.merge` = elementwise
    rmv_vc/vc maxes + `_join_slots_union` (single 2M-wide add-wins
    filter, 2M x 2M compare matrix, one-hot placement).

    Per-id VPU op counts from the kernel shapes (2M candidates, D-wide
    one-hot tombstone reduce, m_keep=M outputs):
    * live/dom:   2M * D * 3   (iota==dc, where, max-reduce)
    * compares:   (2M)^2 * 13  (lexicographic cmp 8 + eq 5)
    * dedup+rank: (2M)^2 * 3   (tie-break or, mask and, sum)
    * placement:  2M*M + 3 * 2M*M * 2 + 2M*M  (one-hot, 3 planes, filled)

    Measured verdict (v5e, north-star shapes, benchmarks/merge_probe.py
    + merge_probe2.py, REPS>=64 with a null-scan RTT calibration —
    removal deltas are RTT-free): the merge sits ~4x above the bytes
    floor and ~8x above the VPU floor; attribution of the ~8.5ms device
    round (taken on the pairwise-join merge the union join replaced):
    elementwise maxes ~1.8ms (AT their 1.5ms bytes floor — the rmv_vc
    plane is 400MB of the 563MB state), dom one-hot reduces ~3.7ms
    (~2.5x their floor; the top residual), placement ~2.3ms,
    compares+ranks ~0.6ms. Restructurings measured: union join ADOPTED
    (9.51 -> 9.00 ms harness time, ~6% of device time); packedcmp
    (sign-combine compare) neutral; domdist (dom distributed over max)
    and einsum placement regress. Like apply, the binding constraint
    above the maxes piece is XLA's scheduling of the fused small-op
    chain, not any peak."""
    cand = 2 * M
    per_id = (
        cand * D_DCS * 3
        + cand * cand * 13
        + cand * cand * 3
        + cand * M + 3 * cand * M * 2 + cand * M
    )
    vpu_ops = R * NK * I * per_id
    vpu_floor_ms = vpu_ops / VPU_PEAK_OPS * 1e3
    hbm_floor_ms = merge_hbm_bytes / (HBM_PEAK_GB_S * 1e9) * 1e3
    floor_ms = max(vpu_floor_ms, hbm_floor_ms)
    attribution = (
        {
            "elementwise_maxes": 1.8, "dom_onehot_reduces": 3.7,
            "placement": 2.3, "compares_ranks": 0.6,
            "methodology": "removal deltas, RTT-calibrated (null-scan "
                           "probe); taken on the pre-union pairwise join. "
                           "r5 re-validated the structure: the full union "
                           "merge measures 8.87ms at REPS=128 and every "
                           "dom-lookup reformulation (sum/mul/einsum-dot) "
                           "lands within noise, bit tree 2.2x worse "
                           "(benchmarks/dom_probe.py) - schedule-bound",
            "repro": "MERGE_REPS=64 python benchmarks/merge_probe.py; "
                     "MERGE_REPS=128 python benchmarks/merge_probe2.py; "
                     "MERGE_REPS=128 python benchmarks/dom_probe.py",
        }
        if (R, I, D_DCS, M) == (32, 100_000, 32, 4)
        else None
    )
    return {
        "merge": {
            "measured_ms": round(merge_ms, 2),
            "vpu": {
                "join_ops_per_id": int(per_id),
                "total_ops": int(vpu_ops),
                "peak_ops_per_sec": VPU_PEAK_OPS,
                "floor_ms": round(vpu_floor_ms, 2),
            },
            "hbm_floor_ms": round(hbm_floor_ms, 2),
            "floor_ms": round(floor_ms, 2),
            "headroom_vs_floor_x": round(merge_ms / max(floor_ms, 1e-9), 1),
            "attribution_ms_r5": attribution,
            "binding_constraint": (
                "dom one-hot tombstone reduces (~2.5x floor) + one-hot "
                "placement; elementwise rmv/vc maxes already run at their "
                "bytes floor — see attribution + probe scripts"
            ),
        },
    }


def compute_model(R, NK, I, D_DCS, M, B, Br, apply_ms, apply_hbm_bytes):
    """Analytic compute roofline for the apply phase (VERDICT-r2 task 2):
    per-piece op counts from the kernel shapes, peak-based floors, and the
    measured removal-ablation attribution, so "what binds apply" is a
    number, not a claim.

    Piece models (see models/topk_rmv_dense.py for the kernels):
    * tombstones — `scatter_max_rows_mxu`: one s8 one-hot [Br, NK*I] x
      plane matrix [Br, 5*D] matmul per replica; MACs = R*Br*NK*I*5*D.
    * delta build — a 4-operand/4-key sort over B per replica plus three
      scalar 2-D scatters; no peak model (bitonic sort networks and XLA's
      serialized scatter loop are latency-bound, not throughput-bound) —
      the op counts are reported for scale.
    * join — elementwise add-wins filter + rank-arithmetic merge over
      [R, NK, I, 2M] plus a 2M-wide sort per id.

    Measured verdict (round 3, v5e, B=32768/Br=2048 — repro commands in
    the fields): the round sits ~5-10x above EVERY peak floor, yet three
    independent restructurings that attack the dominant modeled resource
    all REGRESS in composition: block-bucketed one-hot (32x fewer MACs)
    62.6 -> 87.5ms, runtime-adaptive 3-plane packing 62.6 -> 70.1ms
    (benchmarks/tomb_bucket_probe.py), and the pallas tombstone kernel
    40 -> 103ms (round 2, benchmarks/ablate_apply.py). The binding
    constraint is XLA's scheduling/serialization of the fused small-op
    chain (sorts, scatters, cross-piece fusion), not MXU, VPU, or HBM
    peak — which the attribution corroborates: removal deltas sum to
    ~37ms of a ~62ms round; the residual ~25ms is fusion/scheduling that
    no piece owns."""
    T = NK * I
    planes = 5
    macs = R * Br * T * planes * D_DCS
    mxu_floor_ms = macs * 2 / (MXU_INT8_PEAK_TOPS * 1e12) * 1e3
    hbm_floor_ms = apply_hbm_bytes / (HBM_PEAK_GB_S * 1e9) * 1e3
    floor_ms = max(mxu_floor_ms, hbm_floor_ms)
    # Round-5 attribution. Structure (which slices exist, what they
    # compute) comes from the per-HLO profile (profile_north_star.py,
    # committed as benchmarks/profile_r05.json): tombstone one-hot conv
    # 11.2 + plane-unpack/max 3.9 (reads the 5x-wide s32 conv output —
    # ~2.9GB/round, ~3.5ms HBM floor, ~90% of peak), 3x delta scalar
    # scatter fusions ~5.1 each, sorts, join compares/placement, dom
    # one-hot reduce. CAVEAT (discovered r5, recorded in the profile
    # script's docstring): that timeline is a deterministic MODELED
    # schedule on this AOT backend — r4/r5 captures reproduce to
    # +-0.001ms across sessions and code changes — so magnitudes below
    # come from wall-clock removal deltas (ablate_apply.py), which DO
    # see runtime effects like the r5 unique-indices scatter hint.
    # These are v5e measurements at the north-star shapes — attach only
    # where they apply (not tiny/CPU configs).
    attribution = (
        {
            # r5 session removal deltas (post unique-hint scatters).
            # delta_build = sort+rank+3 scatters removed together; the
            # scatters-only line extrapolates 3/2 x the 2-of-3-scatters
            # delta (11.9) and sits inside delta_build. The r4 session's
            # join delta read ~0.1 ("fuses free"); this session reads
            # 5.0 — treat cross-session piece values as +-2ms.
            "tombstones": 15.9, "delta_build": 20.6,
            "delta_scatters_3x_est": 17.8,
            "join_and_filter": 5.0, "vc_track": 0.0,
            "residual_unattributed": round(
                49.43 - 15.9 - 20.6 - 5.0 - 0.0, 1
            ),
            "full_round": 49.43,
            # full_round is the ablation harness's UNADJUSTED per-rep wall
            # (includes ~RTT/REPS of tunnel overhead — ~8-10ms at REPS=12,
            # which is most of residual_unattributed), so it reads higher
            # than measured_ms above (RTT-adjusted). The piece values are
            # removal DELTAS between equal-overhead runs — RTT-free.
            "methodology": (
                "removal deltas; full_round unadjusted; union-join + "
                "unique-hint scatters (r5 production)"
            ),
            "repro": "ABLATE_B=32768 ABLATE_BR=2048 python "
                     "benchmarks/ablate_apply.py",
        }
        if (R, I, B, Br) == (32, 100_000, 32768, 2048)
        else None
    )
    return {
        "apply": {
            "measured_ms": round(apply_ms, 2),
            "mxu": {
                "tombstone_onehot_macs": int(macs),
                "int8_peak_tops": MXU_INT8_PEAK_TOPS,
                "floor_ms": round(mxu_floor_ms, 2),
            },
            "hbm_floor_ms": round(hbm_floor_ms, 2),
            "floor_ms": round(floor_ms, 2),
            "headroom_vs_floor_x": round(apply_ms / max(floor_ms, 1e-9), 1),
            "sort_elems": int(R * B * 6),
            "scatter_rows": int(R * B * 3),
            "join_elementwise_ops": int(R * T * 2 * M * 12),
            "attribution_ms_r5": attribution,
            "hlo_profile_artifact": "benchmarks/profile_r05.json",
            "binding_constraint": (
                "3x delta scalar scatters (XLA's serialized update loop; "
                "r5 adopts the unique_indices hint — formally-unique "
                "indices, -3.8ms on the isolated sort+build, "
                "benchmarks/delta_place_probe.py — while the unsound "
                "sorted hint and the Mosaic carry-walk placement kernel "
                "are recorded rejections there; i64 packing, cond-"
                "packing and M-major layouts measured neutral-or-worse "
                "in benchmarks/residual_probe.py; the gather family — "
                "position-scatter+gathers, binary-search expansion, "
                "sorted block-window expansion — regresses 9-130x in "
                "benchmarks/delta_probe.py) + tombstone one-hot conv "
                "(~47% MXU util; MAC-cutting restructurings regress, "
                "benchmarks/tomb_bucket_probe.py) + its plane-unpack "
                "(~90% of HBM floor)"
            ),
        },
    }


def bench_curve(R, I, D_DCS, K, M, points, windows, W, e2e_samples):
    """Throughput/latency frontier over round batch size (VERDICT-r3 item
    4): the committed artifact behind BASELINE.md's former prose curve.

    Per point: windowed p50/p99 (scan-fused, W rounds/window) and
    single-dispatch e2e p50/p99 over `e2e_samples` real host-readback
    round trips (p99 of a small sample ~= max; the sample count is in the
    record). Rmv batch keeps the north-star 1/16 ratio.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from antidote_ccrdt_tpu.harness.opgen import TopkRmvEffectGen, Workload
    from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense

    D = make_dense(n_ids=I, n_dcs=D_DCS, size=K, slots_per_id=M)
    gen = TopkRmvEffectGen(
        Workload(n_replicas=R, n_ids=I, zipf_a=1.2, score_max=100_000, seed=11)
    )
    out = []
    for B in points:
        Br = B // 16
        state = D.init(n_replicas=R, n_keys=1)
        batches = [
            _stack_rounds([gen.next_batch(B, Br) for _ in range(W)])
            for _ in range(windows + 1)
        ]

        @jax.jit
        def run_window(state, stacked):
            def body(st, ops):
                st2, _ = D.apply_ops(st, ops, collect_dominated=False)
                return st2, ()
            o, _ = lax.scan(body, state, stacked)
            return o

        state = run_window(state, batches[0])
        _sync(state)
        per_round = []
        for w in range(windows):
            t0 = time.perf_counter()
            state = run_window(state, batches[1 + w])
            _sync(state)
            per_round.extend([(time.perf_counter() - t0) / W] * W)
        p50 = float(np.percentile(per_round, 50) * 1e3)
        p99 = float(np.percentile(per_round, 99) * 1e3)
        rate = R * (B + Br) / float(np.median(per_round))

        @jax.jit
        def run_one(state, ops):
            st2, _ = D.apply_ops(state, ops, collect_dominated=False)
            return st2

        singles = []
        one_ops = [
            jax.tree.map(lambda a: a[i % W], batches[1 + (i // W) % windows])
            for i in range(e2e_samples)
        ]
        st1 = run_one(state, one_ops[0])
        _sync(st1)
        for ops in one_ops:
            t0 = time.perf_counter()
            st1 = run_one(st1, ops)
            _sync(st1)
            singles.append(time.perf_counter() - t0)
        out.append(
            {
                "batch_adds": B,
                "batch_rmvs": Br,
                "merges_per_sec": round(rate),
                "p50_round_ms_windowed": round(p50, 2),
                "p99_round_ms_windowed": round(p99, 2),
                "p50_round_ms_e2e": round(float(np.percentile(singles, 50) * 1e3), 2),
                "p99_round_ms_e2e": round(float(np.percentile(singles, 99) * 1e3), 2),
                "e2e_samples": e2e_samples,
            }
        )
    return out


def bench_scalar_baseline(R, I, D_DCS, K, n_ops):
    """Apply the same kind of effect ops through the scalar reference
    semantics, one op per `update` call, on one CPU core."""
    from antidote_ccrdt_tpu.models.topk_rmv import TopkRmvScalar

    S = TopkRmvScalar()
    state = S.new(K)
    rng = np.random.default_rng(3)
    ids = rng.integers(0, I, n_ops)
    scores = rng.integers(1, 100_000, n_ops)
    dcs = rng.integers(0, D_DCS, n_ops)
    is_rmv = rng.random(n_ops) < 0.1
    frontier = {}
    effects = []
    for j in range(n_ops):
        dc = int(dcs[j])
        if is_rmv[j]:
            effects.append(("rmv", (int(ids[j]), dict(frontier))))
        else:
            ts = frontier.get(dc, 0) + 1
            frontier[dc] = ts
            effects.append(("add", (int(ids[j]), int(scores[j]), (dc, ts))))
    t0 = time.perf_counter()
    for eff in effects:
        state, _extras = S.update(eff, state)
    dt = time.perf_counter() - t0
    return n_ops / dt


def bench_round_phases(R, I, D_DCS, K, M, B, Br, rounds=6, overlap=None):
    """Round-phase span drill (obs/spans.py): run a real two-member
    gossip round loop — apply + device sync + WAL append + delta publish
    + peer sweep + lag update — at the operating point with the span
    plane armed, then attribute each round's wall time to phases.

    This is where the dispatch-gap question gets a number: the summary's
    e2e round latency says how long a round takes; this block says which
    phase owns that time, how much is serial host work vs overlappable
    I/O, and how much no span accounts for (the gap). chaos_gate.py runs
    the same drill tiny and fails if any load-bearing phase goes dark.

    `overlap` routes the round through parallel/overlap.py (None = the
    CCRDT_OVERLAP default, ON): device sync + WAL append + publish ride
    the pipeline's HostStage thread, the peer side prefetches through a
    threadless `DeltaPrefetcher.poll` + `drain_into`. The wal_append /
    delta_encode / gossip spans then land on the host-stage tid and
    attribute() reclassifies them serial -> overlappable — the measured
    proof that the dispatch gap collapses. The `overlap` counter block in
    the result is what chaos_gate.py asserts nonzero when the mode is on.

    PR 15 reshapes the drill along the ingest fast path: (1) each round
    applies TWO op sub-batches and logs TWO WAL steps before the single
    boundary flush, so `wal.group_size` measures real group commit
    instead of the degenerate 1-append-per-flush loop; (2) in overlap
    mode the publisher DEFERS delta windows (`publish(..., defer=True)`)
    and ships one compacted range frame per `coalesce_max()` windows —
    the round thread only waits on gossip when a frame actually shipped,
    and the blocking device sync runs at ship boundaries only; (3) the
    result carries `ingest_phase_ms_total` (recv+decode+dispatch+apply+
    sync) and `coalesce_ratio` (windows covered per wire frame) for the
    bench_gate ingest gate.
    """
    import tempfile

    import jax

    from antidote_ccrdt_tpu.core.behaviour import registry
    from antidote_ccrdt_tpu.harness.opgen import TopkRmvEffectGen, Workload
    from antidote_ccrdt_tpu.harness.wal import ElasticWal, durability_mode
    from antidote_ccrdt_tpu.obs import lag as obs_lag
    from antidote_ccrdt_tpu.obs import spans
    from antidote_ccrdt_tpu.parallel import elastic as elastic_mod
    from antidote_ccrdt_tpu.parallel import overlap as overlap_mod
    from antidote_ccrdt_tpu.parallel.elastic import (
        DeltaPublisher,
        GossipStore,
        sweep_deltas,
    )

    ovl_on = overlap_mod.enabled(overlap)

    D = registry.make_dense(
        "topk_rmv", n_ids=I, n_dcs=D_DCS, size=K, slots_per_id=M
    )
    gen = TopkRmvEffectGen(
        Workload(n_replicas=R, n_ids=I, zipf_a=1.2, score_max=100_000, seed=23)
    )
    # Two op sub-batches per round (half size each): the round loop logs
    # one WAL step per sub-batch and flushes once at the boundary, so
    # the group-commit coalescer has a real batch to coalesce.
    Bh, Brh = max(1, B // 2), max(1, Br // 2)
    batches = [gen.next_batch(Bh, Brh) for _ in range(2 * rounds + 2)]

    @jax.jit
    def run_one(state, ops):
        st2, _ = D.apply_ops(state, ops, collect_dominated=False)
        return st2

    state = D.init(n_replicas=R, n_keys=1)
    state = run_one(state, batches[0])  # compile outside the spanned rounds
    state = run_one(state, batches[1])
    _sync(state)

    # Warm the peer-side ingest path outside the spans too: the fused
    # fold compiles one XLA program per merge width (stack depths 3..9
    # exercise widths 1..4), and the donated merge slots + the delta
    # cut/expand pair compile on first touch. Cold, that is ~1s of
    # one-time compile billed inside round.delta_apply/device_sync —
    # enough to swamp the steady-state attribution this drill exists
    # to measure (rounds=3 on cpu).
    from antidote_ccrdt_tpu.core import batch_merge
    from antidote_ccrdt_tpu.parallel import delta as delta_mod

    for depth in (9, 7):
        _sync(batch_merge.fold_states(D.merge, [state] * depth))
    zl, zr = D.init(n_replicas=R, n_keys=1), D.init(n_replicas=R, n_keys=1)
    _sync(batch_merge.merge_into(D.merge, zl, zr))
    wd = delta_mod.make_delta(D, zl, state)
    _sync(delta_mod.expand_delta(D, wd))

    with tempfile.TemporaryDirectory(prefix="ccrdt_spanbench_") as root:
        with spans.installed("bench0"):
            node = GossipStore(root, "bench0")
            peer = GossipStore(root, "bench1")
            wal = ElasticWal(root, "bench0", D, "topk_rmv",
                             metrics=node.metrics)
            coalescer = overlap_mod.CommitCoalescer(metrics=node.metrics)
            coalescer.add(wal)
            pub = DeltaPublisher(node, D, name="topk_rmv")
            tracker = obs_lag.LagTracker("bench1")
            peer_state = D.init(n_replicas=R, n_keys=1)
            cursors = {}
            owned = list(range(R))
            ovl = None
            if ovl_on:
                # Threadless prefetch (poll() driven inline, deadline-
                # bounded) keeps the drill deterministic; the HostStage
                # is the real worker thread — its spans land off-tid.
                ovl = overlap_mod.OverlapPipeline(
                    peer, D, peer_state, metrics=peer.metrics,
                    start_thread=False,
                )

            compact_on = elastic_mod.compact_enabled()
            coalesce_k = elastic_mod.coalesce_max()
            # Deterministic mirror of the publisher's ship decision so
            # the round thread knows — without racing the host stage —
            # whether this round's publishes put a frame on the wire
            # (anchor cadence, or the coalesce window filling). Only
            # ship rounds pay the recv-wait; staged rounds fall
            # straight through to the next dispatch.
            ship_model = {"seq": 0, "staged": 0}

            def _round_ships() -> bool:
                ships = False
                for _ in range(2):
                    ship_model["seq"] += 1
                    s = ship_model["seq"]
                    if s == 1 or s % pub.full_every == 0:
                        ship_model["staged"] = 0  # anchor supersedes
                        ships = True
                    elif not (ovl_on and compact_on):
                        ships = True  # kill switch: every window ships
                    else:
                        ship_model["staged"] += 1
                        if ship_model["staged"] >= coalesce_k:
                            ship_model["staged"] = 0
                            ships = True
                return ships

            def _boundary(prev, mid, snap, r, ship):
                # Blocking device sync only when a frame actually goes
                # out — staged rounds leave the device chain running
                # and the publish boundary absorbs the sync.
                if ship:
                    with spans.span(
                        "round.device_sync", step=r, via="overlap"
                    ):
                        _sync(snap)
                # Two WAL appends, ONE group-commit flush: group_size
                # now measures real coalescing (the 1-append-per-flush
                # loop through PR 14 pinned the p50 at 1.0). The first
                # append reuses the publisher's delta (PR 11); the
                # second interval (mid -> snap) is cut by the WAL —
                # its publish is deferred, so there is no
                # pre-serialized blob to share.
                enc = pub.encode_delta(mid)
                wal.log_step(
                    2 * r, owned, prev, mid,
                    delta=enc["delta"] if enc else None,
                    blob=enc["blob"] if enc else None,
                )
                wal.log_step(2 * r + 1, owned, mid, snap)
                coalescer.flush()
                pub.publish(mid, encoded=enc, defer=True)
                pub.publish(snap, defer=True)

            for r in range(rounds):
                e2e = spans.begin("round.e2e", step=r)
                prev = state
                with spans.span(
                    "round.device_dispatch", site="bench.apply_ops",
                    n=Bh + Brh,
                ):
                    mid = run_one(state, batches[2 + 2 * r])
                with spans.span(
                    "round.device_dispatch", site="bench.apply_ops",
                    n=Bh + Brh,
                ):
                    state = run_one(mid, batches[3 + 2 * r])
                ship = _round_ships()
                if ovl is not None:
                    # The wait below is the drill's deterministic
                    # stand-in for the threaded prefetcher: on a ship
                    # round the thread holds until the boundary's
                    # frame is visible to the peer so delta_apply has
                    # work to measure. The span opens BEFORE submit —
                    # a full host queue blocks right there, and that
                    # backpressure was part of the dark slice in the
                    # r09 coverage ledger. Staged rounds bill only the
                    # submit and move on.
                    with spans.span(
                        "round.gossip_recv", step=r,
                        via="wait" if ship else "backpressure",
                    ):
                        ovl.submit(_boundary, prev, mid, state, r, ship)
                        if ship:
                            deadline = time.perf_counter() + 0.25
                            while (
                                not ovl.prefetch.poll()
                                and len(ovl.apq) == 0
                                and time.perf_counter() < deadline
                            ):
                                time.sleep(0.001)
                    peer_state = ovl.drain_into(peer_state)
                else:
                    with spans.span("round.device_sync", step=r):
                        _sync(state)
                    enc = pub.encode_delta(mid)
                    wal.log_step(
                        2 * r, owned, prev, mid,
                        delta=enc["delta"] if enc else None,
                        blob=enc["blob"] if enc else None,
                    )
                    wal.log_step(2 * r + 1, owned, mid, state)
                    coalescer.flush()
                    pub.publish(mid, encoded=enc)
                    pub.publish(state)
                    peer_state, _stats = sweep_deltas(
                        peer, D, peer_state, cursors
                    )
                with spans.span("round.lag_update"):
                    tracker.observe_published("bench0", pub.seq)
                    applied = (ovl.cursors if ovl is not None else cursors)
                    tracker.observe_applied(
                        "bench0", applied.get("bench0", -1)
                    )
                    tracker.export_to(node.metrics)
                spans.end(e2e)
            if ovl is not None:
                ovl.submit(pub.flush_wire)  # ship the staged tail
                ovl.host.drain()  # last publish visible before final poll
                # Poll to quiescence: one pass only advances a fresh
                # member past its anchor — the delta chain behind it
                # needs the next pass (threaded mode loops for free).
                while ovl.prefetch.poll():
                    pass
                peer_state = ovl.close(peer_state)
            wal.close()
            recs = spans.drain()
    att = spans.attribute({"bench0": recs})
    fleet = att["fleet"]
    cnt_node = node.metrics.snapshot()["counters"]
    cnt_peer = peer.metrics.snapshot()["counters"]
    ovl_counters = {
        k: v
        for src in (cnt_node, cnt_peer)
        for k, v in src.items()
        if k.startswith("overlap.")
    }
    ing_counters = {}
    for src in (cnt_node, cnt_peer):
        for k, v in src.items():
            if k.startswith("ingest."):
                ing_counters[k] = ing_counters.get(k, 0) + v
    # Windows covered per wire frame: a frame [lo..hi] carries
    # hi-lo+1 windows (ingest.coalesced_ops counts them for multi-
    # window frames), a legacy frame carries one. 1.0 = no compaction.
    frames = cnt_node.get("net.delta_publishes", 0)
    co_frames = cnt_node.get("ingest.coalesced_frames", 0)
    co_ops = cnt_node.get("ingest.coalesced_ops", 0)
    coalesce_ratio = (co_ops + frames - co_frames) / max(1, frames)
    ingest_ms = sum(
        fleet["phases_ms_total"].get(p, 0.0)
        for p in (
            "round.gossip_recv", "round.delta_decode",
            "round.device_dispatch", "round.delta_apply",
            "round.device_sync",
        )
    )
    groups = node.metrics.snapshot()["latencies"].get("wal.group_size", [])
    return {
        "overlap": {"enabled": ovl_on, **ovl_counters},
        "ingest": {
            "compact": bool(compact_on and ovl_on),
            "coalesce_max": coalesce_k,
            **dict(sorted(ing_counters.items())),
        },
        "ingest_phase_ms_total": round(ingest_ms, 3),
        "coalesce_ratio": round(coalesce_ratio, 3),
        "wal_durability": durability_mode(),
        "wal_group_size_p50": (
            float(np.percentile(groups, 50)) if groups else 0.0
        ),
        "wal_append_ms_total": round(
            fleet["phases_ms_total"].get("round.wal_append", 0.0), 3
        ),
        "rounds": fleet["rounds"],
        "e2e_ms_p50": round(fleet["e2e_ms_p50"], 3),
        "serial_ms_p50": round(fleet["serial_ms_p50"], 3),
        "overlap_ms_p50": round(fleet["overlap_ms_p50"], 3),
        "dispatch_gap_ms_p50": round(fleet["gap_ms_p50"], 3),
        "span_coverage_p50": round(fleet["coverage_p50"], 4),
        "phases_ms_total": {
            n: round(v, 3) for n, v in fleet["phases_ms_total"].items()
        },
        "critical_path": fleet["critical_path"],
    }


def bench_ingest():
    """Standalone ingest fast-path microbench (`python bench.py
    bench_ingest`): the spanned gossip round drill twice — compact
    ingest ON, then the `CCRDT_INGEST_COMPACT=0` kill-switch rerun —
    printed as one JSON line carrying both `ingest_phase_ms_total`
    figures plus the coalesce ratio and ingest counters. Same keys as
    the BENCH summary line, so `scripts/bench_gate.py ingest` reads
    either carrier."""
    import jax

    backend = jax.default_backend()
    if os.environ.get("CCRDT_BENCH_TINY"):
        cfg = dict(R=2, I=256, D_DCS=2, K=100, M=4, B=32, Br=8, rounds=3)
    elif backend == "cpu":
        cfg = dict(
            R=8, I=10_000, D_DCS=8, K=100, M=4, B=1024, Br=64, rounds=3
        )
    else:
        cfg = dict(
            R=32, I=100_000, D_DCS=32, K=100, M=4, B=32768, Br=2048,
            rounds=6,
        )
    prev_env = os.environ.get("CCRDT_INGEST_COMPACT")
    try:
        os.environ["CCRDT_INGEST_COMPACT"] = "1"
        on = bench_round_phases(**cfg)
        os.environ["CCRDT_INGEST_COMPACT"] = "0"
        off = bench_round_phases(**cfg)
    finally:
        if prev_env is None:
            os.environ.pop("CCRDT_INGEST_COMPACT", None)
        else:
            os.environ["CCRDT_INGEST_COMPACT"] = prev_env
    out = {
        "metric": "ingest_phase_ms_total (compact on vs kill-switch off)",
        "backend": backend,
        "ingest_phase_ms_total": on["ingest_phase_ms_total"],
        "ingest_phase_ms_total_nocompact": off["ingest_phase_ms_total"],
        "coalesce_ratio": on["coalesce_ratio"],
        "ingest": on["ingest"],
        "span_coverage_p50": on["span_coverage_p50"],
        "wal_group_size_p50": on["wal_group_size_p50"],
        "dispatch_gap_ms_p50": on["dispatch_gap_ms_p50"],
    }
    print(json.dumps(out))
    return out


def bench_serve(frames=400, batch=512):
    """Read-serving plane microbench (serve/plane.py).

    One worker, one swapped replica, one thread, no wire: direct
    `ServePlane.handle` calls with `batch` mixed queries per frame
    (70% value / 20% topk / 10% range), the same frame shape the serve
    demo's clients send over TCP. Measures the serving engine itself —
    codec + batcher + memoized materialization — without chaos or
    socket noise, so rounds are comparable: ``serve_reads_per_sec``
    (served results / wall time) and ``serve_read_p99_ms`` (per-frame
    p99). Protocol-bound after the single warm materialization;
    geometry stays fixed and small on every backend."""
    import random

    from antidote_ccrdt_tpu import serve as serve_mod
    from antidote_ccrdt_tpu.harness.opgen import TopkRmvEffectGen, Workload
    from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense

    R, I, D_DCS, K, M = 4, 256, 4, 8, 2
    dense = make_dense(n_ids=I, n_dcs=D_DCS, size=K, slots_per_id=M)
    gen = TopkRmvEffectGen(
        Workload(n_replicas=R, n_ids=I, zipf_a=1.2, score_max=10_000, seed=3)
    )
    state = dense.init(n_replicas=R, n_keys=1)
    for _ in range(4):
        state, _ = dense.apply_ops(
            state, gen.next_batch(64, 8), collect_dominated=False
        )
    plane = serve_mod.ServePlane(dense, member="bench")
    plane.swap(state, 0)

    rng = random.Random(11)
    reqs = []
    for _ in range(8):  # a few frame shapes, reused round-robin
        qs = []
        for _ in range(batch):
            pick = rng.random()
            if pick < 0.7:
                qs.append({"op": "value", "key": 0})
            elif pick < 0.9:
                qs.append({"op": "topk", "key": 0, "k": rng.choice((3, 5))})
            else:
                lo = rng.choice((0, 100, 1000))
                qs.append({"op": "range", "key": 0, "lo": lo, "hi": lo + 900})
        reqs.append(serve_mod.request_bytes(qs, max_staleness_s=600.0))
    plane.handle(reqs[0])  # warm: compiles the fold, fills the memo

    lat = []
    t0 = time.perf_counter()
    for i in range(frames):
        t = time.perf_counter()
        plane.handle(reqs[i % len(reqs)])
        lat.append(time.perf_counter() - t)
    total = time.perf_counter() - t0
    lat.sort()
    return {
        "frames": frames,
        "batch": batch,
        "serve_reads_per_sec": round(frames * batch / total),
        "serve_read_p50_ms": round(lat[len(lat) // 2] * 1e3, 3),
        "serve_read_p99_ms": round(lat[int(0.99 * (len(lat) - 1))] * 1e3, 3),
    }


def bench_read_tier(frames=300, batch=64):
    """Fleet read-tier microbench (serve/router.py).

    Three in-process `ServePlane` replicas behind a `FleetRouter` with
    direct dispatch (no sockets): measures what the ROUTING layer costs
    on top of serving — HRW candidate choice, attempt threads, breaker
    and watermark bookkeeping, the response re-decode — plus the
    failover blip when one peer's SWIM verdict flips to dead mid-run
    (the longest gap between consecutive successful responses around
    the flip). Report-only in the committed details; the gated carrier
    is READTIER_r*.json from scripts/read_tier_demo.py (real sockets,
    real SIGKILL, chaos on)."""
    import random

    from antidote_ccrdt_tpu import serve as serve_mod
    from antidote_ccrdt_tpu.harness.opgen import TopkRmvEffectGen, Workload
    from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense
    from antidote_ccrdt_tpu.utils.metrics import Metrics

    R, I, D_DCS, K, M = 4, 256, 4, 8, 2
    dense = make_dense(n_ids=I, n_dcs=D_DCS, size=K, slots_per_id=M)
    gen = TopkRmvEffectGen(
        Workload(n_replicas=R, n_ids=I, zipf_a=1.2, score_max=10_000, seed=3)
    )
    state = dense.init(n_replicas=R, n_keys=1)
    for _ in range(4):
        state, _ = dense.apply_ops(
            state, gen.next_batch(64, 8), collect_dominated=False
        )
    members = ["b0", "b1", "b2"]
    planes = {}
    for m in members:
        planes[m] = serve_mod.ServePlane(dense, member=m)
        planes[m].swap(state, 0)
    dead: set = set()

    def qfn(peer, payload, timeout_s, cancel):
        return planes[peer].handle(payload)

    router = serve_mod.FleetRouter(
        members, qfn, metrics=Metrics(), hedge=False, retries=1,
        poll_s=0.0005,
        verdict_fn=lambda p: "dead" if p in dead else "alive",
    )
    rng = random.Random(11)
    qs = [{"op": "value", "key": 0} for _ in range(batch)]
    router.query(qs, key="warm")  # warm: compiles the fold, fills the memo

    from antidote_ccrdt_tpu.topo import rendezvous_order

    victim = rendezvous_order("k0", members)[0]
    lat = []
    ok_t = []
    flip_at = frames // 2
    t_flip = None
    t0 = time.perf_counter()
    for i in range(frames):
        if i == flip_at:
            dead.add(victim)  # SWIM buries a replica mid-run
            t_flip = time.perf_counter()
        t = time.perf_counter()
        out = router.query(qs, key=f"k{rng.randrange(16)}")
        dt = time.perf_counter() - t
        lat.append(dt)
        if "peer" in out and "error" not in out:
            ok_t.append(time.perf_counter())
    total = time.perf_counter() - t0
    lat.sort()
    blip_ms = 0.0
    if t_flip is not None and ok_t:
        window = [t_flip] + [x for x in ok_t if x >= t_flip][:20]
        gaps = [b - a for a, b in zip(window, window[1:])]
        blip_ms = max(gaps) * 1e3 if gaps else 0.0
    return {
        "frames": frames,
        "batch": batch,
        "fleet_reads_per_sec": round(len(ok_t) * batch / total),
        "read_p99_ms": round(lat[int(0.99 * (len(lat) - 1))] * 1e3, 3),
        "failover_blip_ms": round(blip_ms, 3),
        "killed": victim,
    }


def bench_partition_antientropy(P=8, resync_rounds=4):
    """Partition-plane anti-entropy microbench (core/partition.py).

    Two gossip nodes over the FS transport; the writer repeatedly
    advances ONE partition and anchors, the reader repairs each gap via
    `PartialAntiEntropy`. Reports the wire cost of a partial repair —
    ``antientropy_bytes_per_resync`` (digest vector + fetched psnaps,
    averaged over the resyncs) — against the whole-snapshot blob the
    legacy path would have pulled for the same gap, plus
    ``rejoin_stream_seconds``: wall time for a cold `RejoinStreamer`
    to stream the final state partition by partition (shards persisted
    as it goes). Protocol-bound, not accelerator-bound: geometry stays
    fixed and small on every backend so rounds are comparable."""
    import shutil
    import tempfile

    from antidote_ccrdt_tpu.core import partition as pt
    from antidote_ccrdt_tpu.harness.checkpoint import RejoinStreamer
    from antidote_ccrdt_tpu.models.topk_rmv_dense import (
        TopkRmvOps, make_dense,
    )
    from antidote_ccrdt_tpu.net.transport import FsTransport, GossipNode
    from antidote_ccrdt_tpu.parallel.elastic import (
        DeltaPublisher, PartialAntiEntropy, sweep_deltas,
    )

    import jax.numpy as jnp

    R, NK, I, DCS, K, M, B = 4, 1, 256, 4, 8, 2, 32
    dense = make_dense(n_ids=I, n_dcs=DCS, size=K, slots_per_id=M)
    part_map = pt.part_of(np.arange(I), P)
    p_star = int(np.bincount(part_map, minlength=P).argmax())
    pools = {
        "all": np.arange(I, dtype=np.int32),
        "hot": np.arange(I, dtype=np.int32)[part_map == p_star],
    }

    def apply_ops(state, step, pool):
        rng = np.random.default_rng(55_000 + step)
        a_id = pools[pool][rng.integers(0, len(pools[pool]), (R, B))]
        z = np.zeros((R, B), np.int32)
        ops = TopkRmvOps(
            add_key=jnp.asarray(z),
            add_id=jnp.asarray(a_id.astype(np.int32)),
            add_score=jnp.asarray(rng.integers(1, 500, (R, B)).astype(np.int32)),
            add_dc=jnp.asarray(z),
            add_ts=jnp.asarray(np.broadcast_to(
                step * B + np.arange(B) + 1, (R, B)
            ).astype(np.int32)),
            rmv_key=jnp.asarray(np.zeros((R, 1), np.int32)),
            rmv_id=jnp.asarray(np.full((R, 1), -1, np.int32)),
            rmv_vc=jnp.asarray(np.zeros((R, 1, DCS), np.int32)),
        )
        state, _ = dense.apply_ops(state, ops, collect_dominated=False)
        return state

    root = tempfile.mkdtemp(prefix="ccrdt_ae_bench_")
    try:
        a = GossipNode(FsTransport(root, "a"))
        b = GossipNode(FsTransport(root, "b"))
        a.heartbeat(), b.heartbeat()
        pub = DeltaPublisher(
            a, dense, name="topk_rmv", full_every=1, partitions=P
        )
        partial = PartialAntiEntropy(b, partitions=P)
        st_a = dense.init(R, NK)
        step = 0
        for _ in range(3):  # shared prefix over the whole id space
            st_a = apply_ops(st_a, step, "all")
            step += 1
        pub.publish(st_a)
        curs = {}
        st_b, _ = sweep_deltas(b, dense, dense.init(R, NK), curs)

        partial_bytes = whole_bytes = resyncs = 0
        for _ in range(resync_rounds):
            st_a = apply_ops(st_a, step, "hot")
            step += 1
            pub.publish(st_a)
            whole_bytes += len(b.transport.fetch("a"))
            raw_dig = b.transport.fetch_digest("a")
            partial_bytes += len(raw_dig) if raw_dig else 0
            c0 = b.metrics.counters.get("net.psnap_bytes", 0)
            st_b, _stats = sweep_deltas(b, dense, st_b, curs, partial=partial)
            partial_bytes += b.metrics.counters.get("net.psnap_bytes", 0) - c0
            resyncs += 1
        if not np.array_equal(
            pt.state_digests(st_b, P), pt.state_digests(st_a, P)
        ):
            raise RuntimeError("anti-entropy bench diverged — repair broken")

        # Cold rejoin: stream the writer's final anchor partition by
        # partition into an empty worker, persisting each shard. One
        # warmup fetch first so jit compilation of the psnap join does
        # not masquerade as streaming time.
        warm = RejoinStreamer(
            os.path.join(root, "warm"), "topk_rmv", dense, b, "a",
            partitions=P,
        )
        warm.run(warm.start(dense.init(R, NK)))
        streamed0 = int(b.metrics.counters.get("rejoin.parts_streamed", 0))
        t0 = time.perf_counter()
        streamer = RejoinStreamer(
            os.path.join(root, "ckpt"), "topk_rmv", dense, b, "a",
            partitions=P,
        )
        st_r = streamer.run(streamer.start(dense.init(R, NK)))
        rejoin_s = time.perf_counter() - t0
        if streamer.plan or not np.array_equal(
            pt.state_digests(st_r, P), pt.state_digests(st_a, P)
        ):
            raise RuntimeError("rejoin bench did not reach the peer state")
        streamed = int(
            b.metrics.counters.get("rejoin.parts_streamed", 0) - streamed0
        )
    finally:
        shutil.rmtree(root, ignore_errors=True)

    per_resync = partial_bytes / max(1, resyncs)
    return {
        "partitions": P,
        "resyncs": resyncs,
        "antientropy_bytes_per_resync": round(per_resync, 1),
        "whole_bytes_per_resync": round(whole_bytes / max(1, resyncs), 1),
        "antientropy_reduction_x": round(whole_bytes / max(1.0, partial_bytes), 2),
        "rejoin_stream_seconds": round(rejoin_s, 3),
        "rejoin_parts_streamed": streamed,
    }


def bench_working_set(P=64, ids=4096, batches=30, B=256, zipf_a=2.2):
    """Out-of-core pager microbench (core/pager.py).

    One worker whose state is ~10x its device budget by construction
    (``hbm_budget = state_bytes // 10``), serving zipfian op traffic:
    every batch declares its touched ids up front
    (`ensure_resident` over the PER-ACCESS partition list, so hit/miss
    accounting is per access, not per unique partition), applies the
    ops device-side, then folds one uniform peer delta through
    `apply_delta` so the cold tier absorbs merges host-side. Reports
    the three gated headline numbers — ``pager_hit_rate`` (fraction of
    accesses that found their partition resident, post-warmup),
    ``resident_miss_ms_p50`` (page-in latency, raw ms samples from the
    `pager.miss_ms` histogram — NOT LatencyRecorder.summary(), which
    assumes seconds), ``cold_merges_per_sec`` (host-side partition
    folds) — plus the residency ratio for the record. Protocol-bound:
    geometry stays fixed and small on every backend so rounds compare."""
    from antidote_ccrdt_tpu.core import pager as pg
    from antidote_ccrdt_tpu.core import partition as pt
    from antidote_ccrdt_tpu.models.topk_rmv_dense import (
        TopkRmvOps, make_dense,
    )
    from antidote_ccrdt_tpu.parallel.delta import make_delta

    import jax.numpy as jnp

    R, NK, I, DCS, K, M = 2, 1, int(ids), 4, 8, 2
    dense = make_dense(n_ids=I, n_dcs=DCS, size=K, slots_per_id=M)
    rng = np.random.default_rng(77_000)

    def apply_ids(state, a_id, step):
        b = a_id.shape[1]
        ops = TopkRmvOps(
            add_key=jnp.zeros((R, b), jnp.int32),
            add_id=jnp.asarray(a_id.astype(np.int32)),
            add_score=jnp.asarray(rng.integers(1, 500, (R, b)).astype(np.int32)),
            add_dc=jnp.zeros((R, b), jnp.int32),
            add_ts=jnp.asarray(np.broadcast_to(
                step * b + np.arange(b) + 1, (R, b)
            ).astype(np.int32)),
            rmv_key=jnp.zeros((R, 1), jnp.int32),
            rmv_id=jnp.full((R, 1), -1, jnp.int32),
            rmv_vc=jnp.zeros((R, 1, DCS), jnp.int32),
        )
        state, _ = dense.apply_ops(state, ops, collect_dominated=False)
        return state

    def zipf_ids(n):
        return ((rng.zipf(zipf_a, size=(R, n)) - 1) % I).astype(np.int32)

    # Seed the whole id space so every partition has real content to
    # demote, then size the budget off the measured footprint.
    state = dense.init(R, NK)
    for s in range(2):
        state = apply_ids(state, rng.integers(0, I, (R, 512)), s)
    probe = pg.PartitionPager(dense, state, P=P, name="workset_probe")
    total = probe.meta_bytes + sum(probe.part_bytes[p] for p in range(P))
    budget = max(1, total // 10)
    pager = pg.PartitionPager(
        dense, state, P=P, name="workset", hbm_budget_bytes=budget
    )
    peer = dense.init(R, NK)
    step = 2

    def one_batch(state, peer, step):
        a_id = zipf_ids(B)
        # Per-access partition list (not unique): hit/miss accounting
        # per access, and the clock sees zipf frequency, not presence.
        state = pager.ensure_resident(state, pt.part_of(a_id.ravel(), P))
        state = apply_ids(state, a_id, step)
        # Uniform peer delta: mostly-cold partitions, folded host-side.
        prev = peer
        peer = apply_ids(peer, rng.integers(0, I, (R, 64)), step)
        state = pager.apply_delta(state, make_delta(dense, prev, peer))
        return state, peer

    for _ in range(3):  # warmup: jit compiles + demote-to-budget
        state, peer = one_batch(state, peer, step)
        step += 1
    pager.hits = pager.misses = 0
    rec = pager.metrics.latencies.get("pager.miss_ms")
    warm_samples = len(rec.samples) if rec is not None else 0
    folds0 = pager.metrics.counters.get("pager.cold_folds", 0)
    t0 = time.perf_counter()
    for _ in range(batches):
        state, peer = one_batch(state, peer, step)
        step += 1
    elapsed = time.perf_counter() - t0
    folds = pager.metrics.counters.get("pager.cold_folds", 0) - folds0
    rec = pager.metrics.latencies.get("pager.miss_ms")
    miss_samples = list(rec.samples)[warm_samples:] if rec is not None else []
    miss_p50 = float(np.percentile(miss_samples, 50)) if miss_samples else 0.0

    # Sanity: the mixed-residency digest vector must match a full
    # reassembly — a silent cold-digest desync would make every number
    # above a lie about a diverging store.
    full = pager.full_state(state)
    if not np.array_equal(pager.digest_vector(state), pt.state_digests(full, P)):
        raise RuntimeError("working-set bench diverged — cold digests desynced")

    return {
        "partitions": P,
        "state_bytes": int(total),
        "hbm_budget_bytes": int(budget),
        "state_over_budget_x": round(total / budget, 1),
        "pager_hit_rate": round(pager.hit_rate(), 4),
        "resident_miss_ms_p50": round(miss_p50, 3),
        "cold_merges_per_sec": round(folds / max(elapsed, 1e-9), 1),
        "hydrations": int(pager.metrics.counters.get("pager.hydrations", 0)),
        "evictions": int(pager.metrics.counters.get("pager.evictions", 0)),
    }


def bench_audit_overhead(P=8, rounds=12, repeats=3):
    """Audit-plane overhead microbench (obs/audit.py).

    The same two-node FS-transport publish/sweep round loop run both
    ways — audit plane dark, then armed (per-round digest-vector
    sampling via `core.partition.DigestSampler` plus a digest fetch +
    `DivergenceWatchdog.observe_peer` on the reader, the exact work a
    certifiable fleet adds to every round) — reporting
    ``audit_overhead_pct``: the relative wall cost of running certified.
    Each arm takes the min over `repeats` fresh runs (after 2 warmup
    rounds per run) so FS jitter does not masquerade as a regression;
    protocol-bound fixed geometry keeps rounds comparable across
    backends."""
    import shutil
    import tempfile

    from antidote_ccrdt_tpu.core import partition as pt
    from antidote_ccrdt_tpu.models.topk_rmv_dense import (
        TopkRmvOps, make_dense,
    )
    from antidote_ccrdt_tpu.net.transport import FsTransport, GossipNode
    from antidote_ccrdt_tpu.obs.audit import DivergenceWatchdog
    from antidote_ccrdt_tpu.parallel.elastic import (
        DeltaPublisher, sweep_deltas,
    )

    import jax.numpy as jnp

    R, NK, I, DCS, K, M, B = 4, 1, 256, 4, 8, 2, 32
    dense = make_dense(n_ids=I, n_dcs=DCS, size=K, slots_per_id=M)

    def apply_ops(state, step):
        rng = np.random.default_rng(77_000 + step)
        z = np.zeros((R, B), np.int32)
        ops = TopkRmvOps(
            add_key=jnp.asarray(z),
            add_id=jnp.asarray(rng.integers(0, I, (R, B)).astype(np.int32)),
            add_score=jnp.asarray(rng.integers(1, 500, (R, B)).astype(np.int32)),
            add_dc=jnp.asarray(z),
            add_ts=jnp.asarray(np.broadcast_to(
                step * B + np.arange(B) + 1, (R, B)
            ).astype(np.int32)),
            rmv_key=jnp.asarray(np.zeros((R, 1), np.int32)),
            rmv_id=jnp.asarray(np.full((R, 1), -1, np.int32)),
            rmv_vc=jnp.asarray(np.zeros((R, 1, DCS), np.int32)),
        )
        state, _ = dense.apply_ops(state, ops, collect_dominated=False)
        return state

    def run_arm(audited):
        root = tempfile.mkdtemp(prefix="ccrdt_audit_bench_")
        try:
            a = GossipNode(FsTransport(root, "a"))
            b = GossipNode(FsTransport(root, "b"))
            a.heartbeat(), b.heartbeat()
            pub = DeltaPublisher(
                a, dense, name="topk_rmv", full_every=1, partitions=P
            )
            sampler = pt.DigestSampler(P)
            wd = DivergenceWatchdog("b", metrics=b.metrics)
            st_a, st_b, curs = dense.init(R, NK), dense.init(R, NK), {}
            t_loop, state_wd = 0.0, None
            for r in range(rounds + 2):  # 2 warmup rounds (jit + fs cache)
                t0 = time.perf_counter()
                st_a = apply_ops(st_a, r)
                pub.publish(st_a)
                st_b, _ = sweep_deltas(b, dense, st_b, curs)
                if audited:
                    got = b.fetch_digests("a")
                    if got is not None:
                        dig_seq, peer_vec = got
                        own = sampler.sample(st_b, seq=dig_seq)
                        state_wd = wd.observe_peer(
                            "a", own, peer_vec, seq=dig_seq
                        )
                if r >= 2:
                    t_loop += time.perf_counter() - t0
            if audited and state_wd != DivergenceWatchdog.STATE_OK:
                raise RuntimeError(
                    "audit bench watchdog saw divergence on a clean loop"
                )
            if not np.array_equal(
                pt.state_digests(st_b, P), pt.state_digests(st_a, P)
            ):
                raise RuntimeError("audit bench diverged — gossip broken")
            return t_loop, sampler.computes
        finally:
            shutil.rmtree(root, ignore_errors=True)

    t_off = t_on = float("inf")
    computes = 0
    for _ in range(max(1, repeats)):
        t_off = min(t_off, run_arm(False)[0])
    for _ in range(max(1, repeats)):
        t, computes = run_arm(True)
        t_on = min(t_on, t)
    overhead_pct = max(0.0, 100.0 * (t_on - t_off) / max(t_off, 1e-9))
    return {
        "partitions": P,
        "rounds": rounds,
        "repeats": repeats,
        "round_ms_plain": round(1e3 * t_off / max(1, rounds), 3),
        "round_ms_audited": round(1e3 * t_on / max(1, rounds), 3),
        "audit_overhead_pct": round(overhead_pct, 2),
        "digest_computes": computes,
    }


# The mesh microbench body, run in a hermetic forced-8-device CPU child:
# this process's backend is already initialized with its own device count
# (1 on the CI fallback, the real topology on an accelerator), and the
# (2,4) MeshPlan needs 8 visible devices. Fixed protocol geometry on the
# same virtual rig every time, so rounds compare across backends — like
# the other protocol-bound microbenches, NOT an accelerator measurement.
_MESH_BENCH_CHILD = r"""
import json, math, os, shutil, sys, tempfile, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from antidote_ccrdt_tpu.core import partition as pt
from antidote_ccrdt_tpu.mesh import MeshPlan
from antidote_ccrdt_tpu.mesh import reduce as mesh_reduce
from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps, make_dense
from antidote_ccrdt_tpu.net.transport import FsTransport, GossipNode
from antidote_ccrdt_tpu.parallel.elastic import (
    DeltaPublisher, PartialAntiEntropy, sweep_deltas,
)

ITERS = int(os.environ.get("CCRDT_MESH_BENCH_ITERS", "30"))
RESYNCS = int(os.environ.get("CCRDT_MESH_BENCH_RESYNCS", "4"))
P = 8
R, NK, I, DCS, K, M, B = 4, 1, 256, 4, 8, 2, 32
dense = make_dense(n_ids=I, n_dcs=DCS, size=K, slots_per_id=M)
part_map = pt.part_of(np.arange(I), P)
p_star = int(np.bincount(part_map, minlength=P).argmax())
pools = {
    "all": np.arange(I, dtype=np.int32),
    "hot": np.arange(I, dtype=np.int32)[part_map == p_star],
}

def apply_ops(state, step, pool):
    rng = np.random.default_rng(66_000 + step)
    ids = pools[pool][rng.integers(0, len(pools[pool]), (R, B))]
    z = np.zeros((R, B), np.int32)
    ops = TopkRmvOps(
        add_key=jnp.asarray(z),
        add_id=jnp.asarray(ids.astype(np.int32)),
        add_score=jnp.asarray(rng.integers(1, 500, (R, B)).astype(np.int32)),
        add_dc=jnp.asarray(z),
        add_ts=jnp.asarray(np.broadcast_to(
            step * B + np.arange(B) + 1, (R, B)
        ).astype(np.int32)),
        rmv_key=jnp.asarray(np.zeros((R, 1), np.int32)),
        rmv_id=jnp.asarray(np.full((R, 1), -1, np.int32)),
        rmv_vc=jnp.asarray(np.zeros((R, 1, DCS), np.int32)),
    )
    state, _ = dense.apply_ops(state, ops, collect_dominated=False)
    return state

plan = MeshPlan.build(n_dc=2, n_key=4, partitions=P)

# Arm 1: jitted ICI JOIN all-reduce latency on a placed, row-divergent
# state (the per-publish-boundary reconciliation cost).
state = dense.init(R, NK)
for step in range(3):
    state = apply_ops(state, step, "all")
placed = plan.place(state)
jax.block_until_ready(mesh_reduce.ici_reduce(dense, plan, placed))  # jit
times = []
t_all0 = time.perf_counter()
for _ in range(ITERS):
    t0 = time.perf_counter()
    jax.block_until_ready(mesh_reduce.ici_reduce(dense, plan, placed))
    times.append((time.perf_counter() - t0) * 1000.0)
elapsed = time.perf_counter() - t_all0
elems = sum(
    int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(placed)
)
stages = max(1, math.ceil(math.log2(plan.n_dc)))

# Arm 2: cross-slice anti-entropy byte bill — writer advances one hot
# partition per round and anchors per-shard; the reader repairs each gap
# through the mesh-grouped PartialAntiEntropy (shard-local psnap slices
# only), billing mesh.cross_slice_bytes.
root = tempfile.mkdtemp(prefix="ccrdt_mesh_bench_")
try:
    a = GossipNode(FsTransport(root, "a"))
    b = GossipNode(FsTransport(root, "b"))
    a.heartbeat(), b.heartbeat()
    pub = DeltaPublisher(
        a, dense, name="topk_rmv", full_every=1, partitions=P,
        mesh_plan=plan,
    )
    pae = PartialAntiEntropy(b, partitions=P, mesh_plan=plan)
    st_a, curs = placed, {}
    step = 3
    pub.publish(st_a)
    st_b, _ = sweep_deltas(b, dense, plan.place(dense.init(R, NK)), curs,
                           partial=pae)
    whole_bytes = 0
    for _ in range(RESYNCS):
        st_a = apply_ops(st_a, step, "hot")
        step += 1
        pub.publish(st_a)
        whole_bytes += len(b.transport.fetch("a"))
        st_b, _ = sweep_deltas(b, dense, st_b, curs, partial=pae)
    if not np.array_equal(
        pt.state_digests(st_b, P), pt.state_digests(st_a, P)
    ):
        raise RuntimeError("mesh bench diverged — shard repair broken")
    cross_bytes = int(b.metrics.counters.get("mesh.cross_slice_bytes", 0))
    cross_fetches = int(
        b.metrics.counters.get("mesh.cross_slice_fetches", 0)
    )
    wasted = int(b.metrics.counters.get("net.psnap_wasted", 0))
finally:
    shutil.rmtree(root, ignore_errors=True)
if wasted:
    raise RuntimeError(f"mesh bench wasted {wasted} psnap fetches")

print(json.dumps({
    "n_devices": len(jax.devices()),
    "mesh": {"n_dc": plan.n_dc, "n_key": plan.n_key},
    "iters": ITERS,
    "ici_reduce_ms_p50": round(sorted(times)[len(times) // 2], 3),
    "mesh_merges_per_sec": round(
        elems * stages * ITERS / max(elapsed, 1e-9), 1
    ),
    "resyncs": RESYNCS,
    "cross_slice_bytes": cross_bytes,
    "cross_slice_fetches": cross_fetches,
    "cross_slice_bytes_per_resync": round(cross_bytes / max(1, RESYNCS), 1),
    "whole_bytes_per_resync": round(whole_bytes / max(1, RESYNCS), 1),
}))
"""


def bench_mesh_scaling(iters=30, resyncs=4):
    """Mesh-plane microbench (mesh/): ICI JOIN all-reduce latency and
    the cross-slice anti-entropy byte bill, both on the (2,4) plan over
    8 forced host devices in a hermetic CPU subprocess (see
    `_MESH_BENCH_CHILD`). Returns the child's metric dict, or a
    ``{"skipped": reason}`` stub when the rig cannot run (the summary
    keys then ride as null — report-only; the gated carrier for
    `bench_gate.evaluate_mesh` is MULTICHIP_r*.json)."""
    import subprocess
    import sys as _sys

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = ":".join(
        p for p in env.get("PYTHONPATH", "").split(":") if "axon" not in p
    )
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["CCRDT_MESH_BENCH_ITERS"] = str(int(iters))
    env["CCRDT_MESH_BENCH_RESYNCS"] = str(int(resyncs))
    try:
        proc = subprocess.run(
            [_sys.executable, "-c", _MESH_BENCH_CHILD],
            env=env, capture_output=True, text=True, timeout=600,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        return {"skipped": f"mesh bench child failed to run: {e}"}
    if proc.returncode != 0:
        return {
            "skipped": "mesh bench child rc="
            f"{proc.returncode}: {(proc.stderr or proc.stdout)[-500:]}"
        }
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"skipped": f"mesh bench child output torn: {proc.stdout[-500:]}"}


def main():
    import jax

    try:  # persistent compile cache: harmless if the backend rejects it
        jax.config.update("jax_compilation_cache_dir", "/tmp/ccrdt_jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    except Exception:
        pass

    backend = jax.default_backend()
    if os.environ.get("CCRDT_BENCH_TINY"):
        # Smoke-test mode (tests/test_bench_smoke.py): exercise the full
        # path in seconds; the numbers are meaningless.
        R, I, B, Br, windows, W, base_ops = 2, 256, 32, 8, 2, 2, 200
        curve_points = (32, 64)
        curve_cfg = dict(windows=1, W=2, e2e_samples=2)
    elif backend == "cpu":
        # CI / no-accelerator fallback: shrink so the bench still completes.
        R, I, B, Br, windows, W, base_ops = 8, 10_000, 1024, 64, 3, 3, 5_000
        curve_points = (512, 1024)
        curve_cfg = dict(windows=1, W=2, e2e_samples=2)
    else:
        # W amortizes the fixed per-window cost (host sync readback + op
        # upload, ~75-90ms measured) to a few ms/round without hiding it.
        # B (1/16 rmv ratio preserved) amortizes the per-round full-grid
        # join — batch size is a free engine parameter (BASELINE pins
        # keys/replicas/K, not batch), and p50/p99 round latency stays
        # reported honestly. Measured scaling on v5e (round 5, unique-hint
        # scatters): B=16384 -> 12.9M merges/s @ 43ms/round; 32768 ->
        # 21.1-21.9M @ 51-53ms; 49152 -> 22.6M @ 74ms; 65536 -> 24.8M @
        # 90ms. B=32768 is the balanced default: near-peak throughput
        # without letting round latency run away.
        R, I, B, Br, windows, W, base_ops = 32, 100_000, 32768, 2048, 6, 10, 20_000
        # Frontier sweep (committed as the `curve` block). Each point costs
        # two remote compiles (~35s each cold on this tunnel), so the sweep
        # is 3 extra points and the headline B=32768 point is carried over
        # from the main measurement (marked source=headline). Non-power-of-
        # two-ish points compile BADLY on v5e (shape/padding-dependent):
        # manually probed 40960 ran slower per round than 49152 (71.1 vs
        # 72.4ms with 8k fewer ops, r4), and r5's probe of the 52.7->62ms
        # latency headroom found 36864 WORSE THAN 32768 ON BOTH AXES
        # (19.0M @ 65.8ms vs 21.1M @ 52.7) and 45056 dominated by 49152
        # (21.7M @ 70.5 vs 22.6M @ 73.8) — the kind of fact a prose curve
        # hides; the sweep sticks to the clean shapes.
        curve_points = (16384, 49152, 65536)
        curve_cfg = dict(windows=2, W=6, e2e_samples=8)
    D_DCS, K, M = R, 100, 4  # every simulated replica is a DC: vc width = R

    (
        apply_rate, extras_rate, extras_ops_rate, p50_ms, p99_ms,
        p50_e2e_serial_ms, p99_e2e_serial_ms, p50_e2e_ms, p99_e2e_ms,
        dispatch_overhead_ms, state_merge_rate, hbm, compute,
    ) = bench_dense(R, I, D_DCS, K, M, B, Br, windows, W)
    curve = bench_curve(R, I, D_DCS, K, M, curve_points, **curve_cfg)
    curve.append(
        {
            "batch_adds": B,
            "batch_rmvs": Br,
            "merges_per_sec": round(apply_rate),
            "p50_round_ms_windowed": round(p50_ms, 2),
            "p99_round_ms_windowed": round(p99_ms, 2),
            # The headline e2e is the OVERLAPPED pipeline (PR 7: readback
            # rides the HostStage; the round thread only dispatches). The
            # serial numbers stay alongside so the mode switch can never
            # read as a silent speedup — the sweep points above are all
            # serial-mode.
            "p50_round_ms_e2e": round(p50_e2e_ms, 2),
            "p99_round_ms_e2e": round(p99_e2e_ms, 2),
            "p50_round_ms_e2e_serial": round(p50_e2e_serial_ms, 2),
            "p99_round_ms_e2e_serial": round(p99_e2e_serial_ms, 2),
            # boundary=drain (PR 11): the loop drains the host stage at
            # every publish boundary, so each boundary sample carries
            # its own window's device work instead of the final sample
            # swallowing EVERY queued readback — the r08-and-earlier
            # p99 was a billing artifact of the unbounded run-ahead,
            # not a latency any round saw. Mode string changed so the
            # estimator fix can never read as a silent speedup.
            "e2e_mode": "overlapped(pub_every=4,boundary=drain)",
            "source": "headline",
        }
    )
    curve.sort(key=lambda p: p["batch_adds"])
    # Operating-point decision (explicit, as the curve artifact demands):
    # the headline stays at the largest point whose windowed p50 holds the
    # ~60ms round budget; the knee (~49152 on v5e, ~22.6M merges/sec at
    # ~74ms r5) is there for deployments whose latency budget allows it.
    chosen = {
        "batch_adds": B,
        "why": (
            "largest sweep point with windowed p50 <= ~62ms; the higher-"
            "throughput knee trades ~18ms/round of latency for ~+13% "
            "rate and is a config knob, not the default"
        ),
    }
    baseline_rate = bench_scalar_baseline(R, I, D_DCS, K, base_ops)
    antientropy = bench_partition_antientropy(
        resync_rounds=2 if os.environ.get("CCRDT_BENCH_TINY") else 4
    )
    serving = bench_serve(
        frames=5 if os.environ.get("CCRDT_BENCH_TINY") else 400
    )
    read_tier = bench_read_tier(
        frames=5 if os.environ.get("CCRDT_BENCH_TINY") else 300,
        batch=8 if os.environ.get("CCRDT_BENCH_TINY") else 64,
    )
    audit_ov = bench_audit_overhead(
        rounds=4 if os.environ.get("CCRDT_BENCH_TINY") else 12,
        repeats=1 if os.environ.get("CCRDT_BENCH_TINY") else 3,
    )
    phase_rounds = (
        3 if (backend == "cpu" or os.environ.get("CCRDT_BENCH_TINY")) else 6
    )
    round_phases = bench_round_phases(
        R, I, D_DCS, K, M, B, Br, rounds=phase_rounds,
    )
    # Kill-switch arm of the same drill: the raw ingest phase bill is
    # workload-shaped (the drill applies two op sub-batches and logs two
    # WAL steps per round since PR 15), so the carrier records the
    # CCRDT_INGEST_COMPACT=0 rerun alongside it — the within-workload
    # differential is the number that survives drill reshapes and
    # machine drift across rounds.
    _prev_compact = os.environ.get("CCRDT_INGEST_COMPACT")
    try:
        os.environ["CCRDT_INGEST_COMPACT"] = "0"
        _nocompact = bench_round_phases(
            R, I, D_DCS, K, M, B, Br, rounds=phase_rounds,
        )
    finally:
        if _prev_compact is None:
            os.environ.pop("CCRDT_INGEST_COMPACT", None)
        else:
            os.environ["CCRDT_INGEST_COMPACT"] = _prev_compact
    round_phases["ingest_phase_ms_total_nocompact"] = _nocompact[
        "ingest_phase_ms_total"
    ]
    mesh_scaling = bench_mesh_scaling(
        iters=5 if os.environ.get("CCRDT_BENCH_TINY") else 30,
        resyncs=2 if os.environ.get("CCRDT_BENCH_TINY") else 4,
    )
    working_set = (
        bench_working_set(P=16, ids=1024, batches=4, B=64)
        if os.environ.get("CCRDT_BENCH_TINY")
        else bench_working_set()
    )

    # The driver records only the TAIL of stdout (<=2,000 chars) as
    # BENCH_r{N}.json and parses the LAST line; round 4's single fat line
    # (2,258 chars with hbm/compute/curve inline) overflowed that window and
    # left the official record unparseable (VERDICT-r4 weak #1). So: the
    # bulky analysis blocks go to a committed sidecar file (and an earlier
    # stdout line for anyone reading the log), and the final line stays a
    # compact headline the driver can always parse.
    details = {
        "hbm": hbm,
        "compute": compute,
        # extras_mode disambiguates the two rates below (ADVICE-r2 item 3):
        # "table" is the id-keyed dominated table (the replication-path
        # default), "op_aligned" the legacy per-op gather mode — same key
        # names across rounds used to read a methodology switch as a
        # speedup.
        "extras_mode": "table",
        "merges_per_sec_with_extras": round(extras_rate),
        "merges_per_sec_with_extras_op_aligned": round(extras_ops_rate),
        "curve": {"points": curve, "operating_point": chosen},
        # Per-phase buckets from the spanned gossip round drill
        # (bench_round_phases): where a full round's wall time goes, and
        # the dispatch gap no phase owns. The summary line carries only
        # the two headline numbers (gap p50 + coverage).
        "round_phases": round_phases,
        # Partition-plane anti-entropy costs (bench_partition_antientropy):
        # fixed protocol geometry, so rounds compare; the summary line
        # carries the two gated headline numbers.
        "partition_antientropy": antientropy,
        # Read-serving plane microbench (bench_serve): same story — fixed
        # frame shape, two gated headline numbers on the summary line.
        "serve": serving,
        # Fleet read-tier microbench (bench_read_tier): the routing
        # layer's cost over direct serving + the in-process failover
        # blip. Report-only: the gated carrier is READTIER_r*.json from
        # scripts/read_tier_demo.py (bench_gate.evaluate_router).
        "read_tier": read_tier,
        # Audit-plane overhead (bench_audit_overhead): what running
        # certified costs per gossip round; the gated headline pct rides
        # the summary line.
        "audit": audit_ov,
        # Mesh-plane costs (bench_mesh_scaling, forced-8-device child):
        # ICI reduce latency + the cross-slice shard-repair byte bill.
        # Report-only on the summary line; the gated carrier is the
        # MULTICHIP_r*.json round (scripts/bench_gate.py evaluate_mesh).
        "mesh_scaling": mesh_scaling,
        # Out-of-core pager working-set drill (bench_working_set): state
        # 10x the device budget by construction; the three gated headline
        # numbers ride the summary line (bench_gate.evaluate_pager).
        "working_set": working_set,
        "dispatch_overhead_ms_p50": round(dispatch_overhead_ms, 2),
        "batch_per_replica_round": f"{B} adds + {Br} rmvs",
        "backend": backend,
    }
    # Only a real-accelerator run mirrors the details to the committed
    # sidecar path: the tiny smoke mode and the CPU CI fallback produce
    # meaningless numbers, and letting them overwrite the official artifact
    # would recreate the stale-record failure this code exists to prevent.
    sidecar = None
    if backend != "cpu" and not os.environ.get("CCRDT_BENCH_TINY"):
        sidecar = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "benchmarks", "bench_details.json",
        )
        try:
            with open(sidecar, "w") as f:
                json.dump(details, f, indent=1)
        except OSError:
            sidecar = None  # read-only checkout: the stdout copy suffices
    summary = {
        "metric": f"topk_rmv merges/sec ({I//1000}k ids x {R} replicas, K={K})",
        "value": round(apply_rate),
        # Duplicate of "value" under the key scripts/bench_gate.py greps
        # for: the details line can outgrow the driver's 2000-char tail
        # window, but this summary line (checked < 1900 chars below)
        # always survives it.
        "merges_per_sec": round(apply_rate),
        "unit": "merges/sec",
        "vs_baseline": round(apply_rate / baseline_rate, 2),
        "p50_round_ms_windowed": round(p50_ms, 2),
        "p99_round_ms_windowed": round(p99_ms, 2),
        "p50_round_ms_e2e": round(p50_e2e_ms, 2),
        "p99_round_ms_e2e": round(p99_e2e_ms, 2),
        "p50_round_ms_e2e_serial": round(p50_e2e_serial_ms, 2),
        "e2e_mode": "overlapped(boundary=drain)",
        "operating_point_batch_adds": B,
        "replica_state_merges_per_sec": round(state_merge_rate, 1),
        "baseline_cpu_merges_per_sec": round(baseline_rate),
        "dispatch_gap_ms_p50": round_phases["dispatch_gap_ms_p50"],
        "span_coverage_p50": round_phases["span_coverage_p50"],
        "wal_append_ms_total": round_phases["wal_append_ms_total"],
        "wal_group_size_p50": round_phases["wal_group_size_p50"],
        "wal_durability": round_phases["wal_durability"],
        "ingest_phase_ms_total": round_phases["ingest_phase_ms_total"],
        "ingest_phase_ms_total_nocompact": round_phases[
            "ingest_phase_ms_total_nocompact"
        ],
        "coalesce_ratio": round_phases["coalesce_ratio"],
        "antientropy_bytes_per_resync": antientropy[
            "antientropy_bytes_per_resync"
        ],
        "rejoin_stream_seconds": antientropy["rejoin_stream_seconds"],
        "serve_reads_per_sec": serving["serve_reads_per_sec"],
        "serve_read_p99_ms": serving["serve_read_p99_ms"],
        "audit_overhead_pct": audit_ov["audit_overhead_pct"],
        "pager_hit_rate": working_set["pager_hit_rate"],
        "resident_miss_ms_p50": working_set["resident_miss_ms_p50"],
        "cold_merges_per_sec": working_set["cold_merges_per_sec"],
        "mesh_merges_per_sec": mesh_scaling.get("mesh_merges_per_sec"),
        "ici_reduce_ms_p50": mesh_scaling.get("ici_reduce_ms_p50"),
        "cross_slice_bytes": mesh_scaling.get("cross_slice_bytes"),
        "backend": backend,
        # Host class for wall-clock gates: serve_reads_per_sec (and the
        # other host-CPU-bound throughputs) scale with the core count, so
        # bench_gate compares those carriers within one (backend, nproc)
        # group only — same reason the wal e2e gate groups by backend.
        "nproc": os.cpu_count(),
        "details_file": "benchmarks/bench_details.json" if sidecar else "stdout",
    }
    line = json.dumps(summary)
    # Explicit check (not assert: python -O would strip it), and BEFORE the
    # details print — if the summary somehow outgrows the driver's window
    # the failure must not leave the fat details line as the last stdout
    # line, which is exactly the unparseable-record mode being prevented.
    if len(line) >= 1900:
        raise RuntimeError(f"final bench line too long ({len(line)} chars)")
    print(json.dumps({"details": details}))
    print(line)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "bench_ingest":
        bench_ingest()
    else:
        main()
