"""Orbax-backed dense checkpointing: sharded save/restore, retention,
re-layout onto a different mesh, and WAL pairing (restore + replay suffix).

Runs on the 8-virtual-device CPU mesh from conftest.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from antidote_ccrdt_tpu.harness import orbax_ckpt
from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps, make_dense

pytestmark = pytest.mark.skipif(
    not orbax_ckpt.available(), reason="orbax-checkpoint not installed"
)


def _make_state_and_ops(R=4, NK=2, I=64, DCS=4, seed=0):
    D = make_dense(n_ids=I, n_dcs=DCS, size=8, slots_per_id=2)
    state = D.init(n_replicas=R, n_keys=NK)
    rng = np.random.default_rng(seed)
    B, Br = 32, 8
    ops = TopkRmvOps(
        add_key=jnp.asarray(rng.integers(0, NK, (R, B)).astype(np.int32)),
        add_id=jnp.asarray(rng.integers(0, I, (R, B)).astype(np.int32)),
        add_score=jnp.asarray(rng.integers(1, 1000, (R, B)).astype(np.int32)),
        add_dc=jnp.asarray(rng.integers(0, DCS, (R, B)).astype(np.int32)),
        add_ts=jnp.asarray(rng.integers(1, 100, (R, B)).astype(np.int32)),
        rmv_key=jnp.asarray(rng.integers(0, NK, (R, Br)).astype(np.int32)),
        rmv_id=jnp.asarray(rng.integers(0, I, (R, Br)).astype(np.int32)),
        rmv_vc=jnp.asarray(rng.integers(0, 50, (R, Br, DCS)).astype(np.int32)),
    )
    state, _ = D.apply_ops(state, ops)
    return D, state


def _tree_equal(a, b) -> bool:
    return all(
        bool(jnp.all(x == y))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


def test_roundtrip_replicated(tmp_path):
    _, state = _make_state_and_ops()
    with orbax_ckpt.DenseCheckpointManager(str(tmp_path / "ckpt")) as m:
        m.save(0, state)
        like = jax.tree.map(jnp.zeros_like, state)
        restored = m.restore(like)
    assert _tree_equal(state, restored)


def test_roundtrip_sharded_and_relayout(tmp_path):
    _, state = _make_state_and_ops()
    devs = jax.devices()
    mesh = Mesh(np.asarray(devs).reshape(4, 2), ("dc", "extra"))
    shard = NamedSharding(mesh, P("dc"))  # replica axis over 'dc'
    sharded = jax.tree.map(lambda x: jax.device_put(x, shard), state)

    with orbax_ckpt.DenseCheckpointManager(str(tmp_path / "ckpt")) as m:
        m.save(3, sharded)
        # Restore onto a DIFFERENT mesh shape (2 devices on the replica
        # axis): elastic recovery after resizing the fleet.
        mesh2 = Mesh(np.asarray(devs[:2]).reshape(2), ("dc",))
        shard2 = NamedSharding(mesh2, P("dc"))
        like = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=shard2),
            state,
        )
        restored = m.restore(like, step=3)

    assert _tree_equal(state, restored)
    for leaf in jax.tree.leaves(restored):
        assert leaf.sharding.mesh.shape == {"dc": 2}


def test_retention_and_latest(tmp_path):
    _, state = _make_state_and_ops()
    with orbax_ckpt.DenseCheckpointManager(str(tmp_path / "ckpt"), max_to_keep=2) as m:
        for step in (1, 2, 3):
            m.save(step, state)
        assert m.latest_step() == 3
        assert m.all_steps() == [2, 3]  # step 1 aged out


def test_restore_empty_dir_raises(tmp_path):
    _, state = _make_state_and_ops()
    with orbax_ckpt.DenseCheckpointManager(str(tmp_path / "ckpt")) as m:
        with pytest.raises(FileNotFoundError):
            m.restore(jax.tree.map(jnp.zeros_like, state))


def test_pairs_with_wal_replay(tmp_path):
    """Orbax snapshot + journal suffix = the checkpoint.resume recipe, at
    the dense tier: ops after the snapshot re-apply deterministically."""
    D, state = _make_state_and_ops()
    rng = np.random.default_rng(9)
    R, B = 4, 16
    late_ops = TopkRmvOps(
        add_key=jnp.asarray(rng.integers(0, 2, (R, B)).astype(np.int32)),
        add_id=jnp.asarray(rng.integers(0, 64, (R, B)).astype(np.int32)),
        add_score=jnp.asarray(rng.integers(1, 1000, (R, B)).astype(np.int32)),
        add_dc=jnp.asarray(rng.integers(0, 4, (R, B)).astype(np.int32)),
        add_ts=jnp.asarray(rng.integers(100, 200, (R, B)).astype(np.int32)),
        rmv_key=jnp.zeros((R, 1), jnp.int32),
        rmv_id=jnp.zeros((R, 1), jnp.int32),
        rmv_vc=jnp.zeros((R, 1, 4), jnp.int32),
    )
    final, _ = D.apply_ops(state, late_ops)

    with orbax_ckpt.DenseCheckpointManager(str(tmp_path / "ckpt")) as m:
        m.save(0, state)
        restored = m.restore(jax.tree.map(jnp.zeros_like, state))
    replayed, _ = D.apply_ops(restored, late_ops)
    assert _tree_equal(final, replayed)
