"""Replication-lag tracker (obs/lag.py) on an injected fake clock:
watermark/cursor arithmetic, first-sighting lag-seconds, watermark gaps
(anchors skip seqs), peer death mid-window, gauge export, and the
fleet digest-agreement probe."""

import struct
import zlib

from antidote_ccrdt_tpu.obs.lag import (
    LagTracker,
    digest_agreement,
    payload_digest,
)
from antidote_ccrdt_tpu.utils.metrics import Metrics


class Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_lag_ops_and_seconds_basic():
    clk = Clock()
    lt = LagTracker("me", clock=clk)
    # Peer b has shipped seqs 0..2; we have applied none.
    lt.observe_published("b", 2)
    assert lt.lag("b") == (3, 0.0)
    clk.t = 4.0
    ops, secs = lt.lag("b")
    assert ops == 3
    assert secs == 4.0  # age of the oldest unapplied seq, from first sighting
    # Applying 0..1 leaves one op; the oldest pending is now seq 2,
    # first seen at t=0 — lag-seconds still measures from that sighting.
    lt.observe_applied("b", 1)
    ops, secs = lt.lag("b")
    assert ops == 1 and secs == 4.0
    lt.observe_applied("b", 2)
    assert lt.lag("b") == (0, 0.0)


def test_watermark_gaps_are_stamped_at_first_sighting():
    """Anchors make the published seq jump (0 -> 4 with nothing between
    on the transport): every seq in the gap is stamped when the jump is
    seen, not retroactively."""
    clk = Clock()
    lt = LagTracker("me", clock=clk)
    lt.observe_published("b", 0)
    clk.t = 10.0
    lt.observe_published("b", 4)  # gap: 1..4 first seen at t=10
    ops, secs = lt.lag("b")
    assert ops == 5
    assert secs == 10.0  # oldest pending is seq 0 from t=0
    lt.observe_applied("b", 0)
    ops, secs = lt.lag("b")
    assert ops == 4
    assert secs == 0.0  # the survivors (1..4) were first seen just now
    clk.t = 13.0
    assert lt.lag("b") == (4, 3.0)


def test_applied_beyond_published_advances_watermark():
    """A full-snapshot adoption can apply past the last published seq we
    saw (the snapshot embeds newer state): applied must drag published
    forward, never report negative lag."""
    clk = Clock()
    lt = LagTracker("me", clock=clk)
    lt.observe_published("b", 1)
    lt.observe_applied("b", 7)
    assert lt.lag("b") == (0, 0.0)
    assert lt.report()["b"]["published"] == 7
    # Stale re-observations of older watermarks are no-ops.
    lt.observe_published("b", 3)
    assert lt.lag("b") == (0, 0.0)


def test_peer_death_mid_window_drop_freezes_and_forgets():
    clk = Clock()
    lt = LagTracker("me", clock=clk)
    lt.observe_published("b", 5)
    lt.observe_published("c", 1)
    clk.t = 2.0
    assert lt.lag("b") == (6, 2.0)
    # SWIM confirms b DEAD mid-window: its frozen watermark must stop
    # inflating fleet lag.
    lt.drop("b")
    assert lt.lag("b") == (0, 0.0)
    assert set(lt.report()) == {"c"}
    # A re-observed (restarted) b starts a fresh window.
    clk.t = 3.0
    lt.observe_published("b", 0)
    assert lt.lag("b") == (1, 0.0)


def test_self_is_never_tracked():
    lt = LagTracker("me", clock=Clock())
    lt.observe_published("me", 9)
    lt.observe_applied("me", 9)
    assert lt.report() == {}


def test_export_to_metrics_gauges():
    clk = Clock()
    lt = LagTracker("me", clock=clk)
    lt.observe_published("b", 3)
    lt.observe_published("c", 0)
    lt.observe_applied("c", 0)
    clk.t = 1.5
    m = Metrics()
    lt.export_to(m)
    assert m.counters["lag.b.ops"] == 4.0
    assert m.counters["lag.b.seconds"] == 1.5
    assert m.counters["lag.c.ops"] == 0.0
    assert m.counters["lag.max_ops"] == 4.0
    assert m.counters["lag.max_seconds"] == 1.5


def test_staleness_catches_caught_up_but_wedged_peer():
    """Lag reads zero for a peer that merged everything then went
    silent; staleness is the signal that keeps growing."""
    clk, mono = Clock(), Clock()
    lt = LagTracker("me", clock=clk, mono=mono)
    assert lt.staleness("b") == 0.0  # never observed
    lt.observe_published("b", 2)
    lt.observe_applied("b", 2)
    assert lt.lag("b") == (0, 0.0)
    mono.t = 7.5  # b goes quiet; wall clock irrelevant
    assert lt.staleness("b") == 7.5
    assert lt.report()["b"]["staleness_s"] == 7.5
    # Any fresh progress evidence resets the baseline — a watermark
    # advance here, an apply equally would.
    lt.observe_published("b", 3)
    assert lt.staleness("b") == 0.0
    mono.t = 9.0
    lt.observe_applied("b", 3)
    assert lt.staleness("b") == 0.0
    # Re-observing an OLD watermark is not progress: no reset.
    mono.t = 11.0
    lt.observe_published("b", 1)
    assert lt.staleness("b") == 2.0
    lt.drop("b")
    assert lt.staleness("b") == 0.0


def test_export_includes_staleness_gauges():
    clk, mono = Clock(), Clock()
    lt = LagTracker("me", clock=clk, mono=mono)
    lt.observe_published("b", 0)
    lt.observe_applied("b", 0)
    lt.observe_published("c", 0)
    mono.t = 4.0
    lt.observe_applied("c", 0)  # c just progressed; b is 4s stale
    m = Metrics()
    lt.export_to(m)
    assert m.counters["lag.b.staleness_seconds"] == 4.0
    assert m.counters["lag.c.staleness_seconds"] == 0.0
    assert m.counters["lag.max_staleness_seconds"] == 4.0


def test_payload_digest_skips_header():
    blob = struct.pack("<Q", 42) + b"payload"
    assert payload_digest(blob) == zlib.crc32(b"payload") & 0xFFFFFFFF
    # Same payload under a different step header -> same digest.
    assert payload_digest(struct.pack("<Q", 7) + b"payload") == payload_digest(blob)


def test_digest_agreement_partitions():
    agree = digest_agreement({"a": 1, "b": 1, "c": 1})
    assert agree["agree"] and agree["n_digests"] == 1
    assert agree["groups"] == {"00000001": ["a", "b", "c"]}

    split = digest_agreement({"a": 1, "b": 2, "c": 1})
    assert not split["agree"]
    assert split["groups"]["00000001"] == ["a", "c"]
    assert split["groups"]["00000002"] == ["b"]

    # An unreadable member breaks agreement and is reported by name.
    holey = digest_agreement({"a": 1, "b": 1, "c": None})
    assert not holey["agree"]
    assert holey["unreadable"] == ["c"]
    assert holey["n_members"] == 3 and holey["n_digests"] == 1
