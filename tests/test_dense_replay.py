"""DenseReplay: the batched multi-DC pipeline over the dense engines.

Checks the two reconciliation protocols (JOIN broadcast-fold, MONOID
delta exchange), convergence after sync, and the delivery fault model:
duplicated contributions are harmless exactly for JOIN types — the dense
counterpart of test_harness.py's op-level fault tests.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from antidote_ccrdt_tpu.harness.dense_replay import DenseReplay
from antidote_ccrdt_tpu.harness.opgen import TopkRmvEffectGen, Workload
from antidote_ccrdt_tpu.models import average as av
from antidote_ccrdt_tpu.models import leaderboard as lb
from antidote_ccrdt_tpu.models import topk_rmv_dense as tkr


def _avg_ops(R, NK, rng, B=8):
    key = rng.integers(0, NK, (R, B)).astype(np.int32)
    val = rng.integers(-50, 100, (R, B)).astype(np.int32)
    cnt = np.ones((R, B), np.int32)
    return av.AverageOps(
        key=jnp.asarray(key), value=jnp.asarray(val), count=jnp.asarray(cnt)
    ), key, val


def test_average_delta_exchange_matches_global_mean():
    R, NK, rounds = 4, 6, 3
    rng = np.random.default_rng(0)
    replay = DenseReplay(av.AverageDense(), n_replicas=R, n_keys=NK)
    all_sum, all_cnt = np.zeros(NK), np.zeros(NK)
    for _ in range(rounds):
        ops, key, val = _avg_ops(R, NK, rng)
        np.add.at(all_sum, key.ravel(), val.ravel())
        np.add.at(all_cnt, key.ravel(), 1)
        replay.apply(ops)
        replay.sync()
    assert replay.converged()
    obs = np.asarray(replay.observe())  # [R, NK]
    expected = np.where(all_cnt == 0, 0.0, all_sum / np.maximum(all_cnt, 1))
    np.testing.assert_allclose(obs[0], expected, rtol=1e-6)


def test_monoid_duplicate_contribution_double_counts():
    """Exactly-once is load-bearing for MONOID types: a duplicated delta
    shifts the converged sum (the dense dual of
    test_harness.test_duplication_breaks_monoid_types)."""
    R, NK = 3, 4
    rng = np.random.default_rng(1)
    honest = DenseReplay(av.AverageDense(), n_replicas=R, n_keys=NK)
    faulty = DenseReplay(av.AverageDense(), n_replicas=R, n_keys=NK)
    ops, _, _ = _avg_ops(R, NK, rng)
    honest.apply(ops)
    faulty.apply(ops)
    honest.sync()
    faulty.sync(contributors=[0, 0, 1, 2])  # replica 0 delivered twice
    # Both still *converge* (every replica agrees) ...
    assert honest.converged() and faulty.converged()
    # ... but the faulty exchange double-counted replica 0's delta.
    assert not np.allclose(
        np.asarray(honest.observe()), np.asarray(faulty.observe())
    )


def test_total_loss_sync():
    """sync(contributors=[]) models total delivery loss: JOIN replicas keep
    local state; MONOID replicas lose their in-flight deltas, base intact."""
    R, NK = 3, 4
    rng = np.random.default_rng(2)
    rp = DenseReplay(av.AverageDense(), n_replicas=R, n_keys=NK)
    ops, _, _ = _avg_ops(R, NK, rng)
    rp.apply(ops)
    rp.sync()  # converge once
    base_obs = np.asarray(rp.observe()).copy()
    ops2, _, _ = _avg_ops(R, NK, rng)
    rp.apply(ops2)
    rp.sync(contributors=[])  # round 2 deltas all lost in flight
    np.testing.assert_array_equal(np.asarray(rp.observe()), base_obs)
    assert rp.converged()

    D = tkr.make_dense(n_ids=32, n_dcs=R, size=4, slots_per_id=2)
    jp = DenseReplay(D, n_replicas=R)
    gen = TopkRmvEffectGen(Workload(n_replicas=R, n_ids=32, seed=4))
    jp.apply(gen.next_batch(8, 1))
    jp.sync(contributors=[])  # JOIN: nothing learned, local state kept
    assert not jp.converged()  # rows still differ (their own local adds)
    jp.sync()
    assert jp.converged()


def test_join_duplicate_contribution_harmless():
    """The lattice join absorbs duplicated delivery (idempotence) — the
    guarantee the op-based pipeline has to *assume* from its host."""
    R = 4
    wl = Workload(n_replicas=R, n_ids=64, seed=3)
    D = tkr.make_dense(n_ids=64, n_dcs=R, size=4, slots_per_id=4)
    honest = DenseReplay(D, n_replicas=R)
    faulty = DenseReplay(D, n_replicas=R)
    gen = TopkRmvEffectGen(wl)
    for _ in range(2):
        batch = gen.next_batch(16, 2)
        honest.apply(batch)
        faulty.apply(batch)
    honest.sync()
    faulty.sync(contributors=[0, 1, 1, 2, 3, 3, 3])
    assert honest.converged() and faulty.converged()
    h, f = honest.observe(), faulty.observe()
    for a, b in zip(h, f):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_topk_rmv_rounds_converge():
    R, rounds = 8, 3
    wl = Workload(n_replicas=R, n_ids=256, seed=5)
    D = tkr.make_dense(n_ids=256, n_dcs=R, size=8, slots_per_id=4)
    replay = DenseReplay(D, n_replicas=R)
    gen = TopkRmvEffectGen(wl)
    for _ in range(rounds):
        replay.apply(gen.next_batch(32, 4))
        assert not replay.converged() or rounds == 0  # pre-sync rows differ
        replay.sync()
        assert replay.converged()
    obs = replay.observe()
    assert bool(np.asarray(obs.valid)[:, :, 0].all())


def test_leaderboard_ban_wins_through_sync():
    R, P, K = 3, 16, 3
    D = lb.make_dense(n_players=P, size=K)
    replay = DenseReplay(D, n_replicas=R)

    def ops(add_rows, ban_rows):
        B = max(len(a) for a in add_rows)
        Bb = max(max(len(b) for b in ban_rows), 1)
        add = np.zeros((R, B, 3), np.int32)
        add_valid = np.zeros((R, B), bool)
        ban = np.zeros((R, Bb, 2), np.int32)
        ban_valid = np.zeros((R, Bb), bool)
        for r, rows in enumerate(add_rows):
            for j, (pid, score) in enumerate(rows):
                add[r, j] = (0, pid, score)
                add_valid[r, j] = True
        for r, rows in enumerate(ban_rows):
            for j, pid in enumerate(rows):
                ban[r, j] = (0, pid)
                ban_valid[r, j] = True
        return lb.LeaderboardOps(
            add_key=jnp.asarray(add[:, :, 0]),
            add_id=jnp.asarray(add[:, :, 1]),
            add_score=jnp.asarray(add[:, :, 2]),
            add_valid=jnp.asarray(add_valid),
            ban_key=jnp.asarray(ban[:, :, 0]),
            ban_id=jnp.asarray(ban[:, :, 1]),
            ban_valid=jnp.asarray(ban_valid),
        )

    # Round 1: replica 0 adds players 1..4; replica 2 bans player 3.
    replay.apply(
        ops(
            [[(1, 10), (2, 20), (3, 30), (4, 40)], [], []],
            [[], [], [3]],
        )
    )
    replay.sync()
    assert replay.converged()
    ids, scores, valid = replay.observe()
    ids0 = np.asarray(ids)[0, 0][np.asarray(valid)[0, 0]].tolist()
    assert 3 not in ids0  # ban wins regardless of delivery order
    assert set(ids0) == {4, 2, 1}
    # Round 2: re-add of the banned player at any score never resurfaces.
    replay.apply(ops([[], [(3, 99)], []], [[], [], []]))
    replay.sync()
    ids, scores, valid = replay.observe()
    ids0 = np.asarray(ids)[0, 0][np.asarray(valid)[0, 0]].tolist()
    assert 3 not in ids0
