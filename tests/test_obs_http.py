"""OpenMetrics HTTP endpoint (obs/http.py): live scrapes of a running
worker — content, health, error degradation, and env-var gating."""

import json
import urllib.error
import urllib.request

import pytest

from antidote_ccrdt_tpu.obs import http as obs_http
from antidote_ccrdt_tpu.utils.metrics import Metrics


def _get(addr, path, timeout=5.0):
    return urllib.request.urlopen(
        f"http://{addr[0]}:{addr[1]}{path}", timeout=timeout
    )


def _sample_metrics():
    m = Metrics()
    m.count("net.frames_sent", 3)
    m.merge({"counters": {}, "latencies": {"sync": [0.01, 0.02]}})
    return m


def test_metrics_endpoint_serves_live_registry():
    m = _sample_metrics()
    with obs_http.MetricsHttpServer(m, "w0") as srv:
        with _get(srv.address, "/metrics") as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            text = resp.read().decode()
        assert 'ccrdt_net_frames_sent{member="w0"} 3' in text
        assert 'ccrdt_sync_seconds_bucket{member="w0",le="+Inf"} 2' in text
        # Live: a second scrape reflects registry changes in between.
        m.count("net.frames_sent", 4)
        with _get(srv.address, "/metrics") as resp:
            assert 'ccrdt_net_frames_sent{member="w0"} 7' in resp.read().decode()


def test_healthz_and_unknown_path():
    with obs_http.MetricsHttpServer(Metrics(), "w1") as srv:
        with _get(srv.address, "/healthz") as resp:
            doc = json.loads(resp.read())
        assert doc["ok"] is True and doc["member"] == "w1"
        assert doc["uptime_s"] >= 0
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.address, "/nope")
        assert ei.value.code == 404


def test_broken_source_degrades_to_500_then_recovers():
    state = {"broken": True}

    def source():
        if state["broken"]:
            raise RuntimeError("registry exploded")
        return _sample_metrics()

    with obs_http.MetricsHttpServer(source, "w2") as srv:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.address, "/metrics")
        assert ei.value.code == 500
        assert b"scrape failed" in ei.value.read()
        # The endpoint survives its own error: once the source heals,
        # the very next scrape succeeds.
        state["broken"] = False
        with _get(srv.address, "/metrics") as resp:
            assert resp.status == 200
            assert "ccrdt_net_frames_sent" in resp.read().decode()


def test_healthz_readiness_fields_from_health_extra():
    def extra():
        return {
            "max_peer_staleness_s": 0.25,
            "applied_watermark": 7,
            "overlap_queue_depth": 2,
            "serve_seq": 7,
        }

    with obs_http.MetricsHttpServer(Metrics(), "w3", health_extra=extra) as srv:
        with _get(srv.address, "/healthz") as resp:
            doc = json.loads(resp.read())
        assert doc["ok"] is True
        assert doc["max_peer_staleness_s"] == 0.25
        assert doc["applied_watermark"] == 7
        assert doc["overlap_queue_depth"] == 2
        assert doc["serve_seq"] == 7


def test_healthz_survives_broken_health_extra():
    def extra():
        raise RuntimeError("readiness probe exploded")

    with obs_http.MetricsHttpServer(Metrics(), "w4", health_extra=extra) as srv:
        with _get(srv.address, "/healthz") as resp:
            doc = json.loads(resp.read())
        # Liveness stays 200: the broken readiness probe is reported,
        # not fatal.
        assert doc["ok"] is True
        assert "readiness probe exploded" in doc["health_extra_error"]


def _post(addr, path, data, timeout=5.0):
    return urllib.request.urlopen(
        urllib.request.Request(
            f"http://{addr[0]}:{addr[1]}{path}", data=data, method="POST"
        ),
        timeout=timeout,
    )


def test_post_query_routes_to_handler():
    def handler(raw):
        return b'{"echo":' + raw + b"}"

    with obs_http.MetricsHttpServer(
        Metrics(), "w5", query_handler=handler
    ) as srv:
        with _post(srv.address, "/query", b'"hi"') as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"] == "application/json"
            assert resp.read() == b'{"echo":"hi"}'
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(srv.address, "/nope", b"x")
        assert ei.value.code == 404


def test_post_query_without_handler_404_and_broken_handler_500():
    with obs_http.MetricsHttpServer(Metrics(), "w6") as srv:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(srv.address, "/query", b"{}")
        assert ei.value.code == 404

    def handler(raw):
        raise RuntimeError("plane exploded")

    with obs_http.MetricsHttpServer(
        Metrics(), "w7", query_handler=handler
    ) as srv:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(srv.address, "/query", b"{}")
        assert ei.value.code == 500
        assert b"plane exploded" in ei.value.read()


def test_install_from_env_gating(tmp_path):
    m = Metrics()
    assert obs_http.install_from_env(m, "w0", env={}) is None
    assert obs_http.install_from_env(
        m, "w0", env={obs_http.ENV_PORT: "nope"}) is None
    srv = obs_http.install_from_env(
        m, "w0", env={obs_http.ENV_PORT: "0"}, addr_dir=str(tmp_path))
    try:
        assert srv is not None and srv.address[1] > 0
        addrs = obs_http.read_addr_files(str(tmp_path))
        assert addrs == {"w0": srv.address}
        with _get(srv.address, "/healthz") as resp:
            assert resp.status == 200
    finally:
        if srv is not None:
            srv.close()
