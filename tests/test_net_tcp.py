"""TCP gossip transport (net/tcp.py): fast in-process socket tests
(frame exchange, membership from traffic, backpressure policy, retry/
backoff bounds) plus the real-process drill — three localhost workers
over scripts/net_gossip_demo.py, one killed mid-run — marked slow."""

import json
import os
import socket
import struct
import subprocess
import sys
import time

import pytest

from antidote_ccrdt_tpu.net.tcp import TcpTransport, _PeerLink
from antidote_ccrdt_tpu.utils.metrics import Metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEMO = os.path.join(REPO, "scripts", "net_gossip_demo.py")


def wait_for(pred, timeout=8.0, interval=0.01):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def _closed_port() -> int:
    """A port that currently refuses connections (bound, then released)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture
def pair():
    a = TcpTransport("a")
    b = TcpTransport("b")
    a.add_peer("b", b.address)
    b.add_peer("a", a.address)
    yield a, b
    a.close()
    b.close()


def test_snapshot_and_delta_exchange(pair):
    a, b = pair
    blob = struct.pack("<Q", 4) + b"payload-a"
    a.publish(blob)
    assert wait_for(lambda: b.fetch("a") == blob), "snapshot never arrived"
    assert b.fetch_head("a", 8) == blob[:8]
    assert "a" in b.snapshot_members()

    for s in range(5):
        a.publish_delta(s, b"d%d" % s, keep=3)
    assert wait_for(lambda: b.delta_seqs("a") == [2, 3, 4]), b.delta_seqs("a")
    assert b.fetch_delta("a", 3) == b"d3"
    assert b.delta_members() == ["a"]


def test_membership_from_traffic_not_address_book(pair):
    a, b = pair
    # The address book alone is NOT membership evidence.
    assert "b" not in a.members()
    b.heartbeat()
    assert wait_for(lambda: "b" in a.members()), "ping never heard"
    assert "b" in a.alive_members(5.0)
    assert a.peers() == ["b"]


def test_stale_snapshot_does_not_replace(pair):
    from antidote_ccrdt_tpu.net.tcp import A_SNAP

    a, b = pair
    newer = struct.pack("<Q", 9) + b"new"
    a.publish(newer)
    assert wait_for(lambda: b.fetch("a") == newer)
    # A reconnect interleaving delivers an older anchor late: the step
    # header guard must keep the newer one.
    b._handle((A_SNAP, b"a", struct.pack("<Q", 2) + b"old", {}))
    assert b.fetch("a") == newer
    # But an equal-or-newer header does replace (latest-wins).
    newest = struct.pack("<Q", 9) + b"newest"
    b._handle((A_SNAP, b"a", newest, {}))
    assert b.fetch("a") == newest


def test_queue_backpressure_drop_oldest_delta_keep_anchor():
    m = Metrics()
    import random

    link = _PeerLink(
        "peer", ("127.0.0.1", _closed_port()), random.Random(0), m,
        queue_max=4, connect_timeout=0.1, send_timeout=0.1,
        backoff_base=10.0, backoff_max=10.0,  # effectively: never retry
    )
    try:
        mk = lambda payload: (lambda: payload)  # noqa: E731
        link.enqueue("snap", mk(b"anchor"))
        for i in range(6):
            link.enqueue("delta", mk(b"d%d" % i))
        with link._cv:
            kinds = [k for k, _, _meta in link._q]
            builds = [f() for _, f, _meta in link._q]
        # The anchor survived; the OLDEST deltas were shed.
        assert "snap" in kinds
        assert b"d5" in builds and b"d0" not in builds
        assert len(kinds) <= 4
        assert m.counters["net.send_drops"] >= 2
    finally:
        link.close()


def test_queue_snap_latest_wins_and_ping_dedup():
    m = Metrics()
    import random

    link = _PeerLink(
        "peer", ("127.0.0.1", _closed_port()), random.Random(0), m,
        queue_max=8, connect_timeout=0.1, send_timeout=0.1,
        backoff_base=10.0, backoff_max=10.0,
    )
    try:
        mk = lambda payload: (lambda: payload)  # noqa: E731
        link.enqueue("snap", mk(b"old-anchor"))
        link.enqueue("snap", mk(b"new-anchor"))
        link.enqueue("ping", mk(b"p1"))
        link.enqueue("ping", mk(b"p2"))
        with link._cv:
            snaps = [f() for k, f, _m in link._q if k == "snap"]
            pings = [f() for k, f, _m in link._q if k == "ping"]
        assert snaps == [b"new-anchor"]  # queued older anchor replaced
        assert len(pings) == 1  # one pending ping is enough liveness
    finally:
        link.close()


def test_retry_backoff_bounded_and_never_hangs():
    """A dead peer costs retries with growing-but-capped backoff — and
    enqueue never blocks the caller."""
    m = Metrics()
    import random

    link = _PeerLink(
        "peer", ("127.0.0.1", _closed_port()), random.Random(0), m,
        queue_max=8, connect_timeout=0.2, send_timeout=0.2,
        backoff_base=0.01, backoff_max=0.05,
    )
    try:
        t0 = time.time()
        link.enqueue("snap", lambda: b"blob")
        assert time.time() - t0 < 0.1, "enqueue must not block"
        assert wait_for(lambda: m.counters.get("net.retries", 0) >= 3)
        # Backoff grows exponentially but stays <= backoff_max * 1.5 jitter.
        assert link._attempts >= 3
        assert link._backoff() <= 0.05 * 1.5 + 1e-9
    finally:
        link.close()


def test_reconnect_after_peer_restart():
    """Frames queued while the peer is down are delivered once it comes
    back — retry keeps the frame, backoff keeps the cost bounded."""
    a = TcpTransport("a", backoff_base=0.02, backoff_max=0.1)
    port = _closed_port()
    try:
        a.add_peer("b", ("127.0.0.1", port))
        blob = struct.pack("<Q", 1) + b"queued-while-down"
        a.publish(blob)
        assert wait_for(lambda: a.metrics.counters.get("net.retries", 0) >= 1)
        b = TcpTransport("b", bind=("127.0.0.1", port))
        try:
            assert wait_for(lambda: b.fetch("a") == blob), "frame lost on restart"
        finally:
            b.close()
    finally:
        a.close()


# --- the real-process drill (slow) ----------------------------------------


def _drill_reference(type_name):
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import elastic_demo

    return elastic_demo.reference_digest(type_name)


@pytest.mark.slow
def test_real_process_tcp_crash_recovery(tmp_path):
    """Three localhost TCP peers; w1 is killed mid-run. Survivors must
    detect the death via SWIM timeouts (no heartbeat files — liveness is
    piggybacked ages only), adopt its replicas, converge to the
    sequential reference, and exit — with the retry/backoff machinery
    observable in the reported metrics."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    spec = (("w0", []), ("w1", ["--die-at", "4"]), ("w2", []))
    procs = {}
    for member, extra in spec:
        procs[member] = subprocess.Popen(
            [sys.executable, DEMO, "--root", str(tmp_path), "--member", member,
             "--n-members", "3", "--type", "topk_rmv", *extra],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, text=True,
        )
    rcs, outs = {}, {}
    for member, p in procs.items():
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            pytest.fail(f"worker {member} hung (degrade-never-hang violated):\n{out}")
        rcs[member], outs[member] = p.returncode, out

    assert rcs["w1"] == 1, f"victim should crash:\n{outs['w1']}"
    ref = [list(t) for t in _drill_reference("topk_rmv")]
    assert ref, "reference observable is empty — drill is vacuous"
    for m in ("w0", "w2"):
        assert rcs[m] == 0, f"worker {m} failed:\n{outs[m]}"
        with open(os.path.join(str(tmp_path), f"final-{m}.json")) as f:
            got = json.load(f)
        assert got["digest"] == ref, (
            f"{m} diverged from the sequential reference\n"
            f"got: {got['digest']}\nref: {ref}\nlog:\n{outs[m]}"
        )
        assert "w1" not in got["alive"], "crashed member still considered alive"
        # The dead peer's link kept retrying with backoff (bounded, counted).
        assert got["metrics"].get("net.retries", 0) > 0, got["metrics"]
        assert got["metrics"].get("net.frames_recv", 0) > 0, got["metrics"]
