"""MONOID → JOIN lift (parallel/monoid.py): the gossip plane for average
and wordcount. Pins the lattice laws of the versioned-row join, the
contributor write/read discipline, exact-count survival of duplicated
and stale publishes through the real GossipStore, the self-contained
row-replace deltas, and the entry-point guards (raw monoid states must
be rejected — versions are protocol information, not decoration).

Host delivery parity target: the reference replicates all six types
through one path (antidote_ccrdt.erl:47-59); this plane is what lets the
elastic/gossip tier honor that for the MONOID half.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from antidote_ccrdt_tpu.models.average import AverageDense, AverageOps
from antidote_ccrdt_tpu.models.wordcount import WordcountDense, WordcountOps
from antidote_ccrdt_tpu.parallel import delta as delta_mod
from antidote_ccrdt_tpu.parallel.elastic import DeltaPublisher, GossipStore, sweep
from antidote_ccrdt_tpu.parallel.monoid import (
    LiftedMonoidState,
    MonoidContributor,
    MonoidLift,
    apply_monoid_row_delta,
    like_monoid_delta,
    monoid_delta_in_bounds,
    monoid_row_delta,
)

R, NK, B = 4, 2, 8


def avg_ops(rows, step):
    """Deterministic per-(row, step) op batch; non-listed rows padded."""
    rows = set(rows)
    key = np.zeros((R, B), np.int32)
    val = np.zeros((R, B), np.int32)
    cnt = np.zeros((R, B), np.int32)
    for r in rows:
        rng = np.random.default_rng(1000 * (step + 1) + r)
        key[r] = rng.integers(0, NK, B)
        val[r] = rng.integers(1, 50, B)
        cnt[r] = 1
    return AverageOps(jnp.asarray(key), jnp.asarray(val), jnp.asarray(cnt))


def lift_avg():
    return MonoidLift(AverageDense())


def exact_totals(lift, steps_per_row):
    """Sequential ground truth: row r receives steps 0..steps_per_row[r]-1."""
    st = lift.init(R, NK)
    for r, n in enumerate(steps_per_row):
        for s in range(n):
            st, _ = lift.apply_ops(st, avg_ops([r], s), owned=[r])
    tot = lift.total(st)
    return np.asarray(tot.sum), np.asarray(tot.num)


def test_lift_rejects_join_engines():
    from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense

    with pytest.raises(ValueError, match="MONOID"):
        MonoidLift(make_dense(n_ids=8, n_dcs=2, size=4, slots_per_id=2))


def test_versioned_join_is_a_lattice():
    """Idempotent / commutative / associative on states with divergent
    per-row versions — the properties snapshot gossip actually needs."""
    lift = lift_avg()
    a = lift.init(R, NK)
    b = lift.init(R, NK)
    c = lift.init(R, NK)
    for s in range(3):
        a, _ = lift.apply_ops(a, avg_ops([0, 1], s), owned=[0, 1])
    for s in range(5):
        b, _ = lift.apply_ops(b, avg_ops([2], s), owned=[2])
    for s in range(2):
        c, _ = lift.apply_ops(c, avg_ops([3], s), owned=[3])

    def eq(x, y):
        return (
            np.array_equal(np.asarray(x.ver), np.asarray(y.ver))
            and np.array_equal(np.asarray(x.inner.sum), np.asarray(y.inner.sum))
            and np.array_equal(np.asarray(x.inner.num), np.asarray(y.inner.num))
        )

    ab = lift.merge(a, b)
    assert eq(lift.merge(ab, ab), ab), "idempotence"
    assert eq(ab, lift.merge(b, a)), "commutativity"
    assert eq(
        lift.merge(lift.merge(a, b), c), lift.merge(a, lift.merge(b, c))
    ), "associativity"
    # The merged version is the pointwise max.
    assert list(np.asarray(ab.ver)) == [3, 3, 5, 0]


def test_duplicated_and_stale_publishes_do_not_double_count(tmp_path):
    """The task this plane exists for: member A's snapshot arrives twice,
    then a STALE copy arrives after newer content — counts stay exact."""
    lift = lift_avg()
    a = GossipStore(str(tmp_path), "a")
    b = GossipStore(str(tmp_path), "b")
    ca = MonoidContributor(lift, R, NK)
    cb = MonoidContributor(lift, R, NK)
    for s in range(2):
        ca.apply(avg_ops([0, 1], s), owned=[0, 1])
        cb.apply(avg_ops([2, 3], s), owned=[2, 3])
    stale = ca.view  # A's state at step 2 — will be re-published later
    a.publish("average_lifted", stale, step=2)
    for s in range(2, 4):
        ca.apply(avg_ops([0, 1], s), owned=[0, 1])
    a.publish("average_lifted", ca.view, step=4)

    # B sweeps A's fresh snapshot twice (duplicate delivery)...
    for _ in range(2):
        swept, n = sweep(b, lift, cb.view)
        assert n == 1
        cb.absorb(swept)
    # ...then A re-publishes the STALE snapshot (regression on disk) and
    # B sweeps again.
    a.publish("average_lifted", stale, step=2)
    swept, _ = sweep(b, lift, cb.view)
    cb.absorb(swept)

    ref_sum, ref_num = exact_totals(lift, [4, 4, 2, 2])
    tot = lift.total(cb.view)
    assert np.array_equal(np.asarray(tot.sum), ref_sum)
    assert np.array_equal(np.asarray(tot.num), ref_num)


def test_contributor_discipline_vs_naive_reapply(tmp_path):
    """The bug the contributor exists to prevent, demonstrated: applying
    a writer's next batch onto a swept-in HIGHER-version copy of its row
    rides a legitimate version and double-counts."""
    lift = lift_avg()
    # Writer w applied steps 0..2 of row 0 and published.
    w = MonoidContributor(lift, R, NK)
    for s in range(3):
        w.apply(avg_ops([0], s), owned=[0])
    published = w.view
    # A naive adopter merges the snapshot, then "catches up" by applying
    # the full history ON TOP of it (the JOIN drill's in-place re-apply).
    # Since round 4 the raw surface REJECTS this (ADVICE r3 #2) — the
    # demonstration below has to opt in explicitly to show the hazard the
    # guard now screens.
    naive = lift.init(R, NK)
    naive = lift.merge(naive, published)
    with pytest.raises(ValueError, match="swept"):
        lift.apply_ops(naive, avg_ops([0], 0), owned=[0])
    for s in range(3):
        naive, _ = lift.apply_ops(
            naive, avg_ops([0], s), owned=[0], allow_swept=True
        )
    ref_sum, _ = exact_totals(lift, [3, 0, 0, 0])
    assert np.asarray(lift.total(naive).sum)[0].sum() == 2 * ref_sum[0].sum(), (
        "the naive path should double-count — if it doesn't, this test "
        "is no longer pinning the hazard the discipline guards against"
    )
    # The contributor path: regenerate into own (identity there), merge.
    adopter = MonoidContributor(lift, R, NK)
    adopter.absorb(published)
    for s in range(3):
        adopter.apply(avg_ops([0], s), owned=[0])
    tot = lift.total(adopter.view)
    assert np.array_equal(np.asarray(tot.sum), ref_sum)


def test_row_delta_roundtrip_self_contained_and_idempotent():
    lift = lift_avg()
    from antidote_ccrdt_tpu.core import serial

    a = lift.init(R, NK)
    for s in range(2):
        a, _ = lift.apply_ops(a, avg_ops([0, 2], s), owned=[0, 2])
    prev = a
    a, _ = lift.apply_ops(a, avg_ops([0], 2), owned=[0])
    d = monoid_row_delta(lift, prev, a)
    assert list(np.asarray(d["rows"])) == [0]
    blob = serial.dumps_dense("average_lifted_delta", d)
    _, d2 = serial.loads_dense(blob, like_monoid_delta(lift, prev))
    assert monoid_delta_in_bounds(lift, prev, d2)
    # Fresh receiver: NO chaining needed — the delta carries whole rows.
    fresh = lift.init(R, NK)
    got = apply_monoid_row_delta(lift, fresh, d2)
    assert list(np.asarray(got.ver)) == [3, 0, 0, 0]
    assert np.array_equal(
        np.asarray(got.inner.sum)[0], np.asarray(a.inner.sum)[0]
    )
    # Duplicate application is a no-op (version guard).
    again = apply_monoid_row_delta(lift, got, d2)
    assert np.array_equal(np.asarray(again.inner.sum), np.asarray(got.inner.sum))
    assert np.array_equal(np.asarray(again.ver), np.asarray(got.ver))


def test_row_delta_bounds_rejects_foreign_config():
    lift = lift_avg()
    like = lift.init(R, NK)
    ok = monoid_row_delta(lift, like, like)  # empty delta
    assert monoid_delta_in_bounds(lift, like, ok)
    bad_row = {
        "rows": jnp.asarray([R + 3], jnp.int32),
        "ver": jnp.asarray([1], jnp.int32),
        "leaves": {
            p: jnp.zeros((1,) + tuple(shape[1:]), jnp.int32)
            for p, shape in {".sum": (R, NK), ".num": (R, NK)}.items()
        },
    }
    assert not monoid_delta_in_bounds(lift, like, bad_row)
    bad_shape = dict(ok)
    bad_shape["leaves"] = {p: jnp.zeros((0, NK + 5), jnp.int32) for p in ok["leaves"]}
    assert not monoid_delta_in_bounds(lift, like, bad_shape)
    assert not monoid_delta_in_bounds(lift, like, {"rows": ok["rows"]})


def test_entry_points_reject_raw_monoid_states(tmp_path):
    """sweep / DeltaPublisher auto-wrap a raw MONOID engine, but a raw
    (unversioned) state is a usage error — the silent-double-count shape
    of round 2's blanket refusal, now rejected with guidance."""
    store = GossipStore(str(tmp_path), "a")
    dense = AverageDense()
    raw = dense.init(R, NK)
    with pytest.raises(TypeError, match="MonoidLift"):
        sweep(store, dense, raw)
    pub = DeltaPublisher(store, dense, name="average_lifted")
    assert isinstance(pub.dense, MonoidLift)  # auto-lifted
    with pytest.raises(TypeError, match="MonoidLift"):
        pub.publish(raw)
    # The lifted state sails through both.
    lift = lift_avg()
    st = lift.init(R, NK)
    pub.publish(st)
    swept, _ = sweep(store, dense, st)
    assert isinstance(swept, LiftedMonoidState)


def test_wordcount_lift_and_generic_delta_dispatch():
    """The second MONOID engine rides the same plane; parallel.delta's
    engine-generic entry points dispatch lifted states correctly."""
    lift = MonoidLift(WordcountDense(16))
    a = lift.init(R, 1)

    def wc_ops(rows, step):
        key = np.zeros((R, B), np.int32)
        tok = np.full((R, B), -1, np.int32)
        for r in set(rows):
            rng = np.random.default_rng(99 * (step + 1) + r)
            tok[r] = rng.integers(0, 16, B)
        return WordcountOps(jnp.asarray(key), jnp.asarray(tok))

    prev = a
    a, _ = lift.apply_ops(a, wc_ops([1], 0), owned=[1])
    d = delta_mod.make_delta(lift, prev, a)
    assert "ver" in d and list(np.asarray(d["rows"])) == [1]
    like = delta_mod.like_delta_for(lift, prev)
    assert set(like) == {"rows", "ver", "leaves"}
    assert delta_mod.delta_in_bounds(lift, prev, d)
    got = delta_mod.apply_any_delta(lift, lift.init(R, 1), d)
    assert int(np.asarray(got.inner.counts)[1].sum()) == B
    assert int(np.asarray(got.inner.counts)[0].sum()) == 0
    # Totals: exactly one batch, no matter how often the delta re-applies.
    got = delta_mod.apply_any_delta(lift, got, d)
    assert int(np.asarray(lift.total(got).counts).sum()) == B


from conftest import HealthCheck, given, settings, st  # noqa: E402  (hypothesis or skip-stub)

from antidote_ccrdt_tpu.parallel.elastic import sweep_deltas  # noqa: E402


@settings(
    max_examples=20, deadline=None,
    suppress_health_check=[HealthCheck.too_slow],
)
@given(
    script=st.lists(
        st.tuples(
            st.integers(0, 1),
            st.sampled_from(["ops", "publish", "sweep", "dup", "crash"]),
        ),
        min_size=1, max_size=24,
    ),
    keep=st.integers(1, 4),
    full_every=st.integers(2, 6),
)
def test_monoid_gossip_arbitrary_interleavings(script, keep, full_every):
    """VERDICT-r2 task 8: the JOIN protocol property test extended to the
    MONOID plane. Under ANY schedule of op application, delta/full
    publishing with aggressive pruning, sweeping, DUPLICATED stale
    publishes, and member crash/restart (contributor lost, regenerated
    from the durable op source, gossip cursors lost too), every member
    converges to the EXACT sequential totals — a double count from wrong
    replace/version logic shows up as an off-by-a-batch digest."""
    import tempfile

    lift = lift_avg()

    def run_member_ops(contrib, m, k):
        # Member m owns rows {m, m+2}; step k is deterministic per (m, k).
        contrib.apply(avg_ops([m, m + 2], k), owned=[m, m + 2])

    with tempfile.TemporaryDirectory() as root:
        names = ["a", "b"]
        stores = [GossipStore(root, n) for n in names]
        pubs = [
            DeltaPublisher(s, lift, name="average_lifted",
                           full_every=full_every, keep=keep)
            for s in stores
        ]
        contribs = [MonoidContributor(lift, R, NK) for _ in names]
        cursors: list = [{}, {}]
        counters = [0, 0]
        last_published: list = [None, None]

        for m, action in script:
            if action == "ops":
                run_member_ops(contribs[m], m, counters[m])
                counters[m] += 1
            elif action == "publish":
                view = contribs[m].view
                pubs[m].publish(view)
                last_published[m] = (view, pubs[m].seq)
            elif action == "sweep":
                swept, _ = sweep_deltas(
                    stores[m], lift, contribs[m].view, cursors[m]
                )
                contribs[m].absorb(swept)
            elif action == "dup" and last_published[m] is not None:
                # Stale full snapshot reappears on disk (restart replay /
                # torn-writer recovery) AFTER newer content may exist.
                view, seq = last_published[m]
                stores[m].publish("average_lifted", view, seq)
            elif action == "crash":
                # Process dies: contribution state and cursors are gone.
                # Restart regenerates own rows from the durable op source
                # (counters survive in it by definition) — peers' swept-in
                # rows are NOT retained (they re-arrive via gossip).
                contribs[m] = MonoidContributor(lift, R, NK)
                cursors[m] = {}
                for k in range(counters[m]):
                    run_member_ops(contribs[m], m, k)

        # Final convergence: full anchors + sweeps.
        for m in range(2):
            stores[m].publish("average_lifted", contribs[m].view, 10_000)
        for m in range(2):
            swept, _ = sweep_deltas(stores[m], lift, contribs[m].view, cursors[m])
            contribs[m].absorb(swept)

        steps_per_row = [counters[0], counters[1], counters[0], counters[1]]
        ref_sum, ref_num = exact_totals(lift, steps_per_row)
        for m in range(2):
            tot = lift.total(contribs[m].view)
            assert np.array_equal(np.asarray(tot.sum), ref_sum), f"member {m}"
            assert np.array_equal(np.asarray(tot.num), ref_num), f"member {m}"


def test_apply_ops_owned_none_bumps_all_rows():
    lift = lift_avg()
    st = lift.init(R, NK)
    st, _ = lift.apply_ops(st, avg_ops(range(R), 0))
    assert list(np.asarray(st.ver)) == [1] * R
    st, _ = lift.apply_ops(st, avg_ops([], 1), owned=[])
    assert list(np.asarray(st.ver)) == [1] * R


def test_delta_bounds_rejects_duplicate_rows_and_float_ver():
    """ADVICE r4 #1: apply's fancy assignment is last-write-wins, so a
    crafted delta carrying one row twice ([ver 10, ver 3]) would leave the
    stale payload in place; the validator screens it out. Same for
    non-integer ver dtypes (the guard compares against i32 versions)."""
    lift = lift_avg()
    like = lift.init(R, NK)
    shapes = {".sum": (R, NK), ".num": (R, NK)}

    def mk(rows, ver):
        n = len(rows)
        return {
            "rows": jnp.asarray(rows, jnp.int32),
            "ver": jnp.asarray(ver),
            "leaves": {
                p: jnp.zeros((n,) + tuple(s[1:]), jnp.int32)
                for p, s in shapes.items()
            },
        }

    assert monoid_delta_in_bounds(lift, like, mk([0, 2], [1, 1]))
    assert not monoid_delta_in_bounds(lift, like, mk([2, 2], [10, 3]))
    assert not monoid_delta_in_bounds(
        lift, like, mk([0], jnp.asarray([1.0], jnp.float32))
    )


def test_apply_ops_rejects_swept_states():
    """ADVICE r4 #2: the write-once contract is now enforced, not just
    documented — a gossip-merged state refuses further apply_ops unless
    the caller explicitly re-establishes the contract."""
    lift = lift_avg()
    a = lift.init(R, NK)
    a, _ = lift.apply_ops(a, avg_ops([0], 0), owned=[0])
    assert not a.swept
    b = lift.init(R, NK)
    b, _ = lift.apply_ops(b, avg_ops([1], 0), owned=[1])
    merged = lift.merge(a, b)
    assert merged.swept
    with pytest.raises(ValueError, match="swept"):
        lift.apply_ops(merged, avg_ops([0], 1), owned=[0])
    # Escape hatch is explicit and stays sticky on the result.
    forced, _ = lift.apply_ops(merged, avg_ops([0], 1), owned=[0], allow_swept=True)
    assert forced.swept
    # The contributor discipline never trips the guard: own is merge-free.
    contrib = MonoidContributor(lift, R, NK)
    contrib.apply(avg_ops([0], 0), owned=[0])
    contrib.absorb(merged)
    assert contrib.view.swept  # view is a merge product, as expected
    contrib.apply(avg_ops([0], 1), owned=[0])  # still fine: applies to own


def test_delta_adoption_marks_swept():
    """Adopting rows via a row delta is gossip adoption like merge():
    the result must trip apply_ops' write-once guard (code-review r4)."""
    lift = lift_avg()
    a = lift.init(R, NK)
    a, _ = lift.apply_ops(a, avg_ops([0], 0), owned=[0])
    d = monoid_row_delta(lift, lift.init(R, NK), a)
    fresh = lift.init(R, NK)
    got = apply_monoid_row_delta(lift, fresh, d)
    assert got.swept
    with pytest.raises(ValueError, match="swept"):
        lift.apply_ops(got, avg_ops([0], 1), owned=[0])
