"""Multi-process distribution tests: REAL separate OS processes (one per
simulated host) coordinated by jax.distributed, exercising the cross-host
collective backend (Gloo on CPU; same program rides ICI/DCN on TPU pods).

Each worker (scripts/multihost_demo.py) applies distinct per-replica op
batches, reconciles hierarchically (intra-host, then cross-host), and
asserts its local shards converged to the single-process reference.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEMO = os.path.join(REPO, "scripts", "multihost_demo.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.parametrize("nproc", [2])
def test_multihost_convergence(nproc):
    port = _free_port()
    env = dict(os.environ)
    # The workers pick their own backend config; scrub the parent's rig.
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, DEMO, str(pid), str(nproc), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"MULTIHOST-OK {pid}" in out, f"worker {pid} output:\n{out}"
