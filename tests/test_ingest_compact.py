"""Compact device-dedup ingest wire (VERDICT-r3 item 6).

The raw device-dedup wire ships three [R, B] planes (doc / uniq / token);
the compact wire ships only `uniq` + per-document lengths + a resident
exact-id -> bucket table, and `apply_doc_ops_compact` rebuilds the
dropped planes on device. These tests pin that the rebuilt path is
observationally identical to the raw-plane path (and, via the existing
apply_doc_ops differentials, to the host-dedup reference semantics of
worddocumentcount.erl:76-86)."""

import numpy as np
import pytest

import jax.numpy as jnp

from antidote_ccrdt_tpu.models.wordcount import WordDocOps, make_dense


def build_raw_and_compact(docs_tokens, V, vocab_size):
    """From per-replica lists of per-doc exact-id lists, build both wire
    forms plus the bucket table (bucket = exact_id % V, a stand-in for
    the FNV map — any function works for the differential)."""
    R = len(docs_tokens)
    # Multiplier 5 is coprime to both V values used below, so the table is
    # non-degenerate (a *7 % 7 table would be identically zero and hide a
    # wrong gather index in the device-side token rebuild).
    bucket_table = (np.arange(vocab_size, dtype=np.int64) * 5 % V).astype(
        np.int32
    )
    flat = []
    for per_r in docs_tokens:
        toks = [t for d in per_r for t in d]
        docs = [i for i, d in enumerate(per_r) for _ in d]
        flat.append((np.asarray(toks, np.int32), np.asarray(docs, np.int32)))
    B = max(len(t) for t, _ in flat)
    DOCS = max(len(per_r) for per_r in docs_tokens)
    uniq = np.zeros((R, B), np.int32)
    doc = np.zeros((R, B), np.int32)
    token = np.full((R, B), -1, np.int32)
    doc_lens = np.zeros((R, DOCS), np.int32)
    counts = np.zeros((R,), np.int32)
    for r, (t, d) in enumerate(flat):
        uniq[r, : len(t)] = t
        doc[r, : len(d)] = d
        token[r, : len(t)] = bucket_table[t]
        for i, dd in enumerate(docs_tokens[r]):
            doc_lens[r, i] = len(dd)
        counts[r] = len(t)
    raw = WordDocOps(
        key=jnp.zeros((R, B), jnp.int32),
        doc=jnp.asarray(doc),
        uniq=jnp.asarray(np.where(token < 0, -1, uniq)),
        token=jnp.asarray(token),
    )
    compact = dict(
        uniq=jnp.asarray(uniq),
        doc_lens=jnp.asarray(doc_lens),
        counts=jnp.asarray(counts),
        bucket_table=jnp.asarray(bucket_table),
    )
    return raw, compact


CORPUS = [
    # replica 0: dup within doc (8 twice -> once), dup across docs (5),
    # an empty doc in the middle, hash-collision pair (3 and 10 share a
    # bucket when V=7: 3*5%7 == 1 == 10*5%7 -> both count, distinct uniq)
    [[5, 8, 8, 3], [], [5, 10], [1]],
    # replica 1: shorter stream -> exercises per-replica padding tails
    [[2, 2, 2], [6]],
]


@pytest.mark.parametrize("u16_wire", [False, True])
def test_compact_matches_raw_planes(u16_wire):
    V, vocab = 7, 16
    D = make_dense(V)
    raw, compact = build_raw_and_compact(CORPUS, V, vocab)
    if u16_wire:
        # The bench ships u16 halves; the engine upcasts.
        compact = dict(
            uniq=compact["uniq"].astype(jnp.uint16),
            doc_lens=compact["doc_lens"].astype(jnp.uint16),
            counts=compact["counts"],
            bucket_table=compact["bucket_table"].astype(jnp.uint16),
        )
    s_raw, _ = D.apply_doc_ops(D.init(2, 1), raw)
    s_c, _ = D.apply_doc_ops_compact(D.init(2, 1), **compact)
    assert jnp.array_equal(s_raw.counts, s_c.counts)
    assert jnp.array_equal(s_raw.lost, s_c.lost)


def test_compact_exact_mode_no_table():
    """bucket_table=None means token == uniq (exact vocabulary)."""
    V, vocab = 16, 16
    D = make_dense(V)
    docs = [[[5, 8, 8, 3], [5]]]
    raw, compact = build_raw_and_compact(docs, V, vocab)
    raw = WordDocOps(key=raw.key, doc=raw.doc, uniq=raw.uniq, token=raw.uniq)
    s_raw, _ = D.apply_doc_ops(D.init(1, 1), raw)
    compact.pop("bucket_table")
    s_c, _ = D.apply_doc_ops_compact(D.init(1, 1), **compact)
    assert jnp.array_equal(s_raw.counts, s_c.counts)


def test_compact_key_targets_nk_row():
    """The scalar `key` routes a compact batch into the right NK row of a
    multi-key grid (counts land in row `key`, others untouched)."""
    V, vocab = 16, 16
    D = make_dense(V)
    docs = [[[5, 8, 8], [5]]]
    _, compact = build_raw_and_compact(docs, V, vocab)
    s, _ = D.apply_doc_ops_compact(D.init(1, 3), **compact, key=2)
    counts = np.asarray(s.counts)
    assert counts[0, 0].sum() == 0 and counts[0, 1].sum() == 0
    tbl = np.asarray(compact["bucket_table"])
    expect = np.zeros(V, np.int64)
    for t in [5, 8, 5]:  # per-doc dedup: {5,8}, {5}
        expect[tbl[t]] += 1
    np.testing.assert_array_equal(counts[0, 2], expect)


def test_compact_out_of_table_uniq_lands_in_lost():
    """A live uniq id beyond the resident bucket table must land in
    `lost`, not be clamped into the last table entry (ADVICE-r4 #2): the
    raw wire could never produce such an id, and a clamped count would be
    a silent miscount into an arbitrary bucket."""
    V, vocab = 16, 8
    D = make_dense(V)
    docs = [[[5, 3]]]
    _, compact = build_raw_and_compact(docs, V, vocab)
    for bad in (12, -2):  # past the end AND negative: both sides guarded
        uniq = np.asarray(compact["uniq"]).copy()
        uniq[0, 1] = bad
        c2 = dict(compact, uniq=jnp.asarray(uniq))
        s, _ = D.apply_doc_ops_compact(D.init(1, 1), **c2)
        counts = np.asarray(s.counts)
        tbl = np.asarray(compact["bucket_table"])
        assert int(np.asarray(s.lost)[0, 0]) == 1, bad
        assert counts[0, 0].sum() == 1 and counts[0, 0, tbl[5]] == 1


def test_compact_counts_expected_values():
    """End-to-end value check, not just raw-vs-compact agreement."""
    V, vocab = 32, 16
    D = make_dense(V)
    _, compact = build_raw_and_compact(CORPUS, V, vocab)
    s, _ = D.apply_doc_ops_compact(D.init(2, 1), **compact)
    tbl = np.asarray(compact["bucket_table"])
    # replica 0 deduped per doc: {5,8,3}, {}, {5,10}, {1}
    expect0 = np.zeros(V, np.int64)
    for t in [5, 8, 3, 5, 10, 1]:
        expect0[tbl[t]] += 1
    np.testing.assert_array_equal(np.asarray(s.counts)[0, 0], expect0)
    # replica 1: {2}, {6}
    expect1 = np.zeros(V, np.int64)
    for t in [2, 6]:
        expect1[tbl[t]] += 1
    np.testing.assert_array_equal(np.asarray(s.counts)[1, 0], expect1)


def test_compact_native_tokenizer_end_to_end():
    """Real string corpus through the native tokenizer: compact arrays
    produce the same state as the raw three-plane arrays, at a strictly
    smaller wire."""
    from antidote_ccrdt_tpu.harness import native_tokenizer as nt

    if not nt.available():
        pytest.skip(f"native toolchain unavailable: {nt.build_error()}")
    V = 97
    docs = [
        ["the quick brown fox", "the the fox", "", "lazy dog dog"],
        ["a b a", "c"],
    ]
    raw = nt.worddoc_arrays_from_docs(docs, n_buckets=V)
    compact = nt.worddoc_compact_arrays_from_docs(docs, n_buckets=V)
    D = make_dense(V)
    s_raw, _ = D.apply_doc_ops(
        D.init(2, 1), WordDocOps(**{k: jnp.asarray(v) for k, v in raw.items()})
    )
    s_c, _ = D.apply_doc_ops_compact(
        D.init(2, 1), **{k: jnp.asarray(v) for k, v in compact.items()}
    )
    assert jnp.array_equal(s_raw.counts, s_c.counts)
    assert jnp.array_equal(s_raw.lost, s_c.lost)
    # Wire accounting at equal dtype width: 3 token-length planes vs one
    # plane + per-doc lengths + the once-per-corpus vocab table.
    raw_wire = sum(raw[k].nbytes for k in ("doc", "uniq", "token"))
    compact_wire = sum(compact[k].nbytes for k in compact)
    assert compact_wire < raw_wire
