"""Tri-surface parity for the serving plane: the SAME query against the
SAME snapshot must produce byte-identical responses on every wire
surface — the tcp ``{query}`` frame, the bridge ``{query}`` op, and
``POST /query`` — because all three carry `ServePlane.handle` bytes
verbatim and the codec is canonical JSON. The plane's clock is frozen so
the advertised staleness bound cannot drift between the surface calls.

The second half is degrade-never-hang: with `utils.faults` firing at the
``serve.query`` point, each surface fails its own bounded way (closed
connection / error frame / HTTP 500) and recovers on the next request
once the fault plan is gone.
"""

import json
import urllib.error
import urllib.request

import pytest

from antidote_ccrdt_tpu import serve
from antidote_ccrdt_tpu.bridge.client import BridgeClient
from antidote_ccrdt_tpu.bridge.server import BridgeServer
from antidote_ccrdt_tpu.net.tcp import TcpTransport, query_peer
from antidote_ccrdt_tpu.obs import http as obs_http
from antidote_ccrdt_tpu.utils import faults
from antidote_ccrdt_tpu.utils.metrics import Metrics

from tests.test_serve import R, _apply, _engine


@pytest.fixture(autouse=True)
def _no_fault_leak():
    faults.uninstall()
    yield
    faults.uninstall()


def _frozen_plane(metrics=None):
    import time

    dense = _engine()
    plane = serve.ServePlane(dense, member="w0", metrics=metrics or Metrics())
    state = _apply(dense, dense.init(R, 1), [1, 2, 3], [50, 40, 30])
    plane.swap(state, 4)
    t = time.monotonic()
    plane.mono = lambda: t  # freeze: bounds identical across surfaces
    return plane


REQ = serve.request_bytes(
    [{"op": "value", "key": 0}, {"op": "topk", "key": 0, "k": 2}],
    max_staleness_s=60.0,
)


def _post(addr, payload, timeout=5.0):
    return urllib.request.urlopen(
        urllib.request.Request(
            f"http://{addr[0]}:{addr[1]}/query", data=payload, method="POST"
        ),
        timeout=timeout,
    )


def test_three_surfaces_byte_identical():
    plane = _frozen_plane()
    want = plane.handle(REQ)
    assert json.loads(want.decode())["results"][0]["value"]  # non-trivial

    t = TcpTransport("w0")
    t.install_serve(plane)
    try:
        member, tcp_resp = query_peer(t.address, REQ, timeout=5.0)
        assert member == "w0"
    finally:
        t.close()

    with obs_http.MetricsHttpServer(
        plane.metrics, "w0", query_handler=plane.handle
    ) as srv:
        with _post(srv.address, REQ) as r:
            assert r.status == 200
            http_resp = r.read()

    bs = BridgeServer(port=0).start()
    bs.install_serve(plane)
    try:
        cl = BridgeClient("127.0.0.1", bs.address[1])
        bridge_resp = cl.query(REQ)
        cl.close()
    finally:
        bs.close()

    assert tcp_resp == want
    assert http_resp == want
    assert bridge_resp == want


def test_sim_surface_matches_too():
    from antidote_ccrdt_tpu.net.sim import SimNet

    plane = _frozen_plane()
    want = plane.handle(REQ)
    net = SimNet(seed=3)
    a, b = net.join("a"), net.join("b")
    b.install_serve(plane)
    a.query("b", REQ)
    net.advance(1.0)
    assert a.query_resps == [("b", want)]


def test_tcp_surface_no_plane_degrades():
    t = TcpTransport("w9")
    try:
        member, resp = query_peer(t.address, REQ, timeout=5.0)
        assert member == "w9"
        assert json.loads(resp.decode())["error"] == "no serve plane"
    finally:
        t.close()


def test_tcp_surface_fault_closes_never_hangs():
    plane = _frozen_plane()
    t = TcpTransport("w0")
    t.install_serve(plane)
    try:
        faults.install(
            {"serve.query": [{"action": "raise", "at": [0]}]}, seed=7
        )
        with pytest.raises((ConnectionError, OSError)):
            query_peer(t.address, REQ, timeout=2.0)
        # The fault budget is spent: the next query serves normally.
        member, resp = query_peer(t.address, REQ, timeout=5.0)
        assert member == "w0" and b"results" in resp
    finally:
        t.close()


def test_http_surface_fault_500_then_recovers():
    plane = _frozen_plane()
    with obs_http.MetricsHttpServer(
        plane.metrics, "w0", query_handler=plane.handle
    ) as srv:
        faults.install(
            {"serve.query": [{"action": "raise", "at": [0]}]}, seed=7
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(srv.address, REQ)
        assert ei.value.code == 500
        with _post(srv.address, REQ) as r:
            assert r.status == 200 and b"results" in r.read()


def test_bridge_surface_fault_errors_then_recovers():
    plane = _frozen_plane()
    bs = BridgeServer(port=0).start()
    bs.install_serve(plane)
    try:
        cl = BridgeClient("127.0.0.1", bs.address[1])
        faults.install(
            {"serve.query": [{"action": "raise", "at": [0]}]}, seed=7
        )
        with pytest.raises(Exception):
            cl.query(REQ)
        assert b"results" in cl.query(REQ)
        cl.close()
    finally:
        bs.close()


def test_bridge_no_plane_is_an_error_not_a_hang():
    bs = BridgeServer(port=0).start()
    try:
        cl = BridgeClient("127.0.0.1", bs.address[1])
        with pytest.raises(Exception):
            cl.query(REQ)
        cl.close()
    finally:
        bs.close()
