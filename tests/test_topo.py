"""topo/: zone maps, rendezvous anchors, routing, codecs, backpressure.

Fast, mostly network-free unit surface for the DCN-aware topology tier:

* rendezvous election — deterministic, order-independent, and STABLE
  (property-style over seeded random views: removing a non-winner never
  moves the winner, so churn among leaves causes zero anchor churn);
* `ZoneRouter` — leaf vs anchor send targets, relay planning, and the
  hop-stamp loop-freedom invariant;
* the codec-byte framing — raw/zlib round-trips, the incompressible->raw
  fallback, legacy bare-ETF interop, and the savings counter;
* a real in-process 2-zone TCP fleet — hello/codec negotiation plus a
  snapshot crossing the DCN via anchors only (`topo.cross_zone.*`);
* `DeltaPublisher` lag backpressure — a synthetic laggard tightens the
  anchor cadence, a broken lag probe never stops publishing.
"""

import random
import struct
import time
import zlib

from antidote_ccrdt_tpu.core import etf
from antidote_ccrdt_tpu.net.tcp import TcpTransport
from antidote_ccrdt_tpu.topo import (
    CODEC_RAW,
    CODEC_ZLIB,
    UNKNOWN_ZONE,
    ZoneMap,
    ZoneRouter,
    decode_body,
    encode_frame,
    rendezvous_anchor,
    unpack_coded_frames,
)
from antidote_ccrdt_tpu.utils.metrics import Metrics


# -- rendezvous election ------------------------------------------------------


def test_rendezvous_deterministic_and_order_independent():
    members = [f"m{i}" for i in range(8)]
    a = rendezvous_anchor("za", members)
    assert a in members
    for _ in range(5):
        shuffled = members[:]
        random.Random(_).shuffle(shuffled)
        assert rendezvous_anchor("za", shuffled) == a
    assert rendezvous_anchor("za", []) is None
    # Different zones draw independent rankings: with enough zones at
    # least one elects a different member (sha1 mixing, not a constant).
    assert len({rendezvous_anchor(f"z{i}", members) for i in range(16)}) > 1


def test_rendezvous_stability_under_churn():
    """The HRW property the topology leans on: removing any NON-winner
    leaves the winner in place (leaf churn never reshuffles anchors),
    and removing the winner promotes the runner-up for everyone.
    Property-style over seeded random views."""
    rng = random.Random(42)
    for trial in range(50):
        n = rng.randrange(2, 12)
        members = sorted({f"w{rng.randrange(100)}" for _ in range(n)})
        if len(members) < 2:
            continue
        zone = f"zone{trial % 5}"
        winner = rendezvous_anchor(zone, members)
        for leaver in members:
            rest = [m for m in members if m != leaver]
            survivor = rendezvous_anchor(zone, rest)
            if leaver == winner:
                assert survivor != winner  # failover, not resurrection
            else:
                assert survivor == winner, (
                    f"non-winner {leaver} leaving moved the anchor "
                    f"{winner} -> {survivor} (view {members}, zone {zone})"
                )
        # Joins only move the anchor when the joiner itself wins.
        grown = rendezvous_anchor(zone, members + ["w-new"])
        assert grown in (winner, "w-new")


# -- zone map -----------------------------------------------------------------


def test_zone_map_learning_and_grouping():
    zm = ZoneMap("a0", "za")
    assert zm.zone_of("a0") == "za"
    assert zm.zone_of("stranger") == UNKNOWN_ZONE
    assert zm.learn("b0", "zb") is True
    assert zm.learn("b0", "zb") is False  # no new information
    assert zm.learn("b0", "") is False
    assert zm.learn("b0", UNKNOWN_ZONE) is False
    assert zm.learn("a0", "zb") is False  # self's zone is pinned
    assert zm.zone_of("a0") == "za"
    zm.learn("a1", "za")
    assert zm.members_of("za", ["a0", "a1", "b0", "x"]) == ["a0", "a1"]
    assert zm.zones_of(["a1", "b0", "x"]) == ["za", "zb"]
    assert zm.group(["a0", "a1", "b0", "x"]) == {
        "za": ["a0", "a1"],
        "zb": ["b0"],
        UNKNOWN_ZONE: ["x"],
    }


# -- router -------------------------------------------------------------------


def _router(member, zone, layout, membership=None, metrics=None):
    zm = ZoneMap(member, zone)
    for m, z in layout.items():
        zm.learn(m, z)
    return ZoneRouter(member, zone, zm, membership=membership, metrics=metrics)


LAYOUT = {"a0": "za", "a1": "za", "a2": "za", "b0": "zb", "b1": "zb"}
PEERS = sorted(LAYOUT)


def test_send_targets_leaf_vs_anchor():
    anchors = {z: rendezvous_anchor(z, [m for m, mz in LAYOUT.items() if mz == z])
               for z in ("za", "zb")}
    for member, zone in LAYOUT.items():
        r = _router(member, zone, LAYOUT)
        targets = r.send_targets([p for p in PEERS if p != member])
        direct = {p for p, cross in targets if not cross}
        cross = {p for p, cross in targets if cross}
        zone_mates = {m for m, z in LAYOUT.items() if z == zone} - {member}
        assert direct == zone_mates
        if member == anchors[zone]:
            assert cross == {anchors[z] for z in anchors if z != zone}
        else:
            assert cross == set()  # leaves never pay for the DCN


def test_unknown_zone_peers_get_full_mesh_fallback():
    r = _router("a0", "za", {"a1": "za"})
    targets = dict(r.send_targets(["a1", "mystery"]))
    assert targets == {"a1": False, "mystery": False}


def test_plan_relay_origin_zone_vs_remote_zone():
    anchors = {z: rendezvous_anchor(z, [m for m, mz in LAYOUT.items() if mz == z])
               for z in ("za", "zb")}
    az, bz = anchors["za"], anchors["zb"]
    # Origin-zone anchor: a zone-mate's frame crosses to the remote anchor.
    r = _router(az, "za", LAYOUT)
    origin = next(m for m, z in LAYOUT.items() if z == "za" and m != az)
    cands = [p for p in PEERS if p != az]
    assert r.plan_relay(origin, [(origin, "za")], cands) == [(bz, True)]
    # Remote-zone anchor: fans out locally, never back across.
    rb = _router(bz, "zb", LAYOUT)
    path = [(origin, "za"), (az, "za")]
    fanout = rb.plan_relay(origin, path, [p for p in PEERS if p != bz])
    assert fanout == [(m, False) for m, z in sorted(LAYOUT.items())
                      if z == "zb" and m != bz]
    # Non-anchors never relay.
    leaf = next(m for m, z in LAYOUT.items() if z == "zb" and m != bz)
    rl = _router(leaf, "zb", LAYOUT)
    assert rl.plan_relay(origin, path, [p for p in PEERS if p != leaf]) == []


def test_relay_path_stamps_prevent_loops():
    anchors = {z: rendezvous_anchor(z, [m for m, mz in LAYOUT.items() if mz == z])
               for z in ("za", "zb")}
    az = anchors["za"]
    r = _router(az, "za", LAYOUT)
    origin = next(m for m, z in LAYOUT.items() if z == "za" and m != az)
    # A path that already visited zb must not be sent there again.
    path = [(origin, "za"), (az, "za"), (anchors["zb"], "zb")]
    assert r.plan_relay(origin, path, [p for p in PEERS if p != az]) == []
    # loop_safe: own stamp on the path -> drop on arrival.
    assert ZoneRouter.loop_safe(path, "b1")
    assert not ZoneRouter.loop_safe(path, az)


class _FakeMembership:
    def __init__(self, states):
        self.states = states

    def state_of(self, member, timeout_s):
        return self.states.get(member, "dead")


def test_anchor_failover_on_suspect_and_change_counter():
    za_members = sorted(m for m, z in LAYOUT.items() if z == "za")
    winner = rendezvous_anchor("za", za_members)
    leaf = next(m for m in za_members if m != winner)  # observe as a leaf
    m = Metrics()
    states = {p: "alive" for p in LAYOUT}
    r = _router(leaf, "za", LAYOUT,
                membership=_FakeMembership(states), metrics=m)
    cands = [p for p in PEERS if p != leaf]
    assert r.anchor_of("za", cands) == winner
    assert m.counters.get("topo.anchor_changes") == 1
    # SUSPECT demotes the anchor out of the pool within one decision —
    # the runner-up takes over without any coordination.
    states[winner] = "suspect"
    second = r.anchor_of("za", cands)
    assert second != winner
    assert m.counters["topo.anchor_changes"] == 2
    # DEAD everyone: self is alive by definition, so the local pool
    # degrades to exactly {self}; a fully-dead REMOTE zone still elects
    # (pool falls through to all-known) so relays have a destination.
    for p in LAYOUT:
        states[p] = "dead"
    assert r.anchor_of("za", cands) == leaf
    assert r.anchor_of("zb", cands) is not None


# -- codec --------------------------------------------------------------------


def test_codec_roundtrip_raw_zlib_and_legacy():
    term = ("delta", b"w0", 7, 16, b"x" * 512)
    payload = etf.encode(term)
    for codec in (CODEC_RAW, CODEC_ZLIB):
        frame = encode_frame(payload, codec)
        buf = bytearray(frame)
        assert list(unpack_coded_frames(buf)) == [etf.decode(payload)]
        assert not buf
    # Legacy bare-ETF body (no codec byte) decodes identically.
    assert decode_body(payload) == payload
    legacy = struct.pack(">I", len(payload)) + payload
    assert list(unpack_coded_frames(bytearray(legacy))) == [etf.decode(payload)]


def test_codec_zlib_falls_back_to_raw_when_incompressible():
    m = Metrics()
    noise = random.Random(0).randbytes(64)
    frame = encode_frame(noise, CODEC_ZLIB, metrics=m)
    assert frame[4] == CODEC_RAW  # self-describing fallback
    assert m.counters.get("net.codec_saved_bytes", 0) == 0
    # Compressible payloads really do tag zlib and count the win.
    fat = b"delta " * 400
    frame = encode_frame(fat, CODEC_ZLIB, metrics=m)
    assert frame[4] == CODEC_ZLIB
    assert zlib.decompress(frame[5:]) == fat
    assert m.counters["net.codec_saved_bytes"] > 0
    assert m.counters["net.codec_zlib_frames"] == 1


def test_codec_rejects_garbage():
    import pytest

    with pytest.raises(ValueError):
        decode_body(b"")
    with pytest.raises(ValueError):
        decode_body(bytes([9]) + b"junk")
    with pytest.raises(ValueError):
        encode_frame(b"x", 9)


# -- real sockets: 2-zone fleet via anchors -----------------------------------


def _wait_for(pred, timeout=10.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def test_tcp_two_zone_fleet_crosses_dcn_via_anchors():
    """Four real sockets, two zones. A snapshot published in za must
    reach both zb members — but only anchor links may cross the zone
    boundary, and the hello exchange must have negotiated codecs."""
    layout = [("a0", "za"), ("a1", "za"), ("b0", "zb"), ("b1", "zb")]
    ts = [TcpTransport(n, zone=z, hello_timeout=2.0) for n, z in layout]
    try:
        for t in ts:
            for u in ts:
                if u.member != t.member:
                    t.learn_zone(u.member, u.zone)
            t.install_router(timeout_s=1.0)
        for t in ts:
            for u in ts:
                if u.member != t.member:
                    t.add_peer(u.member, u.address)
        # Compressible on purpose: cross-zone links default to zlib and
        # the test asserts the codec actually fired (not just the hello).
        blob = struct.pack("<Q", 1) + b"cross-zone-snapshot " * 64

        def pump():
            for t in ts:
                t.heartbeat()
            ts[0].publish(blob)
            return all(t.fetch("a0") == blob for t in ts[1:])

        assert _wait_for(pump), {
            t.member: t.fetch("a0") is not None for t in ts
        }
        cross = sum(
            t.metrics.counters.get("topo.cross_zone.frames", 0) for t in ts
        )
        assert cross > 0
        assert sum(
            t.metrics.counters.get("topo.relays", 0) for t in ts
        ) > 0, "snapshot crossed without an anchor relay"
        # Hello/codec negotiation ran AND produced a live zlib link: the
        # compressible snapshot must have crossed the DCN deflated.
        assert sum(
            t.metrics.counters.get("net.hello_acks", 0) for t in ts
        ) > 0
        assert sum(
            t.metrics.counters.get("net.codec_zlib_frames", 0) for t in ts
        ) > 0, "cross-zone links never compressed a frame"
        assert sum(
            t.metrics.counters.get("net.codec_saved_bytes", 0) for t in ts
        ) > 0
    finally:
        for t in ts:
            t.close()


# -- dashboard zone grouping --------------------------------------------------


def test_dashboard_groups_members_by_zone(tmp_path):
    """Member rows sort by (zone, member) with a per-zone SWIM tally
    header; single-zone fleets keep the old flat layout (plus column)."""
    import json as _json
    import os
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts"))
    import obs_dashboard

    now = time.time()
    for m, zone in [("b9", "zb"), ("a1", "za"), ("a0", "za")]:
        with open(tmp_path / f"hb-{m}", "wb") as f:
            f.write(struct.pack("<d", now))
        with open(tmp_path / f"obs-{m}.json", "w") as f:
            _json.dump({"member": m, "zone": zone}, f)
    frame = obs_dashboard.render_frame(str(tmp_path), clear=False)
    lines = frame.splitlines()
    order = [ln.split()[0] for ln in lines
             if ln.split() and ln.split()[0] in ("a0", "a1", "b9")]
    assert order == ["a0", "a1", "b9"]  # (zone, member), not plain name
    za_hdr = next(i for i, ln in enumerate(lines) if "zone za" in ln)
    zb_hdr = next(i for i, ln in enumerate(lines) if "zone zb" in ln)
    assert za_hdr < zb_hdr
    assert "alive" in lines[za_hdr]  # the SWIM tally rides the header


# -- lag-driven backpressure --------------------------------------------------


def test_delta_publisher_lag_backpressure():
    """A synthetic laggard must tighten the anchor cadence from
    full_every=4 to lag_full_every=2, counted in net.lag_anchor_cuts;
    a broken lag probe must not stop publishing."""
    import os
    import sys
    import tempfile

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts"))
    from elastic_demo import DRILLS

    from antidote_ccrdt_tpu.parallel.elastic import DeltaPublisher, GossipStore

    drill = DRILLS["topk_rmv"]
    dense = drill.make_engine()
    state = drill.init(dense)
    lag = {"ops": 0.0}
    with tempfile.TemporaryDirectory() as root:
        store = GossipStore(root, "w0")
        pub = DeltaPublisher(
            store, dense, name=drill.publish_name, full_every=4,
            lag_source=lambda: lag["ops"], lag_threshold=5.0,
        )

        def drive(n):
            nonlocal state
            kinds = []
            for _ in range(n):
                step = pub.seq + 1
                state = drill.apply(dense, state, step % 8, [0])
                kinds.append(
                    pub.publish(drill.pub_state(dense, state))["kind"])
            return kinds

        # Healthy fleet: anchors only at seq % 4 == 0.
        kinds = drive(8)  # seqs 0..7
        assert kinds[0] == "full" and kinds[4] == "full"
        assert kinds.count("full") == 2
        assert "net.lag_anchor_cuts" not in store.metrics.counters

        # Laggard appears: cadence halves while the pressure lasts.
        lag["ops"] = 12.0
        kinds = drive(4)  # seqs 8..11
        assert kinds.count("full") == 2  # seq 8 and 10
        assert store.metrics.counters["net.lag_anchor_cuts"] > 0

        # Laggard catches up: back to the relaxed cadence.
        lag["ops"] = 0.0
        cuts = store.metrics.counters["net.lag_anchor_cuts"]
        kinds = drive(4)  # seqs 12..15
        assert kinds.count("full") == 1  # seq 12 only
        assert store.metrics.counters["net.lag_anchor_cuts"] == cuts

        # A probe that raises is treated as "no pressure", never a crash.
        pub.lag_source = lambda: (_ for _ in ()).throw(RuntimeError("probe"))
        assert drive(2)  # publishes fine
