"""Device observatory (obs/devprof.py): signature-diff axis naming,
kill-switch gating, fault-point degradation, warm-up storm collapse,
pager HBM gauges, SIGKILL spill of compile events, profile.* parity
(single source of truth), and the seeded stepping drill chaos_gate leg
12 reuses (`run_devprof_drill`)."""

import hashlib
import os
import random
import signal
import subprocess
import sys

import numpy as np
import pytest

from antidote_ccrdt_tpu.obs import devprof, events, profile
from antidote_ccrdt_tpu.utils import faults
from antidote_ccrdt_tpu.utils.metrics import Metrics


@pytest.fixture(autouse=True)
def _clean():
    profile.uninstall()
    devprof.uninstall()
    devprof.set_warmup(False)
    yield
    profile.uninstall()
    devprof.uninstall()
    devprof.set_warmup(False)


# -- signature diffs --------------------------------------------------------


def test_signature_diff_names_growth_axis():
    a = {"slot_score": np.zeros((1, 1, 4, 4), np.int32)}
    b = {"slot_score": np.zeros((1, 1, 4, 8), np.int32)}
    d = devprof.signature_diff(
        devprof.signature((a,)), devprof.signature((b,))
    )
    assert len(d) == 1
    assert "slot_score" in d[0]
    assert "axis3 4->8" in d[0]


def test_signature_diff_dtype_and_rank_and_donation():
    a = np.zeros((4,), np.int32)
    b = np.zeros((4,), np.float32)
    d = devprof.signature_diff(
        devprof.signature((a,)), devprof.signature((b,))
    )
    assert any("dtype int32->float32" in c for c in d)
    r = devprof.signature_diff(
        devprof.signature((np.zeros((4,), np.int32),)),
        devprof.signature((np.zeros((4, 2), np.int32),)),
    )
    assert any("rank 1->2" in c for c in r)
    dn = devprof.signature_diff(
        devprof.signature((a,), donation="plain"),
        devprof.signature((a,), donation="donate_rhs"),
    )
    assert dn == ["donation plain->donate_rhs"]


def test_signature_diff_sharding_change():
    class _Leaf:
        def __init__(self, sharding):
            self.shape, self.dtype = (4,), "int32"
            self.sharding = sharding

    d = devprof.signature_diff(
        devprof.signature(({"x": _Leaf("mesh0")},)),
        devprof.signature(({"x": _Leaf("mesh1")},)),
    )
    assert any("sharding mesh0->mesh1" in c for c in d)


def test_signature_diff_first_trace_and_retrace():
    s = devprof.signature((np.zeros((4,), np.int32),))
    assert devprof.signature_diff(None, s) == ["first_trace"]
    s2 = devprof.signature((np.zeros((4,), np.int32),))
    assert devprof.signature_diff(s, s2) == ["retrace"]


def test_pad_dim_buckets():
    assert [devprof.pad_dim(n) for n in (0, 1, 2, 3, 5, 8, 9)] == [
        1, 1, 2, 4, 8, 8, 16,
    ]


# -- kill switch ------------------------------------------------------------


def test_kill_switch_env_gating():
    m = Metrics()
    # Default-armed: unset means ON, explicit "0"/"false"/"off" kills.
    assert devprof.install_from_env(m, env={}) is True
    assert devprof.ACTIVE
    devprof.uninstall()
    for off in ("0", "false", "off", "no"):
        assert devprof.install_from_env(m, env={devprof.ENV_FLAG: off}) is False
        assert not devprof.ACTIVE
    assert devprof.install_from_env(
        m, env={devprof.ENV_FLAG: "1", devprof.ENV_WARMUP: "1"}
    ) is True
    assert devprof.WARMUP


def test_disabled_is_zero_cost_no_trace():
    pytest.importorskip("jax")
    from antidote_ccrdt_tpu.core.batch_merge import batch_merge
    from antidote_ccrdt_tpu.models.topk import TopkState

    events.reset("devprof-off")
    assert not devprof.ACTIVE and not profile.ACTIVE
    merged = batch_merge(
        "topk", [TopkState({chr(97 + i): i + 1}, 2) for i in range(3)]
    )
    assert merged.entries == {"c": 3, "b": 2}
    assert not [e for e in events.events() if e["kind"].startswith("devprof.")]


# -- fault point ------------------------------------------------------------


def test_record_fault_degrades_to_unobserved_never_blocks():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    fn = jax.jit(lambda a, b: a + b)
    m = Metrics()
    events.reset("devprof-fault")
    devprof.install(m)
    with faults.injected({
        devprof.FAULT_RECORD: [
            {"action": "raise", "at": [0]},
            {"action": "drop", "at": [1]},
        ]
    }):
        outs = []
        for shape in ((4,), (8,), (16,)):
            a = jnp.zeros(shape, jnp.int32)
            with devprof.observe("unit.fault", fn=fn, operands=(a, a)):
                outs.append(fn(a, a).block_until_ready())
    assert len(outs) == 3  # every dispatch completed despite the faults
    snap = m.snapshot()["counters"]
    assert snap["devprof.unobserved"] == 2
    assert snap["devprof.compiles"] == 1  # only the unfaulted dispatch


# -- warm-up ----------------------------------------------------------------


def _step(sc, states, r, seed):
    rng = random.Random((seed << 16) ^ r)
    out = []
    for wi, st in enumerate(states):
        st, _ = sc.update(
            ("add", (1, 100 + rng.randrange(100),
                     (f"dc{wi}", r * len(states) + wi + 1))),
            st,
        )
        out.append(st)
    return out


def test_warmup_eliminates_first_round_compiles():
    pytest.importorskip("jax")
    from antidote_ccrdt_tpu.core import batch_merge
    from antidote_ccrdt_tpu.models.topk_rmv import TopkRmvScalar

    events.reset("devprof-warm")
    m = Metrics()
    devprof.install(m)
    devprof.set_warmup(True)
    # Pre-trace the ladder past anything 4 rounds of 3 workers can need
    # (M reaches 12; the ladder tops out at the 16 rung).
    assert batch_merge.prewarm_topk_rmv(13, n_ids=1, n_dcs=3, max_slots=13) > 0
    boot = m.snapshot()["counters"].get("devprof.compiles", 0)
    sc = TopkRmvScalar()
    states = [sc.new(13) for _ in range(3)]
    for r in range(4):
        states = _step(sc, states, r, seed=99)
        batch_merge.batch_merge("topk_rmv", list(states))
    steady = m.snapshot()["counters"].get("devprof.compiles", 0) - boot
    assert steady == 0
    # Every boot compile attributed to the dedicated prewarm site.
    assert all(
        e["site"] == "batch_merge.prewarm"
        for e in events.events()
        if e["kind"] == "devprof.compile"
    )


# -- pager HBM telemetry ----------------------------------------------------


def test_pager_hbm_gauge_vs_budget():
    m = Metrics()
    devprof.install(m)
    devprof.note_pager(50, 200)
    devprof.note_pager(150, 200)
    devprof.note_pager(100, 200)
    c = m.snapshot()["counters"]
    assert c["devprof.hbm_used_bytes"] == 100
    assert c["devprof.hbm_budget_bytes"] == 200
    assert c["devprof.hbm_occupancy"] == 0.5
    assert c["devprof.hbm_peak_bytes"] == 150  # high-watermark sticks
    h = devprof.health_fields()
    assert h["devprof_hbm_occupancy"] == 0.5
    assert h["devprof_hbm_peak_bytes"] == 150


# -- SIGKILL spill ----------------------------------------------------------


@pytest.mark.slow
def test_sigkill_spills_compile_events(tmp_path):
    pytest.importorskip("jax")
    code = f"""
import os, signal
os.environ["JAX_PLATFORMS"] = "cpu"
from antidote_ccrdt_tpu.obs import devprof, events
from antidote_ccrdt_tpu.utils.metrics import Metrics
from antidote_ccrdt_tpu.core import batch_merge
from antidote_ccrdt_tpu.models.topk import TopkState
events.configure("w0", spill_dir={str(tmp_path)!r})
devprof.install(Metrics())
states = [TopkState({{chr(97 + i): i + 1}}, 2) for i in range(4)]
batch_merge.batch_merge("topk", states)
os.kill(os.getpid(), signal.SIGKILL)
"""
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True,
        timeout=120,
    )
    assert proc.returncode == -signal.SIGKILL, proc.stderr.decode()
    logs = events.scan_dir(str(tmp_path))
    compiles = [
        e
        for evs in logs.values()
        for e in evs
        if e.get("kind") == "devprof.compile"
    ]
    assert compiles, "compile events must survive the SIGKILL via spill"
    assert all(e.get("site") and e.get("axis") for e in compiles)
    # No clean-exit marker anywhere: the spill is crash evidence.
    assert not any(
        e.get("kind") == "proc.exit" for evs in logs.values() for e in evs
    )


# -- profile.* parity (single source of truth) ------------------------------


def test_profile_parity_with_and_without_devprof():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    def arm(with_devprof):
        fn = jax.jit(lambda a, b: a + b)  # fresh cache per arm
        m, dm = Metrics(), Metrics()
        profile.install(m)
        if with_devprof:
            devprof.install(dm)
        for shape in ((4,), (4,), (8,)):
            a = jnp.zeros(shape, jnp.int32)
            with profile.dispatch("unit.par", fn=fn, operands=(a, a)):
                fn(a, a).block_until_ready()
        profile.uninstall()
        devprof.uninstall()
        return m.snapshot(), dm.snapshot()

    base, _ = arm(False)
    both, dsnap = arm(True)
    # The legacy family is untouched by the devprof plane riding along.
    for k in ("profile.jit_misses", "profile.jit_hits", "profile.h2d_bytes"):
        assert base["counters"][k] == both["counters"][k]
    assert base["counters"]["profile.jit_misses"] == 2
    assert base["counters"]["profile.jit_hits"] == 1
    assert sorted(k for k in base["latencies"]) == sorted(
        k for k in both["latencies"]
    )
    # One cache sample, two families: devprof counted the same compiles.
    assert dsnap["counters"]["devprof.compiles"] == 2
    # And the devprof registry never grows profile.* names (no double
    # bookkeeping in one registry).
    assert not any(k.startswith("profile.") for k in dsnap["counters"])


def test_devprof_only_records_without_profile():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp
    from antidote_ccrdt_tpu.core import batch_merge
    from antidote_ccrdt_tpu.models.topk import TopkState

    events.reset("devprof-solo")
    m = Metrics()
    devprof.install(m)
    assert not profile.ACTIVE
    batch_merge.batch_merge(
        "topk", [TopkState({chr(97 + i): 2 * i + 1}, 3) for i in range(5)]
    )
    c = m.snapshot()["counters"]
    assert c["devprof.dispatches"] >= 3
    assert not any(k.startswith("profile.") for k in c)


# -- the seeded stepping drill (chaos_gate leg 12 imports this) -------------


def _canon(st):
    return (
        sorted((w, sorted(es)) for w, es in st.masked.items()),
        sorted((w, sorted(v.items())) for w, v in st.removals.items()),
        sorted(st.vc.items()),
        sorted(st.observed.items()),
        st.min,
        st.size,
    )


def run_devprof_drill(seed: int = 7, rounds: int = 6, workers: int = 3):
    """Seeded stepping fleet drill: `workers` topk_rmv scalar states grow
    one live add per id per round, and every round batch-merges the fleet
    — the shape growth provokes one recompile per round at
    batch_merge.fold, which the observatory must attribute to the
    slots-per-id axis. Runs an observed arm and a CCRDT_DEVPROF=0 arm on
    the same seed; the kill-switch arm must be byte-identical.

    Returns the dict chaos_gate leg 12 gates on."""
    pytest.importorskip("jax")
    from antidote_ccrdt_tpu.core import batch_merge
    from antidote_ccrdt_tpu.models.topk_rmv import TopkRmvScalar

    # Distinct `size` per seed: capacity is part of the engine-memo key,
    # so the drill always exercises fresh jit caches even after other
    # tests in the same process merged topk_rmv states.
    size = 17 + (seed % 13)

    def arm(observed):
        events.reset("devprof-drill")
        m = Metrics()
        if observed:
            devprof.install(m)
        else:
            assert devprof.install_from_env(
                m, env={devprof.ENV_FLAG: "0"}
            ) is False
        sc = TopkRmvScalar()
        states = [sc.new(size) for _ in range(workers)]
        merged = []
        for r in range(rounds):
            states = _step(sc, states, r, seed)
            merged.append(batch_merge.batch_merge("topk_rmv", list(states)))
        evs = [e for e in events.events() if e["kind"] == "devprof.compile"]
        counters = dict(m.snapshot()["counters"])
        devprof.uninstall()
        digest = hashlib.sha256(
            repr([_canon(s) for s in merged]).encode()
        ).hexdigest()
        return counters, evs, digest

    counters, evs, digest_on = arm(True)
    off_counters, off_evs, digest_off = arm(False)
    unattributed = sum(
        1
        for e in evs
        if not e.get("site") or not e.get("axis") or not e.get("signature")
    )
    growth = [
        e for e in evs if "slot_score" in e.get("axis", "")
        and "axis3" in e.get("axis", "")
    ]
    return {
        "counters": counters,
        "events": evs,
        "unattributed": unattributed,
        "n_compiles": len(evs),
        "n_capacity_growth": len(growth),
        "digest_on": digest_on,
        "digest_off": digest_off,
        "off_devprof_counters": sum(
            1 for k in off_counters if k.startswith("devprof.")
        ),
        "off_events": len(off_evs),
    }


def test_stepping_drill_attributes_every_compile():
    dv = run_devprof_drill(seed=7)
    assert dv["n_compiles"] >= 4  # the storm is real
    assert dv["unattributed"] == 0  # ...and fully attributed
    # topk_rmv capacity growth (slots-per-id axis) dominates the churn:
    # every compile after the first names the growing axis.
    assert dv["n_capacity_growth"] >= dv["n_compiles"] - 1
    assert dv["counters"]["devprof.compiles"] == dv["n_compiles"]
    assert dv["digest_on"] == dv["digest_off"]  # kill switch: bit-identical
    assert dv["off_devprof_counters"] == 0
    assert dv["off_events"] == 0
