"""XLA hot-path profiler (obs/profile.py): dispatch timing, jit
compile-vs-execute classification, transfer-byte accounting, the
zero-overhead disabled path, and the wired call sites in
core/batch_merge and parallel/elastic."""

import numpy as np
import pytest

from antidote_ccrdt_tpu.obs import profile
from antidote_ccrdt_tpu.utils.metrics import Metrics


@pytest.fixture(autouse=True)
def _always_uninstalled():
    # Module-global gate: never let one test's install leak into the
    # rest of the suite.
    profile.uninstall()
    yield
    profile.uninstall()


def test_dispatch_records_wall_time_and_bytes():
    m = Metrics()
    with profile.installed(m):
        assert profile.ACTIVE
        x = np.zeros(1024, np.int32)
        with profile.dispatch("unit.op", operands=(x, [x, {"k": x}])):
            pass
    assert not profile.ACTIVE
    snap = m.snapshot()
    assert len(snap["latencies"]["profile.dispatch.unit.op"]) == 1
    assert snap["counters"]["profile.h2d_bytes"] == 3 * 1024 * 4


def test_jit_hit_miss_classification():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    fn = jax.jit(lambda a, b: a + b)
    m = Metrics()
    with profile.installed(m):
        for shape in ((4,), (4,), (8,)):  # miss, hit, miss (new shape)
            a = jnp.zeros(shape, jnp.int32)
            with profile.dispatch("unit.add", fn=fn):
                fn(a, a).block_until_ready()
    snap = m.snapshot()
    assert snap["counters"]["profile.jit_misses"] == 2
    assert snap["counters"]["profile.jit_hits"] == 1
    assert len(snap["latencies"]["profile.compile.unit.add"]) == 2
    assert len(snap["latencies"]["profile.execute.unit.add"]) == 1
    assert len(snap["latencies"]["profile.dispatch.unit.add"]) == 3


def test_plain_function_still_times_without_classification():
    m = Metrics()
    with profile.installed(m):
        with profile.dispatch("unit.plain", fn=lambda: None):
            pass
    snap = m.snapshot()
    assert "profile.dispatch.unit.plain" in snap["latencies"]
    assert "profile.jit_hits" not in snap["counters"]
    assert "profile.jit_misses" not in snap["counters"]


def test_disabled_leaves_no_trace_and_batch_merge_unaffected():
    pytest.importorskip("jax")
    from antidote_ccrdt_tpu.core.batch_merge import batch_merge
    from antidote_ccrdt_tpu.models.topk import TopkState

    states = [
        TopkState({"a": 1, "b": 5}, 2),
        TopkState({"a": 7}, 2),
        TopkState({"c": 3}, 2),
    ]
    assert not profile.ACTIVE
    merged = batch_merge("topk", states)
    assert merged.entries == {"a": 7, "b": 5}


def test_batch_merge_fold_is_profiled():
    pytest.importorskip("jax")
    from antidote_ccrdt_tpu.core.batch_merge import batch_merge
    from antidote_ccrdt_tpu.models.topk import TopkState

    m = Metrics()
    states = [TopkState({chr(97 + i): i + 1}, 2) for i in range(5)]
    with profile.installed(m):
        merged = batch_merge("topk", states)
    assert merged.entries == {"e": 5, "d": 4}
    snap = m.snapshot()
    # 5 rows fold in 3 rounds: 5 -> 3 -> 2 -> 1.
    assert len(snap["latencies"]["profile.dispatch.batch_merge.fold"]) == 3
    assert snap["counters"]["profile.h2d_bytes"] > 0
    hits = snap["counters"].get("profile.jit_hits", 0)
    misses = snap["counters"].get("profile.jit_misses", 0)
    assert hits + misses == 3


def test_install_from_env_gating():
    m = Metrics()
    assert profile.install_from_env(m, env={}) is False
    assert not profile.ACTIVE
    assert profile.install_from_env(m, env={profile.ENV_FLAG: "0"}) is False
    assert profile.install_from_env(m, env={profile.ENV_FLAG: "1"}) is True
    assert profile.ACTIVE
    with profile.dispatch("unit.env"):
        pass
    assert "profile.dispatch.unit.env" in m.snapshot()["latencies"]


def test_elastic_sweep_is_profiled(tmp_path):
    pytest.importorskip("jax")
    from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense
    from antidote_ccrdt_tpu.parallel.elastic import GossipStore, sweep

    D = make_dense(n_ids=4, n_dcs=1, size=2, slots_per_id=1)
    a = GossipStore(str(tmp_path), "a")
    b = GossipStore(str(tmp_path), "b")
    sa, sb = D.init(1, 1), D.init(1, 1)
    a.publish("topk_rmv", sa, step=1)
    b.publish("topk_rmv", sb, step=1)
    m = Metrics()
    with profile.installed(m):
        _, n = sweep(a, D, sa)
    assert n == 1
    snap = m.snapshot()
    assert len(snap["latencies"]["profile.dispatch.elastic.sweep_merge"]) == 1
    assert snap["counters"]["profile.h2d_bytes"] > 0
