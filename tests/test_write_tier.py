"""Unit + drill tests for the fleet write tier (serve/ingest.py +
serve/write_session.py + obs.audit.certify_writes): idempotent re-ack
under duplicate delivery, `durable` acks racing the async-durability
watermark (honest downgrade, catch-up, and the deliberately-violating
ack-before-fsync arm), owner failover mid-batch vs a sequential
reference (the write_id dedup + CRDT stamp-dedup story), sim
``{write}``/``{write_ack}`` frame plumbing with wid echo and in-flight
cancel, admission control hints, client-certified replication, and the
write-durability certificate's conviction of acked-but-lost writes."""

import json
import threading
import time

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from antidote_ccrdt_tpu.harness.dense_replay import fold_rows
from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps, make_dense
from antidote_ccrdt_tpu.net.sim import SimNet
from antidote_ccrdt_tpu.obs import audit
from antidote_ccrdt_tpu.obs import events as obs_events
from antidote_ccrdt_tpu.serve.ingest import (
    ACK_APPLIED,
    ACK_DURABLE,
    ACK_REPLICATED,
    IngestPlane,
    WriteRouter,
)
from antidote_ccrdt_tpu.serve.plane import encode
from antidote_ccrdt_tpu.serve.routing_common import CLOSED
from antidote_ccrdt_tpu.serve.session import ClientSession
from antidote_ccrdt_tpu.serve.write_session import (
    WriteSession,
    effect_from_wire,
    effect_to_wire,
)
from antidote_ccrdt_tpu.topo import rendezvous_order
from antidote_ccrdt_tpu.utils import faults
from antidote_ccrdt_tpu.utils.metrics import Metrics


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.uninstall()
    yield
    faults.uninstall()


class _DrainLoop:
    """A real background thread standing in for the worker's round
    loop: drains the plane every couple of ms so transport threads
    blocked in `handle()` wake. seq advances per drain tick — the
    virtual "step" each fold lands in."""

    def __init__(self, plane, apply_fn=None, period_s=0.002):
        self.plane = plane
        self.applied = []
        self.seq = 0
        self._apply = apply_fn or self.applied.extend
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        while not self._stop.is_set():
            self.seq += 1
            self.plane.drain(self.seq, self._apply)
            time.sleep(0.002)

    def stop(self):
        self._stop.set()
        self._t.join(2.0)


def _wdoc(wid, ops=None, ack=ACK_DURABLE, **extra):
    doc = {
        "write_id": wid,
        "ops": ops if ops is not None else [["add", [1, 5, [0, 1000001]]]],
        "ack": ack,
    }
    doc.update(extra)
    return encode(doc)


def _plane(member="w0", **kw):
    kw.setdefault("durable_fn", lambda: 10**9)
    kw.setdefault("ack_timeout_s", 2.0)
    kw.setdefault("poll_s", 0.001)
    return IngestPlane(member, **kw)


# --- idempotent re-ack under duplicate delivery -----------------------------


def test_duplicate_delivery_reacks_original_seq():
    p = _plane()
    loop = _DrainLoop(p)
    try:
        a1 = json.loads(p.handle(_wdoc("c:1")).decode())
        a2 = json.loads(p.handle(_wdoc("c:1")).decode())
    finally:
        loop.stop()
    assert a1["write_ack"] and a1["level"] == ACK_DURABLE
    assert a2["duplicate"] is True
    assert (a2["origin"], a2["seq"]) == (a1["origin"], a1["seq"])
    # the duplicate never re-folded: exactly one op reached apply_fn.
    assert len(loop.applied) == 1
    c = p.metrics.snapshot()["counters"]
    assert c["ingest.duplicate_acks"] == 1
    assert c["ingest.applied"] == 1


def test_retry_after_apply_timeout_reacks_the_drain_time_ack():
    # An apply-timeout must NOT break idempotency: the write stays
    # registered in-flight and the drain records its ack, so a client
    # retry with the same write_id re-acks the original fold — at the
    # durability level it asks for — instead of applying a second time.
    p = _plane(ack_timeout_s=0.05)  # nobody drains: the first call times out
    out1 = json.loads(p.handle(_wdoc("c:9")).decode())
    assert out1["error"].startswith("unavailable")
    applied = []
    p.drain(17, applied.extend)  # the wedged round loop finally drains
    out2 = json.loads(p.handle(_wdoc("c:9")).decode())  # client retry
    assert out2["duplicate"] is True
    assert (out2["origin"], out2["seq"]) == ("w0", 17)
    assert out2["level"] == ACK_DURABLE  # upgraded against the fold's seq
    assert len(applied) == 1  # the retry never re-folded
    c = p.metrics.snapshot()["counters"]
    assert c["ingest.applied"] == 1
    assert c["ingest.duplicate_acks"] == 1
    assert c["ingest.apply_timeouts"] == 1


def test_concurrent_duplicate_deliveries_fold_once():
    # Two racing deliveries of one write_id (client retry overtaking a
    # slow original on the same worker) used to both miss the post-ack
    # cache and both enqueue. The in-flight registry parks the second
    # on the first's fold: one _PendingWrite, one apply, two acks.
    p = _plane(ack_timeout_s=2.0)
    acks = []
    acks_lock = threading.Lock()

    def deliver():
        out = json.loads(p.handle(_wdoc("c:7")).decode())
        with acks_lock:
            acks.append(out)

    ts = [threading.Thread(target=deliver, daemon=True) for _ in range(2)]
    for t in ts:
        t.start()
    deadline = time.monotonic() + 1.0
    while p.depth() < 1 and time.monotonic() < deadline:
        time.sleep(0.001)
    time.sleep(0.05)  # let the second delivery attach (not enqueue)
    assert p.depth() == 1  # ONE parked write, never two
    applied = []
    p.drain(5, applied.extend)
    for t in ts:
        t.join(3.0)
    assert len(applied) == 1  # the duplicate never reached apply_fn
    assert [a["seq"] for a in acks] == [5, 5]
    assert any(a.get("duplicate") for a in acks)
    assert p.metrics.snapshot()["counters"]["ingest.duplicate_acks"] == 1


# --- durable acks vs the async-durability watermark -------------------------


def test_durable_ack_downgrades_honestly_when_watermark_lags():
    # Async durability truncates the un-fsynced tail on recovery: a
    # watermark stuck behind the fold seq means the write could still
    # be lost, so the plane must NOT say "durable" — it reports the
    # level actually achieved plus what was requested.
    cell = [-1]
    p = _plane(durable_fn=lambda: cell[0], ack_timeout_s=0.15)
    loop = _DrainLoop(p)
    try:
        ack = json.loads(p.handle(_wdoc("c:1")).decode())
    finally:
        loop.stop()
    assert ack["level"] == ACK_APPLIED
    assert ack["requested"] == ACK_DURABLE
    assert p.metrics.snapshot()["counters"]["ingest.ack_downgrades"] == 1


def test_durable_ack_waits_out_the_racing_watermark():
    # The watermark catches up DURING the ack wait (the fsync landing
    # mid-race): the plane polls durable_fn and upgrades in place.
    cell = [-1]
    p = _plane(durable_fn=lambda: cell[0], ack_timeout_s=2.0)
    loop = _DrainLoop(p)
    flip = threading.Timer(0.05, lambda: cell.__setitem__(0, 10**9))
    flip.start()
    try:
        ack = json.loads(p.handle(_wdoc("c:1")).decode())
    finally:
        flip.cancel()
        loop.stop()
    assert ack["level"] == ACK_DURABLE
    assert p.metrics.snapshot()["counters"]["ingest.durable_acks"] == 1


def test_ack_before_fsync_arm_bills_unsafe_acks():
    # The deliberately-violating arm: durability claimed with the
    # watermark still at -1. The plane counts every lie so the demo's
    # certificate replay can convict it.
    p = _plane(durable_fn=lambda: -1, ack_before_fsync=True)
    loop = _DrainLoop(p)
    try:
        ack = json.loads(p.handle(_wdoc("c:1")).decode())
    finally:
        loop.stop()
    assert ack["level"] == ACK_DURABLE
    assert p.metrics.snapshot()["counters"]["ingest.unsafe_acks"] == 1


# --- admission control ------------------------------------------------------


def test_queue_full_sheds_with_retry_hint_and_blocked_write_times_out():
    p = _plane(queue_max=1, ack_timeout_s=0.1, durable_fn=None)
    first = {}

    def hold():
        first["ack"] = json.loads(p.handle(_wdoc("c:1")).decode())

    t = threading.Thread(target=hold, daemon=True)
    t.start()
    deadline = time.monotonic() + 1.0
    while p.depth() < 1 and time.monotonic() < deadline:
        time.sleep(0.001)
    shed = json.loads(p.handle(_wdoc("c:2")).decode())
    t.join(2.0)
    assert shed["error"].startswith("overloaded")
    assert isinstance(shed["retry_after_ms"], int) and shed["retry_after_ms"] >= 1
    # nobody drained: the parked write fails honestly, never hangs.
    assert first["ack"]["error"].startswith("unavailable")
    c = p.metrics.snapshot()["counters"]
    assert c["ingest.queue_shed"] == 1
    assert c["ingest.apply_timeouts"] == 1


def test_admission_bound_holds_under_concurrent_handlers():
    # The depth test and the append share one lock hold: N racing
    # handlers cannot all pass the bound and push the queue past
    # queue_max — exactly queue_max park, the rest shed honestly.
    p = _plane(queue_max=2, ack_timeout_s=1.0, durable_fn=None)
    outs = []
    outs_lock = threading.Lock()

    def deliver(i):
        out = json.loads(p.handle(_wdoc(f"c:{i}")).decode())
        with outs_lock:
            outs.append(out)

    ts = [
        threading.Thread(target=deliver, args=(i,), daemon=True)
        for i in range(8)
    ]
    for t in ts:
        t.start()
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline:
        c = p.metrics.snapshot()["counters"]
        if c.get("ingest.queue_shed", 0) >= 6:
            break
        time.sleep(0.005)
    assert p.depth() == 2  # never past queue_max
    applied = []
    p.drain(3, applied.extend)
    for t in ts:
        t.join(3.0)
    acked = [o for o in outs if o.get("write_ack")]
    shed = [
        o for o in outs
        if str(o.get("error", "")).startswith("overloaded")
    ]
    assert len(acked) == 2 and len(shed) == 6
    assert len(applied) == 2
    assert p.metrics.snapshot()["counters"]["ingest.queue_shed"] == 6


def test_pressure_probe_sheds_with_its_own_hint():
    p = _plane(pressure_fns=(lambda: 700,))
    shed = json.loads(p.handle(_wdoc("c:1")).decode())
    assert shed["error"].startswith("overloaded")
    assert shed["retry_after_ms"] == 700
    assert p.metrics.snapshot()["counters"]["ingest.pressure_shed"] == 1


# --- replication probes -----------------------------------------------------


def test_probe_answers_applied_coverage():
    p = _plane(watermarks_fn=lambda: {"w0": 9})
    yes = json.loads(p.handle(encode({"probe": {"origin": "w0", "seq": 5}})).decode())
    no = json.loads(p.handle(encode({"probe": {"origin": "w0", "seq": 12}})).decode())
    assert yes["covers"] is True and no["covers"] is False
    assert yes["watermarks"] == {"w0": 9}


# --- the write router -------------------------------------------------------


def _router(peers, write_fn, **kw):
    kw.setdefault("retries", 1)
    kw.setdefault("backoff_base_s", 0.0)
    kw.setdefault("backoff_max_s", 0.0)
    kw.setdefault("poll_s", 0.001)
    return WriteRouter(peers, write_fn, **kw)


def test_route_is_owner_first_and_drops_dead_peers():
    peers = ["a", "b", "c"]
    order = rendezvous_order("k0", peers)
    r = _router(peers, lambda *a: b"")
    assert r.route("k0") == order
    dead = order[0]
    r2 = _router(
        peers, lambda *a: b"",
        verdict_fn=lambda p: "dead" if p == dead else "alive",
    )
    assert r2.route("k0") == [p for p in order if p != dead]


def test_all_peer_sheds_propagate_retry_after_without_breaker_bills():
    def write_fn(peer, payload, timeout_s, cancel):
        return encode(
            {"error": "overloaded: test", "member": peer, "retry_after_ms": 123}
        )

    r = _router(["a", "b"], write_fn)
    out = r.write([["add", [1, 1, [0, 1]]]], "k0")
    assert out["error"] == "overloaded" and out["retry_after_ms"] == 123
    # admission control is not peer sickness: breakers stay closed.
    assert r.breaker("a").state == CLOSED and r.breaker("b").state == CLOSED
    assert r.metrics.snapshot()["counters"]["router.write_sheds"] >= 2


def test_exhausted_walk_returns_unavailable():
    def write_fn(peer, payload, timeout_s, cancel):
        raise ConnectionError("down")

    r = _router(["a", "b"], write_fn)
    out = r.write([["add", [1, 1, [0, 1]]]], "k0")
    assert out["error"] == "unavailable"
    assert r.metrics.snapshot()["counters"]["router.write_exhausted"] == 1


def test_replicated_to_k_certified_by_peer_probes():
    def write_fn(peer, payload, timeout_s, cancel):
        doc = json.loads(payload.decode())
        if "probe" in doc:
            return encode({"member": peer, "covers": True, "watermarks": {}})
        return encode({
            "write_ack": True, "member": peer, "origin": peer, "seq": 5,
            "level": ACK_DURABLE, "requested": ACK_REPLICATED,
        })

    r = _router(["a", "b", "c"], write_fn, replication_wait_s=1.0)
    out = r.write([["add", [1, 1, [0, 1]]]], "k0", ack=ACK_REPLICATED, k=2)
    assert out["level"] == ACK_REPLICATED
    assert out["replication"]["confirmed"] >= 2
    assert r.metrics.snapshot()["counters"]["router.replicated_acks"] == 1


def test_replication_shortfall_downgrades_honestly():
    def write_fn(peer, payload, timeout_s, cancel):
        doc = json.loads(payload.decode())
        if "probe" in doc:
            return encode({"member": peer, "covers": False, "watermarks": {}})
        return encode({
            "write_ack": True, "member": peer, "origin": peer, "seq": 5,
            "level": ACK_DURABLE, "requested": ACK_REPLICATED,
        })

    r = _router(
        ["a", "b"], write_fn,
        replication_wait_s=0.05, replication_poll_s=0.01,
    )
    out = r.write([["add", [1, 1, [0, 1]]]], "k0", ack=ACK_REPLICATED, k=2)
    assert out["level"] == ACK_DURABLE  # never above the truth
    assert out["replication"] == {"confirmed": 1, "want": 2}
    assert r.metrics.snapshot()["counters"]["router.replication_timeouts"] == 1


def test_ack_teaches_session_read_your_writes():
    def write_fn(peer, payload, timeout_s, cancel):
        return encode({
            "write_ack": True, "member": peer, "origin": peer, "seq": 7,
            "level": ACK_DURABLE, "requested": ACK_DURABLE,
        })

    sess = ClientSession(session_id="s-wt")
    r = _router(["a"], write_fn)
    out = r.write([["add", [1, 1, [0, 1]]]], "k0", session=sess)
    assert out["write_ack"]
    # the cross-tier hook: the READ router routes this session only to
    # peers whose applied watermarks cover (a, 7) from here on.
    assert sess.token.floor() == {"a": 7}


# --- owner failover mid-batch vs the sequential reference -------------------

_DCS = 2


def _fold(dense, state, effects):
    """Fold scalar add effects into replica row 0 — the single-row twin
    of the elastic demo drill's `ingest` fold."""
    adds = [p for k, p in effects if k in ("add", "add_r")]
    nb = max(len(adds), 1)
    a_id = np.zeros((1, nb), np.int32)
    a_score = np.zeros((1, nb), np.int32)
    a_dc = np.zeros((1, nb), np.int32)
    a_ts = np.zeros((1, nb), np.int32)
    for j, (id_, score, (dc, ts)) in enumerate(adds):
        a_id[0, j], a_score[0, j] = int(id_), int(score)
        a_dc[0, j], a_ts[0, j] = int(dc) % _DCS, int(ts)
    ops = TopkRmvOps(
        add_key=jnp.zeros((1, nb), jnp.int32), add_id=jnp.asarray(a_id),
        add_score=jnp.asarray(a_score), add_dc=jnp.asarray(a_dc),
        add_ts=jnp.asarray(a_ts),
        rmv_key=jnp.zeros((1, 1), jnp.int32),
        rmv_id=jnp.full((1, 1), -1, jnp.int32),
        rmv_vc=jnp.zeros((1, 1, _DCS), jnp.int32),
    )
    state, _ = dense.apply_ops(state, ops, collect_dominated=False)
    return state


def _digest(dense, state):
    obs = dense.value(fold_rows(dense, state, range(1)))[0][0]
    return sorted((int(i), int(s)) for (i, s) in obs)


class _Worker:
    def __init__(self, name, dense):
        self.name = name
        self.dense = dense
        self.state = dense.init(1, 1)
        self._lock = threading.Lock()
        self.plane = _plane(name)
        self.loop = _DrainLoop(self.plane, self._apply)

    def _apply(self, ops):
        effects = [effect_from_wire(o) for o in ops]
        with self._lock:
            self.state = _fold(self.dense, self.state, effects)

    def stop(self):
        self.loop.stop()


def test_owner_failover_mid_batch_matches_sequential_reference():
    # Worst-case duplicate fold: the owner APPLIES every batch, then the
    # ack is lost on the wire. The router fails over to the successor
    # with the SAME write_id; the successor (a different plane — no
    # dedup cache to help) folds the batch again. Convergence must still
    # hold: after merging both workers, the (dc, ts)-stamped adds dedup
    # under join and the fleet equals a sequential reference that saw
    # each effect exactly once.
    dense = make_dense(n_ids=32, n_dcs=_DCS, size=8, slots_per_id=2)
    # Fresh recorder: the process ring is bounded, so a full-suite run
    # may have filled it already — an index slice over the ring would
    # miss this drill's folds once eviction starts.
    obs_events.reset("failover-drill")
    wa, wb = _Worker("A", dense), _Worker("B", dense)
    planes = {"A": wa.plane, "B": wb.plane}
    drops = {"n": 0}

    def write_fn(peer, payload, timeout_s, cancel):
        raw = planes[peer].handle(payload, surface="test")
        if peer == "A":
            drops["n"] += 1
            raise ConnectionError("ack lost after fold")
        return raw

    r = _router(["A", "B"], write_fn, retries=2, timeout_s=5.0)
    # A key whose rendezvous OWNER is A — the failover path must start
    # at the worker that folds-then-drops.
    key = next(
        f"k{i}" for i in range(64)
        if rendezvous_order(f"k{i}", ["A", "B"])[0] == "A"
    )
    rng = np.random.default_rng(7)  # seeded drill
    ids = [int(i) for i in rng.permutation(32)[:16]]
    effects = [
        ("add", (ids[i], (i + 1) * 3, (i % _DCS, 1_000_000 + i)))
        for i in range(16)
    ]
    try:
        for lo in range(0, 16, 4):
            batch = [effect_to_wire(e) for e in effects[lo:lo + 4]]
            out = r.write(batch, key=key, ack=ACK_DURABLE,
                          write_id=f"c:{lo}")
            assert out.get("write_ack"), out
            assert out["peer"] == "B"  # failover completed every batch
        # Acks are synchronous, so every fold event is on the ring by
        # now; capture before the recorder is restored below.
        folds = obs_events.events("ingest.fold")
    finally:
        wa.stop()
        wb.stop()
        obs_events.reset("?")
    # A really folded batches before the acks were lost; after three
    # straight failures its breaker opens and the remaining batches go
    # straight to B — duplicate folds AND breaker-skipped folds both
    # land in the same merge.
    assert drops["n"] == 3
    c = r.metrics.snapshot()["counters"]
    assert c["router.write_failovers"] >= 3
    assert c["router.write_breaker_opens"] >= 1
    merged = dense.merge(wa.state, wb.state)
    ref = _fold(dense, dense.init(1, 1), effects)
    assert _digest(dense, merged) == _digest(dense, ref)
    # The at-least-once failover duplicates are NOT invisible: each
    # plane emitted ingest.fold per write_id, and the strict
    # exactly-once certificate convicts the cross-member re-folds the
    # join just absorbed (the honest contract for non-idempotent ops).
    # (Fold events only: this in-process drill has no WAL evidence, so
    # the durability axis would convict vacuously and mask the check.)
    strict = audit.certify_writes(
        logs={"drill": folds}, strict_exactly_once=True
    )
    assert strict["duplicates"]["n_duplicated"] == drops["n"]
    assert strict["ok"] is False
    dup = strict["counterexample"]["duplicate_applications"][0]
    assert {f["member"] for f in dup["folds"]} == {"A", "B"}
    # ...while the default certificate reports them without convicting.
    loose = audit.certify_writes(logs={"drill": folds})
    assert loose["ok"] is True
    assert loose["duplicates"]["n_duplicated"] == drops["n"]


# --- sim transport plumbing -------------------------------------------------


def test_sim_write_frames_roundtrip_with_wid_echo():
    net = SimNet(seed=3, latency=(0.001, 0.002))
    a = net.join("a")
    b = net.join("b")
    p = _plane("b")
    loop = _DrainLoop(p)
    b.install_ingest(p)
    try:
        a.write("b", _wdoc("x:1"), wid=b"x:1")
        net.run_until(net.time + 1.0)
    finally:
        loop.stop()
    assert b"x:1" in a.write_results
    who, raw = a.write_results[b"x:1"]
    ack = json.loads(raw.decode())
    assert who == "b" and ack["write_ack"] and ack["origin"] == "b"
    assert net.metrics.snapshot()["counters"]["net.writes"] == 1


def test_sim_cancelled_write_ack_is_dropped_in_flight():
    net = SimNet(seed=3, latency=(0.001, 0.002))
    a = net.join("a")
    b = net.join("b")
    p = _plane("b")
    loop = _DrainLoop(p)
    b.install_ingest(p)
    try:
        a.write("b", _wdoc("x:2"), wid=b"x:2")
        a.cancel_write(b"x:2")  # router failed over before the ack
        net.run_until(net.time + 1.0)
    finally:
        loop.stop()
    assert b"x:2" not in a.write_results
    c = net.metrics.snapshot()["counters"]
    assert c["net.write_cancelled_drops"] == 1


def test_sim_write_without_plane_degrades_honestly():
    net = SimNet(seed=3, latency=(0.001, 0.002))
    a = net.join("a")
    net.join("b")  # no ingest plane installed
    a.write("b", _wdoc("x:3"), wid=b"x:3")
    net.run_until(net.time + 1.0)
    _who, raw = a.write_results[b"x:3"]
    assert json.loads(raw.decode())["error"] == "no ingest plane"


# --- the write session (client-edge batching) -------------------------------


def test_write_session_compacts_burst_and_ships_one_frame():
    p = _plane("w0")
    loop = _DrainLoop(p)
    sess = ClientSession(session_id="s-ws")
    r = _router(
        ["w0"],
        lambda peer, payload, t, c: p.handle(payload, surface="test"),
    )
    ws = WriteSession(
        r, "topk_rmv", session=sess, session_id="c0", m_keep=2,
    )
    try:
        # 8 adds for ONE id: the dense model keeps slots_per_id slots,
        # so compaction (m_keep=2) may ship at most 2 survivors.
        for i in range(8):
            ws.stage("k0", ("add", (7, 10 + i, (0, 1_000_100 + i))))
        res = ws.flush()
    finally:
        loop.stop()
    assert len(res) == 1 and res[0].get("write_ack"), res
    assert res[0]["raw_ops"] == 8 and res[0]["shipped_ops"] <= 2
    assert ws.coalesce_ratio() >= 4.0
    # the burst hit the plane as ONE CCRF range frame...
    c = p.metrics.snapshot()["counters"]
    assert c["ingest.range_frames"] == 1
    assert c["ingest.writes"] == 1
    # ...and the ack taught the session its own (origin, seq).
    assert sess.token.floor() == {"w0": res[0]["seq"]}


def test_effect_wire_roundtrip():
    effects = [
        ("add", (3, 50, (1, 1000007))),
        ("rmv", (3, {0: 12, 1: 9})),
    ]
    assert [effect_from_wire(effect_to_wire(e)) for e in effects] == effects


# --- the write-durability certificate ---------------------------------------


def _acks(origin, through, level=ACK_DURABLE):
    return [
        {"kind": "ingest.ack", "member": "client", "origin": origin,
         "wseq": s, "level": level, "write_id": f"c:{s}"}
        for s in range(1, through + 1)
    ]


def test_certify_writes_convicts_acked_but_lost():
    # Durable acks through 20, fsync evidence through 12, no clean
    # exit, no survivor coverage: [13, 20] is acked-but-lost.
    logs = {
        "client": _acks("w1", 20),
        "w1": [{"kind": "wal.durable", "member": "w1", "through": 12}],
    }
    cert = audit.certify_writes(logs=logs)
    assert cert["ok"] is False
    ce = cert["counterexample"]["acked_but_lost"][0]
    assert ce["origin"] == "w1"
    assert ce["uncovered"] == [13, 20]
    assert "c:13" in ce["lost_write_ids"]
    assert audit.verify_certificate(cert)


def test_certify_writes_passes_on_fsync_coverage():
    logs = {
        "client": _acks("w1", 20),
        "w1": [{"kind": "wal.durable", "member": "w1", "through": 20}],
    }
    cert = audit.certify_writes(logs=logs)
    assert cert["ok"] is True and audit.verify_certificate(cert)


def test_certify_writes_accepts_survivor_coverage():
    # The owner's disk burned, but a surviving member applied the
    # origin's delta stream through the acked seq: the fleet holds it.
    logs = {
        "client": _acks("w1", 20),
        "w1": [{"kind": "wal.append", "member": "w1", "seq": 1}],
        "w2": [{"kind": "delta.apply", "member": "w2", "origin": "w1",
                "dseq": 20}],
    }
    cert = audit.certify_writes(logs=logs)
    assert cert["ok"] is True


def test_certify_writes_duplicate_folds_reported_and_strictly_convicted():
    # Owner w1 folded c:5, died before its ack shipped, and the
    # successor w2 folded it again: the fold evidence names both sites.
    # Default contract (at-least-once, join absorbs) reports; strict
    # exactly-once convicts with the duplicated write_ids.
    logs = {
        "client": _acks("w1", 5),
        "w1": [
            {"kind": "wal.durable", "member": "w1", "through": 9},
            {"kind": "ingest.fold", "member": "w1", "wseq": 5,
             "write_id": "c:5"},
        ],
        "w2": [
            {"kind": "ingest.fold", "member": "w2", "wseq": 7,
             "write_id": "c:5"},
        ],
    }
    cert = audit.certify_writes(logs=logs)
    assert cert["ok"] is True
    assert cert["duplicates"]["n_folded_write_ids"] == 1
    assert cert["duplicates"]["n_duplicated"] == 1
    assert cert["duplicates"]["examples"][0]["write_id"] == "c:5"
    strict = audit.certify_writes(logs=logs, strict_exactly_once=True)
    assert strict["ok"] is False
    assert strict["checks"]["exactly_once_application"] is False
    dup = strict["counterexample"]["duplicate_applications"][0]
    assert dup["write_id"] == "c:5"
    assert {f["member"] for f in dup["folds"]} == {"w1", "w2"}
    assert audit.verify_certificate(strict)


def test_certify_writes_never_convicts_applied_level():
    # `applied` promises nothing across a crash: reported, not convicted.
    logs = {
        "client": _acks("w1", 20, level=ACK_APPLIED),
        "w1": [{"kind": "wal.append", "member": "w1", "seq": 1}],
    }
    cert = audit.certify_writes(logs=logs)
    assert cert["ok"] is True
    assert cert["acks_by_level"] == {ACK_APPLIED: 20}
