"""Golden state-transition tests for scalar topk_rmv, ported from the
reference EUnit suite (antidote_ccrdt_topk_rmv.erl:411-593) as cross-checks
against reference semantics."""

import pytest

from antidote_ccrdt_tpu.core.clock import LogicalClock, ReplicaContext
from antidote_ccrdt_tpu.models.topk_rmv import (
    NIL,
    TopkRmvScalar,
    TopkRmvState,
    _cmp,
    _merge_vcs,
)

T = TopkRmvScalar()


def ctx_with_clock(dc=0):
    return ReplicaContext(dc_id=dc, clock=LogicalClock(), dc_index=dc)


def test_mixed():
    """Port of mixed_test (topk_rmv.erl:416-519)."""
    ctx = ctx_with_clock()
    dc = ctx.dc_id
    size = 2
    top = T.new(size)
    assert top == TopkRmvState({}, {}, {}, {}, NIL, size)

    # add(1, 2) -> observable add
    op1 = T.downstream(("add", (1, 2)), top, ctx)
    t1 = ctx.clock.get_time()
    e1 = (2, 1, (dc, t1))
    assert op1 == ("add", (1, 2, (dc, t1)))
    top1, extra = T.update(op1, top)
    assert extra == []
    assert top1 == TopkRmvState(
        {1: e1}, {1: frozenset([e1])}, {}, {dc: t1}, e1, size
    )

    # add(2, 2) -> observable add (room for two)
    op2 = T.downstream(("add", (2, 2)), top1, ctx)
    t2 = ctx.clock.get_time()
    e2 = (2, 2, (dc, t2))
    assert op2 == ("add", (2, 2, (dc, t2)))
    top2, _ = T.update(op2, top1)
    assert top2 == TopkRmvState(
        {1: e1, 2: e2},
        {1: frozenset([e1]), 2: frozenset([e2])},
        {},
        {dc: t2},
        e1,
        size,
    )

    # add(1, 0): dominated by the current observed elem for id 1 -> add_r
    op3 = T.downstream(("add", (1, 0)), top2, ctx)
    t3 = ctx.clock.get_time()
    e3 = (0, 1, (dc, t3))
    assert op3 == ("add_r", (1, 0, (dc, t3)))
    top3, _ = T.update(op3, top2)
    assert top3 == TopkRmvState(
        {1: e1, 2: e2},
        {1: frozenset([e1, e3]), 2: frozenset([e2])},
        {},
        {dc: t3},
        e1,
        size,
    )

    # rmv of an id nobody has seen -> noop
    assert T.downstream(("rmv", 100), top3, ctx) is None

    # add(100, 1): top is full and 1 < min score -> add_r
    op4 = T.downstream(("add", (100, 1)), top3, ctx)
    t4 = ctx.clock.get_time()
    e4 = (1, 100, (dc, t4))
    assert op4 == ("add_r", (100, 1, (dc, t4)))
    top4, _ = T.update(op4, top3)
    assert top4 == TopkRmvState(
        {1: e1, 2: e2},
        {1: frozenset([e1, e3]), 2: frozenset([e2]), 100: frozenset([e4])},
        {},
        {dc: t4},
        e1,
        size,
    )

    # rmv(1): removes observed id 1, promotes masked id 100, and the
    # promotion is re-broadcast as an extra add op (topk_rmv.erl:291-295).
    op5 = T.downstream(("rmv", 1), top4, ctx)
    vc = {dc: t4}
    assert op5 == ("rmv", (1, vc))
    top5, extras = T.update(op5, top4)
    assert extras == [("add", (100, 1, (dc, t4)))]
    assert top5 == TopkRmvState(
        {2: e2, 100: e4},
        {2: frozenset([e2]), 100: frozenset([e4])},
        {1: vc},
        {dc: t4},
        e4,
        size,
    )


def test_masked_delete():
    """Port of masked_delete_test (topk_rmv.erl:522-554)."""
    ctx = ctx_with_clock()
    dc = ctx.dc_id
    top = T.new(1)
    top1, _ = T.update(("add", (1, 42, (dc, 1))), top)
    top2, _ = T.update(("add", (2, 5, (dc, 2))), top1)
    rmv_op = T.downstream(("rmv", 2), top2, ctx)
    # id 2 is masked but not observed -> tagged removal
    assert rmv_op == ("rmv_r", (2, {dc: 2}))
    top3, extras = T.update(rmv_op, top2)
    assert extras == []
    e1 = (42, 1, (dc, 1))
    assert top3 == TopkRmvState(
        {1: e1}, {1: frozenset([e1])}, {2: {dc: 2}}, {dc: 2}, e1, 1
    )
    # Re-adding the removed element bounces the stored removal back out.
    top4, extras = T.update(("add", (2, 5, (dc, 2))), top3)
    assert extras == [("rmv", (2, {dc: 2}))]
    assert top4 == top3
    # Removal of a never-seen id just records the tombstone.
    top5, extras = T.update(("rmv", (50, {dc: 42})), top4)
    assert extras == []
    assert top5 == TopkRmvState(
        {1: e1},
        {1: frozenset([e1])},
        {2: {dc: 2}, 50: {dc: 42}},
        {dc: 2},
        e1,
        1,
    )


def test_merge_vcs():
    """Port of simple_merge_vc_test (topk_rmv.erl:557-569)."""
    assert _merge_vcs({}, {"a": 3}) == {"a": 3}
    assert _merge_vcs({"a": 3}, {"a": 3}) == {"a": 3}
    assert _merge_vcs({"a": 3}, {"a": 5}) == {"a": 5}
    assert _merge_vcs({"a": 3, "b": 7}, {"a": 5}) == {"a": 5, "b": 7}


def test_delete_semantics():
    """Port of delete_semantics_test (topk_rmv.erl:572-593): two simulated
    DCs, ops shipped across, convergence + add-after-remove bounce."""
    ctx = ctx_with_clock()
    dc = ctx.dc_id
    dc1_top = T.new(1)
    dc2_top = T.new(1)
    id_ = 1
    add_op = T.downstream(("add", (id_, 45)), dc1_top, ctx)
    dc1_top2, _ = T.update(add_op, dc1_top)
    add_op2 = T.downstream(("add", (id_, 50)), dc1_top, ctx)
    t2 = ctx.clock.get_time()
    assert add_op2 == ("add", (id_, 50, (dc, t2)))
    dc1_top3, _ = T.update(add_op2, dc1_top2)
    dc2_top2, _ = T.update(add_op2, dc2_top)
    del_op = T.downstream(("rmv", id_), dc2_top2, ctx)
    dc2_top3, _ = T.update(del_op, dc2_top2)
    dc1_top4, _ = T.update(del_op, dc1_top3)
    assert dc1_top4 == TopkRmvState(
        {}, {}, {id_: {dc: t2}}, {dc: t2}, NIL, 1
    )
    assert dc1_top4 == dc2_top3
    # Applying the earlier (already-dominated) add on DC2 re-broadcasts the rmv.
    dc2_top4, extras = T.update(add_op, dc2_top3)
    assert extras == [del_op]
    assert dc2_top4 == dc2_top3


def test_cmp_order():
    assert _cmp((2, 1, (0, 1)), NIL)
    assert not _cmp(NIL, (2, 1, (0, 1)))
    assert _cmp((3, 1, (0, 1)), (2, 9, (0, 9)))  # score dominates
    assert _cmp((2, 2, (0, 1)), (2, 1, (0, 9)))  # id breaks ties
    assert _cmp((2, 1, (0, 5)), (2, 1, (0, 1)))  # ts breaks ties
    assert not _cmp((2, 1, (0, 1)), (2, 1, (0, 1)))


def test_value_and_equal():
    ctx = ctx_with_clock()
    top = T.new(2)
    op = T.downstream(("add", (7, 10)), top, ctx)
    top1, _ = T.update(op, top)
    assert T.value(top1) == [(7, 10)]
    top_b, _ = T.update(op, T.new(2))
    assert T.equal(top1, top_b)
    # equal ignores non-observable fields (topk_rmv.erl:151-153)
    top_c = top_b._replace(removals={99: {0: 5}})
    assert T.equal(top1, top_c)
    assert not T.equal(top1, T.new(2))


def test_serialization_roundtrip():
    ctx = ctx_with_clock()
    top = T.new(3)
    for i, (idv, s) in enumerate([(1, 10), (2, 20), (3, 30), (1, 5)]):
        op = T.downstream(("add", (idv, s)), top, ctx)
        top, _ = T.update(op, top)
    rmv = T.downstream(("rmv", 2), top, ctx)
    top, _ = T.update(rmv, top)
    blob = T.to_binary(top)
    restored = T.from_binary(blob)
    assert restored == top


def test_compaction_rules():
    """topk_rmv.erl:178-223: the pairwise compaction protocol."""
    a1 = ("add", (1, 10, (0, 1)))
    a2 = ("add", (1, 20, (0, 2)))
    assert T.can_compact(a1, a2)
    c1, c2 = T.compact_ops(a1, a2)
    # keep-best, demote the other to a tagged add
    assert c1 == ("add_r", (1, 10, (0, 1)))
    assert c2 == ("add", (1, 20, (0, 2)))
    c1, c2 = T.compact_ops(a2, a1)
    assert c1 == ("add", (1, 20, (0, 2)))
    assert c2 == ("add_r", (1, 10, (0, 1)))

    # different ids never compact
    assert not T.can_compact(a1, ("add", (2, 10, (0, 3))))

    # add dominated by rmv: add dies
    r = ("rmv", (1, {0: 5}))
    assert T.can_compact(a1, r)
    assert T.compact_ops(a1, r) == (None, r)
    # add NOT dominated (newer ts) does not compact
    a_new = ("add", (1, 10, (0, 9)))
    assert not T.can_compact(a_new, r)
    # (add, rmv_r) has no compaction clause in the reference
    assert not T.can_compact(a1, ("rmv_r", (1, {0: 5})))

    # rmv/rmv vc-merge
    r1 = ("rmv", (1, {0: 5, 1: 2}))
    r2 = ("rmv_r", (1, {1: 7}))
    assert T.can_compact(r1, r2)
    c1, c2 = T.compact_ops(r1, r2)
    assert c1 is None
    assert c2 == ("rmv", (1, {0: 5, 1: 7}))
    # rmv_r pair stays tagged
    c1, c2 = T.compact_ops(("rmv_r", (1, {0: 1})), ("rmv_r", (1, {2: 3})))
    assert c2[0] == "rmv_r"


def test_is_operation_and_tagging():
    assert T.is_operation(("add", (1, 2)))
    assert T.is_operation(("rmv", 1))
    assert not T.is_operation(("add", 1))
    assert not T.is_operation(("ban", 1))
    assert T.is_replicate_tagged(("add_r", (1, 2, (0, 1))))
    assert T.is_replicate_tagged(("rmv_r", (1, {})))
    assert not T.is_replicate_tagged(("add", (1, 2, (0, 1))))
    assert T.require_state_downstream(("add", (1, 2)))
