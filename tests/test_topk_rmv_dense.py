"""Dense topk_rmv kernels: differential tests against the scalar
(reference-semantics) implementation, batch-order independence, and the
merge lattice laws."""

import numpy as np
import pytest
from conftest import HealthCheck, given, settings, st  # noqa: E402  (hypothesis or skip-stub)

import jax
import jax.numpy as jnp

from antidote_ccrdt_tpu.core.clock import LogicalClock, ReplicaContext
from antidote_ccrdt_tpu.models.topk_rmv import TopkRmvScalar
from antidote_ccrdt_tpu.models.topk_rmv_dense import (
    TopkRmvOps,
    make_dense,
)

S = TopkRmvScalar()


def pack_ops(effects, n_dcs, add_pad, rmv_pad):
    """Pack scalar effect ops into one TopkRmvOps batch (single replica)."""
    adds = [e for e in effects if e[0] in ("add", "add_r")]
    rmvs = [e for e in effects if e[0] in ("rmv", "rmv_r")]
    B, Br = max(add_pad, len(adds)), max(rmv_pad, len(rmvs))
    a_key = np.zeros(B, np.int32)
    a_id = np.zeros(B, np.int32)
    a_score = np.zeros(B, np.int32)
    a_dc = np.zeros(B, np.int32)
    a_ts = np.zeros(B, np.int32)  # 0 = padding
    for j, (_, (id_, score, (dc, ts))) in enumerate(adds):
        a_id[j], a_score[j], a_dc[j], a_ts[j] = id_, score, dc, ts
    r_key = np.zeros(Br, np.int32)
    r_id = np.full(Br, -1, np.int32)  # -1 = padding
    r_vc = np.zeros((Br, n_dcs), np.int32)
    for j, (_, (id_, vc)) in enumerate(rmvs):
        r_id[j] = id_
        for dc, ts in vc.items():
            r_vc[j, dc] = ts
    return TopkRmvOps(
        add_key=jnp.asarray(a_key[None]),
        add_id=jnp.asarray(a_id[None]),
        add_score=jnp.asarray(a_score[None]),
        add_dc=jnp.asarray(a_dc[None]),
        add_ts=jnp.asarray(a_ts[None]),
        rmv_key=jnp.asarray(r_key[None]),
        rmv_id=jnp.asarray(r_id[None]),
        rmv_vc=jnp.asarray(r_vc[None]),
    )


def observed_set(dense, state, r=0, nk=0):
    return set(map(tuple, dense.value(state)[r][nk]))


def scalar_value_set(state):
    return set(S.value(state))


def gen_effect_log(rng, n_ops, n_ids, n_dcs, size, rmv_frac=0.25):
    """Generate a causally-consistent effect log by running prepare ops
    through scalar downstream at a single evolving origin."""
    ctxs = [ReplicaContext(dc_id=d, clock=LogicalClock(1000 * d)) for d in range(n_dcs)]
    origin = S.new(size)
    log = []
    for _ in range(n_ops):
        ctx = ctxs[rng.integers(n_dcs)]
        if rng.random() < rmv_frac:
            op = ("rmv", int(rng.integers(n_ids)))
        else:
            op = ("add", (int(rng.integers(n_ids)), int(rng.integers(1, 1000))))
        eff = S.downstream(op, origin, ctx)
        if eff is None:
            continue
        origin, _extras = S.update(eff, origin)
        log.append(eff)
    return origin, log


def test_simple_adds_and_observe():
    D = make_dense(n_ids=8, n_dcs=2, size=2, slots_per_id=4)
    st = D.init(n_replicas=2, n_keys=1)
    effects = [
        ("add", (1, 50, (0, 1))),
        ("add", (2, 30, (0, 2))),
        ("add", (3, 99, (1, 1))),
    ]
    ops = pack_ops(effects, 2, 4, 2)
    ops2 = jax.tree.map(lambda x: jnp.concatenate([x, x], axis=0), ops)
    st, extras = D.apply_ops(st, ops2)
    assert observed_set(D, st, r=0) == {(3, 99), (1, 50)}
    assert observed_set(D, st, r=1) == {(3, 99), (1, 50)}
    assert not bool(extras.dominated.any())
    assert not bool(st.lossy.any())


def test_differential_vs_scalar_single_batch():
    rng = np.random.default_rng(0)
    for trial in range(5):
        n_ids, n_dcs, size = 24, 3, 5
        origin, log = gen_effect_log(rng, 120, n_ids, n_dcs, size)
        D = make_dense(n_ids=n_ids, n_dcs=n_dcs, size=size, slots_per_id=32)
        st = D.init(n_replicas=1, n_keys=1)
        st, _ = D.apply_ops(st, pack_ops(log, n_dcs, 128, 64))
        assert observed_set(D, st) == scalar_value_set(origin), f"trial {trial}"
        assert not bool(st.lossy.any()), f"trial {trial}: capacity overflow"


def test_differential_vs_scalar_multi_batch():
    """Splitting the same log into several sequential batches must agree
    with the scalar fold (join associativity over batches)."""
    rng = np.random.default_rng(7)
    n_ids, n_dcs, size = 16, 2, 4
    origin, log = gen_effect_log(rng, 90, n_ids, n_dcs, size)
    D = make_dense(n_ids=n_ids, n_dcs=n_dcs, size=size, slots_per_id=8)
    for n_chunks in (2, 3, 5):
        st = D.init(n_replicas=1, n_keys=1)
        for chunk in np.array_split(np.arange(len(log)), n_chunks):
            effects = [log[i] for i in chunk]
            st, _ = D.apply_ops(st, pack_ops(effects, n_dcs, 64, 32))
        assert observed_set(D, st) == scalar_value_set(origin), n_chunks
        assert not bool(st.lossy.any())


def test_batch_partition_independence():
    """Any partition of a causal log into batches yields the same state."""
    rng = np.random.default_rng(3)
    n_ids, n_dcs, size = 12, 2, 3
    _, log = gen_effect_log(rng, 60, n_ids, n_dcs, size)
    D = make_dense(n_ids=n_ids, n_dcs=n_dcs, size=size, slots_per_id=8)
    results = []
    for n_chunks in (1, 2, 4, 8):
        st = D.init(n_replicas=1, n_keys=1)
        for chunk in np.array_split(np.arange(len(log)), n_chunks):
            st, _ = D.apply_ops(st, pack_ops([log[i] for i in chunk], n_dcs, 64, 32))
        results.append(st)
    for other in results[1:]:
        assert D.equal(results[0], other)


def test_add_wins_delete_semantics():
    """Dense port of delete_semantics_test (topk_rmv.erl:572-593): a removal
    kills only causally-seen adds; concurrent adds survive."""
    D = make_dense(n_ids=4, n_dcs=2, size=1, slots_per_id=4)
    st = D.init(n_replicas=2, n_keys=1)
    # DC0 adds id=1 score=45 @ts1, then score=50 @ts2; both replicas see both.
    adds = [("add", (1, 45, (0, 1))), ("add", (1, 50, (0, 2)))]
    ops = pack_ops(adds, 2, 4, 2)
    ops = jax.tree.map(lambda x: jnp.concatenate([x, x], axis=0), ops)
    st, _ = D.apply_ops(st, ops)
    assert observed_set(D, st, 0) == {(1, 50)} == observed_set(D, st, 1)
    # Removal with vc {0: 2} (saw both adds) -> id fully removed everywhere.
    rmv = [("rmv", (1, {0: 2}))]
    ops = pack_ops(rmv, 2, 4, 2)
    ops = jax.tree.map(lambda x: jnp.concatenate([x, x], axis=0), ops)
    st, _ = D.apply_ops(st, ops)
    assert observed_set(D, st, 0) == set() == observed_set(D, st, 1)
    # A concurrent add (ts 3 > vc[0]=2) wins over the tombstone.
    conc = [("add", (1, 10, (0, 3)))]
    ops = pack_ops(conc, 2, 4, 2)
    ops = jax.tree.map(lambda x: jnp.concatenate([x, x], axis=0), ops)
    st, extras = D.apply_ops(st, ops)
    assert observed_set(D, st, 0) == {(1, 10)}
    assert not bool(extras.dominated.any())
    # Re-delivering the dominated add (ts 1 <= 2) flags a re-broadcast with
    # the stored tombstone vc (topk_rmv.erl:234-237).
    old = [("add", (1, 45, (0, 1)))]
    ops = pack_ops(old, 2, 4, 2)
    ops = jax.tree.map(lambda x: jnp.concatenate([x, x], axis=0), ops)
    st2, extras = D.apply_ops(st, ops)
    assert bool(extras.dominated[0, 0])
    assert extras.dominated_vc[0, 0].tolist() == [2, 0]
    assert observed_set(D, st2, 0) == {(1, 10)}  # state unchanged


def test_promotions_collected():
    """Dense equivalent of the mixed_test promotion step (topk_rmv.erl:504-519):
    removing an observed id uncovers a masked one, reported as promoted."""
    D = make_dense(n_ids=128, n_dcs=1, size=2, slots_per_id=4)
    st = D.init(n_replicas=1, n_keys=1)
    adds = [
        ("add", (1, 2, (0, 1))),
        ("add", (2, 2, (0, 2))),
        ("add", (100, 1, (0, 4))),  # masked: board is full
    ]
    st, _ = D.apply_ops(st, pack_ops(adds, 1, 4, 2))
    assert observed_set(D, st) == {(1, 2), (2, 2)}
    rmv = [("rmv", (1, {0: 4}))]
    st, extras = D.apply_ops(
        st, pack_ops(rmv, 1, 4, 2), collect_promotions=True
    )
    assert observed_set(D, st) == {(2, 2), (100, 1)}
    promoted = extras.promoted
    got = [
        (int(promoted.ids[0, 0, j]), int(promoted.scores[0, 0, j]))
        for j in range(promoted.ids.shape[-1])
        if bool(promoted.valid[0, 0, j])
    ]
    assert got == [(100, 1)]


def test_merge_lattice_laws():
    """Merge is commutative, associative, idempotent (JOIN algebra)."""
    rng = np.random.default_rng(11)
    n_ids, n_dcs, size = 16, 3, 4
    D = make_dense(n_ids=n_ids, n_dcs=n_dcs, size=size, slots_per_id=8)

    def random_state(seed):
        r = np.random.default_rng(seed)
        _, log = gen_effect_log(r, 50, n_ids, n_dcs, size)
        st = D.init(n_replicas=1, n_keys=1)
        st, _ = D.apply_ops(st, pack_ops(log, n_dcs, 64, 32))
        return st

    a, b, c = random_state(1), random_state(2), random_state(3)
    assert D.equal(D.merge(a, b), D.merge(b, a))
    assert D.equal(D.merge(D.merge(a, b), c), D.merge(a, D.merge(b, c)))
    assert D.equal(D.merge(a, a), a)
    # merge with bottom is identity
    bot = D.init(n_replicas=1, n_keys=1)
    assert D.equal(D.merge(a, bot), a)


def test_union_join_matches_pairwise_join():
    """The production join on both hot paths (`_join_slots_union`,
    single 2M x 2M compare matrix, benchmarks/merge_probe2.py
    restructuring) is slot-for-slot identical to the independently-
    derived pairwise reference `_join_slots` — exact array equality,
    not just observable equality, across randomized divergent states."""
    from antidote_ccrdt_tpu.models.topk_rmv_dense import (
        _join_slots,
        _join_slots_union,
    )

    n_ids, n_dcs, size = 16, 3, 4
    D = make_dense(n_ids=n_ids, n_dcs=n_dcs, size=size, slots_per_id=4)
    for seed in range(5):
        r = np.random.default_rng(seed)
        _, log = gen_effect_log(r, 60, n_ids, n_dcs, size)
        base = D.init(n_replicas=1, n_keys=1)
        base, _ = D.apply_ops(base, pack_ops(log[:20], n_dcs, 32, 16))
        a, _ = D.apply_ops(base, pack_ops(log[20:40], n_dcs, 32, 16))
        b, _ = D.apply_ops(base, pack_ops(log[40:], n_dcs, 32, 16))
        rmv_vc = jnp.maximum(a.rmv_vc, b.rmv_vc)
        got = _join_slots_union(
            (a.slot_score, a.slot_dc, a.slot_ts),
            (b.slot_score, b.slot_dc, b.slot_ts),
            rmv_vc, D.M,
        )
        want = _join_slots(
            (a.slot_score, a.slot_dc, a.slot_ts),
            (b.slot_score, b.slot_dc, b.slot_ts),
            rmv_vc, D.M,
        )
        for g, w in zip(got, want):
            assert jnp.array_equal(g, w), seed


def test_merge_converges_replicas():
    """Two replicas that saw different halves of a log converge via merge to
    the replica that saw everything."""
    rng = np.random.default_rng(5)
    n_ids, n_dcs, size = 20, 2, 5
    _, log = gen_effect_log(rng, 80, n_ids, n_dcs, size)
    D = make_dense(n_ids=n_ids, n_dcs=n_dcs, size=size, slots_per_id=8)
    half = len(log) // 2
    sa = D.init(1, 1)
    sa, _ = D.apply_ops(sa, pack_ops(log[:half], n_dcs, 64, 32))
    sb = D.init(1, 1)
    sb, _ = D.apply_ops(sb, pack_ops(log[half:], n_dcs, 64, 32))
    sall = D.init(1, 1)
    sall, _ = D.apply_ops(sall, pack_ops(log, n_dcs, 64, 32))
    merged = D.merge(sa, sb)
    assert D.equal(merged, sall)
    # Idempotent under duplicate delivery: merging the full state in again
    # changes nothing (robustness the op-based reference cannot offer).
    assert D.equal(D.merge(merged, sall), sall)


def test_lossy_flag_on_overflow():
    D = make_dense(n_ids=2, n_dcs=1, size=1, slots_per_id=2)
    st = D.init(1, 1)
    # 3 live adds for one id with capacity M=2 -> overflow recorded.
    adds = [
        ("add", (0, 10, (0, 1))),
        ("add", (0, 20, (0, 2))),
        ("add", (0, 30, (0, 3))),
    ]
    st, _ = D.apply_ops(st, pack_ops(adds, 1, 4, 1))
    assert bool(st.lossy[0, 0])
    # Observable is still the best add.
    assert observed_set(D, st) == {(0, 30)}


def test_intra_batch_duplicate_delivery():
    """A duplicated add inside one batch must not consume a slot rank or
    drop a distinct add (regression: duplicates deduped before ranking)."""
    D = make_dense(n_ids=2, n_dcs=1, size=2, slots_per_id=2)
    a = ("add", (0, 30, (0, 1)))
    b = ("add", (0, 10, (0, 2)))
    st_dup = D.init(1, 1)
    st_dup, _ = D.apply_ops(st_dup, pack_ops([a, a, b], 1, 4, 1))
    st_ref = D.init(1, 1)
    st_ref, _ = D.apply_ops(st_ref, pack_ops([a, b], 1, 4, 1))
    assert st_dup.slot_ts.tolist() == st_ref.slot_ts.tolist()
    assert not bool(st_dup.lossy.any())
    # After removing a causally, only b survives — on both.
    rmv = [("rmv", (0, {0: 1}))]
    st_dup, _ = D.apply_ops(st_dup, pack_ops(rmv, 1, 4, 1))
    st_ref, _ = D.apply_ops(st_ref, pack_ops(rmv, 1, 4, 1))
    assert observed_set(D, st_dup) == {(0, 10)} == observed_set(D, st_ref)


def test_vc_advances_on_dominated_add():
    """The state vc advances even for dominated adds (topk_rmv.erl:233)."""
    D = make_dense(n_ids=4, n_dcs=2, size=2, slots_per_id=4)
    st = D.init(1, 1)
    st, _ = D.apply_ops(st, pack_ops([("rmv", (1, {0: 5}))], 2, 4, 2))
    st, extras = D.apply_ops(st, pack_ops([("add", (1, 7, (0, 3)))], 2, 4, 2))
    assert bool(extras.dominated[0, 0])
    assert st.vc[0, 0].tolist() == [3, 0]


def test_collect_dominated_off_same_state():
    """collect_dominated=False skips the extras gather but must leave the
    state path bit-identical (dominated adds die at the join filter)."""
    D = make_dense(n_ids=8, n_dcs=2, size=3, slots_per_id=2)
    ops1 = pack_ops(
        [("rmv", (1, {0: 5})), ("add", (1, 7, (0, 3))), ("add", (2, 9, (1, 1)))],
        2, 4, 2,
    )
    ops2 = pack_ops(
        [("add", (1, 11, (0, 6))), ("add", (3, 2, (0, 7)))], 2, 4, 2
    )
    st_a = st_b = D.init(1, 1)
    for ops in (ops1, ops2):
        st_a, ex_a = D.apply_ops(st_a, ops)
        st_b, ex_b = D.apply_ops(st_b, ops, collect_dominated=False)
        assert ex_b.dominated is None and ex_b.dominated_vc is None
        for la, lb in zip(jax.tree.leaves(st_a), jax.tree.leaves(st_b)):
            assert np.array_equal(np.asarray(la), np.asarray(lb))


def test_scatter_max_rows_mxu_exact():
    """The MXU one-hot scatter-max must be bit-exact vs XLA scatter across
    the full i32 range, with duplicate rows (per-column max) and OOB
    padding rows dropped."""
    from antidote_ccrdt_tpu.ops.dense_table import scatter_max_rows_mxu

    rng = np.random.default_rng(0)
    T, D_, Br = 500, 8, 64
    table = jnp.asarray(rng.integers(0, 2**31 - 1, (T, D_)).astype(np.int32))
    rows_np = rng.integers(0, T, Br).astype(np.int32)
    rows_np[::7] = rows_np[0]  # force duplicate runs
    rows_np[3] = T  # OOB padding sentinel
    rows = jnp.asarray(rows_np)
    upd = jnp.asarray(rng.integers(0, 2**31 - 1, (Br, D_)).astype(np.int32))
    # boundary values
    upd = upd.at[0, 0].set(2**31 - 1).at[1, 1].set(0)
    ref = table.at[rows].max(upd, mode="drop")
    got = scatter_max_rows_mxu(table, rows, upd)
    assert np.array_equal(np.asarray(ref), np.asarray(got))


def test_out_of_range_add_fields_dropped_not_aliased():
    # Regression: kid packing (kid = key*I + id) must not let a malformed
    # add_id >= I alias into the NEXT key's id range, nor a negative
    # padding id underflow into key NK-1's range. Both must be dropped
    # whole, leaving every instance untouched and lossy unset.
    D = make_dense(n_ids=4, n_dcs=2, size=2, slots_per_id=2)
    st = D.init(n_replicas=1, n_keys=2)
    ops = TopkRmvOps(
        add_key=jnp.asarray([[0, 0, 1, 1]], jnp.int32),
        add_id=jnp.asarray([[4, -3, 2, 9]], jnp.int32),  # 4,-3,9 invalid
        add_score=jnp.asarray([[99, 98, 50, 97]], jnp.int32),
        add_dc=jnp.asarray([[0, 0, 1, 1]], jnp.int32),
        add_ts=jnp.asarray([[5, 6, 7, 8]], jnp.int32),
        rmv_key=jnp.asarray([[0]], jnp.int32),
        rmv_id=jnp.asarray([[-1]], jnp.int32),
        rmv_vc=jnp.asarray([[[0, 0]]], jnp.int32),
    )
    st2, _ = D.apply_ops(st, ops)
    assert D.value(st2)[0][0] == []          # nothing leaked into key 0
    assert D.value(st2)[0][1] == [(2, 50)]   # only the valid add landed
    assert not bool(st2.lossy.any())
    # vc advances only for valid adds: dc 1 saw ts 7, dc 0 saw nothing.
    assert st2.vc[0, 1, 1] == 7 and st2.vc[0, 0, 0] == 0


def test_out_of_range_rmv_fields_dropped_not_aliased():
    # Regression (mirror of the add-path fix): a removal with rmv_id >= I
    # computes rrow = key*I + id inside the NEXT key's tombstone range and
    # must be dropped, not write a tombstone against a live element of a
    # different instance.
    D = make_dense(n_ids=4, n_dcs=2, size=2, slots_per_id=2)
    st = D.init(1, 2)
    # Key 1 holds element id 2, added at dc 0 ts 5.
    seed = TopkRmvOps(
        add_key=jnp.asarray([[1]], jnp.int32),
        add_id=jnp.asarray([[2]], jnp.int32),
        add_score=jnp.asarray([[50]], jnp.int32),
        add_dc=jnp.asarray([[0]], jnp.int32),
        add_ts=jnp.asarray([[5]], jnp.int32),
        rmv_key=jnp.asarray([[0]], jnp.int32),
        rmv_id=jnp.asarray([[-1]], jnp.int32),
        rmv_vc=jnp.zeros((1, 1, 2), jnp.int32),
    )
    st, _ = D.apply_ops(st, seed)
    # Malformed removals: key=0, id=6 -> rrow 6 == (key 1, id 2);
    # key=9 out of range; both must be dropped whole.
    bad = TopkRmvOps(
        add_key=jnp.asarray([[0]], jnp.int32),
        add_id=jnp.asarray([[0]], jnp.int32),
        add_score=jnp.asarray([[1]], jnp.int32),
        add_dc=jnp.asarray([[0]], jnp.int32),
        add_ts=jnp.asarray([[0]], jnp.int32),  # padding add
        rmv_key=jnp.asarray([[0, 9]], jnp.int32),
        rmv_id=jnp.asarray([[6, 1]], jnp.int32),
        rmv_vc=jnp.full((1, 2, 2), 99, jnp.int32),
    )
    st2, _ = D.apply_ops(st, bad)
    assert D.value(st2)[0][1] == [(2, 50)], "aliased rmv killed another key's element"
    assert int(st2.rmv_vc.sum()) == 0, "tombstone written for out-of-range removal"


def test_dominated_table_mode_golden():
    """"table" extras mode: the dominated mask is keyed by id and the
    re-broadcast payload is the post-batch rmv_vc row — same information
    as the op-aligned mode in the delete-semantics golden scenario."""
    D = make_dense(n_ids=4, n_dcs=2, size=2, slots_per_id=4)
    st = D.init(1, 1)
    st, _ = D.apply_ops(st, pack_ops([("rmv", (1, {0: 5}))], 2, 4, 2))
    st2, ex = D.apply_ops(
        st, pack_ops([("add", (1, 7, (0, 3))), ("add", (2, 9, (1, 1)))], 2, 4, 2),
        collect_dominated="table",
    )
    assert ex.dominated is None and ex.dominated_vc is None
    tbl = np.asarray(ex.dominated_tbl[0, 0])
    assert tbl[1] and not tbl[0] and not tbl[2] and not tbl[3]
    # re-broadcast payload: the stored tombstone vc row for the flagged id
    assert st2.rmv_vc[0, 0, 1].tolist() == [5, 0]
    # state identical to the other modes
    st_ref, _ = D.apply_ops(
        st, pack_ops([("add", (1, 7, (0, 3))), ("add", (2, 9, (1, 1)))], 2, 4, 2),
        collect_dominated=False,
    )
    for la, lb in zip(jax.tree.leaves(st2), jax.tree.leaves(st_ref)):
        assert np.array_equal(np.asarray(la), np.asarray(lb))


@pytest.mark.parametrize("seed", range(3))
def test_dominated_table_equals_scattered_op_flags(seed):
    """On non-lossy batches the id-keyed table must equal the op-aligned
    flags scattered by (key, id): same dominated set, different keying."""
    rng = np.random.default_rng(seed)
    R, NK, I, DCS = 2, 2, 64, 4
    D = make_dense(n_ids=I, n_dcs=DCS, size=8, slots_per_id=4)
    st = D.init(R, NK)
    # Seed tombstones, then a mixed batch with DISTINCT ids per replica so
    # no id can overflow M ranks (table mode may legitimately drop flags
    # only on lossy batches).
    B, Br = 32, 8
    pre = TopkRmvOps(
        add_key=jnp.zeros((R, 1), jnp.int32),
        add_id=jnp.zeros((R, 1), jnp.int32),
        add_score=jnp.zeros((R, 1), jnp.int32),
        add_dc=jnp.zeros((R, 1), jnp.int32),
        add_ts=jnp.zeros((R, 1), jnp.int32),  # padding
        rmv_key=jnp.asarray(rng.integers(0, NK, (R, Br)).astype(np.int32)),
        rmv_id=jnp.asarray(rng.integers(0, I, (R, Br)).astype(np.int32)),
        rmv_vc=jnp.asarray(rng.integers(1, 50, (R, Br, DCS)).astype(np.int32)),
    )
    st, _ = D.apply_ops(st, pre, collect_dominated=False)
    ids = np.stack([rng.permutation(I)[:B] for _ in range(R)]).astype(np.int32)
    ops = TopkRmvOps(
        add_key=jnp.asarray(rng.integers(0, NK, (R, B)).astype(np.int32)),
        add_id=jnp.asarray(ids),
        add_score=jnp.asarray(rng.integers(1, 900, (R, B)).astype(np.int32)),
        add_dc=jnp.asarray(rng.integers(0, DCS, (R, B)).astype(np.int32)),
        add_ts=jnp.asarray(rng.integers(1, 80, (R, B)).astype(np.int32)),
        rmv_key=jnp.full((R, 1), 0, jnp.int32),
        rmv_id=jnp.full((R, 1), -1, jnp.int32),
        rmv_vc=jnp.zeros((R, 1, DCS), jnp.int32),
    )
    st_op, ex_op = D.apply_ops(st, ops, collect_dominated=True)
    st_tbl, ex_tbl = D.apply_ops(st, ops, collect_dominated="table")
    assert not bool(st_tbl.lossy.any())
    expected = np.zeros((R, NK, I), bool)
    dom = np.asarray(ex_op.dominated)
    for r in range(R):
        for b in range(B):
            if dom[r, b]:
                expected[r, int(ops.add_key[r, b]), int(ops.add_id[r, b])] = True
    assert np.array_equal(np.asarray(ex_tbl.dominated_tbl), expected)
    for la, lb in zip(jax.tree.leaves(st_op), jax.tree.leaves(st_tbl)):
        assert np.array_equal(np.asarray(la), np.asarray(lb))


@settings(
    max_examples=25, deadline=None,
    suppress_health_check=[HealthCheck.too_slow],
)
@given(data=st.data())
def test_dominated_table_mode_equivalence_property(data):
    """Property form of the table/op-aligned equivalence: under ANY
    non-overflowing batch (<= M adds per (key, id)), the id-keyed table
    equals the op-aligned flags scattered by (key, id), and the state is
    bit-identical across all three collect_dominated modes."""
    R, NK, I, DCS, M = 2, 2, 16, 3, 3
    D = make_dense(n_ids=I, n_dcs=DCS, size=4, slots_per_id=M)
    st_ = D.init(R, NK)
    # seed tombstones
    n_rmv = data.draw(st.integers(1, 6))
    rmv_id = data.draw(
        st.lists(st.integers(0, I - 1), min_size=n_rmv, max_size=n_rmv)
    )
    rmv_key = data.draw(
        st.lists(st.integers(0, NK - 1), min_size=n_rmv, max_size=n_rmv)
    )
    vc_flat = data.draw(
        st.lists(st.integers(0, 30), min_size=n_rmv * DCS, max_size=n_rmv * DCS)
    )
    pre = TopkRmvOps(
        add_key=jnp.zeros((R, 1), jnp.int32),
        add_id=jnp.zeros((R, 1), jnp.int32),
        add_score=jnp.zeros((R, 1), jnp.int32),
        add_dc=jnp.zeros((R, 1), jnp.int32),
        add_ts=jnp.zeros((R, 1), jnp.int32),
        rmv_key=jnp.broadcast_to(jnp.asarray(rmv_key, jnp.int32), (R, n_rmv)),
        rmv_id=jnp.broadcast_to(jnp.asarray(rmv_id, jnp.int32), (R, n_rmv)),
        rmv_vc=jnp.broadcast_to(
            jnp.asarray(vc_flat, jnp.int32).reshape(1, n_rmv, DCS), (R, n_rmv, DCS)
        ),
    )
    st_, _ = D.apply_ops(st_, pre, collect_dominated=False)
    # adds: at most M per (key, id) -> never lossy
    pairs = data.draw(
        st.lists(
            st.tuples(st.integers(0, NK - 1), st.integers(0, I - 1)),
            min_size=1, max_size=10, unique=True,
        )
    )
    per_pair = data.draw(st.integers(1, M))
    adds = []
    for (k, i) in pairs:
        for j in range(per_pair):
            adds.append(
                (k, i,
                 data.draw(st.integers(1, 50)),       # score
                 data.draw(st.integers(0, DCS - 1)),  # dc
                 data.draw(st.integers(1, 40)))       # ts
            )
    B = len(adds)
    arr = np.asarray(adds, np.int32)
    ops = TopkRmvOps(
        add_key=jnp.broadcast_to(jnp.asarray(arr[:, 0]), (R, B)),
        add_id=jnp.broadcast_to(jnp.asarray(arr[:, 1]), (R, B)),
        add_score=jnp.broadcast_to(jnp.asarray(arr[:, 2]), (R, B)),
        add_dc=jnp.broadcast_to(jnp.asarray(arr[:, 3]), (R, B)),
        add_ts=jnp.broadcast_to(jnp.asarray(arr[:, 4]), (R, B)),
        rmv_key=jnp.zeros((R, 1), jnp.int32),
        rmv_id=jnp.full((R, 1), -1, jnp.int32),
        rmv_vc=jnp.zeros((R, 1, DCS), jnp.int32),
    )
    st_op, ex_op = D.apply_ops(st_, ops, collect_dominated=True)
    st_tbl, ex_tbl = D.apply_ops(st_, ops, collect_dominated="table")
    st_off, _ = D.apply_ops(st_, ops, collect_dominated=False)
    for a, b, c in zip(
        jax.tree.leaves(st_op), jax.tree.leaves(st_tbl), jax.tree.leaves(st_off)
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b))
        assert np.array_equal(np.asarray(a), np.asarray(c))
    # duplicate adds dedup (idempotence) and never overflow here, but a
    # batch CAN still rank >M live adds nowhere (unique pairs, <=M each):
    assert not bool(st_tbl.lossy.any())
    expected = np.zeros((R, NK, I), bool)
    dom = np.asarray(ex_op.dominated)
    ak, ai = np.asarray(ops.add_key), np.asarray(ops.add_id)
    for r in range(R):
        for b_i in range(B):
            if dom[r, b_i]:
                expected[r, ak[r, b_i], ai[r, b_i]] = True
    assert np.array_equal(np.asarray(ex_tbl.dominated_tbl), expected)
