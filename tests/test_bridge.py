"""Bridge server/client: the BEAM-shaped host integration surface.

Runs a real TCP server in-process and drives it through the client — and
once through raw `{packet, 4}` + ETF bytes, proving an Erlang gen_tcp
client needs nothing Python-specific."""

import socket
import struct

import pytest

from antidote_ccrdt_tpu.bridge import BridgeClient, BridgeServer
from antidote_ccrdt_tpu.bridge.client import add, rmv
from antidote_ccrdt_tpu.bridge import protocol as P
from antidote_ccrdt_tpu.core import etf, wire
from antidote_ccrdt_tpu.core.etf import Atom
from antidote_ccrdt_tpu.core.behaviour import registry
from antidote_ccrdt_tpu.core.clock import make_contexts


@pytest.fixture(scope="module")
def server():
    with BridgeServer() as srv:
        yield srv


@pytest.fixture()
def client(server):
    with BridgeClient(*server.address) as c:
        yield c


def test_scalar_topk_rmv_over_bridge(client):
    h = client.new("topk_rmv", 2)
    eff = client.downstream(h, ("add", (1, 50)), dc=0, ts=1)
    assert eff[0] == Atom("add")
    extras = client.update(h, eff)
    assert extras == []
    eff2 = client.downstream(h, ("add", (2, 40)), dc=0, ts=2)
    client.update(h, eff2)
    assert sorted(client.value(h)) == [(1, 50), (2, 40)]
    # removal generates no promotion here; re-add of removed id bounces rmv
    effr = client.downstream(h, ("rmv", 1), dc=0, ts=3)
    client.update(h, effr)
    assert client.value(h) == [(2, 40)]
    eff3 = client.downstream(h, ("add", (1, 45)), dc=0, ts=1)  # stale ts
    # dominated add: server returns the re-broadcast rmv as an extra
    if eff3 != Atom("nil"):
        extras = client.update(h, eff3)
        assert any(x[0] == Atom("rmv") for x in extras)


def test_snapshot_interop_with_local_state(client):
    # Build state locally, ship via reference binary, continue remotely.
    crdt = registry.scalar("leaderboard")
    (ctx,) = make_contexts(1)
    s = crdt.new(3)
    for op in [("add", (1, 10)), ("add", (2, 20)), ("ban", 1)]:
        e = crdt.downstream(op, s, ctx)
        if e:
            s, ex = crdt.update(e, s)
            for x in ex:
                s, _ = crdt.update(x, s)
    h = client.from_binary("leaderboard", wire.to_reference_binary("leaderboard", s))
    assert dict(client.value(h)) == {2: 20}
    blob = client.to_binary(h)
    assert crdt.equal(wire.from_reference_binary("leaderboard", blob), s)


def test_equal_and_free(client):
    h1 = client.new("average")
    h2 = client.new("average")
    assert client.equal(h1, h2)
    client.update(h1, (Atom("add"), (10, 1)))
    assert not client.equal(h1, h2)
    client.free(h1)
    with pytest.raises(Exception, match="no such handle"):
        client.value(h1)


def test_compact_over_bridge(client):
    h = client.new("average")
    effs = [(Atom("add"), (3, 1)), (Atom("add"), (5, 2)), (Atom("add"), (2, 1))]
    out = client.compact(h, effs)
    assert out == [(Atom("add"), (10, 4))]


def test_error_reply(client):
    with pytest.raises(Exception, match="unknown op"):
        client.call((Atom("bogus"), 1))
    with pytest.raises(Exception, match="KeyError"):
        client.call((Atom("value"), 99999))


def test_dense_grid_over_bridge(client):
    client.grid_new("g1", n_replicas=2, n_keys=1, n_ids=64, n_dcs=2, size=4)
    dominated = client.grid_apply(
        "g1",
        [
            [add(0, 1, 50, 0, 1), add(0, 2, 40, 0, 2)],
            [add(0, 3, 30, 1, 1), rmv(0, 2, {0: 9})],
        ],
    )
    assert dominated == 0
    # pre-merge: replica 0 doesn't know id 3 or the removal
    assert dict(client.grid_observe("g1", 0)) == {1: 50, 2: 40}
    client.grid_merge_all("g1")
    merged0 = dict(client.grid_observe("g1", 0))
    merged1 = dict(client.grid_observe("g1", 1))
    assert merged0 == merged1 == {1: 50, 3: 30}  # id 2 removed by tombstone


def test_dense_grid_topk_over_bridge(client):
    client.grid_new("gtk", "topk", n_replicas=2, n_keys=1, n_ids=32, size=2)
    client.grid_apply("gtk", [
        [(Atom("add"), 0, 1, 50), (Atom("add"), 0, 2, 40), (Atom("add"), 0, 3, 60)],
        [(Atom("add"), 0, 4, 99)],
    ])
    assert dict(client.grid_observe("gtk", 0)) == {1: 50, 3: 60}
    client.grid_merge_all("gtk")
    # K=2 board over the joined table: 99 and 60 win, on every replica.
    assert dict(client.grid_observe("gtk", 0)) == {3: 60, 4: 99}
    assert dict(client.grid_observe("gtk", 1)) == {3: 60, 4: 99}
    with pytest.raises(Exception, match="out of range"):
        client.grid_apply("gtk", [[(Atom("add"), 0, 999, 1)], []])


def test_dense_grid_leaderboard_over_bridge(client):
    client.grid_new("glb", "leaderboard", n_replicas=2, n_keys=1,
                    n_players=16, size=3)
    client.grid_apply("glb", [
        [(Atom("add"), 0, 1, 10), (Atom("add"), 0, 2, 20)],
        [(Atom("add"), 0, 3, 30), (Atom("ban"), 0, 2)],
    ])
    client.grid_merge_all("glb")
    # Ban wins over any add (leaderboard.erl:494-499): 2 is out everywhere.
    assert dict(client.grid_observe("glb", 0)) == {1: 10, 3: 30}
    assert dict(client.grid_observe("glb", 1)) == {1: 10, 3: 30}
    with pytest.raises(Exception, match="unknown grid op tag"):
        client.grid_apply("glb", [[(Atom("rmv"), 0, 1)], []])


def test_dense_grid_average_over_bridge(client):
    client.grid_new("gav", "average", n_replicas=3, n_keys=2)
    client.grid_apply("gav", [
        [(Atom("add"), 0, 10, 1), (Atom("add"), 1, 8, 2)],
        [(Atom("add"), 0, 20, 1)],
        [],
    ])
    assert client.grid_observe("gav", 0, 0) == (10, 1)
    assert client.grid_observe("gav", 1, 0) == (20, 1)
    client.grid_merge_all("gav")
    # MONOID fold: the total lands in row 0, other rows reset to identity
    # (rows are deltas — broadcasting a fold would R-multiply the total).
    assert client.grid_observe("gav", 0, 0) == (30, 2)
    assert client.grid_observe("gav", 0, 1) == (8, 2)
    assert client.grid_observe("gav", 1, 0) == (0, 0)
    # Idempotent at the total level: merging again changes nothing.
    client.grid_merge_all("gav")
    assert client.grid_observe("gav", 0, 0) == (30, 2)
    # Accumulation continues after a fold without double counting.
    client.grid_apply("gav", [[], [(Atom("add"), 0, 5, 1)], []])
    client.grid_merge_all("gav")
    assert client.grid_observe("gav", 0, 0) == (35, 3)
    with pytest.raises(Exception, match="count=-1 out of range"):
        client.grid_apply("gav", [[(Atom("add"), 0, 1, -1)], [], []])


def test_dense_grid_wordcount_over_bridge(client):
    client.grid_new("gwc", "wordcount", n_replicas=2, n_keys=1, n_buckets=8)
    client.grid_apply("gwc", [
        [(Atom("add"), 0, 3), (Atom("add"), 0, 3), (Atom("add"), 0, 5)],
        [(Atom("add"), 0, 3)],
    ])
    assert dict(client.grid_observe("gwc", 0)) == {3: 2, 5: 1}
    client.grid_merge_all("gwc")
    assert dict(client.grid_observe("gwc", 0)) == {3: 3, 5: 1}
    assert client.grid_observe("gwc", 1) == []
    with pytest.raises(Exception, match="token=9 out of range"):
        client.grid_apply("gwc", [[(Atom("add"), 0, 9)], []])
    # worddocumentcount shares the kernel but is its own registered grid
    # type (dedup is an encode-time concern, worddocumentcount.erl:76-86).
    client.grid_new("gwd", "worddocumentcount", n_replicas=1, n_keys=1,
                    n_buckets=4)
    client.grid_apply("gwd", [[(Atom("add"), 0, 1)]])
    assert dict(client.grid_observe("gwd", 0)) == {1: 1}


def test_dense_grid_snapshot_roundtrip_all_types(client):
    """grid_to_binary/grid_from_binary for every grid type: the snapshot
    carries its own type + geometry, the restored grid answers observes."""
    cases = [
        ("topk", dict(n_replicas=2, n_keys=1, n_ids=16, size=2),
         [[(Atom("add"), 0, 1, 7)], []]),
        ("leaderboard", dict(n_replicas=2, n_keys=1, n_players=8, size=2),
         [[(Atom("add"), 0, 1, 7)], [(Atom("ban"), 0, 3)]]),
        ("average", dict(n_replicas=2, n_keys=1),
         [[(Atom("add"), 0, 6, 2)], []]),
        ("wordcount", dict(n_replicas=2, n_keys=1, n_buckets=8),
         [[(Atom("add"), 0, 2)], []]),
    ]
    for tname, params, ops in cases:
        src, dst = f"snap_src_{tname}", f"snap_dst_{tname}"
        client.grid_new(src, tname, **params)
        client.grid_apply(src, ops)
        blob = client.grid_to_binary(src)
        client.grid_from_binary(dst, blob)
        assert client.grid_observe(dst, 0) == client.grid_observe(src, 0), tname


def test_grid_rejects_unknown_type(client):
    with pytest.raises(Exception, match="dense grids support"):
        client.grid_new("gx", "no_such_type", n_replicas=1)


def test_grid_rejects_bad_ops(client):
    client.grid_new("gv", n_replicas=1, n_keys=1, n_ids=8, n_dcs=2, size=2)
    with pytest.raises(Exception, match="unknown grid op tag"):
        client.grid_apply("gv", [[(Atom("remove"), 0, 1, [])]])
    with pytest.raises(Exception, match="dc 5 out of range"):
        client.grid_apply("gv", [[add(0, 1, 10, 5, 1)]])
    # id/key beyond the dense capacities would alias into clamped gathers /
    # silently-dropped scatters — must be rejected at the boundary.
    with pytest.raises(Exception, match="out of range"):
        client.grid_apply("gv", [[add(0, 999, 10, 0, 1)]])
    with pytest.raises(Exception, match="out of range"):
        client.grid_apply("gv", [[add(7, 1, 10, 0, 1)]])
    with pytest.raises(Exception, match="out of range"):
        client.grid_apply("gv", [[rmv(0, 999, {0: 1})]])
    with pytest.raises(Exception, match="out of range"):
        client.grid_observe("gv", 3, 0)
    # ts == 0 is the dense empty-slot sentinel: such an add would silently
    # vanish as padding and its dc be dropped from re-broadcast vcs
    # (ADVICE r3 #3) — the wire enforces the "timestamps start at 1"
    # convention loudly instead.
    with pytest.raises(Exception, match="ts 0 out of range"):
        client.grid_apply("gv", [[add(0, 1, 10, 0, 0)]])
    # Server-reported errors keep the stream in sync: client stays usable.
    assert client.grid_apply("gv", [[add(0, 1, 10, 0, 1)]]) == 0
    assert dict(client.grid_observe("gv", 0)) == {1: 10}


def test_wordcount_atom_key_roundtrip():
    # the to-side must keep Atom keys distinct from equal-text binaries
    term = {Atom("x"): 1, b"x": 2}
    state = wire.state_from_term("wordcount", term)
    assert len(state) == 2
    assert wire.state_to_term("wordcount", state) == term


def test_raw_packet4_etf_client(server):
    """Drive the server with hand-built frames: what gen_tcp sends."""
    with socket.create_connection(server.address, timeout=10) as sk:
        def rpc(req_id, op):
            payload = etf.encode((Atom("call"), req_id, op))
            sk.sendall(struct.pack(">I", len(payload)) + payload)
            hdr = sk.recv(4, socket.MSG_WAITALL)
            (n,) = struct.unpack(">I", hdr)
            data = b""
            while len(data) < n:
                data += sk.recv(n - len(data))
            return etf.decode(data)

        r = rpc(1, (Atom("new"), Atom("wordcount"), []))
        assert r[0] == Atom("reply") and r[1] == 1 and r[2][0] == Atom("ok")
        h = r[2][1]
        r = rpc(2, (Atom("update"), h, (Atom("add"), b"hello hello world")))
        assert r[2][0] == Atom("ok")
        r = rpc(3, (Atom("value"), h))
        assert r[2] == (Atom("ok"), {b"hello": 2, b"world": 1})


def test_pipelined_requests(server):
    """Multiple in-flight requests on one connection resolve by req id."""
    with socket.create_connection(server.address, timeout=10) as sk:
        frames = b""
        for i, op in [(7, (Atom("new"), Atom("average"), [])), (8, (Atom("new"), Atom("average"), []))]:
            payload = etf.encode((Atom("call"), i, op))
            frames += struct.pack(">I", len(payload)) + payload
        sk.sendall(frames)
        buf = bytearray()
        got = {}
        while len(got) < 2:
            buf += sk.recv(1 << 16)
            for term in P.unpack_frames(buf):
                rid, ok, res = P.parse_reply(term)
                got[rid] = (ok, res)
        assert set(got) == {7, 8}
        assert all(ok for ok, _ in got.values())


def test_batch_merge_over_the_wire(client):
    """North-star path: N topk_rmv replica states (one live handle, one
    reference-format binary) joined in one call; result equals a state
    that saw every op."""
    eng = registry.scalar("topk_rmv")
    ctxs = make_contexts(2)
    sA, sB, s_all = eng.new(4), eng.new(4), eng.new(4)
    effs = []
    for j, (i, sc) in enumerate([(1, 50), (2, 90), (3, 70), (4, 60), (5, 80)]):
        eff = eng.downstream(("add", (i, sc)), s_all, ctxs[j % 2])
        effs.append(eff)
        s_all, _ = eng.update(eff, s_all)
    for eff in effs[::2]:
        sA, _ = eng.update(eff, sA)
    for eff in effs[1::2]:
        sB, _ = eng.update(eff, sB)

    hA = client.from_binary("topk_rmv", wire.to_reference_binary("topk_rmv", sA))
    blobB = wire.to_reference_binary("topk_rmv", sB)
    h = client.batch_merge("topk_rmv", [hA, blobB])
    got = client.value(h)
    assert sorted(map(tuple, got)) == sorted(eng.value(s_all))


def test_batch_merge_rejects_mixed_types(client):
    h = client.new("average", 0, 0)
    with pytest.raises(Exception):
        client.batch_merge("topk", [h])


def test_registry_and_predicates_over_bridge(client):
    # The registry + predicate callbacks (antidote_ccrdt.erl:37-65) are
    # interrogable over the wire, so a BEAM host needs no local copy.
    assert client.is_type("topk_rmv") is True
    assert client.is_type("nope") is False
    assert client.generates_extra_operations("topk_rmv") is True
    assert client.generates_extra_operations("average") is False
    assert client.is_operation("topk_rmv", ("add", (1, 2))) is True
    assert client.is_operation("topk_rmv", ("frobnicate", 1)) is False
    assert client.require_state_downstream("topk_rmv", ("add", (1, 2))) is True
    assert client.require_state_downstream("average", ("add", 5)) is False
    # A tagged effect (add_r) is replicate-tagged; a plain add is not.
    h = client.new("topk_rmv", 1)
    e1 = client.downstream(h, ("add", (1, 50)), 0, 1)
    client.update(h, e1)
    assert client.is_replicate_tagged("topk_rmv", e1) is False
    e3 = client.downstream(h, ("add", (2, 10)), 0, 3)
    assert client.is_replicate_tagged("topk_rmv", e3) is True


def test_long_grid_op_does_not_block_scalar_ops(server):
    """Round-2 concurrency model (VERDICT r1 weak #5): per-object locks.
    A slow dense-grid dispatch must block only callers of that grid; a
    second client's scalar traffic proceeds concurrently. Deterministic:
    the grid's apply is wrapped with a sleep, so this pins the LOCKING,
    independent of backend timing."""
    import threading
    import time as _t

    with BridgeClient(*server.address) as ca, BridgeClient(*server.address) as cb:
        ca.grid_new("slow", n_replicas=2, n_keys=1, n_ids=64, n_dcs=2, size=4)
        grid = server._grids[b"slow"]
        orig_apply = grid.apply

        def slow_apply(ops):
            _t.sleep(1.5)
            return orig_apply(ops)

        grid.apply = slow_apply
        t_grid_done = []

        def run_grid():
            ca.grid_apply("slow", [[add(0, 1, 50, 0, 1)], []])
            t_grid_done.append(_t.perf_counter())

        th = threading.Thread(target=run_grid)
        t0 = _t.perf_counter()
        th.start()
        # scalar traffic on another connection while the grid op is held
        h = cb.new("average")
        for j in range(20):
            cb.update(h, (Atom("add"), (j, 1)))
        v = cb.value(h)
        t_scalar_done = _t.perf_counter()
        th.join(timeout=30)
        assert t_grid_done, "grid op never completed"
        assert v == sum(range(20)) / 20
        # all 22 scalar round trips finished while the grid op slept
        assert t_scalar_done - t0 < 1.2, (
            f"scalar ops took {t_scalar_done - t0:.2f}s — serialized behind "
            "the grid lock"
        )
        assert t_grid_done[0] - t0 >= 1.5


def test_equal_same_handle_and_concurrent_distinct_handles(server):
    """Lock-table edge cases: equal(h, h) acquires one lock once; two
    clients hammering DISTINCT handles never serialize on each other's
    object locks (smoke: both finish quickly)."""
    import threading

    with BridgeClient(*server.address) as ca, BridgeClient(*server.address) as cb:
        h = ca.new("average")
        assert ca.equal(h, h) is True
        h2 = cb.new("average")
        errs = []

        def hammer(c, hh):
            try:
                for j in range(50):
                    c.update(hh, (Atom("add"), (1, 1)))
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        ths = [
            threading.Thread(target=hammer, args=(ca, h)),
            threading.Thread(target=hammer, args=(cb, h2)),
        ]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=60)
        assert not errs
        assert ca.value(h) == 1.0 and cb.value(h2) == 1.0  # mean of 50 x (1,1)


def test_free_is_idempotent(client):
    h = client.new("average")
    client.free(h)
    client.free(h)  # second free must reply {ok, true}, not an error
    with pytest.raises(Exception, match="no such handle"):
        client.value(h)


def test_grid_snapshot_restore_across_servers(server):
    """Worker-restart story: a dense grid's self-contained snapshot
    (geometry + state) rebuilds the grid on a DIFFERENT server process
    with identical observables."""
    with BridgeClient(*server.address) as c:
        c.grid_new("g2", n_replicas=2, n_keys=1, n_ids=64, n_dcs=2, size=4)
        c.grid_apply(
            "g2",
            [[add(0, 1, 50, 0, 1), add(0, 2, 40, 0, 2)],
             [add(0, 3, 30, 1, 1), rmv(0, 2, {0: 9})]],
        )
        c.grid_merge_all("g2")
        before = dict(c.grid_observe("g2", 0))
        blob = c.grid_to_binary("g2")
        assert isinstance(blob, bytes) and len(blob) > 100
    with BridgeServer() as srv2, BridgeClient(*srv2.address) as c2:
        c2.grid_from_binary("restored", blob)
        assert dict(c2.grid_observe("restored", 0)) == before
        # the restored grid is live, not a read-only copy
        c2.grid_apply("restored", [[add(0, 9, 99, 0, 5)], []])
        c2.grid_merge_all("restored")
        assert dict(c2.grid_observe("restored", 0)).get(9) == 99


def test_grid_restore_rejects_malformed_blob(server):
    with BridgeClient(*server.address) as c:
        with pytest.raises(Exception, match="ValueError|Error"):
            c.grid_from_binary("bad", b"\x83h\x01a\x01")  # not a pair


@pytest.mark.parametrize("seed", range(2))
def test_grid_wire_differential_vs_direct_engines(client, seed):
    """Randomized differential for the round-3 grid packers: the same op
    stream driven (a) through the TCP wire into a grid and (b) directly
    into the dense engines must produce identical observables — pinning
    the ETF op packing, not just per-type examples."""
    import numpy as np

    from antidote_ccrdt_tpu.models.average import AverageDense, AverageOps
    from antidote_ccrdt_tpu.models.topk import TopkOps
    from antidote_ccrdt_tpu.models.topk import make_dense as mk_topk
    from antidote_ccrdt_tpu.models.wordcount import WordcountOps
    from antidote_ccrdt_tpu.models.wordcount import make_dense as mk_wc

    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    R, NK, B = 2, 2, 12

    # -- average ----------------------------------------------------------
    g = f"diff_avg_{seed}"
    client.grid_new(g, "average", n_replicas=R, n_keys=NK)
    keys = rng.integers(0, NK, (R, B))
    vals = rng.integers(-30, 60, (R, B))
    cnts = rng.integers(0, 3, (R, B))  # count 0 = no-op, both paths
    client.grid_apply(g, [
        [(Atom("add"), int(keys[r, j]), int(vals[r, j]), int(cnts[r, j]))
         for j in range(B)]
        for r in range(R)
    ])
    Da = AverageDense()
    st, _ = Da.apply_ops(
        Da.init(R, NK),
        AverageOps(jnp.asarray(keys, jnp.int32), jnp.asarray(vals, jnp.int32),
                   jnp.asarray(cnts, jnp.int32)),
    )
    for r in range(R):
        for k in range(NK):
            assert client.grid_observe(g, r, k) == (
                int(st.sum[r, k]), int(st.num[r, k])
            )

    # -- wordcount --------------------------------------------------------
    V = 16
    g = f"diff_wc_{seed}"
    client.grid_new(g, "wordcount", n_replicas=R, n_keys=NK, n_buckets=V)
    wk = rng.integers(0, NK, (R, B))
    wt = rng.integers(0, V, (R, B))
    client.grid_apply(g, [
        [(Atom("add"), int(wk[r, j]), int(wt[r, j])) for j in range(B)]
        for r in range(R)
    ])
    Dw = mk_wc(V)
    wst, _ = Dw.apply_ops(
        Dw.init(R, NK),
        WordcountOps(jnp.asarray(wk, jnp.int32), jnp.asarray(wt, jnp.int32)),
    )
    for r in range(R):
        for k in range(NK):
            expect = {
                t: int(c) for t, c in enumerate(np.asarray(wst.counts)[r, k]) if c
            }
            assert dict(client.grid_observe(g, r, k)) == expect

    # -- topk -------------------------------------------------------------
    g = f"diff_tk_{seed}"
    I, K = 32, 3
    client.grid_new(g, "topk", n_replicas=R, n_keys=NK, n_ids=I, size=K)
    tk = rng.integers(0, NK, (R, B))
    ti = rng.integers(0, I, (R, B))
    ts = rng.integers(1, 500, (R, B))
    client.grid_apply(g, [
        [(Atom("add"), int(tk[r, j]), int(ti[r, j]), int(ts[r, j]))
         for j in range(B)]
        for r in range(R)
    ])
    Dt = mk_topk(n_ids=I, size=K)
    tst, _ = Dt.apply_ops(
        Dt.init(R, NK),
        TopkOps(jnp.asarray(tk, jnp.int32), jnp.asarray(ti, jnp.int32),
                jnp.asarray(ts, jnp.int32), jnp.ones((R, B), bool)),
    )
    vals_ref = Dt.value(tst)
    for r in range(R):
        for k in range(NK):
            assert client.grid_observe(g, r, k) == vals_ref[r][k]


from conftest import HealthCheck, given, settings, st  # noqa: E402  (hypothesis or skip-stub)


@settings(
    max_examples=15, deadline=None,
    suppress_health_check=[HealthCheck.too_slow],
)
@given(
    script=st.lists(
        st.one_of(
            st.tuples(st.just("apply"), st.integers(0, 2), st.integers(0, 1),
                      st.integers(-20, 40), st.integers(0, 2)),
            st.tuples(st.just("merge_all")),
        ),
        min_size=1, max_size=12,
    ),
)
def test_grid_monoid_merge_all_total_invariant(script):
    """MONOID grid invariant under ANY interleaving of applies and
    merge_all calls: the grid-wide total (sum over replica rows — rows
    are deltas) always equals the exact op sum, and merge_all is
    idempotent at the total level. Pins the fold-to-row-0 + identity-
    reset semantics against the R-multiplication bug a naive broadcast
    would introduce (server.py merge_all docstring)."""
    from antidote_ccrdt_tpu.bridge.server import _Grid

    grid = _Grid("average", {Atom("n_replicas"): 3, Atom("n_keys"): 2})
    exact_sum = [0, 0]
    exact_num = [0, 0]
    for step in script:
        if step[0] == "apply":
            _, replica, key, value, count = step
            ops = [[] for _ in range(3)]
            ops[replica] = [(Atom("add"), key, value, count)]
            grid.apply(ops)
            if count > 0:
                exact_sum[key] += value
                exact_num[key] += count
        else:
            grid.merge_all()
    grid.merge_all()
    import numpy as np

    sums = np.asarray(grid.state.sum).sum(axis=0)
    nums = np.asarray(grid.state.num).sum(axis=0)
    assert list(sums) == exact_sum and list(nums) == exact_num


def test_dense_grid_worddoc_device_dedup_over_wire(client):
    """The device-side per-document dedup is reachable over the wire:
    {doc_add, Key, Doc, Uniq, Token} records dedup on (doc, uniq) —
    string identity — in one device sort (worddocumentcount.erl:76-86);
    two distinct words sharing a bucket still count twice."""
    client.grid_new("gdd", "worddocumentcount", n_replicas=1, n_keys=1,
                    n_buckets=8)
    client.grid_apply("gdd", [[
        (Atom("doc_add"), 0, 0, 11, 3),  # doc 0, word#11 -> bucket 3
        (Atom("doc_add"), 0, 0, 11, 3),  # same word, same doc: dedups
        (Atom("doc_add"), 0, 0, 12, 3),  # DIFFERENT word, same bucket: +1
        (Atom("doc_add"), 0, 1, 11, 3),  # same word, other doc: +1
    ]])
    assert dict(client.grid_observe("gdd", 0)) == {3: 3}
    with pytest.raises(Exception, match="mixes doc_add"):
        client.grid_apply("gdd", [[(Atom("doc_add"), 0, 0, 1, 1),
                                   (Atom("add"), 0, 1)]])
    with pytest.raises(Exception, match="token=9 out of range"):
        client.grid_apply("gdd", [[(Atom("doc_add"), 0, 0, 1, 9)]])
    # Plain pre-deduped adds still work on the same grid.
    client.grid_apply("gdd", [[(Atom("add"), 0, 5)]])
    assert dict(client.grid_observe("gdd", 0)) == {3: 3, 5: 1}


def test_grid_apply_extras_topk_rmv_dominated_rebroadcast(client):
    """update/2's extras surface over the grid wire: a dominated add
    returns its re-broadcast removal {rmv, Key, Id, VcList}
    (topk_rmv.erl:234-237) that the host can feed straight back into
    replication — same term shape the rmv INPUT op uses."""
    client.grid_new("gx", "topk_rmv", n_replicas=2, n_keys=1, n_ids=32,
                    n_dcs=2, size=4)
    # Replica 1 removes id 7 at vc {0: 5}; a later add of id 7 with a
    # stale ts at dc 0 ON THAT REPLICA is dominated by the stored
    # tombstone and must bounce the rmv back (rows are independent
    # replica states — a tombstone only dominates within its own row
    # until a merge ships it).
    assert client.grid_apply_extras("gx", [[], [rmv(0, 7, {0: 5})]]) == [[], []]
    extras = client.grid_apply_extras("gx", [[], [add(0, 7, 99, 0, 3)]])
    assert extras[0] == []
    assert extras[1] == [(Atom("rmv"), 0, 7, [(0, 5)])]
    # The dominated add did not enter the observable.
    client.grid_merge_all("gx")
    assert dict(client.grid_observe("gx", 0)) == {}
    # A fresh add survives and generates no extras.
    assert client.grid_apply_extras("gx", [[add(0, 3, 50, 1, 1)], []]) == [[], []]
    # Promotion extra: id 9 has an observed best (90 @ dc0) and a masked
    # runner-up (70 @ dc1); a removal dominating only the dc0 add
    # uncovers the masked element, which must re-broadcast as a plain
    # add in the grid's own op shape (reference :291-295).
    client.grid_apply("gx", [[add(0, 9, 90, 0, 1), add(0, 9, 70, 1, 1)], []])
    extras = client.grid_apply_extras("gx", [[rmv(0, 9, {0: 1})], []])
    assert (Atom("add"), 0, 9, 70, 1, 1) in extras[0], extras
    # ...and it feeds straight back into another replica.
    client.grid_apply("gx", [[], extras[0]])


def test_grid_apply_extras_leaderboard_promotion(client):
    """Ban-promotion extras over the wire (leaderboard.erl:279-283): a
    ban that opens a board slot re-broadcasts the newly visible player as
    a plain add {add, Key, Id, Score} — the grid's own op shape, so the
    host feeds it straight back (the add_r replicate-tag distinction is
    the scalar surface's is_replicate_tagged concern)."""
    client.grid_new("gxl", "leaderboard", n_replicas=1, n_keys=1,
                    n_players=16, size=2)
    # Fill the K=2 board with 10/9; 8 stays masked below the board.
    assert client.grid_apply_extras("gxl", [[
        (Atom("add"), 0, 1, 10), (Atom("add"), 0, 2, 9), (Atom("add"), 0, 3, 8),
    ]]) == [[]]
    # Banning player 1 promotes the masked player 3 into the board; the
    # extra is the grid's own add shape, so it feeds straight back.
    extras = client.grid_apply_extras("gxl", [[(Atom("ban"), 0, 1)]])
    assert extras == [[(Atom("add"), 0, 3, 8)]]
    client.grid_apply("gxl", extras)  # re-broadcast round trip
    assert dict(client.grid_observe("gxl", 0)) == {2: 9, 3: 8}


def test_grid_apply_extras_other_types_empty(client):
    client.grid_new("gxa", "average", n_replicas=2, n_keys=1)
    out = client.grid_apply_extras("gxa", [[(Atom("add"), 0, 5, 1)], []])
    assert out == [[], []]
    assert client.grid_observe("gxa", 0, 0) == (5, 1)  # state still applied


def test_grid_compact_differential_through_grid_wire(client):
    """VERDICT-r3 item 2's done criterion: an effect log and its
    grid_compact'ed form, both replayed THROUGH THE GRID WIRE, reach the
    same observable state. Also pins: fewer ops out than in, rmv fusion
    to one op per id, and agreement with the scalar pairwise `compact`
    protocol's replay."""
    import numpy as np

    rng = np.random.default_rng(3)
    frontier = {}
    effects = []
    # Id space wide enough vs the grid's slots_per_id that dominated adds
    # never crowd a live add out of the raw batch's M ranks (the `lossy`
    # divergence, where compaction legitimately preserves MORE history
    # than a raw overfull batch).
    for _ in range(120):
        d = int(rng.integers(0, 3))
        i = int(rng.integers(0, 96))
        if rng.random() < 0.3:
            vc = {dd: max(0, t - int(rng.integers(0, 2))) for dd, t in frontier.items()}
            vc = {dd: t for dd, t in vc.items() if t > 0}
            effects.append((Atom("rmv"), (i, vc)))
        else:
            frontier[d] = frontier.get(d, 0) + 1
            effects.append((Atom("add"), (i, int(rng.integers(1, 999)), (d, frontier[d]))))
        if rng.random() < 0.1:  # duplicated delivery
            effects.append(effects[-1])

    compacted = client.grid_compact("topk_rmv", effects)
    assert 0 < len(compacted) < len(effects)
    rmv_ids = [t[1][0] for t in compacted if str(t[0]).startswith("rmv")]
    assert len(rmv_ids) == len(set(rmv_ids))

    def to_grid(ops):
        out = []
        for t in ops:
            kind = str(t[0])
            if kind.startswith("add"):
                i, score, (d, ts) = t[1]
                out.append(add(0, int(i), int(score), int(d), int(ts)))
            else:
                i, vc = t[1]
                out.append(rmv(0, int(i), {int(d): int(ts) for d, ts in dict(vc).items()}))
        return out

    client.grid_new("gcraw", n_replicas=1, n_keys=1, n_ids=96, n_dcs=3,
                    size=8, slots_per_id=8)
    client.grid_new("gccmp", n_replicas=1, n_keys=1, n_ids=96, n_dcs=3,
                    size=8, slots_per_id=8)
    client.grid_apply("gcraw", [to_grid(effects)])
    client.grid_apply("gccmp", [to_grid(compacted)])
    assert client.grid_observe("gcraw", 0) == client.grid_observe("gccmp", 0)

    # Scalar pairwise `compact` (the reference's can_compact/compact_ops
    # walk) replays to the same observable too — two implementations of
    # one contract. On a prefix: the pairwise protocol is O(L^3) (it
    # rescans from the top after every fusion), which is the point of the
    # vectorized whole-log pass.
    prefix = effects[:30]
    h1 = client.new("topk_rmv", 8)
    pairwise = client.compact(h1, prefix)
    h2 = client.new("topk_rmv", 8)
    for e in pairwise:
        client.update(h2, e)
    h3 = client.new("topk_rmv", 8)
    for e in client.grid_compact("topk_rmv", prefix):
        client.update(h3, e)
    assert sorted(client.value(h2)) == sorted(client.value(h3))

    with pytest.raises(Exception, match="no whole-log compactor"):
        client.grid_compact("mystery", [])


# --- robustness PR: structured errors, deadlines, idempotent resends -------


def test_structured_error_frame_on_the_wire(server):
    """Errors ship as {error, {Kind, Msg}} — Kind an atom a BEAM host can
    dispatch on — and every one bumps the server's error counters."""
    before = server.metrics.counters.get("bridge.errors", 0)
    with socket.create_connection(server.address, timeout=10) as sk:
        payload = etf.encode((Atom("call"), 42, (Atom("bogus"), 1)))
        sk.sendall(struct.pack(">I", len(payload)) + payload)
        hdr = sk.recv(4, socket.MSG_WAITALL)
        (n,) = struct.unpack(">I", hdr)
        data = b""
        while len(data) < n:
            data += sk.recv(n - len(data))
        term = etf.decode(data)
    assert term[0] == Atom("reply") and term[1] == 42
    err = term[2]
    assert err[0] == Atom("error")
    kind, msg = err[1]
    assert kind == Atom("ValueError")
    assert b"unknown op" in msg
    assert server.metrics.counters.get("bridge.errors", 0) == before + 1
    assert server.metrics.counters.get("bridge.errors.ValueError", 0) >= 1


def test_malformed_request_gets_bad_request_kind(server):
    with socket.create_connection(server.address, timeout=10) as sk:
        payload = etf.encode((Atom("whatever"), 1, 2, 3, 4))
        sk.sendall(struct.pack(">I", len(payload)) + payload)
        hdr = sk.recv(4, socket.MSG_WAITALL)
        (n,) = struct.unpack(">I", hdr)
        data = b""
        while len(data) < n:
            data += sk.recv(n - len(data))
        term = etf.decode(data)
    rid, ok, payload = P.parse_reply(term)
    assert not ok
    assert "bad_request" in P.error_text(payload)


def test_error_text_legacy_bare_binary():
    """Old peers send {error, Binary}: the decode path must keep
    rendering it (compat with pre-structured-error servers)."""
    assert "boom" in P.error_text(b"boom")
    assert "KeyError: 9" == P.error_text((Atom("KeyError"), b"9"))


def test_icall_resend_replays_cached_reply(server):
    """The idempotency contract, raw on the wire: the SAME (token, req
    id) sent twice executes once — the second reply is served from the
    cache (bridge.replays) and is byte-identical."""
    replays_before = server.metrics.counters.get("bridge.replays", 0)
    with socket.create_connection(server.address, timeout=10) as sk:
        def rpc(term):
            payload = etf.encode(term)
            sk.sendall(struct.pack(">I", len(payload)) + payload)
            hdr = sk.recv(4, socket.MSG_WAITALL)
            (n,) = struct.unpack(">I", hdr)
            data = b""
            while len(data) < n:
                data += sk.recv(n - len(data))
            return etf.decode(data)

        token = b"tok-test-1"
        r1 = rpc((Atom("icall"), token, 1, (Atom("new"), Atom("average"), [])))
        h = r1[2][1]
        up = (Atom("icall"), token, 2, (Atom("update"), h, (Atom("add"), (5, 1))))
        first = rpc(up)
        second = rpc(up)  # resend: must NOT double-apply
        assert first == second
        r = rpc((Atom("icall"), token, 3, (Atom("to_binary"), h)))
        state = wire.from_reference_binary("average", r[2][1])
    assert state == (5, 1)  # one application, not (10, 2)
    assert server.metrics.counters.get("bridge.replays", 0) == replays_before + 1


def test_read_deadline_reaps_idle_connection():
    """A half-open client holding a connection without sending frames is
    dropped at the read deadline instead of pinning a thread forever."""
    import time

    with BridgeServer(read_deadline=0.3) as srv:
        with socket.create_connection(srv.address, timeout=10) as sk:
            deadline = time.time() + 8.0
            dropped = False
            while time.time() < deadline:
                try:
                    if sk.recv(1) == b"":
                        dropped = True
                        break
                except OSError:
                    dropped = True
                    break
            assert dropped, "idle connection was never reaped"
        assert srv.metrics.counters.get("bridge.read_deadline_drops", 0) >= 1
        # An ACTIVE client inside the deadline still works.
        with BridgeClient(*srv.address, timeout=5.0) as c:
            assert c.value(c.new("average")) == 0.0


def test_client_timeout_is_constructor_configurable():
    """The 30s hardwired timeout is gone: the constructor value applies
    to connect AND to every reply read, end to end."""
    import threading
    import time

    lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lst.bind(("127.0.0.1", 0))
    lst.listen(1)
    addr = lst.getsockname()
    holes = []

    def accept_and_hold():
        conn, _ = lst.accept()
        holes.append(conn)  # accept, then never reply

    t = threading.Thread(target=accept_and_hold, daemon=True)
    t.start()
    try:
        c = BridgeClient(*addr, timeout=0.4)
        assert c._sock.gettimeout() == 0.4
        t0 = time.time()
        with pytest.raises(Exception):
            c.call((Atom("value"), 1))
        elapsed = time.time() - t0
        assert elapsed < 5.0  # the old hardwired 30s would hang here
        c.close()
    finally:
        for conn in holes:
            conn.close()
        lst.close()
