"""Smoke the per-type benchmark suite (benchmarks/bench_all.py) end to
end on the CPU backend: every BASELINE.md per-type config must keep
producing its JSON record (the driver and BASELINE.md cite these —
signature rot here corrupts the perf record, not just a test)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_all_emits_every_config():
    from conftest import cpu_subprocess_env

    env = cpu_subprocess_env(CCRDT_BENCH_TINY="1")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "bench_all.py")],
        capture_output=True, text=True, timeout=560, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    recs = [json.loads(l) for l in out.stdout.splitlines() if l.startswith("{")]
    metrics = " ".join(r["metric"] for r in recs)
    for frag in (
        "average", "topk adds", "leaderboard", "wordcount tokens",
        "delta-state publish", "monoid row-replace", "worddocumentcount corpus",
    ):
        assert frag in metrics, f"missing bench config: {frag}"
    assert all(r["value"] > 0 for r in recs)
