"""Pin the driver-facing entry points (`__graft_entry__.py`).

Round-1 regression: the driver imports the module on the default backend
(1-chip axon tunnel) and calls ``dryrun_multichip(8)`` directly — it does
NOT run the ``__main__`` block — so the function must self-provision an
8-device CPU backend (MULTICHIP_r01 failed rc=1 on exactly this).
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import __graft_entry__ as graft  # noqa: E402


def test_entry_compiles_and_runs():
    import jax

    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (2, 2, 16)


def test_dryrun_multichip_in_process():
    # conftest provisioned 8 CPU devices, so this exercises the full impl
    # (all reconciliation planes + convergence asserts) without re-exec.
    graft.dryrun_multichip(8)


def test_reexec_child_guard_raises():
    # The child must never re-exec again: if provisioning failed once it
    # fails forever, and recursion would hang the driver.
    os.environ["CCRDT_DRYRUN_CHILD"] = "1"
    try:
        with pytest.raises(RuntimeError, match="provisioning failed"):
            graft._reexec_dryrun_on_cpu_mesh(8)
    finally:
        del os.environ["CCRDT_DRYRUN_CHILD"]


@pytest.mark.skipif(
    not os.environ.get("CCRDT_SLOW_TESTS"),
    reason="full driver-style subprocess run (two backend startups); "
    "set CCRDT_SLOW_TESTS=1",
)
def test_driver_style_subprocess_self_provisions():
    # Exactly what the driver does: import the module on the DEFAULT
    # backend and call dryrun_multichip(8). Must self-provision.
    code = "import __graft_entry__ as g; g.dryrun_multichip(8)"
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO,
        capture_output=True, text=True, timeout=560,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "dryrun_multichip OK" in proc.stdout
