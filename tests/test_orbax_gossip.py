"""Orbax-backed cross-site gossip (parallel/orbax_gossip.py): two "sites"
holding the same logical grid under DIFFERENT mesh shardings exchange
snapshots through the store and converge via the engine join — the
geo-DR plane for mesh-sharded states."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from antidote_ccrdt_tpu.harness import orbax_ckpt
from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps, make_dense
from antidote_ccrdt_tpu.parallel.orbax_gossip import OrbaxGossip, available

pytestmark = pytest.mark.skipif(
    not available(), reason="orbax-checkpoint not installed"
)

R, NK, I, DCS = 4, 2, 64, 4
D = make_dense(n_ids=I, n_dcs=DCS, size=8, slots_per_id=2)


def site_sharding(dev_slice, axis_dims):
    mesh = Mesh(np.asarray(dev_slice).reshape(*axis_dims), ("dc", "key"))
    return NamedSharding(mesh, P("dc", "key"))


def place(state, sharding):
    return jax.tree.map(lambda x: jax.device_put(x, sharding), state)


def ops_for(seed, row):
    rng = np.random.default_rng(seed)
    B, Br = 24, 6
    row_mask = (np.arange(R) == row)[:, None]
    return TopkRmvOps(
        add_key=jnp.asarray(rng.integers(0, NK, (R, B)).astype(np.int32)),
        add_id=jnp.asarray(rng.integers(0, I, (R, B)).astype(np.int32)),
        add_score=jnp.asarray(rng.integers(1, 900, (R, B)).astype(np.int32)),
        add_dc=jnp.asarray(rng.integers(0, DCS, (R, B)).astype(np.int32)),
        add_ts=jnp.asarray(
            (rng.integers(1, 90, (R, B)) * row_mask).astype(np.int32)
        ),
        rmv_key=jnp.asarray(rng.integers(0, NK, (R, Br)).astype(np.int32)),
        rmv_id=jnp.asarray(
            np.where(row_mask[:, :1].repeat(Br, 1),
                     rng.integers(0, I, (R, Br)), -1).astype(np.int32)
        ),
        rmv_vc=jnp.asarray(rng.integers(0, 40, (R, Br, DCS)).astype(np.int32)),
    )


def test_cross_site_sharded_gossip_converges(tmp_path):
    devs = jax.devices()
    assert len(devs) >= 8
    # Site A: 4x1 mesh over devices 0-3; site B: 2x2 over devices 4-7 —
    # deliberately different mesh shapes AND device sets.
    sh_a = site_sharding(devs[:4], (4, 1))
    sh_b = site_sharding(devs[4:8], (2, 2))

    sa = place(D.init(R, NK), sh_a)
    sb = place(D.init(R, NK), sh_b)
    sa, _ = D.apply_ops(sa, ops_for(1, row=0))
    sb, _ = D.apply_ops(sb, ops_for(2, row=1))

    with OrbaxGossip(str(tmp_path), "site-a") as ga, \
         OrbaxGossip(str(tmp_path), "site-b") as gb:
        ga.publish(sa, step=1)
        gb.publish(sb, step=1)
        assert set(ga.snapshot_members()) == {"site-a", "site-b"}

        sa2, n_a = ga.sweep(D, sa)
        sb2, n_b = gb.sweep(D, sb)
        assert n_a == 1 and n_b == 1
        # Both sites hold the same observable after one exchange (compare
        # via host values — the states live on disjoint device sets).
        assert D.value(sa2) == D.value(sb2)
        # ...and each site's state still lives in ITS OWN shardings.
        dev_set = {
            d for leaf in jax.tree.leaves(sa2) for d in leaf.devices()
        }
        assert dev_set <= set(devs[:4]), "site A state left its mesh"

        # Second exchange must carry NEW data (regression: a reader
        # manager that never reloads pins the peer's first-seen step and
        # gossip silently stops converging after one exchange). Apply
        # fresh ops on site A, re-publish, and require site B to see them.
        sa3, _ = D.apply_ops(sa2, ops_for(7, row=2))
        ga.publish(sa3, step=2)
        cursors: dict = {}
        sb3, n1 = gb.sweep(D, sb2, cursors)
        assert n1 == 1
        assert D.value(sb3) == D.value(sa3)
        # Cursor-aware sweep skips the not-advanced peer entirely.
        sb4, n2 = gb.sweep(D, sb3, cursors)
        assert n2 == 0
        assert D.value(sb4) == D.value(sb3)


def test_fetch_failures_are_skipped(tmp_path):
    sa = D.init(R, NK)
    with OrbaxGossip(str(tmp_path), "a") as ga:
        ga.publish(sa, step=0)
        # Unknown peer and a garbage ckpt dir both read as "nothing yet".
        assert ga.fetch("ghost", sa) is None
        import os

        os.makedirs(os.path.join(str(tmp_path), "ckpt-junk", "5"))
        state2, n = ga.sweep(D, sa)
        assert n == 0
        assert D.equal(state2, sa)


def test_cross_site_monoid_gossip_via_lift(tmp_path):
    """The MONOID half of the geo-DR plane (round 3): OrbaxGossip.sweep
    auto-lifts a raw monoid engine, rejects raw (unversioned) states, and
    converges lifted average states across two sites exactly — repeated
    sweeps of stale snapshots must not double-count."""
    from antidote_ccrdt_tpu.models.average import AverageDense, AverageOps
    from antidote_ccrdt_tpu.parallel.monoid import MonoidContributor, MonoidLift

    dense = AverageDense()
    lift = MonoidLift(dense)

    def avg_ops(rows, seed):
        rng = np.random.default_rng(seed)
        key = np.zeros((R, 8), np.int32)
        val = np.zeros((R, 8), np.int32)
        cnt = np.zeros((R, 8), np.int32)
        for r in set(rows):
            key[r] = rng.integers(0, NK, 8)
            val[r] = rng.integers(1, 50, 8)
            cnt[r] = 1
        return AverageOps(jnp.asarray(key), jnp.asarray(val), jnp.asarray(cnt))

    # Site A writes rows {0, 1}; site B rows {2, 3}.
    ca = MonoidContributor(lift, R, NK)
    cb = MonoidContributor(lift, R, NK)
    ca.apply(avg_ops([0, 1], 1), owned=[0, 1])
    cb.apply(avg_ops([2, 3], 2), owned=[2, 3])

    with OrbaxGossip(str(tmp_path), "siteA") as ga, OrbaxGossip(
        str(tmp_path), "siteB"
    ) as gb:
        with pytest.raises(TypeError, match="MonoidLift"):
            ga.sweep(dense, dense.init(R, NK))  # raw state rejected
        ga.publish(ca.view, step=1)
        gb.publish(cb.view, step=1)
        swept_a, n_a = ga.sweep(dense, ca.view)  # raw ENGINE auto-lifts
        ca.absorb(swept_a)
        for _ in range(2):  # duplicate sweeps: idempotent by row-replace
            swept_b, n_b = gb.sweep(lift, cb.view)
            cb.absorb(swept_b)
            # Cursorless sweeps re-fetch every time — pin that the stale
            # re-merge path actually executes on the repeat.
            assert n_b == 1
    assert n_a == 1

    ref = lift.init(R, NK)
    ref, _ = lift.apply_ops(ref, avg_ops([0, 1], 1), owned=[0, 1])
    ref, _ = lift.apply_ops(ref, avg_ops([2, 3], 2), owned=[2, 3])
    for c in (ca, cb):
        tot = lift.total(c.view)
        rtot = lift.total(ref)
        assert np.array_equal(np.asarray(tot.sum), np.asarray(rtot.sum))
        assert np.array_equal(np.asarray(tot.num), np.asarray(rtot.num))
