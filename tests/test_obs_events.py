"""Flight recorder (obs/events.py): bounded ring semantics, the
line-buffered JSONL spill, trace-context grouping (`delta_paths`), and
the crash-durability contract — a SIGKILLed process leaves a readable
dump with no ``proc.exit`` trailer (the same real-subprocess pattern
tests/test_crash_recovery.py drills at fleet scale)."""

import json
import os
import signal
import subprocess
import sys
import time

from antidote_ccrdt_tpu.obs import events as obs_events
from antidote_ccrdt_tpu.obs.events import FlightRecorder

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_ring_is_bounded_and_seq_monotonic():
    rec = FlightRecorder(member="m", ring=8)
    for i in range(20):
        rec.emit("tick", i=i)
    evs = rec.events()
    # Overflow evicts the OLDEST events; the ring never grows past bound.
    assert len(evs) == 8
    assert [e["i"] for e in evs] == list(range(12, 20))
    assert [e["seq"] for e in evs] == list(range(12, 20))
    assert all(e["member"] == "m" for e in evs)
    # seq keeps counting across eviction — it is the process ordinal,
    # not a ring index.
    nxt = rec.emit("tick", i=20)
    assert nxt["seq"] == 20


def test_events_filter_and_dump(tmp_path):
    rec = FlightRecorder(member="m")
    rec.emit("a.x", v=1)
    rec.emit("b.y", v=2)
    rec.emit("a.x", v=3)
    assert [e["v"] for e in rec.events("a.x")] == [1, 3]
    out = str(tmp_path / "dump.jsonl")
    assert rec.dump(out) == 3
    assert [e["kind"] for e in obs_events.read_log(out)] == ["a.x", "b.y", "a.x"]


def test_spill_is_continuous_and_torn_tail_skipped(tmp_path):
    spill = str(tmp_path / "flight-m-1.jsonl")
    rec = FlightRecorder(member="m", spill_path=spill)
    rec.emit("one")
    rec.emit("two")
    # Line-buffered: both events are on disk BEFORE close — that is the
    # property the post-SIGKILL dump depends on.
    assert len(obs_events.read_log(spill)) == 2
    rec.close()
    # A kill can land mid-write of the final line; readers must skip it.
    with open(spill, "a") as f:
        f.write('{"kind": "torn-half')
    evs = obs_events.read_log(spill)
    assert [e["kind"] for e in evs] == ["one", "two"]


def test_configure_reset_and_module_surface(tmp_path):
    obs_events.reset("w9", ring=16)
    obs_events.emit("hello", x=1)
    assert obs_events.events("hello")[0]["member"] == "w9"
    # configure() with a spill dir names the file per (member, pid) and
    # opens the log with proc.start.
    rec = obs_events.configure("w9", spill_dir=str(tmp_path), crash_hooks=False)
    expect = str(tmp_path / f"flight-w9-{os.getpid()}.jsonl")
    assert rec.spill_path == expect
    obs_events.emit("after")
    kinds = [e["kind"] for e in obs_events.read_log(expect)]
    assert kinds == ["proc.start", "after"]
    obs_events.reset()


def test_install_from_env_gating(tmp_path):
    # Without the env var: in-memory only, member identity still applied.
    assert obs_events.install_from_env("w0", env={}) is False
    assert obs_events.recorder().spill_path is None
    # With it: spill enabled under the named dir.
    d = str(tmp_path / "obs")
    assert obs_events.install_from_env("w0", env={obs_events.ENV_DIR: d})
    assert obs_events.recorder().spill_path.startswith(d)
    obs_events.reset()


def test_delta_paths_groups_by_trace_context():
    logs = {
        "flight-a.jsonl": [
            {"kind": "delta.publish", "member": "a", "origin": "a", "dseq": 3},
            {"kind": "transport.delta_write", "member": "a", "origin": "a",
             "dseq": 3},
            {"kind": "wal.append", "member": "a", "wseq": 3},  # no context
        ],
        "flight-b.jsonl": [
            {"kind": "delta.fetch", "member": "b", "origin": "a", "dseq": 3},
            {"kind": "delta.apply", "member": "b", "origin": "a", "dseq": 3},
            {"kind": "delta.apply", "member": "b", "origin": "c", "dseq": 0},
        ],
    }
    paths = obs_events.delta_paths(logs)
    assert set(paths) == {("a", 3), ("c", 0)}
    a3 = paths[("a", 3)]
    assert sorted(a3) == ["apply", "fetch", "publish", "write"]
    assert [e["member"] for e in a3["apply"]] == ["b"]
    assert list(obs_events.iter_kinds(logs, "wal.append"))[0]["wseq"] == 3


# -- real-subprocess crash durability ---------------------------------------

_CHILD = """
import os, sys, time
sys.path.insert(0, {repo!r})
from antidote_ccrdt_tpu.obs import events as obs_events

obs_events.install_from_env("victim")
for i in range(5):
    obs_events.emit("work.step", i=i)
print("READY", flush=True)
time.sleep({linger})
"""


def _spawn_child(tmp_path, obs_dir, linger):
    env = dict(os.environ)
    env[obs_events.ENV_DIR] = obs_dir
    return subprocess.Popen(
        [sys.executable, "-c", _CHILD.format(repo=REPO, linger=linger)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, text=True,
    )


def _flight_path(obs_dir, pid):
    return os.path.join(obs_dir, f"flight-victim-{pid}.jsonl")


def test_sigkill_leaves_crash_dump_without_proc_exit(tmp_path):
    """The acceptance contract of the crash flight recorder: kill -9 a
    worker and its spill still holds every emitted event, with NO
    proc.exit trailer marking it as a clean shutdown."""
    obs_dir = str(tmp_path / "obs")
    p = _spawn_child(tmp_path, obs_dir, linger=30)
    try:
        assert p.stdout.readline().strip() == "READY"
        os.kill(p.pid, signal.SIGKILL)  # no handler can observe this
        p.wait(timeout=10)
    finally:
        if p.poll() is None:
            p.kill()
    evs = obs_events.read_log(_flight_path(obs_dir, p.pid))
    kinds = [e["kind"] for e in evs]
    assert kinds[0] == "proc.start"
    assert kinds.count("work.step") == 5  # every pre-kill event survived
    assert "proc.exit" not in kinds  # the crash-dump discriminator
    assert [e["seq"] for e in evs] == sorted(e["seq"] for e in evs)


def test_clean_exit_writes_proc_exit_trailer(tmp_path):
    obs_dir = str(tmp_path / "obs")
    p = _spawn_child(tmp_path, obs_dir, linger=0)
    out, _ = p.communicate(timeout=30)
    assert p.returncode == 0, out
    kinds = [e["kind"] for e in obs_events.read_log(_flight_path(obs_dir, p.pid))]
    assert kinds[0] == "proc.start" and kinds[-1] == "proc.exit"


def test_sigterm_also_stamps_proc_exit(tmp_path):
    """TERM is catchable: the exit hooks stamp the trailer, then chain to
    the default action (the process still dies by the signal)."""
    obs_dir = str(tmp_path / "obs")
    p = _spawn_child(tmp_path, obs_dir, linger=30)
    try:
        assert p.stdout.readline().strip() == "READY"
        p.terminate()
        p.wait(timeout=10)
    finally:
        if p.poll() is None:
            p.kill()
    deadline = time.time() + 5
    kinds = []
    while time.time() < deadline:
        kinds = [e["kind"] for e in
                 obs_events.read_log(_flight_path(obs_dir, p.pid))]
        if "proc.exit" in kinds:
            break
        time.sleep(0.05)
    assert "proc.exit" in kinds, kinds
