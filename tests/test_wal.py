"""harness/wal.py: the crash-consistent write-ahead delta log.

Covers the generic segmented log (framing, rotation, torn-tail repair,
compaction watermark) and the elastic-worker discipline on top
(checkpoint ⊔ WAL-suffix recovery for both engine families). The
real-process kill/restart drill lives in scripts/crash_recovery_demo.py
(tests/test_crash_recovery.py, slow).
"""

import os
import struct

import pytest

from antidote_ccrdt_tpu.harness.wal import ElasticWal, WriteAheadLog
from antidote_ccrdt_tpu.utils import faults
from antidote_ccrdt_tpu.utils.metrics import Metrics


@pytest.fixture(autouse=True)
def _no_faults():
    faults.uninstall()
    yield
    faults.uninstall()


# --- WriteAheadLog ---------------------------------------------------------


def test_append_records_roundtrip(tmp_path):
    w = WriteAheadLog(str(tmp_path))
    payloads = [(i, bytes([i]) * (i + 1)) for i in range(8)]
    for seq, p in payloads:
        w.append(seq, p)
    assert list(w.records()) == payloads
    assert w.last_seq == 7
    w.close()
    # A fresh open over the same directory sees the same records.
    w2 = WriteAheadLog(str(tmp_path))
    assert list(w2.records()) == payloads
    assert w2.torn_bytes == 0
    w2.close()


def test_rotation_and_compaction_watermark(tmp_path):
    w = WriteAheadLog(str(tmp_path), segment_bytes=64)
    for i in range(10):
        w.append(i, b"x" * 20)
    segs = [f for f in os.listdir(tmp_path) if f.endswith(".wal")]
    assert len(segs) > 1  # rotation happened
    removed = w.compact(4)
    assert removed > 0
    # Every record ABOVE the watermark survives compaction.
    assert [s for s, _ in w.records()] == list(range(5, 10))
    # The active segment is never removed, even if fully covered.
    assert w.compact(10_000) >= 0
    assert any(s >= 9 for s, _ in w.records())
    w.close()


def test_torn_tail_truncated_on_open(tmp_path):
    w = WriteAheadLog(str(tmp_path))
    for i in range(5):
        w.append(i, b"payload-%d" % i)
    w.close()
    seg = os.path.join(tmp_path, sorted(os.listdir(tmp_path))[-1])
    size = os.path.getsize(seg)
    os.truncate(seg, size - 3)  # torn mid-record, as a crash would leave it
    w2 = WriteAheadLog(str(tmp_path))
    assert w2.torn_bytes > 0
    assert [s for s, _ in w2.records()] == [0, 1, 2, 3]
    assert w2.last_seq == 3
    # Appends land after the repaired tail, not after garbage.
    w2.append(9, b"after-repair")
    assert [s for s, _ in w2.records()] == [0, 1, 2, 3, 9]
    w2.close()
    w3 = WriteAheadLog(str(tmp_path))
    assert [s for s, _ in w3.records()] == [0, 1, 2, 3, 9]
    assert w3.torn_bytes == 0
    w3.close()


def test_corrupt_crc_truncates_like_a_tear(tmp_path):
    w = WriteAheadLog(str(tmp_path))
    for i in range(4):
        w.append(i, b"r%d" % i)
    w.close()
    seg = os.path.join(tmp_path, sorted(os.listdir(tmp_path))[0])
    data = bytearray(open(seg, "rb").read())
    data[-1] ^= 0xFF  # bit rot in the last record's payload
    with open(seg, "wb") as f:
        f.write(data)
    w2 = WriteAheadLog(str(tmp_path))
    assert [s for s, _ in w2.records()] == [0, 1, 2]
    w2.close()


def test_mid_segment_tear_drops_later_segments(tmp_path):
    w = WriteAheadLog(str(tmp_path), segment_bytes=64)
    for i in range(10):
        w.append(i, b"x" * 20)
    w.close()
    segs = sorted(f for f in os.listdir(tmp_path) if f.endswith(".wal"))
    assert len(segs) >= 3
    mid = os.path.join(tmp_path, segs[1])
    os.truncate(mid, os.path.getsize(mid) - 3)
    w2 = WriteAheadLog(str(tmp_path), segment_bytes=64)
    # Everything from the torn record on is gone — bytes past a tear
    # were never acknowledged, and seq order must stay contiguous.
    recs = [s for s, _ in w2.records()]
    assert recs == sorted(recs)
    assert max(recs) < 9
    w2.close()


def test_fsync_fault_surfaces_to_caller(tmp_path):
    w = WriteAheadLog(str(tmp_path))
    w.append(0, b"ok")
    with faults.injected({"wal.fsync": [{"action": "raise", "at": [0]}]}):
        with pytest.raises(faults.InjectedFault):
            w.append(1, b"doomed")
        w.append(2, b"recovered")  # the log object stays usable
    assert [s for s, _ in w.records()] == [0, 1, 2] or [
        s for s, _ in w.records()
    ] == [0, 2]
    w.close()


# --- ElasticWal ------------------------------------------------------------


def _drill(type_name):
    from scripts.elastic_demo import DRILLS

    drill = DRILLS[type_name]
    dense = drill.make_engine()
    return drill, dense, drill.init(dense)


def _log_steps(drill, dense, state, wal, steps, owned):
    for step in range(steps):
        pre = drill.pub_state(dense, state)
        state = drill.apply(dense, state, step, owned)
        wal.log_step(step, owned, pre, drill.pub_state(dense, state))
    return state


@pytest.mark.parametrize("type_name", ["topk_rmv", "average"])
def test_recover_matches_uninterrupted_run(tmp_path, type_name):
    drill, dense, state = _drill(type_name)
    wal = ElasticWal(str(tmp_path), "w0", dense, drill.publish_name)
    state = _log_steps(drill, dense, state, wal, 5, [0, 2])
    wal.close()
    ref = drill.digest(dense, state)

    drill2, dense2, state2 = _drill(type_name)
    m = Metrics()
    wal2 = ElasticWal(str(tmp_path), "w0", dense2, drill2.publish_name, metrics=m)
    rec, last_step, owned = wal2.recover(drill2.pub_state(dense2, state2))
    wal2.close()
    assert last_step == 4 and owned == {0, 2}
    assert m.counters.get("wal.recovered_records", 0) == 5
    state2 = drill2.set_view(dense2, state2, rec)
    assert drill2.digest(dense2, state2) == ref


def test_recover_checkpoint_join_wal_suffix(tmp_path):
    """Compaction up to the checkpoint step discards those records; the
    recovered state (checkpoint ⊔ remaining suffix) is still exact."""
    drill, dense, state = _drill("topk_rmv")
    wal = ElasticWal(str(tmp_path), "w0", dense, drill.publish_name,
                     segment_bytes=1 << 12)
    for step in range(6):
        pre = drill.pub_state(dense, state)
        state = drill.apply(dense, state, step, [1])
        wal.log_step(step, [1], pre, drill.pub_state(dense, state))
        if step == 3:
            wal.checkpoint(drill.pub_state(dense, state), step)
    wal.close()
    ref = drill.digest(dense, state)

    drill2, dense2, state2 = _drill("topk_rmv")
    m = Metrics()
    wal2 = ElasticWal(str(tmp_path), "w0", dense2, drill2.publish_name, metrics=m)
    rec, last_step, _ = wal2.recover(drill2.pub_state(dense2, state2))
    wal2.close()
    assert last_step == 5
    assert m.counters.get("wal.recovered_snapshot") == 1
    state2 = drill2.set_view(dense2, state2, rec)
    assert drill2.digest(dense2, state2) == ref


def test_recover_with_torn_final_record(tmp_path):
    """A crash mid-append loses exactly the torn record: recovery lands
    on the previous step and the restarted worker redoes the lost one —
    never replays garbage."""
    drill, dense, state = _drill("topk_rmv")
    wal = ElasticWal(str(tmp_path), "w0", dense, drill.publish_name)
    state = _log_steps(drill, dense, state, wal, 4, [0])
    wal.close()
    wal_dir = os.path.join(tmp_path, "wal-w0")
    seg = os.path.join(wal_dir, sorted(os.listdir(wal_dir))[-1])
    os.truncate(seg, os.path.getsize(seg) - 7)

    drill2, dense2, state2 = _drill("topk_rmv")
    wal2 = ElasticWal(str(tmp_path), "w0", dense2, drill2.publish_name)
    rec, last_step, _ = wal2.recover(drill2.pub_state(dense2, state2))
    wal2.close()
    assert last_step == 2  # step 3's record was the torn one

    # Redoing step 3 on the recovered state reproduces the full run.
    state2 = drill2.set_view(dense2, state2, rec)
    state2 = drill2.apply(dense2, state2, 3, [0])
    ref_state = _drill("topk_rmv")
    ref = ref_state[0].apply(ref_state[1], ref_state[2], 0, [0])
    for s in range(1, 4):
        ref = ref_state[0].apply(ref_state[1], ref, s, [0])
    assert drill2.digest(dense2, state2) == ref_state[0].digest(ref_state[1], ref)


def test_recover_empty_dir_is_noop(tmp_path):
    drill, dense, state = _drill("topk_rmv")
    wal = ElasticWal(str(tmp_path), "w9", dense, drill.publish_name)
    rec, last_step, owned = wal.recover(drill.pub_state(dense, state))
    wal.close()
    assert rec is None and last_step == -1 and owned == set()


def test_ckpt_replace_fault_preserves_previous_checkpoint(tmp_path):
    """An injected crash between the durable tmp write and the rename
    must leave the PREVIOUS checkpoint readable — the atomic-replace
    guarantee the recovery path depends on."""
    drill, dense, state = _drill("topk_rmv")
    wal = ElasticWal(str(tmp_path), "w0", dense, drill.publish_name)
    state = _log_steps(drill, dense, state, wal, 2, [0])
    wal.checkpoint(drill.pub_state(dense, state), 1)
    state = drill.apply(dense, state, 2, [0])
    with faults.injected({"ckpt.replace": [{"action": "raise", "at": [0]}]}):
        with pytest.raises(faults.InjectedFault):
            wal.checkpoint(drill.pub_state(dense, state), 2)
    wal.close()

    drill2, dense2, state2 = _drill("topk_rmv")
    m = Metrics()
    wal2 = ElasticWal(str(tmp_path), "w0", dense2, drill2.publish_name, metrics=m)
    rec, last_step, _ = wal2.recover(drill2.pub_state(dense2, state2))
    wal2.close()
    assert m.counters.get("wal.recovered_snapshot") == 1  # the step-1 one
    assert last_step == 1
    assert rec is not None


def test_garbage_snapshot_does_not_block_wal_replay(tmp_path):
    drill, dense, state = _drill("topk_rmv")
    wal = ElasticWal(str(tmp_path), "w0", dense, drill.publish_name)
    state = _log_steps(drill, dense, state, wal, 3, [0])
    wal.close()
    snap = os.path.join(tmp_path, "wal-w0", ElasticWal.SNAP)
    with open(snap, "wb") as f:
        f.write(struct.pack("<Q", 7) + b"not a checkpoint")
    drill2, dense2, state2 = _drill("topk_rmv")
    wal2 = ElasticWal(str(tmp_path), "w0", dense2, drill2.publish_name)
    rec, last_step, _ = wal2.recover(drill2.pub_state(dense2, state2))
    wal2.close()
    assert last_step == 2 and rec is not None
    state2 = drill2.set_view(dense2, state2, rec)
    assert drill2.digest(dense2, state2) == drill.digest(dense, state)
