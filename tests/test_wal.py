"""harness/wal.py: the crash-consistent write-ahead delta log.

Covers the generic segmented log (framing, rotation, torn-tail repair,
compaction watermark) and the elastic-worker discipline on top
(checkpoint ⊔ WAL-suffix recovery for both engine families). The
real-process kill/restart drill lives in scripts/crash_recovery_demo.py
(tests/test_crash_recovery.py, slow).
"""

import os
import struct

import pytest

from antidote_ccrdt_tpu.harness.wal import ElasticWal, WriteAheadLog
from antidote_ccrdt_tpu.utils import faults
from antidote_ccrdt_tpu.utils.metrics import Metrics


@pytest.fixture(autouse=True)
def _no_faults():
    faults.uninstall()
    yield
    faults.uninstall()


# --- WriteAheadLog ---------------------------------------------------------


def test_append_records_roundtrip(tmp_path):
    w = WriteAheadLog(str(tmp_path))
    payloads = [(i, bytes([i]) * (i + 1)) for i in range(8)]
    for seq, p in payloads:
        w.append(seq, p)
    assert list(w.records()) == payloads
    assert w.last_seq == 7
    w.close()
    # A fresh open over the same directory sees the same records.
    w2 = WriteAheadLog(str(tmp_path))
    assert list(w2.records()) == payloads
    assert w2.torn_bytes == 0
    w2.close()


def test_rotation_and_compaction_watermark(tmp_path):
    w = WriteAheadLog(str(tmp_path), segment_bytes=64)
    for i in range(10):
        w.append(i, b"x" * 20)
    segs = [f for f in os.listdir(tmp_path) if f.endswith(".wal")]
    assert len(segs) > 1  # rotation happened
    removed = w.compact(4)
    assert removed > 0
    # Every record ABOVE the watermark survives compaction.
    assert [s for s, _ in w.records()] == list(range(5, 10))
    # The active segment is never removed, even if fully covered.
    assert w.compact(10_000) >= 0
    assert any(s >= 9 for s, _ in w.records())
    w.close()


def test_torn_tail_truncated_on_open(tmp_path):
    w = WriteAheadLog(str(tmp_path))
    for i in range(5):
        w.append(i, b"payload-%d" % i)
    w.close()
    seg = os.path.join(tmp_path, sorted(os.listdir(tmp_path))[-1])
    size = os.path.getsize(seg)
    os.truncate(seg, size - 3)  # torn mid-record, as a crash would leave it
    w2 = WriteAheadLog(str(tmp_path))
    assert w2.torn_bytes > 0
    assert [s for s, _ in w2.records()] == [0, 1, 2, 3]
    assert w2.last_seq == 3
    # Appends land after the repaired tail, not after garbage.
    w2.append(9, b"after-repair")
    assert [s for s, _ in w2.records()] == [0, 1, 2, 3, 9]
    w2.close()
    w3 = WriteAheadLog(str(tmp_path))
    assert [s for s, _ in w3.records()] == [0, 1, 2, 3, 9]
    assert w3.torn_bytes == 0
    w3.close()


def test_corrupt_crc_truncates_like_a_tear(tmp_path):
    w = WriteAheadLog(str(tmp_path))
    for i in range(4):
        w.append(i, b"r%d" % i)
    w.close()
    seg = os.path.join(tmp_path, sorted(os.listdir(tmp_path))[0])
    data = bytearray(open(seg, "rb").read())
    data[-1] ^= 0xFF  # bit rot in the last record's payload
    with open(seg, "wb") as f:
        f.write(data)
    w2 = WriteAheadLog(str(tmp_path))
    assert [s for s, _ in w2.records()] == [0, 1, 2]
    w2.close()


def test_mid_segment_tear_drops_later_segments(tmp_path):
    w = WriteAheadLog(str(tmp_path), segment_bytes=64)
    for i in range(10):
        w.append(i, b"x" * 20)
    w.close()
    segs = sorted(f for f in os.listdir(tmp_path) if f.endswith(".wal"))
    assert len(segs) >= 3
    mid = os.path.join(tmp_path, segs[1])
    os.truncate(mid, os.path.getsize(mid) - 3)
    w2 = WriteAheadLog(str(tmp_path), segment_bytes=64)
    # Everything from the torn record on is gone — bytes past a tear
    # were never acknowledged, and seq order must stay contiguous.
    recs = [s for s, _ in w2.records()]
    assert recs == sorted(recs)
    assert max(recs) < 9
    w2.close()


def test_fsync_fault_surfaces_to_caller(tmp_path):
    w = WriteAheadLog(str(tmp_path))
    w.append(0, b"ok")
    with faults.injected({"wal.fsync": [{"action": "raise", "at": [0]}]}):
        with pytest.raises(faults.InjectedFault):
            w.append(1, b"doomed")
        w.append(2, b"recovered")  # the log object stays usable
    assert [s for s, _ in w.records()] == [0, 1, 2] or [
        s for s, _ in w.records()
    ] == [0, 2]
    w.close()


# --- ElasticWal ------------------------------------------------------------


def _drill(type_name):
    from scripts.elastic_demo import DRILLS

    drill = DRILLS[type_name]
    dense = drill.make_engine()
    return drill, dense, drill.init(dense)


def _log_steps(drill, dense, state, wal, steps, owned):
    for step in range(steps):
        pre = drill.pub_state(dense, state)
        state = drill.apply(dense, state, step, owned)
        wal.log_step(step, owned, pre, drill.pub_state(dense, state))
    return state


@pytest.mark.parametrize("type_name", ["topk_rmv", "average"])
def test_recover_matches_uninterrupted_run(tmp_path, type_name):
    drill, dense, state = _drill(type_name)
    wal = ElasticWal(str(tmp_path), "w0", dense, drill.publish_name)
    state = _log_steps(drill, dense, state, wal, 5, [0, 2])
    wal.close()
    ref = drill.digest(dense, state)

    drill2, dense2, state2 = _drill(type_name)
    m = Metrics()
    wal2 = ElasticWal(str(tmp_path), "w0", dense2, drill2.publish_name, metrics=m)
    rec, last_step, owned = wal2.recover(drill2.pub_state(dense2, state2))
    wal2.close()
    assert last_step == 4 and owned == {0, 2}
    assert m.counters.get("wal.recovered_records", 0) == 5
    state2 = drill2.set_view(dense2, state2, rec)
    assert drill2.digest(dense2, state2) == ref


def test_recover_checkpoint_join_wal_suffix(tmp_path):
    """Compaction up to the checkpoint step discards those records; the
    recovered state (checkpoint ⊔ remaining suffix) is still exact."""
    drill, dense, state = _drill("topk_rmv")
    wal = ElasticWal(str(tmp_path), "w0", dense, drill.publish_name,
                     segment_bytes=1 << 12)
    for step in range(6):
        pre = drill.pub_state(dense, state)
        state = drill.apply(dense, state, step, [1])
        wal.log_step(step, [1], pre, drill.pub_state(dense, state))
        if step == 3:
            wal.checkpoint(drill.pub_state(dense, state), step)
    wal.close()
    ref = drill.digest(dense, state)

    drill2, dense2, state2 = _drill("topk_rmv")
    m = Metrics()
    wal2 = ElasticWal(str(tmp_path), "w0", dense2, drill2.publish_name, metrics=m)
    rec, last_step, _ = wal2.recover(drill2.pub_state(dense2, state2))
    wal2.close()
    assert last_step == 5
    assert m.counters.get("wal.recovered_snapshot") == 1
    state2 = drill2.set_view(dense2, state2, rec)
    assert drill2.digest(dense2, state2) == ref


def test_recover_with_torn_final_record(tmp_path):
    """A crash mid-append loses exactly the torn record: recovery lands
    on the previous step and the restarted worker redoes the lost one —
    never replays garbage."""
    drill, dense, state = _drill("topk_rmv")
    wal = ElasticWal(str(tmp_path), "w0", dense, drill.publish_name)
    state = _log_steps(drill, dense, state, wal, 4, [0])
    wal.close()
    wal_dir = os.path.join(tmp_path, "wal-w0")
    seg = os.path.join(wal_dir, sorted(os.listdir(wal_dir))[-1])
    os.truncate(seg, os.path.getsize(seg) - 7)

    drill2, dense2, state2 = _drill("topk_rmv")
    wal2 = ElasticWal(str(tmp_path), "w0", dense2, drill2.publish_name)
    rec, last_step, _ = wal2.recover(drill2.pub_state(dense2, state2))
    wal2.close()
    assert last_step == 2  # step 3's record was the torn one

    # Redoing step 3 on the recovered state reproduces the full run.
    state2 = drill2.set_view(dense2, state2, rec)
    state2 = drill2.apply(dense2, state2, 3, [0])
    ref_state = _drill("topk_rmv")
    ref = ref_state[0].apply(ref_state[1], ref_state[2], 0, [0])
    for s in range(1, 4):
        ref = ref_state[0].apply(ref_state[1], ref, s, [0])
    assert drill2.digest(dense2, state2) == ref_state[0].digest(ref_state[1], ref)


def test_recover_empty_dir_is_noop(tmp_path):
    drill, dense, state = _drill("topk_rmv")
    wal = ElasticWal(str(tmp_path), "w9", dense, drill.publish_name)
    rec, last_step, owned = wal.recover(drill.pub_state(dense, state))
    wal.close()
    assert rec is None and last_step == -1 and owned == set()


def test_ckpt_replace_fault_preserves_previous_checkpoint(tmp_path):
    """An injected crash between the durable tmp write and the rename
    must leave the PREVIOUS checkpoint readable — the atomic-replace
    guarantee the recovery path depends on."""
    drill, dense, state = _drill("topk_rmv")
    wal = ElasticWal(str(tmp_path), "w0", dense, drill.publish_name)
    state = _log_steps(drill, dense, state, wal, 2, [0])
    wal.checkpoint(drill.pub_state(dense, state), 1)
    state = drill.apply(dense, state, 2, [0])
    with faults.injected({"ckpt.replace": [{"action": "raise", "at": [0]}]}):
        with pytest.raises(faults.InjectedFault):
            wal.checkpoint(drill.pub_state(dense, state), 2)
    wal.close()

    drill2, dense2, state2 = _drill("topk_rmv")
    m = Metrics()
    wal2 = ElasticWal(str(tmp_path), "w0", dense2, drill2.publish_name, metrics=m)
    rec, last_step, _ = wal2.recover(drill2.pub_state(dense2, state2))
    wal2.close()
    assert m.counters.get("wal.recovered_snapshot") == 1  # the step-1 one
    assert last_step == 1
    assert rec is not None


# --- durability modes (group commit / async watermark) ----------------------


def _freeze_backstops(monkeypatch):
    """Disable the byte/time group-commit backstops so tests control
    exactly when flush() happens (JIT compile pauses would otherwise
    trip the time bound mid-_log_steps)."""
    monkeypatch.setenv("CCRDT_WAL_GROUP_MS", "1000000")
    monkeypatch.setenv("CCRDT_WAL_GROUP_BYTES", str(1 << 30))


def test_group_commit_stages_until_flush(tmp_path, monkeypatch):
    _freeze_backstops(monkeypatch)
    drill, dense, state = _drill("topk_rmv")
    m = Metrics()
    wal = ElasticWal(str(tmp_path), "w0", dense, drill.publish_name,
                     metrics=m, durability="group")
    assert wal.durability == "group"
    state = _log_steps(drill, dense, state, wal, 3, [0])
    # Appended + staged, but nothing is fsync-acked yet.
    assert wal.log.last_seq == 2
    assert wal.durable_seq == -1
    assert m.counters.get("wal.durability_lag") == 3.0
    # One flush acks the whole batch.
    assert wal.flush() == 3
    assert wal.durable_seq == 2
    assert m.counters.get("wal.durability_lag") == 0.0
    assert m.counters.get("wal.flushes") == 1
    assert m.snapshot()["latencies"].get("wal.group_size") == [3.0]
    assert wal.flush() == 0  # nothing staged -> no second ack
    wal.close()

    # The flushed log recovers exactly like a sync-mode one.
    drill2, dense2, state2 = _drill("topk_rmv")
    wal2 = ElasticWal(str(tmp_path), "w0", dense2, drill2.publish_name)
    rec, last_step, _ = wal2.recover(drill2.pub_state(dense2, state2))
    wal2.close()
    assert last_step == 2
    state2 = drill2.set_view(dense2, state2, rec)
    assert drill2.digest(dense2, state2) == drill.digest(dense, state)


def test_group_fsync_fault_poisons_whole_batch(tmp_path, monkeypatch):
    """One injected EIO at flush() fail-stops the ENTIRE batch: nothing
    is acked (no partial commit), the staged records stay pending, and a
    retry re-commits the same batch."""
    _freeze_backstops(monkeypatch)
    drill, dense, state = _drill("topk_rmv")
    wal = ElasticWal(str(tmp_path), "w0", dense, drill.publish_name,
                     durability="group")
    _log_steps(drill, dense, state, wal, 2, [0])
    with faults.injected({"wal.fsync": [{"action": "raise", "at": [0]}]}):
        with pytest.raises(faults.InjectedFault):
            wal.flush()
        assert wal.durable_seq == -1  # whole batch poisoned, zero acks
        assert wal.flush() == 2       # retry commits the SAME batch
    assert wal.durable_seq == 1
    wal.close()


def test_async_recovery_truncates_to_watermark(tmp_path, monkeypatch):
    """async durability: a crash loses exactly the appended-but-unacked
    tail — recovery truncates every stream to the fsync'd wm watermark
    and replays precisely the certified-durable prefix."""
    _freeze_backstops(monkeypatch)
    drill, dense, state = _drill("topk_rmv")
    wal = ElasticWal(str(tmp_path), "w0", dense, drill.publish_name,
                     durability="async")
    digest_at = {}
    for step in range(5):
        pre = drill.pub_state(dense, state)
        state = drill.apply(dense, state, step, [0])
        wal.log_step(step, [0], pre, drill.pub_state(dense, state))
        digest_at[step] = drill.digest(dense, state)
        if step == 2:
            wal.flush()  # watermark advances to 2; steps 3..4 stay staged
    assert wal.durable_seq == 2 and wal.log.last_seq == 4
    # Crash: abandon the wal WITHOUT close() (close would flush the tail).
    del wal

    drill2, dense2, state2 = _drill("topk_rmv")
    m = Metrics()
    wal2 = ElasticWal(str(tmp_path), "w0", dense2, drill2.publish_name,
                      metrics=m, durability="async")
    rec, last_step, _ = wal2.recover(drill2.pub_state(dense2, state2))
    wal2.close()
    assert last_step == 2  # NOT 4: the unacked tail must not resurrect
    assert m.counters.get("wal.truncated_records") == 2
    state2 = drill2.set_view(dense2, state2, rec)
    assert drill2.digest(dense2, state2) == digest_at[2]


def test_async_reopen_seeds_watermark_over_existing_log(tmp_path, monkeypatch):
    """A sync/group log reopened as async and crashed BEFORE its first
    flush must not truncate records the earlier run made durable: the
    open seeds the wm watermark at the on-disk tail."""
    _freeze_backstops(monkeypatch)
    drill, dense, state = _drill("topk_rmv")
    wal = ElasticWal(str(tmp_path), "w0", dense, drill.publish_name,
                     durability="group")
    state = _log_steps(drill, dense, state, wal, 3, [0])
    wal.close()  # close flushes: all 3 records durable

    wal2 = ElasticWal(str(tmp_path), "w0", dense, drill.publish_name,
                      durability="async")
    assert wal2.durable_seq == 2  # seeded, not -1
    del wal2  # crash before any append/flush

    drill3, dense3, state3 = _drill("topk_rmv")
    m = Metrics()
    wal3 = ElasticWal(str(tmp_path), "w0", dense3, drill3.publish_name,
                      metrics=m, durability="async")
    rec, last_step, _ = wal3.recover(drill3.pub_state(dense3, state3))
    wal3.close()
    assert last_step == 2
    assert m.counters.get("wal.truncated_records", 0) == 0


def test_non_async_reopen_discards_stale_watermark(tmp_path, monkeypatch):
    """Reopening an async log as group applies the watermark truncation
    ONCE (the stale tail was never acked no matter how we reopen), then
    deletes the wm dir so it can never truncate future durable records."""
    _freeze_backstops(monkeypatch)
    drill, dense, state = _drill("topk_rmv")
    wal = ElasticWal(str(tmp_path), "w0", dense, drill.publish_name,
                     durability="async")
    for step in range(4):
        pre = drill.pub_state(dense, state)
        state = drill.apply(dense, state, step, [0])
        wal.log_step(step, [0], pre, drill.pub_state(dense, state))
        if step == 1:
            wal.flush()  # watermark 1; steps 2..3 unacked
    del wal  # crash

    wal2 = ElasticWal(str(tmp_path), "w0", dense, drill.publish_name,
                      durability="group")
    assert wal2.log.last_seq == 1  # truncated to the watermark
    assert not os.path.isdir(os.path.join(tmp_path, "wal-w0", "wm"))
    wal2.close()


# --- per-partition parallel streams -----------------------------------------


def _route_by_step(monkeypatch, nparts=4):
    """Make the partition tag deterministic per logged step so records
    round-robin across streams (the real `delta_parts` projection is
    data-dependent; routing policy, not partition math, is under test)."""
    from antidote_ccrdt_tpu.core import partition as pt

    counter = iter(range(10_000))
    monkeypatch.setattr(
        pt, "delta_parts", lambda *a, **k: {next(counter) % nparts}
    )


def test_multistream_round_trip_merges_by_seq(tmp_path, monkeypatch):
    _freeze_backstops(monkeypatch)
    _route_by_step(monkeypatch)
    drill, dense, state = _drill("topk_rmv")
    wal = ElasticWal(str(tmp_path), "w0", dense, drill.publish_name,
                     partitions=4, durability="group")
    assert wal.nstreams == 4
    state = _log_steps(drill, dense, state, wal, 8, [0])
    wal.close()
    wal_dir = os.path.join(tmp_path, "wal-w0")
    # Round-robin routing: stream 0 stays the top-level dir, streams
    # 1..3 are subdirs, each holding its share of the records.
    for s in (1, 2, 3):
        sdir = os.path.join(wal_dir, f"stream-{s:02d}")
        assert os.path.isdir(sdir)
        assert any(f.endswith(".wal") for f in os.listdir(sdir))

    # A LEGACY reader (no partitions configured) still discovers every
    # on-disk stream and recovers the seq-merged whole.
    drill2, dense2, state2 = _drill("topk_rmv")
    m = Metrics()
    wal2 = ElasticWal(str(tmp_path), "w0", dense2, drill2.publish_name,
                      metrics=m)
    assert wal2.nstreams == 4  # forced up by the on-disk layout
    rec, last_step, _ = wal2.recover(drill2.pub_state(dense2, state2))
    wal2.close()
    assert last_step == 7
    assert m.counters.get("wal.recovered_records") == 8
    state2 = drill2.set_view(dense2, state2, rec)
    assert drill2.digest(dense2, state2) == drill.digest(dense, state)


def test_multistream_torn_tail_loses_only_that_streams_tail(
    tmp_path, monkeypatch
):
    """A crash tears ONE stream's final record: the other streams'
    records survive, recovery lands one step short, and redoing the lost
    step reproduces the full run — the per-stream analog of
    test_recover_with_torn_final_record."""
    _freeze_backstops(monkeypatch)
    _route_by_step(monkeypatch)
    drill, dense, state = _drill("topk_rmv")
    wal = ElasticWal(str(tmp_path), "w0", dense, drill.publish_name,
                     partitions=4, durability="group")
    state = _log_steps(drill, dense, state, wal, 8, [0])
    wal.close()
    # Step 7 routed to stream 3 (7 % 4); tear its segment tail.
    sdir = os.path.join(tmp_path, "wal-w0", "stream-03")
    seg = os.path.join(
        sdir, sorted(f for f in os.listdir(sdir) if f.endswith(".wal"))[-1]
    )
    os.truncate(seg, os.path.getsize(seg) - 7)

    drill2, dense2, state2 = _drill("topk_rmv")
    m = Metrics()
    wal2 = ElasticWal(str(tmp_path), "w0", dense2, drill2.publish_name,
                      partitions=4, metrics=m)
    rec, last_step, _ = wal2.recover(drill2.pub_state(dense2, state2))
    wal2.close()
    assert last_step == 6  # only stream 3's torn record (seq 7) is gone
    assert m.counters.get("wal.recovered_records") == 7
    state2 = drill2.set_view(dense2, state2, rec)
    state2 = drill2.apply(dense2, state2, 7, [0])
    assert drill2.digest(dense2, state2) == drill.digest(dense, state)


def test_multistream_checkpoint_compacts_every_stream(tmp_path, monkeypatch):
    _freeze_backstops(monkeypatch)
    _route_by_step(monkeypatch, nparts=2)
    drill, dense, state = _drill("topk_rmv")
    m = Metrics()
    wal = ElasticWal(str(tmp_path), "w0", dense, drill.publish_name,
                     partitions=2, streams=2, segment_bytes=1 << 12,
                     metrics=m, durability="group")
    for step in range(8):
        pre = drill.pub_state(dense, state)
        state = drill.apply(dense, state, step, [0])
        wal.log_step(step, [0], pre, drill.pub_state(dense, state))
    wal.checkpoint(drill.pub_state(dense, state), 7)
    # The checkpoint's pre-compaction flush acked the batch first.
    assert wal.durable_seq == 7
    assert m.counters.get("wal.segments_compacted", 0) > 0
    wal.close()

    drill2, dense2, state2 = _drill("topk_rmv")
    wal2 = ElasticWal(str(tmp_path), "w0", dense2, drill2.publish_name,
                      partitions=2, streams=2)
    rec, last_step, _ = wal2.recover(drill2.pub_state(dense2, state2))
    wal2.close()
    assert last_step == 7
    state2 = drill2.set_view(dense2, state2, rec)
    assert drill2.digest(dense2, state2) == drill.digest(dense, state)


def test_garbage_snapshot_does_not_block_wal_replay(tmp_path):
    drill, dense, state = _drill("topk_rmv")
    wal = ElasticWal(str(tmp_path), "w0", dense, drill.publish_name)
    state = _log_steps(drill, dense, state, wal, 3, [0])
    wal.close()
    snap = os.path.join(tmp_path, "wal-w0", ElasticWal.SNAP)
    with open(snap, "wb") as f:
        f.write(struct.pack("<Q", 7) + b"not a checkpoint")
    drill2, dense2, state2 = _drill("topk_rmv")
    wal2 = ElasticWal(str(tmp_path), "w0", dense2, drill2.publish_name)
    rec, last_step, _ = wal2.recover(drill2.pub_state(dense2, state2))
    wal2.close()
    assert last_step == 2 and rec is not None
    state2 = drill2.set_view(dense2, state2, rec)
    assert drill2.digest(dense2, state2) == drill.digest(dense, state)
