"""The chaos matrix: utils/faults.py driven through the REAL code paths.

`make chaos` runs this deterministically under JAX_PLATFORMS=cpu. Each
test installs a seeded plan against the production injection points —
FsTransport snapshot/delta I/O, the TCP peer link, the bridge client's
reply read, WAL fsync, checkpoint replace — and asserts two things: the
failure has the intended blast radius (totality, fallback, retry,
exactly-once) and the schedule replays bit-identically from its seed.
"""

import struct

import pytest

from antidote_ccrdt_tpu.utils import faults
from antidote_ccrdt_tpu.utils.metrics import Metrics


@pytest.fixture(autouse=True)
def _clean():
    faults.uninstall()
    yield
    faults.uninstall()


# --- FsTransport -----------------------------------------------------------


def test_torn_delta_write_is_never_visible(tmp_path):
    """The satellite fix: publish_delta fsyncs the tmp file BEFORE the
    rename commits the name. A torn payload (injected truncation) may
    ship garbage bytes, but decode-level totality turns it into None —
    and the windowed seq listing never shows a half-written .tmp."""
    from antidote_ccrdt_tpu.net.transport import FsTransport, GossipNode
    from scripts.elastic_demo import DRILLS

    drill = DRILLS["topk_rmv"]
    dense = drill.make_engine()
    state = drill.init(dense)
    state = drill.apply(dense, state, 0, [0])

    from antidote_ccrdt_tpu.parallel.delta import (
        like_delta_for, make_delta,
    )
    from antidote_ccrdt_tpu.core import serial

    delta = make_delta(dense, drill.init(dense), state)
    blob = serial.dumps_dense("topk_rmv_delta", delta)

    node = GossipNode(FsTransport(str(tmp_path), "a"))
    with faults.injected(
        {"transport.publish_delta": [{"action": "truncate", "at": [0], "keep": 0.5}]}
    ):
        node.publish_delta(blob, seq=0)   # torn
        node.publish_delta(blob, seq=1)   # clean
    assert node.transport.delta_seqs("a") == [0, 1]  # no .tmp leakage
    like = like_delta_for(dense, state)
    assert node.fetch_delta("a", 0, like) is None      # torn -> total None
    assert node.fetch_delta("a", 1, like) is not None  # clean one decodes


def test_torn_snapshot_publish_reads_as_none(tmp_path):
    from antidote_ccrdt_tpu.net.transport import FsTransport, GossipNode
    from scripts.elastic_demo import DRILLS

    drill = DRILLS["topk_rmv"]
    dense = drill.make_engine()
    state = drill.init(dense)
    node = GossipNode(FsTransport(str(tmp_path), "a"))
    with faults.injected(
        {"transport.publish": [{"action": "truncate", "at": [0], "keep": 12}]}
    ):
        node.publish("topk_rmv", state, step=3)
    # The 8-byte step header survives the tear; the payload does not:
    # seq reads fine, the state fetch is total and returns None.
    assert node.snapshot_seq("a") == 3
    assert node.fetch("a", state, dense=dense) is None
    node.publish("topk_rmv", state, step=4)
    assert node.fetch("a", state, dense=dense) is not None


def test_dropped_snapshot_publish_never_lands(tmp_path):
    from antidote_ccrdt_tpu.net.transport import FsTransport

    t = FsTransport(str(tmp_path), "a")
    with faults.injected({"transport.publish": [{"action": "drop", "at": [0]}]}):
        t.publish(struct.pack("<Q", 1) + b"x")
        assert t.fetch("a") is None
        t.publish(struct.pack("<Q", 2) + b"y")
    assert t.fetch("a") == struct.pack("<Q", 2) + b"y"


def test_fetch_delta_oserror_is_total(tmp_path):
    from antidote_ccrdt_tpu.net.transport import FsTransport

    t = FsTransport(str(tmp_path), "a")
    t.publish_delta(0, b"d0")
    with faults.injected(
        {"transport.fetch_delta": [{"action": "raise", "at": [0]}]}
    ):
        assert t.fetch_delta("a", 0) is None  # injected EIO -> None, no raise
        assert t.fetch_delta("a", 0) == b"d0"


def test_fetch_delta_read_tear_breaks_chain_not_process(tmp_path):
    from antidote_ccrdt_tpu.net.transport import FsTransport, GossipNode
    from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense
    from antidote_ccrdt_tpu.parallel.delta import like_delta_for, make_delta
    from antidote_ccrdt_tpu.core import serial

    dense = make_dense(n_ids=16, n_dcs=2, size=4, slots_per_id=2)
    st = dense.init(1, 1)
    node = GossipNode(FsTransport(str(tmp_path), "a"))
    node.publish_delta(serial.dumps_dense("d", make_delta(dense, st, st)), seq=0)
    like = like_delta_for(dense, st)
    with faults.injected(
        {"transport.fetch_delta.read": [{"action": "truncate", "at": [0], "keep": 5}]}
    ):
        assert node.fetch_delta("a", 0, like) is None
        assert node.fetch_delta("a", 0, like) is not None


# --- TCP peer link ---------------------------------------------------------


def test_tcp_send_drop_loses_frame_but_not_link():
    """An injected send drop models a lost frame: the link survives, the
    metrics record the drop, and later (re)publishes still deliver —
    snapshot gossip is latest-wins, so the next anchor heals the gap."""
    import time

    from antidote_ccrdt_tpu.net.tcp import TcpTransport

    a = TcpTransport("a")
    b = TcpTransport("b")
    a.add_peer("b", b.address)
    b.add_peer("a", a.address)
    try:
        with faults.injected({"tcp.send": [{"action": "drop", "at": [0]}]}):
            a.publish(struct.pack("<Q", 1) + b"first")   # eaten by the fault
            # Wait for the sender thread to consume (and drop) the frame
            # BEFORE enqueueing the next one: the snap queue slot is
            # latest-wins, so publishing earlier would replace the frame
            # and the drop would eat the second publish instead.
            deadline = time.time() + 8.0
            while (
                time.time() < deadline
                and a.metrics.counters.get("net.fault_drops", 0) < 1
            ):
                time.sleep(0.01)
        assert b.fetch("a") is None  # the dropped anchor never arrived
        a.publish(struct.pack("<Q", 2) + b"second")  # delivered
        deadline = time.time() + 8.0
        while time.time() < deadline and b.fetch("a") is None:
            time.sleep(0.01)
        got = b.fetch("a")
        assert got == struct.pack("<Q", 2) + b"second"
        assert a.metrics.counters.get("net.fault_drops", 0) >= 1
        # The dropped frame was never counted as sent.
        assert a.metrics.counters.get("net.frames_sent", 0) >= 1
    finally:
        a.close()
        b.close()


# --- WAL / checkpoint ------------------------------------------------------


def test_wal_fsync_eio_blocks_durability_claim(tmp_path):
    from antidote_ccrdt_tpu.harness.wal import WriteAheadLog

    w = WriteAheadLog(str(tmp_path))
    with faults.injected({"wal.fsync": [{"action": "raise", "at": [1]}]}):
        w.append(0, b"ok")
        with pytest.raises(faults.InjectedFault):
            w.append(1, b"not durable")
    w.close()


def test_ckpt_replace_crash_keeps_old_checkpoint(tmp_path):
    from antidote_ccrdt_tpu.harness.checkpoint import (
        load_dense_checkpoint, save_dense_checkpoint,
    )
    from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense

    dense = make_dense(n_ids=16, n_dcs=2, size=4, slots_per_id=2)
    st = dense.init(1, 1)
    path = str(tmp_path / "c.ckpt")
    save_dense_checkpoint(path, "topk_rmv", st, step=1)
    with faults.injected({"ckpt.replace": [{"action": "raise", "at": [0]}]}):
        with pytest.raises(faults.InjectedFault):
            save_dense_checkpoint(path, "topk_rmv", st, step=2)
    step, name, _ = load_dense_checkpoint(path, st)
    assert (step, name) == (1, "topk_rmv")  # the old anchor survived


# --- bridge ----------------------------------------------------------------


def test_bridge_read_reset_retries_exactly_once_semantics():
    """A reply lost to a connection reset is retried under icall: the
    server dedups on (token, req_id), so a non-idempotent op (average
    add: + is not a join) executes once even though it was sent twice."""
    from antidote_ccrdt_tpu.bridge import BridgeClient, BridgeServer
    from antidote_ccrdt_tpu.core.etf import Atom

    with BridgeServer() as srv:
        with BridgeClient(*srv.address, timeout=10.0, retries=3) as c:
            h = c.new("average")
            with faults.injected(
                {"bridge.read": [{"action": "raise", "at": [0],
                                  "message": "connection reset"}]}
            ):
                c.update(h, (Atom("add"), (10, 1)))
            # Applied ONCE: state (10, 1), not (20, 2). The mean hides a
            # double-apply (20/2 == 10/1), the raw state does not.
            from antidote_ccrdt_tpu.core import wire

            assert wire.from_reference_binary("average", c.to_binary(h)) == (10, 1)
            assert c.metrics.counters.get("bridge.reconnects", 0) >= 1
            assert srv.metrics.counters.get("bridge.replays", 0) >= 1


def test_bridge_read_reset_without_retries_poisons():
    from antidote_ccrdt_tpu.bridge import BridgeClient, BridgeServer

    with BridgeServer() as srv:
        c = BridgeClient(*srv.address, timeout=5.0)  # retries=0: legacy
        try:
            with faults.injected(
                {"bridge.read": [{"action": "raise", "at": [0]}]}
            ):
                with pytest.raises(Exception):
                    c.new("average")
            with pytest.raises(Exception, match="closed"):
                c.new("average")
        finally:
            c.close()


# --- replay determinism ----------------------------------------------------


def test_matrix_schedule_replays_bit_identically(tmp_path):
    """The acceptance bar: a multi-point scenario replays the SAME fault
    schedule from the same seed — (point, hit, action) trace equality,
    not just same counts."""
    from antidote_ccrdt_tpu.net.transport import FsTransport

    plan = {
        "transport.publish": [{"action": "drop", "rate": 0.3}],
        "transport.fetch_delta": [{"action": "raise", "rate": 0.2}],
        "wal.fsync": [{"action": "raise", "rate": 0.1}],
    }

    def scenario(root):
        from antidote_ccrdt_tpu.harness.wal import WriteAheadLog

        t = FsTransport(str(root), "a")
        w = WriteAheadLog(str(root) + "-wal")
        for i in range(25):
            t.publish(struct.pack("<Q", i) + b"s")
            t.publish_delta(i, b"d%d" % i)
            t.fetch_delta("a", i)
            try:
                w.append(i, b"r%d" % i)
            except faults.InjectedFault:
                pass
        w.close()
        return faults.trace()

    with faults.injected(plan, seed=31337):
        t1 = scenario(tmp_path / "one")
    with faults.injected(plan, seed=31337):
        t2 = scenario(tmp_path / "two")
    assert t1 == t2
    assert len(t1) > 0
    assert {p for p, _, _ in t1} >= {"transport.publish", "wal.fsync"}


# --- the fleet read tier ----------------------------------------------------


def _drip_server(stop):
    """A peer that ACCEPTS `{query}` frames and then drips unrelated
    frames forever without ever answering — the failure mode that used
    to defeat `query_peer`'s timeout (only connection-level faults
    surfaced; steady inbound bytes kept the recv loop alive)."""
    import socket
    import threading
    import time as _time

    from antidote_ccrdt_tpu.bridge.protocol import pack_frame
    from antidote_ccrdt_tpu.core.etf import Atom

    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    srv.settimeout(0.1)
    ping = pack_frame((Atom("ping"), b"drip", {}))

    def loop():
        conns = []
        while not stop.is_set():
            try:
                c, _ = srv.accept()
                c.settimeout(0.05)
                conns.append(c)
            except OSError:
                pass
            for c in list(conns):
                try:
                    c.recv(4096)
                except socket.timeout:
                    pass
                except OSError:
                    conns.remove(c)
                    continue
                try:
                    c.sendall(ping)  # traffic, but never a query_resp
                except OSError:
                    conns.remove(c)
            _time.sleep(0.02)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        srv.close()

    import threading as _threading

    t = _threading.Thread(target=loop, daemon=True)
    t.start()
    return srv.getsockname()


def test_query_peer_deadline_fires_on_never_answering_peer():
    """Satellite: a peer that accepts the query but never answers must
    surface socket.timeout at the per-query deadline — even while it
    keeps the connection busy with unrelated frames."""
    import socket
    import threading
    import time as _time

    from antidote_ccrdt_tpu.net.tcp import query_peer
    from antidote_ccrdt_tpu.serve import request_bytes

    stop = threading.Event()
    addr = _drip_server(stop)
    try:
        t0 = _time.monotonic()
        with pytest.raises(socket.timeout):
            query_peer(addr, request_bytes([{"op": "value", "key": 0}]),
                       timeout=0.4)
        assert _time.monotonic() - t0 < 3.0  # deadline, not a hang
    finally:
        stop.set()


def test_router_fails_over_from_never_answering_peer():
    """The router consequence: the hung peer burns its per-query
    deadline, the router bills a timeout and fails over to the healthy
    HRW runner-up instead of hanging."""
    import threading
    import time as _time

    from antidote_ccrdt_tpu.net.tcp import TcpTransport
    from antidote_ccrdt_tpu.serve import request_bytes
    from antidote_ccrdt_tpu.serve.router import FleetRouter, tcp_query_fn
    from antidote_ccrdt_tpu.topo import rendezvous_order

    from tests.test_serve_parity import _frozen_plane

    stop = threading.Event()
    drip_addr = _drip_server(stop)
    plane = _frozen_plane()
    # Warm the serve path (first query pays JIT/materialization) so the
    # per-query deadline below measures the transport, not compilation.
    plane.handle(request_bytes([{"op": "value", "key": 0}]))
    t = TcpTransport("good")
    t.install_serve(plane)
    try:
        addrs = {"hung": drip_addr, "good": t.address}
        # Pick a key whose HRW head is the hung peer, so the test
        # actually exercises failover (not first-try luck).
        key = next(
            k for k in (f"k{i}" for i in range(64))
            if rendezvous_order(k, ["hung", "good"])[0] == "hung"
        )
        r = FleetRouter(
            ["hung", "good"], tcp_query_fn(addrs), metrics=Metrics(),
            hedge=False, timeout_s=0.4, retries=0, poll_s=0.01,
        )
        t0 = _time.monotonic()
        out = r.query([{"op": "value", "key": 0}], key=key)
        assert out.get("peer") == "good" and out["results"][0]["value"]
        assert _time.monotonic() - t0 < 5.0
        c = r.metrics.snapshot()["counters"]
        # The timeout may surface either as the worker thread's own
        # socket.timeout (peer_timeouts) or the router-side deadline
        # (timeouts) depending on which poll fires first.
        timeouts = c.get("router.timeouts", 0) + c.get("router.peer_timeouts", 0)
        assert timeouts >= 1 and c["router.failovers"] >= 1
    finally:
        stop.set()
        t.close()


def test_router_route_drop_schedule_replays(tmp_path):
    """router.route joins the matrix: an injected drop at the routing
    point reroutes (same blast radius as connection loss) and the
    seeded schedule replays bit-identically."""
    import json as _json

    from antidote_ccrdt_tpu.serve.router import FleetRouter

    def resp(peer):
        return (_json.dumps({
            "member": peer, "n": 1,
            "results": [{"value": 1, "as_of_seq": 1,
                         "staleness_bound_s": 0.0}],
        }) + "\n").encode()

    plan = {"router.route": [{"action": "drop", "rate": 0.5}]}

    def scenario():
        r = FleetRouter(
            ["a", "b", "c"],
            lambda peer, payload, timeout, cancel: resp(peer),
            metrics=Metrics(), hedge=False, retries=2,
            backoff_base_s=0.0, poll_s=0.001,
            # Drops are billed as connection failures; leave the breakers
            # effectively disabled so the drill measures rerouting, not
            # breaker lockout under a 50% drop rate.
            breaker_failures=10**6,
        )
        answered = 0
        for i in range(20):
            out = r.query([{"op": "value", "key": i}], key=f"k{i}")
            answered += 1 if "peer" in out else 0
        return answered, faults.trace()

    with faults.injected(plan, seed=2024):
        a1, t1 = scenario()
    with faults.injected(plan, seed=2024):
        a2, t2 = scenario()
    assert (a1, t1) == (a2, t2)
    assert any(p == "router.route" and act == "drop" for p, _, act in t1)
    assert a1 == 20  # drops reroute; every query still answers
