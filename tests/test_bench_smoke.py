"""Smoke-test the driver-facing benchmark entry points at tiny shapes on
the CPU test mesh: bench.py must keep producing its numbers (the driver
records the tail of its stdout every round and parses the final compact
summary line — signature rot or a shape bug here fails the round, not
just a test)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402


def test_bench_dense_tiny():
    (
        apply_rate, extras_rate, extras_ops_rate, p50, p99,
        p50_e2e, p99_e2e, p50_e2e_olap, p99_e2e_olap,
        overhead, merge_rate, hbm, compute,
    ) = bench.bench_dense(
        R=2, I=64, D_DCS=2, K=4, M=2, B=16, Br=4, windows=2,
        rounds_per_window=2,
    )
    assert apply_rate > 0 and extras_rate > 0 and merge_rate > 0
    assert extras_ops_rate > 0
    assert p50 > 0 and p99 >= p50
    assert p50_e2e > 0 and p99_e2e >= p50_e2e and overhead > 0
    assert p50_e2e_olap > 0 and p99_e2e_olap >= p50_e2e_olap
    assert set(hbm) == {"apply", "replica_state_merge", "observe"}
    for phase in hbm.values():
        assert phase["achieved_gb_s"] > 0 and phase["bytes_per_dispatch"] > 0
    ca = compute["apply"]
    assert ca["measured_ms"] > 0 and ca["floor_ms"] >= ca["hbm_floor_ms"]
    assert ca["mxu"]["tombstone_onehot_macs"] == 2 * 4 * 64 * 5 * 2
    # The v5e ablation attribution only attaches at north-star shapes.
    assert ca["attribution_ms_r5"] is None


def test_bench_scalar_baseline_tiny():
    rate = bench.bench_scalar_baseline(R=2, I=64, D_DCS=2, K=4, n_ops=200)
    assert rate > 0


def test_bench_main_final_line_is_compact_and_parses():
    """The driver keeps only the tail (<=2,000 chars) of stdout and parses
    the LAST line; round 4's fat single line overflowed that window and the
    official record came back unparseable (VERDICT-r4 weak #1). The contract
    is now: a compact final summary line (<1,900 chars) plus a full-detail
    line earlier in stdout, mirrored to benchmarks/bench_details.json."""
    from conftest import cpu_subprocess_env

    env = cpu_subprocess_env(CCRDT_BENCH_TINY="1")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [l for l in out.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 2, out.stdout
    rec = json.loads(lines[-1])
    assert len(lines[-1]) < 1900
    assert rec["unit"] == "merges/sec" and rec["value"] > 0
    assert "vs_baseline" in rec
    assert rec["replica_state_merges_per_sec"] > 0
    details = json.loads(lines[0])["details"]
    pts = details["curve"]["points"]
    # 2 sweep points + the carried-over headline point (source=headline).
    assert len(pts) == 3 and all(p["merges_per_sec"] > 0 for p in pts)
    assert sum(1 for p in pts if p.get("source") == "headline") == 1
    assert all(
        p["p99_round_ms_e2e"] >= p["p50_round_ms_e2e"] > 0 for p in pts
    )
    assert details["curve"]["operating_point"]["batch_adds"] > 0
    # Tiny-mode numbers are meaningless, so the run must NOT have touched
    # the committed sidecar (only real-accelerator runs write it) and must
    # say so in the summary.
    assert rec["details_file"] == "stdout"
