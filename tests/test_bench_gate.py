"""Bench regression gate (scripts/bench_gate.py): tail-string metric
extraction, latest-vs-best-prior comparison, and the vacuous pass when
rounds lack the metric."""

import importlib.util
import json
import os

_SPEC = importlib.util.spec_from_file_location(
    "bench_gate",
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
        "bench_gate.py",
    ),
)
gate = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(gate)


def _round(tmp_path, n, merges=None, torn=False, backend=None, gap=None,
           coverage=None):
    path = str(tmp_path / f"BENCH_r{n:02d}.json")
    if torn:
        with open(path, "w") as f:
            f.write('{"tail": "tor')
        return path
    tail = "setup only\n"
    if merges is not None:
        # The metric is JSON text INSIDE the tail capture — the shape the
        # real BENCH dumps have (escaped when serialized, plain after load).
        tail += "".join(f'{{"merges_per_sec": {v}}}\n' for v in merges)
    summary = {}
    if backend is not None:
        summary["backend"] = backend
    if gap is not None:
        summary["dispatch_gap_ms_p50"] = gap
        summary["span_coverage_p50"] = 0.9 if coverage is None else coverage
    if summary:
        tail += json.dumps(summary) + "\n"
    with open(path, "w") as f:
        json.dump({"n": n, "cmd": "bench", "rc": 0, "tail": tail}, f)
    return path


def test_extracts_best_from_tail(tmp_path):
    p = _round(tmp_path, 4, merges=[100.0, 250.5, 30.0])
    assert gate.best_merges_per_sec(p) == 250.5
    assert gate.best_merges_per_sec(_round(tmp_path, 1)) is None
    assert gate.best_merges_per_sec(_round(tmp_path, 2, torn=True)) is None


def test_gate_passes_within_tolerance(tmp_path):
    _round(tmp_path, 1)  # metric-less rounds are skipped, not zeros
    _round(tmp_path, 2, merges=[1000.0])
    _round(tmp_path, 3, merges=[850.0])  # -15% vs best prior: allowed
    code, verdict = gate.evaluate(gate.load_rounds(str(tmp_path)), 0.20)
    assert code == 0 and "OK" in verdict


def test_gate_fails_on_regression(tmp_path):
    _round(tmp_path, 1, merges=[1000.0])
    _round(tmp_path, 2, merges=[700.0])  # -30%: beyond the 20% floor
    code, verdict = gate.evaluate(gate.load_rounds(str(tmp_path)), 0.20)
    assert code == 1 and "FAIL" in verdict


def test_latest_compares_against_best_prior_not_last(tmp_path):
    _round(tmp_path, 1, merges=[1000.0])
    _round(tmp_path, 2, merges=[400.0])  # a dip in the middle
    _round(tmp_path, 3, merges=[750.0])  # -25% vs r1 (the best), not r2
    code, _ = gate.evaluate(gate.load_rounds(str(tmp_path)), 0.20)
    assert code == 1
    code, _ = gate.evaluate(gate.load_rounds(str(tmp_path)), 0.30)
    assert code == 0


def test_vacuous_pass_with_fewer_than_two_rounds(tmp_path):
    code, verdict = gate.evaluate(gate.load_rounds(str(tmp_path)), 0.20)
    assert code == 0 and "vacuous" in verdict
    _round(tmp_path, 1, merges=[5.0])
    code, _ = gate.evaluate(gate.load_rounds(str(tmp_path)), 0.20)
    assert code == 0


def test_backend_groups_compare_independently(tmp_path):
    # A CPU-fallback round must not be graded against TPU numbers (it
    # would always "regress"), nor reset the TPU baseline.
    _round(tmp_path, 1, merges=[1_000_000.0], backend="tpu")
    _round(tmp_path, 2, merges=[990_000.0], backend="tpu")
    _round(tmp_path, 3, merges=[5_000.0], backend="cpu")
    code, verdict = gate.evaluate(gate.load_rounds(str(tmp_path)), 0.20)
    assert code == 0
    assert "vacuous" in verdict  # the lone cpu round has no peer
    # ...but a regression WITHIN the tpu group still fails even when the
    # newest round overall is a cpu one.
    _round(tmp_path, 2, merges=[600_000.0], backend="tpu")
    code, verdict = gate.evaluate(gate.load_rounds(str(tmp_path)), 0.20)
    assert code == 1 and "FAIL" in verdict


def _serve_round(tmp_path, n, rps, p99, nproc=None, doc_nproc=None):
    path = str(tmp_path / f"BENCH_r{n:02d}.json")
    summary = {"serve_reads_per_sec": rps, "serve_read_p99_ms": p99}
    if nproc is not None:
        summary["nproc"] = nproc
    doc = {"n": n, "cmd": "bench", "rc": 0, "tail": json.dumps(summary)}
    if doc_nproc is not None:
        doc["nproc"] = doc_nproc
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def test_serve_gate_groups_by_host_class(tmp_path):
    # serve_reads_per_sec is host-CPU wall clock: a 1-core carrier must
    # not be graded against a many-core baseline (it would flag the
    # machine swap, not a code regression), nor reset that baseline.
    _serve_round(tmp_path, 1, 180_000.0, 3.0)  # legacy: no nproc field
    _serve_round(tmp_path, 2, 178_000.0, 3.2)
    _serve_round(tmp_path, 3, 90_000.0, 6.0, nproc=1)
    rounds = gate.load_serve_rounds(str(tmp_path))
    assert [r[4] for r in rounds] == [None, None, 1]
    code, verdict = gate.evaluate_serve(rounds, 0.20)
    assert code == 0
    assert "vacuous" in verdict and "report-only" in verdict
    # ...but a regression WITHIN the 1-core class still fails.
    _serve_round(tmp_path, 4, 60_000.0, 9.0, nproc=1)
    code, verdict = gate.evaluate_serve(
        gate.load_serve_rounds(str(tmp_path)), 0.20
    )
    assert code == 1 and "FAIL" in verdict
    # ...and a regression in the legacy (None) class is still caught when
    # the latest carrier belongs to it.
    _serve_round(tmp_path, 5, 100_000.0, 3.1)
    code, verdict = gate.evaluate_serve(
        gate.load_serve_rounds(str(tmp_path)), 0.20
    )
    assert code == 1 and "FAIL" in verdict


def test_serve_rounds_read_doc_level_nproc(tmp_path):
    # A carrier rebuilt from a raw stdout capture that predates the
    # summary-line field can still declare its host class top-level.
    _serve_round(tmp_path, 1, 120_000.0, 4.0, doc_nproc=2)
    rounds = gate.load_serve_rounds(str(tmp_path))
    assert rounds[0][4] == 2


def test_gap_gate_vacuous_then_pass_then_fail(tmp_path):
    code, verdict = gate.evaluate_gap([], 0.20)
    assert code == 0 and "vacuous" in verdict
    _round(tmp_path, 1, merges=[100.0], backend="cpu", gap=200.0)
    attr = gate.load_attribution_rounds(str(tmp_path))
    code, _ = gate.evaluate_gap(attr, 0.20)
    assert code == 0  # one carrier: vacuous
    _round(tmp_path, 2, merges=[100.0], backend="cpu", gap=230.0)
    attr = gate.load_attribution_rounds(str(tmp_path))
    code, verdict = gate.evaluate_gap(attr, 0.20)
    assert code == 0 and "OK" in verdict  # +15% < 20%
    _round(tmp_path, 3, merges=[100.0], backend="cpu", gap=260.0)
    attr = gate.load_attribution_rounds(str(tmp_path))
    code, verdict = gate.evaluate_gap(attr, 0.20)
    assert code == 1 and "FAIL" in verdict  # +30% vs BEST prior (r1)


def test_gap_gate_absolute_floor_absorbs_noise(tmp_path):
    # Small gaps: +175% relative but 14ms absolute is within one CFS
    # throttle window on a shared-CPU carrier — the 40ms floor must
    # absorb it (the gate hunts 100ms-class host-tail slides, not
    # scheduler noise).
    _round(tmp_path, 1, merges=[100.0], backend="cpu", gap=8.0)
    _round(tmp_path, 2, merges=[100.0], backend="cpu", gap=22.0)
    attr = gate.load_attribution_rounds(str(tmp_path))
    code, _ = gate.evaluate_gap(attr, 0.20)
    assert code == 0
    # ...while a real slide well past the floor still fails.
    _round(tmp_path, 3, merges=[100.0], backend="cpu", gap=90.0)
    attr = gate.load_attribution_rounds(str(tmp_path))
    code, verdict = gate.evaluate_gap(attr, 0.20)
    assert code == 1 and "FAIL" in verdict


def _mesh_round(tmp_path, n, merges=None, ici=None, bytes_=None,
                legacy=False):
    path = str(tmp_path / f"MULTICHIP_r{n:02d}.json")
    if legacy:
        # The r01-r05 dryrun dumps: no mesh metric keys at all.
        doc = {"n_devices": 8, "rc": 0, "ok": True, "tail": "dryrun"}
    else:
        doc = {
            "drill": "multichip_demo",
            "mesh_merges_per_sec": merges,
            "ici_reduce_ms_p50": ici,
            "cross_slice_bytes": bytes_,
        }
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def test_mesh_rounds_skip_legacy_and_sort(tmp_path):
    _mesh_round(tmp_path, 1, legacy=True)
    _mesh_round(tmp_path, 10, merges=2000.0, ici=1.0, bytes_=4000)
    _mesh_round(tmp_path, 6, merges=1000.0, ici=2.0, bytes_=3000)
    _mesh_round(tmp_path, 7, merges=None, ici=1.0, bytes_=3000)  # partial
    rounds = gate.load_mesh_rounds(str(tmp_path))
    assert [r[0] for r in rounds] == [6, 10]
    assert rounds[0][2] == 1000.0 and rounds[1][4] == 4000.0


def test_mesh_gate_vacuous_with_single_carrier(tmp_path):
    _mesh_round(tmp_path, 1, legacy=True)
    _mesh_round(tmp_path, 6, merges=1000.0, ici=1.0, bytes_=2000)
    code, verdict = gate.evaluate_mesh(gate.load_mesh_rounds(str(tmp_path)))
    assert code == 0 and "vacuous" in verdict


def test_mesh_gate_double_threshold(tmp_path):
    # Baseline r06; r07 moves on every metric but each move clears only
    # ONE of the two bars — all three claims must stay OK.
    _mesh_round(tmp_path, 6, merges=100_000.0, ici=1.0, bytes_=4000.0)
    _mesh_round(
        tmp_path, 7,
        merges=99_700.0,   # -300/s abs > 200 floor, but -0.3% < 20%
        ici=1.15,          # +15% < 20%, and +0.15ms < 2ms floor
        bytes_=4500.0,     # +12.5% < 20%, +500B < 2048B floor
    )
    code, verdict = gate.evaluate_mesh(gate.load_mesh_rounds(str(tmp_path)))
    assert code == 0 and "FAIL" not in verdict


def test_mesh_gate_fails_each_metric(tmp_path):
    base = dict(merges=100_000.0, ici=1.0, bytes_=4000.0)
    # merges collapse: -30% AND -30k/s → both bars tripped.
    _mesh_round(tmp_path, 6, **base)
    _mesh_round(tmp_path, 7, merges=70_000.0, ici=1.0, bytes_=4000.0)
    code, verdict = gate.evaluate_mesh(gate.load_mesh_rounds(str(tmp_path)))
    assert code == 1 and "merges" in verdict
    # ici regression: +300% and +3ms.
    _mesh_round(tmp_path, 7, merges=100_000.0, ici=4.0, bytes_=4000.0)
    code, verdict = gate.evaluate_mesh(gate.load_mesh_rounds(str(tmp_path)))
    assert code == 1 and "ici" in verdict
    # anti-entropy fattening: +150% and +6000B.
    _mesh_round(tmp_path, 7, merges=100_000.0, ici=1.0, bytes_=10_000.0)
    code, verdict = gate.evaluate_mesh(gate.load_mesh_rounds(str(tmp_path)))
    assert code == 1 and "cross_slice" in verdict


def test_mesh_gate_compares_against_best_prior(tmp_path):
    _mesh_round(tmp_path, 6, merges=100_000.0, ici=1.0, bytes_=4000.0)
    _mesh_round(tmp_path, 7, merges=40_000.0, ici=9.0, bytes_=90_000.0)
    # r08 within tolerance of the BEST priors (r06 on all three), even
    # though r07 — the latest prior — was a disaster round.
    _mesh_round(tmp_path, 8, merges=95_000.0, ici=1.1, bytes_=4100.0)
    code, verdict = gate.evaluate_mesh(gate.load_mesh_rounds(str(tmp_path)))
    assert code == 0 and "FAIL" not in verdict


def _write_round(tmp_path, n, wps=None, p99=None, blip=None, passed=True):
    path = str(tmp_path / f"WRITETIER_r{n:02d}.json")
    doc = {"round": n}
    if wps is not None:
        doc["fleet_writes_per_sec"] = wps
        doc["write_p99_ms"] = p99
        doc["failover_blip_ms"] = blip
    if passed is not None:
        doc["pass"] = passed
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def test_write_rounds_skip_partial_and_sort(tmp_path):
    _write_round(tmp_path, 1, wps=None)  # no metrics: skipped, not zeros
    _write_round(tmp_path, 9, wps=0.5, p99=8000.0, blip=2000.0)
    _write_round(tmp_path, 3, wps=0.4, p99=9000.0, blip=2500.0, passed=None)
    rounds = gate.load_write_rounds(str(tmp_path))
    assert [r[0] for r in rounds] == [3, 9]
    assert rounds[0][5] is None and rounds[1][5] is True


def test_write_gate_single_round_gates_on_own_pass(tmp_path):
    # One carrier: drift is vacuous, but the carrier's own chaos verdict
    # still gates — a pass=false r01 must never go green.
    _write_round(tmp_path, 1, wps=0.5, p99=8000.0, blip=2000.0)
    code, verdict = gate.evaluate_write(gate.load_write_rounds(str(tmp_path)))
    assert code == 0 and "vacuous" in verdict
    _write_round(tmp_path, 1, wps=0.5, p99=8000.0, blip=2000.0, passed=False)
    code, verdict = gate.evaluate_write(gate.load_write_rounds(str(tmp_path)))
    assert code == 1 and "pass=false" in verdict


def test_write_gate_double_threshold(tmp_path):
    # Each metric moves, but each move clears only ONE of its two bars.
    _write_round(tmp_path, 1, wps=10.0, p99=8000.0, blip=2000.0)
    _write_round(
        tmp_path, 2,
        wps=9.2,       # -8% < 20%, though -0.8/s abs isn't the gate alone
        p99=9500.0,    # +18.75% < 20%, though +1500ms < 2000ms floor
        blip=2900.0,   # +45% > 20%, but +900ms < 1000ms floor
    )
    code, verdict = gate.evaluate_write(gate.load_write_rounds(str(tmp_path)))
    assert code == 0 and "FAIL" not in verdict


def test_write_gate_fails_each_metric(tmp_path):
    base = dict(wps=10.0, p99=8000.0, blip=2000.0)
    _write_round(tmp_path, 1, **base)
    # throughput collapse: -50% AND -5/s.
    _write_round(tmp_path, 2, wps=5.0, p99=8000.0, blip=2000.0)
    code, verdict = gate.evaluate_write(gate.load_write_rounds(str(tmp_path)))
    assert code == 1 and "fleet_writes_per_sec" in verdict
    # ack-tail regression: +50% AND +4000ms.
    _write_round(tmp_path, 2, wps=10.0, p99=12_000.0, blip=2000.0)
    code, verdict = gate.evaluate_write(gate.load_write_rounds(str(tmp_path)))
    assert code == 1 and "write_p99_ms" in verdict
    # failover blip growth: +100% AND +2000ms.
    _write_round(tmp_path, 2, wps=10.0, p99=8000.0, blip=4000.0)
    code, verdict = gate.evaluate_write(gate.load_write_rounds(str(tmp_path)))
    assert code == 1 and "failover_blip_ms" in verdict


def test_write_gate_compares_against_best_prior(tmp_path):
    _write_round(tmp_path, 1, wps=10.0, p99=8000.0, blip=2000.0)
    _write_round(tmp_path, 2, wps=4.0, p99=20_000.0, blip=9000.0)
    # r03 within tolerance of the BEST priors (r01 on all three), even
    # though r02 — the latest prior — was a disaster round.
    _write_round(tmp_path, 3, wps=9.5, p99=8500.0, blip=2100.0)
    code, verdict = gate.evaluate_write(gate.load_write_rounds(str(tmp_path)))
    assert code == 0 and "FAIL" not in verdict


def test_main_against_repo_rounds():
    assert gate.main([]) == 0  # the committed BENCH_r*.json must pass
