"""Bench regression gate (scripts/bench_gate.py): tail-string metric
extraction, latest-vs-best-prior comparison, and the vacuous pass when
rounds lack the metric."""

import importlib.util
import json
import os

_SPEC = importlib.util.spec_from_file_location(
    "bench_gate",
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
        "bench_gate.py",
    ),
)
gate = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(gate)


def _round(tmp_path, n, merges=None, torn=False):
    path = str(tmp_path / f"BENCH_r{n:02d}.json")
    if torn:
        with open(path, "w") as f:
            f.write('{"tail": "tor')
        return path
    tail = "setup only\n"
    if merges is not None:
        # The metric is JSON text INSIDE the tail capture — the shape the
        # real BENCH dumps have (escaped when serialized, plain after load).
        tail += "".join(f'{{"merges_per_sec": {v}}}\n' for v in merges)
    with open(path, "w") as f:
        json.dump({"n": n, "cmd": "bench", "rc": 0, "tail": tail}, f)
    return path


def test_extracts_best_from_tail(tmp_path):
    p = _round(tmp_path, 4, merges=[100.0, 250.5, 30.0])
    assert gate.best_merges_per_sec(p) == 250.5
    assert gate.best_merges_per_sec(_round(tmp_path, 1)) is None
    assert gate.best_merges_per_sec(_round(tmp_path, 2, torn=True)) is None


def test_gate_passes_within_tolerance(tmp_path):
    _round(tmp_path, 1)  # metric-less rounds are skipped, not zeros
    _round(tmp_path, 2, merges=[1000.0])
    _round(tmp_path, 3, merges=[850.0])  # -15% vs best prior: allowed
    code, verdict = gate.evaluate(gate.load_rounds(str(tmp_path)), 0.20)
    assert code == 0 and "OK" in verdict


def test_gate_fails_on_regression(tmp_path):
    _round(tmp_path, 1, merges=[1000.0])
    _round(tmp_path, 2, merges=[700.0])  # -30%: beyond the 20% floor
    code, verdict = gate.evaluate(gate.load_rounds(str(tmp_path)), 0.20)
    assert code == 1 and "FAIL" in verdict


def test_latest_compares_against_best_prior_not_last(tmp_path):
    _round(tmp_path, 1, merges=[1000.0])
    _round(tmp_path, 2, merges=[400.0])  # a dip in the middle
    _round(tmp_path, 3, merges=[750.0])  # -25% vs r1 (the best), not r2
    code, _ = gate.evaluate(gate.load_rounds(str(tmp_path)), 0.20)
    assert code == 1
    code, _ = gate.evaluate(gate.load_rounds(str(tmp_path)), 0.30)
    assert code == 0


def test_vacuous_pass_with_fewer_than_two_rounds(tmp_path):
    code, verdict = gate.evaluate(gate.load_rounds(str(tmp_path)), 0.20)
    assert code == 0 and "vacuous" in verdict
    _round(tmp_path, 1, merges=[5.0])
    code, _ = gate.evaluate(gate.load_rounds(str(tmp_path)), 0.20)
    assert code == 0


def test_main_against_repo_rounds():
    assert gate.main([]) == 0  # the committed BENCH_r*.json must pass
