"""Ingest fast path (PR 15): wire-window compaction differential suite.

The tentpole claim is an *equivalence*: a publisher that coalesces K
pending windows into one range-framed wire blob (`CCRF` + [lo..hi] +
payload, net/transport.py) and a receiver that decodes frame runs in
batches (parallel/overlap.py DeltaPrefetcher) must land every member on
states BIT-IDENTICAL to the per-delta chain — under seeded simulator
chaos (loss + duplication + partition + crash), with the tiny apply
queue forced to shed, and with the `CCRDT_INGEST_COMPACT=0` kill switch
as the reference arm. Alongside the equivalence:

* legacy interop both directions — a compacted frame fed to the legacy
  decode path (raw `serial.loads_dense`) must FAIL cleanly and the
  anchor fallback must heal the legacy peer; plain single-seq blobs
  from a compact-off publisher must chain through the range-aware
  receiver as the degenerate [seq..seq] frame;
* the PR 10 replay certificate over a compacted run — `lo` rides the
  publish/apply events, so `audit_apply_order` accepts the range jump
  as chained, not a gap-skip, and `certify()` signs ok;
* the `ingest.decode` fault point — a poisoned batch decode degrades to
  per-frame decode (`ingest.decode_degraded` billed) and never wedges.

`run_ingest_chaos` is also the drill behind the chaos_gate ingest leg
(scripts/chaos_gate.py INGEST_REQUIRED_NONZERO).
"""

import os
import sys
import zlib

import pytest

from antidote_ccrdt_tpu.core import serial
from antidote_ccrdt_tpu.net.sim import SimNet
from antidote_ccrdt_tpu.net.transport import (
    FRAME_MAGIC,
    GossipNode,
    decode_range_frame,
    encode_range_frame,
)
from antidote_ccrdt_tpu.obs import events as obs_events
from antidote_ccrdt_tpu.obs.audit import certify, verify_certificate
from antidote_ccrdt_tpu.parallel.delta import like_delta_for
from antidote_ccrdt_tpu.parallel.elastic import (
    DeltaPublisher,
    GossipStore,
    my_replicas,
    sweep_deltas,
)
from antidote_ccrdt_tpu.parallel.overlap import OverlapPipeline
from antidote_ccrdt_tpu.utils import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

from elastic_demo import DRILLS, R, STEPS, reference_digest  # noqa: E402

N = 4
DT = 0.1
TIMEOUT = 0.35


def _compact_env(on: bool):
    """Set/restore the kill switch around a drill arm."""
    prev = os.environ.get("CCRDT_INGEST_COMPACT")
    os.environ["CCRDT_INGEST_COMPACT"] = "1" if on else "0"
    return prev


def _restore_env(prev):
    if prev is None:
        os.environ.pop("CCRDT_INGEST_COMPACT", None)
    else:
        os.environ["CCRDT_INGEST_COMPACT"] = prev


def run_ingest_chaos(type_name, seed, *, compact=True, loss=0.05, dup=0.05,
                     depth=2, drain_every=4):
    """tests/test_overlap.run_overlap_chaos with the publishers DEFERRING
    delta windows (`publish(..., defer=True)`): windows stage until the
    coalesce cap fills or an anchor supersedes them, so the wire carries
    range frames instead of per-window blobs. The tiny queue + withheld
    drains still force the shed path; the final convergence loop
    publishes non-deferred (each publish flushes the staged tail first),
    keeps adopting late-detected deaths, and must land every survivor on
    the sequential reference digest. `compact=False` is the
    CCRDT_INGEST_COMPACT=0 kill-switch arm of the differential.

    depth=2/drain_every=4/coalesce-cap 2 (tighter than
    run_overlap_chaos): coalescing cuts wire entries ~K-fold, so the
    overlap drill's depth-3 queue never overflows under compaction and
    the shed keeps hitting snapshots — the DELTA shed (the hole-healing
    path this differential must cover) needs the smaller queue and
    several frames per anchor interval to fire at all."""
    prev_env = _compact_env(compact)
    prev_k = os.environ.get("CCRDT_INGEST_COALESCE")
    os.environ["CCRDT_INGEST_COALESCE"] = "2"
    try:
        net = SimNet(seed=seed, latency=(0.001, 0.02), loss=loss, dup=dup)
        drill = DRILLS[type_name]
        dense = drill.make_engine()
        names = [f"m{i}" for i in range(N)]
        nodes = {m: GossipNode(net.join(m)) for m in names}
        states = {m: drill.init(dense) for m in names}
        # full_every=8 with a publish EVERY step: the coalesce cap (4)
        # fills strictly inside an anchor interval, so CAP-SIZED range
        # frames ship mid-chaos (anchors also flush whatever is staged
        # when they land, but those tail frames are shorter).
        pubs = {
            m: DeltaPublisher(nodes[m], dense, name=drill.publish_name,
                              full_every=8)
            for m in names
        }
        owned = {m: set() for m in names}
        crashed = set()

        for _ in range(3):
            for m in names:
                nodes[m].heartbeat()
            net.advance(DT)
        for m in names:
            assert set(nodes[m].members()) == set(names), \
                "bootstrap incomplete"

        ovls = {
            m: OverlapPipeline(
                nodes[m], dense, drill.pub_state(dense, states[m]),
                depth=depth, start_thread=False,
            )
            for m in names
        }

        def drain(m):
            view = drill.pub_state(dense, states[m])
            swept = ovls[m].drain_into(view)
            if swept is not view:
                states[m] = drill.set_view(dense, states[m], swept)

        for step in range(STEPS):
            if step == 3:
                net.partition({"m0", "m1"}, {"m2", "m3"})
            if step == 6:
                net.heal()
            if step == 7:
                net.crash("m3")
                crashed.add("m3")
            for m in names:
                if m in crashed:
                    continue
                node = nodes[m]
                node.heartbeat()
                now_owned = owned[m] | set(my_replicas(node, R, TIMEOUT))
                gained = now_owned - owned[m]
                if gained:
                    states[m] = drill.adopt(
                        dense, states[m], sorted(gained), step
                    )
                owned[m] = now_owned
                states[m] = drill.apply(
                    dense, states[m], step, sorted(owned[m])
                )
                pubs[m].publish(
                    drill.pub_state(dense, states[m]), defer=True
                )
                ovls[m].prefetch.poll()
                if step % drain_every == drain_every - 1:
                    drain(m)
            net.advance(DT)

        net.loss = net.dup = 0.0
        ref = reference_digest(type_name)
        live = [m for m in names if m not in crashed]
        for _ in range(40):
            for m in live:
                node = nodes[m]
                node.heartbeat()
                now_owned = owned[m] | set(my_replicas(node, R, TIMEOUT))
                gained = now_owned - owned[m]
                if gained:
                    states[m] = drill.adopt(
                        dense, states[m], sorted(gained), STEPS
                    )
                owned[m] = now_owned
                # Non-deferred: ships any staged tail (flush_wire runs
                # inside publish) plus this window — the convergence
                # loop must never leave windows parked host-side.
                pubs[m].publish(drill.pub_state(dense, states[m]))
                ovls[m].prefetch.poll()
                drain(m)
            net.advance(DT)
            if all(drill.digest(dense, states[m]) == ref for m in live):
                break

        for m in names:
            ovls[m].host.close()
        digests = {m: drill.digest(dense, states[m]) for m in live}
        counters = dict(net.metrics.counters)
        for m in live:
            for k, v in nodes[m].metrics.snapshot()["counters"].items():
                if k.startswith(("overlap.", "ingest.", "net.")):
                    counters[k] = counters.get(k, 0.0) + v
        return digests, counters
    finally:
        _restore_env(prev_env)
        if prev_k is None:
            os.environ.pop("CCRDT_INGEST_COALESCE", None)
        else:
            os.environ["CCRDT_INGEST_COALESCE"] = prev_k


# -- the differential: compacted chaos vs reference vs kill switch ------------


@pytest.mark.slow
def test_compact_chaos_bit_identical_with_forced_shed():
    """Compacted ingest under seeded loss/dup/partition/crash with the
    apply queue forced to overflow: every survivor must land exactly on
    the sequential reference, range frames must actually have crossed
    the wire, and the shed path must actually have fired (otherwise the
    drill proved nothing about hole-healing under compaction)."""
    digests, counters = run_ingest_chaos("topk_rmv", seed=7)
    ref = reference_digest("topk_rmv")
    assert ref, "reference observable is empty — drill is vacuous"
    for m, d in digests.items():
        assert d == ref, f"{m} diverged\ngot: {d}\nref: {ref}"
    assert counters.get("ingest.coalesced_frames", 0) > 0, counters
    assert counters.get("ingest.coalesced_ops", 0) > 0, counters
    assert counters.get("overlap.prefetched_deltas", 0) > 0, counters
    assert counters.get("overlap.dropped_deltas", 0) > 0, counters


@pytest.mark.slow
def test_kill_switch_rerun_is_bit_identical():
    """CCRDT_INGEST_COMPACT=0 must be a true kill switch: the same
    seeded chaos schedule replayed with compaction off converges to the
    same digests, and ships zero compacted frames."""
    d_on, c_on = run_ingest_chaos("topk_rmv", seed=11)
    d_off, c_off = run_ingest_chaos("topk_rmv", seed=11, compact=False)
    ref = reference_digest("topk_rmv")
    assert d_on == d_off
    for m, d in d_on.items():
        assert d == ref, f"{m} diverged under compaction"
    assert c_on.get("ingest.coalesced_frames", 0) > 0, c_on
    assert c_off.get("ingest.coalesced_frames", 0) == 0, c_off


# -- two-store publisher/receiver fixtures ------------------------------------


def _two_stores(tmp_path):
    drill = DRILLS["topk_rmv"]
    dense = drill.make_engine()
    a = GossipStore(str(tmp_path), "a")
    b = GossipStore(str(tmp_path), "b")
    return drill, dense, a, b


def _publish_windows(drill, dense, pub, steps=5):
    """Anchor (seq 1, _prev None) + `steps` deferred delta windows; the
    last flush_wire ships whatever the coalesce cap left staged.
    Returns the publisher's final engine state."""
    st = drill.init(dense)
    st = drill.apply(dense, st, 0, range(R))
    pub.publish(drill.pub_state(dense, st))          # seq 0: anchor
    for step in range(1, steps + 1):
        st = drill.apply(dense, st, step, range(R))
        pub.publish(drill.pub_state(dense, st), defer=True)
    pub.flush_wire()
    return st


def test_compacted_sweep_bit_identical_to_per_delta(tmp_path):
    """Same op stream published twice — deferred/compacted vs per-delta
    — swept by the range-aware receiver: identical digests, and the
    compacted arm's cursor lands on the same final seq."""
    drill, dense, a, b = _two_stores(tmp_path)
    prev_env = _compact_env(True)
    try:
        pub = DeltaPublisher(a, dense, name=drill.publish_name,
                             full_every=100)
        st = _publish_windows(drill, dense, pub)
        cursors = {}
        pb = drill.pub_state(dense, drill.init(dense))
        pb, _ = sweep_deltas(b, dense, pb, cursors)
        got = drill.set_view(dense, drill.init(dense), pb)
        assert drill.digest(dense, got) == drill.digest(dense, st)
        # The receiver's cursor jumped ACROSS the range frames to the
        # publisher's head — no per-seq walk, no gap resync.
        assert cursors["a"] == pub.seq
        assert a.metrics.snapshot()["counters"].get(
            "ingest.coalesced_frames", 0
        ) > 0
    finally:
        _restore_env(prev_env)


def test_legacy_blobs_chain_through_range_aware_receiver(tmp_path):
    """Interop, legacy -> new: a kill-switched publisher ships plain
    single-seq blobs (no CCRF header anywhere on the wire); the
    range-aware sweep must chain them as degenerate [seq..seq] frames
    and converge without a single anchor resync past the bootstrap."""
    drill, dense, a, b = _two_stores(tmp_path)
    prev_env = _compact_env(False)
    try:
        pub = DeltaPublisher(a, dense, name=drill.publish_name,
                             full_every=100)
        st = _publish_windows(drill, dense, pub)
        for seq in a.delta_seqs("a"):
            raw = b.transport.fetch_delta("a", seq)
            assert raw is not None and raw[:4] != FRAME_MAGIC
        cursors = {}
        pb = drill.pub_state(dense, drill.init(dense))
        pb, _ = sweep_deltas(b, dense, pb, cursors)
        got = drill.set_view(dense, drill.init(dense), pb)
        assert drill.digest(dense, got) == drill.digest(dense, st)
        assert cursors["a"] == pub.seq
    finally:
        _restore_env(prev_env)


def test_compacted_frame_fails_legacy_decode_anchor_heals(tmp_path):
    """Interop, new -> legacy: a legacy peer's decode path (raw
    `serial.loads_dense`, no CCRF deframing) must REJECT a compacted
    frame outright — the magic differs by design — after which the
    publisher's NEXT full anchor heals it (the frames themselves are
    invisible to a legacy peer). No torn half-decode, no wedge."""
    drill, dense, a, b = _two_stores(tmp_path)
    prev_env = _compact_env(True)
    try:
        # full_every=6: seq 0 anchors (first publish), 1..5 are the
        # framed windows, and the post-frame publish below (seq 6)
        # lands the anchor a legacy peer resyncs through.
        pub = DeltaPublisher(a, dense, name=drill.publish_name,
                             full_every=6)
        st = _publish_windows(drill, dense, pub)
        framed = [
            s for s in a.delta_seqs("a")
            if b.transport.fetch_delta("a", s)[:4] == FRAME_MAGIC
        ]
        assert framed, "no compacted frame reached the wire"
        raw = b.transport.fetch_delta("a", framed[0])
        with pytest.raises(Exception):
            serial.loads_dense(
                raw, like_delta_for(
                    dense, drill.pub_state(dense, drill.init(dense))
                )
            )
        # The new-side deframe of the same bytes is exact.
        lo, hi, payload = decode_range_frame(raw, framed[0])
        assert lo < hi == framed[0]
        assert encode_range_frame(lo, hi, payload) == raw
        # Legacy recovery path: the next anchor publish, then a
        # full-snapshot fetch of it.
        st = drill.apply(dense, st, 6, range(R))
        res = pub.publish(drill.pub_state(dense, st))
        assert res["kind"] == "full"
        pb = drill.pub_state(dense, drill.init(dense))
        got_snap = b.fetch("a", pb, dense=dense)
        assert got_snap is not None
        _seq, peer = got_snap
        healed = drill.set_view(
            dense, drill.init(dense), dense.merge(pb, peer)
        )
        assert drill.digest(dense, healed) == drill.digest(dense, st)
    finally:
        _restore_env(prev_env)


# -- replay certificate over a compacted run (PR 10 interop) ------------------


def test_replay_certificate_over_compacted_run(tmp_path):
    """The flight-recorder events of a compacted publish/sweep run must
    replay-certify clean: `delta.publish`/`delta.apply` carry `lo`, the
    causal-delivery audit accepts the range jumps as chained, and the
    signed certificate verifies. A compacted frame must actually be in
    evidence (else the test is the legacy certificate test again)."""
    drill, dense, a, b = _two_stores(tmp_path)
    prev_env = _compact_env(True)
    obs_events.reset("ingest-cert")
    try:
        pub = DeltaPublisher(a, dense, name=drill.publish_name,
                             full_every=100)
        st = _publish_windows(drill, dense, pub)
        cursors = {}
        pb = drill.pub_state(dense, drill.init(dense))
        pb, _ = sweep_deltas(b, dense, pb, cursors)
        got = drill.set_view(dense, drill.init(dense), pb)
        dig = drill.digest(dense, got)
        assert dig == drill.digest(dense, st)
        # The drill digest is a list of tuples; the certificate's
        # agreement probe wants a scalar (or int-vector) digest.
        dig_crc = zlib.crc32(repr(dig).encode())

        evs = obs_events.events()
        pubs = [dict(e, member="a") for e in evs
                if e["kind"] == "delta.publish" and e.get("origin") == "a"]
        apps = [dict(e, member="b") for e in evs
                if e["kind"] == "delta.apply" and e.get("origin") == "a"]
        assert any(e.get("lo", e["dseq"]) < e["dseq"] for e in pubs), \
            "no compacted frame in evidence"
        assert any(e.get("lo", e["dseq"]) < e["dseq"] for e in apps)
        cert = certify(
            logs={"flight-a-1.jsonl": pubs, "flight-b-1.jsonl": apps},
            digests={"a": dig_crc, "b": dig_crc},
            reference=dig_crc,
            meta={"drill": "ingest-compacted"},
        )
        assert cert["ok"], cert
        assert cert["checks"]["causal_delivery"] is True
        assert cert["checks"]["op_count_reconciliation"] is True
        assert verify_certificate(cert)
    finally:
        _restore_env(prev_env)
        obs_events.reset("?")


# -- the ingest.decode fault point --------------------------------------------


def test_ingest_decode_fault_degrades_never_wedges(tmp_path):
    """A fired `ingest.decode` fault poisons the batched decode pass;
    the prefetcher must bill `ingest.decode_degraded`, fall back to
    per-frame decode, and still converge the receiver bit-identically —
    a corrupt batch stage degrades, it never wedges the chain."""
    drill, dense, a, b = _two_stores(tmp_path)
    prev_env = _compact_env(True)
    try:
        pub = DeltaPublisher(a, dense, name=drill.publish_name,
                             full_every=100)
        st = _publish_windows(drill, dense, pub)
        ovl = OverlapPipeline(
            b, dense, drill.pub_state(dense, drill.init(dense)),
            start_thread=False,
        )
        with faults.injected(
            {"ingest.decode": [{"action": "drop", "at": [0]}]}, seed=5
        ):
            while ovl.prefetch.poll():
                pass
        pb = ovl.drain_into(drill.pub_state(dense, drill.init(dense)))
        got = drill.set_view(dense, drill.init(dense), pb)
        assert drill.digest(dense, got) == drill.digest(dense, st)
        cnt = b.metrics.snapshot()["counters"]
        assert cnt.get("ingest.decode_degraded", 0) > 0, cnt
        ovl.host.close()
    finally:
        _restore_env(prev_env)
