"""Differential tests: batched log compaction vs scalar replay.

The contract (ops/compaction.py): replaying the compacted log from a fresh
state yields the same *observable* state as replaying the original log —
the guarantee the reference's pairwise compact_ops protocol provides
(topk_rmv.erl:178-223), generalized to whole-log single-dispatch form.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from antidote_ccrdt_tpu.models.average import AverageScalar  # noqa: E402
from antidote_ccrdt_tpu.models.topk import TopkScalar  # noqa: E402
from antidote_ccrdt_tpu.models.topk_rmv import TopkRmvScalar  # noqa: E402
from antidote_ccrdt_tpu.models.wordcount import WordcountScalar  # noqa: E402
from antidote_ccrdt_tpu.models.leaderboard import LeaderboardScalar  # noqa: E402
from antidote_ccrdt_tpu.ops.compaction import (  # noqa: E402
    KIND_ADD,
    KIND_ADD_R,
    KIND_DEAD,
    KIND_LB_ADD,
    KIND_LB_ADD_R,
    KIND_LB_BAN,
    KIND_LB_DEAD,
    KIND_RMV,
    KIND_RMV_R,
    TopkRmvLog,
    compact_average_log,
    compact_leaderboard_log,
    compact_topk_log,
    compact_topk_rmv_log,
    compact_wordcount_log,
)


def _random_topk_rmv_log(rng, L, n_ids, n_dcs, rmv_frac=0.3, dup_frac=0.1):
    """A causally-plausible effect log: per-DC clocks advance; removal vcs
    are snapshots of the generator's frontier at removal time."""
    kind = np.full(L, KIND_DEAD, np.int32)
    key = np.zeros(L, np.int32)
    id_ = np.zeros(L, np.int32)
    score = np.zeros(L, np.int32)
    dc = np.zeros(L, np.int32)
    ts = np.zeros(L, np.int32)
    vc = np.zeros((L, n_dcs), np.int32)
    frontier = np.zeros(n_dcs, np.int32)
    n_real = int(L * 0.9)  # leave some padding rows
    prev = None
    for i in range(n_real):
        if prev is not None and rng.random() < dup_frac:
            (kind[i], id_[i], score[i], dc[i], ts[i], vc[i]) = prev
            continue
        d = rng.integers(0, n_dcs)
        x = rng.integers(0, n_ids)
        if rng.random() < rmv_frac:
            kind[i] = KIND_RMV if rng.random() < 0.7 else KIND_RMV_R
            id_[i] = x
            # vc snapshot: current frontier, jittered down (concurrent adds
            # it did not observe survive — the add-wins case).
            vc[i] = np.maximum(frontier - rng.integers(0, 3, n_dcs), 0)
        else:
            frontier[d] += 1
            kind[i] = KIND_ADD if rng.random() < 0.7 else KIND_ADD_R
            id_[i] = x
            score[i] = rng.integers(1, 1000)
            dc[i] = d
            ts[i] = frontier[d]
        prev = (kind[i], id_[i], score[i], dc[i], ts[i], vc[i].copy())
    return TopkRmvLog(
        kind=jnp.asarray(kind),
        key=jnp.asarray(key),
        id=jnp.asarray(id_),
        score=jnp.asarray(score),
        dc=jnp.asarray(dc),
        ts=jnp.asarray(ts),
        vc=jnp.asarray(vc),
    )


def _replay_scalar(log_np, size=10):
    S = TopkRmvScalar()
    state = S.new(size)
    kind, key, id_, score, dc, ts, vc = log_np
    names = {KIND_ADD: "add", KIND_ADD_R: "add_r", KIND_RMV: "rmv", KIND_RMV_R: "rmv_r"}
    for i in range(len(kind)):
        k = int(kind[i])
        if k == KIND_DEAD:
            continue
        if k in (KIND_ADD, KIND_ADD_R):
            eff = (names[k], (int(id_[i]), int(score[i]), (int(dc[i]), int(ts[i]))))
        else:
            vcd = {d: int(vc[i, d]) for d in range(vc.shape[1]) if vc[i, d] > 0}
            eff = (names[k], (int(id_[i]), vcd))
        state, _extras = S.update(eff, state)
    return S, state


def _log_to_np(log):
    return tuple(
        np.asarray(x) for x in (log.kind, log.key, log.id, log.score, log.dc, log.ts, log.vc)
    )


class TestTopkRmvCompaction:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_observable_equal_after_compaction(self, seed):
        rng = np.random.default_rng(seed)
        log = _random_topk_rmv_log(rng, L=128, n_ids=12, n_dcs=4)
        n_in = int(np.sum(np.asarray(log.kind) != KIND_DEAD))
        # m_keep large enough to be lossless for this id density
        clog, n_live = compact_topk_rmv_log(log, 16)
        assert int(n_live) < n_in  # it actually compacts
        S, ref_state = _replay_scalar(_log_to_np(log))
        _, cmp_state = _replay_scalar(_log_to_np(clog))
        # value/1 mirrors the reference's *unsorted* observed fold
        # (topk_rmv.erl:92-95) — order is not part of the contract.
        assert sorted(S.value(ref_state)) == sorted(S.value(cmp_state))
        assert S.equal(ref_state, cmp_state)

    def test_idempotent(self):
        rng = np.random.default_rng(7)
        log = _random_topk_rmv_log(rng, L=64, n_ids=8, n_dcs=3)
        c1, n1 = compact_topk_rmv_log(log, 8)
        c2, n2 = compact_topk_rmv_log(c1, 8)
        assert int(n1) == int(n2)
        S, s1 = _replay_scalar(_log_to_np(c1))
        _, s2 = _replay_scalar(_log_to_np(c2))
        assert S.equal(s1, s2)

    def test_rmv_fusion_single_op_per_id(self):
        # Three removals of one id fuse into one rmv with the vc join.
        D = 3
        vcs = np.array([[5, 0, 0], [0, 7, 0], [2, 1, 9]], np.int32)
        log = TopkRmvLog(
            kind=jnp.asarray([KIND_RMV, KIND_RMV_R, KIND_RMV], np.int32),
            key=jnp.zeros(3, jnp.int32),
            id=jnp.full(3, 4, jnp.int32),
            score=jnp.zeros(3, jnp.int32),
            dc=jnp.zeros(3, jnp.int32),
            ts=jnp.zeros(3, jnp.int32),
            vc=jnp.asarray(vcs),
        )
        clog, n_live = compact_topk_rmv_log(log, 4)
        assert int(n_live) == 1
        assert int(clog.kind[0]) == KIND_RMV  # rmv absorbs rmv_r
        np.testing.assert_array_equal(np.asarray(clog.vc[0]), [5, 7, 9])

    def test_dominated_add_deleted(self):
        # add (dc0, ts=3) dominated by rmv vc [5,0]; concurrent add at dc1
        # survives (add-wins).
        log = TopkRmvLog(
            kind=jnp.asarray([KIND_ADD, KIND_RMV, KIND_ADD], np.int32),
            key=jnp.zeros(3, jnp.int32),
            id=jnp.asarray([1, 1, 1], np.int32),
            score=jnp.asarray([50, 0, 60], np.int32),
            dc=jnp.asarray([0, 0, 1], np.int32),
            ts=jnp.asarray([3, 0, 2], np.int32),
            vc=jnp.asarray([[0, 0], [5, 0], [0, 0]], np.int32),
        )
        clog, n_live = compact_topk_rmv_log(log, 4)
        assert int(n_live) == 2  # fused rmv + surviving add
        kinds = set(int(k) for k in np.asarray(clog.kind[:2]))
        assert kinds == {KIND_RMV, KIND_ADD}
        add_row = int(np.argmax(np.asarray(clog.kind[:2]) == KIND_ADD))
        assert int(clog.dc[add_row]) == 1 and int(clog.score[add_row]) == 60

    def test_duplicate_dedup_keeps_observable_add(self):
        # Exact [add_r, add] duplicates: dedup must keep the untagged add
        # (compact_ops({add_r,X},{add,X}) -> {noop, {add,X}}, :255-259).
        log = TopkRmvLog(
            kind=jnp.asarray([KIND_ADD_R, KIND_ADD], np.int32),
            key=jnp.zeros(2, jnp.int32),
            id=jnp.asarray([1, 1], np.int32),
            score=jnp.asarray([50, 50], np.int32),
            dc=jnp.asarray([0, 0], np.int32),
            ts=jnp.asarray([3, 3], np.int32),
            vc=jnp.zeros((2, 2), np.int32),
        )
        clog, n_live = compact_topk_rmv_log(log, 4)
        assert int(n_live) == 1
        assert int(clog.kind[0]) == KIND_ADD

    def test_winner_demotion_tags(self):
        # Two untagged adds same id: winner stays add, loser demoted add_r.
        log = TopkRmvLog(
            kind=jnp.asarray([KIND_ADD, KIND_ADD], np.int32),
            key=jnp.zeros(2, jnp.int32),
            id=jnp.asarray([2, 2], np.int32),
            score=jnp.asarray([10, 90], np.int32),
            dc=jnp.asarray([0, 1], np.int32),
            ts=jnp.asarray([1, 1], np.int32),
            vc=jnp.zeros((2, 2), np.int32),
        )
        clog, n_live = compact_topk_rmv_log(log, 4)
        assert int(n_live) == 2
        assert int(clog.score[0]) == 90 and int(clog.kind[0]) == KIND_ADD
        assert int(clog.score[1]) == 10 and int(clog.kind[1]) == KIND_ADD_R


class TestSimpleTypeCompaction:
    def test_average(self):
        rng = np.random.default_rng(0)
        L, NK = 64, 4
        key = rng.integers(0, NK, L).astype(np.int32)
        val = rng.integers(-50, 100, L).astype(np.int32)
        num = rng.integers(0, 4, L).astype(np.int32)  # some zero: padding
        k, v, n, n_live = compact_average_log(
            jnp.asarray(key), jnp.asarray(val), jnp.asarray(num)
        )
        assert int(n_live) <= NK
        S = AverageScalar()
        for nk in range(NK):
            ref = S.new()
            for i in range(L):
                if key[i] == nk and num[i] > 0:
                    ref, _ = S.update(("add", (int(val[i]), int(num[i]))), ref)
            got = S.new()
            for i in range(int(n_live)):
                if int(k[i]) == nk:
                    got, _ = S.update(("add", (int(v[i]), int(n[i]))), got)
            assert S.equal(ref, got)

    def test_topk_max_not_last_wins(self):
        key = jnp.zeros(4, jnp.int32)
        id_ = jnp.asarray([7, 7, 3, 7], jnp.int32)
        score = jnp.asarray([50, 90, 20, 60], jnp.int32)
        k, i, s, n_live = compact_topk_log(key, id_, score)
        assert int(n_live) == 2
        got = {(int(i[j]), int(s[j])) for j in range(2)}
        assert got == {(7, 90), (3, 20)}  # max, not last-wins (quirk #4)

    def test_topk_differential(self):
        rng = np.random.default_rng(3)
        L = 100
        key = np.zeros(L, np.int32)
        id_ = rng.integers(0, 10, L).astype(np.int32)
        score = rng.integers(0, 500, L).astype(np.int32)
        score[rng.random(L) < 0.1] = -1  # padding
        k, i, s, n_live = compact_topk_log(
            jnp.asarray(key), jnp.asarray(id_), jnp.asarray(score)
        )
        S = TopkScalar()
        ref = S.new(5)
        for j in range(L):
            if score[j] >= 0:
                ref, _ = S.update(("add", (int(id_[j]), int(score[j]))), ref)
        got = S.new(5)
        for j in range(int(n_live)):
            got, _ = S.update(("add", (int(i[j]), int(s[j]))), got)
        assert S.value(ref) == S.value(got)

    def test_wordcount(self):
        rng = np.random.default_rng(5)
        L = 80
        key = rng.integers(0, 2, L).astype(np.int32)
        tok = rng.integers(0, 12, L).astype(np.int32)
        cnt = rng.integers(1, 5, L).astype(np.int32)
        tok[rng.random(L) < 0.15] = -1  # padding
        k, t, c, n_live = compact_wordcount_log(
            jnp.asarray(key), jnp.asarray(tok), jnp.asarray(cnt)
        )
        for nk in range(2):
            ref = {}
            for j in range(L):
                if tok[j] >= 0 and key[j] == nk:
                    ref[int(tok[j])] = ref.get(int(tok[j]), 0) + int(cnt[j])
            got = {}
            for j in range(int(n_live)):
                if int(k[j]) == nk:
                    got[int(t[j])] = int(c[j])
            assert ref == got


class TestLeaderboardCompaction:
    @staticmethod
    def _replay(kind, id_, score, n, board_size=4):
        """Replay rows [0, n) of a leaderboard log through the scalar type."""
        S = LeaderboardScalar()
        state = S.new(board_size)
        names = {KIND_LB_ADD: "add", KIND_LB_ADD_R: "add_r"}
        for j in range(n):
            k = int(kind[j])
            if k == KIND_LB_DEAD:
                continue
            if k == KIND_LB_BAN:
                eff = ("ban", int(id_[j]))
            else:
                eff = (names[k], (int(id_[j]), int(score[j])))
            state, _extras = S.update(eff, state)
        return S, state

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_observable_equal_after_compaction(self, seed):
        rng = np.random.default_rng(seed)
        L, P = 96, 10
        kind = np.where(
            rng.random(L) < 0.2,
            KIND_LB_BAN,
            np.where(rng.random(L) < 0.3, KIND_LB_ADD_R, KIND_LB_ADD),
        ).astype(np.int32)
        kind[rng.random(L) < 0.1] = KIND_LB_DEAD  # padding
        key = np.zeros(L, np.int32)
        id_ = rng.integers(0, P, L).astype(np.int32)
        score = rng.integers(1, 1000, L).astype(np.int32)
        ko, keyo, ido, so, n_live = compact_leaderboard_log(
            jnp.asarray(kind), jnp.asarray(key), jnp.asarray(id_), jnp.asarray(score)
        )
        n_in = int(np.sum(kind != KIND_LB_DEAD))
        assert int(n_live) < n_in  # it actually compacts
        S, ref = self._replay(kind, id_, score, L)
        _, got = self._replay(np.asarray(ko), np.asarray(ido), np.asarray(so), int(n_live))
        assert S.equal(ref, got)

    def test_add_add_keeps_max(self):
        kind = jnp.asarray([KIND_LB_ADD_R, KIND_LB_ADD, KIND_LB_ADD], jnp.int32)
        key = jnp.zeros(3, jnp.int32)
        id_ = jnp.asarray([5, 5, 5], jnp.int32)
        score = jnp.asarray([70, 90, 40], jnp.int32)
        ko, _, ido, so, n_live = compact_leaderboard_log(kind, key, id_, score)
        assert int(n_live) == 1
        assert (int(ko[0]), int(ido[0]), int(so[0])) == (KIND_LB_ADD, 5, 90)

    def test_ban_deletes_all_adds_either_order(self):
        # Pairwise (leaderboard.erl:201) only deletes adds *before* the ban;
        # whole-log closure drops adds after it too (bans are permanent, and
        # the ban rides the same log — replay-equivalent, strictly smaller).
        kind = jnp.asarray(
            [KIND_LB_ADD, KIND_LB_BAN, KIND_LB_ADD, KIND_LB_BAN], jnp.int32
        )
        key = jnp.zeros(4, jnp.int32)
        id_ = jnp.asarray([3, 3, 3, 3], jnp.int32)
        score = jnp.asarray([10, 0, 99, 0], jnp.int32)
        ko, _, ido, _, n_live = compact_leaderboard_log(kind, key, id_, score)
        assert int(n_live) == 1  # bans dedupe, adds die
        assert int(ko[0]) == KIND_LB_BAN and int(ido[0]) == 3

    def test_idempotent(self):
        rng = np.random.default_rng(9)
        L = 64
        kind = rng.integers(0, 3, L).astype(np.int32)
        key = rng.integers(0, 2, L).astype(np.int32)
        id_ = rng.integers(0, 8, L).astype(np.int32)
        score = rng.integers(1, 100, L).astype(np.int32)
        args = tuple(jnp.asarray(x) for x in (kind, key, id_, score))
        k1, key1, i1, s1, n1 = compact_leaderboard_log(*args)
        k2, _, i2, s2, n2 = compact_leaderboard_log(k1, key1, i1, s1)
        assert int(n1) == int(n2)
        np.testing.assert_array_equal(np.asarray(k1), np.asarray(k2))
        np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))


# --- round 4: the production callers (VERDICT-r3 item 2) -------------------


class TestCompactEffectOps:
    """Term-level whole-log compaction (`compact_effect_ops`) — the surface
    the bridge's grid_compact serves. Differential contract: replaying the
    compacted effect list through the scalar reference semantics matches
    replaying the raw list."""

    @staticmethod
    def _log_to_effects(log):
        kind, key, id_, score, dc, ts, vc = _log_to_np(log)
        names = {KIND_ADD: "add", KIND_ADD_R: "add_r",
                 KIND_RMV: "rmv", KIND_RMV_R: "rmv_r"}
        out = []
        for i in range(len(kind)):
            k = int(kind[i])
            if k == KIND_DEAD:
                continue
            if k in (KIND_ADD, KIND_ADD_R):
                out.append((names[k],
                            (int(id_[i]), int(score[i]),
                             (int(dc[i]), int(ts[i])))))
            else:
                vcd = {d: int(vc[i, d]) for d in range(vc.shape[1]) if vc[i, d] > 0}
                out.append((names[k], (int(id_[i]), vcd)))
        return out

    @pytest.mark.parametrize("seed", [0, 5])
    def test_topk_rmv_differential(self, seed):
        from antidote_ccrdt_tpu.ops.compaction import compact_effect_ops

        rng = np.random.default_rng(seed)
        log = _random_topk_rmv_log(rng, 192, n_ids=24, n_dcs=3)
        effects = self._log_to_effects(log)
        compacted = compact_effect_ops("topk_rmv", effects)
        assert len(compacted) < len(effects)
        S = TopkRmvScalar()
        raw = S.new(8)
        for e in effects:
            raw, _ = S.update(e, raw)
        cmp_ = S.new(8)
        for e in compacted:
            cmp_, _ = S.update(e, cmp_)
        # value/1's list order tracks insertion (unspecified in the
        # reference); compaction reorders groups, so compare as sets.
        assert sorted(S.value(raw)) == sorted(S.value(cmp_))
        # Tombstones fused per id, never dropped.
        assert raw.removals == cmp_.removals
        # One rmv per id at most.
        rmv_ids = [p[0] for k, p in compacted if k.startswith("rmv")]
        assert len(rmv_ids) == len(set(rmv_ids))

    def test_average_topk_wordcount_leaderboard(self):
        from antidote_ccrdt_tpu.ops.compaction import compact_effect_ops

        out = compact_effect_ops("average", [("add", (3, 1)), ("add", (5, 2))])
        assert out == [("add", (8, 3))]

        out = compact_effect_ops(
            "topk", [("add", (4, 10)), ("add", (4, 30)), ("add", (2, 7))]
        )
        assert sorted(out) == [("add", (2, 7)), ("add", (4, 30))]

        out = compact_effect_ops(
            "leaderboard",
            [("add", (1, 10)), ("add_r", (1, 40)), ("ban", 2), ("add", (2, 99))],
        )
        assert ("ban", 2) in out
        assert ("add_r", (1, 40)) in out and len(out) == 2

        out = compact_effect_ops(
            "wordcount", [("add", "a b a"), ("add_counts", {"b": 2, "c": 1})]
        )
        assert out == [("add_counts", {"a": 2, "b": 3, "c": 1})]
        # worddocumentcount dedupes per document FIRST (wordcount.erl:76-86).
        out = compact_effect_ops(
            "worddocumentcount", [("add", "a b a"), ("add", "a c")]
        )
        assert out == [("add_counts", {"a": 2, "b": 1, "c": 1})]

    def test_unknown_type_and_empty(self):
        from antidote_ccrdt_tpu.ops.compaction import compact_effect_ops

        assert compact_effect_ops("topk_rmv", []) == []
        with pytest.raises(ValueError, match="no whole-log compactor"):
            compact_effect_ops("mystery", [("add", 1)])


class TestCoalesce:
    """Batch coalescing (`coalesce_topk_rmv_ops` via the engine's
    `coalesce_ops`): k batches fuse into one compacted batch whose single
    apply reaches the same observable state as applying the k batches in
    sequence."""

    def _gen(self, seed, R=3, I=64, D=3, B=48, Br=8, k=3, zipf_a=1.1):
        from antidote_ccrdt_tpu.harness.opgen import TopkRmvEffectGen, Workload

        gen = TopkRmvEffectGen(
            Workload(n_replicas=R, n_ids=I, zipf_a=zipf_a, score_max=1000, seed=seed)
        )
        return [gen.next_batch(B, Br) for _ in range(k)]

    @pytest.mark.parametrize("seed", [0, 1])
    def test_matches_sequential_apply(self, seed):
        from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense

        # Id space wide / skew mild enough that per-batch rank overflow
        # (lossy) stays clear — the precondition for exact slot equality
        # (asserted below; a lossy sequential path may drop history the
        # coalesced union keeps).
        R, I, D = 3, 4096, 3
        dense = make_dense(n_ids=I, n_dcs=D, size=8, slots_per_id=4)
        batches = self._gen(seed, R=R, I=I, D=D, B=32, Br=6, zipf_a=1.02)
        seq = dense.init(n_replicas=R)
        for ops in batches:
            seq, _ = dense.apply_ops(seq, ops, collect_dominated=False)
        fused, n_add, n_rmv = dense.coalesce_ops(batches)
        assert (n_add > 0).all()
        one = dense.init(n_replicas=R)
        one, _ = dense.apply_ops(one, fused, collect_dominated=False)
        # The id-space is large enough that per-batch rank overflow is not
        # hit (no lossy truncation) — then slot/tombstone equality is exact.
        assert not np.asarray(seq.lossy).any()
        assert np.array_equal(np.asarray(seq.slot_score), np.asarray(one.slot_score))
        assert np.array_equal(np.asarray(seq.slot_ts), np.asarray(one.slot_ts))
        assert np.array_equal(np.asarray(seq.slot_dc), np.asarray(one.slot_dc))
        assert np.array_equal(np.asarray(seq.rmv_vc), np.asarray(one.rmv_vc))
        # vc is NOT compared: compaction deletes dominated adds, which the
        # sequential path lets advance the clock (the same divergence the
        # reference's add/rmv compaction rule accepts, topk_rmv.erl:182-187).
        assert dense.equal(seq, one)

    def test_window_overflow_raises(self):
        from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense

        dense = make_dense(n_ids=64, n_dcs=3, size=8, slots_per_id=4)
        batches = self._gen(2)
        with pytest.raises(ValueError, match="overflows"):
            dense.coalesce_ops(batches, out_adds=4, out_rmvs=1)

    def test_dense_replay_and_stream_apply(self):
        from antidote_ccrdt_tpu.harness.dense_replay import DenseReplay
        from antidote_ccrdt_tpu.harness.pipeline import stream_apply
        from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense

        R = 3
        dense = make_dense(n_ids=64, n_dcs=3, size=8, slots_per_id=4)
        batches = self._gen(3, R=R)

        rp_raw = DenseReplay(dense, n_replicas=R)
        for ops in batches:
            rp_raw.apply(ops)
        rp_c = DenseReplay(dense, n_replicas=R)
        rp_c.apply_coalesced(batches)
        assert dense.equal(rp_raw.state, rp_c.state)
        assert rp_c.metrics.counters["coalesce_ops_out"] < rp_c.metrics.counters[
            "coalesce_ops_in"
        ]

        # stream_apply(coalesce=2) over 3 batches: one fused pair + the
        # partial tail group, same observable end state.
        st, n = stream_apply(
            dense, dense.init(n_replicas=R), iter(batches), coalesce=2,
            apply_kwargs=dict(collect_dominated=False),
        )
        assert n == 3
        assert dense.equal(rp_raw.state, st)

    def test_replay_without_capability_raises(self):
        from antidote_ccrdt_tpu.harness.dense_replay import DenseReplay
        from antidote_ccrdt_tpu.models.average import AverageDense

        rp = DenseReplay(AverageDense(), n_replicas=2)
        with pytest.raises(TypeError, match="coalesce"):
            rp.apply_coalesced([])
