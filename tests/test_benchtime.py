"""utils/benchtime.py — the measurement discipline every benchmark leans
on. If `windowed`/`stack_rounds`/`sync` rot, every recorded perf number
silently degrades to measuring the wrong thing, so they get their own
tests (they were previously exercised only by the benchmarks)."""

import numpy as np

import jax
import jax.numpy as jnp

from antidote_ccrdt_tpu.utils.benchtime import stack_rounds, sync, windowed


def test_stack_rounds_stacks_leading_axis():
    batches = [
        {"a": jnp.full((2,), i), "b": jnp.full((3, 4), i)} for i in range(5)
    ]
    stacked = stack_rounds(batches)
    assert stacked["a"].shape == (5, 2)
    assert stacked["b"].shape == (5, 3, 4)
    assert np.asarray(stacked["a"])[3, 0] == 3


def test_sync_returns_first_leaf_element():
    tree = {"x": jnp.arange(6).reshape(2, 3) + 10}
    assert int(sync(tree)) == 10


def test_windowed_rate_arithmetic_exact(monkeypatch):
    """Pin windowed()'s accounting exactly with a deterministic clock:
    each perf_counter call advances 1s, so every timed window 'takes' 1s.
    Then rate must be OPS*W per second of window time and p50 must be
    (1/W) seconds — warmup excluded, per-round division by W correct. A
    regression that counts the warmup window's ops, mis-divides by W, or
    drops a timed window changes these exact values."""
    from antidote_ccrdt_tpu.utils import benchtime

    W, OPS, TIMED = 4, 7, 2

    def apply_fn(st, ops):
        return st + jnp.sum(ops)

    windows = [
        stack_rounds([jnp.full((2,), w * 10 + r) for r in range(W)])
        for w in range(1 + TIMED)
    ]

    t = {"now": 0.0}

    def fake_clock():
        t["now"] += 1.0
        return t["now"]

    monkeypatch.setattr(benchtime.time, "perf_counter", fake_clock)
    rate, p50_ms = windowed(apply_fn, jnp.zeros(()), windows, ops_per_round=OPS)
    # each timed window: t0 then t1 -> exactly 1.0s; times = [1/W] * TIMED
    assert rate == OPS * W * TIMED / (TIMED / W * W)  # = OPS * W
    assert rate == OPS * W
    assert p50_ms == 1000.0 / W
