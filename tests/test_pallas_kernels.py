"""Pallas kernels: differential tests against the XLA reference paths.

These run in interpret mode on the CPU test mesh; the same kernels compile
for TPU (sort verified on v5e — see kernel module docstring for measured
timings and why the XLA variadic sort remains the default hot path)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from antidote_ccrdt_tpu.models.topk_rmv_dense import _sort_slots
from antidote_ccrdt_tpu.ops.pallas_kernels import (
    combine_duplicate_rows,
    oddeven_network,
    scatter_max_rows_onehot_pallas,
    scatter_max_rows_pallas,
    sort_slots_pallas,
)


def test_oddeven_network_sorts_everything():
    for n in (2, 3, 4, 6, 8, 16):
        net = oddeven_network(n)
        rng = np.random.default_rng(n)
        for _ in range(50):
            a = rng.integers(0, 10, n)
            b = a.copy()
            for i, j in net:
                # descending compare-exchange
                if b[j] > b[i]:
                    b[i], b[j] = b[j], b[i]
            assert (b == np.sort(a)[::-1]).all(), (n, a, b)


@pytest.mark.parametrize("seed", range(5))
@pytest.mark.parametrize("w,m", [(8, 4), (4, 2), (6, 3)])
def test_sort_slots_matches_xla(seed, w, m):
    rng = np.random.default_rng(seed)
    shape = (2, 3, 17, w)
    NEG = np.iinfo(np.int32).min + 1
    ts = rng.integers(0, 4, shape).astype(np.int32)  # many empties + dups
    score = np.where(ts == 0, NEG, rng.integers(-3, 3, shape)).astype(np.int32)
    dc = np.where(ts == 0, 0, rng.integers(0, 3, shape)).astype(np.int32)
    ref = _sort_slots(jnp.asarray(score), jnp.asarray(dc), jnp.asarray(ts), m)
    got = sort_slots_pallas(jnp.asarray(score), jnp.asarray(dc), jnp.asarray(ts), m, True, 128)
    for name, a, b in zip(["score", "dc", "ts", "n_live"], ref, got):
        assert np.array_equal(np.asarray(a), np.asarray(b)), (name, seed, w)


@pytest.mark.parametrize("seed", range(8))
def test_scatter_max_matches_reference(seed):
    rng = np.random.default_rng(seed)
    R = int(rng.integers(1, 4))
    T = int(rng.integers(2, 60))
    D = int(rng.integers(1, 40))
    B = int(rng.integers(1, 40))
    table = rng.integers(0, 10, (R, T, D)).astype(np.int32)
    rows = rng.integers(-3, T, (R, B)).astype(np.int32)  # negatives = padding
    upd = rng.integers(0, 20, (R, B, D)).astype(np.int32)
    exp = table.copy()
    for r in range(R):
        for j in range(B):
            if rows[r, j] >= 0:
                exp[r, rows[r, j]] = np.maximum(exp[r, rows[r, j]], upd[r, j])
    r2, u2 = combine_duplicate_rows(jnp.asarray(rows), jnp.asarray(upd), T)
    got = scatter_max_rows_pallas(jnp.asarray(table), r2, u2, True)
    assert np.array_equal(np.asarray(got), exp)


@pytest.mark.parametrize("seed", range(8))
def test_onehot_scatter_max_matches_reference(seed):
    # Tiled one-hot MXU scatter-max (verified infrastructure; the XLA
    # one-hot matmul remains the production tombstone path — see kernel
    # docstring for the measured in-situ regression).
    # T is always a multiple of 4 (the G-fold row packing); duplicates and
    # sentinel/negative (dropped) rows are exercised.
    rng = np.random.default_rng(100 + seed)
    R = int(rng.integers(1, 4))
    T = 4 * int(rng.integers(1, 20))
    D = int(rng.integers(1, 40))
    B = int(rng.integers(1, 50))
    table = rng.integers(0, 10, (R, T, D)).astype(np.int32)
    rows = rng.integers(-3, T + 2, (R, B)).astype(np.int32)  # some dropped
    upd = rng.integers(0, 1 << 20, (R, B, D)).astype(np.int32)
    exp = table.copy()
    for r in range(R):
        for j in range(B):
            if 0 <= rows[r, j] < T:
                exp[r, rows[r, j]] = np.maximum(exp[r, rows[r, j]], upd[r, j])
    got = scatter_max_rows_onehot_pallas(
        jnp.asarray(table), jnp.asarray(rows), jnp.asarray(upd), True
    )
    assert np.array_equal(np.asarray(got), exp), seed


def test_onehot_scatter_max_full_value_range():
    # 31-bit values must survive the 5x7-bit plane decomposition exactly.
    table = jnp.zeros((1, 8, 3), jnp.int32)
    upd = jnp.asarray([[[2**31 - 1, 1, 0x55555555 & 0x7FFFFFFF]]], jnp.int32)
    rows = jnp.asarray([[5]], jnp.int32)
    got = np.asarray(scatter_max_rows_onehot_pallas(table, rows, upd, True))
    assert got[0, 5, 0] == 2**31 - 1
    assert got[0, 5, 1] == 1
    assert got[0, 5, 2] == 0x55555555 & 0x7FFFFFFF


def test_combine_duplicate_rows_idempotent_totals():
    # Every surviving entry of a duplicate run must carry the run TOTAL so
    # writes are idempotent-to-final in any order.
    rows = jnp.asarray([[3, 3, 3, -1]], jnp.int32)
    upd = jnp.asarray([[[5, 0], [1, 9], [2, 2], [7, 7]]], jnp.int32)
    r2, u2 = combine_duplicate_rows(rows, upd, 10)
    r2, u2 = np.asarray(r2), np.asarray(u2)
    for j in range(3):
        assert r2[0, j] == 3
        assert (u2[0, j] == [5, 9]).all(), u2[0]
    # padding went to row 0 with a zero update (row 0 untouched)
    assert r2[0, 3] == 0 and (u2[0, 3] == 0).all()


@pytest.mark.parametrize("seed", range(6))
def test_delta_place_carry_walk_matches_scatter(seed):
    """The compaction-sort + carry-walk placement kernel
    (ops/delta_place.py) must reproduce the production 3-scatter delta
    build exactly: full-range signed scores/ts, duplicate kid runs with
    keep gaps, dead sentinels, and streams shorter than one GROUP
    (exercising the pad path) included."""
    from antidote_ccrdt_tpu.models.topk_rmv_dense import NEG_INF
    from antidote_ccrdt_tpu.ops.delta_place import delta_place_pallas

    rng = np.random.default_rng(200 + seed)
    R = int(rng.integers(1, 3))
    T = int(rng.integers(10, 400))
    M = int(rng.integers(1, 5))
    D = int(rng.integers(1, 33))
    B = int(rng.integers(8, 700))

    kid = np.sort(rng.integers(0, T + 1, (R, B)).astype(np.int32), axis=1)
    rank = np.full((R, B), M, np.int32)
    keep = np.zeros((R, B), bool)
    for r in range(R):
        prev, cnt = -1, 0
        for j in range(B):
            k = kid[r, j]
            cnt = cnt + 1 if k == prev else 0
            prev = k
            if k < T and cnt < M and rng.random() > 0.25:
                rank[r, j], keep[r, j] = cnt, True
    score = rng.integers(-(2**31) + 2, 2**31 - 1, (R, B)).astype(np.int32)
    ts = rng.integers(-(2**31) + 2, 2**31 - 1, (R, B)).astype(np.int32)
    dc = rng.integers(0, D, (R, B)).astype(np.int32)

    exp_s = np.full((R, T, M), NEG_INF, np.int32)
    exp_d = np.zeros((R, T, M), np.int32)
    exp_t = np.zeros((R, T, M), np.int32)
    for r in range(R):
        for j in range(B):
            if keep[r, j]:
                exp_s[r, kid[r, j], rank[r, j]] = score[r, j]
                exp_d[r, kid[r, j], rank[r, j]] = dc[r, j]
                exp_t[r, kid[r, j], rank[r, j]] = ts[r, j]

    got = delta_place_pallas(
        jnp.asarray(score), jnp.asarray(ts), jnp.asarray(dc),
        jnp.asarray(kid), jnp.asarray(rank), jnp.asarray(keep),
        T, M, D, True,
    )
    for g, w in zip(got, (exp_s, exp_d, exp_t)):
        assert np.array_equal(np.asarray(g), w), seed
