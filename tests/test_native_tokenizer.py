"""Native tokenizer: parity with the Python ingest path (which itself
mirrors wordcount.erl:76-85 / worddocumentcount.erl:76-86 semantics)."""

import collections

import numpy as np
import pytest

from antidote_ccrdt_tpu.harness import native_tokenizer as nt
from antidote_ccrdt_tpu.models.wordcount import VocabEncoder, hash_token, tokenize

pytestmark = pytest.mark.skipif(
    not nt.available(), reason=f"native toolchain unavailable: {nt.build_error()}"
)

DOCS = [
    "the quick brown fox",
    "the  quick\nfox",  # double space + newline -> empty token (parity!)
    "",  # empty doc -> one empty token
    "a a a b",
    "unicode été café café",
]


def test_hashed_matches_python_hash_token():
    V = 97
    tok = nt.NativeTokenizer(V)
    ids, doc_end = tok.encode_batch(DOCS)
    expect = []
    for d in DOCS:
        expect.extend(hash_token(t, V) for t in tokenize(d))
    assert ids.tolist() == expect
    assert doc_end.tolist() == list(
        np.cumsum([len(tokenize(d)) for d in DOCS])
    )


def test_exact_vocab_counts_match_vocab_encoder():
    tok = nt.NativeTokenizer(0)
    ids, _ = tok.encode_batch(DOCS)
    vocab = tok.vocab()
    assert len(vocab) == tok.vocab_size()
    native_counts = collections.Counter(vocab[i] for i in ids)

    enc = VocabEncoder()
    py_ids = []
    for d in DOCS:
        py_ids.extend(enc.encode(d))
    inv = {i: t for t, i in enc.vocab.items()}
    py_counts = collections.Counter(inv[i] for i in py_ids)
    assert native_counts == py_counts


def test_per_document_dedup_parity():
    tok = nt.NativeTokenizer(0)
    ids, doc_end = tok.encode_batch(DOCS, per_document=True)
    vocab = tok.vocab()
    prev = 0
    for d, end in zip(DOCS, doc_end.tolist()):
        words = [vocab[i] for i in ids[prev:end]]
        assert sorted(words) == sorted(set(tokenize(d))), d
        prev = end


def test_empty_token_in_vocab_roundtrip():
    tok = nt.NativeTokenizer(0)
    ids, _ = tok.encode_batch(["a  b"])  # 'a', '', 'b'
    vocab = tok.vocab()
    assert [vocab[i] for i in ids] == ["a", "", "b"]


def test_dense_ops_loader_counts():
    """End-to-end: docs -> native ops -> dense wordcount == scalar counts."""
    from antidote_ccrdt_tpu.models.wordcount import WordcountScalar, make_dense

    V = 64
    docs_per_replica = [DOCS[:3], DOCS[3:]]
    ops = nt.wordcount_ops_from_docs(docs_per_replica, n_buckets=V)
    D = make_dense(V)
    st = D.init(n_replicas=2, n_keys=1)
    st, _ = D.apply_ops(st, ops)
    merged = np.asarray(st.counts).sum(axis=0)[0]

    S = WordcountScalar()
    sc = S.new()
    for d in DOCS:
        sc, _ = S.update(("add", d), sc)
    expect = np.zeros(V, np.int64)
    for w, c in S.value(sc).items():
        expect[hash_token(w, V)] += c
    assert merged.tolist() == expect.tolist()


def test_vocab_growth_across_batches():
    """Exact vocab persists across encode_batch calls (streaming corpus),
    and dangling-reference hazards on vocab growth do not corrupt lookups."""
    tok = nt.NativeTokenizer(0)
    rng = np.random.default_rng(0)
    words = [f"w{i}" for i in range(2000)]
    seen = {}
    for chunk in range(20):
        docs = [
            " ".join(rng.choice(words, 50)) for _ in range(10)
        ]
        ids, _ = tok.encode_batch(docs)
        vocab = tok.vocab()
        # global invariant: every id decodes to a token that re-encodes to it
        for i in set(ids.tolist()):
            t = vocab[i]
            if t in seen:
                assert seen[t] == i
            seen[t] = i
    assert tok.vocab_size() == len(set(seen))


def test_fnv1a_buckets_matches_hash_token():
    import numpy as np

    from antidote_ccrdt_tpu.harness.native_tokenizer import fnv1a_buckets
    from antidote_ccrdt_tpu.models.wordcount import hash_token

    rng = np.random.default_rng(0)
    words = ["", "a", "été", "word-with-longer-text"] + [
        "w" + str(rng.integers(0, 10**9)) for _ in range(200)
    ]
    for V in (7, 1024, 1 << 16):
        got = fnv1a_buckets(words, V)
        assert [int(x) for x in got] == [hash_token(w, V) for w in words]


def test_device_doc_dedup_counts_hash_collisions_twice():
    """Two DISTINCT co-occurring words that collide into one bucket must
    contribute 2 to it (string-identity dedup — worddocumentcount.erl:76-86
    parity; dedup on hashed ids would wrongly count 1)."""
    import itertools

    import jax
    import numpy as np
    import pytest

    from antidote_ccrdt_tpu.harness import native_tokenizer as nt
    from antidote_ccrdt_tpu.models.wordcount import hash_token, make_dense

    if not nt.available():
        pytest.skip("native toolchain unavailable")
    V = 64
    pair = None
    for a, b in itertools.combinations((f"t{i}" for i in range(80)), 2):
        if hash_token(a, V) == hash_token(b, V):
            pair = (a, b)
            break
    assert pair is not None
    doc = f"{pair[0]} {pair[1]}"
    D = make_dense(V)
    state, _ = D.apply_doc_ops(
        D.init(1, 1), nt.worddoc_ops_from_docs([[doc]], n_buckets=V)
    )
    counts = np.asarray(jax.device_get(state.counts))[0, 0]
    assert counts[hash_token(pair[0], V)] == 2
    assert counts.sum() == 2  # exactly the two tokens of the document


def test_mt_encode_bit_identical_across_thread_counts():
    """Parallel batch encode (ccrdt_tok_encode_batch_mt) must produce the
    exact ids, doc ends, and exact-mode vocabulary id order of the serial
    encode at EVERY thread count — the exact-mode remap pass assigns
    global ids in document-order first appearance, so the thread split is
    unobservable (see native/ccrdt_tokenizer.cpp header)."""
    import numpy as np
    import pytest

    from antidote_ccrdt_tpu.harness import native_tokenizer as nt

    if not nt.available():
        pytest.skip("native toolchain unavailable")
    rng = np.random.default_rng(5)
    docs = [
        " ".join(f"w{t}" for t in rng.integers(0, 200, rng.integers(0, 30)))
        for _ in range(97)
    ]
    docs += ["", " ", "x"]  # empty docs and empty tokens at shard edges
    for buckets in (64, 0):
        for per_doc in (False, True):
            ref_tok = nt.NativeTokenizer(buckets)
            ref_ids, ref_de = ref_tok.encode_batch(
                docs, per_document=per_doc, threads=1
            )
            for threads in (2, 3, 8, 200):  # 200 > n_docs: clamped
                tok = nt.NativeTokenizer(buckets)
                ids, de = tok.encode_batch(
                    docs, per_document=per_doc, threads=threads
                )
                assert np.array_equal(ids, ref_ids), (buckets, per_doc, threads)
                assert np.array_equal(de, ref_de), (buckets, per_doc, threads)
                if buckets == 0:
                    assert tok.vocab() == ref_tok.vocab(), (per_doc, threads)


def test_mt_vocab_reuse_across_calls():
    """A second MT batch must reuse ids the first one assigned (the global
    vocabulary is consulted read-only inside the pool, then extended only
    in the serial remap)."""
    import numpy as np
    import pytest

    from antidote_ccrdt_tpu.harness import native_tokenizer as nt

    if not nt.available():
        pytest.skip("native toolchain unavailable")
    tok = nt.NativeTokenizer(0)
    ids1, _ = tok.encode_batch(["a b c", "b d"], threads=4)
    ids2, _ = tok.encode_batch(["d c b a e", "e a"], threads=4)
    assert list(ids1) == [0, 1, 2, 1, 3]
    assert list(ids2) == [3, 2, 1, 0, 4, 4, 0]
    assert tok.vocab() == ["a", "b", "c", "d", "e"]
