"""Zone-topology chaos on the deterministic simulator (net/sim.py).

The net/ chaos drills (tests/test_net_chaos.py) shake a FULL-MESH fleet.
This file runs the same elastic drill — same op streams, adoption
discipline, digests — over the `topo/` hierarchy instead: six members in
two zones, routers installed, so every cross-zone byte rides the
rendezvous anchors. The fault schedule is topology-shaped:

* a WHOLE-ZONE partition (the DCN cut) that must heal via the anchors'
  gap->full-snapshot resync;
* the za anchor CRASHED mid-run — the rendezvous runner-up must take
  over within a SWIM round (the failover the election cache makes
  observable as anchor transitions).

Acceptance is the strongest available: every survivor's digest equals
the sequential single-process reference, which is the same digest the
full-mesh chaos drill converges to — so topology changes the traffic
shape, provably not the replicated state. `run_topo_chaos` returns
(digests, counters, anchor_events) and is the engine behind the
`scripts/chaos_gate.py` topology leg.
"""

import os
import sys

from antidote_ccrdt_tpu.net.sim import SimNet
from antidote_ccrdt_tpu.net.transport import GossipNode
from antidote_ccrdt_tpu.parallel.elastic import (
    DeltaPublisher,
    my_replicas,
    sweep,
    sweep_deltas,
)
from antidote_ccrdt_tpu.topo import rendezvous_anchor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

from elastic_demo import DRILLS, R, STEPS, reference_digest  # noqa: E402

DT = 0.1
TIMEOUT = 0.35
ZONES = {  # two zones x three members — the demo fleet's shape
    "m0": "za", "m1": "za", "m2": "za",
    "m3": "zb", "m4": "zb", "m5": "zb",
}


def run_topo_chaos(type_name, seed, *, loss=0.03, dup=0.03, delta=True):
    """One zone-aware chaos run. Returns ({member: digest}, counters,
    anchor_events) where anchor_events is the chronological list of
    anchor transitions each member observed:
    {"member", "zone", "old", "new", "vt"}."""
    net = SimNet(seed=seed, latency=(0.001, 0.02), loss=loss, dup=dup)
    drill = DRILLS[type_name]
    dense = drill.make_engine()
    names = sorted(ZONES)
    transports = {m: net.join(m, zone=ZONES[m]) for m in names}
    routers = {m: transports[m].install_router(TIMEOUT) for m in names}
    nodes = {m: GossipNode(transports[m]) for m in names}
    states = {m: drill.init(dense) for m in names}
    cursors = {m: {} for m in names}
    pubs = {
        m: DeltaPublisher(nodes[m], dense, name=drill.publish_name, full_every=4)
        for m in names
    } if delta else {}
    owned = {m: set() for m in names}
    crashed = set()
    anchor_events = []
    anchor_view = {}  # (observer, zone) -> last seen anchor

    def poll_anchors():
        """Record every anchor transition as the members see it — the
        failover evidence the chaos gate requires."""
        for m in names:
            if m in crashed:
                continue
            peers = [p for p in names if p != m]
            for zone in ("za", "zb"):
                a = routers[m].anchor_of(zone, peers)
                key = (m, zone)
                if a is not None and anchor_view.get(key) != a:
                    anchor_events.append({
                        "member": m, "zone": zone,
                        "old": anchor_view.get(key), "new": a,
                        "vt": net.time,
                    })
                    anchor_view[key] = a

    def publish_and_sweep(m, seq_hint):
        node = nodes[m]
        view = drill.pub_state(dense, states[m])
        if delta:
            pubs[m].publish(view)
            swept, _ = sweep_deltas(node, dense, view, cursors[m])
        else:
            node.publish(drill.publish_name, view, seq_hint)
            swept, _ = sweep(node, dense, view)
        states[m] = drill.set_view(dense, states[m], swept)

    # Bootstrap: fault-free rounds until every member knows the roster
    # (cross-zone rosters arrive via the anchors' piggybacked ages).
    net.loss, net.dup, (loss0, dup0) = 0.0, 0.0, (net.loss, net.dup)
    for _ in range(6):
        for m in names:
            nodes[m].heartbeat()
        net.advance(DT)
    for m in names:
        assert set(nodes[m].members()) == set(names), (
            m, nodes[m].members())
    net.loss, net.dup = loss0, dup0
    poll_anchors()

    za_anchor = rendezvous_anchor("za", [m for m in names if ZONES[m] == "za"])

    for step in range(STEPS):
        if step == 3:  # the DCN cut: the whole of zb unreachable from za
            net.partition(
                {m for m in names if ZONES[m] == "za"},
                {m for m in names if ZONES[m] == "zb"},
            )
        if step == 6:
            net.heal()
        if step == 7:  # kill the za ANCHOR, not a leaf
            net.crash(za_anchor)
            crashed.add(za_anchor)
        for m in names:
            if m in crashed:
                continue
            node = nodes[m]
            node.heartbeat()
            now_owned = owned[m] | set(my_replicas(node, R, TIMEOUT))
            gained = now_owned - owned[m]
            if gained:
                states[m] = drill.adopt(dense, states[m], sorted(gained), step)
            owned[m] = now_owned
            states[m] = drill.apply(dense, states[m], step, sorted(owned[m]))
            if step % 2 == 0:
                publish_and_sweep(m, step)
        net.advance(DT)
        poll_anchors()

    # Quiescent tail: keep gossiping — AND adopting, so replicas of any
    # late-detected death keep their op streams — until convergence.
    net.loss = net.dup = 0.0
    ref = reference_digest(type_name)
    live = [m for m in names if m not in crashed]
    for _ in range(60):
        for m in live:
            node = nodes[m]
            node.heartbeat()
            now_owned = owned[m] | set(my_replicas(node, R, TIMEOUT))
            gained = now_owned - owned[m]
            if gained:
                states[m] = drill.adopt(dense, states[m], sorted(gained), STEPS)
            owned[m] = now_owned
            publish_and_sweep(m, STEPS)
        net.advance(DT)
        poll_anchors()
        if all(drill.digest(dense, states[m]) == ref for m in live):
            break

    digests = {m: drill.digest(dense, states[m]) for m in live}
    return digests, dict(net.metrics.counters), anchor_events


def test_topo_chaos_converges_to_reference():
    """Zone partition + anchor crash: every survivor still reaches the
    exact sequential reference — the same digest the full-mesh chaos
    drill pins, so the topology is state-transparent."""
    digests, counters, _ = run_topo_chaos("topk_rmv", seed=7)
    ref = reference_digest("topk_rmv")
    assert ref, "reference observable is empty — drill is vacuous"
    for m, d in digests.items():
        assert d == ref, f"{m} diverged\ngot: {d}\nref: {ref}"
    # The topology actually carried the traffic: cross-zone frames flowed
    # and anchors relayed; the zone partition actually blocked routes.
    assert counters.get("topo.cross_zone.frames", 0) > 0, counters
    assert counters.get("topo.cross_zone.bytes", 0) > 0, counters
    assert counters.get("topo.relays", 0) > 0, counters
    assert counters.get("net.sim_unreachable", 0) > 0, counters
    assert counters.get("net.dead_events", 0) > 0, counters


def test_topo_anchor_crash_fails_over():
    """The za anchor is SIGKILLed (sim-crash) mid-run: some survivor in
    za must observe an anchor transition AWAY from the victim."""
    za_members = sorted(m for m in ZONES if ZONES[m] == "za")
    victim = rendezvous_anchor("za", za_members)
    _, _, anchor_events = run_topo_chaos("topk_rmv", seed=7)
    failovers = [
        ev for ev in anchor_events
        if ev["zone"] == "za" and ev["old"] == victim
        and ev["new"] != victim and ev["member"] != victim
    ]
    assert failovers, (
        f"no survivor re-elected away from crashed anchor {victim}: "
        f"{anchor_events}"
    )
    # Failover stays inside the zone (rendezvous pools are per-zone).
    assert all(ZONES[ev["new"]] == "za" for ev in failovers)


def test_topo_chaos_deterministic_replay():
    """Same seed -> identical digests, counters, AND anchor histories:
    elections are pure functions of the (replayed) membership view."""
    r1 = run_topo_chaos("topk_rmv", seed=3)
    r2 = run_topo_chaos("topk_rmv", seed=3)
    assert r1 == r2


def test_topo_matches_full_mesh_digests():
    """Direct head-to-head on the same op streams: the topo fleet and
    the classic full-mesh chaos fleet end at the same digest (both equal
    the reference, compared explicitly for the avoidance of doubt)."""
    from test_net_chaos import run_chaos

    topo_digests, _, _ = run_topo_chaos("topk_rmv", seed=5)
    mesh_digests, _ = run_chaos("topk_rmv", seed=5, delta=True)
    ref = reference_digest("topk_rmv")
    assert all(d == ref for d in topo_digests.values()), topo_digests
    assert all(d == ref for d in mesh_digests.values()), mesh_digests
