"""The mesh plane (mesh/) end to end on the 8-virtual-device CPU rig.

Pinned here:

* `MeshPlan` ownership is total and exactly-once: every digest partition
  (meta included) maps to one key shard, the map is a pure function of
  (P, n_key) — independent of member names, device order, or the alive
  set, so it is stable under worker churn by construction;
* per-shard artifact production recombines to the unsharded artifacts
  byte for byte: stitched digest vectors equal `state_digests`, shard
  psnap blobs equal the whole-producer's blobs, mesh WAL streams recover
  to the same digests, per-shard checkpoint files are bitwise identical
  to the unsharded writer's;
* the ICI reduce (`mesh/reduce.py`) preserves the observable state (fold
  of rows), is idempotent, keeps the state pinned to the plan's
  shardings, and degrades to plain gossip under an injected `mesh.reduce`
  fault;
* resharded ingest: a snapshot produced under mesh shape A joins into a
  worker running shape B with the digest vector unchanged;
* `CCRDT_MESH=0` / MONOID engines never arm the plane;
* a seeded sim chaos fleet of mesh-sharded workers (loss + dup + a
  partition that forms and heals + a crash) converges bit-identically to
  the sequential reference with `mesh.ici_reduces` and
  `mesh.cross_slice_fetches` lit and ZERO wasted psnap fetches —
  `scripts/chaos_gate.py` leg 8 runs the same drill in a forced-8-device
  subprocess.
"""

import os
import sys

import numpy as np
import pytest

import jax

from antidote_ccrdt_tpu import mesh as mesh_mod
from antidote_ccrdt_tpu.core import partition as pt
from antidote_ccrdt_tpu.core import serial
from antidote_ccrdt_tpu.mesh import MeshPlan, gossip as mesh_gossip
from antidote_ccrdt_tpu.mesh import reduce as mesh_reduce
from antidote_ccrdt_tpu.net.sim import SimNet
from antidote_ccrdt_tpu.net.transport import FsTransport, GossipNode
from antidote_ccrdt_tpu.parallel.elastic import (
    DeltaPublisher,
    PartialAntiEntropy,
    my_replicas,
    sweep_deltas,
)
from antidote_ccrdt_tpu.utils import faults
from antidote_ccrdt_tpu.utils.metrics import Metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

from elastic_demo import DRILLS, R, STEPS, reference_digest  # noqa: E402

P = 8

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-virtual-device conftest rig"
)


def _plan(n_dc=2, n_key=4):
    return MeshPlan.build(n_dc=n_dc, n_key=n_key, partitions=P)


def _drill_state(steps=4, owned=None):
    drill = DRILLS["topk_rmv"]
    dense = drill.make_engine()
    state = drill.init(dense)
    for s in range(steps):
        state = drill.apply(
            dense, state, s, range(R) if owned is None else owned
        )
    return drill, dense, state


# --- ownership --------------------------------------------------------------


def test_plan_assigns_every_partition_to_exactly_one_shard():
    plan = _plan()
    owners = plan.owner_map()
    assert sorted(owners) == list(range(P + 1))  # meta partition included
    # Exactly-once: the per-shard lists tile 0..P with no overlap.
    seen = []
    for s in range(plan.n_key):
        parts = plan.owned_parts(s)
        assert all(plan.shard_of(p) == s for p in parts)
        seen += parts
    assert sorted(seen) == list(range(P + 1))
    with pytest.raises(ValueError):
        plan.shard_of(P + 1)
    with pytest.raises(ValueError):
        plan.owned_parts(plan.n_key)


def test_plan_ownership_stable_under_churn():
    """The map is a pure function of (P, n_key): a rebuilt plan (new
    incarnation after a crash), a plan over permuted devices, and a plan
    built on a different worker all agree — no coordination needed."""
    a = _plan()
    b = _plan()  # a restarted worker's rebuild
    assert a.owner_map() == b.owner_map()
    devs = list(jax.devices())
    flipped = MeshPlan.build(
        n_dc=2, n_key=4, partitions=P, devices=list(reversed(devs))
    )
    assert flipped.owner_map() == a.owner_map()
    # A different key extent is a DIFFERENT fleet contract, and says so.
    assert MeshPlan.build(n_dc=4, n_key=2, partitions=P).owner_map() != (
        a.owner_map()
    )


def test_plan_places_state_on_mesh():
    plan = _plan()
    _drill, _dense, state = _drill_state(steps=2)
    placed = plan.place(state)
    shs = plan.shardings(placed)
    leaves, sh_leaves = (
        jax.tree_util.tree_leaves(placed), jax.tree_util.tree_leaves(shs)
    )
    assert leaves and len(leaves) == len(sh_leaves)
    for leaf, sh in zip(leaves, sh_leaves):
        assert leaf.sharding == sh
    # At least one leaf actually spans all 8 devices (dc × key sharded).
    assert any(len(leaf.sharding.device_set) == 8 for leaf in leaves)
    # ensure_placed on an already-placed tree is leaf-identical (no copy).
    again = plan.ensure_placed(placed)
    for x, y in zip(
        jax.tree_util.tree_leaves(placed), jax.tree_util.tree_leaves(again)
    ):
        assert x is y


# --- per-shard artifacts recombine byte-for-byte ----------------------------


def test_sharded_digest_vector_bitwise_equals_unsharded():
    plan = _plan()
    _drill, _dense, state = _drill_state()
    whole = pt.state_digests(state, P)
    stitched = mesh_gossip.sharded_digest_vector(state, plan)
    assert stitched.dtype == whole.dtype
    assert np.array_equal(stitched, whole)
    # Placement does not change digests either (same bytes, new layout).
    placed = plan.place(state)
    assert np.array_equal(mesh_gossip.sharded_digest_vector(placed, plan), whole)
    # A missing slice is a loud error, not a degraded vector.
    entries = mesh_gossip.shard_digest_entries(state, plan, 0)
    with pytest.raises(ValueError):
        mesh_gossip.stitch_digests(plan, entries)


def test_shard_psnap_blobs_byte_identical_to_whole_producer():
    plan = _plan()
    drill, dense, state = _drill_state()
    for shard in range(plan.n_key):
        for part, blob in mesh_gossip.shard_psnap_blobs(
            "topk_rmv", state, 7, dense, plan, shard
        ):
            assert plan.shard_of(part) == shard
            want = pt.encode_psnap_blob(
                7,
                part,
                serial.dumps_dense(
                    "topk_rmv_psnap", pt.restrict_psnap(dense, state, part, P)
                ),
            )
            assert blob == want  # byte-for-byte, not just decodable


def test_mesh_wal_streams_recover_identical(tmp_path):
    """A mesh-routed WAL (stream per key shard) recovers to the same
    digests as the legacy stream split, and its stream routing follows
    the plan's ownership."""
    from antidote_ccrdt_tpu.harness.wal import ElasticWal

    plan = _plan()
    drill = DRILLS["topk_rmv"]
    dense = drill.make_engine()

    def write(root, member, mesh_plan):
        wal = ElasticWal(
            str(root), member, dense, drill.publish_name,
            partitions=P, mesh_plan=mesh_plan,
        )
        prev = st = drill.init(dense)
        for s in range(4):
            st = drill.apply(dense, st, s, [0, 1])
            wal.log_step(s, [0, 1], prev, st)
            prev = st
        wal.close()
        return st, wal

    final, mwal = write(tmp_path / "mesh", "w0", plan)
    assert mwal.nstreams == plan.n_key
    for p in range(P + 1):
        assert mwal.stream_for_part(p) == plan.shard_of(p) % mwal.nstreams

    reader = ElasticWal(
        str(tmp_path / "mesh"), "w0", dense, drill.publish_name,
        partitions=P, mesh_plan=plan,
    )
    state, last_step, owned = reader.recover(drill.init(dense))
    assert last_step == 3 and owned == {0, 1}
    assert np.array_equal(pt.state_digests(state, P), pt.state_digests(final, P))
    reader.close()

    # And a legacy (no-plan) reader still recovers the same log: stream
    # routing is a layout choice, not a record-format change.
    legacy = ElasticWal(
        str(tmp_path / "mesh"), "w0", dense, drill.publish_name, partitions=P
    )
    state2, last2, owned2 = legacy.recover(drill.init(dense))
    assert (last2, owned2) == (3, {0, 1})
    assert np.array_equal(pt.state_digests(state2, P), pt.state_digests(final, P))
    legacy.close()


def test_mesh_checkpoint_files_bitwise_equal_unsharded(tmp_path):
    from antidote_ccrdt_tpu.harness.checkpoint import (
        load_partitioned_checkpoint,
        save_mesh_checkpoint,
        save_partitioned_checkpoint,
    )

    plan = _plan()
    drill, dense, state = _drill_state()
    save_partitioned_checkpoint(
        str(tmp_path / "whole"), "topk_rmv", state, dense, 4, partitions=P
    )
    save_mesh_checkpoint(
        str(tmp_path / "mesh"), "topk_rmv", state, dense, 4, plan
    )
    whole_files = sorted(os.listdir(tmp_path / "whole"))
    mesh_files = sorted(os.listdir(tmp_path / "mesh"))
    assert whole_files == mesh_files
    for fn in whole_files:
        with open(tmp_path / "whole" / fn, "rb") as f:
            a = f.read()
        with open(tmp_path / "mesh" / fn, "rb") as f:
            b = f.read()
        assert a == b, f"{fn} differs between mesh and unsharded writers"
    step, name, st, durable = load_partitioned_checkpoint(
        str(tmp_path / "mesh"), drill.init(dense), dense
    )
    assert (step, name) == (4, "topk_rmv")
    assert sorted(durable) == list(range(P + 1))
    assert np.array_equal(pt.state_digests(st, P), pt.state_digests(state, P))


# --- the ICI reduce ---------------------------------------------------------


def _divergent_rows_state():
    """Per-row DISTINCT content (each row r only saw replica r's ops), so
    the dc reduce has real work to do."""
    drill = DRILLS["topk_rmv"]
    dense = drill.make_engine()
    state = drill.init(dense)
    for s in range(3):
        for r in range(R):
            state = drill.apply(dense, state, s, [r])
    return drill, dense, state


def test_ici_reduce_preserves_observable_and_is_idempotent():
    plan = _plan()
    drill, dense, state = _divergent_rows_state()
    before = drill.digest(dense, state)  # fold of rows
    placed = plan.place(state)
    m = Metrics()
    red = mesh_reduce.ici_reduce(dense, plan, placed, metrics=m)
    assert m.counters.get("mesh.ici_reduces") == 1
    # (a) the observable fold is unchanged,
    assert drill.digest(dense, red) == before
    # (b) rows actually changed (the reduce pre-joined the dc blocks),
    assert not np.array_equal(
        np.asarray(jax.tree_util.tree_leaves(red)[0]),
        np.asarray(jax.tree_util.tree_leaves(state)[0]),
    )
    # (c) the output stays pinned to the plan,
    for leaf, sh in zip(
        jax.tree_util.tree_leaves(red),
        jax.tree_util.tree_leaves(plan.shardings(red)),
    ):
        assert leaf.sharding == sh
    # (d) idempotent: reducing a reduced state is a bitwise no-op.
    red2 = mesh_reduce.ici_reduce(dense, plan, red)
    for a, b in zip(
        jax.tree_util.tree_leaves(red), jax.tree_util.tree_leaves(red2)
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    # (e) exact row semantics: reduced row r is the join of the global
    # rows in r's congruence class mod R//n_dc (its dc block).
    block = R // plan.n_dc
    ref = state
    rows = [
        jax.tree.map(lambda a, i=i: a[i : i + 1], state) for i in range(R)
    ]
    for r in range(R):
        acc = rows[r % block]
        for j in range(r % block + block, R, block):
            acc = dense.merge(acc, rows[j])
        ref = jax.tree.map(
            lambda full, one, r=r: full.at[r : r + 1].set(one), ref, acc
        )
    for a, b in zip(
        jax.tree_util.tree_leaves(red), jax.tree_util.tree_leaves(ref)
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_ici_reduce_fault_point_drops_and_raises():
    plan = _plan()
    drill, dense, state = _divergent_rows_state()
    placed = plan.place(state)
    m = Metrics()
    with faults.injected({"mesh.reduce": [{"action": "drop", "at": [0]}]}):
        out = mesh_reduce.ici_reduce(dense, plan, placed, metrics=m)
    assert out is placed  # skipped, untouched
    assert m.counters.get("mesh.reduce_skipped") == 1
    with faults.injected({"mesh.reduce": [{"action": "raise", "at": [0, 1]}]}):
        with pytest.raises(faults.InjectedFault):
            mesh_reduce.ici_reduce(dense, plan, placed)
        # try_ici_reduce degrades to plain gossip instead.
        out = mesh_reduce.try_ici_reduce(dense, plan, placed, metrics=m)
    assert out is placed
    assert m.counters.get("mesh.reduce_failures") == 1


def test_mesh_disabled_and_monoid_paths_stay_off():
    drill = DRILLS["topk_rmv"]
    dense = drill.make_engine()
    env_before = os.environ.get(mesh_mod.ENV_FLAG)
    os.environ[mesh_mod.ENV_FLAG] = "0"
    try:
        assert mesh_mod.install_from_env(dense) is None
    finally:
        if env_before is None:
            os.environ.pop(mesh_mod.ENV_FLAG, None)
        else:
            os.environ[mesh_mod.ENV_FLAG] = env_before
    assert mesh_mod.install_from_env(dense, override=False) is None
    # MONOID engines are excluded even when forced on.
    mono = DRILLS["average"].make_engine()
    assert not mesh_mod.supports(mono)
    assert mesh_mod.install_from_env(mono, override=True) is None
    # JOIN engine + explicit override arms on this 8-device rig.
    plan = mesh_mod.install_from_env(dense, partitions=P, override=True)
    assert plan is not None and plan.n_dc * plan.n_key <= 8


def test_reshard_ingest_digest_unchanged():
    """Mesh shape A -> B rejoin: a snapshot placed under (2,4) ingests
    into a (4,2) worker; the digest vector is unchanged and the result
    lands on the local plan's shardings."""
    plan_a = _plan(2, 4)
    plan_b = _plan(4, 2)
    drill, dense, state = _divergent_rows_state()
    fetched = plan_a.place(state)
    local = plan_b.place(drill.init(dense))
    whole = dense.merge(drill.init(dense), state)
    m = Metrics()
    merged = mesh_gossip.ingest_snapshot(dense, local, fetched, plan_b, metrics=m)
    assert m.counters.get("mesh.resharded_ingests") == 1
    assert np.array_equal(
        pt.state_digests(merged, P), pt.state_digests(whole, P)
    )
    for leaf, sh in zip(
        jax.tree_util.tree_leaves(merged),
        jax.tree_util.tree_leaves(plan_b.shardings(merged)),
    ):
        assert leaf.sharding == sh


# --- sharded anchors over the gossip plane ----------------------------------


def test_sharded_anchor_publishes_per_shard_and_partial_repair(tmp_path):
    """An anchor with a mesh plan publishes shard-local digest slices +
    psnap blobs; a diverged peer repairs partition-granularly through
    `PartialAntiEntropy` with the mesh fetch grouping, billing
    cross-slice fetch/byte counters, with zero waste."""
    plan = _plan()
    drill = DRILLS["topk_rmv"]
    dense = drill.make_engine()
    a = GossipNode(FsTransport(str(tmp_path), "a"))
    b = GossipNode(FsTransport(str(tmp_path), "b"))
    a.heartbeat(), b.heartbeat()

    pub = DeltaPublisher(
        a, dense, name="topk_rmv", full_every=1, partitions=P, mesh_plan=plan
    )
    st_a = drill.init(dense)
    for s in range(3):
        st_a = drill.apply(dense, st_a, s, range(R))
    pub.publish(st_a)
    assert a.metrics.counters.get("mesh.shard_digest_slices", 0) >= plan.n_key
    assert (
        sum(
            v
            for k, v in a.metrics.counters.items()
            if k.startswith("mesh.shard") and k.endswith(".psnap_publishes")
        )
        > 0
    )

    curs = {}
    pae = PartialAntiEntropy(b, partitions=P, mesh_plan=plan)
    st_b, _ = sweep_deltas(b, dense, drill.init(dense), curs, partial=pae)
    assert np.array_equal(pt.state_digests(st_b, P), pt.state_digests(st_a, P))

    # a advances alone; b's next sweep repairs via shard-local psnaps
    # (full_every=1: every publish is an anchor, so the partial path
    # engages off the digest vectors, same shape as test_partition's).
    st_a = drill.apply(dense, st_a, 3, range(R))
    pub.publish(st_a)
    st_b, _stats = sweep_deltas(b, dense, st_b, curs, partial=pae)
    assert np.array_equal(
        pt.state_digests(st_b, P), pt.state_digests(st_a, P)
    )
    c = b.metrics.counters
    assert c.get("mesh.cross_slice_fetches", 0) > 0, dict(c)
    assert c.get("mesh.cross_slice_bytes", 0) > 0, dict(c)
    assert c.get("net.psnap_wasted", 0) == 0, dict(c)


# --- seeded sim chaos with mesh-sharded workers ------------------------------

N = 4
DT = 0.1
TIMEOUT = 0.35


def run_mesh_chaos(seed, *, loss=0.03, dup=0.03, spans=False):
    """tests/test_partition.py's `run_partition_chaos` with every worker
    mesh-sharded: states pinned to a shared (2,4) plan, one ICI reduce
    per publish boundary, per-shard anchors, and mesh-grouped partial
    repairs. Returns ({member: digest}, fleet counters, span names seen).
    Also chaos_gate leg 8 (scripts/chaos_gate.py runs this in a
    forced-8-device subprocess)."""
    from antidote_ccrdt_tpu.obs import spans as obs_spans

    net = SimNet(seed=seed, latency=(0.001, 0.02), loss=loss, dup=dup)
    plan = MeshPlan.build(n_dc=2, n_key=4, partitions=P)
    drill = DRILLS["topk_rmv"]
    dense = drill.make_engine()
    names = [f"m{i}" for i in range(N)]
    nodes = {m: GossipNode(net.join(m)) for m in names}
    states = {m: plan.place(drill.init(dense)) for m in names}
    cursors = {m: {} for m in names}
    pubs = {
        m: DeltaPublisher(
            nodes[m], dense, name=drill.publish_name, full_every=4,
            keep=4, partitions=P, mesh_plan=plan,
        )
        for m in names
    }
    partials = {
        m: PartialAntiEntropy(
            nodes[m], partitions=P, max_tries=6, mesh_plan=plan
        )
        for m in names
    }
    owned = {m: set() for m in names}
    crashed = set()

    def publish_and_sweep(m):
        states[m] = mesh_reduce.try_ici_reduce(
            dense, plan, states[m], metrics=nodes[m].metrics
        )
        pubs[m].publish(states[m])
        states[m], _ = sweep_deltas(
            nodes[m], dense, states[m], cursors[m], partial=partials[m]
        )

    def body():
        for _ in range(3):
            for m in names:
                nodes[m].heartbeat()
            net.advance(DT)
        for m in names:
            assert set(nodes[m].members()) == set(names), "bootstrap incomplete"

        for step in range(STEPS):
            if step == 3:
                net.partition({"m0", "m1"}, {"m2", "m3"})
            if step == 6:
                net.heal()
            if step == 7:
                net.crash("m3")
                crashed.add("m3")
            for m in names:
                if m in crashed:
                    continue
                node = nodes[m]
                node.heartbeat()
                now_owned = owned[m] | set(my_replicas(node, R, TIMEOUT))
                gained = now_owned - owned[m]
                if gained:
                    states[m] = drill.adopt(
                        dense, states[m], sorted(gained), step
                    )
                owned[m] = now_owned
                states[m] = drill.apply(dense, states[m], step, sorted(owned[m]))
                if step % 2 == 0:
                    publish_and_sweep(m)
            net.advance(DT)

        net.loss = net.dup = 0.0
        ref = reference_digest("topk_rmv")
        live = [m for m in names if m not in crashed]
        for _ in range(40):
            for m in live:
                node = nodes[m]
                node.heartbeat()
                now_owned = owned[m] | set(my_replicas(node, R, TIMEOUT))
                gained = now_owned - owned[m]
                if gained:
                    states[m] = drill.adopt(
                        dense, states[m], sorted(gained), STEPS
                    )
                owned[m] = now_owned
                publish_and_sweep(m)
            net.advance(DT)
            if all(drill.digest(dense, states[m]) == ref for m in live):
                break
        return {m: drill.digest(dense, states[m]) for m in live}

    span_names = set()
    if spans:
        with obs_spans.installed("mesh-chaos", metrics=net.metrics):
            digests = body()
            span_names = {
                r.get("name") for r in obs_spans.drain() if r.get("k") == "span"
            }
    else:
        digests = body()
    return digests, dict(net.metrics.counters), span_names


def test_mesh_chaos_converges_with_reduces_and_shard_fetches():
    digests, counters, span_names = run_mesh_chaos(seed=7, spans=True)
    ref = reference_digest("topk_rmv")
    assert ref, "reference observable is empty — drill is vacuous"
    for m, d in digests.items():
        assert d == ref, f"{m} diverged\ngot: {d}\nref: {ref}"
    assert counters.get("mesh.ici_reduces", 0) > 0, counters
    assert counters.get("mesh.cross_slice_fetches", 0) > 0, counters
    assert counters.get("net.psnap_wasted", 0) == 0, counters
    assert "round.ici_reduce" in span_names, sorted(span_names)


def test_mesh_chaos_deterministic_replay():
    d1, c1, _ = run_mesh_chaos(seed=3)
    d2, c2, _ = run_mesh_chaos(seed=3)
    assert d1 == d2
    # Timing-free counters replay exactly; drop the latency-mirroring
    # keys the metrics plane may fold differently across runs.
    assert c1 == c2
