"""Metrics export (obs/export.py): Prometheus exposition golden format,
JSONL rendering, and the cross-process dump/merge path — plus the
`Metrics.snapshot()`/`merge()` semantics the aggregation depends on."""

import json
import os

from antidote_ccrdt_tpu.obs import export as obs_export
from antidote_ccrdt_tpu.utils.metrics import Metrics


def _sample_metrics():
    m = Metrics()
    m.count("net.frames_sent", 3)
    m.set("wal.last_seq", 17.0)
    m.merge({"counters": {}, "latencies": {"sync": [0.010, 0.020, 0.030, 0.040]}})
    return m


def test_prometheus_golden_format():
    text = obs_export.prometheus_text(_sample_metrics())
    assert text.endswith("\n")
    lines = text.splitlines()
    # Counters: dots sanitized to underscores, ccrdt_ prefix, HELP/TYPE
    # preceding each sample, int-valued floats rendered as ints.
    assert lines[0] == "# HELP ccrdt_net_frames_sent ccrdt counter/gauge net.frames_sent"
    assert lines[1] == "# TYPE ccrdt_net_frames_sent gauge"
    assert lines[2] == "ccrdt_net_frames_sent 3"
    assert "ccrdt_wal_last_seq 17" in lines
    # Latencies: CUMULATIVE histogram buckets (le inclusive) + sum/count.
    assert "# TYPE ccrdt_sync_seconds histogram" in lines
    assert 'ccrdt_sync_seconds_bucket{le="0.005"} 0' in lines
    assert 'ccrdt_sync_seconds_bucket{le="0.01"} 1' in lines
    assert 'ccrdt_sync_seconds_bucket{le="0.025"} 2' in lines
    assert 'ccrdt_sync_seconds_bucket{le="0.05"} 4' in lines
    assert 'ccrdt_sync_seconds_bucket{le="+Inf"} 4' in lines
    assert "ccrdt_sync_seconds_sum 0.1" in lines
    assert "ccrdt_sync_seconds_count 4" in lines
    # The +Inf bucket always equals _count, and counts never decrease
    # along the ladder (what makes them summable across workers).
    bucket_counts = [
        int(ln.rsplit(" ", 1)[1])
        for ln in lines
        if ln.startswith("ccrdt_sync_seconds_bucket")
    ]
    assert bucket_counts == sorted(bucket_counts)
    assert bucket_counts[-1] == 4


def test_prometheus_labels_and_prefix():
    m = Metrics()
    m.count("x")
    text = obs_export.prometheus_text(m, prefix="app", labels={"member": "w0"})
    assert 'app_x{member="w0"} 1' in text.splitlines()
    # Labels merge with the le label on bucket samples (le last).
    m.merge({"counters": {}, "latencies": {"t": [0.5]}})
    text = obs_export.prometheus_text(m, labels={"member": "w0"})
    lines = text.splitlines()
    assert 'ccrdt_t_seconds_bucket{member="w0",le="0.5"} 1' in lines
    assert 'ccrdt_t_seconds_bucket{member="w0",le="0.25"} 0' in lines
    assert 'ccrdt_t_seconds_sum{member="w0"} 0.5' in lines


def test_prometheus_accepts_plain_snapshot_and_empty_series():
    snap = {"counters": {"a.b": 2.5}, "latencies": {"empty": []}}
    lines = obs_export.prometheus_text(snap).splitlines()
    assert "ccrdt_a_b 2.5" in lines
    # An empty latency series still exports well-formed buckets/sum/count.
    assert 'ccrdt_empty_seconds_bucket{le="+Inf"} 0' in lines
    assert "ccrdt_empty_seconds_sum 0" in lines
    assert "ccrdt_empty_seconds_count 0" in lines
    assert not any('quantile="' in ln for ln in lines)


def test_prometheus_custom_buckets():
    m = Metrics()
    m.merge({"counters": {}, "latencies": {"t": [0.5, 1.5, 9.0]}})
    lines = obs_export.prometheus_text(m, buckets=(1.0, 2.0)).splitlines()
    assert 'ccrdt_t_seconds_bucket{le="1"} 1' in lines
    assert 'ccrdt_t_seconds_bucket{le="2"} 2' in lines
    assert 'ccrdt_t_seconds_bucket{le="+Inf"} 3' in lines


def test_jsonl_lines():
    out = obs_export.jsonl_lines(_sample_metrics(), member="w1")
    docs = [json.loads(ln) for ln in out]
    by_metric = {d["metric"]: d for d in docs}
    assert by_metric["net.frames_sent"] == {
        "member": "w1", "metric": "net.frames_sent", "value": 3.0}
    assert by_metric["sync"]["summary"]["n"] == 4
    assert abs(by_metric["sync"]["summary"]["p50_ms"] - 25.0) < 1e-9


def test_snapshot_merge_roundtrip():
    a, b = Metrics(), Metrics()
    a.count("ops", 2)
    a.merge({"counters": {}, "latencies": {"t": [0.1]}})
    b.count("ops", 3)
    b.count("only_b")
    b.merge({"counters": {}, "latencies": {"t": [0.3, 0.5]}})
    merged = Metrics()
    merged.merge(a.snapshot())
    merged.merge(b.snapshot())
    assert merged.counters["ops"] == 5.0
    assert merged.counters["only_b"] == 1.0
    # Samples concatenate: fleet percentiles run over the union, never
    # over averaged per-worker percentiles.
    assert sorted(merged.latencies["t"].samples) == [0.1, 0.3, 0.5]
    # Snapshots are copies — mutating one never aliases the registry.
    snap = merged.snapshot()
    snap["counters"]["ops"] = 999
    snap["latencies"]["t"].append(9.9)
    assert merged.counters["ops"] == 5.0
    assert len(merged.latencies["t"].samples) == 3


def test_dump_load_merge_dir(tmp_path):
    d = str(tmp_path / "metrics")
    for member, n in (("w0", 2), ("w1", 5)):
        m = Metrics()
        m.count("net.frames_sent", n)
        m.merge({"counters": {}, "latencies": {"sync": [0.01 * n]}})
        path = obs_export.dump_snapshot(m, member, d)
        assert os.path.basename(path) == f"metrics-{member}-{os.getpid()}.json"
    # A torn/partial file must be skipped, not crash the merge.
    with open(os.path.join(d, "metrics-broken-1.json"), "w") as f:
        f.write('{"member": "bro')
    docs = obs_export.load_snapshots(d)
    assert len(docs) == 2
    merged, members = obs_export.merge_dir(d)
    assert sorted(members) == ["w0", "w1"]
    assert merged.counters["net.frames_sent"] == 7.0
    assert sorted(merged.latencies["sync"].samples) == [0.02, 0.05]


def test_install_atexit_dump_gated_on_env(tmp_path):
    m = Metrics()
    assert obs_export.install_atexit_dump(m, "w0", env={}) is False
    assert obs_export.install_atexit_dump(
        m, "w0", env={obs_export.ENV_DIR: str(tmp_path / "md")}) is True
