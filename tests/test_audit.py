"""The certified-convergence plane (obs/audit.py) unit surface.

Pinned here:

* **LawChecker** — the full registry passes its merge/delta law suite;
  the committed non-commutative fixture (`ops.laws.broken_merge_fixture`)
  is caught on exactly the laws it breaks (commutativity + associativity
  FAIL, idempotence PASSES — law verdicts are independent, not a single
  pass/fail bit); a registered type without a fixture lands in
  `unaudited` and flips the gate, never silently skips.
* **certify / verify_certificate** — a clean flight-log spill with
  agreeing digests and a matching reference certifies ok with a valid
  signature; any post-signing tamper breaks verification; divergent
  digest vectors fail certification with a counterexample naming the
  divergent partitions; coverage via snapshot folds and partial resyncs
  reconciles, truncation is caught as `uncovered`.
* **DivergenceWatchdog** — the ok -> diverged -> wedged state machine on
  an injected monotonic clock: divergence flagged on the FIRST
  disagreeing exchange, wedge only after `wedge_after_s` with no
  progress, shrinking divergence / `note_repair_progress` reset the
  wedge clock, agreement records a time-to-agreement sample, equal
  vectors never alarm, `drop` forgets a dead peer's frozen vector, and
  the gauges/health/status surfaces export what the dashboards read.
"""

import copy

import pytest

from antidote_ccrdt_tpu.obs import audit
from antidote_ccrdt_tpu.obs.audit import (
    DivergenceWatchdog,
    LawChecker,
    certify,
    reconcile_op_counts,
    sign_certificate,
    verify_certificate,
)
from antidote_ccrdt_tpu.utils.metrics import Metrics


class Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _wd(**kw):
    kw.setdefault("wedge_after_s", 5.0)
    clk = Clock()
    m = Metrics()
    return DivergenceWatchdog("me", mono=clk, metrics=m, **kw), clk, m


# -- lattice-law checking ----------------------------------------------------


@pytest.mark.slow
def test_law_checker_registry_all_green():
    rep = LawChecker(pairs=16, seed=3).run()
    assert rep["ok"], rep
    assert rep["n_types"] >= 6 and rep["unaudited"] == []
    assert rep["n_law_failures"] == 0
    # Every type got at least commutativity + associativity.
    assert rep["n_law_checks"] >= 2 * rep["n_types"]


def test_law_checker_catches_broken_fixture_per_law():
    from antidote_ccrdt_tpu.ops.laws import broken_merge_fixture

    m = Metrics()
    rep = LawChecker(
        types=["broken_merge_fixture"],
        extra_fixtures={"broken_merge_fixture": broken_merge_fixture},
        pairs=16, metrics=m,
    ).run()
    assert not rep["ok"]
    laws = rep["types"]["broken_merge_fixture"]["laws"]
    # 2a - b: non-commutative, non-associative, but idempotent — the
    # checker must name the broken laws, not blanket-fail the type.
    assert not laws["commutativity"]["ok"]
    assert not laws["associativity"]["ok"]
    assert laws["idempotence"]["ok"]
    assert laws["commutativity"]["failed_instances"] >= 1
    assert m.counters["audit.law_failures"] == 2.0


def test_law_checker_unaudited_type_flips_gate():
    rep = LawChecker(types=["topk", "ghost-type"], pairs=8).run()
    assert not rep["ok"]
    assert rep["unaudited"] == ["ghost-type"]
    assert rep["types"]["topk"]["ok"]


# -- replay certification ----------------------------------------------------


def _pub(origin, dseq, seq):
    return {"kind": "delta.publish", "member": origin, "origin": origin,
            "dseq": dseq, "seq": seq}


def _app(member, origin, dseq, seq):
    return {"kind": "delta.apply", "member": member, "origin": origin,
            "dseq": dseq, "seq": seq}


def _clean_logs():
    return {
        "flight-a-1.jsonl": [
            _pub("a", 1, 0), _pub("a", 2, 1),
            _app("a", "b", 1, 2),
        ],
        "flight-b-1.jsonl": [
            _pub("b", 1, 0),
            _app("b", "a", 1, 1), _app("b", "a", 2, 2),
        ],
    }


def test_certify_clean_run_signs_ok():
    digests = {"a": [7, 8, 9], "b": [7, 8, 9]}
    cert = certify(logs=_clean_logs(), digests=digests,
                   reference=[7, 8, 9], meta={"drill": "unit"})
    assert cert["ok"]
    assert cert["checks"] == {
        "causal_delivery": True,
        "op_count_reconciliation": True,
        "partition_digest_agreement": True,
        "matches_reference": True,
    }
    assert "counterexample" not in cert
    assert cert["n_flight_logs"] == 2
    assert verify_certificate(cert)
    # Tamper with anything after signing — verification breaks.
    forged = copy.deepcopy(cert)
    forged["ok"] = True
    forged["worker_digests"]["a"] = "deadbeef"
    assert not verify_certificate(forged)
    resigned = sign_certificate(dict(forged))
    assert verify_certificate(resigned)
    assert resigned["signature"] != cert["signature"]


def test_certify_without_evidence_omits_checks():
    # No digests, no reference: those checks are ABSENT, not vacuously
    # true — the certificate only claims what it could audit.
    cert = certify(logs=_clean_logs())
    assert cert["ok"]
    assert set(cert["checks"]) == {
        "causal_delivery", "op_count_reconciliation"}
    assert cert["agreement"] is None and cert["reference"] is None


def test_certify_divergent_digests_counterexample_names_partition():
    digests = {"a": [5, 6, 7], "b": [5, 60, 7], "c": [5, 6, 7]}
    cert = certify(logs=_clean_logs(), digests=digests, reference=[5, 6, 7])
    assert not cert["ok"]
    assert not cert["checks"]["partition_digest_agreement"]
    assert not cert["checks"]["matches_reference"]
    cx = cert["counterexample"]
    assert cx["divergent_parts"] == [1]
    assert sorted(cx["reference_mismatch"]) == ["b"]
    # The digest groups split b from {a, c}.
    assert any(sorted(ms) == ["a", "c"] for ms in cx["digest_groups"].values())
    # A failed certificate still carries a valid signature.
    assert verify_certificate(cert)


def test_reconcile_covers_via_snapshot_and_psnap():
    logs = _clean_logs()
    # c saw none of a's deltas directly: a full snapshot fold at a's
    # step 2 covers the stream; for b, a partial resync at dig_seq 1
    # plus the applied delta 2... but drop the delta: dig_seq 1 alone
    # leaves dseq 2 uncovered.
    logs["flight-c-1.jsonl"] = [
        {"kind": "snap.apply", "member": "c", "origin": "a", "step": 2,
         "seq": 0},
        {"kind": "psnap.resync", "member": "c", "origin": "b", "dig_seq": 1,
         "seq": 1},
    ]
    rec = reconcile_op_counts(logs)
    assert rec["ok"], rec
    assert rec["origins"]["a"]["max_dseq"] == 2
    # Now truncate: one applier short of the watermark.
    logs["flight-c-1.jsonl"] = [
        {"kind": "psnap.resync", "member": "c", "origin": "a", "dig_seq": 1,
         "seq": 0},
        _app("c", "b", 1, 1),
    ]
    rec = reconcile_op_counts(logs)
    assert not rec["ok"]
    assert rec["uncovered"] == [{
        "applier": "c", "origin": "a",
        "covered_through": 1, "published_through": 2, "applied": 0,
    }]
    cert = certify(logs=logs)
    assert not cert["ok"]
    assert cert["counterexample"]["uncovered"][0]["applier"] == "c"


def test_reconcile_coverage_spans_incarnations():
    # A restarted worker's coverage is judged on the union of its
    # incarnations: pre-crash it applied dseq 1, post-recovery 2.
    logs = {
        "flight-a-1.jsonl": [_pub("a", 1, 0), _pub("a", 2, 1)],
        "flight-b-100.jsonl": [_app("b", "a", 1, 0)],
        "flight-b-200.jsonl": [
            _app("b", "a", 1, 0), _app("b", "a", 2, 1)],
    }
    assert reconcile_op_counts(logs)["ok"]


def test_reconcile_excludes_member_dead_at_quiesce():
    # The mesh drill's shape: b SIGKILLed mid-run (crash dump: no
    # proc.exit) and never restarted. Its final state does not exist,
    # so it owes no coverage — but its PUBLISHED stream stays on the
    # books: a must still cover everything b shipped before dying.
    def _life(member, t, exit_):
        evs = [{"kind": "proc.start", "member": member, "t": t, "seq": 0}]
        if exit_:
            evs.append(
                {"kind": "proc.exit", "member": member, "t": t + 9,
                 "seq": 99})
        return evs

    logs = {
        "flight-a-1.jsonl": _life("a", 0.0, True) + [
            _pub("a", 1, 1), _pub("a", 2, 2),
            _app("a", "b", 1, 3),
        ],
        "flight-b-1.jsonl": _life("b", 0.0, False) + [
            _pub("b", 1, 1), _app("b", "a", 1, 2),
        ],
    }
    rec = reconcile_op_counts(logs)
    assert rec["ok"], rec
    assert rec["dead_members"] == ["b"]
    # ...but drop a's coverage of b's stream: the dead member's ops
    # were LOST, and the check must still catch exactly that.
    logs["flight-a-1.jsonl"] = _life("a", 0.0, True) + [
        _pub("a", 1, 1), _pub("a", 2, 2)]
    rec = reconcile_op_counts(logs)
    assert not rec["ok"]
    assert rec["uncovered"][0] == {
        "applier": "a", "origin": "b",
        "covered_through": -1, "published_through": 1, "applied": 0,
    }
    # A RESTARTED member (crash dump + successor incarnation) is not
    # dead — its union coverage is judged as before.
    logs["flight-a-1.jsonl"] = _life("a", 0.0, True) + [
        _pub("a", 1, 1), _pub("a", 2, 2), _app("a", "b", 1, 3)]
    logs["flight-b-2.jsonl"] = _life("b", 5.0, True) + [
        _app("b", "a", 1, 1)]
    rec = reconcile_op_counts(logs)
    assert rec["dead_members"] == []
    assert not rec["ok"]  # b's union coverage of a stops at dseq 1 < 2
    # Without the proc lifecycle discipline anywhere in the spill
    # (in-process sim drills), nobody is excused.
    assert reconcile_op_counts({
        "flight-a-1.jsonl": [_pub("a", 1, 0)],
        "flight-b-1.jsonl": [],
    })["dead_members"] == []


# -- divergence watchdog -----------------------------------------------------


def test_watchdog_agreeing_vectors_never_alarm():
    wd, clk, m = _wd()
    for i in range(5):
        clk.t = float(i * 10)  # far past any wedge bound
        assert wd.observe_peer("b", [1, 2, 3], [1, 2, 3], seq=i) \
            == wd.STATE_OK
    assert wd.state() == wd.STATE_OK
    assert wd.divergence_age_s() == 0.0
    assert "audit.divergences" not in m.counters
    assert m.counters["audit.watchdog_state"] == 0.0


def test_watchdog_flags_first_divergent_exchange_then_wedges():
    wd, clk, m = _wd(wedge_after_s=5.0)
    assert wd.observe_peer("b", [1, 2], [1, 2], seq=0) == wd.STATE_OK
    clk.t = 1.0
    # First disagreeing observation — diverged within ONE exchange.
    assert wd.observe_peer("b", [1, 2], [1, 9], seq=1) == wd.STATE_DIVERGED
    assert m.counters["audit.divergences"] == 1.0
    assert wd.divergent_parts() == [1]
    # Still diverged inside the bound: no alarm.
    clk.t = 4.0
    assert wd.observe_peer("b", [1, 2], [1, 9], seq=2) == wd.STATE_DIVERGED
    assert "audit.wedge_alarms" not in m.counters
    # Past the bound with zero progress: wedged.
    clk.t = 6.5
    assert wd.observe_peer("b", [1, 2], [1, 9], seq=3) == wd.STATE_WEDGED
    assert m.counters["audit.wedge_alarms"] == 1.0
    assert m.counters["audit.watchdog_state"] == 2.0
    assert abs(wd.divergence_age_s() - 5.5) < 1e-9
    # Agreement heals even a wedged peer and samples time-to-agreement.
    clk.t = 8.0
    assert wd.observe_peer("b", [1, 2], [1, 2], seq=4) == wd.STATE_OK
    assert m.counters["audit.agreements"] == 1.0
    assert abs(wd.tta_p50_s() - 7.0) < 1e-9
    assert m.counters["audit.watchdog_state"] == 0.0


def test_watchdog_progress_resets_wedge_clock():
    wd, clk, m = _wd(wedge_after_s=5.0)
    clk.t = 0.0
    wd.observe_peer("b", [1, 2, 3], [9, 9, 3])
    # The divergent set SHRINKS at t=4 — repair is landing, clock resets.
    clk.t = 4.0
    assert wd.observe_peer("b", [1, 2, 3], [9, 2, 3]) == wd.STATE_DIVERGED
    clk.t = 8.0  # 8s since onset, but only 4s since progress
    assert wd.observe_peer("b", [1, 2, 3], [9, 2, 3]) == wd.STATE_DIVERGED
    # Out-of-band progress (applied psnaps) also resets it.
    clk.t = 8.5
    wd.note_repair_progress("b")
    clk.t = 13.0
    assert wd.observe_peer("b", [1, 2, 3], [9, 2, 3]) == wd.STATE_DIVERGED
    assert "audit.wedge_alarms" not in m.counters
    # ...but stalling past the bound finally trips it.
    clk.t = 19.0
    assert wd.observe_peer("b", [1, 2, 3], [9, 2, 3]) == wd.STATE_WEDGED


def test_watchdog_drop_forgets_dead_peer():
    wd, clk, _m = _wd(wedge_after_s=2.0)
    wd.observe_peer("dead", [1], [2])
    assert wd.state() == wd.STATE_DIVERGED
    # SWIM declares it dead: its frozen vector must not age into a
    # wedge alarm.
    wd.drop("dead")
    assert wd.state() == wd.STATE_OK
    assert wd.divergent_parts() == []
    assert wd.peers() == {}


def test_watchdog_scalar_and_mismatched_vectors():
    wd, clk, _m = _wd()
    # Scalar digests compare as 1-vectors.
    assert wd.observe_peer("b", 7, 7) == wd.STATE_OK
    assert wd.observe_peer("b", 7, 8) == wd.STATE_DIVERGED
    assert wd.divergent_parts() == [0]
    # Incomparable lengths (mid-repartition peer) flag every index.
    assert wd.observe_peer("c", [1, 2], [1, 2, 3]) == wd.STATE_DIVERGED
    assert set(wd._peers["c"]["parts"]) == {0, 1, 2}


def test_watchdog_health_and_status_surfaces():
    wd, clk, m = _wd(wedge_after_s=5.0)
    clk.t = 1.0
    wd.observe_peer("b", [1, 2], [1, 9], seq=41)
    clk.t = 3.5
    h = wd.health_fields()
    assert h["audit_watchdog_state"] == "diverged"
    assert abs(h["audit_divergence_age_s"] - 2.5) < 1e-9
    assert h["audit_divergent_parts"] == [1]
    assert "audit_tta_p50_ms" not in h  # no agreements yet
    st = wd.status_fields()
    assert st["state"] == "diverged" and st["ttas"] == 0
    assert st["tta_p50_ms"] is None and st["cert_ok"] is None
    # Heal + record a certificate: both surfaces pick it up.
    clk.t = 4.0
    wd.observe_peer("b", [1, 9], [1, 9], seq=42)
    cert = certify(logs=_clean_logs(), digests={"a": [1], "b": [1]})
    wd.note_certificate(cert)
    h = wd.health_fields()
    assert h["audit_watchdog_state"] == "ok"
    assert h["audit_last_certificate"]["ok"] is True
    assert h["audit_last_certificate"]["signature"] == \
        cert["signature"][:16]
    assert abs(h["audit_tta_p50_ms"] - 3000.0) < 1e-6
    assert wd.status_fields()["cert_ok"] is True
    assert m.counters["audit.certificate_ok"] == 1.0


def test_watchdog_tta_p50_is_median():
    wd, clk, _m = _wd()
    for i, dur in enumerate([1.0, 9.0, 2.0]):
        t0 = 100.0 * i
        clk.t = t0
        wd.observe_peer("b", [1], [2])
        clk.t = t0 + dur
        wd.observe_peer("b", [1], [1])
    assert wd.tta_p50_s() == 2.0


# -- the audit CLI (scripts/ccrdt_audit.py) ----------------------------------


def _load_audit_cli():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "ccrdt_audit",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts", "ccrdt_audit.py",
        ),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_cli_certify_verify_roundtrip_and_tamper(tmp_path, capsys):
    import json
    import os

    cli = _load_audit_cli()
    obs_dir = tmp_path / "obs"
    os.makedirs(obs_dir)
    for fname, evs in _clean_logs().items():
        with open(obs_dir / fname, "w") as fh:
            for ev in evs:
                fh.write(json.dumps(ev) + "\n")
    dig_file = tmp_path / "digests.json"
    # Dashed-hex labels (what certificates print) must round-trip in.
    dig_file.write_text(json.dumps(
        {"a": [7, 8], "b": "00000007-00000008"}))
    cert_path = str(tmp_path / "cert.json")
    rc = cli.main([
        "certify", str(obs_dir), "--digests", str(dig_file),
        "--reference", "00000007-00000008", "--out", cert_path,
    ])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "certificate  : OK" in out
    assert cli.main(["verify", cert_path]) == 0
    assert "valid" in capsys.readouterr().out
    # Tamper with the verdict on disk: verify must exit 1.
    doc = json.loads(open(cert_path).read())
    doc["checks"]["causal_delivery"] = False
    with open(cert_path, "w") as fh:
        json.dump(doc, fh)
    assert cli.main(["verify", cert_path]) == 1
    assert "INVALID" in capsys.readouterr().out


def test_cli_certify_divergence_exits_nonzero(tmp_path, capsys):
    import json
    import os

    cli = _load_audit_cli()
    obs_dir = tmp_path / "obs"
    os.makedirs(obs_dir)
    (obs_dir / "flight-a-1.jsonl").write_text(
        json.dumps(_pub("a", 1, 0)) + "\n")
    dig_file = tmp_path / "digests.json"
    dig_file.write_text(json.dumps({"a": [1, 2], "b": [1, 99]}))
    rc = cli.main(["certify", str(obs_dir), "--digests", str(dig_file),
                   "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["counterexample"]["divergent_parts"] == [1]
